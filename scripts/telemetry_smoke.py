#!/usr/bin/env python
"""Telemetry-plane gate (``make telemetry-smoke``; docs/DESIGN.md §11).

Builds the bench gossipsub step TELEMETRY-ON at the PERF_SMOKE shape
(N=2048, live counters, one panel row per round + a two-peer flight
recorder) and asserts the plane's whole contract:

  1. **one compile, zero host transfers** — the full ROUNDS-round run
     executes under ``jax.transfer_guard('disallow')`` and the step's
     compile cache grows by exactly 1 (cache-size sentinel): the
     recorder writes every round as plain device ops inside the one
     compiled program, never by polling the host.
  2. **exact reconciliation** — summed per-round EV deltas of the
     recorded panel equal the end-of-run drained counters bit-for-bit
     (telemetry/panel.reconcile). A panel that drifts from the
     counters is lying about the run; the gate hard-stops on it.
  3. **telemetry-on kernel census** — the compiled phase-step (r=8)
     kernel total with telemetry on is pinned against the committed
     TELEMETRY_SMOKE.json (ceiling TELEMETRY_SMOKE_KERNEL_TOL, default
     1.10 — looser than PERF_SMOKE's 1.05 because the committed number
     also rides XLA-version fusion jitter across images). The
     image-independent invariant is checked alongside: the on-vs-off
     census delta measured FRESH on this machine must stay within the
     committed extra-kernel budget x the same tolerance.
  4. **overhead ceiling** — warm-vs-warm, same build except the
     TelemetryConfig (both with live counters, so the delta isolates
     the recorder): telemetry-on must run no more than
     TELEMETRY_SMOKE_OVERHEAD (default 0.15 = 15%) slower.

``TELEMETRY_SMOKE_UPDATE=1`` rewrites the baseline from this run
(same workflow as PERF_SMOKE / ENSEMBLE_SMOKE). CPU-only by contract,
like the other smoke gates; telemetry-OFF elision is pinned separately
by chaos-smoke's census-equality gate and
tests/test_telemetry.py::test_telemetry_on_is_bitwise_additive.
"""

from __future__ import annotations

import argparse
import json
import os
import sys
import time

_here = os.path.dirname(os.path.abspath(__file__))
sys.path.insert(0, os.path.dirname(_here))

import numpy as np  # noqa: E402

BASELINE_NAME = "TELEMETRY_SMOKE.json"
SMOKE_ROUNDS = 48
#: warm-vs-warm slowdown ceiling for the telemetry-on build
DEFAULT_OVERHEAD = 0.15
#: census ceiling vs the committed baseline (and for the extra-kernel
#: budget) — absorbs cross-image XLA fusion jitter
DEFAULT_KERNEL_TOL = 1.10
TIMING_REPS = 3


def _fresh(state):
    """Donatable copy of a state tree (jitted steps donate their state
    argument, so every run window needs its own buffers — key leaves
    included, or the first window's donation deletes the shared key)."""
    import jax
    import jax.numpy as jnp

    from go_libp2p_pubsub_tpu.checkpoint import is_prng_key

    def cp(x):
        if is_prng_key(x):
            return jax.random.wrap_key_data(
                jnp.copy(jax.random.key_data(x)), impl=jax.random.key_impl(x))
        return jnp.copy(x)

    return jax.tree_util.tree_map(cp, state)


def _pub_args(n: int, rounds: int):
    """One valid publish per round from a rotating origin — enough to
    keep the allocator/delivery path live in the timed window."""
    import jax.numpy as jnp

    from go_libp2p_pubsub_tpu.perf.sweep import PUBS_PER_ROUND

    out = []
    for i in range(rounds):
        po = np.full((PUBS_PER_ROUND,), -1, np.int32)
        po[0] = i % n
        out.append((jnp.asarray(po),
                    jnp.asarray(np.zeros((PUBS_PER_ROUND,), np.int32)),
                    jnp.asarray(np.ones((PUBS_PER_ROUND,), bool))))
    return out


def _build(n: int, rounds: int, telemetry_on: bool):
    """(state, step, tcfg) — the bench gossipsub per-round step with
    live counters; only the TelemetryConfig differs between the on and
    off builds, so their timing delta isolates the recorder."""
    from go_libp2p_pubsub_tpu.perf.sweep import build_bench
    from go_libp2p_pubsub_tpu.telemetry import TelemetryConfig

    tcfg = (TelemetryConfig(rows=rounds, tracked=(0, 7))
            if telemetry_on else None)
    st, step, _, _ = build_bench(n, 64, heartbeat_every=1,
                                 rounds_per_phase=1, telemetry=tcfg,
                                 count_events=True)
    return st, step, tcfg


def _timed_window(step, state, args) -> float:
    """Seconds for one warm run over ``args`` (state must be fresh —
    the step donates it)."""
    import jax

    t0 = time.perf_counter()
    for a in args:
        state = step(state, *a)
    jax.block_until_ready(state)
    return time.perf_counter() - t0


def run_gate(n: int, rounds: int) -> dict:
    import jax

    from go_libp2p_pubsub_tpu.ensemble.runner import _cache_size
    from go_libp2p_pubsub_tpu.telemetry import reconcile

    failures: list[str] = []
    args = _pub_args(n, rounds)

    # --- guarded telemetry-on run: one compile, zero host transfers --
    st_on, step_on, tcfg = _build(n, rounds, telemetry_on=True)
    before = _cache_size(step_on)
    st_fin = _fresh(st_on)
    with jax.transfer_guard("disallow"):
        for a in args:
            st_fin = step_on(st_fin, *a)
        jax.block_until_ready(st_fin)
    after = _cache_size(step_on)
    compiles = -1 if before is None or after is None else after - before
    if compiles not in (-1, 1):
        failures.append(
            f"one-compile: telemetry-on step compiled {compiles} times "
            f"across the {rounds}-round run (expected exactly 1)"
        )

    # --- reconciliation (host side, outside the run window) ----------
    panel = np.asarray(st_fin.core.telem.panel)
    events = np.asarray(st_fin.core.events)
    mism = reconcile(panel, events)
    if mism:
        failures.append(
            "drain-vs-timeline reconciliation failed: " + "; ".join(mism[:4])
        )
    from go_libp2p_pubsub_tpu.telemetry.panel import _EV_COL0, EV_METRICS
    if panel[:, _EV_COL0:_EV_COL0 + len(EV_METRICS)].sum() <= 0:
        failures.append("telemetry panel recorded no events — the run "
                        "window never exercised the recorder")

    # --- warm-vs-warm overhead ---------------------------------------
    st_off, step_off, _ = _build(n, rounds, telemetry_on=False)
    # warm the off build (the on build is warm from the guarded run)
    _timed_window(step_off, _fresh(st_off), args)
    t_on = min(_timed_window(step_on, _fresh(st_on), args)
               for _ in range(TIMING_REPS))
    t_off = min(_timed_window(step_off, _fresh(st_off), args)
                for _ in range(TIMING_REPS))
    overhead = t_on / t_off - 1.0
    ceiling = float(os.environ.get("TELEMETRY_SMOKE_OVERHEAD",
                                   DEFAULT_OVERHEAD))
    if overhead > ceiling:
        failures.append(
            f"overhead: telemetry-on ran {100 * overhead:.1f}% slower "
            f"than telemetry-off warm-vs-warm (ceiling {100 * ceiling:.0f}%"
            f"; {t_on:.3f}s vs {t_off:.3f}s over {rounds} rounds)"
        )

    # --- telemetry-on kernel census (phase r=8, the PERF_SMOKE shape) -
    from go_libp2p_pubsub_tpu.perf.profile import compiled_phase_kernel_count
    from go_libp2p_pubsub_tpu.perf.regress import PERF_SMOKE_R
    from go_libp2p_pubsub_tpu.telemetry import TelemetryConfig

    r = PERF_SMOKE_R
    census_on = compiled_phase_kernel_count(
        n, r, telemetry=TelemetryConfig(rows=max(rounds // r, 1)))
    census_off = compiled_phase_kernel_count(n, r)
    # image-portable (round 14): the hard census gate compares against
    # the measured-on-this-image baseline; the committed value is an
    # informational pin (perf.profile.on_image_census_baseline)
    from go_libp2p_pubsub_tpu.perf.profile import on_image_census_baseline

    # the UPDATE path reseeds the on-image entries too — a deliberate
    # recorder change is accepted the same way the committed rewrite is
    upd = bool(os.environ.get("TELEMETRY_SMOKE_UPDATE"))
    oni_on = on_image_census_baseline(census_on, variant="telemetry_on",
                                      update=upd)
    oni_off = on_image_census_baseline(census_off, update=upd)

    return {
        "census_on_on_image": oni_on["total"],
        "census_off_on_image": oni_off["total"],
        "on_image_seeded": oni_on["seeded"] or oni_off["seeded"],
        "failures": failures,
        "compiles": compiles,
        "rate_on": round(rounds / t_on, 2),
        "rate_off": round(rounds / t_off, 2),
        "overhead_frac": round(overhead, 4),
        "census_on_total": census_on["total"],
        "census_off_total": census_off["total"],
        "extra_kernels": census_on["total"] - census_off["total"],
        "n_peers": n,
        "rounds": rounds,
        "rounds_per_phase": r,
    }


def check_baseline(root: str, res: dict) -> list[str]:
    """Census ceiling — image-portable since round 14: the hard gate
    compares against the on-image baselines (seeded by the first run
    on this image); the committed TELEMETRY_SMOKE.json values are an
    informational pin (printed when they drift, never failed — census
    counts are image-dependent, PR 8's 324-vs-393 lesson)."""
    tol = float(os.environ.get("TELEMETRY_SMOKE_KERNEL_TOL",
                               DEFAULT_KERNEL_TOL))
    out = []
    if not res["on_image_seeded"]:
        if res["census_on_total"] > tol * res["census_on_on_image"]:
            out.append(
                f"telemetry-on kernel census regressed: "
                f"{res['census_on_total']} > {tol:.2f} x on-image "
                f"baseline {res['census_on_on_image']} "
                f"(TELEMETRY_SMOKE_KERNEL_TOL overrides)"
            )
        budget = res["census_on_on_image"] - res["census_off_on_image"]
        if budget > 0 and res["extra_kernels"] > tol * budget:
            out.append(
                f"telemetry recorder kernel budget blown: "
                f"+{res['extra_kernels']} kernels over the telemetry-off "
                f"build (on-image budget +{budget}, tol {tol:.2f}) — the "
                "panel write stopped fusing"
            )
    path = os.path.join(root, BASELINE_NAME)
    if not os.path.exists(path) or os.environ.get("TELEMETRY_SMOKE_UPDATE"):
        return out
    with open(path) as f:
        base = json.load(f)
    if (int(base.get("n_peers", res["n_peers"])) != res["n_peers"]
            or int(base.get("rounds_per_phase", res["rounds_per_phase"]))
            != res["rounds_per_phase"]):
        return out  # reshape run: the committed census is shape-specific
    committed = base.get("census_on_total")
    if committed is not None and res["census_on_total"] != committed:
        print(
            f"telemetry-smoke NOTE: telemetry-on census "
            f"{res['census_on_total']} != committed {committed} "
            f"({BASELINE_NAME}) — informational pin; the hard gate uses "
            f"the on-image baseline {res['census_on_on_image']}",
            file=sys.stderr,
        )
    return out


def write_baseline(root: str, res: dict) -> str:
    path = os.path.join(root, BASELINE_NAME)
    doc = {
        "schema": 1,
        "note": ("telemetry-plane smoke baseline (scripts/telemetry_smoke"
                 ".py); TELEMETRY_SMOKE_UPDATE=1 rewrites. rate_* are "
                 "per-round-engine rounds/s on the gate machine; census "
                 "totals are compiled phase-step (r=8) kernel counts."),
        **{k: res[k] for k in (
            "n_peers", "rounds", "rounds_per_phase", "rate_on", "rate_off",
            "overhead_frac", "census_on_total", "census_off_total",
            "extra_kernels")},
    }
    with open(path, "w") as f:
        json.dump(doc, f, indent=2)
        f.write("\n")
    return path


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    ap.add_argument("--n", type=int,
                    default=int(os.environ.get("TELEMETRY_SMOKE_N", 0)) or None)
    ap.add_argument("--rounds", type=int, default=SMOKE_ROUNDS)
    args = ap.parse_args(argv)

    import jax

    # regress.py policy: the gate is CPU-only and uses the bench PRNG
    jax.config.update("jax_platforms", "cpu")
    jax.config.update("jax_default_prng_impl", "unsafe_rbg")

    from go_libp2p_pubsub_tpu.compile_cache import enable_persistent_cache
    from go_libp2p_pubsub_tpu.perf.regress import PERF_SMOKE_N, repo_root

    root = repo_root()
    enable_persistent_cache(os.path.join(root, ".jax_cache"))
    n = args.n or PERF_SMOKE_N

    res = run_gate(n, args.rounds)
    failures = list(res["failures"]) + check_baseline(root, res)
    if os.environ.get("TELEMETRY_SMOKE_UPDATE") and not res["failures"]:
        print(f"wrote {write_baseline(root, res)}")

    print(json.dumps({
        "telemetry_smoke": "PASS" if not failures else "FAIL",
        **{k: v for k, v in res.items() if k != "failures"},
        "failures": failures,
    }))
    return 1 if failures else 0


if __name__ == "__main__":
    sys.exit(main())
