"""``make lift-audit`` — the liftability audit gate (docs/DESIGN.md §16).

Three legs, any failing exits non-zero:

  1. **soundness** — every field the shipped ``score.params.ScoreParams``
     plane lifts must be PROVEN liftable by the dataflow pass
     (``analysis/lift.py``): verdict VALUE or VALUE_GUARDED, with at
     least one classified use site. A SHAPE verdict on a lifted field
     means the lift is unsound and the gate fails loudly.
  2. **manifest parity** — the pass's ``SCORE_PLANE_FIELDS`` and the
     plane's ``LIFTED_FIELD_NAMES`` must be identical sets, so the
     audit and the shipped plane cannot drift apart.
  3. **byte-identical reproduction** — the committed ``LIFT_AUDIT.json``
     must equal this run's audit byte for byte (the MEM_AUDIT pattern:
     the artifact is a deterministic function of the source tree).
     ``LIFT_UPDATE=1`` rewrites it instead.

Pure AST analysis — no jax import, no device, <1 s.
"""

from __future__ import annotations

import json
import os
import sys

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)


def main(repo: str | None = None) -> int:
    """``repo`` overrides the artifact root (the doctored-artifact
    negative tests point it at a tmp copy; default: this checkout)."""
    from go_libp2p_pubsub_tpu.analysis import lift
    from go_libp2p_pubsub_tpu.score.params import (
        LIFTED_FIELD_NAMES,
        MESH_LIFTED_FIELD_NAMES,
    )

    repo = repo or REPO
    failures: list[str] = []
    payload = lift.audit()

    failures.extend(lift.check_plane(payload["fields"]))

    want = set(LIFTED_FIELD_NAMES)
    got = set(lift.SCORE_PLANE_FIELDS)
    if want != got:
        failures.append(
            "plane manifest drift: analysis/lift.py SCORE_PLANE_FIELDS "
            f"vs score/params.py LIFTED_FIELD_NAMES — only in pass: "
            f"{sorted(got - want)}; only in plane: {sorted(want - got)}"
        )

    want_m = set(MESH_LIFTED_FIELD_NAMES)
    got_m = set(lift.MESH_PLANE_FIELDS)
    if want_m != got_m:
        failures.append(
            "mesh plane manifest drift: analysis/lift.py "
            "MESH_PLANE_FIELDS vs score/params.py "
            f"MESH_LIFTED_FIELD_NAMES — only in pass: "
            f"{sorted(got_m - want_m)}; only in plane: "
            f"{sorted(want_m - got_m)}"
        )

    path = lift.audit_path(repo)
    text = lift.dump_audit(payload)
    update = bool(os.environ.get("LIFT_UPDATE"))
    if update:
        with open(path, "w") as f:
            f.write(text)
        action = "updated"
    elif not os.path.exists(path):
        failures.append(
            f"{lift.AUDIT_NAME} missing — run LIFT_UPDATE=1 "
            "scripts/lift_audit.py to record it"
        )
        action = "missing"
    else:
        with open(path) as f:
            committed = f.read()
        if committed != text:
            # name the diverging keys (round-19 satellite — the shared
            # walker every byte-identity gate uses); fall back to the
            # generic message when the committed file is not even JSON
            try:
                from go_libp2p_pubsub_tpu.analysis.costmodel import (
                    baseline_divergences,
                )

                diverged = baseline_divergences(
                    json.loads(committed), json.loads(text))
                detail = (" — diverging keys: " + "; ".join(diverged)
                          if diverged else
                          " — artifacts parse equal: formatting-only "
                          "drift (re-serialize with LIFT_UPDATE=1)")
            except (json.JSONDecodeError, ValueError):
                detail = " — committed artifact is not parseable JSON"
            failures.append(
                f"{lift.AUDIT_NAME} does not reproduce byte-identical — "
                "the device-scope sources changed the classification; "
                "review the verdict diff and LIFT_UPDATE=1 to re-record"
                + detail
            )
        action = "verified" if committed == text else "stale"

    summary = {
        "lift_audit": "FAIL" if failures else "PASS",
        "artifact": action,
        **payload["summary"],
        "lifted_fields": len(lift.SCORE_PLANE_FIELDS),
        "mesh_fields": len(lift.MESH_PLANE_FIELDS),
    }
    if failures:
        for f in failures:
            print(f"lift-audit FAIL: {f}", file=sys.stderr)
    print(json.dumps(summary))
    return 1 if failures else 0


if __name__ == "__main__":
    sys.exit(main())
