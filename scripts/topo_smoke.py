"""`make topo-smoke`: the power-law sparse-plane A/B gate (round 18).

The PR-11 sparse data plane was committed as a tradeoff number — dense
rolls beat CSR 3× on the 100%-dense banded bench ring (BENCH_r06).
This gate runs the A/B on the graph family the paper's deployments
actually have (power-law degree distributions with mean degree ≪ the
capacity cap K; arXiv:1507.08417) and asserts the sparse plane WINS
there, on both axes:

  * **delivery-rounds/s** — both layouts run the identical
    attestation-storm workload (one canonical edge list, one publish
    schedule, identical per-sim chaos/PRNG streams) as ONE scanned
    S-sim window per layout; warm-vs-warm, csr must beat dense by at
    least the committed ``rate_lift_floor``;
  * **audited bytes moved** — the trace-time halo-bytes tally
    (ops/edges.tally_halo_bytes: the edge involution + neighbor-view
    seams) per round; the csr/dense ratio is the topology density by
    construction, and the gate asserts csr < dense;

while the PAIRING holds: per-sim delivered/duplicate/RPC counters must
be BIT-IDENTICAL across the two layouts (same graph, same streams —
the layout changes how, never what), and each layout's window compiles
exactly once (cache sentinel).

Round 21 adds the FUSED csr cell (``Net.build(..., fused=True)`` —
the capacity-bounded delivery composites, docs/DESIGN.md §21) as a
third A/B arm: its per-sim counters must stay bit-identical to the
unfused csr run (the fusion changes how, never what) and its
statically-priced hbm_bytes/round must stay within a tight ceiling of
the unfused price. NOTE the measured sign: on THIS cell the fused arm
prices slightly ABOVE unfused (~1.04x) — floodsub has no heartbeat, so
none of the fused selection win applies, and at max_degree=64 the
capacity-bounded scan pays ceil(log2(64))=6 full-width passes where
the work-efficient associative scan amortizes below that. The >= 20%
fused traffic CUT lives where the heartbeat does: the gossipsub csr
bench row (COST_AUDIT.json's fusion contract; `make fuse-smoke`). The
committed artifact records both sides of that tradeoff.

TOPO_SMOKE_UPDATE=1 rewrites TOPO_SMOKE.json from this run (floors at
wide margins — scale-feasibility style, not perf-regression style) and
cuts the BENCH_r08.json artifact triple: schema-v3 lines with the
``fingerprint["topology"]`` block AND the round-19
``fingerprint["cost"]`` block now POPULATED (the committed BENCH_r07
pair predates the cost audit and reads back the COST_UNAUDITED
sentinel — r08 retires that read for the power-law cell; the headline
``parsed`` line is the fused csr run, ``parsed_unfused`` /
``parsed_dense`` ride alongside).
"""

from __future__ import annotations

import json
import os
import sys
import time

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)

BASELINE_PATH = os.path.join(REPO, "TOPO_SMOKE.json")
BENCH_PATH = os.path.join(REPO, "BENCH_r08.json")

N = int(os.environ.get("TOPO_SMOKE_N", 4096))
MAX_DEGREE = int(os.environ.get("TOPO_SMOKE_K", 64))
EXPONENT = 2.2
D_MIN = 2
MSG_SLOTS = 64
ROUNDS = int(os.environ.get("TOPO_SMOKE_ROUNDS", 32))
PUB_WIDTH = 8
SIMS = 4
SEED = 0
LOSS = 0.1

#: update-mode margins: the lift floor commits at half the measured
#: margin above 1.0 (never below 1.0 — "csr beats dense" is the gate)
RATE_MARGIN = 0.5

#: fused/unfused csr hbm price ceiling on this (heartbeat-less,
#: cap=64) cell: the fused scan pays a small known premium here (see
#: module docstring) and may never grow past it — growth means the
#: fused composites regressed, not just traded
FUSED_HBM_CEILING = 1.10


def run_cell(layout: str, net, el):
    """One layout's S-sim scanned window: returns (rate, per-sim event
    counters, bytes/round, compile-count sentinel)."""
    import jax
    import jax.numpy as jnp
    import numpy as np

    from go_libp2p_pubsub_tpu import driver, ensemble, topo
    from go_libp2p_pubsub_tpu.chaos.faults import ChaosConfig
    from go_libp2p_pubsub_tpu.models.floodsub import floodsub_step
    from go_libp2p_pubsub_tpu.state import SimState

    chaos = ChaosConfig(generator="iid", loss_rate=LOSS)

    def step(st, po, pt, pv):
        return floodsub_step(net, st, po, pt, pv, chaos=chaos)

    po, pt, pv = topo.publish_bursts(
        "attestation_storm", ROUNDS, PUB_WIDTH, N, seed=1,
        period=8, burst_len=2)
    xs = (jnp.asarray(np.repeat(po[:, None], SIMS, axis=1)),
          jnp.asarray(np.repeat(pt[:, None], SIMS, axis=1)),
          jnp.asarray(np.repeat(pv[:, None], SIMS, axis=1)))

    ens = ensemble.lift_step(jax.jit(step, donate_argnums=0))
    window = driver.make_window(ens)

    def fresh():
        return ensemble.batch_states(
            SimState.init(N, MSG_SLOTS, k=net.max_degree,
                          n_edges=net.n_edges), SIMS)

    st, _ = window(fresh(), xs)         # compile + warm
    jax.block_until_ready(st.events)

    st2 = fresh()
    jax.block_until_ready(st2.events)
    t0 = time.perf_counter()
    st2, _ = window(st2, xs)
    jax.block_until_ready(st2.events)
    warm_s = time.perf_counter() - t0

    try:
        n_compiles = int(window._cache_size())
    except Exception:  # pragma: no cover — older jax without the API
        n_compiles = -1  # sentinel: UNKNOWN, skips the gate visibly
    events = np.asarray(st2.events)      # [S, N_EVENTS]

    # audited bytes + the round-19 static price, from ONE trace of the
    # UNJITTED step body (a jitted call under tracing can hit the
    # jaxpr cache and tally nothing — edges.TallyCacheHit owns that):
    # costmodel.cost_of arms the same ops/edges byte-tally seams the
    # old tally_step leg measured, so halo_bytes IS the audited
    # bytes-moved number, and flops/hbm ride along for the
    # fingerprint["cost"] block. The independent model-vs-tally
    # cross-check lives in `make cost-audit`'s halo-measured contract.
    from go_libp2p_pubsub_tpu.analysis import costmodel

    def raw_step(st, po_r, pt_r, pv_r):
        return floodsub_step.__wrapped__(net, st, po_r, pt_r, pv_r,
                                         chaos=chaos)

    args1 = (jnp.asarray(po[0]), jnp.asarray(pt[0]), jnp.asarray(pv[0]))
    cost = costmodel.cost_of(
        lambda s: raw_step(s, *args1),
        SimState.init(N, MSG_SLOTS, k=net.max_degree,
                      n_edges=net.n_edges))
    bpr = cost["halo_bytes"]
    assert bpr > 0, "halo-bytes tally is empty — engine moved nothing?"
    return {
        "layout": layout,
        "rounds_per_sec": round(ROUNDS / warm_s, 3),
        "warm_s": round(warm_s, 4),
        "events_per_sim": events,
        "bytes_per_round": int(bpr),
        "cost_per_round": {k: cost[k] for k in
                           ("flops", "hbm_bytes", "halo_bytes",
                            "rng_bits")},
        "n_compiles": int(n_compiles),
    }


def run_smoke() -> dict:
    import numpy as np

    from go_libp2p_pubsub_tpu import graph, topo
    from go_libp2p_pubsub_tpu.trace.events import EV

    el = topo.powerlaw(N, exponent=EXPONENT, d_min=D_MIN,
                       max_degree=MAX_DEGREE, seed=SEED)
    subs = graph.subscribe_all(N, 1)
    _t, net_d, net_c = topo.build_nets(el, subs, max_degree=MAX_DEGREE)
    # the round-21 arm: same edge list, same streams, fused composites
    from go_libp2p_pubsub_tpu.state import Net

    net_f = Net.build(_t, subs, edge_layout="csr", fused=True)

    dense = run_cell("dense", net_d, el)
    csr = run_cell("csr", net_c, el)
    fused = run_cell("csr_fused", net_f, el)

    ev_d, ev_c = dense.pop("events_per_sim"), csr.pop("events_per_sim")
    ev_f = fused.pop("events_per_sim")
    paired_exact = bool(np.array_equal(ev_d, ev_c))
    fused_exact = bool(np.array_equal(ev_c, ev_f))
    delivered = [int(x) for x in ev_d[:, EV.DELIVER_MESSAGE]]
    return {
        "n_peers": N,
        "generator": "powerlaw",
        "exponent": EXPONENT,
        "max_degree": MAX_DEGREE,
        "n_edges": int(net_c.n_edges),
        "mean_degree": round(el.mean_degree, 3),
        "density": round(net_c.n_edges / float(N * net_d.max_degree), 4),
        "rounds": ROUNDS,
        "n_sims": SIMS,
        "workload": "attestation_storm",
        "engine": "floodsub",
        "loss_rate": LOSS,
        "dense": dense,
        "csr": csr,
        "csr_fused": fused,
        "rate_lift": round(csr["rounds_per_sec"]
                           / max(dense["rounds_per_sec"], 1e-9), 3),
        "bytes_ratio": round(csr["bytes_per_round"]
                             / max(dense["bytes_per_round"], 1), 4),
        "fused_hbm_ratio": round(
            fused["cost_per_round"]["hbm_bytes"]
            / max(csr["cost_per_round"]["hbm_bytes"], 1e-9), 4),
        "paired_per_sim_counters_exact": paired_exact,
        "fused_per_sim_counters_exact": fused_exact,
        "delivered_per_sim": delivered,
        "el": el,
    }


def bench_records(res: dict) -> dict:
    """The BENCH_r07 wrapper: dense + csr delivery-rounds/s lines with
    the round-18 fingerprint["topology"] block."""
    from go_libp2p_pubsub_tpu.perf.artifacts import (
        NORTH_STAR_RATE,
        chaos_fingerprint,
        cost_fingerprint,
        ensemble_fingerprint,
        topology_fingerprint,
    )
    from go_libp2p_pubsub_tpu.chaos.faults import ChaosConfig

    el = res["el"]
    topo_block = topology_fingerprint(
        generator="powerlaw",
        family="power-law",
        params={"exponent": EXPONENT, "d_min": D_MIN,
                "max_degree": MAX_DEGREE},
        n_edges=res["n_edges"],
        mean_degree=el.mean_degree,
        max_degree=el.max_degree,
        density=res["density"],
        seed=SEED,
        workload_pattern=res["workload"],
    )
    import jax

    def line(cell):
        rate = cell["rounds_per_sec"]
        fused = cell["layout"].endswith("_fused")
        layout = cell["layout"].removesuffix("_fused")
        return {
            "schema": 3,
            "metric": (f"floodsub_delivery_rounds_per_sec_n{N}_"
                       f"powerlaw_{cell['layout']}"),
            "value": rate,
            "unit": "delivery-rounds/s",
            "vs_baseline": round(rate / NORTH_STAR_RATE, 6),
            "unit_note": ("power-law topo-smoke cell (scripts/"
                          "topo_smoke.py): S-sim scanned window, warm; "
                          "CPU-image measurement like BENCH_r06"),
            "fingerprint": {
                "config": "topo_powerlaw",
                "n_peers": N,
                "msg_slots": MSG_SLOTS,
                "degree": MAX_DEGREE,
                "n_topics": 1,
                "rounds_per_phase": 1,
                "heartbeat_every": 1,
                "pubs_per_round": PUB_WIDTH,
                "engine": {"mode": "per_round",
                           "edge_layout": layout,
                           "fused": fused,
                           "router": "floodsub"},
                "chaos": chaos_fingerprint(
                    ChaosConfig(generator="iid", loss_rate=LOSS)),
                "ensemble": ensemble_fingerprint(n_sims=SIMS),
                "topology": topo_block,
                "bytes_per_round_audited": cell["bytes_per_round"],
                # the round-19 static price (legacy lines read back
                # perf.artifacts.COST_UNAUDITED via BenchRecord.cost)
                "cost": cost_fingerprint(
                    build=f"floodsub_{cell['layout']}",
                    flops_per_round=cell["cost_per_round"]["flops"],
                    hbm_bytes_per_round=cell["cost_per_round"]["hbm_bytes"],
                    halo_bytes_per_round=cell["cost_per_round"]["halo_bytes"],
                    rng_bits_per_round=cell["cost_per_round"]["rng_bits"],
                ),
                "platform": jax.default_backend(),
            },
        }

    return {
        "n": 8,
        "cmd": "python scripts/topo_smoke.py (TOPO_SMOKE_UPDATE=1)",
        "rc": 0,
        "note": ("round-21 power-law A/B/C: the fused csr plane "
                 "(headline) vs the unfused csr and dense arms — per-sim "
                 "counters bit-identical across all three (the fusion "
                 "and the layout change how, never what), and every "
                 "line's fingerprint['cost'] block is POPULATED (the "
                 "BENCH_r07 pair predates the cost audit and reads the "
                 "COST_UNAUDITED sentinel; this artifact retires that "
                 "read for the power-law cell)"),
        "parsed": line(res["csr_fused"]),
        "parsed_unfused": line(res["csr"]),
        "parsed_dense": line(res["dense"]),
    }


def main() -> int:
    import jax

    jax.config.update("jax_platforms", "cpu")

    from go_libp2p_pubsub_tpu.compile_cache import enable_persistent_cache

    enable_persistent_cache(os.path.join(REPO, ".jax_cache"))

    res = run_smoke()
    el = res.pop("el")
    print(json.dumps(res, indent=1))

    failures = []
    if not res["paired_per_sim_counters_exact"]:
        failures.append("per-sim counters differ across layouts — the "
                        "pairing (identical graph + streams) broke")
    if not res["fused_per_sim_counters_exact"]:
        failures.append("per-sim counters differ fused-vs-unfused on the "
                        "csr plane — the fused composites changed WHAT "
                        "was delivered, not just how")
    if res["fused_hbm_ratio"] > FUSED_HBM_CEILING:
        failures.append(
            f"static price: fused/unfused csr hbm_bytes ratio "
            f"{res['fused_hbm_ratio']} over the {FUSED_HBM_CEILING} "
            "ceiling — the fused composites regressed past their known "
            "heartbeat-less premium on this cell")
    if any(d <= 0 for d in res["delivered_per_sim"]):
        failures.append("a sim delivered nothing — dead wire")
    compiles = (res["dense"]["n_compiles"], res["csr"]["n_compiles"],
                res["csr_fused"]["n_compiles"])
    if -1 in compiles:
        # UNKNOWN must not read as the passing value 1 — say so out loud
        print("topo-smoke: one-compile sentinel UNAVAILABLE "
              "(window._cache_size missing) — compile-count gate skipped")
    elif compiles != (1, 1, 1):
        failures.append(
            f"one-compile sentinel: dense={res['dense']['n_compiles']} "
            f"csr={res['csr']['n_compiles']} "
            f"csr_fused={res['csr_fused']['n_compiles']}")
    if res["bytes_ratio"] >= 1.0:
        failures.append(
            f"audited bytes: csr/dense ratio {res['bytes_ratio']} >= 1 "
            "— the sparse layout stopped saving wire bytes")
    if res["rate_lift"] <= 1.0:
        failures.append(
            f"rate: csr {res['csr']['rounds_per_sec']} <= dense "
            f"{res['dense']['rounds_per_sec']} delivery-rounds/s — the "
            "sparse plane lost on its own regime")

    update = bool(os.environ.get("TOPO_SMOKE_UPDATE"))
    if update or not os.path.exists(BASELINE_PATH):
        if failures:
            print("topo-smoke: FAIL (refusing to baseline a broken run):")
            for f in failures:
                print("  -", f)
            return 1
        lift_floor = round(1.0 + (res["rate_lift"] - 1.0) * RATE_MARGIN, 3)
        baseline = {
            "note": ("topo-smoke baseline (scripts/topo_smoke.py; "
                     "TOPO_SMOKE_UPDATE=1 rewrites)"),
            "n_peers": N,
            "max_degree": MAX_DEGREE,
            "rounds": ROUNDS,
            "n_sims": SIMS,
            "engine": "floodsub",
            "workload": "attestation_storm",
            "density": res["density"],
            "rate_lift_floor": max(lift_floor, 1.0),
            "bytes_ratio_ceiling": round(
                min(res["bytes_ratio"] * 1.25, 0.999), 4),
            # informational: the fused arm's static traffic cut on this
            # cell (the hard <1.0 gate is unconditional in main())
            "fused_hbm_ratio": res["fused_hbm_ratio"],
        }
        with open(BASELINE_PATH, "w") as f:
            json.dump(baseline, f, indent=1, sort_keys=True)
            f.write("\n")
        print(f"topo-smoke: wrote {BASELINE_PATH}")
        res["el"] = el
        wrapper = bench_records(res)
        with open(BENCH_PATH, "w") as f:
            json.dump(wrapper, f, indent=1, sort_keys=True)
            f.write("\n")
        print(f"topo-smoke: wrote {BENCH_PATH}")
        return 0

    with open(BASELINE_PATH) as f:
        base = json.load(f)
    shape_keys = ("n_peers", "max_degree", "rounds", "n_sims", "engine",
                  "workload")
    mismatched = [k for k in shape_keys if res[k] != base.get(k)]
    if not mismatched:
        if res["rate_lift"] < base["rate_lift_floor"]:
            failures.append(
                f"rate lift {res['rate_lift']} below the committed floor "
                f"{base['rate_lift_floor']}")
        if res["bytes_ratio"] > base["bytes_ratio_ceiling"]:
            failures.append(
                f"bytes ratio {res['bytes_ratio']} above the committed "
                f"ceiling {base['bytes_ratio_ceiling']}")
    else:
        print("topo-smoke: NOTE — run shape differs from the committed "
              "baseline on %s; lift/bytes gates SKIPPED (pairing + "
              "delivery + one-compile gates still apply)" % mismatched)

    if failures:
        print("topo-smoke: FAIL")
        for f in failures:
            print("  -", f)
        return 1
    print("topo-smoke: PASS — csr %.1f vs dense %.1f delivery-rounds/s "
          "(lift %.2fx) at density %.3f; audited bytes ratio %.3f; "
          "fused/unfused hbm ratio %.3f; per-sim counters bit-identical "
          "across all three arms"
          % (res["csr"]["rounds_per_sec"], res["dense"]["rounds_per_sec"],
             res["rate_lift"], res["density"], res["bytes_ratio"],
             res["fused_hbm_ratio"]))
    return 0


if __name__ == "__main__":
    sys.exit(main())
