#!/usr/bin/env python
"""2-D (sims × peers) mesh dryrun on the 8-virtual-device CPU harness
(docs/DESIGN.md §14) — the round-14 refresh of the MULTICHIP artifact
series.

Builds an S=8 ensemble window of the bench gossipsub step, places it on
a ``parallel.make_mesh_2d(2, 4)`` mesh via
``ensemble.shard_ensemble_state(axis="sims+peers")`` (sim axis over 2
mesh rows, peer axis over 4 columns), runs the whole window as ONE scan
dispatch, and checks:

  * **bit-exactness** — the placed run equals the unplaced batched run
    leaf-for-leaf (placement must never change a value);
  * **collective profile** — the compiled window contains halo
    collective-permutes and ZERO peer-sized all-gathers, exactly like
    the 1-D audit (tests/test_collectives.py): the sims axis adds no
    collectives (each row is an independent replica of the 1-D layout).

Writes the MULTICHIP_r06.json wrapper (same shape the driver's
multichip artifacts carry: n_devices/rc/ok/skipped/tail, plus the mesh
shape and collective profile) that scan-smoke's projection refresh
gates on.

Round 18 adds the **sharded-CSR cell** (MULTICHIP_r07.json): the same
S=8 window built on ``edge_layout="csr"`` — CSR-RESIDENT flat [S, E, W]
state planes placed via ``shard_ensemble_state(axis="sims+peers",
n_edges=E)`` (the edge axis partitions with the peer axis; row-owner
alignment is free on the full-density bench ring). Asserts the same
three contracts as the dense cell — bit-exact vs unplaced, halo
collective-permutes present, ZERO all-gathers (the flat gathers lower
through the banded-roll structure, state.Net.csr_band_off) — plus the
trace-time halo-gather tally EQUAL to the dense build's (the sparse
plane must not change the halo budget; `make hlo-audit` pins the same
equality at guard shapes). Usage:

    python scripts/mesh2d_dryrun.py [--n 4096] [--rounds 8] [--write]
"""

from __future__ import annotations

import argparse
import json
import os
import sys

_here = os.path.dirname(os.path.abspath(__file__))
sys.path.insert(0, os.path.dirname(_here))

ARTIFACT_NAME = "MULTICHIP_r06.json"
CSR_ARTIFACT_NAME = "MULTICHIP_r07.json"


def _halo_tally(step, state) -> dict:
    """Trace-time halo-gather tally of one step call (edges.tally_step
    owns the unjitted-body caveat)."""
    import jax.numpy as jnp
    import numpy as np

    from go_libp2p_pubsub_tpu.ops import edges
    from go_libp2p_pubsub_tpu.perf.sweep import PUBS_PER_ROUND

    po = jnp.asarray(np.zeros((PUBS_PER_ROUND,), np.int32))
    pt = jnp.zeros((PUBS_PER_ROUND,), jnp.int32)
    pv = jnp.ones((PUBS_PER_ROUND,), bool)
    return edges.fold_tally(edges.tally_step(step, state, (po, pt, pv)))


def run_dryrun(n: int, rounds: int, sims: int = 8,
               mesh_rows: int = 2, edge_layout: str = "dense") -> dict:
    import jax
    import jax.numpy as jnp
    import numpy as np

    from go_libp2p_pubsub_tpu import ensemble
    from go_libp2p_pubsub_tpu.checkpoint import is_prng_key
    from go_libp2p_pubsub_tpu.driver import make_window
    from go_libp2p_pubsub_tpu.parallel import (
        collective_profile,
        make_mesh_2d,
    )
    from go_libp2p_pubsub_tpu.perf.sweep import PUBS_PER_ROUND, build_bench

    n_dev = jax.device_count()
    if n_dev < mesh_rows * 2:
        return {"ok": False, "rc": 1, "skipped": True,
                "tail": f"needs >= {mesh_rows * 2} devices, have {n_dev}"}
    mesh = make_mesh_2d(mesh_rows, n_dev // mesh_rows)

    bench_kw = dict(config="default")
    if edge_layout != "dense":
        bench_kw["edge_layout"] = edge_layout
    st0, step, n_topics, _ = build_bench(n, 64, **bench_kw)
    # CSR-resident flat planes: the edge axis E shards with the peers
    # axis (row-owner-aligned for free on the full-density bench ring)
    n_edges = None
    if edge_layout == "csr":
        n_edges = int(st0.core.dlv.fe_words.shape[0])
    ens = ensemble.lift_step(step)
    rng = np.random.default_rng(0)
    po = jnp.asarray(np.stack([
        ensemble.tile(rng.integers(0, n, size=(PUBS_PER_ROUND,))
                      .astype(np.int32), sims)
        for _ in range(rounds)]))
    pt = jnp.zeros((rounds, sims, PUBS_PER_ROUND), jnp.int32)
    pv = jnp.ones((rounds, sims, PUBS_PER_ROUND), bool)
    window = make_window(ens)

    def batched():
        return ensemble.batch_states(
            build_bench(n, 64, **bench_kw)[0], sims)

    gold, _ = window(batched(), (po, pt, pv))
    jax.block_until_ready(gold)

    placed = ensemble.shard_ensemble_state(batched(), mesh, n,
                                           axis="sims+peers",
                                           n_edges=n_edges)
    lowered = window.lower(placed, (po, pt, pv))
    compiled = lowered.compile()
    prof = collective_profile(compiled.as_text())
    got, _ = window(placed, (po, pt, pv))
    jax.block_until_ready(got)

    def unkey(x):
        return jax.random.key_data(x) if is_prng_key(x) else x

    mismatches = []
    flat_a, _ = jax.tree_util.tree_flatten_with_path(gold)
    flat_b = jax.tree_util.tree_leaves(got)
    for (path, a), b in zip(flat_a, flat_b):
        if not bool(jnp.array_equal(unkey(a), unkey(b))):
            mismatches.append(jax.tree_util.keystr(path))
    ok = (not mismatches
          and prof["all-gather"] == 0
          and prof["collective-permute"] > 0)
    tail = (f"2-D mesh {mesh_rows}x{n_dev // mesh_rows} (sims x peers), "
            f"S={sims}, N={n}, {rounds}-round window as ONE dispatch, "
            f"edge_layout={edge_layout}; collectives={prof}; "
            + ("bit-exact vs unplaced" if not mismatches
               else f"MISMATCHED leaves: {mismatches[:5]}"))
    return {
        "n_devices": n_dev,
        "mesh_shape": {"sims": mesh_rows, "peers": n_dev // mesh_rows},
        "rc": 0 if ok else 1,
        "ok": ok,
        "skipped": False,
        "collectives": prof,
        "n_peers": n,
        "n_sims": sims,
        "rounds": rounds,
        "edge_layout": edge_layout,
        "n_edges": n_edges,
        "tail": tail,
    }


def run_dryrun_csr(n: int, rounds: int, sims: int = 8,
                   mesh_rows: int = 2) -> dict:
    """The round-18 sharded-CSR cell (module docstring): the csr
    window's contracts plus the dense-vs-csr halo-tally equality."""
    from go_libp2p_pubsub_tpu.perf.sweep import build_bench

    res = run_dryrun(n, rounds, sims=sims, mesh_rows=mesh_rows,
                     edge_layout="csr")
    if res.get("skipped"):
        return res
    st_d, step_d, _, _ = build_bench(n, 64, config="default")
    st_c, step_c, _, _ = build_bench(n, 64, config="default",
                                     edge_layout="csr")
    tally_d = _halo_tally(step_d, st_d)
    tally_c = _halo_tally(step_c, st_c)
    res["halo_tally"] = {"dense": tally_d, "csr": tally_c}
    if tally_d != tally_c:
        res["ok"] = False
        res["rc"] = 1
        res["tail"] += (f"; HALO TALLY DRIFT dense={tally_d} vs "
                        f"csr={tally_c}")
    else:
        res["tail"] += f"; halo tally equal to dense ({tally_d})"
    return res


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    ap.add_argument("--n", type=int, default=4096)
    ap.add_argument("--rounds", type=int, default=8)
    ap.add_argument("--write", action="store_true",
                    help=f"write {ARTIFACT_NAME} at the repo root")
    args = ap.parse_args(argv)

    # the virtual 8-device harness must be configured before jax's
    # backend initializes (the conftest/scaling_cpu_mesh mechanism)
    os.environ["JAX_PLATFORMS"] = "cpu"
    flags = os.environ.get("XLA_FLAGS", "")
    if "host_platform_device_count" not in flags:
        os.environ["XLA_FLAGS"] = (
            flags + " --xla_force_host_platform_device_count=8").strip()
    import jax

    jax.config.update("jax_platforms", "cpu")
    # threefry on purpose (the parity-gate PRNG, ensemble/batch.py):
    # its counter-mode draws are placement-invariant, so sharded ==
    # unplaced bit-for-bit; unsafe_rbg's RngBitGenerator partitioning
    # is not value-stable under GSPMD (the round-5..8 PRNG caveat)
    from go_libp2p_pubsub_tpu.compile_cache import enable_persistent_cache
    from go_libp2p_pubsub_tpu.perf.regress import repo_root

    root = repo_root()
    enable_persistent_cache(os.path.join(root, ".jax_cache"))

    res = run_dryrun(args.n, args.rounds)
    print(json.dumps(res))
    res_csr = run_dryrun_csr(args.n, args.rounds)
    print(json.dumps(res_csr))
    if args.write:
        path = os.path.join(root, ARTIFACT_NAME)
        with open(path, "w") as f:
            json.dump(res, f, indent=2)
            f.write("\n")
        print(f"wrote {path}", file=sys.stderr)
        path = os.path.join(root, CSR_ARTIFACT_NAME)
        with open(path, "w") as f:
            json.dump(res_csr, f, indent=2)
            f.write("\n")
        print(f"wrote {path}", file=sys.stderr)
    return 0 if (res["ok"] and res_csr["ok"]) else 1


if __name__ == "__main__":
    sys.exit(main())
