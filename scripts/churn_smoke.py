#!/usr/bin/env python
"""Dynamic-overlay churn-storm gate (``make churn-smoke``;
docs/DESIGN.md §22).

Drives a power-law gossipsub cell whose edge pool MUTATES mid-window —
20% of the peers killed and replaced, edges rewired, preferential-
attachment joins — entirely device-side from one host-compiled
``topo.MutationSchedule``, and asserts the round-22 contract:

  1. **storm control** — the supervised service loop runs the full
     storm with ZERO recoveries, the ``topo-involution`` probe and the
     mutation-aware folded invariants green at every boundary, and
     exactly ONE window compile across the whole mutating window (the
     recompile-free sentinel: joins/kills/rewires ride the scan ``xs``,
     never the program).
  2. **mesh reform + delivery bands** — after the killed cohort is
     replaced, the fraction of live peers holding at least one mesh
     edge recovers past ``CHURN_SMOKE_MESH`` (default 0.9) within one
     segment, and the post-heal per-dispatch delivery rate stays within
     ``CHURN_SMOKE_BAND`` (default 0.5) of the pre-kill rate —
     non-vacuously (the post-heal window must actually deliver).
  3. **dense-vs-CSR parity under mutation** — the SAME storm through
     the dense ``[N, K]`` and flat-``[E]`` CSR faces finishes with
     bit-identical event counters, delivery planes and topology planes.
  4. **bad-mutation localization** — an injected involution-breaking
     topology corruption (``FaultPlan(corrupt_kind="topo")``) trips the
     ``topo-involution`` probe at the segment boundary; the
     supervisor's rollback replay names EXACTLY the injected dispatch,
     the forensic bundle records both the probe and the
     ``edge-involution-wf`` oracle invariant, and the recovered run
     still finishes digest-identical to the control.
  5. **mid-storm resume** — a run checkpointed (format v6, no version
     bump) BETWEEN the kill and the replacement resumes from disk and
     finishes bit-exact vs the uninterrupted control.
  6. **census** — the dynamic plane is opt-in: the mutation-off
     compiled kernel census must still equal the on-image baseline
     (the chaos-report census leg, reused).

``CHURN_SMOKE_UPDATE=1`` rewrites CHURN_SMOKE.json from this run.
Env knobs: CHURN_SMOKE_N / _D / _SEG (shape), CHURN_SMOKE_SEED,
CHURN_SMOKE_MESH, CHURN_SMOKE_BAND, CHURN_SMOKE_TOL. CPU-only by
contract; census under the gate PRNG.
"""

from __future__ import annotations

import argparse
import dataclasses
import json
import os
import sys
import tempfile
import time

_here = os.path.dirname(os.path.abspath(__file__))
sys.path.insert(0, os.path.dirname(_here))
sys.path.insert(0, _here)

import numpy as np  # noqa: E402

BASELINE_NAME = "CHURN_SMOKE.json"
CELL_N = 48
CELL_D = 32
CELL_SEG = 8
CELL_MSG_SLOTS = 64
CELL_DEGREE = 14
KILL_FRAC = 0.2
DEFAULT_MESH = 0.9
DEFAULT_BAND = 0.5
DEFAULT_TOL = 0.4


def build_cell(n: int, d: int, seg: int, seed: int,
               edge_layout: str = "dense"):
    """The storm cell: a power-law overlay with spare capacity slots
    (joins/rewires need free slots), a churn_storm schedule, and the
    dynamic step + make_args/template_fn triple the supervisor
    consumes."""
    import jax.numpy as jnp

    from go_libp2p_pubsub_tpu import graph
    from go_libp2p_pubsub_tpu import topo as topolib
    from go_libp2p_pubsub_tpu.config import (
        GossipSubParams,
        PeerScoreThresholds,
    )
    from go_libp2p_pubsub_tpu.models.gossipsub import (
        GossipSubConfig,
        GossipSubState,
        make_gossipsub_step,
    )
    from go_libp2p_pubsub_tpu.state import Net

    el = topolib.powerlaw(n, max_degree=CELL_DEGREE - 4, seed=seed)
    tp = topolib.to_topology(el, max_degree=CELL_DEGREE)
    subs = graph.subscribe_all(n, 1)
    net = Net.build(tp, subs, edge_layout=edge_layout, dynamic=True)
    params = dataclasses.replace(GossipSubParams(), flood_publish=False)
    thr = PeerScoreThresholds(
        gossip_threshold=-2.0, publish_threshold=-4.0,
        graylist_threshold=-8.0, accept_px_threshold=10.0,
        opportunistic_graft_threshold=1.0)
    cfg = GossipSubConfig.build(params, thr, score_enabled=False,
                                edge_layout=edge_layout)
    sched = topolib.churn_storm(tp, n_dispatches=d, kill_frac=KILL_FRAC,
                                rewires=8, joins=2, join_links=2,
                                seed=seed)
    writes, up = sched.build()
    # one publish per dispatch from a peer that is UP at that dispatch
    # (a dead origin would make the post-kill delivery band vacuous)
    n_pub = 4
    po = np.full((d, n_pub), -1, np.int32)
    pt = np.zeros((d, n_pub), np.int32)
    pv = np.zeros((d, n_pub), bool)
    for i in range(d):
        live = np.flatnonzero(up[i])
        po[i, 0] = int(live[i % len(live)])
        pv[i, 0] = True

    step = make_gossipsub_step(cfg, net, dynamic_peers=True,
                               dynamic_topo=True)

    def make_args(i: int):
        return (po[i], pt[i], pv[i], up[i], writes[i])

    def template_fn():
        return GossipSubState.init(net, CELL_MSG_SLOTS, cfg, seed=seed,
                                   dynamic_topo=True)

    del jnp
    return {
        "net": net, "cfg": cfg, "sched": sched, "writes": writes,
        "up": up, "step": step, "make_args": make_args,
        "template_fn": template_fn, "kill_at": d // 4,
        "replace_at": d // 2,
    }


def make_invariants(cell, seg: int):
    from go_libp2p_pubsub_tpu.oracle import InvariantConfig, ScanInvariants

    return ScanInvariants(
        "gossipsub", cell["net"], cell["cfg"],
        InvariantConfig(check_every=seg, delivery_window=16),
        batched=False, due_fn=cell["sched"].due_fn(check_every=seg))


def make_supervisor(cell, root: str, n_dispatches: int, seg: int, *,
                    observe=None, faults=None):
    from go_libp2p_pubsub_tpu.oracle import HealthConfig
    from go_libp2p_pubsub_tpu.serve import (
        RetentionPolicy,
        ServiceConfig,
        Supervisor,
    )

    svc = ServiceConfig(
        n_dispatches=n_dispatches, segment_len=seg,
        health=HealthConfig(topo_involution=True, delivery_floor=1),
        retention=RetentionPolicy(keep_last=8),
        report_name=None)
    return Supervisor(cell["step"], cell["make_args"],
                      cell["template_fn"], root, svc,
                      invariants=make_invariants(cell, seg),
                      observe=observe, faults=faults)


def check_control(cell, work: str, n: int, d: int, seg: int,
                  failures: list):
    """Storm control: zero recoveries, one compile, green invariants,
    mesh reform + paired delivery bands from the folded observer."""
    from go_libp2p_pubsub_tpu.serve import state_digest
    from go_libp2p_pubsub_tpu.trace.events import EV

    def observe(st):
        return {"delivered": st.core.events[EV.DELIVER_MESSAGE],
                "mesh_any": st.mesh.any(axis=(1, 2))}

    sup = make_supervisor(cell, os.path.join(work, "control"), d, seg,
                          observe=observe)
    t0 = time.perf_counter()
    report = sup.run(fresh=True)
    dt = time.perf_counter() - t0
    if report.recoveries or report.retries:
        failures.append(
            f"control: clean storm reported recoveries="
            f"{report.recoveries} retries={report.retries}")
    bad = {k: v for k, v in report.window_compiles.items() if v != 1}
    if bad:
        failures.append(
            f"recompile-free: the mutating window compiled "
            f"{report.window_compiles} — joins/kills/rewires must ride "
            "the scan xs, never the program (exactly 1 per shape)")
    if not report.invariant_checks:
        failures.append("control: no invariant checks ran (vacuous gate)")

    obs = report.observations
    up = cell["up"]
    kill_at, replace_at = cell["kill_at"], cell["replace_at"]
    deliv = np.asarray(obs["delivered"], np.int64)
    deltas = np.diff(np.concatenate([[0], deliv]))
    mesh_any = np.asarray(obs["mesh_any"])
    live_frac = ((mesh_any & up).sum(axis=1)
                 / np.maximum(up.sum(axis=1), 1))

    mesh_floor = float(os.environ.get("CHURN_SMOKE_MESH", DEFAULT_MESH))
    reform = next((i for i in range(replace_at, d)
                   if live_frac[i] >= mesh_floor), None)
    latency = None if reform is None else reform - replace_at + 1
    if latency is None or latency > seg:
        failures.append(
            f"mesh-reform: live-peer mesh coverage did not recover to "
            f"{mesh_floor:.2f} within one segment of the replacement "
            f"(latency={latency}, coverage after replace: "
            f"{np.round(live_frac[replace_at:], 3).tolist()})")

    band = float(os.environ.get("CHURN_SMOKE_BAND", DEFAULT_BAND))
    pre = float(deltas[:kill_at].mean())
    post = float(deltas[replace_at + seg:].mean())
    if pre <= 0 or post <= 0:
        failures.append(
            f"delivery-band: vacuous storm (pre-kill {pre:.1f}, "
            f"post-heal {post:.1f} deliveries/dispatch — both must be "
            "positive)")
    elif post < band * pre:
        failures.append(
            f"delivery-band: post-heal delivery rate {post:.1f} < "
            f"{band:.2f} x pre-kill {pre:.1f} per dispatch "
            "(CHURN_SMOKE_BAND overrides)")
    return {
        "digest": state_digest(report.states),
        "report": report,
        "rounds_per_sec": round(d / dt, 2) if dt > 0 else 0.0,
        "reform_latency_dispatches": latency,
        "pre_kill_deliveries_per_dispatch": round(pre, 2),
        "post_heal_deliveries_per_dispatch": round(post, 2),
        "mesh_coverage_final": round(float(live_frac[-1]), 4),
    }


def check_parity(n: int, d: int, seg: int, seed: int, failures: list):
    """The same storm through the dense and CSR faces, scanned — every
    event counter, the delivery plane and the topology planes must be
    bit-identical."""
    from go_libp2p_pubsub_tpu.ensemble import WindowRunner

    finals = {}
    for layout in ("dense", "csr"):
        cell = build_cell(n, d, seg, seed, edge_layout=layout)
        runner = WindowRunner(cell["step"], d, segment_len=seg,
                              invariants=make_invariants(cell, seg))
        res = runner.run(cell["template_fn"](), cell["make_args"])
        if res.compiles not in (0, 1):
            failures.append(
                f"parity: {layout} storm window compiled {res.compiles} "
                "times (expected at most 1)")
        if res.invariant_report is not None \
                and not res.invariant_report.all_ok:
            failures.append(
                f"parity: {layout} storm violated invariants: "
                f"{res.invariant_report.violations()}")
        finals[layout] = res.states
    a, b = finals["dense"], finals["csr"]
    pairs = [("events", a.core.events, b.core.events),
             ("dlv.have", a.core.dlv.have, b.core.dlv.have),
             ("topo.nbr", a.core.topo.nbr, b.core.topo.nbr),
             ("topo.nbr_ok", a.core.topo.nbr_ok, b.core.topo.nbr_ok),
             ("topo.rev", a.core.topo.rev, b.core.topo.rev),
             ("topo.edge_perm", a.core.topo.edge_perm,
              b.core.topo.edge_perm),
             ("topo.epoch", a.core.topo.epoch, b.core.topo.epoch)]
    mismatch = [name for name, x, y in pairs
                if not np.array_equal(np.asarray(x), np.asarray(y))]
    if mismatch:
        failures.append(
            f"parity: dense vs CSR diverged under mutation on {mismatch}"
            " — the two faces must be bit-identical")
    ev = np.asarray(a.core.events)
    return {"bit_exact": not mismatch,
            "events_head": ev[:8].tolist()}


def check_bad_mutation(cell, work: str, d: int, seg: int, control: dict,
                       failures: list):
    """An involution-breaking corruption must be caught same-segment by
    the topo-involution probe, localized to its dispatch by the replay,
    and recovered bit-exact."""
    from go_libp2p_pubsub_tpu.serve import FaultPlan, state_digest

    bad_seg, bad_disp = 1, 3
    expect_bad = bad_seg * seg + bad_disp
    plan = FaultPlan(corrupt_segment=bad_seg, corrupt_dispatch=bad_disp,
                     corrupt_kind="topo")
    sup = make_supervisor(cell, os.path.join(work, "bad"), d, seg,
                          faults=plan)
    report = sup.run(fresh=True)
    if report.recoveries != 1:
        failures.append(
            f"bad-mutation: {report.recoveries} recoveries, expected "
            "exactly 1 (probe trips once, then the replay exhausts the "
            "transient)")
    if not report.bundles:
        failures.append("bad-mutation: no forensic bundle emitted")
        return {}
    bundle = report.bundles[0]
    if bundle["first_bad_dispatch"] != expect_bad:
        failures.append(
            f"bad-mutation: replay localized dispatch "
            f"{bundle['first_bad_dispatch']}, expected {expect_bad}")
    if "topo-involution" not in bundle.get("window_probe_failures", []):
        failures.append(
            f"bad-mutation: boundary probe named "
            f"{bundle.get('window_probe_failures')} — topo-involution "
            "must catch the corruption in ITS OWN segment")
    replay_names = bundle.get("replay_failures") or []
    if "topo-involution" not in replay_names:
        failures.append(
            f"bad-mutation: replay failures {replay_names} missing the "
            "topo-involution probe")
    if "invariant:edge-involution-wf" not in replay_names:
        failures.append(
            f"bad-mutation: replay failures {replay_names} missing "
            "invariant:edge-involution-wf — the deep oracle must agree "
            "with the probe")
    digest = state_digest(report.states)
    if digest != control["digest"]:
        failures.append(
            "bad-mutation: recovered digest differs from control — a "
            "transient bad mutation must recover bit-exact")
    return {"first_bad": bundle["first_bad_dispatch"],
            "recoveries": report.recoveries,
            "replay_failures": replay_names,
            "bit_exact": digest == control["digest"]}


def check_resume(cell, work: str, d: int, seg: int, control: dict,
                 failures: list):
    """Checkpoint mid-storm (between the kill and the replacement),
    resume from disk, finish bit-exact vs the uninterrupted control —
    the mutable topology plane rides checkpoint v6 with NO version
    bump."""
    from go_libp2p_pubsub_tpu import checkpoint
    from go_libp2p_pubsub_tpu.serve import state_digest

    if checkpoint._FORMAT_VERSION != 6:
        failures.append(
            f"resume: checkpoint format bumped to "
            f"{checkpoint._FORMAT_VERSION} — the TopoState plane must "
            "ride v6 pytree-generically")
    root = os.path.join(work, "resume")
    mid = cell["replace_at"]  # kill is live, the replacement has not run
    make_supervisor(cell, root, mid, seg).run(fresh=True)
    report = make_supervisor(cell, root, d, seg).run(fresh=False)
    if report.resumed_from != mid:
        failures.append(
            f"resume: resumed_from={report.resumed_from}, expected "
            f"{mid} (the mid-storm checkpoint)")
    digest = state_digest(report.states)
    if digest != control["digest"]:
        failures.append(
            "resume: mid-storm resumed digest differs from the "
            "uninterrupted control — v6 round-trip of the mutated "
            "topology is NOT bit-exact")
    return {"resumed_from": report.resumed_from,
            "bit_exact": digest == control["digest"]}


def check_census(failures: list) -> dict:
    """Mutation-off is statically free: the chaos-off compiled kernel
    census must equal the on-image baseline (chaos_report leg,
    reused)."""
    from chaos_report import check_census as _chaos_census

    census = _chaos_census()
    if not census["equal"]:
        failures.append(
            f"census: mutation-off kernel census {census['total']} != "
            f"on-image baseline {census['on_image']} — the dynamic "
            "overlay must add zero device ops when not requested")
    return census


def emit_artifact(cell, control: dict, res: dict, n: int, d: int,
                  seg: int) -> None:
    from go_libp2p_pubsub_tpu.perf.artifacts import (
        BenchRecord,
        dump_record,
        dynamics_fingerprint,
        execution_fingerprint,
        topology_fingerprint,
    )

    sched, writes = cell["sched"], cell["writes"]
    tp_ok = np.asarray(cell["sched"].nbr_ok)
    deg = tp_ok.sum(axis=1)
    rec = BenchRecord(
        metric=f"churn_storm_rounds_per_sec_n{n}_seg{seg}",
        value=control["rounds_per_sec"],
        unit="rounds/s",
        vs_baseline=0.0,
        schema=3,
        fingerprint={
            "execution": execution_fingerprint(
                scan=True, segment_rounds=seg, dispatches_per_window=1,
                rounds_per_dispatch=1),
            "dynamics": dynamics_fingerprint(
                mutation_dispatches=len(sched.mutation_dispatches),
                writes_per_dispatch=int(writes.shape[1]),
                kills=sched.n_kills, joins=sched.n_joins,
                rewires=sched.n_rewires,
                schedule_hash=sched.schedule_hash()),
            "service": control["report"].fingerprint(),
            "topology": topology_fingerprint(
                generator="powerlaw", family="power-law",
                params={"max_degree": CELL_DEGREE},
                n_edges=int(tp_ok.sum()) // 2,
                mean_degree=float(deg.mean()),
                max_degree=int(deg.max()),
                density=float(tp_ok.mean())),
        },
        extras={
            "reform_latency_dispatches":
                control["reform_latency_dispatches"],
            "pre_kill_deliveries_per_dispatch":
                control["pre_kill_deliveries_per_dispatch"],
            "post_heal_deliveries_per_dispatch":
                control["post_heal_deliveries_per_dispatch"],
            "bad_mutation": res.get("bad", {}),
            "resume": res.get("resume", {}),
        },
    )
    print(dump_record(rec), flush=True)


def check_baseline(root: str, cell, control: dict, n: int, d: int,
                   seg: int) -> list:
    path = os.path.join(root, BASELINE_NAME)
    if not os.path.exists(path) or os.environ.get("CHURN_SMOKE_UPDATE"):
        return []
    with open(path) as f:
        base = json.load(f)
    if (int(base.get("n_peers", n)) != n
            or int(base.get("dispatches", d)) != d
            or int(base.get("segment_len", seg)) != seg
            or int(base.get("seed", -1))
            != int(os.environ.get("CHURN_SMOKE_SEED", 0))):
        return []  # reshape run: committed numbers are cell-specific
    out = []
    committed_hash = base.get("schedule_hash")
    live_hash = cell["sched"].schedule_hash()
    if committed_hash and committed_hash != live_hash:
        out.append(
            f"schedule drift: the storm compiled to {live_hash[:16]} "
            f"but {BASELINE_NAME} pins {committed_hash[:16]} — the "
            "mutation program is no longer deterministic (or it "
            "changed intentionally: CHURN_SMOKE_UPDATE=1 rewrites)")
    tol = float(os.environ.get("CHURN_SMOKE_TOL", DEFAULT_TOL))
    committed = base.get("rounds_per_sec")
    if committed and control["rounds_per_sec"] < tol * committed:
        out.append(
            f"storm rate regressed: {control['rounds_per_sec']:.1f} < "
            f"{tol:.2f} x committed {committed:.1f} rounds/s "
            f"({BASELINE_NAME}; CHURN_SMOKE_TOL overrides, "
            "CHURN_SMOKE_UPDATE=1 rewrites)")
    return out


def write_baseline(root: str, cell, control: dict, n: int, d: int,
                   seg: int) -> str:
    path = os.path.join(root, BASELINE_NAME)
    sched = cell["sched"]
    doc = {
        "schema": 1,
        "note": (
            "dynamic-overlay churn-storm smoke baseline (scripts/"
            "churn_smoke.py); CHURN_SMOKE_UPDATE=1 rewrites. "
            "rounds_per_sec is the supervised storm cell (probes + "
            "folded invariants + observer) on the gate machine; "
            "schedule_hash pins the compiled mutation program "
            "(determinism witness). The rate floor gates at "
            "CHURN_SMOKE_TOL; reform latency and delivery bands gate "
            "absolutely inside the script."),
        "n_peers": n, "dispatches": d, "segment_len": seg,
        "seed": int(os.environ.get("CHURN_SMOKE_SEED", 0)),
        "rounds_per_sec": control["rounds_per_sec"],
        "reform_latency_dispatches": control["reform_latency_dispatches"],
        "pre_kill_deliveries_per_dispatch":
            control["pre_kill_deliveries_per_dispatch"],
        "post_heal_deliveries_per_dispatch":
            control["post_heal_deliveries_per_dispatch"],
        "schedule_hash": sched.schedule_hash(),
        "mutation_dispatches": len(sched.mutation_dispatches),
        "kills": sched.n_kills, "joins": sched.n_joins,
        "rewires": sched.n_rewires,
    }
    with open(path, "w") as f:
        json.dump(doc, f, indent=2)
        f.write("\n")
    return path


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    ap.add_argument("--smoke", action="store_true",
                    help="exit non-zero on any gate failure")
    ap.add_argument("--no-census", action="store_true",
                    help="skip the mutation-off kernel-census leg")
    args = ap.parse_args(argv)

    import jax

    jax.config.update("jax_platforms", "cpu")
    jax.config.update("jax_default_prng_impl", "unsafe_rbg")
    from go_libp2p_pubsub_tpu.compile_cache import enable_persistent_cache
    from go_libp2p_pubsub_tpu.perf.regress import repo_root

    root = repo_root()
    enable_persistent_cache(os.path.join(root, ".jax_cache"))

    n = int(os.environ.get("CHURN_SMOKE_N", CELL_N))
    d = int(os.environ.get("CHURN_SMOKE_D", CELL_D))
    seg = int(os.environ.get("CHURN_SMOKE_SEG", CELL_SEG))
    seed = int(os.environ.get("CHURN_SMOKE_SEED", 0))

    failures: list = []
    work = tempfile.mkdtemp(prefix="churn_smoke_")
    cell = build_cell(n, d, seg, seed)
    control = check_control(cell, work, n, d, seg, failures)
    res = {
        "parity": check_parity(n, d, seg, seed, failures),
        "bad": check_bad_mutation(cell, work, d, seg, control, failures),
        "resume": check_resume(cell, work, d, seg, control, failures),
    }
    if not args.no_census:
        res["census"] = check_census(failures)
        if res["census"].get("seeded"):
            print("churn-smoke NOTE: on-image census baseline was "
                  "seeded by this run", file=sys.stderr)
    emit_artifact(cell, control, res, n, d, seg)
    failures += check_baseline(root, cell, control, n, d, seg)
    if os.environ.get("CHURN_SMOKE_UPDATE") and not failures:
        print(f"wrote {write_baseline(root, cell, control, n, d, seg)}")

    summary = {
        "churn_smoke": "PASS" if not failures else "FAIL",
        "control": {k: v for k, v in control.items() if k != "report"},
        **{k: v for k, v in res.items()},
        "failures": failures,
    }
    if args.smoke and failures:
        for f in failures:
            print(f"churn-smoke FAIL: {f}", file=sys.stderr)
        print(json.dumps(summary))
        return 1
    print(json.dumps(summary))
    return 0


if __name__ == "__main__":
    sys.exit(main())
