"""Micro-benchmark: carry-layout conventions for the scanned step.

Three formulations of the same loop body (a representative mix of the
step's hot ops: roll-gather across the edge involution, elementwise score
update, pairwise rank, popcount reduce) over a [N,K]-shaped state:

  A. row-major carry [N,K] (the current convention),
  B. transposed storage [K,N] with jnp.transpose at body entry/exit
     (compute code unchanged — tests whether XLA turns the transposes
     into free layout assignments),
  C. native [K,N] compute (the full-refactor endpoint).

Prints one human line per variant plus a final schema-v2 JSON line
(perf.artifacts) so microbench runs are recordable artifacts like the
bench proper.

Usage: python scripts/layout_microbench.py [N] [ITERS]
"""

from __future__ import annotations

import os
import sys
import time

import numpy as np

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))


def main():
    import jax
    import jax.numpy as jnp

    n = int(sys.argv[1]) if len(sys.argv) > 1 else 100_000
    iters = int(sys.argv[2]) if len(sys.argv) > 2 else 100
    k = 16
    offs = tuple(int(o) for o in list(range(1, 9)) + [n - o for o in range(1, 9)])

    def body_nk(scores, counters, words):
        # peer_gather (banded rolls): [N,K]
        g = jnp.stack([jnp.roll(scores[:, r % k], -o, axis=0) for r, o in enumerate(offs)], axis=1)
        counters = counters * 0.95 + (g > 0).astype(jnp.float32)
        vals = counters + g
        # pairwise rank over K
        outranks = (vals[:, None, :] > vals[:, :, None])
        rank = jnp.sum(outranks, axis=-1).astype(jnp.int32)
        sel = rank < 4
        # popcount-ish reduce over packed words
        w = words ^ jax.lax.shift_right_logical(words, jnp.uint32(1))
        tot = jnp.sum(w & jnp.uint32(0x55555555), dtype=jnp.uint32)
        scores = jnp.where(sel, vals, scores * 0.9) + (tot.astype(jnp.float32) * 1e-30)
        words = words + jnp.uint32(1)
        return scores, counters, words

    def body_kn(scores, counters, words):
        # same math, [K,N] layout: rolls along the minor axis
        g = jnp.stack([jnp.roll(scores[r % k], -o, axis=0) for r, o in enumerate(offs)], axis=0)
        counters = counters * 0.95 + (g > 0).astype(jnp.float32)
        vals = counters + g
        outranks = (vals[None, :, :] > vals[:, None, :])
        rank = jnp.sum(outranks, axis=1).astype(jnp.int32)
        sel = rank < 4
        w = words ^ jax.lax.shift_right_logical(words, jnp.uint32(1))
        tot = jnp.sum(w & jnp.uint32(0x55555555), dtype=jnp.uint32)
        scores = jnp.where(sel, vals, scores * 0.9) + (tot.astype(jnp.float32) * 1e-30)
        words = words + jnp.uint32(1)
        return scores, counters, words

    def scan_a(state):
        def f(c, _):
            return body_nk(*c), None
        c, _ = jax.lax.scan(f, state, None, length=iters)
        return c

    def scan_b(state):
        def f(c, _):
            s, cn, w = c
            s2, cn2, w2 = body_nk(s.T, cn.T, w.T)
            return (s2.T, cn2.T, w2.T), None
        c, _ = jax.lax.scan(f, state, None, length=iters)
        return c

    def scan_c(state):
        def f(c, _):
            return body_kn(*c), None
        c, _ = jax.lax.scan(f, state, None, length=iters)
        return c

    rng = np.random.default_rng(0)
    s0 = jnp.asarray(rng.standard_normal((n, k)).astype(np.float32))
    c0 = jnp.asarray(rng.standard_normal((n, k)).astype(np.float32))
    w0 = jnp.asarray(rng.integers(0, 2**32, size=(n, 2), dtype=np.uint64).astype(np.uint32))

    results = {}
    for key, name, fn, st in [
        ("row_major_nk", "A row-major [N,K] carry", scan_a, (s0, c0, w0)),
        ("transposed_body", "B [K,N] storage + transposed body", scan_b,
         (s0.T, c0.T, w0.T)),
        ("native_kn", "C native [K,N] compute", scan_c, (s0.T, c0.T, w0.T)),
    ]:
        run = jax.jit(fn)
        out = run(st)
        _ = float(jnp.sum(out[0]))  # honest completion barrier (see bench.py)
        t0 = time.perf_counter()
        out = run(st)
        _ = float(jnp.sum(out[0]))
        dt = (time.perf_counter() - t0) / iters
        results[key] = round(dt * 1e6, 1)
        print(f"{name:36s} {dt * 1e6:9.1f} us/iter")

    # recordable artifact line (headline = the production convention A)
    import json

    from go_libp2p_pubsub_tpu.perf.artifacts import SCHEMA_VERSION

    print(json.dumps({
        "schema": SCHEMA_VERSION,
        "metric": f"layout_microbench_us_per_iter_n{n}",
        "value": results["row_major_nk"],
        "unit": "us/iter",
        "vs_baseline": 0.0,  # not a north-star metric
        "variants": results,
        "fingerprint": {
            "n_peers": n, "k": k, "iters": iters,
            "platform": jax.default_backend(),
        },
    }))


if __name__ == "__main__":
    main()
