"""`make scale-smoke`: the million-peer window gate (round 15).

Runs an N=1M (SCALE_SMOKE_N), small-K, CPU window of the floodsub data
plane on the **csr** edge layout (ops/csr.py — the sparse data plane),
compiled as ONE scanned program (driver.make_window) with the invariant
oracle folded in (oracle.ScanInvariants), and asserts:

  * ZERO invariant violations across the window's folded checks;
  * peak process RSS stays under the committed ceiling
    (SCALE_SMOKE.json ``peak_rss_mb_ceiling``) — the memory wall the
    sparse plane + byte audit (`make mem-audit`) exist to manage;
  * the warm window sustains at least the committed rounds/s floor
    (``rounds_per_sec_floor``).

SCALE_SMOKE_UPDATE=1 rewrites the baseline from this run's measurements
(ceiling = 1.35x measured RSS, floor = 0.5x measured rate — wide margins:
this is a scale-feasibility gate, not a perf-regression gate; the
PERF_SMOKE machinery owns rate regressions at bench shapes).

The report also prints the v5e-8 N-scaling projection at the smoke's N
(perf.projection.project_at_scale) with the memory term fed from the
committed MEM_AUDIT.json bytes/peer — the round-15 ask that the
10k-ticks/s target be priced at 1M peers, not just 100k.

Delivery sanity: round 0 publishes a handful of messages; the window
must actually propagate them (delivered receipts > 0) so the gate can
never pass on a dead wire.
"""

from __future__ import annotations

import json
import os
import resource
import sys
import time

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)

BASELINE_PATH = os.path.join(REPO, "SCALE_SMOKE.json")
MEM_AUDIT_PATH = os.path.join(REPO, "MEM_AUDIT.json")

N = int(os.environ.get("SCALE_SMOKE_N", 1_000_000))
DEGREE_D = int(os.environ.get("SCALE_SMOKE_D", 4))   # K = 2d = 8
MSG_SLOTS = int(os.environ.get("SCALE_SMOKE_M", 32))
ROUNDS = int(os.environ.get("SCALE_SMOKE_ROUNDS", 8))
CHECK_EVERY = 4
PUB_WIDTH = 4

#: update-mode margins (see module docstring)
RSS_MARGIN = 1.35
RATE_MARGIN = 0.5


def peak_rss_mb() -> float:
    """Linux ru_maxrss is KB."""
    return resource.getrusage(resource.RUSAGE_SELF).ru_maxrss / 1024.0


def run_smoke() -> dict:
    import jax
    import jax.numpy as jnp
    import numpy as np

    from go_libp2p_pubsub_tpu import driver, graph
    from go_libp2p_pubsub_tpu.models.floodsub import floodsub_step
    from go_libp2p_pubsub_tpu.oracle.invariants import ScanInvariants
    from go_libp2p_pubsub_tpu.state import Net, SimState
    from go_libp2p_pubsub_tpu.trace.events import EV

    topo = graph.ring_lattice(N, d=DEGREE_D)
    subs = graph.subscribe_all(N, 1)
    net = Net.build(topo, subs, edge_layout="csr")
    k = net.max_degree

    def step(st, po, pt, pv):
        return floodsub_step(net, st, po, pt, pv)

    from go_libp2p_pubsub_tpu.oracle.invariants import InvariantConfig

    si = ScanInvariants(
        "floodsub", net, inv=InvariantConfig(check_every=CHECK_EVERY),
        batched=False, rounds_per_step=1,
    )
    win = driver.make_window(step, check=si.check, check_every=CHECK_EVERY)
    due = si.precompute(ROUNDS)

    rng = np.random.default_rng(0)
    po = np.full((ROUNDS, PUB_WIDTH), -1, np.int32)
    po[0] = rng.integers(0, N, size=PUB_WIDTH)
    pt = np.zeros((ROUNDS, PUB_WIDTH), np.int32)
    pv = np.ones((ROUNDS, PUB_WIDTH), bool)
    xs = (jnp.asarray(po), jnp.asarray(pt), jnp.asarray(pv))

    def fresh():
        # CSR-RESIDENT state (round 18): the flat [E, W] first-arrival
        # plane — the million-peer window now runs the fully-flat
        # delivery commit (models/common.finish_delivery_flat)
        return SimState.init(N, MSG_SLOTS, k=k, n_edges=net.n_edges)

    # compile + warm (the window donates its state)
    t0 = time.perf_counter()
    st, ys = win(fresh(), xs, due)
    jax.block_until_ready(st.events)
    cold_s = time.perf_counter() - t0
    ok_cold = np.asarray(ys["ok"])

    # warm timed rep on a fresh tree
    st2 = fresh()
    jax.block_until_ready(st2.events)
    t0 = time.perf_counter()
    st2, ys2 = win(st2, xs, due)
    delivered = int(np.asarray(st2.events)[EV.DELIVER_MESSAGE])
    warm_s = time.perf_counter() - t0
    ok_warm = np.asarray(ys2["ok"])

    return {
        "n_peers": N,
        "k": k,
        "msg_slots": MSG_SLOTS,
        "rounds": ROUNDS,
        "engine": "floodsub",
        "edge_layout": "csr",
        "n_edges": int(net.n_edges),
        "checks": int(ok_warm.shape[0]),
        "properties": len(si.names),
        "violations": int((~ok_cold).sum() + (~ok_warm).sum()),
        "delivered": delivered,
        "cold_s": round(cold_s, 2),
        "warm_rounds_per_sec": round(ROUNDS / warm_s, 3),
        "peak_rss_mb": round(peak_rss_mb(), 1),
    }


def projection_report(density: float = 1.0) -> dict | None:
    if not os.path.exists(MEM_AUDIT_PATH):
        return None
    from go_libp2p_pubsub_tpu.perf.projection import project_at_scale

    with open(MEM_AUDIT_PATH) as f:
        audit = json.load(f)
    # the smoke runs the CSR layout — price its memory term under the
    # ACTIVE layout at the run's density (round-18 headroom fix; the
    # smoke ring is full-density, so the csr tier saves nothing HERE,
    # but the term now tracks the layout instead of assuming dense)
    return project_at_scale(N, audit=audit, edge_layout="csr",
                            density=density).summary()


def main() -> int:
    import jax

    jax.config.update("jax_platforms", "cpu")
    res = run_smoke()
    update = bool(os.environ.get("SCALE_SMOKE_UPDATE"))
    # RSS/rate gate disposition is part of the machine-readable output
    # (round-18 fix: a skipped gate must never read as a pass), and it
    # must be decided BEFORE the primary record prints — a consumer of
    # the main JSON line sees the same SKIPPED the gate logic acts on
    # the RSS/rate gates only mean anything at the committed SHAPE —
    # every env-overridable knob the baseline records must match, or a
    # bigger M/K run would fail with no regression (and a smaller one
    # would mask a real one)
    shape_keys = ("n_peers", "k", "msg_slots", "rounds", "engine",
                  "edge_layout")
    base = None
    mismatched = []
    if not update and os.path.exists(BASELINE_PATH):
        with open(BASELINE_PATH) as f:
            base = json.load(f)
        mismatched = [k for k in shape_keys if res[k] != base.get(k)]
    # three dispositions, decided before the record prints: RUN (gated
    # against the committed baseline), SKIPPED (shape mismatch — the
    # gates would be meaningless), BASELINED (update/first run — this
    # run WRITES the baseline, so nothing gated it)
    res["rss_rate_gates"] = ("BASELINED" if base is None
                             else "SKIPPED" if mismatched else "RUN")
    print(json.dumps(res, indent=1))

    proj = projection_report(
        density=res["n_edges"] / float(res["n_peers"] * res["k"]))
    if proj is not None:
        print("v5e-8 N-scaling projection at the smoke N "
              "(perf.projection.project_at_scale):")
        print(json.dumps(proj, indent=1))

    failures = []
    if res["violations"]:
        failures.append(
            f"{res['violations']} invariant violations in the window")
    if res["delivered"] <= 0:
        failures.append("window delivered nothing — dead wire")

    if base is None:
        if failures:
            print("scale-smoke: FAIL (refusing to baseline a broken run):")
            for f in failures:
                print("  -", f)
            return 1
        baseline = {
            "note": ("scale-smoke baseline (scripts/scale_smoke.py; "
                     "SCALE_SMOKE_UPDATE=1 rewrites)"),
            "n_peers": res["n_peers"],
            "k": res["k"],
            "msg_slots": res["msg_slots"],
            "rounds": res["rounds"],
            "engine": res["engine"],
            "edge_layout": res["edge_layout"],
            "peak_rss_mb_ceiling": round(res["peak_rss_mb"] * RSS_MARGIN),
            "rounds_per_sec_floor": round(
                res["warm_rounds_per_sec"] * RATE_MARGIN, 3),
        }
        with open(BASELINE_PATH, "w") as f:
            json.dump(baseline, f, indent=1, sort_keys=True)
            f.write("\n")
        print(f"scale-smoke: wrote {BASELINE_PATH}")
        return 0

    if not mismatched:
        if res["peak_rss_mb"] > base["peak_rss_mb_ceiling"]:
            failures.append(
                f"peak RSS {res['peak_rss_mb']} MB exceeds the committed "
                f"ceiling {base['peak_rss_mb_ceiling']} MB")
        if res["warm_rounds_per_sec"] < base["rounds_per_sec_floor"]:
            failures.append(
                f"warm rate {res['warm_rounds_per_sec']} rounds/s below "
                f"the committed floor {base['rounds_per_sec_floor']}")
    else:
        # EXPLICIT marker, in the human output AND the machine-readable
        # record (round-18 bugfix): a gate that did not run must
        # never be mistaken for one that passed — the old output's only
        # trace was an easy-to-miss NOTE line before an unqualified
        # "PASS"
        print(json.dumps({"rss_rate_gates": "SKIPPED",
                          "mismatched_shape_keys": mismatched}))
        print("scale-smoke: RSS/rate gates SKIPPED — run shape differs "
              "from the committed baseline on %s (%s); invariant + "
              "delivery gates still apply"
              % (mismatched,
                 {k: (res[k], base.get(k)) for k in mismatched}))

    if failures:
        print("scale-smoke: FAIL")
        for f in failures:
            print("  -", f)
        return 1
    if res["rss_rate_gates"] == "SKIPPED":
        print("scale-smoke: PASS (RSS/rate gates SKIPPED — shrunken "
              "shape; invariant + delivery gates only) — N=%s csr "
              "window, %s rounds/s, zero violations"
              % (res["n_peers"], res["warm_rounds_per_sec"]))
        return 0
    print("scale-smoke: PASS — N=%s csr window under %s MB, "
          "%s rounds/s, zero violations"
          % (res["n_peers"], base["peak_rss_mb_ceiling"],
             res["warm_rounds_per_sec"]))
    return 0


if __name__ == "__main__":
    sys.exit(main())
