"""Multi-chip scaling measurement on the virtual CPU mesh (round-1 review
item: "measure multi-chip scaling before hardware arrives").

Runs the bench workload sharded over 1/2/4/8 virtual CPU devices and
reports (a) relative step time and (b) which collectives GSPMD inserted
for the cross-peer neighbor gathers. On the banded ring topology the
peer-axis relabeling keeps every mesh edge within +-8 ids, so the
expected lowering is halo exchange (collective-permute of the band
edges), NOT all-gathers of peer-sized tensors.

CPU timing is NOT a TPU perf prediction — XLA:CPU's collective runtime
is a functional stand-in — but GSPMD partitioning decisions (which
collectives, how many, on what shapes) are platform-independent, which
is what this measures. tests/test_collectives.py pins the collective
profile in CI.

Usage: python scripts/scaling_cpu_mesh.py [N] [ROUNDS]
"""

from __future__ import annotations

import os
import sys
import time

import numpy as np


def main():
    os.environ["JAX_PLATFORMS"] = "cpu"
    flags = os.environ.get("XLA_FLAGS", "")
    if "host_platform_device_count" not in flags:
        os.environ["XLA_FLAGS"] = (
            flags + " --xla_force_host_platform_device_count=8"
        ).strip()
    import jax

    jax.config.update("jax_platforms", "cpu")
    import jax.numpy as jnp

    sys.path.insert(0, ".")
    sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))
    from bench import build_bench
    from go_libp2p_pubsub_tpu.parallel import (
        collective_profile,
        make_mesh,
        shard_state,
    )

    n = int(sys.argv[1]) if len(sys.argv) > 1 else 100_000
    rounds = int(sys.argv[2]) if len(sys.argv) > 2 else 10

    rng = np.random.default_rng(0)
    po = jnp.asarray(rng.integers(0, n, size=(rounds, 4)).astype(np.int32))
    pt = jnp.asarray(np.zeros((rounds, 4), np.int32))
    pv = jnp.asarray(np.ones((rounds, 4), bool))

    results = []
    base_time = None
    for n_dev in (1, 2, 4, 8):
        st, step, n_topics, honest = build_bench(n, 64, config="default")
        if n_dev > 1:
            mesh = make_mesh(n_dev)
            st = shard_state(st, mesh, n)

        def run_seg(s):
            def body(carry, xs):
                return step(carry, *xs), None
            s, _ = jax.lax.scan(body, s, (po, pt, pv))
            return s

        runj = jax.jit(run_seg, donate_argnums=0)
        lowered = runj.lower(st)
        compiled = lowered.compile()
        prof = collective_profile(compiled.as_text())
        st = compiled(st)
        jax.block_until_ready(st)
        # re-shard a fresh state (donation consumed the last one) and time
        # the AOT-compiled executable — calling the jit wrapper here would
        # re-trace and re-compile inside the timed region
        st2, _, _, _ = build_bench(n, 64, config="default")
        if n_dev > 1:
            st2 = shard_state(st2, make_mesh(n_dev), n)
        t0 = time.perf_counter()
        st2 = compiled(st2)
        jax.block_until_ready(st2)
        dt = (time.perf_counter() - t0) / rounds
        if base_time is None:
            base_time = dt
        results.append((n_dev, dt, base_time / dt, prof))
        print(f"devices={n_dev}: {dt*1e3:8.1f} ms/round  "
              f"speedup x{base_time/dt:4.2f}  collectives={prof}")

    print("\n| devices | ms/round (CPU) | speedup | collective-permute | "
          "all-gather | all-reduce |")
    print("|---|---|---|---|---|---|")
    for n_dev, dt, sp, prof in results:
        print(f"| {n_dev} | {dt*1e3:.1f} | x{sp:.2f} | "
              f"{prof['collective-permute']} | {prof['all-gather']} | "
              f"{prof['all-reduce']} |")


if __name__ == "__main__":
    main()
