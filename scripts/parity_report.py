"""Parity report: vectorized routers vs. the scalar per-node oracles.

Runs the BASELINE.json comparison configs (scaled to oracle-tractable
sizes — the oracles are deliberately naive per-node Python) and writes
PARITY.md with, per config:

  * propagation-latency CDF sup-distance (north-star tolerance: 2%)
  * mean-hop relative difference
  * delivery coverage on both sides
  * aggregate event-counter ratios (deliver / duplicate / RPC)

FloodSub is deterministic given the topology, so its row is checked
bit-for-bit (seen sets, first_round, first_edge, every counter) rather
than distributionally.

Usage: python scripts/parity_report.py  (CPU; a few minutes)
"""

from __future__ import annotations

import sys

import numpy as np


def main():
    import jax

    jax.config.update("jax_platforms", "cpu")
    # repo-root anchored (not cwd): the script must import the package
    # and read/write PARITY.md correctly from any working directory
    import os as _os

    sys.path.insert(0, _os.path.dirname(_os.path.dirname(
        _os.path.abspath(__file__))))
    import jax.numpy as jnp

    from go_libp2p_pubsub_tpu import graph
    from go_libp2p_pubsub_tpu.config import GossipSubParams
    from go_libp2p_pubsub_tpu.models.floodsub import floodsub_step
    from go_libp2p_pubsub_tpu.models.gossipsub import (
        GossipSubConfig,
        GossipSubState,
        make_gossipsub_step,
        no_publish,
    )
    from go_libp2p_pubsub_tpu.oracle.floodsub import OracleFloodSub
    from go_libp2p_pubsub_tpu.oracle.gossipsub import OracleGossipSub
    from go_libp2p_pubsub_tpu.ops import bitset
    from go_libp2p_pubsub_tpu.state import Net, SimState, hops
    from go_libp2p_pubsub_tpu.trace.events import EV

    MAX_H = 16
    rows = []

    def cdf(hop_list, n_msgs, n_peers):
        hist = np.zeros(MAX_H + 1)
        for h in hop_list:
            hist[min(int(h), MAX_H)] += 1
        return np.cumsum(hist) / (n_msgs * n_peers)

    # ---- config 1: FloodSub, 64 hosts, connectAll — bit-exact ----------
    n, msg_slots = 64, 64
    topo = graph.connect_all(n)
    subs = graph.subscribe_all(n, 1)
    net = Net.build(topo, subs)
    st = SimState.init(n, msg_slots, seed=0, k=net.max_degree)
    oracle = OracleFloodSub(topo, subs, msg_slots=msg_slots)
    rng = np.random.default_rng(0)
    exact = True
    for r in range(30):
        pubs = [(int(rng.integers(0, n)), 0, True)] if r % 2 == 0 else []
        po = np.full((1,), pubs[0][0] if pubs else -1, np.int32)
        pt = np.zeros((1,), np.int32)
        pv = np.asarray([bool(pubs)])
        st = floodsub_step(net, st, jnp.asarray(po), jnp.asarray(pt), jnp.asarray(pv))
        oracle.step(pubs)
    have = np.asarray(bitset.unpack(st.dlv.have, msg_slots))
    fr = np.asarray(st.dlv.first_round)
    fe = np.asarray(st.dlv.first_edge)
    for i in range(n):
        if set(np.nonzero(have[i])[0].tolist()) != oracle.seen[i]:
            exact = False
        for slot in oracle.seen[i]:
            if fr[i, slot] != oracle.first_round[(i, slot)]:
                exact = False
            if fe[i, slot] != oracle.first_edge[(i, slot)]:
                exact = False
    ev = np.asarray(st.events)
    ev_exact = all(int(ev[e]) == oracle.events[e] for e in range(len(ev)))
    rows.append(("FloodSub 64 connectAll (config #1)",
                 "bit-exact" if exact and ev_exact else "MISMATCH",
                 "-", "-", "every seen set, first_round, first_edge, counter"))

    # ---- gossipsub configs: CDF comparison ------------------------------
    # Without scoring the mesh FREEZES once converged, so a single run's
    # CDF mostly measures the mesh-formation lottery of one RNG draw (the
    # across-seed spread of converged mean degree is as large as any
    # engine/oracle gap — measured at 512/d=10: engine 8.13-8.45, oracle
    # 8.18-8.53). Each side therefore pools 5 seeds, and the error bars
    # come from a leave-one-out jackknife: the sup-distance is recomputed
    # for every (drop one engine seed, drop one oracle seed) pool pair,
    # and the row reports pooled sup + jackknife mean and max (round-3
    # review item: margins without spread are not evidence of parity).
    SEEDS_V = (3, 4, 5, 6, 7)
    SEEDS_O = (11, 12, 13, 14, 15)

    def _sup_with_jackknife(hv_per_seed, ho_per_seed, denom_per_run):
        """hv_per_seed/ho_per_seed: list of per-seed hop lists.
        denom_per_run: (subscribed peer, msg) pair count of ONE run.
        Returns (pooled_sup, jk_mean, jk_max)."""
        sv, so = len(hv_per_seed), len(ho_per_seed)

        def pooled(per_seed, skip):
            hist = np.zeros(MAX_H + 1)
            for i, hs in enumerate(per_seed):
                if i == skip:
                    continue
                for h in hs:
                    hist[min(int(h), MAX_H)] += 1
            runs = len(per_seed) - (1 if skip is not None else 0)
            return np.cumsum(hist) / (runs * denom_per_run)

        full = float(np.max(np.abs(pooled(hv_per_seed, None)
                                   - pooled(ho_per_seed, None))))
        jk = [
            float(np.max(np.abs(pooled(hv_per_seed, i) - pooled(ho_per_seed, j))))
            for i in range(sv) for j in range(so)
        ]
        return full, float(np.mean(jk)), float(np.max(jk))

    def gossip_row(label, n, deg, params, warmup=20, pub_rounds=18, drain=14,
                   seed=5, n_topics=1, topic_sched=None,
                   validation_delay_topic=None, extra_note=""):
        topo = graph.random_connect(n, d=deg, seed=seed)
        subs = graph.subscribe_all(n, n_topics)
        schedule = np.random.default_rng(7).integers(
            0, n, size=(pub_rounds, 2)).astype(np.int32)
        topics = (topic_sched if topic_sched is not None
                  else np.zeros((pub_rounds, 2), np.int32))

        netx = Net.build(topo, subs)
        cfg = GossipSubConfig.build(
            params, validation_delay_topic=validation_delay_topic
        )
        step = make_gossipsub_step(cfg, netx)
        empty = no_publish(2)
        pv = jnp.ones((2,), bool)
        from go_libp2p_pubsub_tpu.trace.events import N_EVENTS

        hv_seeds, ev_v = [], np.zeros(N_EVENTS, np.int64)
        for sd in SEEDS_V:
            stx = GossipSubState.init(netx, 64, cfg, seed=sd)
            for _ in range(warmup):
                stx = step(stx, *empty)
            for r in range(pub_rounds):
                stx = step(stx, jnp.asarray(schedule[r]),
                           jnp.asarray(topics[r]), pv)
            for _ in range(drain):
                stx = step(stx, *empty)
            h = np.asarray(hops(stx.core.msgs, stx.core.dlv))
            hv_seeds.append([int(x) for x in h[h >= 0]])
            ev_v = ev_v + np.asarray(stx.core.events)

        ho_seeds, ev_o = [], np.zeros(len(ev_v))
        for sd in SEEDS_O:
            o = OracleGossipSub(topo, subs, cfg, msg_slots=64, seed=sd)
            for _ in range(warmup):
                o.step()
            for r in range(pub_rounds):
                o.step([(int(p), int(t), True)
                        for p, t in zip(schedule[r], topics[r])])
            for _ in range(drain):
                o.step()
            ho_seeds.append(list(o.hops().values()))
            ev_o = ev_o + np.asarray(o.events)

        n_msgs = pub_rounds * 2
        sup, jk_mean, jk_max = _sup_with_jackknife(
            hv_seeds, ho_seeds, n_msgs * n
        )
        hv = [h for hs in hv_seeds for h in hs]
        ho = [h for hs in ho_seeds for h in hs]
        mean_rel = abs(np.mean(hv) - np.mean(ho)) / np.mean(ho)
        cov_v = len(hv) / (len(SEEDS_V) * n_msgs * n)
        cov_o = len(ho) / (len(SEEDS_O) * n_msgs * n)
        ratios = []
        for e in (EV.DELIVER_MESSAGE, EV.DUPLICATE_MESSAGE, EV.SEND_RPC):
            ratios.append(
                (float(ev_v[e]) / len(SEEDS_V))
                / max(float(ev_o[e]) / len(SEEDS_O), 1.0)
            )
        note = "dlv/dup/rpc ratios " + "/".join(f"{x:.3f}" for x in ratios)
        if extra_note:
            note = extra_note + "; " + note
        rows.append((label,
                     f"{100*sup:.2f}% (jk {100*jk_mean:.2f}/{100*jk_max:.2f}%)",
                     f"{100*mean_rel:.2f}%",
                     f"{cov_v*100:.1f}% / {cov_o*100:.1f}%",
                     note))

    # ---- config 2: RandomSub sqrt-fanout (scaled) -----------------------
    def randomsub_row(label, n, deg, pub_rounds=18, drain=12, seed=5):
        from go_libp2p_pubsub_tpu.models.randomsub import make_randomsub_step
        from go_libp2p_pubsub_tpu.oracle.randomsub import OracleRandomSub
        from go_libp2p_pubsub_tpu.state import SimState

        topo = graph.random_connect(n, d=deg, seed=seed)
        subs = graph.subscribe_all(n, 1)
        schedule = np.random.default_rng(7).integers(
            0, n, size=(pub_rounds, 2)).astype(np.int32)
        netx = Net.build(topo, subs)
        stx = SimState.init(n, 64, seed=3, k=netx.max_degree)
        step = make_randomsub_step(netx)
        pt = jnp.zeros((2,), jnp.int32)
        pv = jnp.ones((2,), bool)
        for r in range(pub_rounds):
            stx = step(stx, jnp.asarray(schedule[r]), pt, pv)
        for _ in range(drain):
            stx = step(stx, *no_publish(2))
        hvv = np.asarray(hops(stx.msgs, stx.dlv))
        hv = [int(x) for x in hvv[hvv >= 0]]
        o = OracleRandomSub(topo, subs, msg_slots=64, seed=11)
        for r in range(pub_rounds):
            o.step([(int(p), 0, True) for p in schedule[r]])
        for _ in range(drain):
            o.step()
        ho = list(o.hops().values())
        n_msgs = pub_rounds * 2
        cv, co = cdf(hv, n_msgs, n), cdf(ho, n_msgs, n)
        sup = float(np.max(np.abs(cv - co)))
        mean_rel = abs(np.mean(hv) - np.mean(ho)) / np.mean(ho)
        rows.append((label, f"{100*sup:.2f}%", f"{100*mean_rel:.2f}%",
                     f"{cv[-1]*100:.1f}% / {co[-1]*100:.1f}%",
                     "sqrt-fanout target, fresh draw per round"))

    randomsub_row("RandomSub sqrt-fanout, 192 peers d=8 (config #2 scaled)",
                  192, 8)

    gossip_row("GossipSub v1.0, 192 peers d=8 (config #3 scaled)",
               192, 8, GossipSubParams())
    gossip_row("GossipSub v1.0 + flood-publish, 192 peers d=8",
               192, 8, GossipSubParams(flood_publish=True))
    gossip_row("GossipSub v1.0, 512 peers d=10 sparse",
               512, 10, GossipSubParams(), pub_rounds=14)
    gossip_row("GossipSub + mixed per-topic validation latency (1/3/2 rounds)",
               192, 8, GossipSubParams(), n_topics=3,
               topic_sched=(np.arange(36) % 3).reshape(18, 2).astype(np.int32),
               validation_delay_topic=(1, 3, 2), drain=40,
               extra_note="async verdicts interleave across topics "
                          "(validation.go:123-135,391-438)")

    # ---- v1.1 composed rows (score plane live in the loop) --------------
    def v11_row(label, n, deg, sp, thr, adversary=None, n_topics=1,
                subs=None, warmup=24, pub_rounds=18, drain=12, seed=5,
                fanout=False, topic_sched=None, extra_note=""):
        import dataclasses as _dc

        from go_libp2p_pubsub_tpu.config import (
            PeerScoreParams,
            PeerScoreThresholds,
        )

        topo = graph.random_connect(n, d=deg, seed=seed)
        if subs is None:
            subs = graph.subscribe_all(n, n_topics)
        rng = np.random.default_rng(7)
        if adversary is not None:
            honest = np.flatnonzero(~adversary)
            schedule = honest[rng.integers(0, len(honest),
                                           size=(pub_rounds, 2))].astype(np.int32)
        else:
            schedule = rng.integers(0, n, size=(pub_rounds, 2)).astype(np.int32)
        topics = (topic_sched if topic_sched is not None
                  else np.zeros((pub_rounds, 2), np.int32))

        cfg = GossipSubConfig.build(GossipSubParams(), thr, score_enabled=True)
        if not fanout:
            cfg = _dc.replace(cfg, fanout_slots=0)
        netx = Net.build(topo, subs)
        subm = np.asarray(netx.subscribed)
        per_topic = {}
        for t in topics.ravel():
            per_topic[int(t)] = per_topic.get(int(t), 0) + 1
        total = sum(cnt * int(subm[:, t].sum())
                    for t, cnt in per_topic.items())

        step = make_gossipsub_step(cfg, netx, score_params=sp,
                                   adversary_no_forward=adversary)
        empty = no_publish(2)
        pv = jnp.ones((2,), bool)
        hv_seeds = []
        for sd in SEEDS_V:
            stx = GossipSubState.init(netx, 64, cfg, score_params=sp, seed=sd)
            for _ in range(warmup):
                stx = step(stx, *empty)
            for r in range(pub_rounds):
                stx = step(stx, jnp.asarray(schedule[r]),
                           jnp.asarray(topics[r]), pv)
            for _ in range(drain):
                stx = step(stx, *empty)
            h = np.asarray(hops(stx.core.msgs, stx.core.dlv))
            mt = np.asarray(stx.core.msgs.topic)
            mask = (h >= 0) & subm[:, np.clip(mt, 0, None)]
            hv_seeds.append([int(x) for x in h[mask]])

        adv_set = (set(np.flatnonzero(adversary).tolist())
                   if adversary is not None else None)
        ho_seeds = []
        for sd in SEEDS_O:
            o = OracleGossipSub(topo, subs, cfg, msg_slots=64, seed=sd,
                                score_params=sp, adversary=adv_set)
            for _ in range(warmup):
                o.step()
            for r in range(pub_rounds):
                o.step([(int(p), int(t), True)
                        for p, t in zip(schedule[r], topics[r])])
            for _ in range(drain):
                o.step()
            ho_seeds.append([hh for (i, slot), hh in o.hops().items()
                             if subm[i, o.msgs[slot].topic]])

        sup, jk_mean, jk_max = _sup_with_jackknife(
            hv_seeds, ho_seeds, total
        )
        hv = [h for hs in hv_seeds for h in hs]
        ho = [h for hs in ho_seeds for h in hs]
        mean_rel = abs(np.mean(hv) - np.mean(ho)) / np.mean(ho)
        cov_v = len(hv) / (len(SEEDS_V) * total)
        cov_o = len(ho) / (len(SEEDS_O) * total)
        note = "composed v1.1: scoring+thresholds live in the loop"
        if extra_note:
            note = note + "; " + extra_note
        rows.append((label,
                     f"{100*sup:.2f}% (jk {100*jk_mean:.2f}/{100*jk_max:.2f}%)",
                     f"{100*mean_rel:.2f}%",
                     f"{cov_v*100:.1f}% / {cov_o*100:.1f}%",
                     note))

    from go_libp2p_pubsub_tpu.config import (
        PeerScoreParams,
        PeerScoreThresholds,
        TopicScoreParams,
    )

    _rng = np.random.default_rng(2)
    _adv = _rng.random(192) < 0.2
    v11_row(
        "GossipSub v1.1 sybil-20% + deficit scoring (config #4 scaled)",
        192, 8,
        PeerScoreParams(
            topics={0: TopicScoreParams(
                mesh_message_deliveries_weight=-0.5,
                mesh_message_deliveries_threshold=4.0,
                mesh_message_deliveries_activation=10.0,
                mesh_message_deliveries_window=2.0,
            )},
            skip_app_specific=True,
            behaviour_penalty_weight=-1.0,
            behaviour_penalty_threshold=1.0,
            behaviour_penalty_decay=0.9,
        ),
        PeerScoreThresholds(gossip_threshold=-10.0, publish_threshold=-20.0,
                            graylist_threshold=-40.0),
        adversary=_adv,
    )
    _t_rng = np.random.default_rng(4)
    v11_row(
        "GossipSub v1.1 eth2 subnets: 8 topics, 2/peer, fanout (config #5 scaled)",
        192, 8,
        PeerScoreParams(
            topics={t: TopicScoreParams(
                mesh_message_deliveries_weight=0.0,
                mesh_failure_penalty_weight=0.0,
            ) for t in range(8)},
            skip_app_specific=True,
            behaviour_penalty_weight=-1.0,
            behaviour_penalty_threshold=1.0,
            behaviour_penalty_decay=0.9,
        ),
        PeerScoreThresholds(),
        n_topics=8,
        subs=graph.subscribe_random(192, n_topics=8, topics_per_peer=2,
                                    seed=3),
        fanout=True,
        topic_sched=_t_rng.integers(0, 8, size=(18, 2)).astype(np.int32),
        seed=9,
        extra_note="coverage hole structurally attributed below",
    )

    # ---- write report ---------------------------------------------------
    lines = [
        "# PARITY — vectorized routers vs. scalar per-node oracles",
        "",
        "Generated by `scripts/parity_report.py` (CPU run). The oracles",
        "(`oracle/`) are deliberately naive per-node Python transcriptions of",
        "the reference call stacks (SURVEY §3); RNG streams cannot match a",
        "batched engine (survey §7 hard-part (d)), so the randomsub and",
        "gossipsub rows compare propagation-latency CDFs — the north-star",
        "tolerance is 2% sup-norm. FloodSub has no randomness: its row is",
        "bit-exact equivalence. The v1.1 rows run the COMPOSED machine —",
        "scoring, thresholds, promise penalties (and sybils / fanout) live",
        "in the loop on both sides (tests/test_parity_v11.py asserts the",
        "same bound in CI).",
        "",
        "Round-2 notes. (1) The round-1 v1.0 residual (1.44-1.46%) was",
        "attributed by ablation (Dlazy=0 collapsed the gap) to the gossip",
        "plane, and root-caused to an engine bug — the recycled-slot",
        "clear erased fresh publishes from the origin's mcache, so the",
        "origin never advertised IHAVE or served IWANT for its own",
        "message and gossip recovery ran one hop late. Fixed in",
        "models/gossipsub.py (mcache put ordering); the v1.0 rows below",
        "reflect the fix. (2) Without scoring the mesh freezes once",
        "converged, so a single-seed comparison mostly measures the",
        "mesh-formation lottery (across-seed converged-degree spread at",
        "512/d=10: engine 8.13-8.45, oracle 8.18-8.53 — overlapping, no",
        "bias).",
        "",
        "Round 3: every gossipsub row (v1.0 AND v1.1) pools 5 RNG seeds",
        "per side, and the sup column carries leave-one-out jackknife",
        "error bars: `pooled (jk mean/max)` over all 25 (drop-one-engine,",
        "drop-one-oracle) pool pairs. For the LOSSLESS rows both the",
        "pooled sup and the jackknife max are enforced <= 2% — a margin",
        "that only holds for one lucky seed set is not parity. The lossy",
        "queue_cap row's bound is noise-derived (3.5%; see its residual",
        "note below) because whole-message deaths quantize its CDF. The mixed-validation-latency",
        "row runs per-topic async verdict delays (survey §7 hard-part c;",
        "tests/test_parity_valdelay.py pins the same bound plus the",
        "deterministic hop law in CI).",
        "",
    ]
    header_row = ("| config | CDF sup-dist | mean-hop rel. diff | "
                  "coverage (vec/oracle) | notes |")
    sep_row = "|---|---|---|---|---|"
    gen_rows = ["| " + " | ".join(str(x) for x in r) + " |" for r in rows]

    # preserve hand-curated content from the existing PARITY.md: the
    # PREAMBLE prose (the hardcoded list above is only the bootstrap for
    # a missing file — a direct edit to PARITY.md's intro must survive
    # regeneration), table rows this script does not generate (the
    # phase-engine rows are maintained by tests/test_parity_phase.py and
    # tests/test_parity_phase_oracle.py, which print their measurements),
    # and every "## " analysis section after the table. Anchored to the
    # repo root, not the cwd, so running from scripts/ (or CI) can't
    # silently write a stripped file.
    from pathlib import Path as _Path

    parity_path = _Path(__file__).resolve().parent.parent / "PARITY.md"
    extra_rows, tail, preamble = [], [], None
    if parity_path.exists():
        own = {str(r[0]) for r in rows}
        in_tail = False
        seen_table = False
        pre = []
        for ln in parity_path.read_text().splitlines():
            if ln.startswith("## "):
                in_tail = True
            if in_tail:
                tail.append(ln)
            elif ln.startswith("|"):
                seen_table = True
                cells = ln.split("|")
                label = cells[1].strip() if len(cells) > 1 else ""
                if (label and label != "config"
                        and not set(label) <= {"-"}
                        and label not in own):
                    extra_rows.append(ln)
            elif not seen_table:
                pre.append(ln)
        if pre:
            preamble = pre
    if extra_rows:
        # visibility guard: a preserved row whose label SHOULD have been
        # regenerated (e.g. after renaming a config label above) would
        # linger here as a stale duplicate that enforcement never checks
        # — the list below is what a reviewer must eyeball
        print("preserved hand-curated rows (not re-enforced by this run):")
        for ln in extra_rows:
            print("  " + ln.split("|")[1].strip())
    out = (preamble if preamble is not None else lines) \
        + [header_row, sep_row] + gen_rows + extra_rows + [""] + tail
    print("\n".join(out))

    # enforce the documented tolerances BEFORE writing: bit-exactness for
    # floodsub, the 2% north-star sup-norm for every distributional row's
    # POOLED sup AND its jackknife max (no leave-one-out pool pair may
    # exceed 2% either — a margin that only holds for one lucky seed set
    # is not parity). A failing run must NOT rewrite the checked-in
    # report with the out-of-tolerance numbers it just rejected.
    failed = [r[0] for r in rows if r[1] == "MISMATCH"]
    for r in rows:
        if "%" not in str(r[1]):
            continue
        pooled_sup = float(str(r[1]).split("%")[0])
        if pooled_sup > 2.0:
            failed.append(f"{r[0]} (pooled {pooled_sup}%)")
        if "jk " in str(r[1]):
            jk_max = float(str(r[1]).split("/")[-1].rstrip("%)"))
            if jk_max > 2.0:
                failed.append(f"{r[0]} (jk max {jk_max}%)")
    if failed:
        print("PARITY FAILURES (PARITY.md left untouched):",
              "; ".join(failed))
        sys.exit(1)
    parity_path.write_text("\n".join(out) + ("\n" if tail else ""))


if __name__ == "__main__":
    main()
