"""Parity report: vectorized routers vs. the scalar per-node oracles.

Runs the BASELINE.json comparison configs (scaled to oracle-tractable
sizes — the oracles are deliberately naive per-node Python) and writes
PARITY.md with, per config:

  * propagation-latency CDF sup-distance (north-star tolerance: 2%)
  * mean-hop relative difference
  * delivery coverage on both sides
  * aggregate event-counter ratios (deliver / duplicate / RPC)

FloodSub is deterministic given the topology, so its row is checked
bit-for-bit (seen sets, first_round, first_edge, every counter) rather
than distributionally.

Usage: python scripts/parity_report.py  (CPU; a few minutes)
"""

from __future__ import annotations

import sys

import numpy as np


def main():
    import jax

    jax.config.update("jax_platforms", "cpu")
    sys.path.insert(0, ".")
    import jax.numpy as jnp

    from go_libp2p_pubsub_tpu import graph
    from go_libp2p_pubsub_tpu.config import GossipSubParams
    from go_libp2p_pubsub_tpu.models.floodsub import floodsub_step
    from go_libp2p_pubsub_tpu.models.gossipsub import (
        GossipSubConfig,
        GossipSubState,
        make_gossipsub_step,
        no_publish,
    )
    from go_libp2p_pubsub_tpu.oracle.floodsub import OracleFloodSub
    from go_libp2p_pubsub_tpu.oracle.gossipsub import OracleGossipSub
    from go_libp2p_pubsub_tpu.ops import bitset
    from go_libp2p_pubsub_tpu.state import Net, SimState, hops
    from go_libp2p_pubsub_tpu.trace.events import EV

    MAX_H = 16
    rows = []

    def cdf(hop_list, n_msgs, n_peers):
        hist = np.zeros(MAX_H + 1)
        for h in hop_list:
            hist[min(int(h), MAX_H)] += 1
        return np.cumsum(hist) / (n_msgs * n_peers)

    # ---- config 1: FloodSub, 64 hosts, connectAll — bit-exact ----------
    n, msg_slots = 64, 64
    topo = graph.connect_all(n)
    subs = graph.subscribe_all(n, 1)
    net = Net.build(topo, subs)
    st = SimState.init(n, msg_slots, seed=0, k=net.max_degree)
    oracle = OracleFloodSub(topo, subs, msg_slots=msg_slots)
    rng = np.random.default_rng(0)
    exact = True
    for r in range(30):
        pubs = [(int(rng.integers(0, n)), 0, True)] if r % 2 == 0 else []
        po = np.full((1,), pubs[0][0] if pubs else -1, np.int32)
        pt = np.zeros((1,), np.int32)
        pv = np.asarray([bool(pubs)])
        st = floodsub_step(net, st, jnp.asarray(po), jnp.asarray(pt), jnp.asarray(pv))
        oracle.step(pubs)
    have = np.asarray(bitset.unpack(st.dlv.have, msg_slots))
    fr = np.asarray(st.dlv.first_round)
    fe = np.asarray(st.dlv.first_edge)
    for i in range(n):
        if set(np.nonzero(have[i])[0].tolist()) != oracle.seen[i]:
            exact = False
        for slot in oracle.seen[i]:
            if fr[i, slot] != oracle.first_round[(i, slot)]:
                exact = False
            if fe[i, slot] != oracle.first_edge[(i, slot)]:
                exact = False
    ev = np.asarray(st.events)
    ev_exact = all(int(ev[e]) == oracle.events[e] for e in range(len(ev)))
    rows.append(("FloodSub 64 connectAll (config #1)",
                 "bit-exact" if exact and ev_exact else "MISMATCH",
                 "-", "-", "every seen set, first_round, first_edge, counter"))

    # ---- gossipsub configs: CDF comparison ------------------------------
    def gossip_row(label, n, deg, params, warmup=20, pub_rounds=18, drain=14,
                   seed=5):
        topo = graph.random_connect(n, d=deg, seed=seed)
        subs = graph.subscribe_all(n, 1)
        schedule = np.random.default_rng(7).integers(
            0, n, size=(pub_rounds, 2)).astype(np.int32)

        netx = Net.build(topo, subs)
        cfg = GossipSubConfig.build(params)
        stx = GossipSubState.init(netx, 64, cfg, seed=3)
        step = make_gossipsub_step(cfg, netx)
        empty = no_publish(2)
        for _ in range(warmup):
            stx = step(stx, *empty)
        pt = jnp.zeros((2,), jnp.int32)
        pv = jnp.ones((2,), bool)
        for r in range(pub_rounds):
            stx = step(stx, jnp.asarray(schedule[r]), pt, pv)
        for _ in range(drain):
            stx = step(stx, *empty)
        hv = np.asarray(hops(stx.core.msgs, stx.core.dlv))
        hv = [int(x) for x in hv[hv >= 0]]
        ev_v = np.asarray(stx.core.events)

        o = OracleGossipSub(topo, subs, cfg, msg_slots=64, seed=11)
        for _ in range(warmup):
            o.step()
        for r in range(pub_rounds):
            o.step([(int(p), 0, True) for p in schedule[r]])
        for _ in range(drain):
            o.step()
        ho = list(o.hops().values())

        n_msgs = pub_rounds * 2
        cv, co = cdf(hv, n_msgs, n), cdf(ho, n_msgs, n)
        sup = float(np.max(np.abs(cv - co)))
        mean_rel = abs(np.mean(hv) - np.mean(ho)) / np.mean(ho)
        ratios = []
        for e in (EV.DELIVER_MESSAGE, EV.DUPLICATE_MESSAGE, EV.SEND_RPC):
            ratios.append(float(ev_v[e]) / max(float(o.events[e]), 1.0))
        rows.append((label, f"{100*sup:.2f}%", f"{100*mean_rel:.2f}%",
                     f"{cv[-1]*100:.1f}% / {co[-1]*100:.1f}%",
                     "dlv/dup/rpc ratios " + "/".join(f"{x:.3f}" for x in ratios)))

    # ---- config 2: RandomSub sqrt-fanout (scaled) -----------------------
    def randomsub_row(label, n, deg, pub_rounds=18, drain=12, seed=5):
        from go_libp2p_pubsub_tpu.models.randomsub import make_randomsub_step
        from go_libp2p_pubsub_tpu.oracle.randomsub import OracleRandomSub
        from go_libp2p_pubsub_tpu.state import SimState

        topo = graph.random_connect(n, d=deg, seed=seed)
        subs = graph.subscribe_all(n, 1)
        schedule = np.random.default_rng(7).integers(
            0, n, size=(pub_rounds, 2)).astype(np.int32)
        netx = Net.build(topo, subs)
        stx = SimState.init(n, 64, seed=3, k=netx.max_degree)
        step = make_randomsub_step(netx)
        pt = jnp.zeros((2,), jnp.int32)
        pv = jnp.ones((2,), bool)
        for r in range(pub_rounds):
            stx = step(stx, jnp.asarray(schedule[r]), pt, pv)
        for _ in range(drain):
            stx = step(stx, *no_publish(2))
        hvv = np.asarray(hops(stx.msgs, stx.dlv))
        hv = [int(x) for x in hvv[hvv >= 0]]
        o = OracleRandomSub(topo, subs, msg_slots=64, seed=11)
        for r in range(pub_rounds):
            o.step([(int(p), 0, True) for p in schedule[r]])
        for _ in range(drain):
            o.step()
        ho = list(o.hops().values())
        n_msgs = pub_rounds * 2
        cv, co = cdf(hv, n_msgs, n), cdf(ho, n_msgs, n)
        sup = float(np.max(np.abs(cv - co)))
        mean_rel = abs(np.mean(hv) - np.mean(ho)) / np.mean(ho)
        rows.append((label, f"{100*sup:.2f}%", f"{100*mean_rel:.2f}%",
                     f"{cv[-1]*100:.1f}% / {co[-1]*100:.1f}%",
                     "sqrt-fanout target, fresh draw per round"))

    randomsub_row("RandomSub sqrt-fanout, 192 peers d=8 (config #2 scaled)",
                  192, 8)

    gossip_row("GossipSub v1.0, 192 peers d=8 (config #3 scaled)",
               192, 8, GossipSubParams())
    gossip_row("GossipSub v1.0 + flood-publish, 192 peers d=8",
               192, 8, GossipSubParams(flood_publish=True))
    gossip_row("GossipSub v1.0, 512 peers d=10 sparse",
               512, 10, GossipSubParams(), pub_rounds=14)

    # ---- write report ---------------------------------------------------
    lines = [
        "# PARITY — vectorized routers vs. scalar per-node oracles",
        "",
        "Generated by `scripts/parity_report.py` (CPU run). The oracles",
        "(`oracle/`) are deliberately naive per-node Python transcriptions of",
        "the reference call stacks (SURVEY §3); RNG streams cannot match a",
        "batched engine (survey §7 hard-part (d)), so the randomsub and",
        "gossipsub rows compare propagation-latency CDFs — the north-star",
        "tolerance is 2% sup-norm. FloodSub has no randomness: its row is",
        "bit-exact equivalence.",
        "",
        "| config | CDF sup-dist | mean-hop rel. diff | coverage (vec/oracle) | notes |",
        "|---|---|---|---|---|",
    ]
    for r in rows:
        lines.append("| " + " | ".join(str(x) for x in r) + " |")
    lines.append("")
    open("PARITY.md", "w").write("\n".join(lines))
    print("\n".join(lines))

    # enforce the documented tolerances: bit-exactness for floodsub, the
    # 2% north-star sup-norm for every distributional (CDF) row
    failed = [r[0] for r in rows if r[1] == "MISMATCH"]
    failed += [r[0] for r in rows
               if r[1].endswith("%") and float(r[1].rstrip("%")) > 2.0]
    if failed:
        print("PARITY FAILURES:", "; ".join(failed))
        sys.exit(1)


if __name__ == "__main__":
    main()
