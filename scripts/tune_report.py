"""``make tune-smoke`` — the ensemble parameter-search gate (round 20).

A 2-generation, 8-candidate × 4-sim micro-search on the sybil-flood
cell, CPU-pinned, asserting the tune/ subsystem's acceptance claims:

  * **one compile per search** — generation 1's window compiles
    exactly once; every later generation re-dispatches the SAME
    program with a new candidate plane (compiles == 0 warm);
  * **one dispatch per generation** — the whole C*S-row,
    all-rounds, invariant-checked window is a single XLA dispatch;
  * **defaults are candidate 0** — the profile's own values decode/
    encode round-trip exactly and run as the pairing baseline in
    every generation;
  * **the invariant gate is live** — the negative check evaluates an
    IN-SPACE wide-mesh candidate under a deliberately TIGHT envelope
    (the base config's own degree bounds) and must disqualify it
    while the defaults row passes;
  * **every candidate row carries fingerprint["cost"]** priced by the
    static auditor plus the degree-scaled wire model;
  * **byte-identical reproduction** — the committed ``TUNE_SMOKE.json``
    must equal this run's record byte for byte (the LIFT_AUDIT /
    MEM_AUDIT pattern); ``TUNE_SMOKE_UPDATE=1`` rewrites it.

The gate pins the THREEFRY PRNG (not unsafe_rbg): the paired-lift
claim needs batched rows with equal sim keys to draw identical
streams, which is exactly threefry's elementwise vmap batching
(ensemble/batch.py's bit-exactness contract).
"""

from __future__ import annotations

import argparse
import json
import os
import sys

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)

ARTIFACT = "TUNE_SMOKE.json"

#: smoke shape: small enough for `make quick`, big enough that the
#: attack window and the score machinery are genuinely exercised
SMOKE_GENERATIONS = 2
SMOKE_CANDIDATES = 8
SMOKE_SIMS = 4
NEG_N = 48
NEG_ROUNDS = 24


def run_search(seed: int = 0, generations: int = SMOKE_GENERATIONS,
               n_candidates: int = SMOKE_CANDIDATES,
               n_sims: int = SMOKE_SIMS, cost_weight: float = 0.0,
               checkpoint: str | None = None, resume: bool = False):
    from go_libp2p_pubsub_tpu import tune

    space = tune.default_space()
    cell = tune.make_cell(space, n_candidates=n_candidates,
                          n_sims=n_sims, seed=seed)
    rec = tune.search(
        cell, generations=generations,
        escfg=tune.ESConfig(n_candidates=n_candidates, mu=3, seed=seed),
        cost_weight=cost_weight, checkpoint_path=checkpoint,
        resume=resume)
    return space, cell, rec


def run_negative(space, seed: int = 0) -> dict:
    """The seeded-violation check: a lossless, adversary-free cell
    whose invariant checker keeps the BASE config's tight degree
    bounds, evaluated on {defaults, in-space wide mesh}. The wide
    candidate grafts past Dhi+overshoot and must be disqualified; the
    defaults row must stay clean."""
    import numpy as np

    from go_libp2p_pubsub_tpu import tune

    cell = tune.make_cell(space, n_candidates=2, n_sims=2, n=NEG_N,
                          rounds=NEG_ROUNDS, seed=seed, adversary=False,
                          loss=0.0, envelope="tight")
    wide = dict(cell.base_values)
    # the space's widest mesh: legal by construction, far outside the
    # base profile's Dhi=4 (+ Dout + opportunistic overshoot) bound
    wide.update(D=10, Dlo=6, Dhi=16, Dscore=5, Dout=5, Dlazy=12)
    res = tune.evaluate(cell, [cell.base_values, wide])
    return {
        "n": NEG_N,
        "rounds": NEG_ROUNDS,
        "envelope": "tight",
        "wide_candidate": {k: wide[k]
                           for k in ("D", "Dlo", "Dhi", "Dscore",
                                     "Dout", "Dlazy")},
        "ok": [bool(v) for v in res.ok],
        "disqualified": int((~res.ok).sum()),
        "defaults_ok": bool(res.ok[0]),
        "compiles": res.compiles,
        "dispatches": res.dispatches,
        "fitness": [None if not np.isfinite(v) else round(float(v), 6)
                    for v in res.fitness],
    }


def build_record(seed: int = 0) -> dict:
    from go_libp2p_pubsub_tpu import tune

    space, cell, rec = run_search(seed=seed)
    base = cell.base_values
    roundtrip = space.decode(space.encode(base))
    defaults_ok = all(
        roundtrip[k] == base[k] if isinstance(base[k], int)
        else abs(float(roundtrip[k]) - float(base[k])) < 1e-9
        for k in base)
    env = space.degree_envelope()
    rec["defaults_candidate0"] = bool(defaults_ok)
    rec["space_check_failures"] = len(
        tune.check_space(space, cell.profile, n_random=32, seed=seed))
    rec["envelope"] = env
    rec["negative_check"] = run_negative(space, seed=seed)
    best_gen = rec["generations"][-1]
    rec["paired_lift_best"] = next(
        r["delivery_lift"] for r in best_gen["candidates"]
        if r["candidate"] == best_gen["best_candidate"])
    return rec


def check_record(rec: dict) -> list:
    failures = []
    gens = rec["generations"]
    if len(gens) != SMOKE_GENERATIONS:
        failures.append(f"expected {SMOKE_GENERATIONS} generations, "
                        f"got {len(gens)}")
    for g in gens:
        want = (-1, 1) if g["generation"] == 0 else (-1, 0)
        if g["compiles"] not in want:
            failures.append(
                f"generation {g['generation']} ran {g['compiles']} "
                f"compiles (expected {want[1]} — one compile per "
                "search, zero warm recompiles)")
        if g["dispatches"] != 1:
            failures.append(
                f"generation {g['generation']} executed as "
                f"{g['dispatches']} dispatches (expected ONE window)")
        for row in g["candidates"]:
            cost = row.get("fingerprint", {}).get("cost", {})
            if not cost.get("recorded"):
                failures.append(
                    f"generation {g['generation']} candidate "
                    f"{row['candidate']} carries no audited "
                    "fingerprint['cost']")
                break
    if not rec.get("defaults_candidate0"):
        failures.append(
            "defaults-as-candidate-0 round-trip failed: "
            "space.decode(space.encode(base)) != base")
    if rec.get("space_check_failures"):
        failures.append(
            f"{rec['space_check_failures']} space-legality failures "
            "(every box point must materialize through the real "
            "validators)")
    neg = rec.get("negative_check", {})
    if neg.get("ok") != [True, False]:
        failures.append(
            "negative check: expected the tight-envelope gate to pass "
            "the defaults and disqualify the wide-mesh candidate, got "
            f"ok={neg.get('ok')}")
    if neg.get("compiles") not in (-1, 1):
        failures.append(
            f"negative check ran {neg.get('compiles')} compiles "
            "(expected 1 — its own window, invariants folded)")
    return failures


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    ap.add_argument("--smoke", action="store_true",
                    help="assert the acceptance gates + the committed "
                         "artifact; exit 1 on failure")
    ap.add_argument("--seed", type=int, default=0)
    ap.add_argument("--generations", type=int, default=SMOKE_GENERATIONS)
    ap.add_argument("--candidates", type=int, default=SMOKE_CANDIDATES)
    ap.add_argument("--sims", type=int, default=SMOKE_SIMS)
    ap.add_argument("--cost-weight", type=float, default=0.0,
                    help="lift traded per relative hbm byte/round "
                         "(fitness.rank_scores)")
    ap.add_argument("--checkpoint", type=str, default=None,
                    help="rolling ES-state checkpoint path")
    ap.add_argument("--resume", action="store_true",
                    help="resume from --checkpoint if present")
    args = ap.parse_args(argv)

    # CPU + threefry by contract (see module docstring), warm compiles
    # served from the persistent cache like every smoke gate
    import jax

    jax.config.update("jax_platforms", "cpu")
    from go_libp2p_pubsub_tpu.compile_cache import enable_persistent_cache
    from go_libp2p_pubsub_tpu.perf.regress import repo_root

    enable_persistent_cache(os.path.join(repo_root(), ".jax_cache"))

    if not args.smoke:
        # report mode: run the requested search and print the record
        _space, _cell, rec = run_search(
            seed=args.seed, generations=args.generations,
            n_candidates=args.candidates, n_sims=args.sims,
            cost_weight=args.cost_weight, checkpoint=args.checkpoint,
            resume=args.resume)
        print(json.dumps(rec))
        return 0

    rec = build_record(seed=args.seed)
    failures = check_record(rec)

    path = os.path.join(repo_root(), ARTIFACT)
    text = json.dumps(rec, indent=1, sort_keys=True) + "\n"
    update = bool(os.environ.get("TUNE_SMOKE_UPDATE"))
    if update:
        with open(path, "w") as f:
            f.write(text)
        action = "updated"
    elif not os.path.exists(path):
        failures.append(
            f"{ARTIFACT} missing — run TUNE_SMOKE_UPDATE=1 "
            "scripts/tune_report.py --smoke to record it")
        action = "missing"
    else:
        with open(path) as f:
            committed = f.read()
        action = "verified" if committed == text else "stale"
        if committed != text:
            try:
                from go_libp2p_pubsub_tpu.analysis.costmodel import (
                    baseline_divergences,
                )

                diverged = baseline_divergences(
                    json.loads(committed), json.loads(text))
                detail = (" — diverging keys: " + "; ".join(diverged[:8])
                          if diverged else
                          " — artifacts parse equal: formatting-only "
                          "drift (re-serialize with TUNE_SMOKE_UPDATE=1)")
            except (json.JSONDecodeError, ValueError):
                detail = " — committed artifact is not parseable JSON"
            failures.append(
                f"{ARTIFACT} does not reproduce byte-identical — the "
                "search record changed; review the diff and "
                "TUNE_SMOKE_UPDATE=1 to re-record" + detail)

    summary = {
        "tune_smoke": "FAIL" if failures else "PASS",
        "artifact": action,
        "generations": len(rec["generations"]),
        "candidates": rec["cell"]["n_candidates"],
        "sims": rec["cell"]["n_sims"],
        "compiles": [g["compiles"] for g in rec["generations"]],
        "disqualified_negative": rec["negative_check"]["disqualified"],
        "best_score": rec["best"]["score"],
    }
    if failures:
        for f in failures:
            print(f"tune-smoke FAIL: {f}", file=sys.stderr)
    print(json.dumps(summary))
    return 1 if failures else 0


if __name__ == "__main__":
    sys.exit(main())
