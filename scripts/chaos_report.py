"""Chaos scenario runner + the ``make chaos-smoke`` gate.

Runs the chaos plane's two canonical degraded-network experiments
(the v1.1 evaluation methodology's shape, arxiv 2007.02754) end to end
and emits one schema-v2 JSON line per measurement, each carrying the
chaos fingerprint (generator kind, loss rate, scenario hash —
perf/artifacts.chaos_fingerprint).

Since round 10 every cell is a MONTE CARLO BAND, not a point estimate:
``--seeds S`` (default 8) runs S sims with independent PRNG/fault
streams as ONE vmapped XLA program through the ensemble plane
(go_libp2p_pubsub_tpu/ensemble), and each metric line reports the
median with the IQR (plus per-sim values and a bootstrap CI) — the
many-trial distribution shape the evaluation literature reports.
Fingerprints carry the ``ensemble`` block (S, sim-key derivation,
aggregation mode). The smoke assertions compare BANDS: medians for the
ratio ordering, every sim for recovery liveness.

  * **flap** — i.i.d. link-flap loss on the same topology, subscription
    set, publish schedule and fault seed for gossipsub v1.1 AND
    floodsub: delivery ratio under loss per router, plus gossipsub's
    IWANT-recovery share (the lazy-gossip machinery's measured
    contribution — floodsub has no recovery path, so under enough loss
    its single-shot forwarding strands peers that gossipsub's
    IHAVE/IWANT retries reach). A phase-engine (r > 1, coalesced
    stacked wire) cell runs the same generator through the flagship
    cadence.
  * **partition** — a scheduled 2-group partition with P3
    deficit-scoring live: cross-group mesh edges starve and are pruned
    during the window; after heal the mesh re-grafts (measured
    mesh-repair latency) and messages published DURING the partition
    cross over via IWANT service from mcache (measured
    time-to-recover; the publish window sits inside the mcache history
    so recovery is possible at all — the experiment the chaos plane
    exists for).

``--smoke`` additionally asserts the acceptance invariants and that
the CHAOS-OFF compiled HLO kernel census still equals the committed
PERF_SMOKE.json baseline (the elision-when-off contract at the
compiler level; rates are perf-smoke's job, structure is ours), and
exits non-zero on any failure. The gate is CPU-only by contract, like
perf-smoke.
"""

from __future__ import annotations

import argparse
import json
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import numpy as np  # noqa: E402

#: smoke-shape defaults: big enough for a measurable cut and a real
#: recovery tail, small enough that the whole gate is tens of seconds
#: warm (the kernel census dominates, and `make quick` runs perf-smoke
#: first so its compile cache is hot)
SMOKE_N = 128
FLAP_LOSS = 0.6
FLAP_ROUNDS = 80
PARTITION_START = 12
PARTITION_ROUNDS = 24
# rounds after heal. 56 covers the full post-heal arc in EVERY stream,
# not just lucky ones (the band's re-baselining of the round-8 tail of
# 40): heal-time survivors are pruned by their partition-era P3 deficit
# over ~heal+20 rounds, pruned edges wait out prune_backoff plus the
# reference's lazy 15-tick backoff-present clear (gossipsub.go:
# 1585-1604), and the re-graft wave lands around heal+40
PARTITION_TAIL = 56
#: Monte Carlo width: sims per cell, one vmapped program (ensemble
#: plane); every reported number is a median over SMOKE_SEEDS
#: independent PRNG/fault streams derived via fold_in(sim_key, i)
SMOKE_SEEDS = 8


def _flap_params(gossip: bool = True):
    """Low-degree v1.1 overlay so the mesh (D=3) leaves non-mesh
    neighbors for IHAVE gossip — the recovery path under test.
    ``gossip=False`` disables the lazy-gossip machinery (Dlazy=0,
    gossip_factor=0: no IHAVE advertising, hence no IWANT recovery)
    for the paired ablation cell — same mesh, same fault streams,
    recovery off."""
    from go_libp2p_pubsub_tpu.config import GossipSubParams

    extra = {} if gossip else {"Dlazy": 0, "gossip_factor": 0.0}
    return GossipSubParams(D=3, Dlo=2, Dhi=4, Dscore=2, Dout=1,
                           history_length=6, history_gossip=4, **extra)


def _score_params():
    """Honest-net live scoring (deficit off), like the bench default."""
    from go_libp2p_pubsub_tpu.perf.sweep import bench_score_params

    return bench_score_params("default", 1)[1]


def _publish_schedule(rng, n, rounds, pub_rounds, width=4):
    po = np.full((rounds, width), -1, np.int32)
    po[:pub_rounds] = rng.integers(0, n, size=(pub_rounds, width))
    pt = np.zeros((rounds, width), np.int32)
    pv = np.ones((rounds, width), bool)
    return po, pt, pv


def run_flap(n=SMOKE_N, loss=FLAP_LOSS, rounds=FLAP_ROUNDS, seed=0,
             rounds_per_phase=1, seeds=SMOKE_SEEDS, full=True,
             telemetry=False, invariants=False):
    """One flap cell over ``seeds`` sims (one vmapped program per
    router): per-sim gossipsub/floodsub delivery ratios and IWANT
    shares plus their median/IQR bands. Same topology / schedule for
    every sim and both routers; per-sim fault + sampler streams derive
    from ``fold_in(sim_key, i)``, shared across the two routers (the
    chaos hash keys on the canonical link id and the sim key, which
    both runs share per sim).

    ``telemetry=True`` builds the gossipsub cell TELEMETRY-ON (one
    panel row per round/phase; telemetry/panel.py), reconciles the
    batched panels against the drained counters per sim, and returns
    the raw ``[S, T, n_metrics]`` panels plus a latency-CDF envelope
    for the ``--timeline`` artifact.

    ``invariants=True`` runs the invariant oracle plane
    (oracle/invariants.py, docs/DESIGN.md §12) inside the gossipsub
    cell: every safety property checked every
    ``InvariantConfig.check_every`` dispatches on device, the
    ``InvariantReport`` returned as ``out["invariants"]``. The flap
    generator is active for the whole run, so the delivery-liveness
    clause is vacuous here by the due contract (the quiet/partition
    cells in scripts/invariant_report.py exercise it)."""
    from go_libp2p_pubsub_tpu import ensemble, graph
    from go_libp2p_pubsub_tpu.chaos import ChaosConfig
    from go_libp2p_pubsub_tpu.config import PeerScoreThresholds
    from go_libp2p_pubsub_tpu.ensemble import stats as estats
    from go_libp2p_pubsub_tpu.models.gossipsub import (
        GossipSubConfig,
        GossipSubState,
        make_gossipsub_step,
    )
    from go_libp2p_pubsub_tpu.models.gossipsub_phase import (
        make_gossipsub_phase_step,
    )
    from go_libp2p_pubsub_tpu.state import Net, SimState

    s = int(seeds)
    topo = graph.random_connect(n, d=4, seed=seed)
    subs = graph.subscribe_all(n, 1)
    net = Net.build(topo, subs)
    cc = ChaosConfig(loss_rate=loss)
    rng = np.random.default_rng(seed)
    po, pt, pv = _publish_schedule(rng, n, rounds, pub_rounds=3)

    sp = _score_params()
    cfg = GossipSubConfig.build(
        _flap_params(), PeerScoreThresholds(), score_enabled=True,
        chaos=cc,
    )
    r = int(rounds_per_phase)
    tcfg = None
    if telemetry:
        from go_libp2p_pubsub_tpu.telemetry import TelemetryConfig

        tcfg = TelemetryConfig(rows=rounds // r)

    def run_gossipsub(g_cfg, tele=None, hook=None):
        # round 14: the whole cell is ONE scan-window program
        # (ensemble.run_window) — S sims x all rounds in a single
        # dispatch, the invariant checks folded into the scan body
        gs0 = GossipSubState.init(net, 64, g_cfg, score_params=sp, seed=seed,
                                  telemetry=tele)
        gstates = ensemble.batch_states(gs0, s)
        if r > 1:
            step = make_gossipsub_phase_step(g_cfg, net, r, score_params=sp,
                                             telemetry=tele)
            ens = ensemble.lift_step(step)
            assert rounds % r == 0

            def phase_args(p):
                sl = slice(p * r, (p + 1) * r)
                return (ensemble.tile(po[sl], s), ensemble.tile(pt[sl], s),
                        ensemble.tile(pv[sl], s))

            return ensemble.run_window(ens, gstates, phase_args, rounds // r,
                                       rounds_per_phase=r,
                                       heartbeat_fn=lambda p: True,
                                       invariants=hook)
        step = make_gossipsub_step(g_cfg, net, score_params=sp,
                                   telemetry=tele)
        ens = ensemble.lift_step(step)

        def round_args(i):
            return (ensemble.tile(po[i], s), ensemble.tile(pt[i], s),
                    ensemble.tile(pv[i], s))

        return ensemble.run_window(ens, gstates, round_args, rounds,
                                   invariants=hook)

    def ratios_of(core):
        return np.asarray(estats.sim_delivery_ratios(
            core.dlv.first_round, core.msgs.birth,
            core.msgs.topic, core.msgs.origin, net.subscribed,
        ))

    hook = None
    if invariants:
        from go_libp2p_pubsub_tpu.oracle import invariants as oracle_inv

        # phase cadence: checks land at phase boundaries, and the
        # delivery window scales with the control-latency quantum
        # (docs/DESIGN.md §12 cadence note). ScanInvariants folds the
        # checks INTO the window program (§14) — the cell stays one
        # dispatch with the oracle enabled.
        hook = oracle_inv.ScanInvariants(
            "phase" if r > 1 else "gossipsub", net, cfg,
            oracle_inv.InvariantConfig(
                check_every=max(8 // r, 1),
                delivery_window=12 if r == 1 else 24,
            ),
            rounds_per_step=r,
        )
    grun = run_gossipsub(cfg, tele=tcfg, hook=hook)
    g_ratios = ratios_of(grun.states.core)
    iwant_shares = estats.batched_iwant_shares(grun.states.core.events)
    out = {
        "gossipsub_ratios": g_ratios,
        "gossipsub_band": estats.quantile_band(g_ratios),
        "iwant_shares": iwant_shares,
        "iwant_band": estats.quantile_band(iwant_shares),
        "compiles": {"gossipsub": grun.compiles},
        "chaos": cc,
        "n": n,
        "rounds": rounds,
        "rounds_per_phase": r,
        "seeds": s,
    }
    if hook is not None:
        out["invariants"] = grun.invariant_report
        # folded checker: it compiles as part of the ONE window program
        out["invariant_compiles"] = grun.compiles
        out["dispatches"] = grun.dispatches
    if telemetry:
        from go_libp2p_pubsub_tpu.telemetry import reconcile_batched

        core = grun.states.core
        mism = reconcile_batched(np.asarray(core.telem.panel),
                                 np.asarray(core.events))
        if mism:  # the correctness anchor — a lying panel is a hard stop
            raise AssertionError(
                "drain-vs-timeline reconciliation failed: " + "; ".join(mism)
            )
        counts = estats.latency_cdf_counts(
            core.dlv.first_round, core.msgs.birth, core.msgs.topic,
            core.msgs.origin, net.subscribed, max_lat=20,
        )
        bands = estats.cdf_bands(counts, qs=(0.1, 0.9))
        out["panels"] = np.asarray(core.telem.panel)
        out["latency_cdf"] = {
            "lat": list(range(counts.shape[1])),
            "pooled": [round(float(v), 4) for v in bands["pooled"]],
            "q10": [round(float(v), 4) for v in bands["bands"][0]],
            "q90": [round(float(v), 4) for v in bands["bands"][1]],
        }
    if not full:
        return out

    # paired ablation: the SAME overlay/fault streams with the lazy-
    # gossip machinery off (Dlazy=0, gossip_factor=0 — no IHAVE, so no
    # IWANT recovery): the per-sim delivery delta IS the recovery
    # machinery's measured contribution, paired on fault stream
    cfg_ng = GossipSubConfig.build(
        _flap_params(gossip=False), PeerScoreThresholds(),
        score_enabled=True, chaos=cc,
    )
    ngrun = run_gossipsub(cfg_ng)
    ng_ratios = ratios_of(ngrun.states.core)

    fs0 = SimState.init(n, 64, seed=seed, k=net.max_degree)
    fens = ensemble.lift_floodsub(net, chaos=cc)
    frun = ensemble.run_window(
        fens, ensemble.batch_states(fs0, s),
        lambda i: (ensemble.tile(po[i], s), ensemble.tile(pt[i], s),
                   ensemble.tile(pv[i], s)),
        rounds,
    )
    f_ratios = np.asarray(estats.sim_delivery_ratios(
        frun.states.dlv.first_round, frun.states.msgs.birth,
        frun.states.msgs.topic, frun.states.msgs.origin, net.subscribed,
    ))
    out.update({
        "nogossip_ratios": ng_ratios,
        "nogossip_band": estats.quantile_band(ng_ratios),
        "floodsub_ratios": f_ratios,
        "floodsub_band": estats.quantile_band(f_ratios),
    })
    out["compiles"].update({"gossipsub_nogossip": ngrun.compiles,
                            "floodsub": frun.compiles})
    return out


#: partition-cell due-contract constant (oracle/invariants.py; mirrors
#: the measured recovery arc the smoke already pins): the fault-scoped
#: degree clauses stay suspended for this many rounds after heal (the
#: P3 zombie-prune → backoff-clear → re-graft wave lands around
#: heal+40, tail 56), and the SAME tick arms the recovery clauses —
#: partition-era messages fully delivered (ttr median 6, far earlier)
#: and the mesh re-formed. One constant on purpose: a reform deadline
#: earlier than the grace end would enforce the degree bound while the
#: grace contract still declares it suspended.
PARTITION_GRACE_AFTER_HEAL = 44


def run_partition(n=SMOKE_N, seed=1, start=PARTITION_START,
                  window=PARTITION_ROUNDS, tail=PARTITION_TAIL,
                  seeds=SMOKE_SEEDS, telemetry=False, invariants=False):
    """Partition/heal cell over ``seeds`` sims (one vmapped program):
    scheduled 2-group split with P3 deficit scoring live (cross-group
    mesh edges starve -> pruned during the window; short prune backoff
    so post-heal re-grafting is visible in the tail). Publishes land
    DURING the partition, inside the mcache window before heal, so
    recovery crosses via IWANT. The deny schedule is SHARED across
    sims (the scenario is the experiment); the protocol's sampler
    streams — mesh selection, gossip targeting — differ per sim, so
    mesh-repair latency / time-to-recover come back as distributions."""
    from go_libp2p_pubsub_tpu import ensemble, graph
    from go_libp2p_pubsub_tpu.chaos import (
        ChaosConfig,
        halves,
        make_cross_mesh_observer,
        mesh_reform_latency,
        time_to_recover,
        two_group_partition,
    )
    from go_libp2p_pubsub_tpu.config import (
        GossipSubParams,
        PeerScoreParams,
        PeerScoreThresholds,
        TopicScoreParams,
    )
    from go_libp2p_pubsub_tpu.models.gossipsub import (
        GossipSubConfig,
        GossipSubState,
        make_gossipsub_step,
    )
    from go_libp2p_pubsub_tpu.state import Net

    topo = graph.random_connect(n, d=4, seed=seed)
    subs = graph.subscribe_all(n, 1)
    net = Net.build(topo, subs)
    heal = start + window
    rounds = heal + tail
    scenario = two_group_partition(n, start=start, rounds=window)
    groups = np.asarray(halves(n))

    # P3 deficit live — and DOMINANT (time-in-mesh off) — so partition
    # starvation actually prunes cross-group mesh edges while steady
    # in-group traffic keeps in-group edges clean; the deficit penalty
    # (threshold² · weight · topic_weight = -4.5) stays above the
    # gossip threshold (-10) so IHAVE toward pruned peers keeps flowing
    # (that's the recovery path). Sticky P3b off and a short backoff so
    # the post-heal re-graft is visible inside the tail; P3 stops
    # counting at prune (mesh-only in the reference too), so pruned
    # cross peers return to ~0 score and are re-graftable after heal.
    tp = TopicScoreParams(
        time_in_mesh_weight=0.0,
        mesh_message_deliveries_weight=-1.0,
        mesh_message_deliveries_threshold=3.0,
        mesh_message_deliveries_activation=5.0,
        mesh_message_deliveries_window=2.0,
        mesh_message_deliveries_decay=0.9,
        mesh_failure_penalty_weight=0.0,
    )
    sp = PeerScoreParams(topics={0: tp}, skip_app_specific=True)
    params = GossipSubParams(D=3, Dlo=2, Dhi=4, Dscore=2, Dout=1,
                             history_length=12, history_gossip=10,
                             prune_backoff=4.0)
    cc = ChaosConfig(scheduled=True)
    cfg = GossipSubConfig.build(params, PeerScoreThresholds(),
                                score_enabled=True, chaos=cc)
    s = int(seeds)
    tcfg = None
    if telemetry:
        from go_libp2p_pubsub_tpu.telemetry import TelemetryConfig

        tcfg = TelemetryConfig(rows=rounds)
    st0 = GossipSubState.init(net, 64, cfg, score_params=sp, seed=seed,
                              telemetry=tcfg)
    step = make_gossipsub_step(cfg, net, score_params=sp, telemetry=tcfg)
    ens = ensemble.lift_step(step)
    from go_libp2p_pubsub_tpu.ensemble import stats as estats

    rng = np.random.default_rng(seed)
    nbr = np.asarray(net.nbr)
    nbr_ok = np.asarray(net.nbr_ok)
    width = 2
    # steady traffic from BOTH groups from warmup through heal: in-group
    # mesh edges keep delivering (P3-clean) while cross-group edges
    # starve and get pruned; the publishes of the last pre-heal rounds
    # (the born window below) sit inside the mcache history at heal so
    # IWANT recovery across the healed cut is possible at all. Traffic
    # stops at heal — publish volume after the born window stays far
    # below msg_slots, so the measured messages never recycle.
    pub_rounds = range(2, heal - 1)
    po_all = np.full((rounds, width), -1, np.int32)
    for t in pub_rounds:
        po_all[t] = rng.integers(0, n, size=width)
    pt_r = ensemble.tile(np.zeros(width, np.int32), s)
    pv_r = ensemble.tile(np.ones(width, bool), s)
    denies = []
    for t in range(rounds):
        deny = scenario.link_deny_at(t, nbr)
        denies.append(np.zeros(nbr.shape, bool) if deny is None else deny)

    # round 14: the cross-mesh repair arc is observed ON DEVICE inside
    # the scan window (chaos.make_cross_mesh_observer — the same
    # _cross_edge_mask reduction as the old host callback, so the
    # series is bit-identical) and comes back as stacked scan ys —
    # per-round observability no longer forces per-round dispatch
    observe = make_cross_mesh_observer(nbr, nbr_ok, groups)

    hook = None
    if invariants:
        from go_libp2p_pubsub_tpu.oracle import invariants as oracle_inv

        # at least one CHECK TICK must land at/after the recovery
        # deadline, or the partition-specific clauses (heal-liveness
        # delivery, mesh re-formation) never arm while grace keeps the
        # degree bounds suspended — an all-ok report that checked
        # nothing this cell exists for. Checks land at multiples of
        # check_every, so a bare tail >= grace test is not enough when
        # heal is cadence-misaligned. Refuse rather than rubber-stamp.
        check_every = 4
        deadline = heal + PARTITION_GRACE_AFTER_HEAL
        last_check = (rounds // check_every) * check_every
        if last_check < deadline:
            raise ValueError(
                f"run_partition(invariants=True): the last check tick "
                f"{last_check} (checks every {check_every} of {rounds} "
                f"rounds) never reaches the recovery deadline "
                f"{deadline} = heal + {PARTITION_GRACE_AFTER_HEAL}, so "
                "the heal-recovery clauses would run vacuously; extend "
                "tail")

        def due_fn(tick):
            # the due contract (docs/DESIGN.md §12): pre-partition
            # publishes are quiet-window due; fault-scoped safety
            # clauses suspend from the split until the measured re-form
            # arc completes; partition-era in-mcache messages are due
            # after the recovery deadline (the papers' heal-liveness)
            return oracle_inv.due_vector(
                quiet=(0, start),
                recover=(heal - 4, heal - 1,
                         heal + PARTITION_GRACE_AFTER_HEAL),
                grace=start <= tick < heal + PARTITION_GRACE_AFTER_HEAL,
            )

        hook = oracle_inv.ScanInvariants(
            "gossipsub", net, cfg,
            oracle_inv.InvariantConfig(check_every=check_every,
                                       delivery_window=8),
            due_fn=due_fn,
        )
    # the scheduled deny masks ride as stacked scan xs (one [S, N, K]
    # row per round), like the churn/publish planes — the whole
    # partition/heal/tail arc is ONE dispatch
    run = ensemble.run_window(
        ens, ensemble.batch_states(st0, s),
        lambda t: (ensemble.tile(po_all[t], s), pt_r, pv_r,
                   ensemble.tile(denies[t], s)),
        rounds, observe=observe, invariants=hook,
    )
    st = run.states
    mesh_series = [(t + 1, run.observations[t]) for t in range(rounds)]

    by_tick = {t: c for t, c in mesh_series}
    pre = by_tick[start] if start >= 1 else None
    during = by_tick[heal - 1]  # [S]
    repairs = np.asarray([
        r if (r := mesh_reform_latency(
            [(t, int(c[i])) for t, c in mesh_series], heal_tick=heal,
        )) is not None else np.nan
        for i in range(s)
    ], np.float64)
    born = (heal - 4, heal - 1)
    ratios = np.asarray(estats.sim_delivery_ratios(
        st.core.dlv.first_round, st.core.msgs.birth, st.core.msgs.topic,
        st.core.msgs.origin, net.subscribed, born_in=born,
    ))
    fr = np.asarray(st.core.dlv.first_round)
    birth = np.asarray(st.core.msgs.birth)
    topic = np.asarray(st.core.msgs.topic)
    origin = np.asarray(st.core.msgs.origin)
    subscribed = np.asarray(net.subscribed)
    ttrs = np.asarray([
        t if (t := time_to_recover(
            fr[i], birth[i], topic[i], origin[i], subscribed,
            heal_tick=heal, born_in=born,
        )) is not None else np.nan
        for i in range(s)
    ], np.float64)
    out = {
        "cross_mesh_pre_partition": (
            None if pre is None else [int(x) for x in pre]
        ),
        "cross_mesh_at_heal": [int(x) for x in during],
        "mesh_repair_latencies": repairs,
        "repair_band": estats.quantile_band(repairs),
        "partition_delivery_ratios": ratios,
        "ratio_band": estats.quantile_band(ratios),
        "times_to_recover": ttrs,
        "ttr_band": estats.quantile_band(ttrs),
        "compiles": run.compiles,
        "scenario": scenario,
        "chaos": cc,
        "n": n,
        "rounds": rounds,
        "start": start,
        "heal": heal,
        "seeds": s,
    }
    if hook is not None:
        out["invariants"] = run.invariant_report
        out["invariant_compiles"] = run.compiles
        out["dispatches"] = run.dispatches
    if telemetry:
        from go_libp2p_pubsub_tpu.telemetry import reconcile_batched

        mism = reconcile_batched(np.asarray(st.core.telem.panel),
                                 np.asarray(st.core.events))
        if mism:
            raise AssertionError(
                "drain-vs-timeline reconciliation failed: " + "; ".join(mism)
            )
        out["panels"] = np.asarray(st.core.telem.panel)
        # the repair-arc series the run report plots — the SAME rows
        # mesh_reform_latency consumed above, so plot and metric agree
        cs = np.asarray([c for _, c in mesh_series], np.float64)  # [T, S]
        qs = np.quantile(cs, [0.25, 0.5, 0.75], axis=1)
        out["cross_mesh_series"] = {
            "ticks": [int(t) for t, _ in mesh_series],
            "q25": [round(float(v), 2) for v in qs[0]],
            "q50": [round(float(v), 2) for v in qs[1]],
            "q75": [round(float(v), 2) for v in qs[2]],
        }
    return out


def check_census() -> dict:
    """CHAOS-OFF structural gate, image-portable since round 14: the
    compiled phase-step kernel census at the PERF_SMOKE shape must
    EQUAL the baseline MEASURED ON THIS IMAGE (seeded by the first
    census-gate run here — perf.profile.on_image_census_baseline), so
    the elision-when-off contract is checked diff-neutrally: a
    container/XLA change moves both sides together (PR 8's 324-vs-393
    was exactly that, on seed), while a diff that leaks chaos kernels
    into the off build still fails. The committed PERF_SMOKE value is
    reported as an informational pin."""
    from go_libp2p_pubsub_tpu.perf.profile import (
        compiled_phase_kernel_count,
        on_image_census_baseline,
    )
    from go_libp2p_pubsub_tpu.perf.regress import (
        BASELINE_NAME,
        PERF_SMOKE_N,
        PERF_SMOKE_R,
        repo_root,
    )

    base_path = os.path.join(repo_root(), BASELINE_NAME)
    committed = None
    if os.path.exists(base_path):
        with open(base_path) as f:
            committed = (json.load(f).get("hlo_kernels") or {}).get("total")
    census = compiled_phase_kernel_count(
        int(os.environ.get("PERF_SMOKE_N", PERF_SMOKE_N)),
        int(os.environ.get("PERF_SMOKE_R", PERF_SMOKE_R)),
    )
    onimage = on_image_census_baseline(census)
    return {"total": census["total"], "committed": committed,
            "on_image": onimage["total"], "seeded": onimage["seeded"],
            "committed_equal": (committed is None
                                or census["total"] == committed),
            "equal": census["total"] == onimage["total"]}


def _emit(metric, value, chaos=None, scenario=None, extras=None,
          n_sims=1):
    from go_libp2p_pubsub_tpu.perf.artifacts import (
        BenchRecord,
        chaos_fingerprint,
        dump_record,
        ensemble_fingerprint,
    )

    rec = BenchRecord(
        metric=metric, value=float(value), unit="ratio", vs_baseline=0.0,
        schema=2,
        fingerprint={"chaos": chaos_fingerprint(chaos, scenario),
                     "ensemble": ensemble_fingerprint(n_sims)},
        extras=extras or {},
    )
    print(dump_record(rec), flush=True)


def _band_extras(band: dict, per_sim, ci=None) -> dict:
    """The distribution block every band metric line carries: IQR
    bounds, per-sim values, undefined count, optional bootstrap CI."""
    out = {
        "iqr": [band.get("q25"), band.get("q75")],
        "min": band.get("min"),
        "max": band.get("max"),
        "n_sims": band["n"],
        "n_undefined": band["n_undefined"],
        "per_sim": [None if not np.isfinite(v) else round(float(v), 4)
                    for v in np.asarray(per_sim, np.float64)],
    }
    if ci is not None:
        out["bootstrap_ci_median"] = [round(ci[0], 4), round(ci[1], 4)]
    return out


def run_timeline(prefix: str, n=SMOKE_N, loss=FLAP_LOSS, rounds=FLAP_ROUNDS,
                 seed=0, seeds=SMOKE_SEEDS) -> tuple:
    """The ``--timeline`` mode: both canonical cells TELEMETRY-ON, the
    per-round panels reduced to schema-v3 timeline bands, written as
    ``<prefix>.json`` (one artifact line per cell) and rendered as the
    self-contained ``<prefix>.html`` dashboard (scripts/run_report.py).
    The batched panels are reconciled against the drained counters per
    sim before anything is written — a lying timeline never ships."""
    from go_libp2p_pubsub_tpu.perf.artifacts import (
        BenchRecord,
        chaos_fingerprint,
        ensemble_fingerprint,
    )
    from go_libp2p_pubsub_tpu.telemetry import timeline_block

    import run_report as run_report_mod

    flap = run_flap(n=n, loss=loss, rounds=rounds, seed=seed, seeds=seeds,
                    full=False, telemetry=True)
    part = run_partition(n=n, seed=seed + 1, seeds=seeds, telemetry=True)
    lines = [
        BenchRecord(
            metric="chaos_flap_delivery_ratio_gossipsub",
            value=float(flap["gossipsub_band"]["q50"]), unit="ratio",
            vs_baseline=0.0, schema=3,
            fingerprint={"chaos": chaos_fingerprint(flap["chaos"]),
                         "ensemble": ensemble_fingerprint(flap["seeds"])},
            extras={
                "n_peers": flap["n"], "rounds": flap["rounds"],
                "iqr": [flap["gossipsub_band"].get("q25"),
                        flap["gossipsub_band"].get("q75")],
                "iwant_recovery_share_median":
                    round(float(flap["iwant_band"]["q50"]), 4),
                "iwant_recovery_share_iqr": [
                    round(float(flap["iwant_band"]["q25"]), 4),
                    round(float(flap["iwant_band"]["q75"]), 4)],
                "latency_cdf": flap["latency_cdf"],
            },
            timeline_raw=timeline_block(flap["panels"]),
        ),
        BenchRecord(
            metric="chaos_partition_delivery_ratio",
            value=float(part["ratio_band"]["q50"]), unit="ratio",
            vs_baseline=0.0, schema=3,
            fingerprint={"chaos": chaos_fingerprint(part["chaos"],
                                                    part["scenario"]),
                         "ensemble": ensemble_fingerprint(part["seeds"])},
            extras={
                "n_peers": part["n"], "rounds": part["rounds"],
                "iqr": [part["ratio_band"].get("q25"),
                        part["ratio_band"].get("q75")],
                "partition_window": [part["start"], part["heal"]],
                "mesh_reform_latency_median": part["repair_band"].get("q50"),
                "mesh_reform_latency_iqr": [part["repair_band"].get("q25"),
                                            part["repair_band"].get("q75")],
                "time_to_recover_median": part["ttr_band"].get("q50"),
                "cross_mesh_series": part["cross_mesh_series"],
            },
            timeline_raw=timeline_block(part["panels"]),
        ),
    ]
    return run_report_mod.write_report(prefix, lines)


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    ap.add_argument("--smoke", action="store_true",
                    help="assert the acceptance invariants; exit 1 on failure")
    ap.add_argument("--timeline", metavar="PREFIX",
                    help="run both cells telemetry-on and write the "
                         "PREFIX.json timeline artifact + the PREFIX.html "
                         "dashboard (scripts/run_report.py), then exit")
    ap.add_argument("--n", type=int, default=SMOKE_N)
    ap.add_argument("--loss", type=float, default=FLAP_LOSS)
    ap.add_argument("--rounds", type=int, default=FLAP_ROUNDS)
    ap.add_argument("--seed", type=int, default=0)
    ap.add_argument("--seeds", type=int, default=SMOKE_SEEDS,
                    help="sims per cell (one vmapped program; metrics "
                         "report median/IQR over the sims)")
    ap.add_argument("--no-census", action="store_true",
                    help="skip the chaos-off kernel-census gate")
    args = ap.parse_args(argv)
    if args.seeds < 1:
        ap.error("--seeds must be >= 1")

    # CPU-only by contract (like perf-smoke): same platform + PRNG +
    # persistent compile cache, so the gate means the same thing on any
    # dev box or CI runner
    import jax

    jax.config.update("jax_platforms", "cpu")
    jax.config.update("jax_default_prng_impl", "unsafe_rbg")
    from go_libp2p_pubsub_tpu.compile_cache import enable_persistent_cache
    from go_libp2p_pubsub_tpu.perf.regress import repo_root

    enable_persistent_cache(os.path.join(repo_root(), ".jax_cache"))

    from go_libp2p_pubsub_tpu.ensemble import stats as estats

    if args.timeline:
        json_path, html_path = run_timeline(
            args.timeline, n=args.n, loss=args.loss, rounds=args.rounds,
            seed=args.seed, seeds=args.seeds,
        )
        print(json.dumps({"timeline_artifact": json_path,
                          "report": html_path}))
        return 0

    failures = []

    flap = run_flap(n=args.n, loss=args.loss, rounds=args.rounds,
                    seed=args.seed, seeds=args.seeds)
    g_med = flap["gossipsub_band"]["q50"]
    ng_med = flap["nogossip_band"]["q50"]
    f_med = flap["floodsub_band"]["q50"]
    iw_med = flap["iwant_band"]["q50"]
    _emit("chaos_flap_delivery_ratio_gossipsub", g_med,
          chaos=flap["chaos"], n_sims=flap["seeds"],
          extras={"n_peers": flap["n"], "rounds": flap["rounds"],
                  "iwant_recovery_share_median": round(iw_med, 4),
                  "iwant_recovery_share_iqr": [
                      round(flap["iwant_band"]["q25"], 4),
                      round(flap["iwant_band"]["q75"], 4)],
                  **_band_extras(
                      flap["gossipsub_band"], flap["gossipsub_ratios"],
                      ci=estats.bootstrap_ci(flap["gossipsub_ratios"]))})
    _emit("chaos_flap_delivery_ratio_gossipsub_nogossip", ng_med,
          chaos=flap["chaos"], n_sims=flap["seeds"],
          extras={"n_peers": flap["n"], "rounds": flap["rounds"],
                  **_band_extras(
                      flap["nogossip_band"], flap["nogossip_ratios"],
                      ci=estats.bootstrap_ci(flap["nogossip_ratios"]))})
    _emit("chaos_flap_delivery_ratio_floodsub", f_med,
          chaos=flap["chaos"], n_sims=flap["seeds"],
          extras={"n_peers": flap["n"], "rounds": flap["rounds"],
                  **_band_extras(
                      flap["floodsub_band"], flap["floodsub_ratios"],
                      ci=estats.bootstrap_ci(flap["floodsub_ratios"]))})
    # the recovery claim, paired per sim on identical fault streams:
    # the lazy-gossip machinery must lift delivery in EVERY stream
    # (round-10 re-baseline: the round-8 single-seed gate asserted
    # gossipsub > floodsub, which the 8-sim band exposes as sampling
    # luck — flooding's 2d-degree redundancy out-delivers a D=3 mesh at
    # this loss; the machinery's causal lift is the robust invariant)
    paired = flap["gossipsub_ratios"] - flap["nogossip_ratios"]
    if float(paired.min()) <= 0.0:
        failures.append(
            "flap: lazy-gossip recovery failed to lift delivery in at "
            "least one sim (per-sim with-minus-without deltas: "
            f"{[round(float(v), 4) for v in paired]})"
        )
    if flap["iwant_band"]["min"] <= 0.0:
        failures.append(
            "flap: IWANT-recovery share hit zero in at least one sim — "
            "the lazy gossip path recovered nothing there "
            f"(per-sim: {[round(float(v), 4) for v in flap['iwant_shares']]})"
        )
    for router, nc in sorted(flap["compiles"].items()):
        if nc not in (-1, 1):  # -1 = cache-size sentinel unavailable
            failures.append(
                f"flap: {router} ensemble ran {nc} compiles across "
                f"{flap['seeds']} sims x {flap['rounds']} rounds "
                "(expected exactly 1 — the one-program contract broke)"
            )

    # the same generator through the phase engine's coalesced stacked
    # wire path (r=4: chaos masks per sub-round, control head masked once)
    flap_phase = run_flap(n=args.n, loss=args.loss, rounds=args.rounds,
                          seed=args.seed, rounds_per_phase=4,
                          seeds=args.seeds, full=False)
    _emit("chaos_flap_delivery_ratio_gossipsub_phase4",
          flap_phase["gossipsub_band"]["q50"], chaos=flap_phase["chaos"],
          n_sims=flap_phase["seeds"],
          extras={"n_peers": flap_phase["n"],
                  "rounds": flap_phase["rounds"],
                  "iwant_recovery_share_median":
                      round(flap_phase["iwant_band"]["q50"], 4),
                  **_band_extras(flap_phase["gossipsub_band"],
                                 flap_phase["gossipsub_ratios"])})
    # the lifted PHASE step (stacked coalesced wire path) is the one
    # lift guards.py's ensemble engine does not cover — pin its
    # one-program contract here too
    for router, nc in sorted(flap_phase["compiles"].items()):
        if nc not in (-1, 1):
            failures.append(
                f"flap-phase: {router} ensemble ran {nc} compiles "
                f"across {flap_phase['seeds']} sims x "
                f"{flap_phase['rounds']} rounds (expected exactly 1)"
            )

    part = run_partition(n=args.n, seed=args.seed + 1, seeds=args.seeds)
    ratio_med = part["ratio_band"]["q50"]
    _emit("chaos_partition_delivery_ratio", ratio_med,
          chaos=part["chaos"], scenario=part["scenario"],
          n_sims=part["seeds"],
          extras={
              "n_peers": part["n"], "rounds": part["rounds"],
              "mesh_reform_latency_median": part["repair_band"].get("q50"),
              "mesh_reform_latency_iqr": [
                  part["repair_band"].get("q25"),
                  part["repair_band"].get("q75")],
              "time_to_recover_median": part["ttr_band"].get("q50"),
              "time_to_recover_iqr": [part["ttr_band"].get("q25"),
                                      part["ttr_band"].get("q75")],
              "cross_mesh_pre_partition": part["cross_mesh_pre_partition"],
              "cross_mesh_at_heal": part["cross_mesh_at_heal"],
              **_band_extras(part["ratio_band"],
                             part["partition_delivery_ratios"]),
          })
    # recovery liveness is per-sim: EVERY sim must repair its mesh and
    # fully deliver partition-era messages (an infinite latency in any
    # stream is a recovery bug, not sampling noise)
    if part["repair_band"]["n_undefined"] > 0:
        failures.append(
            f"partition: cross-group mesh never re-formed after the "
            f"post-heal starvation prune in "
            f"{part['repair_band']['n_undefined']}/{part['seeds']} sims "
            "(infinite mesh-reform latency)"
        )
    if part["ttr_band"]["n_undefined"] > 0:
        failures.append(
            f"partition: delivery of partition-era messages never "
            f"completed after heal in "
            f"{part['ttr_band']['n_undefined']}/{part['seeds']} sims"
        )
    if part["ratio_band"].get("min", 0.0) < 1.0:
        failures.append(
            f"partition: eventual delivery incomplete in at least one "
            f"sim (min ratio {part['ratio_band'].get('min', 0.0):.4f} "
            f"< 1.0; per-sim: "
            f"{[round(float(v), 4) for v in part['partition_delivery_ratios']]})"
        )
    if part["compiles"] not in (-1, 1):
        failures.append(
            f"partition: ensemble ran {part['compiles']} compiles "
            "(expected exactly 1)"
        )

    if not args.no_census:
        census = check_census()
        print(json.dumps({"chaos_off_kernel_census": census}), flush=True)
        if census["seeded"]:
            print(
                "chaos-smoke NOTE: on-image census baseline was seeded "
                "THIS run — the equality leg compared nothing yet "
                "(fresh image/cache; run 2 onward gets the real gate)",
                file=sys.stderr,
            )
        if not census["equal"]:
            failures.append(
                f"chaos-off kernel census {census['total']} != on-image "
                f"baseline {census['on_image']} — the elision-when-off "
                "contract broke (the committed PERF_SMOKE pin "
                f"{census['committed']} is informational)"
            )

    if args.smoke and failures:
        for f in failures:
            print(f"chaos-smoke FAIL: {f}", file=sys.stderr)
        print(json.dumps({"chaos_smoke": "FAIL", "errors": len(failures)}))
        return 1
    print(json.dumps({"chaos_smoke": "PASS" if not failures else "REPORT",
                      "warnings": failures}))
    return 0


if __name__ == "__main__":
    sys.exit(main())
