"""Chaos scenario runner + the ``make chaos-smoke`` gate.

Runs the chaos plane's two canonical degraded-network experiments
(the v1.1 evaluation methodology's shape, arxiv 2007.02754) end to end
and emits one schema-v2 JSON line per measurement, each carrying the
chaos fingerprint (generator kind, loss rate, scenario hash —
perf/artifacts.chaos_fingerprint):

  * **flap** — i.i.d. link-flap loss on the same topology, subscription
    set, publish schedule and fault seed for gossipsub v1.1 AND
    floodsub: delivery ratio under loss per router, plus gossipsub's
    IWANT-recovery share (the lazy-gossip machinery's measured
    contribution — floodsub has no recovery path, so under enough loss
    its single-shot forwarding strands peers that gossipsub's
    IHAVE/IWANT retries reach). A phase-engine (r > 1, coalesced
    stacked wire) cell runs the same generator through the flagship
    cadence.
  * **partition** — a scheduled 2-group partition with P3
    deficit-scoring live: cross-group mesh edges starve and are pruned
    during the window; after heal the mesh re-grafts (measured
    mesh-repair latency) and messages published DURING the partition
    cross over via IWANT service from mcache (measured
    time-to-recover; the publish window sits inside the mcache history
    so recovery is possible at all — the experiment the chaos plane
    exists for).

``--smoke`` additionally asserts the acceptance invariants and that
the CHAOS-OFF compiled HLO kernel census still equals the committed
PERF_SMOKE.json baseline (the elision-when-off contract at the
compiler level; rates are perf-smoke's job, structure is ours), and
exits non-zero on any failure. The gate is CPU-only by contract, like
perf-smoke.
"""

from __future__ import annotations

import argparse
import json
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import numpy as np  # noqa: E402

#: smoke-shape defaults: big enough for a measurable cut and a real
#: recovery tail, small enough that the whole gate is tens of seconds
#: warm (the kernel census dominates, and `make quick` runs perf-smoke
#: first so its compile cache is hot)
SMOKE_N = 128
FLAP_LOSS = 0.6
FLAP_ROUNDS = 80
PARTITION_START = 12
PARTITION_ROUNDS = 24
PARTITION_TAIL = 40  # rounds after heal


def _flap_params():
    """Low-degree v1.1 overlay so the mesh (D=3) leaves non-mesh
    neighbors for IHAVE gossip — the recovery path under test."""
    from go_libp2p_pubsub_tpu.config import GossipSubParams

    return GossipSubParams(D=3, Dlo=2, Dhi=4, Dscore=2, Dout=1,
                           history_length=6, history_gossip=4)


def _score_params():
    """Honest-net live scoring (deficit off), like the bench default."""
    from go_libp2p_pubsub_tpu.perf.sweep import bench_score_params

    return bench_score_params("default", 1)[1]


def _publish_schedule(rng, n, rounds, pub_rounds, width=4):
    po = np.full((rounds, width), -1, np.int32)
    po[:pub_rounds] = rng.integers(0, n, size=(pub_rounds, width))
    pt = np.zeros((rounds, width), np.int32)
    pv = np.ones((rounds, width), bool)
    return po, pt, pv


def run_flap(n=SMOKE_N, loss=FLAP_LOSS, rounds=FLAP_ROUNDS, seed=0,
             rounds_per_phase=1):
    """One flap cell: (gossipsub ratio, iwant share, floodsub ratio,
    chaos cfg). Same topology / schedule / fault stream for both
    routers (the chaos hash keys on the canonical link id and the sim
    key, which both runs share)."""
    import jax.numpy as jnp

    from go_libp2p_pubsub_tpu import graph
    from go_libp2p_pubsub_tpu.chaos import ChaosConfig, delivery_stats, \
        iwant_recovery_share
    from go_libp2p_pubsub_tpu.config import PeerScoreThresholds
    from go_libp2p_pubsub_tpu.models.floodsub import floodsub_step
    from go_libp2p_pubsub_tpu.models.gossipsub import (
        GossipSubConfig,
        GossipSubState,
        make_gossipsub_step,
    )
    from go_libp2p_pubsub_tpu.models.gossipsub_phase import (
        make_gossipsub_phase_step,
    )
    from go_libp2p_pubsub_tpu.state import Net, SimState

    topo = graph.random_connect(n, d=4, seed=seed)
    subs = graph.subscribe_all(n, 1)
    net = Net.build(topo, subs)
    cc = ChaosConfig(loss_rate=loss)
    rng = np.random.default_rng(seed)
    po, pt, pv = _publish_schedule(rng, n, rounds, pub_rounds=3)

    sp = _score_params()
    cfg = GossipSubConfig.build(
        _flap_params(), PeerScoreThresholds(), score_enabled=True,
        chaos=cc,
    )
    r = int(rounds_per_phase)
    gs = GossipSubState.init(net, 64, cfg, score_params=sp, seed=seed)
    if r > 1:
        step = make_gossipsub_phase_step(cfg, net, r, score_params=sp)
        assert rounds % r == 0
        for p in range(rounds // r):
            gs = step(gs, jnp.asarray(po[p * r:(p + 1) * r]),
                      jnp.asarray(pt[p * r:(p + 1) * r]),
                      jnp.asarray(pv[p * r:(p + 1) * r]),
                      do_heartbeat=True)
    else:
        step = make_gossipsub_step(cfg, net, score_params=sp)
        for i in range(rounds):
            gs = step(gs, jnp.asarray(po[i]), jnp.asarray(pt[i]),
                      jnp.asarray(pv[i]))
    g_stats = delivery_stats(
        np.asarray(gs.core.dlv.first_round), np.asarray(gs.core.msgs.birth),
        np.asarray(gs.core.msgs.topic), np.asarray(gs.core.msgs.origin),
        np.asarray(net.subscribed),
    )
    g_events = np.asarray(gs.core.events)

    fs = SimState.init(n, 64, seed=seed, k=net.max_degree)
    for i in range(rounds):
        fs = floodsub_step(net, fs, jnp.asarray(po[i]), jnp.asarray(pt[i]),
                           jnp.asarray(pv[i]), chaos=cc)
    f_stats = delivery_stats(
        np.asarray(fs.dlv.first_round), np.asarray(fs.msgs.birth),
        np.asarray(fs.msgs.topic), np.asarray(fs.msgs.origin),
        np.asarray(net.subscribed),
    )
    return {
        "gossipsub_ratio": g_stats.ratio,
        "iwant_share": iwant_recovery_share(g_events),
        "floodsub_ratio": f_stats.ratio,
        "chaos": cc,
        "n": n,
        "rounds": rounds,
        "rounds_per_phase": r,
    }


def run_partition(n=SMOKE_N, seed=1, start=PARTITION_START,
                  window=PARTITION_ROUNDS, tail=PARTITION_TAIL):
    """Partition/heal cell: scheduled 2-group split with P3 deficit
    scoring live (cross-group mesh edges starve -> pruned during the
    window; short prune backoff so post-heal re-grafting is visible in
    the tail). Publishes land DURING the partition, inside the mcache
    window before heal, so recovery crosses via IWANT."""
    import jax.numpy as jnp

    from go_libp2p_pubsub_tpu import graph
    from go_libp2p_pubsub_tpu.chaos import (
        ChaosConfig,
        cross_group_mesh_count,
        delivery_stats,
        halves,
        mesh_repair_latency,
        time_to_recover,
        two_group_partition,
    )
    from go_libp2p_pubsub_tpu.config import (
        GossipSubParams,
        PeerScoreParams,
        PeerScoreThresholds,
        TopicScoreParams,
    )
    from go_libp2p_pubsub_tpu.models.gossipsub import (
        GossipSubConfig,
        GossipSubState,
        make_gossipsub_step,
    )
    from go_libp2p_pubsub_tpu.state import Net

    topo = graph.random_connect(n, d=4, seed=seed)
    subs = graph.subscribe_all(n, 1)
    net = Net.build(topo, subs)
    heal = start + window
    rounds = heal + tail
    scenario = two_group_partition(n, start=start, rounds=window)
    groups = np.asarray(halves(n))

    # P3 deficit live — and DOMINANT (time-in-mesh off) — so partition
    # starvation actually prunes cross-group mesh edges while steady
    # in-group traffic keeps in-group edges clean; the deficit penalty
    # (threshold² · weight · topic_weight = -4.5) stays above the
    # gossip threshold (-10) so IHAVE toward pruned peers keeps flowing
    # (that's the recovery path). Sticky P3b off and a short backoff so
    # the post-heal re-graft is visible inside the tail; P3 stops
    # counting at prune (mesh-only in the reference too), so pruned
    # cross peers return to ~0 score and are re-graftable after heal.
    tp = TopicScoreParams(
        time_in_mesh_weight=0.0,
        mesh_message_deliveries_weight=-1.0,
        mesh_message_deliveries_threshold=3.0,
        mesh_message_deliveries_activation=5.0,
        mesh_message_deliveries_window=2.0,
        mesh_message_deliveries_decay=0.9,
        mesh_failure_penalty_weight=0.0,
    )
    sp = PeerScoreParams(topics={0: tp}, skip_app_specific=True)
    params = GossipSubParams(D=3, Dlo=2, Dhi=4, Dscore=2, Dout=1,
                             history_length=12, history_gossip=10,
                             prune_backoff=4.0)
    cc = ChaosConfig(scheduled=True)
    cfg = GossipSubConfig.build(params, PeerScoreThresholds(),
                                score_enabled=True, chaos=cc)
    st = GossipSubState.init(net, 64, cfg, score_params=sp, seed=seed)
    step = make_gossipsub_step(cfg, net, score_params=sp)

    rng = np.random.default_rng(seed)
    nbr = np.asarray(net.nbr)
    width = 2
    mesh_series = []
    # steady traffic from BOTH groups from warmup through heal: in-group
    # mesh edges keep delivering (P3-clean) while cross-group edges
    # starve and get pruned; the publishes of the last pre-heal rounds
    # (the born window below) sit inside the mcache history at heal so
    # IWANT recovery across the healed cut is possible at all. Traffic
    # stops at heal — publish volume after the born window stays far
    # below msg_slots, so the measured messages never recycle.
    pub_rounds = range(2, heal - 1)
    for t in range(rounds):
        po = np.full((width,), -1, np.int32)
        if t in pub_rounds:
            po[:] = rng.integers(0, n, size=width)
        deny = scenario.link_deny_at(t, nbr)
        if deny is None:
            deny = np.zeros(nbr.shape, bool)
        st = step(st, jnp.asarray(po), jnp.asarray(np.zeros(width, np.int32)),
                  jnp.asarray(np.ones(width, bool)), jnp.asarray(deny))
        mesh_series.append((t + 1, cross_group_mesh_count(
            np.asarray(st.mesh), nbr, np.asarray(net.nbr_ok), groups)))

    pre = dict(mesh_series)[start] if start >= 1 else None
    during = dict(mesh_series)[heal - 1]
    repair = mesh_repair_latency(
        [(t, c) for t, c in mesh_series],
        heal_tick=heal, min_edges=max(1, during + 1),
    )
    born = (heal - 4, heal - 1)
    stats = delivery_stats(
        np.asarray(st.core.dlv.first_round), np.asarray(st.core.msgs.birth),
        np.asarray(st.core.msgs.topic), np.asarray(st.core.msgs.origin),
        np.asarray(net.subscribed), born_in=born,
    )
    ttr = time_to_recover(
        np.asarray(st.core.dlv.first_round), np.asarray(st.core.msgs.birth),
        np.asarray(st.core.msgs.topic), np.asarray(st.core.msgs.origin),
        np.asarray(net.subscribed), heal_tick=heal, born_in=born,
    )
    return {
        "cross_mesh_pre_partition": pre,
        "cross_mesh_at_heal": during,
        "mesh_repair_latency": repair,
        "partition_delivery_ratio": stats.ratio,
        "time_to_recover": ttr,
        "scenario": scenario,
        "chaos": cc,
        "n": n,
        "rounds": rounds,
        "heal": heal,
    }


def check_census() -> dict:
    """CHAOS-OFF structural gate: the compiled phase-step kernel census
    at the PERF_SMOKE shape must EQUAL the committed baseline — the
    elision-when-off contract, checked at the compiler level."""
    from go_libp2p_pubsub_tpu.perf.profile import compiled_phase_kernel_count
    from go_libp2p_pubsub_tpu.perf.regress import (
        BASELINE_NAME,
        PERF_SMOKE_N,
        PERF_SMOKE_R,
        repo_root,
    )

    base_path = os.path.join(repo_root(), BASELINE_NAME)
    committed = None
    if os.path.exists(base_path):
        with open(base_path) as f:
            committed = (json.load(f).get("hlo_kernels") or {}).get("total")
    census = compiled_phase_kernel_count(
        int(os.environ.get("PERF_SMOKE_N", PERF_SMOKE_N)),
        int(os.environ.get("PERF_SMOKE_R", PERF_SMOKE_R)),
    )
    return {"total": census["total"], "committed": committed,
            "equal": committed is None or census["total"] == committed}


def _emit(metric, value, chaos=None, scenario=None, extras=None):
    from go_libp2p_pubsub_tpu.perf.artifacts import (
        BenchRecord,
        chaos_fingerprint,
        dump_record,
    )

    rec = BenchRecord(
        metric=metric, value=float(value), unit="ratio", vs_baseline=0.0,
        schema=2,
        fingerprint={"chaos": chaos_fingerprint(chaos, scenario)},
        extras=extras or {},
    )
    print(dump_record(rec), flush=True)


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    ap.add_argument("--smoke", action="store_true",
                    help="assert the acceptance invariants; exit 1 on failure")
    ap.add_argument("--n", type=int, default=SMOKE_N)
    ap.add_argument("--loss", type=float, default=FLAP_LOSS)
    ap.add_argument("--rounds", type=int, default=FLAP_ROUNDS)
    ap.add_argument("--seed", type=int, default=0)
    ap.add_argument("--no-census", action="store_true",
                    help="skip the chaos-off kernel-census gate")
    args = ap.parse_args(argv)

    # CPU-only by contract (like perf-smoke): same platform + PRNG +
    # persistent compile cache, so the gate means the same thing on any
    # dev box or CI runner
    import jax

    jax.config.update("jax_platforms", "cpu")
    jax.config.update("jax_default_prng_impl", "unsafe_rbg")
    from go_libp2p_pubsub_tpu.compile_cache import enable_persistent_cache
    from go_libp2p_pubsub_tpu.perf.regress import repo_root

    enable_persistent_cache(os.path.join(repo_root(), ".jax_cache"))

    failures = []

    flap = run_flap(n=args.n, loss=args.loss, rounds=args.rounds,
                    seed=args.seed)
    _emit("chaos_flap_delivery_ratio_gossipsub", flap["gossipsub_ratio"],
          chaos=flap["chaos"],
          extras={"n_peers": flap["n"], "rounds": flap["rounds"],
                  "iwant_recovery_share": round(flap["iwant_share"], 4)})
    _emit("chaos_flap_delivery_ratio_floodsub", flap["floodsub_ratio"],
          chaos=flap["chaos"],
          extras={"n_peers": flap["n"], "rounds": flap["rounds"]})
    if flap["gossipsub_ratio"] <= flap["floodsub_ratio"]:
        failures.append(
            f"flap: gossipsub delivery ratio {flap['gossipsub_ratio']:.4f} "
            f"does not exceed floodsub's {flap['floodsub_ratio']:.4f} at "
            f"loss={args.loss}"
        )
    if flap["iwant_share"] <= 0.0:
        failures.append("flap: IWANT-recovery share is zero — the lazy "
                        "gossip path recovered nothing")

    # the same generator through the phase engine's coalesced stacked
    # wire path (r=4: chaos masks per sub-round, control head masked once)
    flap_phase = run_flap(n=args.n, loss=args.loss, rounds=args.rounds,
                          seed=args.seed, rounds_per_phase=4)
    _emit("chaos_flap_delivery_ratio_gossipsub_phase4",
          flap_phase["gossipsub_ratio"], chaos=flap_phase["chaos"],
          extras={"n_peers": flap_phase["n"], "rounds": flap_phase["rounds"],
                  "iwant_recovery_share":
                      round(flap_phase["iwant_share"], 4)})

    part = run_partition(n=args.n, seed=args.seed + 1)
    _emit("chaos_partition_delivery_ratio", part["partition_delivery_ratio"],
          chaos=part["chaos"], scenario=part["scenario"],
          extras={
              "n_peers": part["n"], "rounds": part["rounds"],
              "mesh_repair_latency": part["mesh_repair_latency"],
              "time_to_recover": part["time_to_recover"],
              "cross_mesh_pre_partition": part["cross_mesh_pre_partition"],
              "cross_mesh_at_heal": part["cross_mesh_at_heal"],
          })
    if part["mesh_repair_latency"] is None:
        failures.append("partition: mesh never repaired after heal "
                        "(infinite mesh-repair latency)")
    if part["time_to_recover"] is None:
        failures.append("partition: delivery of partition-era messages "
                        "never completed after heal")
    if part["partition_delivery_ratio"] < 1.0:
        failures.append(
            f"partition: eventual delivery incomplete "
            f"({part['partition_delivery_ratio']:.4f} < 1.0)"
        )

    if not args.no_census:
        census = check_census()
        print(json.dumps({"chaos_off_kernel_census": census}), flush=True)
        if not census["equal"]:
            failures.append(
                f"chaos-off kernel census {census['total']} != committed "
                f"PERF_SMOKE baseline {census['committed']} — the "
                "elision-when-off contract broke"
            )

    if args.smoke and failures:
        for f in failures:
            print(f"chaos-smoke FAIL: {f}", file=sys.stderr)
        print(json.dumps({"chaos_smoke": "FAIL", "errors": len(failures)}))
        return 1
    print(json.dumps({"chaos_smoke": "PASS" if not failures else "REPORT",
                      "warnings": failures}))
    return 0


if __name__ == "__main__":
    sys.exit(main())
