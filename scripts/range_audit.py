"""``make range-audit`` — the static range/overflow gate (docs/DESIGN.md
§23, analysis/ranges.py).

Two legs, either failing exits non-zero:

  1. **contracts** — the jaxpr-level interval interpreter walks every
     engine×layout build (the cost-audit registry plus the dynamic
     overlay, ``narrow_counters`` and event-counting cells) and the
     hard contracts must hold: every sub-i32 arithmetic site proven
     non-wrapping; every gather/scatter index proven in-bounds or
     NAMED in the sanctioned-drop catalog; the 100k/1M/10M symbolic
     index-width leg carries an explicit PROVEN_I32/NEEDS_I64 verdict
     per flat-index site with no unacknowledged audit-geometry
     refutation; every EV counter's overflow horizon above the floor;
     the source ``.astype`` narrowing sites equal to the declared
     manifest.
  2. **byte-identical reproduction** — the committed
     ``RANGE_AUDIT.json`` must equal this run's audit byte for byte
     (the COST_AUDIT pattern); a mismatch NAMES the diverging keys.
     ``RANGE_UPDATE=1`` rewrites.

Pure tracing + numpy interval arithmetic — no compile, no execution,
PRNG-impl-independent. ~15 s warm. Emits one JSON summary line;
findings to stderr.
"""

from __future__ import annotations

import json
import os
import sys

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)


def main() -> int:
    import jax

    jax.config.update("jax_platforms", "cpu")

    from go_libp2p_pubsub_tpu.analysis import ranges as rg

    failures: list[str] = []
    try:
        payload = rg.build_audit()
    except rg.RangeContractViolation as e:
        print(f"range-audit FAIL: {e}", file=sys.stderr)
        print(json.dumps({"range_audit": "FAIL", "artifact": "contract",
                          "contract": e.contract, "build": e.build,
                          "failures": 1}))
        return 1

    path = rg.audit_path(REPO)
    text = rg.dump_audit(payload)
    update = bool(os.environ.get("RANGE_UPDATE"))
    if update:
        with open(path, "w") as f:
            f.write(text)
        action = "updated"
    elif not os.path.exists(path):
        failures.append(
            f"{rg.AUDIT_NAME} missing — run RANGE_UPDATE=1 "
            "scripts/range_audit.py to record it")
        action = "missing"
    else:
        with open(path) as f:
            committed_text = f.read()
        if committed_text == text:
            action = "verified"
        else:
            action = "stale"
            try:
                diverged = rg.baseline_divergences(
                    json.loads(committed_text), payload)
                detail = ("diverging keys: " + "; ".join(diverged)
                          if diverged else
                          "artifacts parse equal — formatting-only "
                          "drift (re-serialize with RANGE_UPDATE=1)")
            except json.JSONDecodeError:
                detail = "committed artifact is not parseable JSON"
            failures.append(
                f"{rg.AUDIT_NAME} does not reproduce byte-identical — "
                f"the value ranges moved; {detail} "
                "(review, then RANGE_UPDATE=1 to re-record)")

    summary = {
        "range_audit": "FAIL" if failures else "PASS",
        "artifact": action,
        "builds": sorted(payload["builds"]),
        "contracts": sorted(payload["contracts"]),
        "needs_i64": payload["index_width"]["needs_i64"],
        "min_i32_horizon_rounds": payload["contracts"]
            ["overflow_horizon"]["min_i32_horizon_rounds"],
        "failures": len(failures),
    }
    if failures:
        for f in failures:
            print(f"range-audit FAIL: {f}", file=sys.stderr)
    print(json.dumps(summary))
    return 1 if failures else 0


if __name__ == "__main__":
    sys.exit(main())
