"""Device-op profile of the scanned GossipSub step (bench configuration).

Thin CLI over go_libp2p_pubsub_tpu/perf/profile.py — the library-ified
profiler that captures a jax.profiler trace of one scanned segment and
prints the top HLO ops by self time (the attribution the ablation timer
can't give on the tunneled platform, where per-call dispatch RTT swamps
isolated-phase timings).

Builds the EXACT bench workload (perf.sweep.build_bench) so op
attribution maps 1:1 onto what BENCH_r*.json measures; BENCH_CONFIG
selects the variant, BENCH_PHASE_R the cadence (the bench default is
r=8; BENCH_PHASE_R=1 profiles the per-round step).

Usage: python scripts/profile_trace.py [N] [ROUNDS]
"""

from __future__ import annotations

import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

from go_libp2p_pubsub_tpu.perf.profile import main  # noqa: E402

if __name__ == "__main__":
    main()
