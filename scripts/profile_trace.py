"""Device-op profile of the scanned GossipSub step (bench configuration).

Captures a jax.profiler trace of one scanned segment and prints the top HLO
ops by self time — the attribution the ablation timer can't give on the
tunneled platform (per-call dispatch RTT swamps isolated-phase timings).

Builds the EXACT bench workload (bench.build_bench) so op attribution maps
1:1 onto what BENCH_r*.json measures; BENCH_CONFIG selects the variant.

Usage: python scripts/profile_trace.py [N] [ROUNDS]
"""

from __future__ import annotations

import glob
import os
import sys

import numpy as np


def main():
    import jax
    import jax.numpy as jnp

    sys.path.insert(0, ".")
    sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))
    from bench import build_bench

    config = os.environ.get("BENCH_CONFIG", "default")
    # BENCH_PHASE_R > 1 profiles the phase engine at that cadence (the
    # bench default is r=8); BENCH_PHASE_R=1 profiles the per-round step
    r = int(os.environ.get("BENCH_PHASE_R", 1))
    n = int(sys.argv[1]) if len(sys.argv) > 1 else 100_000
    rounds = int(sys.argv[2]) if len(sys.argv) > 2 else 50
    rounds = max(rounds - rounds % max(r, 1), r)  # never truncate to an empty run
    st, step, n_topics, honest = build_bench(
        n, 64, config=config, heartbeat_every=r if r > 1 else 1,
        rounds_per_phase=r,
    )

    rng = np.random.default_rng(0)
    if honest is not None:
        po = honest[rng.integers(0, len(honest), size=(rounds, 4))].astype(np.int32)
    else:
        po = rng.integers(0, n, size=(rounds, 4)).astype(np.int32)
    po = jnp.asarray(po)
    pt = jnp.asarray(rng.integers(0, n_topics, size=(rounds, 4)).astype(np.int32))
    pv = jnp.asarray(np.ones((rounds, 4), bool))

    if r > 1:
        from go_libp2p_pubsub_tpu.driver import make_scan

        unroll = int(os.environ.get("BENCH_UNROLL", 2 * r))
        scan = make_scan(step, heartbeat_every=r, rounds_per_phase=r,
                         static_heartbeat=True, unroll=max(1, unroll // r))

        def run_seg(s):
            return scan(s, po, pt, pv)
        run = jax.jit(run_seg, donate_argnums=0)
    else:
        def run_seg(s):
            def body(carry, xs):
                return step(carry, *xs), None
            s, _ = jax.lax.scan(body, s, (po, pt, pv))
            return s

        run = jax.jit(run_seg, donate_argnums=0)
    st = run(st)
    jax.block_until_ready(st)

    logdir = "/tmp/pubsub_prof"
    os.system(f"rm -rf {logdir}")
    with jax.profiler.trace(logdir):
        st = run(st)
        jax.block_until_ready(st)

    # ---- summarize: top ops by self time -------------------------------
    # (xprof's converter works where tensorboard_plugin_profile 2.13 fails)
    paths = glob.glob(f"{logdir}/**/*.xplane.pb", recursive=True)
    print("xplane:", paths)
    from xprof.convert import raw_to_tool_data

    data, _ = raw_to_tool_data.xspace_to_tool_data(paths, "hlo_stats", {})
    import json

    obj = data if isinstance(data, dict) else json.loads(data)
    out_path = "/tmp/pubsub_prof/hlo_stats.json"
    with open(out_path, "w") as f:
        json.dump(obj, f, default=lambda o: o.decode() if isinstance(o, bytes) else str(o))
    print("wrote", out_path)
    rows = [r["c"] if isinstance(r, dict) else r for r in obj["rows"]]

    def val(r, i):
        v = r[i]
        return v.get("v") if isinstance(v, dict) else v

    items, total = [], 0.0
    from collections import defaultdict

    bycat = defaultdict(float)
    for r in rows:
        selft = float(val(r, 9) or 0)
        total += selft
        bycat[val(r, 2)] += selft
        items.append((selft, val(r, 3), (val(r, 4) or ""), (val(r, 25) or "")))
    items.sort(reverse=True)
    print(f"total device self time: {total/1e3:.1f} ms; per round: {total/rounds:.0f} us")
    print("\nby category:")
    for k, v in sorted(bycat.items(), key=lambda x: -x[1]):
        print(f"  {v/rounds:8.1f} us/rd {100*v/total:5.1f}%  {k}")
    print("\ntop 30 ops:")
    for selft, name, text, src in items[:30]:
        import re

        s = re.sub(r"<[^>]+>", "", src)
        print(f"  {selft/rounds:7.1f} us/rd {name:<30} {s.strip()[:80]}")
        print(f"      {text[:140]}")


if __name__ == "__main__":
    main()
