"""Device-op profile of the scanned GossipSub step (bench configuration).

Captures a jax.profiler trace of one scanned segment and prints the top HLO
ops by self time — the attribution the ablation timer can't give on the
tunneled platform (per-call dispatch RTT swamps isolated-phase timings).

Usage: python scripts/profile_trace.py [N] [ROUNDS]
"""

from __future__ import annotations

import dataclasses
import glob
import os
import sys

import numpy as np


def build(n_peers: int, msg_slots: int):
    import jax
    import jax.numpy as jnp

    sys.path.insert(0, ".")
    from go_libp2p_pubsub_tpu import graph
    from go_libp2p_pubsub_tpu.config import (
        GossipSubParams,
        PeerScoreParams,
        PeerScoreThresholds,
        TopicScoreParams,
    )
    from go_libp2p_pubsub_tpu.models.gossipsub import (
        GossipSubConfig,
        GossipSubState,
        make_gossipsub_step,
    )
    from go_libp2p_pubsub_tpu.state import Net

    topo = graph.ring_lattice(n_peers, d=8)
    subs = graph.subscribe_all(n_peers, 1)
    net = Net.build(topo, subs)
    params = dataclasses.replace(GossipSubParams(), flood_publish=False)
    tp = TopicScoreParams(
        mesh_message_deliveries_weight=0.0, mesh_failure_penalty_weight=0.0
    )
    sp = PeerScoreParams(
        topics={0: tp},
        skip_app_specific=True,
        behaviour_penalty_weight=-1.0,
        behaviour_penalty_threshold=1.0,
        behaviour_penalty_decay=0.9,
    )
    cfg = GossipSubConfig.build(params, PeerScoreThresholds(), score_enabled=True)
    st = GossipSubState.init(net, msg_slots, cfg, score_params=sp, seed=0)
    step = make_gossipsub_step(cfg, net, score_params=sp)
    return st, step


def main():
    import jax
    import jax.numpy as jnp

    n = int(sys.argv[1]) if len(sys.argv) > 1 else 100_000
    rounds = int(sys.argv[2]) if len(sys.argv) > 2 else 50
    st, step = build(n, 64)

    rng = np.random.default_rng(0)
    po = jnp.asarray(rng.integers(0, n, size=(rounds, 4)).astype(np.int32))
    pt = jnp.asarray(np.zeros((rounds, 4), np.int32))
    pv = jnp.asarray(np.ones((rounds, 4), bool))

    def run_seg(s):
        def body(carry, xs):
            return step(carry, *xs), None
        s, _ = jax.lax.scan(body, s, (po, pt, pv))
        return s

    run = jax.jit(run_seg, donate_argnums=0)
    st = run(st)
    jax.block_until_ready(st)

    logdir = "/tmp/pubsub_prof"
    os.system(f"rm -rf {logdir}")
    with jax.profiler.trace(logdir):
        st = run(st)
        jax.block_until_ready(st)

    # ---- summarize: top ops by self time -------------------------------
    paths = glob.glob(f"{logdir}/**/*.xplane.pb", recursive=True)
    print("xplane:", paths)
    from tensorboard_plugin_profile.convert import raw_to_tool_data

    data, _ = raw_to_tool_data.xspace_to_tool_data(paths, "hlo_stats", {})
    import json

    out_path = "/tmp/pubsub_prof/hlo_stats.json"
    with open(out_path, "w") as f:
        f.write(data if isinstance(data, str) else str(data))
    print("wrote", out_path)


if __name__ == "__main__":
    main()
