"""Ablation profiler: time the GossipSub step's sub-computations separately
on the current default JAX platform (the real chip under the driver; CPU
with JAX_PLATFORMS=cpu elsewhere).

Each phase is jitted on its own so the wall split is attributable; numbers
won't add exactly to the fused step (XLA fuses across phases there) but
they rank the hot spots, which is what perf work needs.

Usage: python scripts/profile_step.py [N] [ROUNDS]
"""

from __future__ import annotations

import dataclasses
import sys
import time

import numpy as np


def main():
    import jax
    import jax.numpy as jnp

    sys.path.insert(0, ".")
    from go_libp2p_pubsub_tpu import graph
    from go_libp2p_pubsub_tpu.config import (
        GossipSubParams,
        PeerScoreParams,
        PeerScoreThresholds,
        TopicScoreParams,
    )
    from go_libp2p_pubsub_tpu.models import common
    from go_libp2p_pubsub_tpu.models.gossipsub import (
        GossipSubConfig,
        GossipSubState,
        TopicParamsArrays,
        gather_nbr_subscribed,
        gossip_edge_mask,
        heartbeat,
        joined_msg_words,
        make_gossipsub_step,
        no_publish,
        slot_topic_words,
    )
    from go_libp2p_pubsub_tpu.ops import bitset, edges
    from go_libp2p_pubsub_tpu.score.engine import compute_scores, refresh_scores
    from go_libp2p_pubsub_tpu.state import Net

    n = int(sys.argv[1]) if len(sys.argv) > 1 else 100_000
    rounds = int(sys.argv[2]) if len(sys.argv) > 2 else 30
    m = 64

    topo = graph.ring_lattice(n, d=8)
    subs = graph.subscribe_all(n, 1)
    net = Net.build(topo, subs)
    params = dataclasses.replace(GossipSubParams(), flood_publish=False)
    tp0 = TopicScoreParams(
        mesh_message_deliveries_weight=0.0, mesh_failure_penalty_weight=0.0
    )
    sp = PeerScoreParams(
        topics={0: tp0},
        skip_app_specific=True,
        behaviour_penalty_weight=-1.0,
        behaviour_penalty_threshold=1.0,
        behaviour_penalty_decay=0.9,
    )
    cfg = GossipSubConfig.build(params, PeerScoreThresholds(), score_enabled=True)
    st = GossipSubState.init(net, m, cfg, score_params=sp, seed=0)
    step = make_gossipsub_step(cfg, net, score_params=sp)

    tpa = TopicParamsArrays.build(sp, net.n_topics, 1.0)
    tp = tpa.gather(net.my_topics)
    nbr_sub = gather_nbr_subscribed(net)
    subscribed_words_t = bitset.pack(net.subscribed)
    nbr_sub_words = jnp.where(
        net.nbr_ok[:, :, None],
        subscribed_words_t[jnp.clip(net.nbr, 0)],
        jnp.uint32(0),
    )

    po, pt, pv = no_publish(4)
    po = po.at[0].set(0)
    pt = pt.at[0].set(0)
    pv = pv.at[0].set(True)

    def timeit(name, fn, *args, iters=rounds):
        out = fn(*args)
        jax.block_until_ready(out)
        t0 = time.perf_counter()
        for _ in range(iters):
            out = fn(*args)
        jax.block_until_ready(out)
        dt = (time.perf_counter() - t0) / iters
        print(f"{name:34s} {dt * 1e3:8.3f} ms")
        return out

    # warm state: run a few full steps (not donated here)
    step_nodonate = jax.jit(lambda s, a, b, c: step(s, a, b, c))
    for _ in range(3):
        st = step_nodonate(st, po, pt, pv)
    jax.block_until_ready(st)

    print(f"platform={jax.devices()[0].platform} n={n} m={m} rounds={rounds}")
    timeit("full step", step_nodonate, st, po, pt, pv)

    # --- phases --------------------------------------------------------
    @jax.jit
    def phase_wire(s):
        parts = [
            edges.topic_pack(s.graft_out, net.my_topics, net.n_topics),
            edges.topic_pack(s.prune_out, net.my_topics, net.n_topics),
            s.ihave_out,
            jax.lax.bitcast_convert_type(s.scores, jnp.uint32)[..., None],
        ]
        wire = net.edge_gather(jnp.concatenate(parts, axis=-1))
        return jnp.where(net.nbr_ok[:, :, None], wire, jnp.uint32(0))

    timeit("wire exchange (merged gather)", phase_wire, st)

    @jax.jit
    def phase_delivery(s):
        core = s.core
        joined_words = joined_msg_words(net, core.msgs)
        slotw = slot_topic_words(net, core.msgs.topic)
        flood_edges = jnp.zeros_like(net.nbr_ok)
        emask = gossip_edge_mask(
            cfg, net, s, joined_words, net.nbr_ok, slotw, core.msgs.topic,
            flood_edges, s.scores,
        )
        return common.delivery_round(net, core.msgs, core.dlv, emask, core.tick)

    timeit("edge mask + delivery round", phase_delivery, st)

    @jax.jit
    def phase_scores(s):
        sc = refresh_scores(s.score, s.mesh, s.core.tick, tp, sp)
        return compute_scores(sc, s.mesh, tp, sp, s.p6, s.app_score, net)

    timeit("refresh+compute scores", phase_scores, st)

    @jax.jit
    def phase_heartbeat(s):
        return heartbeat(cfg, net, s, tp, sp, nbr_sub, None, nbr_sub_words,
                         present_ok=net.nbr_ok)

    timeit("heartbeat (full)", phase_heartbeat, st)


if __name__ == "__main__":
    main()
