"""``make hlo-audit`` — the compiled-program contract gate
(docs/DESIGN.md §16, analysis/hloaudit.py).

Audits the LOWERED StableHLO of every engine×layout build (the guards
harness shapes, so the compile cache is shared with ``make analyze``;
lowering is trace-only — no compile):

  per_round / phase / floodsub / randomsub / csr / phase_csr / lifted
      host-transfer-free program text, donation-marker coverage over
      the program parameters, per-category op census, RNG
      presence/absence contracts (floodsub must draw NOTHING).
  dense-vs-csr tally
      the trace-time halo-gather tally (ops/edges seams) must be EQUAL
      between the dense and CSR builds of the same engine — the layout
      must never change the halo budget (docs/DESIGN.md §15).
  ragged gather bound
      on a ragged random topology the seams lower to real gather ops,
      so the program's gather-family census must be >= the tally (no
      cross-peer movement outside the tally seams).
  window scan
      a make_window program carries its dispatch loop as
      stablehlo.while (>= 1); the plain per-round step carries none.
  recompile attribution
      the attributor (hloaudit.attribute_recompile) must name EXACTLY
      the changed static for a threshold-only config diff — and must
      report an EMPTY diff for the same pair under the round-16 lifted
      surface (the thresholds ride the traced plane).

CPU + the gate PRNG (unsafe_rbg — RNG contracts count
rng_bit_generator ops). Emits one JSON summary line; findings to
stderr.
"""

from __future__ import annotations

import json
import os
import sys

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)

#: donation coverage floors per build (fraction of program parameters
#: carrying donation markers; the state tree dominates the parameter
#: list at these shapes — publish args and the lifted plane are the
#: only non-donated inputs)
DONATION_FLOOR = 0.5


def _ragged_harness():
    """A tiny RAGGED gossipsub build (random topology — no banded-roll
    lowering, so every halo seam is a real gather op) for the
    gather-bound leg."""
    import jax

    from go_libp2p_pubsub_tpu import graph
    from go_libp2p_pubsub_tpu.analysis.guards import EngineHarness, _pub_args
    from go_libp2p_pubsub_tpu.config import (
        GossipSubParams,
        PeerScoreThresholds,
    )
    from go_libp2p_pubsub_tpu.models.gossipsub import (
        GossipSubConfig,
        GossipSubState,
        make_gossipsub_step,
    )
    from go_libp2p_pubsub_tpu.perf.sweep import bench_score_params
    from go_libp2p_pubsub_tpu.state import Net

    n = 96
    net = Net.build(graph.random_connect(n, d=6, seed=3),
                    graph.subscribe_all(n, 1))
    assert net.band_off is None, "random_connect should be ragged"
    _tp, sp = bench_score_params("default", 1)
    cfg = GossipSubConfig.build(GossipSubParams(), PeerScoreThresholds(),
                                score_enabled=True)
    st = GossipSubState.init(net, 64, cfg, score_params=sp)
    step = make_gossipsub_step(cfg, net, score_params=sp)
    del jax
    return EngineHarness("ragged", step, st,
                         lambda i: _pub_args((4,), i), {})


def _csr_sharded_harness():
    """The round-18 sharded-CSR row: the guard-shape gossipsub step on
    an ``edge_shards=4`` csr build — the row-owner-aligned BLOCK-PADDED
    edge layout the GSPMD edge sharding partitions (ops/csr.
    pad_csr_blocks). Participates in the equal-tally leg below: the
    sharding layout must not change the halo budget either (the GSPMD
    collective contract itself is pinned on the 8-virtual-device
    harness — scripts/mesh2d_dryrun.py, MULTICHIP_r07.json)."""
    from go_libp2p_pubsub_tpu import graph
    from go_libp2p_pubsub_tpu.analysis.guards import (
        GUARD_M,
        GUARD_N,
        EngineHarness,
        _pub_args,
    )
    from go_libp2p_pubsub_tpu.config import (
        GossipSubParams,
        PeerScoreThresholds,
    )
    from go_libp2p_pubsub_tpu.models.gossipsub import (
        GossipSubConfig,
        GossipSubState,
        make_gossipsub_step,
    )
    from go_libp2p_pubsub_tpu.perf.sweep import bench_score_params
    from go_libp2p_pubsub_tpu.state import Net
    import dataclasses as _dc

    net = Net.build(graph.ring_lattice(GUARD_N, d=8),
                    graph.subscribe_all(GUARD_N, 1),
                    edge_layout="csr", edge_shards=4)
    _tp, sp = bench_score_params("default", 1)
    # mirror the bench config exactly (build_bench: flood_publish off,
    # tracer detached, no fanout slots) so the tally equality against
    # the dense/csr bench rows compares LAYOUTS, not configs
    cfg = GossipSubConfig.build(
        _dc.replace(GossipSubParams(), flood_publish=False),
        PeerScoreThresholds(), score_enabled=True, edge_layout="csr")
    cfg = _dc.replace(cfg, count_events=False, fanout_slots=0)
    st = GossipSubState.init(net, GUARD_M, cfg, score_params=sp)
    step = make_gossipsub_step(cfg, net, score_params=sp)
    return EngineHarness("csr_sharded", step, st,
                         lambda i: _pub_args((4,), i), {})


def _window_text():
    """StableHLO of a small make_window program (the one-dispatch scan
    contract)."""
    import jax.numpy as jnp

    from go_libp2p_pubsub_tpu.analysis import guards
    from go_libp2p_pubsub_tpu.driver import make_window

    h = guards.build_engine("floodsub")
    net = h.static_kwargs["net"]

    def stepped(st, po, pt, pv):
        from go_libp2p_pubsub_tpu.models.floodsub import floodsub_step

        return floodsub_step(net, st, po, pt, pv)

    win = make_window(stepped)
    d = 4
    po = jnp.full((d, 4), -1, jnp.int32)
    xs = (po, jnp.zeros((d, 4), jnp.int32), jnp.zeros((d, 4), bool))
    return win.lower(h.state, xs).as_text()


def main() -> int:
    import jax

    jax.config.update("jax_platforms", "cpu")
    jax.config.update("jax_default_prng_impl", "unsafe_rbg")

    from go_libp2p_pubsub_tpu.compile_cache import enable_persistent_cache

    enable_persistent_cache(os.path.join(REPO, ".jax_cache"))

    import dataclasses as dc

    from go_libp2p_pubsub_tpu.analysis import guards, hloaudit as ha
    from go_libp2p_pubsub_tpu.config import (
        GossipSubParams,
        PeerScoreThresholds,
    )
    from go_libp2p_pubsub_tpu.models.gossipsub import GossipSubConfig

    failures: list[str] = []
    report: dict = {}

    cells = [
        ("gossipsub", lambda: guards.build_engine("gossipsub"), True),
        ("gossipsub_phase",
         lambda: guards.build_engine("gossipsub_phase"), True),
        ("floodsub", lambda: guards.build_engine("floodsub"), False),
        ("randomsub", lambda: guards.build_engine("randomsub"), True),
        ("csr", guards.build_csr_harness, True),
        ("csr_sharded", _csr_sharded_harness, True),
        ("phase_csr", guards.build_phase_csr_harness, True),
        ("lifted", guards.build_lifted_harness, True),
    ]
    tallies: dict = {}
    for name, build, expect_rng in cells:
        try:
            h = build()
            # tally_gathers traces the raw step body (cache-immune);
            # the zero-check below is the belt-and-braces contract
            tallies[name] = ha.tally_gathers(h)
            text = ha.lowered_text(h)
            if tallies[name]["total"] == 0:
                raise ha.HloContractViolation(
                    name, "census",
                    "trace-time halo tally is ZERO — either the engine "
                    "stopped routing through the ops/edges seams or the "
                    "tally ran against a cached trace",
                )
            ha.check_no_host_transfer(name, text)
            ratio = ha.check_donation_coverage(name, text, DONATION_FLOOR)
            ha.check_rng(name, text, expect_rng)
            census = ha.hlo_census(text)
            report[name] = {
                "donation_coverage": round(ratio, 3),
                "halo_tally": tallies[name],
                "census": {k: v for k, v in sorted(census.items())
                           if k.startswith("cat:") or k == "while"},
            }
        except ha.HloContractViolation as e:
            failures.append(str(e))
        except Exception as e:  # noqa: BLE001 — any crash is a finding
            failures.append(f"[{name}] audit crashed: "
                            f"{type(e).__name__}: {str(e)[:300]}")

    # dense vs CSR: the layout must not change the halo budget (the
    # csr-sharded row holds the same equality — round 18)
    for dense, sparse in (("gossipsub", "csr"),
                          ("gossipsub", "csr_sharded"),
                          ("gossipsub_phase", "phase_csr")):
        td, ts = tallies.get(dense), tallies.get(sparse)
        if td is not None and ts is not None and td["total"] != ts["total"]:
            failures.append(
                f"[{sparse}] census: halo-gather tally {ts['total']} != "
                f"dense build's {td['total']} — the edge layout changed "
                "the halo budget (docs/DESIGN.md §15 contract)"
            )
    # lifted vs static: the score lift must not change the halo budget
    tl, tg = tallies.get("lifted"), tallies.get("gossipsub")
    if tl is not None and tg is not None and tl["total"] != tg["total"]:
        failures.append(
            f"[lifted] census: halo-gather tally {tl['total']} != static "
            f"build's {tg['total']} — the traced plane added cross-peer "
            "movement"
        )

    # ragged bound: HLO gather-family >= trace tally
    try:
        h = _ragged_harness()
        tally = ha.tally_gathers(h)
        text = ha.lowered_text(h)
        if tally["total"] == 0:
            raise ha.HloContractViolation(
                "ragged", "census", "trace-time halo tally is ZERO")
        ha.check_gather_bound("ragged", text, tally["total"])
        report["ragged"] = {
            "halo_tally": tally,
            "gather_family": ha.hlo_census(text).get("cat:gather_family", 0),
        }
    except ha.HloContractViolation as e:
        failures.append(str(e))
    except Exception as e:  # noqa: BLE001
        failures.append(f"[ragged] audit crashed: "
                        f"{type(e).__name__}: {str(e)[:300]}")

    # window: the dispatch loop is a single top-level scan program
    try:
        wtext = _window_text()
        ha.check_no_host_transfer("window", wtext)
        n_while = ha.check_while_count("window", wtext, expect_min=1)
        report["window"] = {"while": n_while}
    except ha.HloContractViolation as e:
        failures.append(str(e))
    except Exception as e:  # noqa: BLE001
        failures.append(f"[window] audit crashed: "
                        f"{type(e).__name__}: {str(e)[:300]}")

    # recompile-cause attribution: a threshold diff is named under the
    # static surface and vanishes under the lifted one
    from go_libp2p_pubsub_tpu.perf.sweep import bench_score_params

    cfg_a = GossipSubConfig.build(GossipSubParams(), PeerScoreThresholds(),
                                  score_enabled=True)
    cfg_b = dc.replace(cfg_a, gossip_threshold=-5.0)
    _tp, sp_a = bench_score_params("default", 1)
    sp_b = dc.replace(sp_a, topic_score_cap=50.0)
    named = ha.attribute_recompile(
        ha.static_fingerprint(cfg_a, score_params=sp_a),
        ha.static_fingerprint(cfg_b, score_params=sp_b))
    keys = sorted(n.split(":")[0] for n in named)
    if keys != ["gossip_threshold", "score_params.topic_score_cap"]:
        failures.append(
            "[attributor] threshold+weight diff should name exactly "
            f"the two changed statics, got {named}")
    lifted_diff = ha.attribute_recompile(
        ha.static_fingerprint(cfg_a, score_params=sp_a, lifted=True),
        ha.static_fingerprint(cfg_b, score_params=sp_b, lifted=True))
    if lifted_diff:
        failures.append(
            "[attributor] the lifted static surface still differs on a "
            f"plane-carried field: {lifted_diff}")
    report["attributor"] = {"static_diff": named, "lifted_diff": lifted_diff}

    summary = {"hlo_audit": "FAIL" if failures else "PASS",
               "cells": sorted(report), "failures": len(failures)}
    if failures:
        for f in failures:
            print(f"hlo-audit FAIL: {f}", file=sys.stderr)
    print(json.dumps(summary))
    if os.environ.get("HLO_AUDIT_VERBOSE"):
        print(json.dumps(report, indent=1, sort_keys=True), file=sys.stderr)
    return 1 if failures else 0


if __name__ == "__main__":
    sys.exit(main())
