#!/usr/bin/env python
"""Fused-plane gate (``make fuse-smoke``; docs/DESIGN.md §21).

Builds the bench gossipsub per-round step on the flat-[E] CSR plane
twice — ``fused=False`` (the round-14 data plane, unchanged) and
``fused=True`` (the round-21 fused delivery/selection composites) —
and asserts the fusion contract end to end:

  1. **fused-off census unchanged** — the compiled-HLO kernel census
     of the fused-off build must EQUAL the measured-on-this-image
     baseline (``.jax_cache/CENSUS_ONIMAGE.json``, variant
     ``csr_fused_off``): flipping the flag off must recover the
     pre-round-21 compiled program exactly. Strict equality, not a
     tolerance — same image, same shape, same PRNG impl.
  2. **fused-on census delta pinned** — on XLA:CPU the fused build
     trades kernel COUNT for kernel WIDTH: the sort-composite rank
     adds a constant handful of thunks (sorts don't fuse) while the
     capacity-bounded scan shrinks the E-length fusion bodies. The
     gate pins that trade: the fused-minus-unfused thunk delta must
     not exceed the committed ``census_delta_thunks`` (FUSE_SMOKE.json)
     — growth means the fused composites stopped fusing.
  3. **the drop** — the fused build's actual win is HBM traffic, and
     the static cost audit prices it: the committed COST_AUDIT.json
     fusion contract's csr ratio_at_hi must stay under
     FUSED_HBM_RATIO_CEILING (0.8 — i.e. a >= 20% hbm_bytes/round
     drop). fuse-smoke re-reads the committed artifact so the drop is
     pinned HERE too, next to the census numbers it explains.
  4. **one compile** — the fused run's full ROUNDS-round window
     compiles the step exactly once (cache-size sentinel); warm
     fused-vs-unfused delivery-rounds/s are recorded (informational —
     CPU timing of a TPU-shaped trade).

``FUSE_SMOKE_UPDATE=1`` rewrites FUSE_SMOKE.json and reseeds the
on-image census entries (the PERF_SMOKE / TELEMETRY_SMOKE workflow).
CPU-only by contract like the other smoke gates.
"""

from __future__ import annotations

import argparse
import json
import os
import sys
import time

_here = os.path.dirname(os.path.abspath(__file__))
sys.path.insert(0, os.path.dirname(_here))

import numpy as np  # noqa: E402

BASELINE_NAME = "FUSE_SMOKE.json"
SMOKE_ROUNDS = 32
DEFAULT_N = 512
#: the committed csr fused/unfused hbm ratio must stay under this —
#: mirrored from analysis/costmodel.FUSED_HBM_RATIO_CEILING so a
#: stale-artifact edit can't silently relax the drop
HBM_RATIO_CEILING = 0.8
TIMING_REPS = 3


def _fresh(state):
    """Donatable copy of a state tree (the jitted step donates its
    state argument; key leaves need the key_data round-trip)."""
    import jax
    import jax.numpy as jnp

    from go_libp2p_pubsub_tpu.checkpoint import is_prng_key

    def cp(x):
        if is_prng_key(x):
            return jax.random.wrap_key_data(
                jnp.copy(jax.random.key_data(x)), impl=jax.random.key_impl(x))
        return jnp.copy(x)

    return jax.tree_util.tree_map(cp, state)


def _pub_args(n: int, rounds: int):
    """One valid publish per round from a rotating origin."""
    import jax.numpy as jnp

    from go_libp2p_pubsub_tpu.perf.sweep import PUBS_PER_ROUND

    out = []
    for i in range(rounds):
        po = np.full((PUBS_PER_ROUND,), -1, np.int32)
        po[0] = i % n
        out.append((jnp.asarray(po),
                    jnp.asarray(np.zeros((PUBS_PER_ROUND,), np.int32)),
                    jnp.asarray(np.ones((PUBS_PER_ROUND,), bool))))
    return out


def _build(n: int, fused: bool):
    """(state, step) — the bench gossipsub per-round step on the CSR
    edge plane; only the ``fused`` flag differs between the builds."""
    from go_libp2p_pubsub_tpu.perf.sweep import build_bench

    st, step, _, _ = build_bench(n, 64, heartbeat_every=1,
                                 rounds_per_phase=1,
                                 edge_layout="csr", fused=fused)
    return st, step


def _census(step, state, n: int) -> dict:
    """Compiled-HLO thunk census of the per-round step (r=1), shaped
    for perf.profile.on_image_census_baseline's key."""
    import jax.numpy as jnp

    from go_libp2p_pubsub_tpu.perf.profile import (hlo_kernel_census,
                                                   require_gate_prng)
    from go_libp2p_pubsub_tpu.perf.sweep import PUBS_PER_ROUND

    require_gate_prng()
    po = jnp.asarray(np.full((PUBS_PER_ROUND,), -1, np.int32))
    pt = jnp.asarray(np.zeros((PUBS_PER_ROUND,), np.int32))
    pv = jnp.asarray(np.ones((PUBS_PER_ROUND,), bool))
    census = hlo_kernel_census(
        step.lower(state, po, pt, pv).compile().as_text())
    census["n_peers"] = int(n)
    census["rounds_per_phase"] = 1
    return census


def _timed_window(step, state, args) -> float:
    import jax

    t0 = time.perf_counter()
    for a in args:
        state = step(state, *a)
    jax.block_until_ready(state)
    return time.perf_counter() - t0


def _committed_hbm_ratio(root: str):
    """(ratio_at_hi, failure | None) from the committed COST_AUDIT.json
    fusion contract — the drop this gate pins."""
    from go_libp2p_pubsub_tpu.analysis import costmodel as cm

    path = cm.audit_path(root)
    if not os.path.exists(path):
        return None, (f"{cm.AUDIT_NAME} missing — the fused hbm drop is "
                      "unpinned (run COST_UPDATE=1 scripts/cost_audit.py)")
    try:
        with open(path) as f:
            fusion = json.load(f)["contracts"]["fusion"]["csr"]
        ratio = float(fusion["ratio_at_hi"])
    except (json.JSONDecodeError, KeyError, TypeError, ValueError):
        return None, (f"{cm.AUDIT_NAME} carries no parseable fusion "
                      "contract for the csr build")
    if ratio > HBM_RATIO_CEILING:
        return ratio, (
            f"fused hbm drop lost: committed csr fused/unfused "
            f"hbm_bytes ratio {ratio:.4f} is over the {HBM_RATIO_CEILING} "
            "ceiling — the fused build no longer cuts >= 20% of traffic")
    return ratio, None


def run_gate(n: int, rounds: int) -> dict:
    import jax

    from go_libp2p_pubsub_tpu.ensemble.runner import _cache_size
    from go_libp2p_pubsub_tpu.perf.profile import on_image_census_baseline

    failures: list[str] = []
    args = _pub_args(n, rounds)
    upd = bool(os.environ.get("FUSE_SMOKE_UPDATE"))

    st_off, step_off = _build(n, fused=False)
    st_on, step_on = _build(n, fused=True)

    # --- censuses + on-image comparison ------------------------------
    census_off = _census(step_off, st_off, n)
    census_on = _census(step_on, st_on, n)
    oni_off = on_image_census_baseline(census_off, variant="csr_fused_off",
                                       update=upd)
    oni_on = on_image_census_baseline(census_on, variant="csr_fused_on",
                                      update=upd)
    seeded = oni_off["seeded"] or oni_on["seeded"]
    if not seeded:
        # fused-off must recover the pre-fusion compiled program EXACTLY
        if census_off["total"] != oni_off["total"]:
            failures.append(
                f"fused-off census changed: {census_off['total']} != "
                f"on-image baseline {oni_off['total']} — the fused=False "
                "build must compile to the unchanged CSR program")
        if census_on["total"] != oni_on["total"]:
            failures.append(
                f"fused-on census moved: {census_on['total']} != "
                f"on-image baseline {oni_on['total']}")

    # --- guarded fused run: one compile over the whole window --------
    before = _cache_size(step_on)
    st_fin = _fresh(st_on)
    with jax.transfer_guard("disallow"):
        for a in args:
            st_fin = step_on(st_fin, *a)
        jax.block_until_ready(st_fin)
    after = _cache_size(step_on)
    compiles = -1 if before is None or after is None else after - before
    if compiles not in (-1, 1):
        failures.append(
            f"one-compile: the fused step compiled {compiles} times "
            f"across the {rounds}-round run (expected exactly 1)")

    # --- warm fused-vs-unfused delivery rounds/s ---------------------
    _timed_window(step_off, _fresh(st_off), args)  # warm the off build
    t_on = min(_timed_window(step_on, _fresh(st_on), args)
               for _ in range(TIMING_REPS))
    t_off = min(_timed_window(step_off, _fresh(st_off), args)
                for _ in range(TIMING_REPS))

    # --- the pinned drop: committed fusion-contract hbm ratio --------
    from go_libp2p_pubsub_tpu.perf.regress import repo_root

    ratio, ratio_failure = _committed_hbm_ratio(repo_root())
    if ratio_failure:
        failures.append(ratio_failure)

    return {
        "failures": failures,
        "compiles": compiles,
        "n_peers": n,
        "rounds": rounds,
        "census_fused_off": census_off["total"],
        "census_fused_on": census_on["total"],
        "census_delta_thunks": census_on["total"] - census_off["total"],
        "census_off_on_image": oni_off["total"],
        "census_on_on_image": oni_on["total"],
        "on_image_seeded": seeded,
        "rate_fused_on": round(rounds / t_on, 2),
        "rate_fused_off": round(rounds / t_off, 2),
        "hbm_ratio_at_hi": ratio,
        "hbm_drop_frac": (None if ratio is None else round(1.0 - ratio, 4)),
    }


def check_baseline(root: str, res: dict) -> list[str]:
    """Committed-baseline leg: the fused-on thunk delta may not GROW
    past the committed pin (the sort-composite's constant overhead);
    rate numbers are informational."""
    out: list[str] = []
    path = os.path.join(root, BASELINE_NAME)
    if not os.path.exists(path):
        if not os.environ.get("FUSE_SMOKE_UPDATE"):
            out.append(f"{BASELINE_NAME} missing — run FUSE_SMOKE_UPDATE=1 "
                       "scripts/fuse_smoke.py to record it")
        return out
    if os.environ.get("FUSE_SMOKE_UPDATE"):
        return out
    with open(path) as f:
        base = json.load(f)
    if int(base.get("n_peers", res["n_peers"])) != res["n_peers"]:
        return out  # reshape run: the committed delta is shape-specific
    pinned = base.get("census_delta_thunks")
    if pinned is not None and res["census_delta_thunks"] > int(pinned):
        out.append(
            f"fused-on census delta grew: +{res['census_delta_thunks']} "
            f"thunks over fused-off (committed pin +{int(pinned)}) — the "
            "fused composites stopped fusing")
    committed_off = base.get("census_fused_off")
    if (committed_off is not None
            and res["census_fused_off"] != committed_off):
        print(
            f"fuse-smoke NOTE: fused-off census {res['census_fused_off']} "
            f"!= committed {committed_off} ({BASELINE_NAME}) — "
            "informational pin; the hard gate uses the on-image baseline "
            f"{res['census_off_on_image']}", file=sys.stderr)
    return out


def write_baseline(root: str, res: dict) -> str:
    path = os.path.join(root, BASELINE_NAME)
    doc = {
        "schema": 1,
        "note": ("fused-CSR-plane smoke baseline (scripts/fuse_smoke.py); "
                 "FUSE_SMOKE_UPDATE=1 rewrites. census_* are compiled "
                 "per-round-step thunk counts on the gate image; "
                 "census_delta_thunks pins the fused build's constant "
                 "sort-machinery overhead (growth = lost fusion); "
                 "hbm_drop_frac is the committed COST_AUDIT.json fusion "
                 "contract's csr traffic cut; rate_* are warm CPU "
                 "rounds/s, informational."),
        **{k: res[k] for k in (
            "n_peers", "rounds", "census_fused_off", "census_fused_on",
            "census_delta_thunks", "rate_fused_on", "rate_fused_off",
            "hbm_ratio_at_hi", "hbm_drop_frac")},
    }
    with open(path, "w") as f:
        json.dump(doc, f, indent=2)
        f.write("\n")
    return path


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    ap.add_argument("--n", type=int,
                    default=int(os.environ.get("FUSE_SMOKE_N", 0)) or None)
    ap.add_argument("--rounds", type=int, default=SMOKE_ROUNDS)
    args = ap.parse_args(argv)

    import jax

    # smoke-gate policy: CPU-only, bench PRNG
    jax.config.update("jax_platforms", "cpu")
    jax.config.update("jax_default_prng_impl", "unsafe_rbg")

    from go_libp2p_pubsub_tpu.compile_cache import enable_persistent_cache
    from go_libp2p_pubsub_tpu.perf.regress import repo_root

    root = repo_root()
    enable_persistent_cache(os.path.join(root, ".jax_cache"))
    n = args.n or DEFAULT_N

    res = run_gate(n, args.rounds)
    failures = list(res["failures"]) + check_baseline(root, res)
    if os.environ.get("FUSE_SMOKE_UPDATE") and not res["failures"]:
        print(f"wrote {write_baseline(root, res)}")

    print(json.dumps({
        "fuse_smoke": "PASS" if not failures else "FAIL",
        **{k: v for k, v in res.items() if k != "failures"},
        "failures": failures,
    }))
    return 1 if failures else 0


if __name__ == "__main__":
    sys.exit(main())
