"""Bytes/peer audit over the live state trees (`make mem-audit`).

The round-15 memory-budget satellite (docs/DESIGN.md §15): with PR 9 the
dispatch overhead is gone, memory is the wall between N=100k and
"millions of users" — so measure it instead of guessing. For each
audited engine the script abstractly evaluates (``jax.eval_shape`` — no
allocation) the full state tree at two reference peer counts, fits each
leaf's byte cost as ``bytes(N) = const + slope·N`` (every axis is either
N-proportional or fixed at the audit's K/M/S/H, so two points determine
the line exactly), and emits:

  * per-leaf rows: path, dtype, bytes/peer (the slope), fixed bytes,
    and whether the leaf carries the padded edge axis K;
  * per-engine totals: bytes/peer and projected resident state at
    N ∈ {100k, 1M, 10M};
  * the dense-vs-CSR exchange projection: the per-round transmit
    tensor's dense ``N·K·W`` words against the flat ``E·W = density·N·K·W``
    CSR form (ops/csr.py) at representative densities — the byte ratio
    IS the topology density, which is the whole sparse-plane argument;
  * the narrowing delta: the ``narrow_counters`` (int16) build's
    bytes/peer against the default, leaf-exact;
  * the round-22 dynamic-topology tier: what the opt-in mutable overlay
    planes (``dynamic_topo=True`` -> state.TopoState) add, as
    const+slope·N rows plus a 1M/10M headroom table.

Everything is shape arithmetic — deterministic, platform-independent —
so the committed MEM_AUDIT.json baseline must reproduce byte-identical
with defaults; MEM_AUDIT_UPDATE=1 rewrites it. The v5e-8 N-scaling
projection (perf/projection.py project_at_scale) reads the totals'
``bytes_per_peer`` as its memory term.
"""

from __future__ import annotations

import json
import os
import sys

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)

AUDIT_PATH = os.path.join(REPO, "MEM_AUDIT.json")

#: two reference peer counts — any pair works (leaf bytes are affine in
#: N); these keep eval_shape instant
N_LO, N_HI = 256, 512
#: audit array-sizing (the bench geometry: ring d=8 -> K=16, M=64)
AUDIT_DEGREE_D = 8
AUDIT_M = 64
#: projection targets
TARGETS = (100_000, 1_000_000, 10_000_000)
#: representative edge densities E/(N·K) for the CSR projection: a full
#: regular graph, the ~0.6 of a padded random graph, and the long-tail
#: power-law regime
DENSITIES = (1.0, 0.6, 0.25)

ENGINES = ("gossipsub", "gossipsub_narrow", "floodsub",
           "gossipsub_csr", "floodsub_csr")



def _state_tree(engine: str, n: int):
    """The engine's state tree as avals (no device allocation)."""
    import jax

    from go_libp2p_pubsub_tpu import graph
    from go_libp2p_pubsub_tpu.state import Net, SimState

    csr = engine.endswith("_csr")
    layout = "csr" if csr else "dense"
    if engine.startswith("floodsub"):
        if csr:
            topo = graph.ring_lattice(n, d=AUDIT_DEGREE_D)
            subs = graph.subscribe_all(n, 1)
            net = Net.build(topo, subs, edge_layout="csr")

            def build():
                return SimState.init(n, AUDIT_M, k=net.max_degree,
                                     n_edges=net.n_edges)
        else:
            def build():
                return SimState.init(n, AUDIT_M, k=2 * AUDIT_DEGREE_D)

        return jax.eval_shape(build)

    from go_libp2p_pubsub_tpu.config import (
        GossipSubParams,
        PeerScoreThresholds,
    )
    from go_libp2p_pubsub_tpu.models.gossipsub import (
        GossipSubConfig,
        GossipSubState,
    )
    from go_libp2p_pubsub_tpu.perf.sweep import bench_score_params

    topo = graph.ring_lattice(n, d=AUDIT_DEGREE_D)
    subs = graph.subscribe_all(n, 1)
    net = Net.build(topo, subs, edge_layout=layout)
    _tp, sp = bench_score_params("default", 1)
    cfg = GossipSubConfig.build(
        GossipSubParams(), PeerScoreThresholds(), score_enabled=True,
        narrow_counters=(engine == "gossipsub_narrow"),
        edge_layout=layout,
    )

    def build():
        return GossipSubState.init(net, AUDIT_M, cfg, score_params=sp)

    return jax.eval_shape(build)


def _leaf_rows(engine: str) -> list[dict]:
    import jax
    import jax.tree_util as jtu

    # the tier's membership is named once, next to the pack/unpack code
    from go_libp2p_pubsub_tpu.state import CSR_RESIDENT_SUFFIXES

    def flat(n):
        tree = _state_tree(engine, n)
        out = {}
        for path, leaf in jtu.tree_flatten_with_path(tree)[0]:
            dt = str(leaf.dtype)
            if dt.startswith("key<"):
                # PRNG keys: normalized to 8 bytes/element (threefry's
                # 2x u32) so the audit is independent of the ambient
                # jax_default_prng_impl — the same normalization the
                # STATE_SCHEMA baseline applies to key dtypes
                dt = "key"
                nbytes = int(leaf.size) * 8
            else:
                nbytes = int(leaf.size) * leaf.dtype.itemsize
            out[jtu.keystr(path)] = (dt, list(leaf.shape), nbytes)
        return out

    lo, hi = flat(N_LO), flat(N_HI)
    assert set(lo) == set(hi), "leaf set changed with N"
    k_dim = 2 * AUDIT_DEGREE_D
    csr_resident = engine.endswith("_csr")
    rows = []
    for path in sorted(lo):
        dt, shape_lo, b_lo = lo[path]
        _, shape_hi, b_hi = hi[path]
        slope = (b_hi - b_lo) / (N_HI - N_LO)
        const = b_lo - slope * N_LO
        # edge-axis tag: a non-N axis equal to the padded degree K
        n_axes = [i for i, (a, b) in enumerate(zip(shape_lo, shape_hi))
                  if a != b]
        edge_axis = any(
            d == k_dim and i not in n_axes
            for i, d in enumerate(shape_lo)
        )
        row = {
            "path": path,
            "dtype": dt,
            "shape_at_lo": shape_lo,
            "bytes_per_peer": slope,
            "const_bytes": const,
            "edge_axis": bool(edge_axis),
        }
        # round-18 CSR-resident tier: the flat [E, ...] planes — the
        # fit in N is the fit in E on the audit ring (E = K·N there,
        # density 1), so the PER-EDGE cost is slope/K: const+slope·E
        # from the same two eval_shape points. At density δ the tier's
        # resident bytes/peer are δ·slope — the dense build's never
        # shrink (that delta is the csr_tier block below).
        if csr_resident and any(path.endswith(sf)
                                for sf in CSR_RESIDENT_SUFFIXES):
            row["edge_resident"] = True
            row["bytes_per_edge"] = slope / k_dim
        rows.append(row)
    return rows


def _engine_block(engine: str) -> dict:
    rows = _leaf_rows(engine)
    bpp = sum(r["bytes_per_peer"] for r in rows)
    const = sum(r["const_bytes"] for r in rows)
    return {
        "leaves": rows,
        "totals": {
            "bytes_per_peer": bpp,
            "const_bytes": const,
            "resident_mb": {
                str(n): round((const + bpp * n) / 1024 ** 2, 2)
                for n in TARGETS
            },
        },
    }


def _exchange_block() -> dict:
    """Dense-vs-CSR projection of the per-round transmit exchange (the
    [N, K, W] word tensor every delivery round moves)."""
    k = 2 * AUDIT_DEGREE_D
    w = (AUDIT_M + 31) // 32
    dense_per_peer = k * w * 4
    return {
        "k": k,
        "msg_slots": AUDIT_M,
        "dense_bytes_per_peer": dense_per_peer,
        "csr_bytes_per_peer": {
            str(d): round(dense_per_peer * d, 2) for d in DENSITIES
        },
        "note": (
            "per-round transmit words; the CSR/dense byte ratio equals "
            "the topology density E/(N*K) (ops/csr.py) — dead padded "
            "slots never cross the wire on the csr layout"
        ),
    }


def _csr_tier_block(blocks: dict) -> dict:
    """The round-18 CSR-resident tier: which bytes scale with E instead
    of N·K, and the dense-vs-csr bytes/peer delta by density (at
    density δ the flat planes cost δ × their dense capacity — the
    dense build always pays full capacity)."""
    out_engines = {}
    for eng in ("gossipsub_csr", "floodsub_csr"):
        rows = [r for r in blocks[eng]["leaves"] if r.get("edge_resident")]
        flat_bpp = sum(r["bytes_per_peer"] for r in rows)
        dense_eng = eng[: -len("_csr")]
        dense_bpp = blocks[dense_eng]["totals"]["bytes_per_peer"]
        out_engines[eng] = {
            "edge_resident_leaves": [r["path"] for r in rows],
            "bytes_per_edge": sum(r["bytes_per_edge"] for r in rows),
            "flat_bytes_per_peer_at_full_density": flat_bpp,
            "dense_engine_bytes_per_peer": dense_bpp,
            "bytes_per_peer_by_density": {
                str(d): round(dense_bpp - flat_bpp * (1.0 - d), 2)
                for d in DENSITIES
            },
            "saved_bytes_per_peer_by_density": {
                str(d): round(flat_bpp * (1.0 - d), 2) for d in DENSITIES
            },
        }
    return {
        "note": ("CSR-resident state tier (round 18): flat [E, ...] "
                 "planes cost density x capacity; the dense build "
                 "always pays full capacity (docs/DESIGN.md §18)"),
        "engines": out_engines,
    }


def _dynamics_rows(n: int) -> dict:
    """The ``.core.topo`` plane's leaves (dtype, shape, bytes) at one N —
    abstract (eval_shape), like every other audit row."""
    import jax
    import jax.tree_util as jtu

    from go_libp2p_pubsub_tpu import graph
    from go_libp2p_pubsub_tpu.config import (
        GossipSubParams,
        PeerScoreThresholds,
    )
    from go_libp2p_pubsub_tpu.models.gossipsub import (
        GossipSubConfig,
        GossipSubState,
    )
    from go_libp2p_pubsub_tpu.state import Net

    topo = graph.ring_lattice(n, d=AUDIT_DEGREE_D)
    subs = graph.subscribe_all(n, 1)
    net = Net.build(topo, subs, dynamic=True)
    cfg = GossipSubConfig.build(GossipSubParams(), PeerScoreThresholds(),
                                score_enabled=False)
    tree = jax.eval_shape(
        lambda: GossipSubState.init(net, AUDIT_M, cfg, seed=0,
                                    dynamic_topo=True))
    out = {}
    for path, leaf in jtu.tree_flatten_with_path(tree)[0]:
        key = jtu.keystr(path)
        if ".topo." not in key:
            continue
        out[key] = (str(leaf.dtype), list(leaf.shape),
                    int(leaf.size) * leaf.dtype.itemsize)
    return out


def _dynamics_block(blocks: dict) -> dict:
    """The round-22 dynamic-topology tier: what carrying the overlay in
    the state tree (``dynamic_topo=True`` — the mutable nbr/nbr_ok/rev/
    edge_perm/epoch planes of state.TopoState) costs on top of the
    frozen build, as const+slope·N rows plus the 1M/10M headroom table.
    The tier is pure opt-in: with the flag off the planes do not exist
    and the tree is bit-identical to pre-round-22 (the mutation-off
    test pins that), so the baseline engine blocks above are unchanged
    by construction."""
    lo, hi = _dynamics_rows(N_LO), _dynamics_rows(N_HI)
    assert set(lo) == set(hi), "topo leaf set changed with N"
    rows = []
    for path in sorted(lo):
        dt, shape_lo, b_lo = lo[path]
        _, _, b_hi = hi[path]
        slope = (b_hi - b_lo) / (N_HI - N_LO)
        const = b_lo - slope * N_LO
        rows.append({
            "path": path,
            "dtype": dt,
            "shape_at_lo": shape_lo,
            "bytes_per_peer": slope,
            "const_bytes": const,
        })
    bpp = sum(r["bytes_per_peer"] for r in rows)
    const = sum(r["const_bytes"] for r in rows)
    base = blocks["gossipsub"]["totals"]["bytes_per_peer"]
    return {
        "note": ("dynamic-topology tier (round 22): the mutable overlay "
                 "planes GossipSubState.init(dynamic_topo=True) adds "
                 "(state.TopoState; docs/DESIGN.md §22). Off by default "
                 "— the frozen build pays zero bytes for it"),
        "leaves": rows,
        "totals": {
            "bytes_per_peer": bpp,
            "const_bytes": const,
            "resident_mb": {
                str(n): round((const + bpp * n) / 1024 ** 2, 2)
                for n in TARGETS
            },
        },
        "headroom": {
            # what turning mutation on costs at the scale targets, next
            # to the frozen gossipsub build it rides on; index_width is
            # the range auditor's symbolic flat-index verdict at this N
            # (analysis/ranges.py scale leg — the audit geometry this
            # table's projections assume, plus the growth-envelope
            # geometry as the honest qualifier)
            str(n): {
                "frozen_mb": round(base * n / 1024 ** 2, 2),
                "dynamic_mb": round((base + bpp) * n / 1024 ** 2, 2),
                "added_mb": round(bpp * n / 1024 ** 2, 2),
                "added_frac": round(bpp / base, 4),
                "index_width": _index_width(n, "audit"),
                "index_width_envelope": _index_width(n, "envelope"),
            }
            for n in (1_000_000, 10_000_000)
        },
    }


def _index_width(n: int, geometry: str) -> str:
    """The range auditor's flat-index verdict at one peer count — the
    headroom table's i32-validity column (analysis/ranges.py)."""
    from go_libp2p_pubsub_tpu.analysis.ranges import index_width_verdict

    return index_width_verdict(n, geometry)


def build_audit() -> dict:
    blocks = {e: _engine_block(e) for e in ENGINES}
    gs = blocks["gossipsub"]["totals"]["bytes_per_peer"]
    narrow = blocks["gossipsub_narrow"]["totals"]["bytes_per_peer"]
    return {
        "schema": 3,
        "note": ("bytes/peer audit of the live state trees "
                 "(scripts/memstat.py; MEM_AUDIT_UPDATE=1 rewrites)"),
        "shape": {"degree_d": AUDIT_DEGREE_D, "k": 2 * AUDIT_DEGREE_D,
                  "msg_slots": AUDIT_M, "n_lo": N_LO, "n_hi": N_HI},
        "engines": blocks,
        "exchange": _exchange_block(),
        "csr_tier": _csr_tier_block(blocks),
        "dynamics_tier": _dynamics_block(blocks),
        "narrowing": {
            "gossipsub_bytes_per_peer": gs,
            "narrow_counters_bytes_per_peer": narrow,
            "saved_bytes_per_peer": gs - narrow,
        },
    }


def bytes_per_peer_for(audit: dict, engine: str = "gossipsub",
                       edge_layout: str = "dense",
                       density: float = 1.0) -> float:
    """Resident bytes/peer for the ACTIVE layout (the round-18 headroom
    fix: a csr run's memory term must price the flat tier at ITS
    density, not the always-dense capacity). ``density`` is E/(N·K).
    Thin alias of the one pricing rule in perf.projection so the
    printed headroom table and ``project_at_scale`` cannot drift."""
    from go_libp2p_pubsub_tpu.perf.projection import audit_bytes_per_peer

    return audit_bytes_per_peer(audit, engine, edge_layout, density)


def check_committed(committed: dict, fresh: dict) -> list[str]:
    """The byte-identity gate on explicit inputs (the negative-test
    surface): a reproduction failure NAMES the diverging keys (round-19
    satellite — shared walker: analysis/costmodel.py)."""
    if committed == fresh:
        return []
    from go_libp2p_pubsub_tpu.analysis.costmodel import baseline_divergences

    diverged = baseline_divergences(committed, fresh)
    return [
        "live state trees no longer match the committed MEM_AUDIT.json "
        "(a state-plane change moved the byte budget; "
        "MEM_AUDIT_UPDATE=1 rewrites after review) — diverging keys: "
        + "; ".join(diverged)
    ]


def main() -> int:
    import jax

    jax.config.update("jax_platforms", "cpu")
    audit = build_audit()
    update = bool(os.environ.get("MEM_AUDIT_UPDATE"))
    if update or not os.path.exists(AUDIT_PATH):
        with open(AUDIT_PATH, "w") as f:
            json.dump(audit, f, indent=1, sort_keys=True)
            f.write("\n")
        print(f"mem-audit: wrote {AUDIT_PATH}")
    else:
        with open(AUDIT_PATH) as f:
            committed = json.load(f)
        failures = check_committed(committed, audit)
        if failures:
            for msg in failures:
                print(f"mem-audit: FAIL — {msg}")
            return 1
        print("mem-audit: OK — committed baseline reproduces")

    # human-readable summary: the headroom table + top leaves. The
    # table prices each engine row under its OWN layout (round-18 fix:
    # the csr rows are the flat tier, not the always-dense capacity)
    for eng in ENGINES:
        tot = audit["engines"][eng]["totals"]
        print(f"\n[{eng}] {tot['bytes_per_peer']:.1f} bytes/peer; "
              "resident state:")
        for n, mb in tot["resident_mb"].items():
            print(f"  N={int(n):>10,}: {mb:>10.2f} MB  "
                  f"index_width={_index_width(int(n), 'audit')}")
    tier = audit["csr_tier"]["engines"]["gossipsub_csr"]
    print("\ncsr-resident tier (gossipsub): "
          f"{tier['flat_bytes_per_peer_at_full_density']:.0f} B/peer of "
          f"capacity rides flat [E] planes ({tier['bytes_per_edge']:.1f} "
          "B/edge); dense-vs-csr bytes/peer by density:")
    for d in DENSITIES:
        print(f"  density {d}: dense "
              f"{tier['dense_engine_bytes_per_peer']:.0f} vs csr "
              f"{tier['bytes_per_peer_by_density'][str(d)]} "
              f"(saves {tier['saved_bytes_per_peer_by_density'][str(d)]})")
    dyn = audit["dynamics_tier"]
    print(f"\ndynamic-topology tier (opt-in): "
          f"{dyn['totals']['bytes_per_peer']:.0f} B/peer of overlay "
          "planes; headroom over the frozen gossipsub build:")
    for n, row in dyn["headroom"].items():
        print(f"  N={int(n):>10,}: +{row['added_mb']:>9.2f} MB "
              f"({row['frozen_mb']:.2f} -> {row['dynamic_mb']:.2f}, "
              f"+{row['added_frac'] * 100:.1f}%) "
              f"index_width={row['index_width']} "
              f"(envelope {row['index_width_envelope']})")
    top = sorted(audit["engines"]["gossipsub"]["leaves"],
                 key=lambda r: -r["bytes_per_peer"])[:8]
    print("\nheaviest gossipsub leaves (bytes/peer):")
    for r in top:
        tag = " [edge-axis]" if r["edge_axis"] else ""
        print(f"  {r['path']:<40} {r['dtype']:<8} "
              f"{r['bytes_per_peer']:8.1f}{tag}")
    ex = audit["exchange"]
    print(f"\nexchange (per round): dense {ex['dense_bytes_per_peer']} "
          f"B/peer; csr {ex['csr_bytes_per_peer']} (by density)")
    nar = audit["narrowing"]
    print(f"narrow_counters saves {nar['saved_bytes_per_peer']:.1f} "
          "bytes/peer (int16 IHAVE counters)")
    return 0


if __name__ == "__main__":
    sys.exit(main())
