"""``make cost-audit`` — the static device-cost gate (docs/DESIGN.md
§19, analysis/costmodel.py).

Three legs, any failing exits non-zero:

  1. **contracts** — the jaxpr-level cost interpreter walks every
     engine×layout build (per-round + phase × dense/csr, floodsub,
     randomsub, lifted, a scanned window) and the hard contracts must
     hold: csr/dense halo-bytes ratio == power-law topology density AND
     == the measured ``ops/edges.tally_halo_bytes`` accounting (routed
     through the guarded ``tally_step`` path — a cached jaxpr raises
     ``TallyCacheHit`` instead of reading zero); floodsub rng_bits ==
     0; telemetry-on flop delta under the static share ceiling; the
     invariant checker's flops under a bounded share of step flops.
  2. **byte-identical reproduction** — the committed ``COST_AUDIT.json``
     must equal this run's audit byte for byte (the MEM_AUDIT pattern);
     a mismatch NAMES the diverging keys. ``COST_UPDATE=1`` rewrites.
  3. **roofline sanity** — the v5e-8 roofline term built from the
     audit's gossipsub fit must be finite and DISARMED by default in
     the projection (committed round-5 projections reproduce
     byte-identically; tests/test_perf.py pins the numbers).

Pure tracing — no compile, no execution; the metrics are
PRNG-impl-independent at jaxpr level (the impl rides the key dtype,
not the primitives). ~15 s warm. Emits one JSON summary line; findings
to stderr.
"""

from __future__ import annotations

import json
import os
import sys

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)


def main() -> int:
    import jax

    jax.config.update("jax_platforms", "cpu")

    from go_libp2p_pubsub_tpu.analysis import costmodel as cm
    from go_libp2p_pubsub_tpu.perf import projection

    failures: list[str] = []
    try:
        payload = cm.build_audit()
    except cm.CostContractViolation as e:
        print(f"cost-audit FAIL: {e}", file=sys.stderr)
        print(json.dumps({"cost_audit": "FAIL", "artifact": "contract",
                          "failures": 1}))
        return 1

    path = cm.audit_path(REPO)
    text = cm.dump_audit(payload)
    update = bool(os.environ.get("COST_UPDATE"))
    if update:
        with open(path, "w") as f:
            f.write(text)
        action = "updated"
    elif not os.path.exists(path):
        failures.append(
            f"{cm.AUDIT_NAME} missing — run COST_UPDATE=1 "
            "scripts/cost_audit.py to record it")
        action = "missing"
    else:
        with open(path) as f:
            committed_text = f.read()
        # cost-REGRESSION gate (round 21): the fresh per-build
        # hbm_bytes/round must stay under the COMMITTED ceilings —
        # independent of byte-identity, so a regression is NAMED as a
        # budget breach, not just a diverging key
        try:
            ceilings = (json.loads(committed_text)
                        .get("contracts", {})
                        .get("hbm_ceilings", {})
                        .get("ceilings", {}))
            cm.check_hbm_ceilings(ceilings, payload["builds"])
        except json.JSONDecodeError:
            pass  # the byte-identity leg below reports unparseable JSON
        except cm.CostContractViolation as e:
            failures.append(str(e))
        if committed_text == text:
            action = "verified"
        else:
            action = "stale"
            try:
                diverged = cm.baseline_divergences(
                    json.loads(committed_text), payload)
                detail = ("diverging keys: " + "; ".join(diverged)
                          if diverged else
                          "artifacts parse equal — formatting-only "
                          "drift (re-serialize with COST_UPDATE=1)")
            except json.JSONDecodeError:
                detail = "committed artifact is not parseable JSON"
            failures.append(
                f"{cm.AUDIT_NAME} does not reproduce byte-identical — "
                f"the device programs moved the cost budget; {detail} "
                "(review, then COST_UPDATE=1 to re-record)")

    # roofline sanity: the term must price finite numbers from the
    # committed fit, and stay DISARMED in the default projection
    gs = payload["builds"]["gossipsub"]["per_round"]
    shard_n = 12_500
    ms = projection.roofline_ms_per_round(
        cm.eval_fit(gs, "flops", shard_n),
        cm.eval_fit(gs, "hbm_bytes", shard_n))
    if not (ms > 0 and ms < 1e6):
        failures.append(
            f"roofline term priced a nonsense bound ({ms} ms/round at "
            f"shard N={shard_n})")
    # project_at_scale is the surface that gained the field — its
    # default summary must stay roofline-free (project()'s summary is
    # a fixed literal and cannot regress here)
    default_summary = projection.project_at_scale(100_000, 16).summary()
    if any("roofline" in k for k in default_summary):
        failures.append(
            "the default project_at_scale summary carries roofline "
            "keys — the term must stay disarmed so committed "
            "projections reproduce byte-identically")

    summary = {
        "cost_audit": "FAIL" if failures else "PASS",
        "artifact": action,
        "builds": sorted(payload["builds"]),
        "contracts": sorted(payload["contracts"]),
        "roofline_ms_per_round_at_12500": round(ms, 6),
        "failures": len(failures),
    }
    if failures:
        for f in failures:
            print(f"cost-audit FAIL: {f}", file=sys.stderr)
    print(json.dumps(summary))
    return 1 if failures else 0


if __name__ == "__main__":
    sys.exit(main())
