"""Compare a Go-reference trace file against a simulator trace: one-command
external validation (VERDICT round-3 item 4).

The reference's PBTracer writes varint-delimited TraceEvent protos
(tracer.go:131-181, protoio.NewDelimitedWriter); its JSONTracer writes
newline-JSON. Our pb/pubsub_trace.proto mirrors the schema and
wire/framing.py speaks the same LEB128 delimiting, so a trace produced by
the actual Go reference parses here directly. No Go toolchain exists in
this image (documented in README.md), so the reference run must happen
elsewhere — the moment such a file exists, this script closes the loop:

    python scripts/compare_ref_trace.py ref_trace.pb sim_trace.pb

Method: reconstruct each file's propagation-latency distribution
(DeliverMessage.timestamp - PublishMessage.timestamp per messageID),
quantize to rounds (the simulator's tick is --sim-round-ns, default 1e9;
the reference's per-hop time is --ref-round-ns, default auto = median of
per-message first-delivery latencies, the one-hop time), and report both
CDFs with their sup-distance against the north star's 2% envelope.
Coverage (deliveries per publish) prints separately — a trace alone does
not carry subscriber counts, so the CDFs are delivered-sample CDFs.
"""

from __future__ import annotations

import argparse
import json
import os
import sys

import numpy as np

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))


def load_events(path: str):
    """TraceEvents from a reference/simulator file: .pb (varint-delimited,
    reference PBTracer format) or .json (our JSONTracer lines)."""
    from go_libp2p_pubsub_tpu.pb import trace_pb2

    if path.endswith(".json"):
        out = []
        for line in open(path):
            line = line.strip()
            if not line:
                continue
            d = json.loads(line)
            ev = trace_pb2.TraceEvent()
            _json_to_event(d, ev, trace_pb2)
            out.append(ev)
        return out
    from go_libp2p_pubsub_tpu.wire import framing

    with open(path, "rb") as f:
        return list(framing.read_delimited_messages(f, trace_pb2.TraceEvent))


def _json_to_event(d: dict, ev, trace_pb2) -> None:
    """Minimal JSON->proto for the fields the CDF needs (our JSONTracer
    writes MessageToDict camelCase JSON)."""
    from google.protobuf.json_format import ParseDict

    ParseDict(d, ev, ignore_unknown_fields=True)


def latency_samples(events, round_ns: float | None):
    """(latencies-in-rounds array, n_publish, n_deliver, auto_round_ns)."""
    pub_ts: dict[bytes, int] = {}
    deliver: list[tuple[bytes, int]] = []
    for ev in events:
        if ev.type == ev.PUBLISH_MESSAGE:
            pub_ts.setdefault(ev.publishMessage.messageID, ev.timestamp)
        elif ev.type == ev.DELIVER_MESSAGE:
            deliver.append((ev.deliverMessage.messageID, ev.timestamp))
    lat_ns = np.array(
        [ts - pub_ts[mid] for mid, ts in deliver if mid in pub_ts],
        dtype=np.float64,
    )
    auto = None
    if round_ns is None:
        # per-hop time estimate: median of each message's FIRST delivery
        # latency (the one-hop messages dominate the minimum)
        firsts: dict[bytes, float] = {}
        for mid, ts in deliver:
            if mid in pub_ts:
                d = ts - pub_ts[mid]
                if mid not in firsts or d < firsts[mid]:
                    firsts[mid] = d
        if not firsts:
            raise SystemExit("no (publish, deliver) pairs in trace")
        auto = float(np.median([v for v in firsts.values() if v > 0]))
        # refine: min-over-peers biases the first-hop estimate low; a few
        # fixed-point rounds of (assign hop counts, re-fit) recover the
        # per-hop time when jitter < half a hop. Pass --ref-round-ns when
        # the reference run's link latency is known — the estimate is a
        # convenience, not ground truth.
        for _ in range(3):
            k = np.maximum(np.rint(lat_ns / auto), 1)
            auto = float(np.median(lat_ns / k))
        round_ns = auto
    rounds = np.maximum(np.rint(lat_ns / round_ns), 0)
    return rounds, len(pub_ts), len(deliver), auto


def cdf_of(rounds: np.ndarray, max_h: int) -> np.ndarray:
    hist = np.zeros(max_h + 1)
    for h in rounds:
        hist[min(int(h), max_h)] += 1
    if hist.sum() == 0:
        return hist
    return np.cumsum(hist) / hist.sum()


def main(argv=None):
    ap = argparse.ArgumentParser(description=__doc__.split("\n")[0])
    ap.add_argument("ref_trace", help="Go-reference trace (.pb or .json)")
    ap.add_argument("sim_trace", help="simulator trace (.pb or .json)")
    ap.add_argument("--max-h", type=int, default=16)
    ap.add_argument("--ref-round-ns", type=float, default=None,
                    help="reference per-hop time (default: auto-estimate)")
    ap.add_argument("--sim-round-ns", type=float, default=1e9,
                    help="simulator tick_ns (TraceSession default 1e9)")
    ap.add_argument("--envelope", type=float, default=0.02,
                    help="pass/fail sup-distance bound (north star: 2%%)")
    args = ap.parse_args(argv)

    ref_ev = load_events(args.ref_trace)
    sim_ev = load_events(args.sim_trace)
    ref_r, ref_pub, ref_dlv, ref_auto = latency_samples(
        ref_ev, args.ref_round_ns
    )
    sim_r, sim_pub, sim_dlv, _ = latency_samples(sim_ev, args.sim_round_ns)

    ref_cdf = cdf_of(ref_r, args.max_h)
    sim_cdf = cdf_of(sim_r, args.max_h)
    sup = float(np.max(np.abs(ref_cdf - sim_cdf)))

    print(f"ref : {len(ref_ev)} events, {ref_pub} publishes, "
          f"{ref_dlv} deliveries"
          + (f", auto hop time {ref_auto/1e6:.2f} ms" if ref_auto else ""))
    print(f"sim : {len(sim_ev)} events, {sim_pub} publishes, "
          f"{sim_dlv} deliveries")
    print(f"{'rounds':>6} {'ref CDF':>9} {'sim CDF':>9} {'|diff|':>8}")
    for h in range(args.max_h + 1):
        d = abs(ref_cdf[h] - sim_cdf[h])
        print(f"{h:>6} {ref_cdf[h]:>9.4f} {sim_cdf[h]:>9.4f} {d:>8.4f}")
    verdict = "PASS" if sup <= args.envelope else "FAIL"
    print(json.dumps({
        "cdf_sup_distance": round(sup, 6),
        "envelope": args.envelope,
        "verdict": verdict,
        "ref_deliver_per_publish": round(ref_dlv / max(ref_pub, 1), 2),
        "sim_deliver_per_publish": round(sim_dlv / max(sim_pub, 1), 2),
    }))
    return 0 if verdict == "PASS" else 1


if __name__ == "__main__":
    sys.exit(main())
