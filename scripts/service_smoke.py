#!/usr/bin/env python
"""Supervised-service-loop gate (``make service-smoke``; docs/DESIGN.md
§17).

Drives the deterministic supervised cell
(``go_libp2p_pubsub_tpu.serve._child`` — chaos + health probes + folded
invariants) through the full failure catalog and asserts the round-17
recovery contract:

  1. **control** — an uninterrupted supervised run completes with zero
     recoveries, exactly ONE window compile per window shape (the
     one-compile-per-window-shape sentinel), and a fresh ``done``
     heartbeat.
  2. **kill/resume bit-exactness** — a child process is SIGKILLed at a
     RANDOMIZED (seeded) segment and crash site — including
     mid-checkpoint-write, where the tmp file is truncated before the
     kill — and the re-invoked run resumes from the rolling store and
     finishes with a final-state digest IDENTICAL to the control's.
  3. **corrupted-checkpoint fallback** — the store's newest snapshot is
     truncated on disk; ``restore_latest`` classifies it
     (``CheckpointCorrupt``) and falls back to the previous manifest
     entry.
  4. **seeded-NaN rollback-and-localize** — a NaN injected into a state
     leaf mid-segment trips the ``finite-state`` probe; the supervisor
     rolls back, the per-dispatch replay names EXACTLY the injected
     dispatch in the forensic bundle, and the recovered run still
     finishes digest-identical to the control.
  5. **heartbeat freshness** — the control's ``HEARTBEAT.json`` is
     ``done``, covers every dispatch, and was written during this gate
     run.
  6. **overhead ceiling** — warm-vs-warm, a supervised run (probes +
     folded invariants + heartbeat; end-of-run checkpoint) must cost at
     most ``SERVICE_SMOKE_OVERHEAD`` (default 10%) over a bare
     ``WindowRunner`` driving the SAME segmented window with the same
     folded invariants — the supervision machinery itself is what's
     being priced; the every-segment checkpoint cadence is measured
     alongside and reported in the artifact (durability price, not
     gated).
  7. **census** — the service loop is observational: with probes off it
     adds zero device ops, so the chaos-off compiled kernel census must
     still equal the on-image baseline (the chaos-report census leg,
     reused).

``SERVICE_SMOKE_UPDATE=1`` rewrites SERVICE_SMOKE.json from this run.
Env knobs: SERVICE_SMOKE_N / _ROUNDS / _SEG (shape),
SERVICE_SMOKE_SEED (kill-site draw), SERVICE_SMOKE_OVERHEAD,
SERVICE_SMOKE_TOL. CPU-only by contract; census under the gate PRNG.
"""

from __future__ import annotations

import argparse
import json
import os
import subprocess
import sys
import time

_here = os.path.dirname(os.path.abspath(__file__))
sys.path.insert(0, os.path.dirname(_here))
sys.path.insert(0, _here)

import numpy as np  # noqa: E402

BASELINE_NAME = "SERVICE_SMOKE.json"
CHILD_N = 48
CHILD_ROUNDS = 32
CHILD_SEG = 8
OVERHEAD_N = 2048
OVERHEAD_ROUNDS = 32
OVERHEAD_SEG = 8
TIMING_REPS = 3
DEFAULT_OVERHEAD = 0.10
DEFAULT_TOL = 0.4
CHILD_TIMEOUT = 420


def child_cmd(root: str, *extra: str) -> list:
    return [sys.executable, "-m", "go_libp2p_pubsub_tpu.serve._child",
            "--root", root, "--n", str(CHILD_N),
            "--rounds", str(CHILD_ROUNDS), "--segment", str(CHILD_SEG),
            "--probes", "--invariants", "--report", *extra]


def run_child(repo_root: str, root: str, *extra: str):
    env = dict(os.environ,
               JAX_PLATFORMS="cpu",
               SERVE_CHILD_PRNG="unsafe_rbg",
               SERVE_CHILD_CACHE=os.path.join(repo_root, ".jax_cache"))
    return subprocess.run(
        child_cmd(root, *extra), cwd=repo_root, env=env,
        capture_output=True, text=True, timeout=CHILD_TIMEOUT)


def read_final(root: str) -> dict:
    with open(os.path.join(root, "FINAL.json")) as f:
        return json.load(f)


def check_control(repo_root: str, work: str, t_gate0: float,
                  failures: list) -> dict | None:
    root = os.path.join(work, "control")
    proc = run_child(repo_root, root, "--fresh")
    if proc.returncode != 0:
        failures.append(
            f"control: supervised run failed rc={proc.returncode}: "
            f"{proc.stderr[-500:]}")
        return None
    final = read_final(root)
    if final["recoveries"] or final["retries"]:
        failures.append(
            f"control: clean run reported recoveries="
            f"{final['recoveries']} retries={final['retries']}")
    bad = {k: v for k, v in final["window_compiles"].items() if v != 1}
    if bad:
        failures.append(
            f"one-compile-per-window-shape: control window compiled "
            f"{final['window_compiles']} (every shape must be exactly 1)")
    # heartbeat freshness
    hb_path = os.path.join(root, "HEARTBEAT.json")
    try:
        with open(hb_path) as f:
            hb = json.load(f)
        if hb.get("status") != "done":
            failures.append(f"heartbeat: status {hb.get('status')!r}, "
                            "expected 'done'")
        if hb.get("dispatch") != CHILD_ROUNDS:
            failures.append(
                f"heartbeat: dispatch {hb.get('dispatch')} != "
                f"{CHILD_ROUNDS} (stale — not covering the whole run)")
        if not (t_gate0 <= float(hb.get("updated_at", 0))
                <= time.time() + 1):
            failures.append(
                "heartbeat: updated_at is outside this gate run "
                "(stale liveness file)")
    except (OSError, ValueError) as e:
        failures.append(f"heartbeat: unreadable ({e})")
    return final


def check_kill_resume(repo_root: str, work: str, control: dict,
                      seed: int, failures: list) -> dict:
    from go_libp2p_pubsub_tpu.serve import KILL_SITES

    rng = np.random.default_rng(seed)
    n_segments = CHILD_ROUNDS // CHILD_SEG
    seg = int(rng.integers(1, n_segments))
    site = str(rng.choice(list(KILL_SITES)))
    root = os.path.join(work, "kill")
    proc = run_child(repo_root, root, "--fresh",
                     "--kill-segment", str(seg), "--kill-site", site)
    if proc.returncode != -9 and proc.returncode != 137:
        failures.append(
            f"kill/resume: the child was not SIGKILLed "
            f"(rc={proc.returncode}) — the {site}@segment{seg} crash "
            "point never fired")
        return {"segment": seg, "site": site}
    proc = run_child(repo_root, root)
    if proc.returncode != 0:
        failures.append(
            f"kill/resume: resume failed rc={proc.returncode}: "
            f"{proc.stderr[-500:]}")
        return {"segment": seg, "site": site}
    final = read_final(root)
    if final["digest"] != control["digest"]:
        failures.append(
            f"kill/resume: resumed digest {final['digest'][:16]} != "
            f"control {control['digest'][:16]} (SIGKILL at {site}, "
            f"segment {seg}) — resume is NOT bit-exact")
    if final.get("resumed_from") is None:
        failures.append(
            f"kill/resume: the resumed run did not restore from the "
            f"store (resumed_from is null; kill was {site}@segment{seg})")
    return {"segment": seg, "site": site,
            "resumed_from": final.get("resumed_from"),
            "bit_exact": final.get("digest") == control["digest"]}


def check_corrupt_fallback(repo_root: str, work: str,
                           failures: list) -> dict:
    import jax

    jax.config.update("jax_platforms", "cpu")
    from go_libp2p_pubsub_tpu.serve import CheckpointStore, truncate_file
    from go_libp2p_pubsub_tpu.serve._child import build_cell

    store_dir = os.path.join(work, "control", "checkpoints")
    _step, _margs, template_fn, _net, _cfg = build_cell(
        CHILD_N, CHILD_ROUNDS, 7, 0.1)
    store = CheckpointStore(store_dir)
    latest = store.latest()
    if latest is None:
        failures.append("corrupt-fallback: control store has no entries")
        return {}
    truncate_file(os.path.join(store_dir, latest["file"]))
    st, entry = store.restore_latest(template_fn())
    if st is None or entry is None:
        failures.append(
            "corrupt-fallback: no snapshot restored after corrupting "
            "the latest — the manifest fallback is broken")
        return {"corrupted": latest["ordinal"]}
    if entry["ordinal"] >= latest["ordinal"]:
        failures.append(
            f"corrupt-fallback: restored ordinal {entry['ordinal']} is "
            f"not OLDER than the corrupted {latest['ordinal']}")
    return {"corrupted": latest["ordinal"],
            "fell_back_to": entry["ordinal"]}


def check_nan_recovery(repo_root: str, work: str, control: dict,
                       failures: list) -> dict:
    seg, disp = 2, 3
    expect_bad = seg * CHILD_SEG + disp
    root = os.path.join(work, "nan")
    proc = run_child(repo_root, root, "--fresh",
                     "--corrupt-segment", str(seg),
                     "--corrupt-dispatch", str(disp),
                     "--corrupt-leaf", "scores", "--corrupt-kind", "nan")
    if proc.returncode != 0:
        failures.append(
            f"nan-recovery: run failed rc={proc.returncode}: "
            f"{proc.stderr[-500:]}")
        return {}
    final = read_final(root)
    if final["recoveries"] != 1:
        failures.append(
            f"nan-recovery: {final['recoveries']} recoveries, expected "
            "exactly 1 (probe must trip once, then the segment recovers)")
    if final["first_bad"] != [expect_bad]:
        failures.append(
            f"nan-recovery: replay localized dispatch(es) "
            f"{final['first_bad']}, expected [{expect_bad}] — the "
            "rollback replay did not name the injected dispatch")
    if final["digest"] != control["digest"]:
        failures.append(
            "nan-recovery: recovered digest differs from control — "
            "transient corruption must recover bit-exact")
    bundle = (final.get("bundles") or [None])[0]
    if bundle:
        with open(os.path.join(bundle, "bundle.json")) as f:
            b = json.load(f)
        if "finite-state" not in b.get("window_probe_failures", []):
            failures.append(
                f"nan-recovery: bundle names {b.get('window_probe_failures')}"
                " — the finite-state probe should have tripped")
        if not b.get("nan_census"):
            failures.append("nan-recovery: bundle has an empty nan_census")
    else:
        failures.append("nan-recovery: no forensic bundle emitted")
    return {"first_bad": final.get("first_bad"),
            "recoveries": final.get("recoveries"),
            "bit_exact": final.get("digest") == control["digest"]}


def check_overhead(n: int, rounds: int, seg: int, failures: list,
                   ceiling: float) -> dict:
    import jax

    jax.config.update("jax_platforms", "cpu")
    import tempfile

    from go_libp2p_pubsub_tpu import ensemble
    from go_libp2p_pubsub_tpu.oracle import (
        HealthConfig,
        InvariantConfig,
        ScanInvariants,
    )
    from go_libp2p_pubsub_tpu.serve import (
        RetentionPolicy,
        ServiceConfig,
        Supervisor,
    )
    from go_libp2p_pubsub_tpu.serve._child import build_cell

    step, make_args, template_fn, net, cfg = build_cell(
        n, rounds, 7, 0.1)

    def spec():
        return ScanInvariants(
            "gossipsub", net, cfg,
            InvariantConfig(check_every=seg, delivery_window=16),
            batched=False)

    bare = ensemble.WindowRunner(step, rounds, invariants=spec(),
                                 segment_len=seg)

    def run_bare():
        t0 = time.perf_counter()
        bare.run(template_fn(), make_args)
        return time.perf_counter() - t0

    def make_sup(ckpt_every: int, root: str) -> Supervisor:
        svc = ServiceConfig(
            n_dispatches=rounds, segment_len=seg, health=HealthConfig(),
            retention=RetentionPolicy(keep_last=2),
            checkpoint_every_segments=ckpt_every, report_name=None)
        return Supervisor(step, make_args, template_fn, root, svc,
                          invariants=spec())

    tmp = tempfile.mkdtemp(prefix="service_smoke_ov_")
    sup = make_sup(rounds // seg, os.path.join(tmp, "loop"))
    sup_ck = make_sup(1, os.path.join(tmp, "durable"))

    def run_sup(s):
        t0 = time.perf_counter()
        s.run(fresh=True)
        return time.perf_counter() - t0

    # warm every program (window jit + probe jit), then min over reps
    run_bare(), run_sup(sup), run_sup(sup_ck)
    t_bare = min(run_bare() for _ in range(TIMING_REPS))
    t_sup = min(run_sup(sup) for _ in range(TIMING_REPS))
    t_durable = min(run_sup(sup_ck) for _ in range(TIMING_REPS))
    overhead = t_sup / t_bare - 1.0 if t_bare > 0 else float("inf")
    if overhead > ceiling:
        failures.append(
            f"overhead: supervised loop costs {100 * overhead:.1f}% over "
            f"the bare segmented WindowRunner (ceiling "
            f"{100 * ceiling:.0f}%; warm-vs-warm min over "
            f"{TIMING_REPS} reps: {t_sup:.3f}s vs {t_bare:.3f}s; "
            "SERVICE_SMOKE_OVERHEAD overrides)")
    return {
        "n_peers": n, "rounds": rounds, "segment_len": seg,
        "bare_rounds_per_sec": round(rounds / t_bare, 2),
        "supervised_rounds_per_sec": round(rounds / t_sup, 2),
        "durable_rounds_per_sec": round(rounds / t_durable, 2),
        "overhead_frac": round(overhead, 4),
        "checkpoint_cost_frac": round(t_durable / t_sup - 1.0, 4),
    }


def check_census(failures: list) -> dict:
    """The service loop adds zero device ops when probes are off: the
    chaos-off compiled census must equal the on-image baseline — the
    chaos_report census leg, reused verbatim."""
    from chaos_report import check_census as _chaos_census

    census = _chaos_census()
    if not census["equal"]:
        failures.append(
            f"census: chaos-off kernel census {census['total']} != "
            f"on-image baseline {census['on_image']} — the service loop "
            "must add zero device ops when probes are off")
    return census


def emit_artifact(res: dict, control: dict) -> None:
    from go_libp2p_pubsub_tpu.chaos import ChaosConfig
    from go_libp2p_pubsub_tpu.perf.artifacts import (
        BenchRecord,
        chaos_fingerprint,
        dump_record,
        execution_fingerprint,
    )

    ov = res["overhead"]
    rec = BenchRecord(
        metric=(f"service_loop_rounds_per_sec_n{ov['n_peers']}_"
                f"seg{ov['segment_len']}"),
        value=ov["supervised_rounds_per_sec"],
        unit="rounds/s",
        vs_baseline=0.0,
        schema=3,
        fingerprint={
            "chaos": chaos_fingerprint(ChaosConfig(loss_rate=0.1)),
            "execution": execution_fingerprint(
                scan=True, segment_rounds=ov["segment_len"],
                dispatches_per_window=1,
                rounds_per_dispatch=ov["segment_len"]),
            "service": control["service"],
        },
        extras={
            "bare_rounds_per_sec": ov["bare_rounds_per_sec"],
            "durable_rounds_per_sec": ov["durable_rounds_per_sec"],
            "overhead_frac": ov["overhead_frac"],
            "checkpoint_cost_frac": ov["checkpoint_cost_frac"],
            "kill": res["kill"],
            "nan": res["nan"],
        },
    )
    print(dump_record(rec), flush=True)


def check_baseline(root: str, ov: dict) -> list:
    path = os.path.join(root, BASELINE_NAME)
    if not os.path.exists(path) or os.environ.get("SERVICE_SMOKE_UPDATE"):
        return []
    with open(path) as f:
        base = json.load(f)
    if (int(base.get("n_peers", ov["n_peers"])) != ov["n_peers"]
            or int(base.get("rounds", ov["rounds"])) != ov["rounds"]
            or int(base.get("segment_len", ov["segment_len"]))
            != ov["segment_len"]):
        return []  # reshape run: committed rates are shape-specific
    tol = float(os.environ.get("SERVICE_SMOKE_TOL", DEFAULT_TOL))
    committed = base.get("supervised_rounds_per_sec")
    out = []
    if committed and ov["supervised_rounds_per_sec"] < tol * committed:
        out.append(
            f"supervised rate regressed: "
            f"{ov['supervised_rounds_per_sec']:.1f} < {tol:.2f} x "
            f"committed {committed:.1f} rounds/s ({BASELINE_NAME}; "
            "SERVICE_SMOKE_TOL overrides, SERVICE_SMOKE_UPDATE=1 "
            "rewrites)")
    return out


def write_baseline(root: str, ov: dict) -> str:
    path = os.path.join(root, BASELINE_NAME)
    doc = {
        "schema": 1,
        "note": (
            "supervised-service-loop smoke baseline (scripts/"
            "service_smoke.py); SERVICE_SMOKE_UPDATE=1 rewrites. "
            "supervised_* is the probes+invariants loop with an "
            "end-of-run checkpoint, bare_* the same segmented "
            "WindowRunner without supervision, durable_* the "
            "every-segment checkpoint cadence — all warm, min over "
            "reps on the gate machine. overhead_frac gates at "
            "SERVICE_SMOKE_OVERHEAD (default 0.10); the rate floor at "
            "SERVICE_SMOKE_TOL."),
        **ov,
    }
    with open(path, "w") as f:
        json.dump(doc, f, indent=2)
        f.write("\n")
    return path


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    ap.add_argument("--smoke", action="store_true",
                    help="exit non-zero on any gate failure")
    ap.add_argument("--no-census", action="store_true",
                    help="skip the chaos-off kernel-census leg")
    args = ap.parse_args(argv)

    import tempfile

    import jax

    jax.config.update("jax_platforms", "cpu")
    jax.config.update("jax_default_prng_impl", "unsafe_rbg")
    from go_libp2p_pubsub_tpu.compile_cache import enable_persistent_cache
    from go_libp2p_pubsub_tpu.perf.regress import repo_root

    root = repo_root()
    enable_persistent_cache(os.path.join(root, ".jax_cache"))

    n_ov = int(os.environ.get("SERVICE_SMOKE_N", OVERHEAD_N))
    rounds_ov = int(os.environ.get("SERVICE_SMOKE_ROUNDS",
                                   OVERHEAD_ROUNDS))
    seg_ov = int(os.environ.get("SERVICE_SMOKE_SEG", OVERHEAD_SEG))
    seed = int(os.environ.get("SERVICE_SMOKE_SEED", 0))
    ceiling = float(os.environ.get("SERVICE_SMOKE_OVERHEAD",
                                   DEFAULT_OVERHEAD))

    failures: list = []
    t_gate0 = time.time()
    work = tempfile.mkdtemp(prefix="service_smoke_")
    control = check_control(root, work, t_gate0, failures)
    res = {"work": work}
    if control is not None:
        res["kill"] = check_kill_resume(root, work, control, seed,
                                        failures)
        res["nan"] = check_nan_recovery(root, work, control, failures)
        res["corrupt_fallback"] = check_corrupt_fallback(root, work,
                                                         failures)
    else:
        res["kill"] = res["nan"] = res["corrupt_fallback"] = {}
    res["overhead"] = check_overhead(n_ov, rounds_ov, seg_ov, failures,
                                     ceiling)
    if not args.no_census:
        res["census"] = check_census(failures)
        if res["census"].get("seeded"):
            print("service-smoke NOTE: on-image census baseline was "
                  "seeded by this run", file=sys.stderr)
    if control is not None:
        emit_artifact(res, control)
    failures += check_baseline(root, res["overhead"])
    if os.environ.get("SERVICE_SMOKE_UPDATE") and not failures:
        print(f"wrote {write_baseline(root, res['overhead'])}")

    summary = {"service_smoke": "PASS" if not failures else "FAIL",
               **{k: v for k, v in res.items() if k != "work"},
               "failures": failures}
    if args.smoke and failures:
        for f in failures:
            print(f"service-smoke FAIL: {f}", file=sys.stderr)
        print(json.dumps(summary))
        return 1
    print(json.dumps(summary))
    return 0


if __name__ == "__main__":
    sys.exit(main())
