#!/usr/bin/env python
"""Invariant oracle gate (``make oracle-smoke``; docs/DESIGN.md §12).

Runs the registered safety/liveness properties (oracle/invariants.py —
the machine-checkable clauses of the ACL2s GossipSub verification,
arXiv:2311.08859, and the FloodSub correctness formalization,
arXiv:2507.19013) inside the repo's canonical degraded-network bands
and asserts the plane's whole contract:

  1. **conformance** — every applicable property passes on:
     (a) the chaos-smoke 60%-loss flap band (S=8, one vmapped
         program; safety properties live, delivery-liveness vacuous by
         the due contract — the flap generator never goes quiet);
     (b) the same generator through the phase engine's stacked
         coalesced wire path (r=4, checks at phase boundaries);
     (c) the partition/heal scenario (S=8): degree bounds suspend for
         the declared grace window and must hold again after it, and
         partition-era in-mcache messages are delivery-due after the
         post-heal deadline — the papers' heal-liveness clause;
     (d) a QUIET cell (loss off, S=8, gossipsub + floodsub) where the
         fresh-publish eventual-delivery clause is non-vacuous
         end-to-end.
  2. **one compile, zero host transfers** — the quiet cell's whole run
     window executes under ``jax.transfer_guard('disallow')`` (due
     rows precomputed to device, violation masks accumulate on
     device), and both the lifted step and the invariant checker
     compile exactly once per cell (cache-size sentinels).
  3. **overhead ceiling** — warm-vs-warm on the flap cell, same build
     with and without the hook: checking every
     ``check_every`` dispatches must cost no more than
     ORACLE_SMOKE_OVERHEAD (default 0.10 = 10%).
  4. **elision / census** — invariants are observers: the engine
     programs are untouched, pinned by the chaos-off compiled-HLO
     kernel census equaling the committed PERF_SMOKE baseline (the
     census helper itself now hard-fails under the wrong PRNG impl —
     the known 376-vs-393 threefry confound).

``ORACLE_SMOKE_UPDATE=1`` rewrites the committed ORACLE_SMOKE.json
baseline (overhead + property-catalog sentinel) from this run. CPU-only
by contract, bench PRNG (unsafe_rbg), like the other smoke gates.
"""

from __future__ import annotations

import argparse
import json
import os
import sys

_here = os.path.dirname(os.path.abspath(__file__))
sys.path.insert(0, os.path.dirname(_here))  # repo root
if _here not in sys.path:
    sys.path.insert(1, _here)

import numpy as np  # noqa: E402

BASELINE_NAME = "ORACLE_SMOKE.json"
#: warm-vs-warm slowdown ceiling for the invariants-on run
DEFAULT_OVERHEAD = 0.10
TIMING_REPS = 3
QUIET_ROUNDS = 48
QUIET_PUB_AT = (8, 11)   # publish rounds [lo, hi) — after mesh warmup
QUIET_WINDOW = 12        # delivery window W for the quiet cells


def _fmt_report(rep) -> str:
    vio = rep.violations(limit=8)
    return (f"{rep.violated}/{rep.checked} property evaluations failed "
            f"(first: {vio})")


def _cell_failures(name: str, out: dict, failures: list) -> None:
    """Fold one chaos_report cell's invariant results into failures."""
    rep = out.get("invariants")
    if rep is None:
        failures.append(f"{name}: cell ran without the invariant hook")
        return
    if not rep.all_ok:
        failures.append(f"{name}: {_fmt_report(rep)}")
    if out.get("invariant_compiles") not in (-1, 1):
        failures.append(
            f"{name}: the checked window compiled "
            f"{out.get('invariant_compiles')} times across the run "
            "(expected exactly 1 — the checker is folded into the "
            "window program)")
    if out.get("dispatches") not in (None, 1):
        failures.append(
            f"{name}: executed as {out.get('dispatches')} dispatches "
            "(expected ONE whole-run window)")


def run_quiet_cell(n: int, seeds: int, seed: int, engine: str) -> dict:
    """The quiet (loss-free) conformance cell: publishes after mesh
    warmup, the whole run declared QUIET, so the fresh-publish
    eventual-delivery clause is due — and checked — for every message.
    The run window executes under ``transfer_guard('disallow')``; every
    input (args, due rows) is materialized on device beforehand."""
    import jax
    import jax.numpy as jnp

    from go_libp2p_pubsub_tpu import ensemble, graph
    from go_libp2p_pubsub_tpu.config import PeerScoreThresholds
    from go_libp2p_pubsub_tpu.models.gossipsub import (
        GossipSubConfig,
        GossipSubState,
        make_gossipsub_step,
    )
    from go_libp2p_pubsub_tpu.oracle import invariants as oracle_inv
    from go_libp2p_pubsub_tpu.state import Net, SimState

    from chaos_report import _flap_params, _score_params

    s = int(seeds)
    rounds = QUIET_ROUNDS
    topo = graph.random_connect(n, d=4, seed=seed)
    subs = graph.subscribe_all(n, 1)
    net = Net.build(topo, subs)
    rng = np.random.default_rng(seed)
    width = 4
    po = np.full((rounds, width), -1, np.int32)
    po[QUIET_PUB_AT[0]:QUIET_PUB_AT[1]] = rng.integers(
        0, n, size=(QUIET_PUB_AT[1] - QUIET_PUB_AT[0], width))
    pt = np.zeros((rounds, width), np.int32)
    pv = np.ones((rounds, width), bool)

    if engine == "gossipsub":
        sp = _score_params()
        cfg = GossipSubConfig.build(_flap_params(), PeerScoreThresholds(),
                                    score_enabled=True)
        st0 = GossipSubState.init(net, 64, cfg, score_params=sp, seed=seed)
        step = make_gossipsub_step(cfg, net, score_params=sp)
        ens = ensemble.lift_step(step)
    elif engine == "floodsub":
        cfg = None
        st0 = SimState.init(n, 64, seed=seed, k=net.max_degree)
        ens = ensemble.lift_floodsub(net)
    else:
        raise ValueError(f"quiet cell has no {engine!r} build")

    # round 14: the checks are FOLDED into the one window program
    # (oracle.ScanInvariants + ensemble.run_window) — the whole quiet
    # cell is a single XLA dispatch, checker included
    spec = oracle_inv.ScanInvariants(
        engine, net, cfg,
        oracle_inv.InvariantConfig(check_every=4,
                                   delivery_window=QUIET_WINDOW),
        due_fn=lambda tick: oracle_inv.due_vector(quiet=(0, rounds)),
    )
    # everything the window consumes goes to device BEFORE the guard
    args = [(ensemble.tile(po[i], s), ensemble.tile(pt[i], s),
             ensemble.tile(pv[i], s)) for i in range(rounds)]
    states = ensemble.batch_states(st0, s)
    spec.precompute(rounds)
    with jax.transfer_guard("disallow"):
        run = ensemble.run_window(ens, states, lambda i: args[i], rounds,
                                  invariants=spec)
    rep = run.invariant_report
    # non-vacuity: the due clause must actually have covered messages
    births = np.asarray(
        (run.states.core if hasattr(run.states, "core")
         else run.states).msgs.birth)
    n_due = int(((births >= 0)
                 & (births + QUIET_WINDOW <= rounds)).sum())
    return {
        "engine": engine,
        "report": rep,
        "step_compiles": run.compiles,
        "dispatches": run.dispatches,
        "n_due_messages": n_due,
    }


def measure_overhead(n: int, loss: float, rounds: int, seeds: int,
                     seed: int) -> dict:
    """Warm-vs-warm flap cell, identical build, with vs without the
    invariant hook (the telemetry-smoke timing pattern: state builds
    and compiles outside the window, min over TIMING_REPS)."""
    from go_libp2p_pubsub_tpu import ensemble, graph
    from go_libp2p_pubsub_tpu.chaos import ChaosConfig
    from go_libp2p_pubsub_tpu.config import PeerScoreThresholds
    from go_libp2p_pubsub_tpu.models.gossipsub import (
        GossipSubConfig,
        GossipSubState,
        make_gossipsub_step,
    )
    from go_libp2p_pubsub_tpu.oracle import invariants as oracle_inv
    from go_libp2p_pubsub_tpu.state import Net

    from chaos_report import _flap_params, _publish_schedule, _score_params

    s = int(seeds)
    topo = graph.random_connect(n, d=4, seed=seed)
    subs = graph.subscribe_all(n, 1)
    net = Net.build(topo, subs)
    rng = np.random.default_rng(seed)
    po, pt, pv = _publish_schedule(rng, n, rounds, pub_rounds=3)
    sp = _score_params()
    cfg = GossipSubConfig.build(_flap_params(), PeerScoreThresholds(),
                                score_enabled=True,
                                chaos=ChaosConfig(loss_rate=loss))
    st0 = GossipSubState.init(net, 64, cfg, score_params=sp, seed=seed)
    step = make_gossipsub_step(cfg, net, score_params=sp)
    ens = ensemble.lift_step(step)
    args = [(ensemble.tile(po[i], s), ensemble.tile(pt[i], s),
             ensemble.tile(pv[i], s)) for i in range(rounds)]

    # ONE WindowRunner per side, reused across reps: a fresh runner per
    # rep would re-trace its window jit inside the timed loop and read
    # as bogus overhead (compile ~seconds; the window dispatches in ms)
    spec = oracle_inv.ScanInvariants(
        "gossipsub", net, cfg,
        oracle_inv.InvariantConfig(check_every=8))
    spec.precompute(rounds)
    run_on = ensemble.WindowRunner(ens, rounds, invariants=spec)
    run_off = ensemble.WindowRunner(ens, rounds)

    def window(with_hook: bool):
        runner = run_on if with_hook else run_off
        return runner.run(ensemble.batch_states(st0, s), lambda i: args[i])

    window(True)          # warm both window programs
    window(False)
    # interleave the reps so slow-box drift hits both sides equally;
    # keep only (seconds, report) — holding whole EnsembleRuns would
    # pin every rep's batched state tree on device for the loop
    pairs = []
    for _ in range(TIMING_REPS):
        on = window(True)
        pairs.append((on.seconds, on.invariant_report, window(False).seconds))
    t_on = min(p[0] for p in pairs)
    t_off = min(p[2] for p in pairs)
    return {
        # the last timed rep's masks (each windowed rep carries its own)
        "all_ok": pairs[-1][1].all_ok,
        "t_on": t_on,
        "t_off": t_off,
        "overhead_frac": round(t_on / t_off - 1.0, 4),
        "rate_on": round(s * rounds / t_on, 2),
        "rate_off": round(s * rounds / t_off, 2),
    }


def emit_artifact(reports: dict, seeds: int) -> dict:
    """One schema-v3 line carrying the ``invariants`` block; round-trip
    checked (and the legacy default asserted) through perf.artifacts."""
    from go_libp2p_pubsub_tpu.perf.artifacts import (
        INVARIANTS_OFF,
        BenchRecord,
        chaos_fingerprint,
        dump_record,
        ensemble_fingerprint,
        record_from_line,
    )

    checked = sum(r.checked for r in reports.values())
    violated = sum(r.violated for r in reports.values())
    flap = reports["flap"]
    rec = BenchRecord(
        metric="oracle_invariant_conformance",
        value=round(1.0 - (violated / checked if checked else 0.0), 6),
        unit="ratio",
        vs_baseline=0.0,
        schema=3,
        fingerprint={"chaos": chaos_fingerprint(),
                     "ensemble": ensemble_fingerprint(seeds)},
        extras={"cells": {k: {"checked": r.checked, "violated": r.violated}
                          for k, r in reports.items()}},
        invariants_raw=flap.artifact_block(),
    )
    line = dump_record(rec)
    print(line, flush=True)
    errors = []
    back = record_from_line(json.loads(line))
    if not back.invariants.get("enabled") or (
            back.invariants.get("properties") != list(flap.names)):
        errors.append("artifact: invariants block lost on round-trip")
    legacy = record_from_line({"metric": "x", "value": 1.0})
    if legacy.invariants != INVARIANTS_OFF:
        errors.append("artifact: legacy line did not read back "
                      "INVARIANTS_OFF")
    return {"record": rec, "errors": errors}


def check_baseline(root: str, res: dict) -> list[str]:
    path = os.path.join(root, BASELINE_NAME)
    if not os.path.exists(path) or os.environ.get("ORACLE_SMOKE_UPDATE"):
        return []
    with open(path) as f:
        base = json.load(f)
    out = []
    committed = base.get("properties") or []
    missing = [p for p in committed if p not in res["properties"]]
    if missing:
        out.append(
            f"property catalog shrank: committed properties {missing} are "
            f"no longer registered ({BASELINE_NAME}; deregistering a "
            "verified property needs an explicit ORACLE_SMOKE_UPDATE=1 "
            "rebaseline)")
    return out


def write_baseline(root: str, res: dict) -> str:
    path = os.path.join(root, BASELINE_NAME)
    doc = {
        "schema": 1,
        "note": ("invariant-oracle smoke baseline (scripts/"
                 "invariant_report.py); ORACLE_SMOKE_UPDATE=1 rewrites. "
                 "rate_* are S x rounds aggregate sim-rounds/s on the "
                 "gate machine; properties is the registered catalog "
                 "sentinel (a property can only leave it deliberately)."),
        **{k: res[k] for k in (
            "n_peers", "rounds", "seeds", "check_every", "n_properties",
            "properties", "overhead_frac", "rate_on", "rate_off")},
    }
    with open(path, "w") as f:
        json.dump(doc, f, indent=2)
        f.write("\n")
    return path


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    ap.add_argument("--smoke", action="store_true",
                    help="exit non-zero on any gate failure")
    ap.add_argument("--n", type=int, default=None)
    ap.add_argument("--seeds", type=int, default=None)
    ap.add_argument("--seed", type=int, default=0)
    ap.add_argument("--no-census", action="store_true",
                    help="skip the chaos-off kernel-census gate")
    args = ap.parse_args(argv)

    # CPU-only, bench PRNG, persistent compile cache — the chaos-smoke
    # gate policy (the census is PRNG-impl-dependent: 393 under
    # unsafe_rbg, 376 under threefry; perf/profile.py hard-fails on the
    # wrong impl now)
    import jax

    jax.config.update("jax_platforms", "cpu")
    jax.config.update("jax_default_prng_impl", "unsafe_rbg")
    from go_libp2p_pubsub_tpu.compile_cache import enable_persistent_cache
    from go_libp2p_pubsub_tpu.perf.regress import repo_root

    root = repo_root()
    enable_persistent_cache(os.path.join(root, ".jax_cache"))

    from chaos_report import (
        FLAP_LOSS,
        FLAP_ROUNDS,
        SMOKE_N,
        SMOKE_SEEDS,
        check_census,
        run_flap,
        run_partition,
    )

    n = args.n or SMOKE_N
    seeds = args.seeds or SMOKE_SEEDS
    failures: list[str] = []
    reports = {}

    # (a) the 60%-loss flap band, per-round engine
    flap = run_flap(n=n, loss=FLAP_LOSS, rounds=FLAP_ROUNDS, seed=args.seed,
                    seeds=seeds, full=False, invariants=True)
    _cell_failures("flap", flap, failures)
    reports["flap"] = flap["invariants"]

    # (b) the same generator through the phase engine (stacked wire)
    flap_phase = run_flap(n=n, loss=FLAP_LOSS, rounds=FLAP_ROUNDS,
                          seed=args.seed, rounds_per_phase=4, seeds=seeds,
                          full=False, invariants=True)
    _cell_failures("flap-phase4", flap_phase, failures)
    reports["flap_phase4"] = flap_phase["invariants"]

    # (c) partition/heal: grace + heal-liveness due clauses live
    part = run_partition(n=n, seed=args.seed + 1, seeds=seeds,
                         invariants=True)
    _cell_failures("partition", part, failures)
    reports["partition"] = part["invariants"]

    # (d) quiet cells: eventual delivery non-vacuous, guarded window
    for engine in ("gossipsub", "floodsub"):
        q = run_quiet_cell(n, seeds, args.seed + 2, engine)
        rep = q["report"]
        reports[f"quiet_{engine}"] = rep
        if not rep.all_ok:
            failures.append(f"quiet-{engine}: {_fmt_report(rep)}")
        if q["step_compiles"] not in (-1, 1):
            failures.append(
                f"quiet-{engine}: the scan window compiled "
                f"{q['step_compiles']} times under the guarded run "
                "(expected exactly 1 — step AND folded checker are one "
                "program)")
        if q["dispatches"] != 1:
            failures.append(
                f"quiet-{engine}: the cell executed as "
                f"{q['dispatches']} dispatches (expected ONE whole-run "
                "window dispatch)")
        if q["n_due_messages"] <= 0:
            failures.append(
                f"quiet-{engine}: no message was delivery-due — the "
                "liveness clause ran vacuously in the cell built to "
                "exercise it")

    # overhead ceiling (warm-vs-warm, flap shape)
    ov = measure_overhead(n, FLAP_LOSS, FLAP_ROUNDS, seeds, args.seed)
    ceiling = float(os.environ.get("ORACLE_SMOKE_OVERHEAD",
                                   DEFAULT_OVERHEAD))
    if not ov["all_ok"]:
        failures.append("overhead cell recorded property violations — "
                        "the timed run must be conformant too")
    if ov["overhead_frac"] > ceiling:
        failures.append(
            f"overhead: invariant checking ran "
            f"{100 * ov['overhead_frac']:.1f}% slower than the same run "
            f"without the hook (ceiling {100 * ceiling:.0f}%; "
            f"{ov['t_on']:.3f}s vs {ov['t_off']:.3f}s)")

    # elision: the engine programs are untouched — chaos-off census
    # still equals the on-image baseline (the committed PERF_SMOKE
    # value is an informational pin; round-14 image portability)
    if not args.no_census:
        census = check_census()
        print(json.dumps({"chaos_off_kernel_census": census}), flush=True)
        if not census["equal"]:
            failures.append(
                f"chaos-off kernel census {census['total']} != on-image "
                f"baseline {census['on_image']} — the oracle plane must "
                "not touch the engine programs (committed pin "
                f"{census['committed']} is informational)")

    art = emit_artifact(reports, seeds)
    failures += art["errors"]

    flap_rep = reports["flap"]
    res = {
        "n_peers": n,
        "rounds": FLAP_ROUNDS,
        "seeds": seeds,
        "check_every": flap_rep.check_every,
        "n_properties": len(flap_rep.names),
        "properties": list(flap_rep.names),
        "overhead_frac": ov["overhead_frac"],
        "rate_on": ov["rate_on"],
        "rate_off": ov["rate_off"],
    }
    failures += check_baseline(root, res)
    if os.environ.get("ORACLE_SMOKE_UPDATE") and not failures:
        print(f"wrote {write_baseline(root, res)}")

    summary = {
        "oracle_smoke": "PASS" if not failures else "FAIL",
        "cells": {k: {"checked": r.checked, "violated": r.violated,
                      "n_checks": r.n_checks}
                  for k, r in reports.items()},
        "n_properties": res["n_properties"],
        "overhead_frac": res["overhead_frac"],
        "failures": failures,
    }
    if args.smoke and failures:
        for f in failures:
            print(f"oracle-smoke FAIL: {f}", file=sys.stderr)
        print(json.dumps(summary))
        return 1
    print(json.dumps(summary))
    return 0


if __name__ == "__main__":
    sys.exit(main())
