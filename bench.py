"""Benchmark: GossipSub v1.1 heartbeat-tick throughput at scale on TPU.

North-star metric (BASELINE.json): simulated heartbeat-ticks/sec for a
100k-peer GossipSub v1.1 mesh with live scoring; target >= 10_000 ticks/s
on a v5e-8. This runs on however many chips are visible (the driver runs
it on one), with the peer axis sharded across them.

Prints ONE JSON line — a perf.artifacts SCHEMA V2 record: the v1 fields
{"metric", "value", "unit", "vs_baseline", ...} plus "schema": 2 and a
"fingerprint" object (config knobs incl. the score-weight elision flags,
cadence, shard shape, engine gating) so the artifact alone says what was
measured. The unit of both the value and the 10k target is SIMULATED
DELIVERY ROUNDS (hop-quanta) per wall second — see BASELINE.md "The tick
<-> delivery-round equivalence rule". In phase mode (the default, r=8)
the line also carries `heartbeats_per_sec` (= value / r, the control
cadence — NOT the headline unit) and `continuity_r1_ticks_per_sec` (the
rounds-1..3 heavy-tick engine re-measured in the same session,
BENCH_CONTINUITY=0 to skip), so the artifact is cross-round comparable.

The workload builder and measurement loop live in
go_libp2p_pubsub_tpu/perf/sweep.py (this file is the driver-facing CLI);
``build_bench`` stays importable from here for scripts/tests.
"""

from __future__ import annotations

import json
import math
import os

from go_libp2p_pubsub_tpu.perf.sweep import build_bench  # noqa: F401 — re-export


def main():
    import jax

    # the image's sitecustomize pins the axon TPU platform; BENCH_PLATFORM
    # overrides it through jax.config (env vars are clobbered at startup)
    plat = os.environ.get("BENCH_PLATFORM")
    if plat:
        jax.config.update("jax_platforms", plat)
    # rbg PRNG: the sim's random draws (selection noise, gater bernoulli)
    # need statistical quality, not cryptographic strength — threefry's
    # custom-calls profiled ~1.1 ms/tick on the eth2 config. RNG parity
    # with the reference is impossible either way (survey §7 hard-part d);
    # comparisons are distributional. BENCH_PRNG overrides the impl
    # (empty string = keep jax's threefry default).
    prng = os.environ.get("BENCH_PRNG", "unsafe_rbg")
    if prng:
        jax.config.update("jax_default_prng_impl", prng)

    from go_libp2p_pubsub_tpu.perf.artifacts import NORTH_STAR_RATE, SCHEMA_VERSION
    from go_libp2p_pubsub_tpu.perf.sweep import (
        measure_rate,
        metric_name,
        workload_fingerprint,
    )

    config = os.environ.get("BENCH_CONFIG", "default")
    default_n = 50_000 if config == "sybil" else 100_000
    n_peers = int(os.environ.get("BENCH_N", default_n))
    msg_slots = int(os.environ.get("BENCH_M", 64))
    # BENCH_PHASE_R: rounds per phase. The DEFAULT headline (round 4, per
    # the round-3 review's "make the reference-faithful cadence the
    # first-class bench") is the multi-round phase engine at r=8 —
    # continuous delivery with control/heartbeat every 8 rounds, the
    # reference's own timing shape (1 Hz maintenance against ~100 ms
    # hops, gossipsub.go:1278-1301). BENCH_PHASE_R=1 reproduces the
    # rounds-1..3 heavy-tick metric (delivery + full maintenance every
    # round); BASELINE.md round-4 records both on the same chip.
    rounds_per_phase = int(os.environ.get("BENCH_PHASE_R", 8))
    heartbeat_every = int(
        os.environ.get("BENCH_HB", rounds_per_phase if rounds_per_phase > 1 else 1)
    )
    group = math.lcm(heartbeat_every, rounds_per_phase)
    # long segments amortize the tunneled platform's per-call dispatch +
    # readback (~190 ms/segment observed): 100-round segments measured ~37%
    # below the device-limited rate, 1600-round segments within ~2% of it
    seg = int(os.environ.get("BENCH_ROUNDS", 1600))
    # the fixed-schedule scan groups lcm(he, r) rounds per iteration; keep
    # the executed round count and the rate denominator in sync
    seg -= seg % group
    unroll_env = os.environ.get("BENCH_UNROLL")
    unroll = int(unroll_env) if unroll_env else None

    res = measure_rate(config, n_peers, msg_slots, heartbeat_every,
                       rounds_per_phase, seg, reps=3, unroll=unroll)
    if res is None:
        print(json.dumps({"metric": "error", "value": 0, "unit": "", "vs_baseline": 0}))
        return
    value, n_peers, unroll_used = res

    out = {
        "schema": SCHEMA_VERSION,
        "metric": metric_name(config, n_peers, rounds_per_phase),
        "value": round(value, 2),
        "unit": "ticks/s" if rounds_per_phase == 1 else "delivery-rounds/s",
        "vs_baseline": round(value / NORTH_STAR_RATE, 4),
    }
    if rounds_per_phase > 1:
        # the derived control-cadence rate, so nobody reads the headline
        # as heartbeats/s: the heartbeat fires every heartbeat_every
        # rounds (BENCH_HB, which defaults to r but may differ)
        out["heartbeats_per_sec"] = round(value / heartbeat_every, 2)
        out["unit_note"] = (
            "value counts simulated delivery rounds (hop-quanta)/s; "
            "control runs once per %d rounds, heartbeat once per %d — "
            "see BASELINE.md equivalence rule"
            % (rounds_per_phase, heartbeat_every)
        )
        if os.environ.get("BENCH_CONTINUITY", "1") == "1":
            # the rounds-1..3 heavy tick (control every round), measured
            # in the same session for cross-round continuity. Full-length
            # segments: 800-round ones measured ~6% below the
            # device-limited rate (the dispatch-amortization bias the
            # round-1 notes quantify), which would misread as a
            # continuity regression
            cont = measure_rate(config, n_peers, msg_slots, 1, 1, seg, reps=2)
            if cont is not None:
                out["continuity_r1_ticks_per_sec"] = round(cont[0], 2)
                # the r=1 build has different buffer shapes and may OOM-
                # fall back to a smaller N than the headline — record the
                # size the continuity rate was actually measured at
                out["continuity_r1_n"] = cont[1]
    # the self-description (ADVICE round 5: the artifact itself must
    # record the elision-enabling config, not just BASELINE.md prose)
    out["fingerprint"] = workload_fingerprint(
        config, n_peers, msg_slots, heartbeat_every, rounds_per_phase,
        seg_rounds=seg, unroll=unroll_used,
    )
    print(json.dumps(out))


if __name__ == "__main__":
    main()
