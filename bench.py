"""Benchmark: GossipSub v1.1 heartbeat-tick throughput at scale on TPU.

North-star metric (BASELINE.json): simulated heartbeat-ticks/sec for a
100k-peer GossipSub v1.1 mesh with live scoring; target >= 10_000 ticks/s
on a v5e-8. This runs on however many chips are visible (the driver runs
it on one), with the peer axis sharded across them.

Prints ONE JSON line: {"metric", "value", "unit", "vs_baseline", ...}
where vs_baseline is value / 10_000 (the north-star target rate). The
unit of both the value and the target is SIMULATED DELIVERY ROUNDS
(hop-quanta) per wall second — see BASELINE.md "The tick <-> delivery-
round equivalence rule". In phase mode (the default, r=8) the line also
carries `heartbeats_per_sec` (= value / r, the control cadence — NOT the
headline unit) and `continuity_r1_ticks_per_sec` (the rounds-1..3
heavy-tick engine re-measured in the same session, BENCH_CONTINUITY=0
to skip), so the artifact is self-describing and cross-round comparable.
"""

from __future__ import annotations

import dataclasses
import json
import os
import sys
import time

import numpy as np


def build_bench(n_peers: int, msg_slots: int, seed: int = 0, config: str = "default",
                heartbeat_every: int = 1, rounds_per_phase: int = 1):
    """Build (state, step) for a BENCH_CONFIG:

    default — GossipSub v1.1, single topic, live scoring (the BASELINE.json
              north-star workload the driver measures)
    eth2    — 100k-peer Eth2 attestation-subnet geometry: 64 topics, each
              peer subscribed to 2 random subnets (BASELINE.json config #5).
              A THROUGHPUT workload, not a coverage one: over the banded
              ring-lattice adjacency a topic's 3%-density induced subgraph
              fragments into segments (1-D lattices don't percolate under
              dilution), so publishes propagate within their segment only —
              coverage claims live in the parity suite's random-graph
              configs (PARITY.md eth2 row: reachability structurally
              attributed)
    sybil   — 20% sybil attackers (control-plane-only peers that never
              forward data), peer gater + deficit scoring enabled
              (BASELINE.json config #4; default BENCH_N 50k)

    ``rounds_per_phase`` > 1 builds the multi-round phase engine
    (models/gossipsub_phase.py): r delivery rounds per dispatch, control
    once per phase — the reference's continuous-delivery / 1 Hz-heartbeat
    timing shape (gossipsub.go:1278-1301).
    """
    import jax
    import jax.numpy as jnp

    from go_libp2p_pubsub_tpu import graph
    from go_libp2p_pubsub_tpu.config import (
        GossipSubParams,
        PeerGaterParams,
        PeerScoreParams,
        PeerScoreThresholds,
        TopicScoreParams,
    )
    from go_libp2p_pubsub_tpu.models.gossipsub import (
        GossipSubConfig,
        GossipSubState,
        make_gossipsub_step,
    )
    from go_libp2p_pubsub_tpu.models.gossipsub_phase import (
        make_gossipsub_phase_step,
    )
    from go_libp2p_pubsub_tpu.parallel import make_mesh, shard_state
    from go_libp2p_pubsub_tpu.state import Net

    # bounded-degree topology (K stays small and static for the compiler)
    topo = graph.ring_lattice(n_peers, d=8)  # degree 16, K=16
    if config == "eth2":
        n_topics = 64  # attestation subnet count
        subs = graph.subscribe_random(n_peers, n_topics=n_topics,
                                      topics_per_peer=2, seed=seed)
    else:
        n_topics = 1
        subs = graph.subscribe_all(n_peers, 1)
    net = Net.build(topo, subs)

    params = dataclasses.replace(GossipSubParams(), flood_publish=False)
    if config == "sybil":
        # deficit penalties on: the sybils are what scoring must catch
        tp = TopicScoreParams(
            mesh_message_deliveries_weight=-0.5,
            mesh_message_deliveries_threshold=4.0,
            mesh_message_deliveries_activation=10.0,
            mesh_message_deliveries_window=2.0,
        )
    else:
        tp = TopicScoreParams(
            mesh_message_deliveries_weight=0.0,  # deficit off: honest net
            mesh_failure_penalty_weight=0.0,
            # honest net continued: every publish is valid (pv all-True),
            # so P4 provably never fires — zero weight lets the phase
            # engine's static elision drop the [N,K,W] trans-accumulation
            # plane, the second of the two OR+store passes the round-4
            # elision note identified (sybil keeps the default weight:
            # its adversary vector is what P4 exists to catch)
            invalid_message_deliveries_weight=0.0,
        )
    sp = PeerScoreParams(
        topics={t: tp for t in range(n_topics)},
        skip_app_specific=True,
        behaviour_penalty_weight=-1.0,
        behaviour_penalty_threshold=1.0,
        behaviour_penalty_decay=0.9,
    )
    gater = PeerGaterParams() if config == "sybil" else None
    adversary = None
    if config == "sybil":
        rng = np.random.default_rng(seed)
        adversary = rng.random(n_peers) < 0.2
    cfg = GossipSubConfig.build(
        params, PeerScoreThresholds(), score_enabled=True, gater_params=gater,
        validation_capacity=8 if config == "sybil" else 0,
        heartbeat_every=heartbeat_every,
    )
    # tracer-detached configuration (tracing is opt-in in the reference):
    # no aggregate event counters; no fanout slots when every peer
    # subscribes the topic (fanout provably can't occur in that workload)
    cfg = dataclasses.replace(
        cfg, count_events=False,
        fanout_slots=0 if config != "eth2" else cfg.fanout_slots,
    )
    st = GossipSubState.init(net, msg_slots, cfg, score_params=sp, seed=seed)
    if rounds_per_phase > 1:
        step = make_gossipsub_phase_step(
            cfg, net, rounds_per_phase, score_params=sp, gater_params=gater,
            adversary_no_forward=adversary,
        )
    else:
        step = make_gossipsub_step(cfg, net, score_params=sp, gater_params=gater,
                                   adversary_no_forward=adversary,
                                   static_heartbeat=heartbeat_every > 1)

    n_dev = len(jax.devices())
    if n_dev > 1 and n_peers % n_dev == 0:
        mesh = make_mesh(n_dev)
        st = shard_state(st, mesh, n_peers)

    # honest peers only as publish origins: a sybil origin would silently
    # drop its own publish (adversary peers never transmit message data)
    honest = np.flatnonzero(~adversary) if adversary is not None else None
    return st, step, n_topics, honest


def main():
    import jax

    # the image's sitecustomize pins the axon TPU platform; BENCH_PLATFORM
    # overrides it through jax.config (env vars are clobbered at startup)
    plat = os.environ.get("BENCH_PLATFORM")
    if plat:
        jax.config.update("jax_platforms", plat)
    # rbg PRNG: the sim's random draws (selection noise, gater bernoulli)
    # need statistical quality, not cryptographic strength — threefry's
    # custom-calls profiled ~1.1 ms/tick on the eth2 config. RNG parity
    # with the reference is impossible either way (survey §7 hard-part d);
    # comparisons are distributional. BENCH_PRNG overrides the impl
    # (empty string = keep jax's threefry default).
    prng = os.environ.get("BENCH_PRNG", "unsafe_rbg")
    if prng:
        jax.config.update("jax_default_prng_impl", prng)
    import jax.numpy as jnp

    config = os.environ.get("BENCH_CONFIG", "default")
    default_n = 50_000 if config == "sybil" else 100_000
    n_peers = int(os.environ.get("BENCH_N", default_n))
    msg_slots = int(os.environ.get("BENCH_M", 64))
    # BENCH_PHASE_R: rounds per phase. The DEFAULT headline (round 4, per
    # the round-3 review's "make the reference-faithful cadence the
    # first-class bench") is the multi-round phase engine at r=8 —
    # continuous delivery with control/heartbeat every 8 rounds, the
    # reference's own timing shape (1 Hz maintenance against ~100 ms
    # hops, gossipsub.go:1278-1301). BENCH_PHASE_R=1 reproduces the
    # rounds-1..3 heavy-tick metric (delivery + full maintenance every
    # round); BASELINE.md round-4 records both on the same chip.
    rounds_per_phase = int(os.environ.get("BENCH_PHASE_R", 8))
    heartbeat_every = int(
        os.environ.get("BENCH_HB", rounds_per_phase if rounds_per_phase > 1 else 1)
    )
    import math

    group = math.lcm(heartbeat_every, rounds_per_phase)
    # long segments amortize the tunneled platform's per-call dispatch +
    # readback (~190 ms/segment observed): 100-round segments measured ~37%
    # below the device-limited rate, 1600-round segments within ~2% of it
    seg = int(os.environ.get("BENCH_ROUNDS", 1600))
    # the fixed-schedule scan groups lcm(he, r) rounds per iteration; keep
    # the executed round count and the rate denominator in sync
    seg -= seg % group
    pubs_per_round = 4

    def measure(n_req, he, r, seg_rounds, reps=3):
        """Build + run one configuration; returns (rate, n_used) or None.

        Tries n_req, halving down to 10k as the OOM fallback."""
        import jax

        group_m = math.lcm(he, r)
        seg_m = seg_rounds - seg_rounds % group_m
        sizes, nn = [n_req], n_req // 2
        while nn >= 10_000:
            sizes.append(nn)
            nn //= 2
        for n in sizes:
            try:
                st, step, n_topics, honest = build_bench(
                    n, msg_slots, config=config, heartbeat_every=he,
                    rounds_per_phase=r,
                )
                # publish schedule [R, P]
                rng = np.random.default_rng(0)
                if honest is not None:
                    po = honest[
                        rng.integers(0, len(honest), size=(seg_m, pubs_per_round))
                    ].astype(np.int32)
                else:
                    po = rng.integers(
                        0, n, size=(seg_m, pubs_per_round)
                    ).astype(np.int32)
                pt = rng.integers(
                    0, n_topics, size=(seg_m, pubs_per_round)
                ).astype(np.int32)
                pv = np.ones((seg_m, pubs_per_round), bool)
                po_j, pt_j, pv_j = jnp.asarray(po), jnp.asarray(pt), jnp.asarray(pv)

                # unroll: adjacent iterations let XLA cancel the carry layout
                # conversions the while-loop form pays per tick (profiled ~35%
                # of device time); 4 rounds is the per-round knee, and phase
                # mode gains another ~7-8% from unrolling TWO phases per scan
                # iteration (r=8: 1200 -> 1296, r=16: 1365 -> 1460 rounds/s,
                # round-4 measurements)
                unroll = int(os.environ.get(
                    "BENCH_UNROLL", 2 * group_m if r > 1 else 4
                ))
                from go_libp2p_pubsub_tpu.driver import make_scan

                # the schedule-owning scan (driver.make_scan) drives all
                # three builds: per-round, static-heartbeat, and phase
                scan = make_scan(
                    step,
                    heartbeat_every=he,
                    rounds_per_phase=r,
                    static_heartbeat=he > 1 or r > 1,
                    unroll=max(1, unroll // group_m),
                )

                st = scan(st, po_j, pt_j, pv_j)  # compile + warmup
                jax.block_until_ready(st)
                rates = []
                for _ in range(reps):
                    t0 = time.perf_counter()
                    st = scan(st, po_j, pt_j, pv_j)
                    # force a device->host readback inside the timed region:
                    # jax.block_until_ready on the axon remote platform has
                    # been observed to return before execution completes
                    # (async handles report ready), inflating rates ~1000x.
                    # Fetching a scalar that depends on the full step (the
                    # tick counter + a score checksum) is the honest
                    # completion barrier.
                    _ = (int(st.core.tick), float(jnp.sum(st.scores)))
                    dt = time.perf_counter() - t0
                    rates.append(seg_m / dt)
                return max(rates), n
            except Exception as e:  # noqa: BLE001 — smaller N on OOM
                msg = str(e)
                if ("RESOURCE_EXHAUSTED" in msg or "Out of memory" in msg
                        or "exceeds" in msg):
                    continue
                raise
        return None

    res = measure(n_peers, heartbeat_every, rounds_per_phase, seg)
    if res is None:
        print(json.dumps({"metric": "error", "value": 0, "unit": "", "vs_baseline": 0}))
        return
    value, n_peers = res

    tag = "" if config == "default" else f"_{config}"
    if rounds_per_phase > 1:
        # reference-cadence metric: delivery rounds/s with control every
        # r rounds (heartbeat_every = r by default) — the honest
        # comparison to the reference's continuous delivery + 1 Hz
        # heartbeat shape; same 10k north-star denominator. See
        # BASELINE.md "The tick <-> delivery-round equivalence rule":
        # the value counts simulated hop-quanta per second, the same
        # unit the r=1 tick counts and the 10k target is denominated in.
        metric = (
            f"gossipsub_v1.1_delivery_rounds_per_sec_n{n_peers}{tag}"
            f"_phase{rounds_per_phase}"
        )
    else:
        metric = f"gossipsub_v1.1_heartbeat_ticks_per_sec_n{n_peers}{tag}"
    out = {
        "metric": metric,
        "value": round(value, 2),
        "unit": "ticks/s" if rounds_per_phase == 1 else "delivery-rounds/s",
        "vs_baseline": round(value / 10_000.0, 4),
    }
    if rounds_per_phase > 1:
        # the derived control-cadence rate, so nobody reads the headline
        # as heartbeats/s: the heartbeat fires every heartbeat_every
        # rounds (BENCH_HB, which defaults to r but may differ)
        out["heartbeats_per_sec"] = round(value / heartbeat_every, 2)
        out["unit_note"] = (
            "value counts simulated delivery rounds (hop-quanta)/s; "
            "control runs once per %d rounds, heartbeat once per %d — "
            "see BASELINE.md equivalence rule"
            % (rounds_per_phase, heartbeat_every)
        )
        if os.environ.get("BENCH_CONTINUITY", "1") == "1":
            # the rounds-1..3 heavy tick (control every round), measured
            # in the same session for cross-round continuity. Full-length
            # segments: 800-round ones measured ~6% below the
            # device-limited rate (the dispatch-amortization bias the
            # round-1 notes quantify), which would misread as a
            # continuity regression
            cont = measure(n_peers, 1, 1, seg, reps=2)
            if cont is not None:
                out["continuity_r1_ticks_per_sec"] = round(cont[0], 2)
                # the r=1 build has different buffer shapes and may OOM-
                # fall back to a smaller N than the headline — record the
                # size the continuity rate was actually measured at
                out["continuity_r1_n"] = cont[1]
    print(json.dumps(out))


if __name__ == "__main__":
    main()
