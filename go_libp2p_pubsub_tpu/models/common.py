"""Shared delivery engine: one synchronous message-propagation round.

This is the vectorized core of the reference's hot path (survey §3.2/3.3):
router.Publish -> per-peer RPC queues -> reader -> validation -> forward.
All routers share it; they differ only in *which edges carry* a message
(flood: every topic edge, floodsub.go:76-100; gossipsub: mesh/fanout edges;
randomsub: a random subset chosen at publish).

Gather-only dataflow for all N-sized traffic: each receiver j reads its
senders' forward sets at nbr[j,k] and applies edge/topic masks. (The one
deliberate exception is an M-element scatter marking message origins —
M is the tiny message-slot axis, not a peer-sized tensor.) The
transmit tensor `trans[N, K, W]` (packed words) *is* the round's wire
traffic; aggregate popcounts of it produce the SendRPC/RecvRPC trace
counters, and the score engine later consumes it for delivery attribution.
"""

from __future__ import annotations

import os

import jax
import jax.numpy as jnp
from flax import struct

from ..ops import bitset
from ..state import Delivery, MsgTable, Net
from ..trace.events import EV

# opt-in fused Pallas delivery kernel for banded topologies (exact parity
# with the XLA path — tests/test_pallas.py). Off by default: the current
# libtpu's Mosaic pass rejects the packed-word shape casts on real TPU
# (see ops/pallas_delivery.py docstring), so the opt-in runs the kernel in
# interpret mode (set PUBSUB_PALLAS_COMPILE=1 to attempt a real compile on
# a future libtpu). The XLA path stays the production default.
USE_PALLAS = os.environ.get("PUBSUB_PALLAS", "") == "1"

# opt-in fused Pallas kernels for the flat-[E] CSR plane (round 21,
# ops/pallas_csr.py — exact parity with the fused composite,
# tests/test_pallas_csr.py). Same Mosaic caveat and interpret-mode
# gating as PUBSUB_PALLAS; requires a `fused=True` Net (the composite
# and the kernel share the capacity-bounded scan contract).
USE_PALLAS_CSR = os.environ.get("PUBSUB_PALLAS_CSR", "") == "1"


def _pallas_block() -> int:
    return int(os.environ.get("PUBSUB_PALLAS_BLOCK", "2000"))


@struct.dataclass
class RoundInfo:
    """Per-round delivery observables consumed by tracing + scoring.

    With inline validation (val_delay=0) the entry and validated cohorts
    coincide (`recv_new_words is new_words`); with the async-validation
    pipeline, `recv_new_words` is this round's fresh receipts (queue
    admission — the throttle's cohort) while `new_words` is the receipts
    whose validation completed this round (delivery/forwarding/scoring
    cohort, the reference's post-validation publishMessage timing)."""

    trans: jax.Array        # [N, K, W] u32 — words transmitted to j on edge k
    new_words: jax.Array    # [N, W] u32 — receipts validated this round
    new_bits: jax.Array     # [N, M] bool — same, unpacked
    recv_new_words: jax.Array  # [N, W] u32 — first receipts this round
    n_deliver: jax.Array    # i64 — validated receipts of valid messages
    n_reject: jax.Array     # i64 — validated receipts of invalid messages
    n_duplicate: jax.Array  # i64 — arrivals beyond the first per (peer,msg)
    n_rpc: jax.Array        # i64 — total (edge, msg) transmissions
    n_drop: jax.Array = struct.field(default_factory=lambda: jnp.int32(0))
    # ^ transmissions lost to the outbound-queue cap (doDropRPC,
    #   gossipsub.go:1153-1160; comm.go:139-170) — 0 when queue_cap is off


def member_msg_words(member: jax.Array, msg_topic: jax.Array) -> jax.Array:
    """[N, W] packed mask: messages whose topic satisfies member[n, topic]
    (member is an [N, T] bool relation; padding topics (-1) match nothing).

    For wide topic universes this is an MXU matmul rather than an [N, M]
    per-message gather (which profiled ~0.8 ms/round at T=64, N=100k):
    per-topic packed words have disjoint bits — each message slot has
    exactly one topic — so OR equals SUM, and splitting words into bytes
    keeps every partial sum exact in f32 (byte sums of disjoint bits are
    <= 255, far inside the 24-bit mantissa)."""
    n, n_topics = member.shape
    onehot_t = msg_topic[None, :] == jnp.arange(n_topics, dtype=jnp.int32)[:, None]
    tw = bitset.pack(onehot_t)  # [T, W], disjoint bits across T
    if n_topics <= 8:
        # narrow universe: masked OR over T is cheaper than an MXU trip
        contrib = jnp.where(member[:, :, None], tw[None, :, :], jnp.uint32(0))
        return bitset.word_or_reduce(contrib, axis=1)
    w = tw.shape[-1]
    tb = jnp.stack(
        [(tw >> jnp.uint32(8 * i)) & jnp.uint32(0xFF) for i in range(4)], axis=-1
    ).reshape(n_topics, w * 4).astype(jnp.float32)
    jb = jnp.dot(member.astype(jnp.float32), tb)  # [N, W*4]
    jb = jb.astype(jnp.uint32).reshape(n, w, 4)
    return (
        jb[..., 0] | (jb[..., 1] << jnp.uint32(8))
        | (jb[..., 2] << jnp.uint32(16)) | (jb[..., 3] << jnp.uint32(24))
    )


def subscribed_msg_words(net: Net, msgs: MsgTable) -> jax.Array:
    """[N, W] packed mask: messages whose topic peer n subscribes to."""
    return member_msg_words(net.subscribed, msgs.topic)


def origin_msg_words(net: Net, msgs: MsgTable) -> jax.Array:
    """[N, W] packed mask: messages peer n originated (never sent back to the
    origin — the `pid == peer.ID(msg.GetFrom())` check, floodsub.go:87,
    gossipsub.go:1007).

    Each message has exactly one origin, so this is an M-element scatter of
    single-bit words — not an [N, M] one-hot compare+pack (which costs
    N*M work per round just to mark M bits)."""
    n = net.n_peers
    m = msgs.capacity
    w = bitset.n_words(m)
    slot = jnp.arange(m, dtype=jnp.int32)
    upd = jnp.uint32(1) << (slot % 32).astype(jnp.uint32)
    row = jnp.where(msgs.origin >= 0, msgs.origin, n)  # OOB-drop padding
    # distinct bit positions per (row, word) pair make add equivalent to or
    return jnp.zeros((n, w), jnp.uint32).at[row, slot // 32].add(upd, mode="drop")


def pipeline_entry_masks(msg_topic: jax.Array, delay_topic: tuple, v: int) -> jax.Array:
    """[V, W] u32 stage-entry masks for the per-topic validation-latency
    pipeline: a receipt of a topic with delay d enters shift stage V - d,
    so its verdict lands d rounds after arrival (the reference's per-topic
    async validators complete at different times, validation.go:391-438).
    Padding topics (-1) never match a stage — their bits can't arrive."""
    import numpy as np

    dt = jnp.asarray(np.asarray(delay_topic, np.int32))[jnp.clip(msg_topic, 0)]
    stage = jnp.where(msg_topic >= 0, v - dt, -1)  # [M]
    return bitset.pack(stage[None, :] == jnp.arange(v, dtype=jnp.int32)[:, None])


def pipeline_insert(pending_shifted: jax.Array, new_words: jax.Array,
                    msg_topic: jax.Array, delay_topic: tuple | None) -> jax.Array:
    """Insert this round's fresh receipts into the (already shifted)
    pipeline at their per-topic entry stage (stage 0 when uniform)."""
    v = pending_shifted.shape[1]
    if delay_topic is None:
        return pending_shifted.at[:, 0, :].set(
            pending_shifted[:, 0, :] | new_words
        )
    masks = pipeline_entry_masks(msg_topic, delay_topic, v)  # [V, W]
    return pending_shifted | (new_words[:, None, :] & masks[None, :, :])


def delivery_round(
    net: Net,
    msgs: MsgTable,
    dlv: Delivery,
    edge_mask: jax.Array,  # [N, K, W] u32: words edge (j,k) may carry j-ward
    tick: jax.Array,
    forward_mask: jax.Array | None = None,  # [N, W] extra gate on what gets re-forwarded
    count_events: bool = True,
    queue_cap: int = 0,    # per-edge outbound message budget per round
                           # (pubsub.go:240's 32-deep queue); 0 = lossless
    val_delay_topic: tuple | None = None,  # per-topic pipeline delays
                           # (cfg.validation_delay_topic); None = uniform
) -> tuple[Delivery, RoundInfo]:
    """Advance one propagation round: transmit every sender's `fwd` set along
    permitted edges, dedup against the seen-cache, record first receipts.

    Semantics per receiver j, edge k (sender s = nbr[j,k]):
      trans = fwd[s] & not-echo(s->j) & edge_mask & not-mine(j)
    where echo excludes the single edge a message arrived on (the "source"
    exclusion, floodsub.go:85-86) and not-mine excludes the origin.

    Messages are marked seen whether valid or not (markSeen happens inside
    validation, validation.go:285-293); only valid ones are re-forwarded
    (honest behavior — Reject stops propagation, validation.go:309-351).

    A state built with the async-validation pipeline (survey §7 hard
    part (c); validation.go's worker pool — `dlv.pending` is not None)
    marks receipts seen on arrival but holds them in the pipeline before
    their verdict; forwarding, the Deliver/Reject outcome, and `first_round`
    (the propagation-CDF timestamp, matching the reference's
    post-validation DeliverMessage timing) all happen at pipeline exit.
    """
    n, k_slots = net.nbr.shape
    m = msgs.capacity

    if dlv.fe_words.ndim == 2:
        # CSR-RESIDENT first-arrival plane (round 18): [E, W] flat
        assert net.edge_layout == "csr" and (
            dlv.fe_words.shape[0] == net.n_edges), (
            "flat fe_words needs a matching edge_layout='csr' Net "
            f"({dlv.fe_words.shape[0]} != E={net.n_edges})"
        )
    else:
        assert dlv.fe_words.shape[1] == k_slots, (
            "Delivery.fe_words edge axis does not match the topology's "
            f"max_degree ({dlv.fe_words.shape[1]} != {k_slots}) — construct "
            "the state with SimState.init(..., k=net.max_degree)"
        )
    # the pipeline's presence in the state IS the configuration — deriving
    # it here means a caller can never mismatch the two
    val_delay = 0 if dlv.pending is None else dlv.pending.shape[1]

    if (USE_PALLAS and net.band_off is not None and forward_mask is None
            and val_delay == 0 and queue_cap == 0
            and msgs.wire_block is None):  # kernel predates the block plane
        from ..ops.pallas_delivery import pallas_supported

        block = min(_pallas_block(), n)
        if pallas_supported(net.band_off, n, block):
            interpret = os.environ.get("PUBSUB_PALLAS_COMPILE", "") != "1"
            return _delivery_round_pallas(
                net, msgs, dlv, edge_mask, tick, block=block,
                interpret=interpret, count_events=count_events,
            )

    not_mine = ~origin_msg_words(net, msgs)  # [N, W]
    if msgs.wire_block is not None:
        # oversized messages never cross any edge (sendRPC's fragmentRPC
        # drop, gossipsub.go:1126-1140) — they still live in mcache and
        # get IHAVE-advertised, like the reference's
        not_mine = not_mine & ~bitset.pack(msgs.wire_block)[None, :]

    if net.edge_layout == "csr":
        # sparse data plane (ops/csr.py, docs/DESIGN.md §15): the whole
        # transmit composition runs over the flat [E, W] edge space —
        # the neighbor fwd view and the echo involution are E-sized
        # gathers, the edge/chaos/adversary masks pack down to the
        # present edges, and dead padded slots never move (absent
        # edges aren't in E, so the dense path's nbr_ok word mask has
        # no flat counterpart). One local unpack rebuilds the
        # [N, K, W] transmit tensor for the shared commit tail
        # (finish_delivery) and the RoundInfo consumers (scoring
        # attribution, IWANT merge, telemetry popcounts), so the
        # delivery semantics stay single-source and dense-vs-CSR
        # parity is bit-exact (tests/test_csr.py, all four engines).
        flat_resident = dlv.fe_words.ndim == 2
        if (flat_resident and net.fused and USE_PALLAS_CSR
                and val_delay == 0 and queue_cap == 0):
            got = _delivery_round_pallas_csr(
                net, msgs, dlv, edge_mask, not_mine, tick,
                forward_mask=forward_mask, count_events=count_events,
            )
            if got is not None:
                return got
        fwd_e = net.peer_gather_flat(dlv.fwd)                    # [E, W]
        echo_e = net.edge_gather_flat(
            dlv.fe_words if flat_resident
            else net.pack_edges(dlv.fe_words)
        )
        mask_e = net.pack_edges(edge_mask)
        # receiver-side gate, read at each edge's owner (a local read)
        not_mine_e = net.owner_gather(not_mine)
        trans_e = fwd_e & ~echo_e & mask_e & not_mine_e
        if flat_resident:
            # fully-flat commit (round 18): the reductions back to the
            # peer axis run as ONE segmented scan over [E, W] and the
            # first-arrival plane commits flat — the dense [N, K, W]
            # transmit tensor is never materialized. This is the path
            # the power-law topo-smoke A/B wins on (dead padded slots
            # cost nothing, at rest or in flight).
            return finish_delivery_flat(
                net, msgs, dlv, trans_e, tick, forward_mask=forward_mask,
                count_events=count_events, queue_cap=queue_cap,
                val_delay_topic=val_delay_topic,
            )
        trans = net.unpack_edges(trans_e)
        return finish_delivery(
            net, msgs, dlv, trans, tick, forward_mask=forward_mask,
            count_events=count_events, queue_cap=queue_cap,
            val_delay_topic=val_delay_topic,
        )

    # what each sender is forwarding this round: [N, K, W] word gather
    fwd_gathered = net.peer_gather(dlv.fwd)

    # echo exclusion: sender s does not send m back on the edge it arrived
    # on. The packed first-arrival plane IS the sender-side echo set, so
    # this is a plain word gather: echo[j,k] = "messages s first-received
    # on its edge to j"
    echo_words = net.edge_gather(dlv.fe_words)

    ok_words = jnp.where(net.nbr_ok[..., None], jnp.uint32(0xFFFFFFFF), jnp.uint32(0))

    trans = fwd_gathered & ~echo_words & edge_mask & ok_words & not_mine[:, None, :]
    return finish_delivery(
        net, msgs, dlv, trans, tick, forward_mask=forward_mask,
        count_events=count_events, queue_cap=queue_cap,
        val_delay_topic=val_delay_topic,
    )


def finish_delivery(
    net: Net,
    msgs: MsgTable,
    dlv: Delivery,
    trans: jax.Array,  # [N, K, W] u32: the round's (pre-cap) transmit tensor
    tick: jax.Array,
    forward_mask: jax.Array | None = None,
    count_events: bool = True,
    queue_cap: int = 0,
    val_delay_topic: tuple | None = None,
) -> tuple[Delivery, RoundInfo]:
    """Cap + commit a computed transmit tensor: queue_cap backpressure,
    seen-cache dedup, first-arrival attribution, validation pipeline,
    forward-set update. Shared tail of the receiver-side `delivery_round`
    above and the phase engine's sender-side transmit form
    (gossipsub_phase.py) so the delivery semantics stay single-source."""
    m = msgs.capacity
    val_delay = 0 if dlv.pending is None else dlv.pending.shape[1]

    n_drop = jnp.int32(0)
    if queue_cap > 0:
        # outbound-queue backpressure: each directed link carries at most
        # queue_cap messages per round; the overflow is genuinely LOST —
        # the reference drops the whole RPC when the per-peer writer queue
        # is full (doDropRPC gossipsub.go:1155-1160, comm.go:139-170).
        # Lowest slots first models "queue fills, later sends dropped".
        want = trans
        trans = bitset.keep_lowest_bits(want, queue_cap, m)  # static cap
        n_drop = bitset.popcount(want & ~trans, axis=None).sum().astype(jnp.int32)

    recv_words = bitset.word_or_reduce(trans, axis=1)  # [N, W]
    new_words = recv_words & ~dlv.have

    # first-arrival edge: lowest edge slot carrying each new bit, isolated
    # in word algebra
    fa_words = bitset.first_set_per_bit(trans, axis=1) & new_words[:, None, :]
    valid_words = bitset.pack(msgs.valid)  # [W]

    if val_delay > 0:
        # fresh receipts enter at their per-topic stage (uniform: stage 0);
        # this round's validated cohort exits stage V-1
        validated = dlv.pending[:, -1]
        shifted = jnp.concatenate(
            [jnp.zeros_like(dlv.pending[:, :1]), dlv.pending[:, :-1]], axis=1
        )
        pending = pipeline_insert(shifted, new_words, msgs.topic, val_delay_topic)
    else:
        validated = new_words
        pending = dlv.pending

    validated_bits = bitset.unpack(validated, m)
    first_round = jnp.where(validated_bits, tick, dlv.first_round)

    # forwarding: validated receipts of valid messages (store-and-forward
    # happens after the verdict — Reject stops propagation)
    fwd_next = validated & valid_words[None, :]
    if forward_mask is not None:
        fwd_next = fwd_next & forward_mask

    dlv = dlv.replace(
        have=dlv.have | new_words,
        fwd=fwd_next,
        first_round=first_round,
        # overwrite (not OR) on new receipts so stale bits can't survive a
        # slot whose message is re-received after its fe column was cleared
        fe_words=(dlv.fe_words & ~new_words[:, None, :]) | fa_words,
        pending=pending,
    )

    info = _round_info(trans, validated, m, valid_words, count_events)
    info = info.replace(recv_new_words=new_words, n_drop=n_drop)
    if count_events and val_delay > 0:
        # arrival-cohort counters (duplicates/rpc) are already arrival-based
        # inside _round_info only when the cohorts coincide; recompute here
        n_new = bitset.popcount(new_words, axis=None).astype(jnp.int32).sum()
        info = info.replace(n_duplicate=info.n_rpc - n_new)
    return dlv, info


def finish_delivery_flat(
    net: Net,
    msgs: MsgTable,
    dlv: Delivery,
    trans_e: jax.Array,  # [E, W] u32: the round's flat transmit plane
    tick: jax.Array,
    forward_mask: jax.Array | None = None,
    count_events: bool = True,
    queue_cap: int = 0,
    val_delay_topic: tuple | None = None,
) -> tuple[Delivery, RoundInfo]:
    """The CSR-RESIDENT commit tail (round 18): cap + dedup +
    first-arrival attribution + pipeline + forward update, with every
    per-edge quantity staying on the flat [E, W] plane. Exact-equal to
    ``finish_delivery`` on the unpacked tensor (tests/test_csr.py):

      * the per-peer receive OR and the first-arrival isolation both
        fall out of ONE segmented prefix-OR over the row segments
        (ops/csr.segment_or_scan) — ``inc`` at each row's last edge is
        the receive set, ``x & ~exc`` keeps each bit's first carrying
        edge, and flat row-major order IS ascending dense slot order,
        so the attribution matches ``first_set_per_bit`` bit for bit;
      * the first-arrival plane commits flat — dead padded slots are
        never resident OR in flight;
      * ``RoundInfo.trans`` carries the FLAT plane (popcount-compatible
        with the dense form — absent slots transmit nothing either
        way). Engines that need the dense tensor (scoring attribution)
        run the dense-resident path instead.
    """
    from ..ops import csr

    m = msgs.capacity
    val_delay = 0 if dlv.pending is None else dlv.pending.shape[1]

    n_drop = jnp.int32(0)
    if queue_cap > 0:
        # per-directed-link budget: one flat row IS one (receiver, edge)
        # pair, so the cap applies exactly as in the dense form
        want = trans_e
        trans_e = bitset.keep_lowest_bits(want, queue_cap, m)
        n_drop = bitset.popcount(want & ~trans_e, axis=None).sum().astype(jnp.int32)

    # fused build (round 21): the capacity bound K caps every row
    # segment, so the scan runs ceil(log2 K) shifted levels instead of
    # log2(E) — the dominant delivery-chain term the cost audit's
    # fusion contract pins. Bit-exact either way.
    cap = net.max_degree if net.fused else None
    inc, exc = csr.segment_or_scan(trans_e, net.csr_seg_start, cap=cap)
    recv_words = jnp.where(
        net.csr_row_nonempty[:, None],
        inc[jnp.clip(net.csr_row_last, 0)], jnp.uint32(0),
    )  # [N, W]
    new_words = recv_words & ~dlv.have

    # first-arrival edge, isolated flat: the first edge of each row
    # carrying each new bit (exc = OR of the row's earlier edges)
    new_e = net.owner_gather(new_words)
    fa_e = trans_e & ~exc & new_e
    valid_words = bitset.pack(msgs.valid)  # [W]

    if val_delay > 0:
        validated = dlv.pending[:, -1]
        shifted = jnp.concatenate(
            [jnp.zeros_like(dlv.pending[:, :1]), dlv.pending[:, :-1]], axis=1
        )
        pending = pipeline_insert(shifted, new_words, msgs.topic, val_delay_topic)
    else:
        validated = new_words
        pending = dlv.pending

    validated_bits = bitset.unpack(validated, m)
    first_round = jnp.where(validated_bits, tick, dlv.first_round)

    fwd_next = validated & valid_words[None, :]
    if forward_mask is not None:
        fwd_next = fwd_next & forward_mask

    dlv = dlv.replace(
        have=dlv.have | new_words,
        fwd=fwd_next,
        first_round=first_round,
        # same overwrite-on-new-receipt rule as the dense commit, on the
        # flat plane (new_words read at each edge's owner row)
        fe_words=(dlv.fe_words & ~new_e) | fa_e,
        pending=pending,
    )

    info = _round_info(trans_e, validated, m, valid_words, count_events)
    info = info.replace(recv_new_words=new_words, n_drop=n_drop)
    if count_events and val_delay > 0:
        n_new = bitset.popcount(new_words, axis=None).astype(jnp.int32).sum()
        info = info.replace(n_duplicate=info.n_rpc - n_new)
    return dlv, info


def _round_info(trans, new_words, m, valid_words, count_events=True) -> RoundInfo:
    """Delivery observables from a round's transmit/new sets (shared by the
    XLA and pallas paths so the trace-counter semantics stay single-source).

    `count_events=False` (no EventTracer attached — tracing is opt-in in
    the reference, pubsub.go WithEventTracer) skips the aggregate popcount
    reductions; the per-message delivery state (first_round/first_edge,
    the CDF source) is exact either way."""
    if not count_events:
        z = jnp.int32(0)
        return RoundInfo(
            trans=trans,
            new_words=new_words,
            new_bits=bitset.unpack(new_words, m),
            recv_new_words=new_words,
            n_deliver=z, n_reject=z, n_duplicate=z, n_rpc=z,
        )
    n_rpc = bitset.popcount(trans, axis=None).astype(jnp.int32).sum()
    n_new = bitset.popcount(new_words, axis=None).astype(jnp.int32).sum()
    n_deliver = (
        bitset.popcount(new_words & valid_words[None, :], axis=None)
        .astype(jnp.int32).sum()
    )
    return RoundInfo(
        trans=trans,
        new_words=new_words,
        new_bits=bitset.unpack(new_words, m),
        recv_new_words=new_words,
        n_deliver=n_deliver,
        n_reject=n_new - n_deliver,
        n_duplicate=n_rpc - n_new,
        n_rpc=n_rpc,
    )


def _delivery_round_pallas(net, msgs, dlv, edge_mask, tick, block=None,
                           interpret=False, count_events=True):
    """Banded fast path: one fused kernel for the whole round (see
    ops/pallas_delivery.py). Bit-identical to the generic path above.
    The kernel speaks the [N, M] i8 first-edge form; the packed state is
    converted at the boundary (this path is opt-in)."""
    from ..ops.pallas_delivery import delivery_round_banded

    n, k_slots = net.nbr.shape
    m = msgs.capacity
    w = bitset.n_words(m)
    ok_words = jnp.where(net.nbr_ok[..., None], jnp.uint32(0xFFFFFFFF), jnp.uint32(0))
    emask_flat = (edge_mask & ok_words).reshape(n, k_slots * w)
    valid_words = bitset.pack(msgs.valid)
    fe_i8 = bitset.first_edge_of(dlv.fe_words, m)
    trans, have2, fwd2, fr2, fe2 = delivery_round_banded(
        dlv.fwd, fe_i8, emask_flat, dlv.have, dlv.first_round,
        msgs.origin, valid_words, tick,
        block=min(block or n, n), m=m,
        offsets=net.band_off, revs=net.band_rev,
        interpret=interpret,
    )
    new_words = have2 & ~dlv.have
    dlv2 = dlv.replace(
        have=have2, fwd=fwd2, first_round=fr2,
        fe_words=bitset.edge_eq_words(fe2, k_slots),
    )
    return dlv2, _round_info(trans, new_words, m, valid_words, count_events)


def _pick_div(total: int, lo: int, want: int) -> int | None:
    """Largest divisor of ``total`` in [lo, want] (static block sizing)."""
    for b in range(min(want, total), lo - 1, -1):
        if total % b == 0:
            return b
    return None


def _delivery_round_pallas_csr(net, msgs, dlv, edge_mask, not_mine, tick,
                               forward_mask=None, count_events=True):
    """The CSR-resident round through the fused Pallas kernels
    (ops/pallas_csr.csr_delivery — the three-call form of the flat
    gather/scan/commit chain). Bit-identical to the composite path
    below (tests/test_pallas_csr.py); opt-in via PUBSUB_PALLAS_CSR=1 on
    a fused Net. Returns None when the static block preconditions don't
    hold (the caller falls through to the composite)."""
    from ..ops import edges as _edges
    from ..ops import pallas_csr as pcsr

    e = net.n_edges
    cap = net.max_degree
    want = _pallas_block()
    block = _pick_div(e, cap, want)
    block_rows = _pick_div(net.n_peers, 1, want)
    if (block is None or block_rows is None
            or not pcsr.pallas_csr_supported(e, block, cap)):
        return None
    interpret = os.environ.get("PUBSUB_PALLAS_COMPILE", "") != "1"
    m = msgs.capacity
    mask_e = net.pack_edges(edge_mask)
    valid_words = bitset.pack(msgs.valid)
    # the kernel's col/eperm gathers ARE the flat peer/edge halo set the
    # composite path tallies (peer_gather_flat / edge_gather_flat)
    _edges._tally("peer", dlv.fe_words)
    _edges._tally("edge", dlv.fe_words)
    res = pcsr.csr_delivery(
        dlv.fwd, dlv.fe_words, mask_e, not_mine, dlv.have,
        dlv.first_round, valid_words[None, :], tick,
        net.csr_col, net.csr_row, net.csr_eperm, net.csr_seg_start,
        net.csr_row_last, net.csr_row_nonempty,
        cap=cap, block=block, block_rows=block_rows, interpret=interpret,
    )
    fwd_next = res["fwd"]
    if forward_mask is not None:
        fwd_next = fwd_next & forward_mask
    dlv2 = dlv.replace(
        have=res["have"], fwd=fwd_next, first_round=res["first_round"],
        fe_words=res["fe"],
    )
    new_words = res["new"]
    info = _round_info(res["trans_e"], new_words, m, valid_words,
                       count_events)
    info = info.replace(recv_new_words=new_words)
    return dlv2, info


def accumulate_round_events(events: jax.Array, info: RoundInfo, n_publish) -> jax.Array:
    """Fold a round's delivery observables into the cumulative event
    counters (the EventTracer accounting that trace_test.go:26-195 checks:
    publish/deliver/duplicate/reject totals plus RPC counts)."""
    ev = events
    ev = ev.at[EV.PUBLISH_MESSAGE].add(jnp.asarray(n_publish, jnp.int32))
    ev = ev.at[EV.DELIVER_MESSAGE].add(info.n_deliver)
    ev = ev.at[EV.REJECT_MESSAGE].add(info.n_reject)
    ev = ev.at[EV.DUPLICATE_MESSAGE].add(info.n_duplicate)
    ev = ev.at[EV.SEND_RPC].add(info.n_rpc)
    ev = ev.at[EV.RECV_RPC].add(info.n_rpc)
    ev = ev.at[EV.DROP_RPC].add(info.n_drop)
    return ev
