"""Routers — the strategy layer (`PubSubRouter`, pubsub.go:169-198),
vectorized: floodsub, randomsub, gossipsub."""
