"""RandomSub router, vectorized (randomsub.go).

Reference semantics (randomsub.go:99-160): on each publish/forward, send to
max(RandomSubD=6, ceil(sqrt(topic size))) random peers subscribed to the
topic (gossipsub-capable peers are sampled; floodsub peers always get it —
here all peers are mesh-capable, survey #11 protocol negotiation arrives
with the adversary/protocol flags).

Vector form: each sender draws a fresh random-k edge selection per topic
slot per round; the receiver-side gather translates it through the
reverse-edge index exactly like the gossipsub mesh mask.
"""

from __future__ import annotations

import math

import jax
import jax.numpy as jnp
import numpy as np

from ..ops import bitset
from ..ops.select import select_random_mask
from ..state import Net, SimState, allocate_publishes
from .common import accumulate_round_events, delivery_round
from .gossipsub import gather_edge_slots, gather_nbr_subscribed, joined_msg_words, msg_slot_of

RANDOMSUB_D = 6  # randomsub.go:17


def make_randomsub_step(net: Net, d: int = RANDOMSUB_D):
    """Build the jitted per-round RandomSub step.

    The per-topic fanout target is max(d, ceil(sqrt(topic_size)))
    (randomsub.go:124-131), with topic sizes from the static subscription
    table."""
    topic_size = np.asarray(jnp.sum(net.subscribed, axis=0))  # [T]
    target_t = np.maximum(d, np.ceil(np.sqrt(topic_size))).astype(np.int32)
    # per (peer, slot) target
    mt = np.asarray(net.my_topics)
    target_ns = jnp.asarray(
        np.where(mt >= 0, target_t[np.clip(mt, 0, None)], 0)
    )  # [N,S]

    def step(st: SimState, pub_origin, pub_topic, pub_valid) -> SimState:
        tick = st.tick
        m = st.msgs.capacity

        # fresh random fanout per sender/slot/round
        key = jax.random.fold_in(st.key, tick)
        eligible = gather_nbr_subscribed(net)  # [N,S,K]
        sel = select_random_mask(key, eligible, target_ns)  # [N,S,K]

        # receiver view: sender chose me for the message's topic?
        sel_in = gather_edge_slots(sel, net).transpose(0, 2, 1)  # [N,K,S]
        mslot = msg_slot_of(net, st.msgs.topic)  # [N,M]
        n, k_dim = net.nbr.shape
        idx = jnp.broadcast_to(jnp.clip(mslot, 0)[:, None, :], (n, k_dim, m))
        carry = jnp.take_along_axis(sel_in, idx, axis=2) & (mslot >= 0)[:, None, :]
        edge_mask = bitset.pack(carry) & joined_msg_words(net, st.msgs)[:, None, :]

        dlv, info = delivery_round(net, st.msgs, st.dlv, edge_mask, tick)
        msgs, dlv, _slots, is_pub, _keep, _pw = allocate_publishes(
            st.msgs, dlv, tick, pub_origin, pub_topic, pub_valid
        )
        events = accumulate_round_events(st.events, info, jnp.sum(is_pub.astype(jnp.int32)))
        return st.replace(tick=tick + 1, msgs=msgs, dlv=dlv, events=events)

    return jax.jit(step, donate_argnums=0)
