"""RandomSub router, vectorized (randomsub.go).

Reference semantics (randomsub.go:99-160): on each publish/forward, send to
max(RandomSubD=6, ceil(sqrt(topic size))) random *gossip-capable* peers
subscribed to the topic, while peers speaking only /floodsub/1.0.0 always
receive (randomsub.go:107-116 splits the peer list before sampling).

Vector form: each sender draws a fresh random-k edge selection per topic
slot per round over the gossip-capable neighbors, ORs in the floodsub-only
edges unconditionally; the receiver-side gather translates it through the
reverse-edge index exactly like the gossipsub mesh mask.

Edge layout: both the carry-outbox gather here and the shared delivery
engine dispatch on the Net's static ``edge_layout`` — a CSR-built Net
(ops/csr.py) runs them over the flat [E] edge space, bit-exact vs the
dense involution (tests/test_csr.py).
"""

from __future__ import annotations


import jax
import jax.numpy as jnp
import numpy as np

from ..chaos import adversary as adversary_mod
from ..chaos import faults as chaos_faults
from ..ops.select import select_random_mask
from ..score.engine import slot_topic_words
from ..state import Net, SimState, allocate_publishes
from ..trace.events import EV
from .common import accumulate_round_events, delivery_round
from .gossipsub import gather_nbr_subscribed, joined_msg_words, sender_carry_words

RANDOMSUB_D = 6  # randomsub.go:17


def make_randomsub_step(net: Net, d: int = RANDOMSUB_D,
                        size_estimate: int | None = None,
                        queue_cap: int = 0,
                        stacked: bool = True,
                        chaos: "chaos_faults.ChaosConfig | None" = None,
                        telemetry=None,
                        adversary=None,
                        lift_scores: bool = False):
    """Build the jitted per-round RandomSub step.

    `size_estimate` mirrors the reference's static network-size parameter:
    NewRandomSub takes `size` and targets max(D, ceil(sqrt(size))) for
    every send (randomsub.go:61-67, 124-131). When None, the target is
    sized per topic from the gossip-capable subscriber count instead — a
    documented deviation (a refinement the reference cannot compute,
    since a node doesn't know the topic's global size; parity claims
    against the Go reference should pass the same size estimate the Go
    node was constructed with). Floodsub-only peers are split out before
    sampling either way (randomsub.go:107-116).

    ``queue_cap`` is the sub-router outbound-queue budget (comm.go:
    139-170 — the writer queues sit below every router); the async
    validation pipeline likewise rides in the state
    (``SimState.init(val_delay=...)``), both shared with floodsub and
    gossipsub through the common delivery engine. ``stacked`` selects
    the round-7 stacked recycled-slot clears in allocate_publishes
    (False = legacy per-plane kernels, bit-identical — A/B only).

    ``chaos`` enables the link-fault plane (chaos/faults.py — same
    generators and elision contract as the other routers); a
    ``scheduled=True`` config makes the step take a trailing
    ``link_deny [N, K]`` argument, and a GE generator needs
    ``SimState.init(chaos_ge=True)``.

    ``telemetry`` (a telemetry.TelemetryConfig) appends the per-round
    panel recorder as the step's last operation (mesh/score columns
    record zeros — randomsub has neither plane); the state needs
    ``SimState.init(telemetry=...)``. None elides it statically.

    ``adversary`` (a chaos.adversary.Adversary) applies the attack
    plane's DATA behaviors — drop-on-forward and censorship, masked
    into the receiver gather with eager neighbor-view constants (zero
    extra halo permutes); the mesh/score behaviors have no randomsub
    analogue. None elides it statically.

    ``lift_scores=True`` (round 16) makes the step take a trailing
    TRACED ``score_plane`` — accepted and unused (randomsub has no
    score machinery), threading the four-engine lifted call convention
    so ensemble sweeps treat every router uniformly."""
    chaos = chaos_faults.resolve(chaos)
    chaos_sched = chaos is not None and chaos.scheduled
    adv_pop = adversary_mod.resolve(adversary)
    adv = (adversary_mod.AdversaryConsts(adv_pop, net)
           if adv_pop is not None else None)
    protocol = np.asarray(net.protocol)
    if size_estimate is not None:
        gs_size = np.full((net.n_topics,), size_estimate, np.int64)
    else:
        gs_size = np.asarray(
            jnp.sum(net.subscribed & jnp.asarray(protocol >= 1)[:, None], axis=0)
        )  # [T] gossip-capable subscribers only
    target_t = np.maximum(d, np.ceil(np.sqrt(gs_size))).astype(np.int32)
    # per (peer, slot) target
    mt = np.asarray(net.my_topics)
    target_ns = jnp.asarray(
        np.where(mt >= 0, target_t[np.clip(mt, 0, None)], 0)
    )  # [N,S]

    eligible = gather_nbr_subscribed(net)  # [N,S,K] static, eager
    # the random draw samples gossip-capable peers only; floodsub-only
    # neighbors are always sent to (randomsub.go:107-116)
    fs_edge = (net.peer_gather(net.protocol) == 0) & net.nbr_ok  # [N,K]
    elig_random = eligible & ~fs_edge[:, None, :]
    always = eligible & fs_edge[:, None, :]
    # a floodsub-only *sender* runs the floodsub router, not randomsub:
    # it forwards to every subscribed neighbor (floodsub.go:76-100)
    i_am_floodsub = jnp.asarray(protocol == 0)

    def _round(st: SimState, pub_origin, pub_topic, pub_valid,
               link_deny=None) -> SimState:
        tick = st.tick
        m = st.msgs.capacity

        # fresh random fanout per sender/slot/round
        key = jax.random.fold_in(st.key, tick)
        sel = (select_random_mask(key, elig_random, target_ns,
                                  fused=net.fused)
               | always)  # [N,S,K]
        sel = jnp.where(i_am_floodsub[:, None, None], eligible, sel)

        # sender-side packed outbox, word-gathered by receivers
        slotw = slot_topic_words(net, st.msgs.topic)           # [N,S,W]
        carry_out = sender_carry_words(sel, slotw)             # [N,K,W]
        carried = jnp.where(
            net.nbr_ok[:, :, None],
            net.edge_gather(carry_out),
            jnp.uint32(0),
        )
        edge_mask = carried & joined_msg_words(net, st.msgs)[:, None, :]
        if chaos is not None:
            ge_bad = st.chaos.ge_bad if st.chaos is not None else None
            link_ok, ge_bad_next = chaos_faults.round_link_ok(
                chaos, chaos_faults.chaos_seed(st.key), net.nbr, tick,
                ge_bad, link_deny,
            )
            edge_mask = jnp.where(link_ok[:, :, None], edge_mask, jnp.uint32(0))
        n_adv_drop = None
        if adv is not None and adv.data_plane:
            edge_mask, removed = adv.mask_transmit_nbr(tick, edge_mask,
                                                       st.msgs)
            n_adv_drop = adversary_mod.withheld_count(net, st.dlv.fwd,
                                                      removed)

        dlv, info = delivery_round(net, st.msgs, st.dlv, edge_mask, tick,
                                   queue_cap=queue_cap)
        msgs, dlv, _slots, is_pub, _keep, _pw = allocate_publishes(
            st.msgs, dlv, tick, pub_origin, pub_topic, pub_valid,
            stacked_clears=stacked,
        )
        events = accumulate_round_events(st.events, info, jnp.sum(is_pub.astype(jnp.int32)))
        if chaos is not None:
            events = events.at[EV.LINK_DOWN].add(
                chaos_faults.count_links_down(net.nbr, net.nbr_ok, link_ok)
            )
            if chaos.needs_state:
                st = st.replace(chaos=st.chaos.replace(ge_bad=ge_bad_next))
        if n_adv_drop is not None:
            events = events.at[EV.ADV_DROP].add(n_adv_drop)
        telem = st.telem
        if telemetry is not None:
            from ..telemetry import panel as _tele

            telem = _tele.record_step(
                telemetry, telem, tick, st.events, events, net, msgs, dlv,
            )
        return st.replace(tick=tick + 1, msgs=msgs, dlv=dlv, events=events,
                          telem=telem)

    if lift_scores:
        # rest = ([link_deny,] score_plane); the plane is unused here
        def step(st, pub_origin, pub_topic, pub_valid, *rest):
            deny = rest[0] if chaos_sched else None
            return _round(st, pub_origin, pub_topic, pub_valid, deny)
    elif chaos_sched:
        def step(st, pub_origin, pub_topic, pub_valid, link_deny):
            return _round(st, pub_origin, pub_topic, pub_valid, link_deny)
    else:
        def step(st, pub_origin, pub_topic, pub_valid):
            return _round(st, pub_origin, pub_topic, pub_valid)

    return jax.jit(step, donate_argnums=0)
