"""RandomSub router, vectorized (randomsub.go).

Reference semantics (randomsub.go:99-160): on each publish/forward, send to
max(RandomSubD=6, ceil(sqrt(topic size))) random peers subscribed to the
topic (gossipsub-capable peers are sampled; floodsub peers always get it —
here all peers are mesh-capable, survey #11 protocol negotiation arrives
with the adversary/protocol flags).

Vector form: each sender draws a fresh random-k edge selection per topic
slot per round; the receiver-side gather translates it through the
reverse-edge index exactly like the gossipsub mesh mask.
"""

from __future__ import annotations

import math

import jax
import jax.numpy as jnp
import numpy as np

from ..ops import bitset
from ..ops.select import select_random_mask
from ..score.engine import slot_topic_words
from ..state import Net, SimState, allocate_publishes
from .common import accumulate_round_events, delivery_round
from .gossipsub import gather_nbr_subscribed, joined_msg_words, sender_carry_words

RANDOMSUB_D = 6  # randomsub.go:17


def make_randomsub_step(net: Net, d: int = RANDOMSUB_D):
    """Build the jitted per-round RandomSub step.

    The per-topic fanout target is max(d, ceil(sqrt(topic_size)))
    (randomsub.go:124-131), with topic sizes from the static subscription
    table."""
    topic_size = np.asarray(jnp.sum(net.subscribed, axis=0))  # [T]
    target_t = np.maximum(d, np.ceil(np.sqrt(topic_size))).astype(np.int32)
    # per (peer, slot) target
    mt = np.asarray(net.my_topics)
    target_ns = jnp.asarray(
        np.where(mt >= 0, target_t[np.clip(mt, 0, None)], 0)
    )  # [N,S]

    eligible = gather_nbr_subscribed(net)  # [N,S,K] static, eager

    def step(st: SimState, pub_origin, pub_topic, pub_valid) -> SimState:
        tick = st.tick
        m = st.msgs.capacity

        # fresh random fanout per sender/slot/round
        key = jax.random.fold_in(st.key, tick)
        sel = select_random_mask(key, eligible, target_ns)  # [N,S,K]

        # sender-side packed outbox, word-gathered by receivers
        slotw = slot_topic_words(net, st.msgs.topic)           # [N,S,W]
        carry_out = sender_carry_words(sel, slotw)             # [N,K,W]
        carried = jnp.where(
            net.nbr_ok[:, :, None],
            net.edge_gather(carry_out),
            jnp.uint32(0),
        )
        edge_mask = carried & joined_msg_words(net, st.msgs)[:, None, :]

        dlv, info = delivery_round(net, st.msgs, st.dlv, edge_mask, tick)
        msgs, dlv, _slots, is_pub, _keep, _pw = allocate_publishes(
            st.msgs, dlv, tick, pub_origin, pub_topic, pub_valid
        )
        events = accumulate_round_events(st.events, info, jnp.sum(is_pub.astype(jnp.int32)))
        return st.replace(tick=tick + 1, msgs=msgs, dlv=dlv, events=events)

    return jax.jit(step, donate_argnums=0)
