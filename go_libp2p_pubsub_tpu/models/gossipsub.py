"""GossipSub v1.0/v1.1 router, vectorized (gossipsub.go, 1909 LoC in the
reference) — the centerpiece of the framework (BASELINE.json north_star).

The per-node state machine — mesh maintenance, heartbeat, IHAVE/IWANT lazy
gossip, GRAFT/PRUNE control with backoff, scoring, graylisting — runs for
all N virtual peers at once as masked array ops over the padded neighbor
axis; peer selection is the rank/top-k primitive (ops/select.py).

Round model (survey §7): one jitted `step()` = one network-hop round; the
heartbeat runs every `heartbeat_every` rounds inside the same jit. Control
written to per-edge outboxes in round r is read by the far end in round
r+1 via the reverse-edge gather — the one-RTT control latency of the
reference's wire layer.

Approximations vs the reference (all distributional, per the north star's
CDF comparison):
  * control responses are delayed one round (reference replies in the same
    RPC turn)
  * per-heartbeat GRAFT processing is batched, so Dhi admission checks use
    mesh sizes from the round start
  * one outstanding IWANT promise slot per edge (reference keeps one per
    IWANT batch; AddPromise gossip_tracer.go:48-75). Measured at
    adversarial advertise-never-serve rates (tests/
    test_promise_sensitivity.py): the per-batch model accrues ~2.3x the
    P7 of the per-edge model, but both drive attacker edges under the
    gossip threshold and leave honest edges clean — the protective
    outcome is granularity-insensitive
  * IHAVE truncation to MaxIHaveLength keeps lowest slots (reference
    shuffles; gossipsub.go:655-667). With the cap forced to bind hard
    (budget 4 vs 64-slot windows) the two policies' propagation CDFs
    differ by 0.3% sup — far inside the parity envelope
  * over-subscription outbound bubble-up displaces random-keep members only
    (the reference's rotation can displace score-keep members in corner
    cases, gossipsub.go:1409-1441)
"""

from __future__ import annotations

import dataclasses
import os

import jax
import jax.numpy as jnp
import numpy as np
from flax import struct

from ..chaos import adversary as adversary_mod
from ..chaos import faults as chaos_faults
from ..chaos.faults import ChaosConfig
from ..config import (
    GossipSubParams,
    PeerScoreParams,
    PeerScoreThresholds,
    ticks_for,
)
from ..ops import bitset, edges
from ..ops import fused_round as fr
from ..ops.select import (
    count_true,
    masked_width_random,
    masked_width_topk,
    median_masked,
    select_random_mask,
    select_topk_mask,
)
from ..routers import (
    RouterConfig,
    choke_decide,
    choke_guard,
    choke_lateness_update,
    choke_suppression,
    dontwant_announcements,
    dontwant_suppression,
    idontwant_sent_count,
    ring_commit,
    ring_keep,
)
from ..score.engine import (
    ScoreState,
    TopicParamsArrays,
    add_penalties,
    clear_edges,
    clear_mesh_status,
    compute_scores,
    ip_colocation_surplus_sq,
    on_deliveries,
    on_graft,
    on_prune,
    refresh_scores,
    slot_topic_words,
)
from ..score.gater import GaterState, gater_accept, gater_decay, gater_on_round
from ..state import (
    Net,
    SimState,
    TopoState,
    allocate_publishes,
    wrap_csr_resident,
)
from ..trace.events import EV
from .common import (
    RoundInfo,
    accumulate_round_events,
    delivery_round,
    origin_msg_words,
    subscribed_msg_words,
)


# ---------------------------------------------------------------------------
# configuration


@dataclasses.dataclass(frozen=True)
class GossipSubConfig:
    """Static (jit-constant) configuration: GossipSubParams with durations
    in ticks, plus the v1.1 thresholds and feature switches."""

    D: int = 6
    Dlo: int = 5
    Dhi: int = 12
    Dscore: int = 4
    Dout: int = 2
    Dlazy: int = 6
    gossip_factor: float = 0.25
    history_length: int = 5
    history_gossip: int = 3
    gossip_retransmission: int = 3
    max_ihave_messages: int = 10
    max_ihave_length: int = 5000
    iwant_followup_ticks: int = 3
    prune_backoff_ticks: int = 60
    graft_flood_ticks: int = 10
    opportunistic_graft_ticks: int = 60
    opportunistic_graft_peers: int = 2
    backoff_clear_ticks: int = 15   # gossipsub.go:1587
    backoff_slack_ticks: int = 2    # gossipsub.go:1596
    direct_connect_ticks: int = 300  # gossipsub.go:1606-1628
    heartbeat_every: int = 1        # rounds per heartbeat tick
    # v1.1 switches
    score_enabled: bool = False
    flood_publish: bool = False
    do_px: bool = False
    # edge-liveness gating without PX: dormant provisioned edges carry
    # nothing until activated (state.edge_live). PX implies it; a build
    # with pre-provisioned dormant pairs (api.Network.connect(dormant=
    # True) — the runtime-connect pool, notify.go:19-75 Connected) sets
    # it so post-start connects flip edges live with no recompile
    edge_liveness: bool = False
    # outbound-queue backpressure: per-link message budget per round; the
    # overflow is genuinely lost and traced DROP_RPC (the reference's
    # 32-deep per-peer writer queue, pubsub.go:240 + comm.go:139-170).
    # 0 = lossless (unmodeled), the default
    queue_cap: int = 0
    # peer gater + validation pipeline model (validation.go front-end queue;
    # 0 capacity = unbounded, gater inert without throttle pressure)
    gater_enabled: bool = False
    gater_quiet_ticks: int = 60
    validation_capacity: int = 0  # accepted validations per peer per round
    # async validation latency in rounds (survey §7 hard-part (c)): receipts
    # spend this many rounds in the pipeline between arrival (markSeen) and
    # their verdict (forward + Deliver/Reject + CDF timestamp). 0 = inline.
    validation_delay_rounds: int = 0
    # per-topic validation latency (the reference's per-topic async
    # validators complete at different times — NumCPU workers + per-topic
    # throttles, validation.go:123-135,391-438): a static tuple of T
    # per-topic delays, each in [1, validation_delay_rounds]; a message's
    # verdict lands delay[topic] rounds after arrival, so verdicts of
    # different topics interleave out of arrival order. None = uniform
    # validation_delay_rounds for every topic.
    validation_delay_topic: tuple | None = None
    # WithValidatorTimeout analogue (validation.go:522-529): an async
    # validator whose verdict would land more than this many rounds after
    # arrival times out, and the message is IGNORED (dropped without the
    # P4 sender penalty — the reference's expired validation context).
    # Composes with the per-topic delays above: a topic whose effective
    # delay exceeds the timeout never produces an Accept. 0 = no timeout.
    validator_timeout_rounds: int = 0
    # fanout (publishing to unjoined topics, gossipsub.go:981-1002,1517-1554)
    fanout_slots: int = 2         # concurrent unjoined publish topics/peer
    fanout_ttl_ticks: int = 60
    # aggregate trace counters (EventTracer accounting). Tracing is opt-in
    # in the reference (WithEventTracer); False skips the event popcount
    # reductions — per-message delivery state stays exact
    count_events: bool = True
    # coalesced stacked wire exchange (phase engine only): the whole
    # control head — control outboxes, score plane, IWANT-service window,
    # P5 app plane — crosses the edge involution in ONE gather (one halo
    # permute set per phase on the sharded mesh), and the phase's
    # attribution accumulators fold as leading-axis-stacked tensors.
    # False selects the legacy per-plane path (round-3..6 structure) for
    # A/B; the bench fingerprint records the choice
    # (engine.wire_coalesced) and the measured permute_sets_per_phase.
    # Bit-identical either way (tests/test_phase_stacked.py).
    wire_coalesced: bool = True
    # sparse data plane (round 15, ops/csr.py + docs/DESIGN.md §15): the
    # edge-exchange layout — "dense" (the padded [N, K] involution, the
    # default: traces the pre-CSR program bit for bit) or "csr" (the
    # capacity-bounded flat [E] edge space; cross-peer movement is
    # E-sized, the sparse-topology regime's shape). A frozen static: one
    # build traces exactly ONE layout, zero runtime branching; the Net
    # must be built with the same value (prepare_step_consts enforces).
    edge_layout: str = "dense"
    # fused composite kernels (round 21, docs/DESIGN.md §21): statically
    # select the bandwidth-lean forms on the hot path — the sort-form
    # selection (ops/select fused=True: O(K) bytes/row instead of the
    # pairwise form's O(K^2) compare planes) in the heartbeat, fanout
    # and gossip-target blocks, and the capacity-bounded segmented OR in
    # the CSR delivery commit (via the matching Net.build(fused=True)).
    # A frozen static like edge_layout: False traces the pre-fusion
    # program bit for bit (the census gate's contract); True is
    # bit-exact in VALUES (tests/test_pallas_csr.py, all four engines)
    # and is what `make cost-audit`'s fusion contract prices.
    fused: bool = False
    # int-packed control counters (round 15 narrowing contract, docs/
    # DESIGN.md §15): store the per-edge IHAVE flood-protection counters
    # (peerhave/iasked) as int16 instead of int32. EXACT by range
    # analysis — both are cleared every heartbeat; iasked saturates at
    # the max_ihave_length cap it gates on, and peerhave grows at most
    # one batch per round so the heartbeat cadence bounds it — and
    # build() refuses configs whose bound (max_ihave_length or
    # heartbeat_every) falls outside int16, so the narrowed build is
    # bit-identical in VALUES (tests/test_csr.py). Off by default (the
    # committed STATE_SCHEMA pins the wide dtypes).
    narrow_counters: bool = False
    # chaos plane (chaos/faults.py): link-fault injection — i.i.d. or
    # Gilbert–Elliott flap generators drawn from the sim PRNG stream,
    # plus (scheduled=True) a per-round link_deny argument fed by the
    # Scenario partition compiler. None (or an all-zero config) elides
    # the plane STATICALLY: the traced program is identical to a build
    # without it (bit-exactness + the PERF_SMOKE kernel census pinned
    # by tests/test_chaos.py and `make chaos-smoke`)
    chaos: "ChaosConfig | None" = None
    # exact per-event tracing support (trace.go:166-194, 341-414): the
    # step additionally records this round's duplicate-arrival plane
    # ([N,K,W] — arrivals beyond the first per (peer,msg)) in
    # state.dup_trans so the drain can expand every DuplicateMessage and
    # control-only RPC into an individual TraceEvent (drain.TraceSession
    # exact mode) instead of aggregate counters. Off by default: costs one
    # [N,K,W] store per round when on, zero when off
    trace_exact: bool = False
    # router plane (routers/, docs/DESIGN.md §24): the post-v1.1
    # protocol frontier — v1.2 IDONTWANT duplicate suppression, the
    # episub-style lazy-choke router, and the per-edge latency ring
    # that consumes topo.link_class_planes. None (the one spelling of
    # v1.1 semantics) elides the plane STATICALLY: the traced program,
    # kernel census and state tree are the pre-router ones, bit for bit
    # (`make choke-smoke`'s router-off census gate).
    router: "RouterConfig | None" = None
    # thresholds (v1.1; zeros for v1.0)
    gossip_threshold: float = 0.0
    publish_threshold: float = 0.0
    graylist_threshold: float = 0.0
    accept_px_threshold: float = 0.0
    opportunistic_graft_threshold: float = 0.0

    @classmethod
    def build(
        cls,
        params: GossipSubParams | None = None,
        thresholds: PeerScoreThresholds | None = None,
        score_enabled: bool = False,
        heartbeat_every: int = 1,
        gater_params: "PeerGaterParams | None" = None,
        validation_capacity: int = 0,
        validation_delay_rounds: int = 0,
        validation_delay_topic: tuple | None = None,
        validator_timeout_rounds: int = 0,
        queue_cap: int = 0,
        trace_exact: bool = False,
        wire_coalesced: bool = True,
        chaos: "ChaosConfig | None" = None,
        edge_layout: str = "dense",
        narrow_counters: bool = False,
        fused: bool = False,
        router: "RouterConfig | None" = None,
    ) -> "GossipSubConfig":
        p = params or GossipSubParams()
        p.validate()
        if router is not None:
            router.validate()
        if edge_layout not in ("dense", "csr"):
            raise ValueError(
                f"edge_layout must be 'dense' or 'csr', got {edge_layout!r}"
            )
        # derived from the counter dtype, not hard-coded — the range
        # auditor (analysis/ranges.py, contract narrow-nonwrap) proves
        # the int16 sites non-wrapping under exactly these caps
        i16_cap = int(np.iinfo(np.int16).max) + 1
        if narrow_counters and p.max_ihave_length >= i16_cap:
            # the iasked counter saturates at the cap it gates on; a cap
            # outside int16 range would overflow before the gate fires
            raise ValueError(
                f"narrow_counters needs max_ihave_length < {i16_cap} "
                f"(got {p.max_ihave_length}) — the int16 iasked counter "
                "must be able to represent its own cap"
            )
        if narrow_counters and heartbeat_every >= i16_cap:
            # peerhave's true bound is the heartbeat clear cadence, not
            # max_ihave_messages: it counts one IHAVE batch per round
            # (handle_ihave) and only clearIHaveCounters resets it, so
            # an edge advertising every round reaches heartbeat_every
            # before the clear
            raise ValueError(
                f"narrow_counters needs heartbeat_every < {i16_cap} "
                f"(got {heartbeat_every}) — the int16 peerhave counter "
                "grows once per round until the heartbeat clear"
            )
        if validator_timeout_rounds < 0:
            raise ValueError(
                f"validator_timeout_rounds must be >= 0, got {validator_timeout_rounds}"
            )
        if validation_delay_topic is not None:
            validation_delay_topic = tuple(int(d) for d in validation_delay_topic)
            if validation_delay_rounds <= 0:
                validation_delay_rounds = max(validation_delay_topic)
            if not all(
                1 <= d <= validation_delay_rounds for d in validation_delay_topic
            ):
                raise ValueError(
                    "validation_delay_topic entries must lie in "
                    f"[1, {validation_delay_rounds}] (the pipeline depth); "
                    f"got {validation_delay_topic}"
                )
        hb = p.heartbeat_interval
        kw = dict(
            D=p.D, Dlo=p.Dlo, Dhi=p.Dhi, Dscore=p.Dscore, Dout=p.Dout,
            Dlazy=p.Dlazy, gossip_factor=p.gossip_factor,
            history_length=p.history_length, history_gossip=p.history_gossip,
            gossip_retransmission=p.gossip_retransmission,
            max_ihave_messages=p.max_ihave_messages,
            max_ihave_length=p.max_ihave_length,
            iwant_followup_ticks=ticks_for(p.iwant_followup_time, hb),
            prune_backoff_ticks=ticks_for(p.prune_backoff, hb),
            graft_flood_ticks=ticks_for(p.graft_flood_threshold, hb),
            opportunistic_graft_ticks=p.opportunistic_graft_ticks,
            opportunistic_graft_peers=p.opportunistic_graft_peers,
            direct_connect_ticks=p.direct_connect_ticks,
            heartbeat_every=heartbeat_every,
            score_enabled=score_enabled,
            flood_publish=p.flood_publish,
            do_px=p.do_px,
            gater_enabled=gater_params is not None,
            gater_quiet_ticks=ticks_for(gater_params.quiet, hb) if gater_params else 60,
            validation_capacity=validation_capacity,
            validation_delay_rounds=validation_delay_rounds,
            validation_delay_topic=validation_delay_topic,
            validator_timeout_rounds=validator_timeout_rounds,
            queue_cap=queue_cap,
            trace_exact=trace_exact,
            wire_coalesced=wire_coalesced,
            chaos=chaos,
            edge_layout=edge_layout,
            narrow_counters=narrow_counters,
            fused=fused,
            router=router,
            fanout_ttl_ticks=ticks_for(p.fanout_ttl, hb),
        )
        if chaos is not None:
            chaos.validate()
        if thresholds is not None:
            thresholds.validate()
            kw.update(
                gossip_threshold=thresholds.gossip_threshold,
                publish_threshold=thresholds.publish_threshold,
                graylist_threshold=thresholds.graylist_threshold,
                accept_px_threshold=thresholds.accept_px_threshold,
                opportunistic_graft_threshold=thresholds.opportunistic_graft_threshold,
            )
        return cls(**kw)

    def validation_timed_out(self, topic: int) -> bool:
        """True when this topic's async verdict can never land inside the
        validator timeout (effective delay > validator_timeout_rounds):
        its messages resolve to ValidationIgnore, the reference's
        expired-context outcome (validation.go:522-529)."""
        if self.validator_timeout_rounds <= 0:
            return False
        if self.validation_delay_topic is not None:
            delay = self.validation_delay_topic[topic]
        else:
            delay = self.validation_delay_rounds
        return delay > self.validator_timeout_rounds


# ---------------------------------------------------------------------------
# state


@struct.dataclass
class GossipSubState:
    core: SimState
    # mesh overlay (gossipsub.go:441 mesh map)
    mesh: jax.Array             # [N,S,K] bool
    # prune backoff (gossipsub.go:449): expiry tick + presence (presence
    # outlives expiry until the 15-tick clear — gossipsub.go:1585-1604; the
    # heartbeat candidate filter tests presence, graft admission tests expiry)
    backoff_expire: jax.Array   # [N,S,K] i32
    backoff_present: jax.Array  # [N,S,K] bool
    # message cache ring (mcache.go): window 0 = current heartbeat
    mcache: jax.Array           # [N,H,W] u32
    # control outboxes, read by the far end next round
    ihave_out: jax.Array        # [N,K,W] u32
    iwant_out: jax.Array        # [N,K,W] u32
    graft_out: jax.Array        # [N,S,K] bool
    prune_out: jax.Array        # [N,S,K] bool
    # IHAVE flood protection (cleared each heartbeat, gossipsub.go:1566-1576)
    peerhave: jax.Array         # [N,K] i32
    iasked: jax.Array           # [N,K] i32
    # IWANT retransmission 2-bit saturating counters (mcache.peertx,
    # mcache.go:66-80, tracked at the requesting end of the edge)
    served_lo: jax.Array        # [N,K,W] u32
    served_hi: jax.Array        # [N,K,W] u32
    # gossip promises (gossip_tracer.go): one slot per edge
    promise_mid: jax.Array      # [N,K] i32 (-1 none)
    promise_expire: jax.Array   # [N,K] i32
    # v1.1 score plane
    score: ScoreState
    scores: jax.Array           # [N,K] f32 (memoized per heartbeat,
                                # gossipsub.go:1333-1341)
    p6: jax.Array               # [N,K] f32 colocation surplus^2 (static topo)
    app_score: jax.Array        # [N] f32 (P5)
    # peer gater (peer_gater.go)
    gater: GaterState
    # fanout: per-peer slots for topics published to without joining
    # (gossipsub.go:444-447 fanout + lastpub maps)
    fanout_topic: jax.Array    # [N,F] i32, -1 free
    fanout_peers: jax.Array    # [N,F,K] bool
    fanout_lastpub: jax.Array  # [N,F] i32
    # peer lifecycle (dynamic_peers builds): effective liveness + blacklist.
    # up models the notify/dead-peer plane (notify.go:19-75, handleDeadPeers
    # pubsub.go:648-689); blacklist is the global-view blacklist
    # (blacklist.go:12-64, enforced at pubsub.go:1048-1060,636-639) — a
    # blacklisted peer is disconnected everywhere next round
    up: jax.Array              # [N] bool
    blacklist: jax.Array       # [N] bool
    # PX connection plane (do_px only): which provisioned edges are live.
    # Dormant edges (graph.dormant_edges) start False; a PRUNE carrying PX
    # (makePrune gossipsub.go:1814-1850) lets the pruned peer activate
    # dormant edges to suggested peers (pxConnect :861-941). Kept symmetric
    # over the edge involution.
    edge_live: jax.Array       # [N,K] bool
    # PX flag riding this round's PRUNEs (parallel outbox to prune_out)
    prune_px_out: jax.Array    # [N,S,K] bool
    # inbound-link saturation observed last round (queue_cap only; zeros
    # otherwise): congested_in[i,k] = the sender nbr[i,k]'s outbound queue
    # toward i was full. The host's announce-retry model reads it — a
    # SubOpts announcement riding a full queue is dropped and retried
    # with jitter (pubsub.go:861-901)
    congested_in: jax.Array    # [N,K] bool
    # exact-trace duplicate plane (cfg.trace_exact only, else None):
    # this round's arrivals beyond the first per (peer, msg), per edge —
    # the drain expands them to DuplicateMessage events (trace.go:186-194)
    dup_trans: jax.Array | None = None  # [N,K,W] u32
    # router plane (cfg.router, routers/, docs/DESIGN.md §24) — every
    # leaf None on v1.1 builds (the elision contract: the state TREE is
    # the pre-router one, which is what the smoke's bit-exact census
    # compares). dontwant ⊆ dlv.have by construction (fed from the
    # round's post-throttle new receipts); choked ⊆ mesh with at least
    # Dlo unchoked per slot (choke_guard, re-applied at every mesh
    # mutation site); inflight is the delayed-commit ring — edge axes
    # leading so it rides the CSR-resident tier flat as [E, L, W]
    dontwant: jax.Array | None = None    # [N,W] u32 announced ids
    choked: jax.Array | None = None      # [N,S,K] bool lazy-demoted mesh links
    choke_ema: jax.Array | None = None   # [N,K] f32 lateness EMA
    inflight: jax.Array | None = None    # [N,K,L,W] u32 ([E,L,W] flat)

    @classmethod
    def init(
        cls,
        net: Net,
        msg_slots: int,
        cfg: GossipSubConfig,
        score_params: PeerScoreParams | None = None,
        seed: int = 0,
        app_score: np.ndarray | None = None,
        dormant: np.ndarray | None = None,
        wire_block: bool = False,
        telemetry=None,
        dynamic_topo: bool = False,
    ) -> "GossipSubState":
        n, k = net.nbr.shape
        s = net.n_slots
        w = bitset.n_words(msg_slots)
        h = cfg.history_length
        if score_params is not None and cfg.score_enabled:
            p6 = ip_colocation_surplus_sq(
                net,
                score_params.ip_colocation_factor_threshold,
                score_params.ip_colocation_factor_whitelist,
            )
        else:
            p6 = jnp.zeros((n, k), jnp.float32)
        # CSR-resident tier (round 18): against an edge_layout="csr" Net
        # the per-edge planes allocate FLAT — fe_words/served_* as
        # [E, W], peerhave/iasked as [E] — dead padded slots are not
        # resident (MEM_AUDIT.json's csr rows; the steps densify them
        # transiently, state.wrap_csr_resident)
        e = net.n_edges  # None on dense builds
        ph_shape = (n, k) if e is None else (e,)
        sv_shape = (n, k, w) if e is None else (e, w)
        return cls(
            core=SimState.init(n, msg_slots, seed, k=k,
                               val_delay=cfg.validation_delay_rounds,
                               wire_block=wire_block,
                               chaos_ge=(cfg.chaos is not None
                                         and cfg.chaos.needs_state),
                               telemetry=telemetry,
                               n_edges=e,
                               # the state-resident mutable overlay
                               # (dynamic_topo builds): seeded from the
                               # build topology, mutated in place by the
                               # step's write batches
                               topo=(TopoState.from_net(net)
                                     if dynamic_topo else None)),
            mesh=jnp.zeros((n, s, k), bool),
            backoff_expire=jnp.zeros((n, s, k), jnp.int32),
            backoff_present=jnp.zeros((n, s, k), bool),
            mcache=jnp.zeros((n, h, w), jnp.uint32),
            ihave_out=jnp.zeros((n, k, w), jnp.uint32),
            iwant_out=jnp.zeros((n, k, w), jnp.uint32),
            graft_out=jnp.zeros((n, s, k), bool),
            prune_out=jnp.zeros((n, s, k), bool),
            # IHAVE flood-protection counters: int16 under the round-15
            # narrowing contract (cfg.narrow_counters — exact: heartbeat-
            # cleared, cap-bounded; build() refuses caps outside range)
            peerhave=jnp.zeros(
                ph_shape, jnp.int16 if cfg.narrow_counters else jnp.int32),
            iasked=jnp.zeros(
                ph_shape, jnp.int16 if cfg.narrow_counters else jnp.int32),
            served_lo=jnp.zeros(sv_shape, jnp.uint32),
            served_hi=jnp.zeros(sv_shape, jnp.uint32),
            promise_mid=jnp.full((n, k), -1, jnp.int32),
            promise_expire=jnp.zeros((n, k), jnp.int32),
            score=ScoreState.empty(n, s, k),
            scores=jnp.zeros((n, k), jnp.float32),
            p6=p6,
            app_score=jnp.zeros((n,), jnp.float32)
            if app_score is None
            else jnp.asarray(app_score, jnp.float32),
            gater=GaterState.empty(n, k),
            fanout_topic=jnp.full((n, cfg.fanout_slots), -1, jnp.int32),
            fanout_peers=jnp.zeros((n, cfg.fanout_slots, k), bool),
            fanout_lastpub=jnp.zeros((n, cfg.fanout_slots), jnp.int32),
            up=jnp.ones((n,), bool),
            blacklist=jnp.zeros((n,), bool),
            # copy, never alias: the step donates state buffers, and an
            # aliased net.nbr_ok would be deleted with them
            edge_live=net.nbr_ok & ~jnp.asarray(dormant, bool)
            if dormant is not None
            else jnp.copy(net.nbr_ok),
            prune_px_out=jnp.zeros((n, s, k), bool),
            congested_in=jnp.zeros((n, k), bool),
            dup_trans=(
                jnp.zeros((n, k, w), jnp.uint32) if cfg.trace_exact else None
            ),
            dontwant=(
                jnp.zeros((n, w), jnp.uint32)
                if cfg.router is not None and cfg.router.idontwant else None
            ),
            choked=(
                jnp.zeros((n, s, k), bool)
                if cfg.router is not None and cfg.router.choke else None
            ),
            choke_ema=(
                jnp.zeros((n, k), jnp.float32)
                if cfg.router is not None and cfg.router.choke else None
            ),
            inflight=(
                jnp.zeros(
                    (((n, k) if e is None else (e,))
                     + (cfg.router.latency_rounds, w)), jnp.uint32)
                if cfg.router is not None and cfg.router.latency_rounds > 0
                else None
            ),
        )


def msg_slot_of(net: Net, msg_topic: jax.Array) -> jax.Array:
    """[N, M] receiver topic-slot per message (-1 when not subscribed)."""
    t = jnp.clip(msg_topic, 0)
    s = net.slot_of[:, t]
    return jnp.where(msg_topic[None, :] >= 0, s, -1)


def joined_msg_words(net: Net, msgs) -> jax.Array:
    """[N, W]: messages in topics peer n has joined (mesh exists <=>
    subscribed in the sim) — the alias documents that equivalence."""
    return subscribed_msg_words(net, msgs)


# ---------------------------------------------------------------------------
# control-plane handlers (per round)


def handle_graft_prune(cfg: GossipSubConfig, net: Net, st: GossipSubState, tp: dict,
                       acc_ok: jax.Array, graft_in_raw: jax.Array,
                       prune_in_raw: jax.Array, px_in_raw, thr=None,
                       msh=None):
    """Process GRAFT/PRUNE received this round (handleGraft
    gossipsub.go:718-809, handlePrune :811-843). Returns updated state plus
    next round's PRUNE responses. `*_raw` are the pre-gathered edge views
    from the step's merged wire exchange (already nbr_ok-masked).
    ``thr`` is the threshold source — cfg (static floats, the default)
    or the traced ScoreParams plane of a lifted build (round 16).
    ``msh`` is the mesh-degree source — cfg, or the traced MeshParams
    plane of a candidate-lifted build (round 20)."""
    thr = cfg if thr is None else thr
    msh = cfg if msh is None else msh
    tick = st.core.tick

    graft_in = graft_in_raw & acc_ok[:, None, :]
    prune_in = prune_in_raw & acc_ok[:, None, :]

    # PX ingest (handlePrune gossipsub.go:834-841): a PRUNE carrying PX is
    # honored only if the pruner's score clears AcceptPXThreshold
    if cfg.do_px:
        px_in = px_in_raw & prune_in
        px_ok = jnp.any(px_in, axis=1) & (st.scores >= thr.accept_px_threshold)  # [N,K]
    else:
        px_ok = None

    # handlePrune: drop from mesh, obey backoff, sticky P3b
    pruned = prune_in & st.mesh
    score = on_prune(st.score, pruned, tp) if cfg.score_enabled else st.score
    mesh = st.mesh & ~prune_in
    backoff_expire = jnp.where(
        prune_in, jnp.maximum(st.backoff_expire, tick + cfg.prune_backoff_ticks),
        st.backoff_expire,
    )
    backoff_present = st.backoff_present | prune_in

    # handleGraft — a floodsub-only node doesn't speak meshsub and ignores
    # GRAFTs entirely (gossipsub_feat.go)
    want = graft_in & ~mesh & net.nbr_ok[:, None, :] & (net.protocol >= 1)[:, None, None]

    rej_direct = want & net.direct[:, None, :]  # gossipsub.go:742-750

    backoff_active = backoff_present & (tick < backoff_expire)
    rej_backoff = want & backoff_active          # gossipsub.go:753-770
    flood_cutoff = backoff_expire + (cfg.graft_flood_ticks - cfg.prune_backoff_ticks)
    flood = rej_backoff & (tick < flood_cutoff)  # gossipsub.go:760-765
    penalty_counts = jnp.sum(
        rej_backoff.astype(jnp.float32) + flood.astype(jnp.float32), axis=1
    )  # [N,K]

    if cfg.score_enabled:
        rej_score = want & (st.scores[:, None, :] < 0)  # gossipsub.go:772-783
    else:
        rej_score = jnp.zeros_like(want)

    mesh_deg = count_true(mesh)  # [N,S]
    rej_full = (
        want & (mesh_deg[:, :, None] >= msh.Dhi) & ~net.outbound[:, None, :]
    )  # gossipsub.go:785-792

    rejected = rej_direct | rej_backoff | rej_score | rej_full
    accepted = want & ~rejected

    mesh = mesh | accepted
    if cfg.score_enabled:
        score = on_graft(score, accepted, tick)
        score = add_penalties(score, penalty_counts)

    re_back = rej_backoff | rej_score | rej_full  # refresh/add backoff
    backoff_expire = jnp.where(
        re_back, jnp.maximum(backoff_expire, tick + cfg.prune_backoff_ticks), backoff_expire
    )
    backoff_present = backoff_present | re_back

    st = st.replace(
        mesh=mesh,
        backoff_expire=backoff_expire,
        backoff_present=backoff_present,
        score=score,
    )
    # graft-rejection PRUNEs carry PX for decently-scored peers (handleGraft
    # calls makePrune with doPX && score-ok, gossipsub.go:796-806); score-
    # rejections get none
    if cfg.do_px:
        # rejected & ~rej_score already implies score >= 0 (rej_score covers
        # every negative-score rejection)
        px_resp = rejected & ~rej_score
    else:
        px_resp = jnp.zeros_like(rejected)
    if cfg.count_events:
        n_graft = jnp.sum(accepted.astype(jnp.int32))
        n_prune = jnp.sum(pruned.astype(jnp.int32))
    else:
        n_graft = n_prune = jnp.int32(0)
    return st, rejected, px_resp, px_ok, n_graft, n_prune


_prefix_cap_bits = bitset.prefix_cap_bits


def handle_ihave(cfg: GossipSubConfig, net: Net, st: GossipSubState,
                 joined_words: jax.Array, acc_ok: jax.Array,
                 ihave_in_raw: jax.Array, thr=None) -> GossipSubState:
    """IHAVE received this round -> IWANT requests + a promise
    (handleIHave gossipsub.go:615-677). `ihave_in_raw` is the pre-gathered
    edge view from the step's merged wire exchange. ``thr`` is the
    threshold source (cfg, or a lifted build's traced plane)."""
    thr = cfg if thr is None else thr
    m = st.core.msgs.capacity
    tick = st.core.tick
    ihave_in = jnp.where(acc_ok[:, :, None], ihave_in_raw, jnp.uint32(0))

    got = bitset.popcount(ihave_in, axis=-1) > 0  # [N,K] one batch per round
    peerhave = st.peerhave + got.astype(st.peerhave.dtype)

    ok = got
    if cfg.score_enabled:
        ok = ok & (st.scores >= thr.gossip_threshold)  # gossipsub.go:616-621
    ok = ok & (peerhave <= cfg.max_ihave_messages)     # gossipsub.go:624-628
    ok = ok & (st.iasked < cfg.max_ihave_length)       # gossipsub.go:630-633

    wants = ihave_in & ~st.core.dlv.have[:, None, :] & joined_words[:, None, :]
    wants = jnp.where(ok[:, :, None], wants, jnp.uint32(0))

    # the MaxIHaveLength ask budget (gossipsub.go:655-658) can only bind if
    # one heartbeat's asks could exceed it; with msg_slots far below the cap
    # (the iasked >= cap gate above already ran) skip the prefix-cap pass
    if m * (cfg.heartbeat_every + 1) > cfg.max_ihave_length:
        budget = jnp.maximum(cfg.max_ihave_length - st.iasked, 0).astype(
            jnp.int32)  # the prefix-cap cumsum compares in int32
        asks = _prefix_cap_bits(wants, budget, m)
    else:
        asks = wants
    n_asked = bitset.popcount(asks, axis=-1)
    iasked = st.iasked + n_asked.astype(st.iasked.dtype)

    # adopt one promised mid per edge when none is outstanding
    first_ask, _has = bitset.lowest_bit(asks)
    adopt = (n_asked > 0) & (st.promise_mid < 0)
    promise_mid = jnp.where(adopt, first_ask, st.promise_mid)
    promise_expire = jnp.where(adopt, tick + cfg.iwant_followup_ticks, st.promise_expire)

    return st.replace(
        peerhave=peerhave,
        iasked=iasked,
        iwant_out=asks,
        promise_mid=promise_mid,
        promise_expire=promise_expire,
    )


def _served_capped(cfg: GossipSubConfig, lo: jax.Array, hi: jax.Array) -> jax.Array:
    """Word-mask of slots whose 2-bit served count has reached the
    retransmission cap (cap clamps to the counter range 0..3). Shared with
    the fused kernel so the two paths cannot drift."""
    return fr.served_capped_mask(cfg.gossip_retransmission, lo, hi)


def iwant_responses(cfg: GossipSubConfig, net: Net, st: GossipSubState,
                    nbr_score_of_me, window_g: jax.Array | None = None,
                    thr=None):
    """The IWANT-response carry for this round's delivery + retransmission
    counter update (handleIWant gossipsub.go:679-716). `st.iwant_out` holds
    what I asked each neighbor last round; the neighbor serves from its full
    mcache history window subject to the per-(edge,msg) cap.
    `nbr_score_of_me` [N,K] comes from the step's merged wire exchange
    (None only when scoring is disabled). ``window_g`` is the neighbors'
    gathered mcache-window plane when the coalesced wire exchange already
    carried it (None: gather here, the legacy extra permute set).
    ``thr`` is the threshold source (cfg, or a lifted plane)."""
    thr = cfg if thr is None else thr
    asked = st.iwant_out
    if window_g is None:
        sender_window = bitset.word_or_reduce(st.mcache, axis=1)   # [N,W]
        window_g = jnp.where(
            net.nbr_ok[:, :, None],
            net.peer_gather(sender_window),                         # [N,K,W]
            jnp.uint32(0),
        )
    capped = _served_capped(cfg, st.served_lo, st.served_hi)
    resp = asked & window_g & ~capped

    if cfg.score_enabled:
        # responder ignores requesters below the gossip threshold
        # (gossipsub.go:681-685): the score the neighbor holds of me
        resp = jnp.where(
            (nbr_score_of_me >= thr.gossip_threshold)[:, :, None], resp, jnp.uint32(0)
        )

    # 2-bit saturating increment on served slots
    sat = st.served_hi & st.served_lo
    inc = resp & ~sat
    carry = st.served_lo & inc
    lo = st.served_lo ^ inc
    hi = st.served_hi | carry
    return st.replace(served_lo=lo, served_hi=hi), resp


# ---------------------------------------------------------------------------
# delivery-edge selection


def sender_carry_words(mesh: jax.Array, slotw: jax.Array) -> jax.Array:
    """[N,K,W] sender-side: words each peer would push on edge k — the OR
    over its topic slots of (slot's topic messages) where the edge is in
    that slot's mesh. Word algebra only."""
    contrib = jnp.where(mesh[:, :, :, None], slotw[:, :, None, :], jnp.uint32(0))
    return bitset.word_or_reduce(contrib, axis=1)  # [N,K,W]


def fanout_topic_words(fanout_topic: jax.Array, msg_topic: jax.Array) -> jax.Array:
    """[N,F,W] packed: messages in the topic of fanout slot f. Direct
    compare+pack — the [N,F]-row gather from the tiny [T,W] table lowers
    to a slow TPU gather (same finding as slot_topic_words)."""
    bits = (
        msg_topic[None, None, :] == fanout_topic[:, :, None]
    ) & (msg_topic >= 0)[None, None, :]
    return bitset.pack(bits)


def fanout_carry_words(fanout_peers: jax.Array, fanout_topic: jax.Array,
                       msg_topic: jax.Array) -> jax.Array:
    """[N,K,W]: words each peer pushes on edge k for its fanout topics
    (gossipsub.go:1000-1002 — fanout peers receive published messages of
    unjoined topics)."""
    ftw = fanout_topic_words(fanout_topic, msg_topic)  # [N,F,W]
    contrib = jnp.where(fanout_peers[:, :, :, None], ftw[:, :, None, :], jnp.uint32(0))
    return bitset.word_or_reduce(contrib, axis=1)


# -- packed fanout-peer form (phase-loop internal) --------------------------
# The [N, F, K] bool peers plane is a pathological write target on TPU —
# bit-packed pred tiles make every sub-round update a read-modify-write
# over layout-padded tiles (the 2-axis scatter measured 670 us/round at
# eth2 N=100k, the P-step where-chain still 226 us). K <= 32, so the K
# axis packs into ONE u32 per (peer, slot): updates become [N, F] u32
# selects and the carry consumer extracts bits on the fly. The phase
# engine packs at its head and unpacks at its tail, so the state
# dataclass, the heartbeat, peer transitions, and every external consumer
# keep the bool plane.

def pack_fanout_peers(fanout_peers: jax.Array) -> jax.Array:
    """[N,F,K] bool -> [N,F] u32 edge bitmask (K <= 32)."""
    k = fanout_peers.shape[-1]
    assert k <= 32, "packed fanout form needs max_degree <= 32"
    w = jnp.uint32(1) << jnp.arange(k, dtype=jnp.uint32)
    return jnp.sum(
        jnp.where(fanout_peers, w, jnp.uint32(0)), axis=-1, dtype=jnp.uint32
    )


def unpack_fanout_peers(fp_pack: jax.Array, k: int) -> jax.Array:
    """[N,F] u32 -> [N,F,K] bool."""
    return (
        (fp_pack[:, :, None] >> jnp.arange(k, dtype=jnp.uint32)) & 1
    ).astype(bool)


def fanout_carry_words_packed(fp_pack: jax.Array, k: int,
                              fanout_topic: jax.Array,
                              msg_topic: jax.Array) -> jax.Array:
    """fanout_carry_words on the packed [N,F] u32 peers form (the
    on-the-fly unpack fuses into the carry fold — same XLA graph, but
    the loop reads 0.8 MB of packed words instead of the padded bool
    plane)."""
    return fanout_carry_words(
        unpack_fanout_peers(fp_pack, k), fanout_topic, msg_topic
    )


def gossip_edge_mask(cfg: GossipSubConfig, net: Net, st: GossipSubState,
                     joined_words: jax.Array, acc_ok: jax.Array,
                     slotw: jax.Array, msg_topic: jax.Array,
                     flood_edges: jax.Array, nbr_score_of_me,
                     thr=None) -> jax.Array:
    """[N,K,W] edge-carry mask: mesh push (forwarding along the sender's
    mesh, gossipsub.go:981-1002) + fanout push + floodsub-peer edges
    (protocol negotiation, gossipsub.go:973-978) + v1.1 flood-publish for
    origin-sent messages (gossipsub.go:957-963), gated by the receiver's
    graylist/gater.

    Sender-side packed outbox + word gather (no [N,K,M] traffic)."""
    thr = cfg if thr is None else thr
    carry_out = sender_carry_words(st.mesh, slotw)
    if cfg.fanout_slots > 0:
        carry_out = carry_out | fanout_carry_words(
            st.fanout_peers, st.fanout_topic, msg_topic
        )
    mask = jnp.where(
        net.nbr_ok[:, :, None],
        net.edge_gather(carry_out),
        jnp.uint32(0),
    )

    # floodsub-semantics edges (either endpoint is a floodsub peer): the
    # sender forwards everything; the receiver's joined filter applies below
    mask = mask | jnp.where(flood_edges[:, :, None], jnp.uint32(0xFFFFFFFF), jnp.uint32(0))

    if cfg.flood_publish:
        # origin floods to every topic peer it scores above publishThreshold;
        # elementwise compare fused into the pack
        origin_is_sender = st.core.msgs.origin[None, :] == net.nbr[..., None]  # [N,K,M]
        if cfg.score_enabled:
            flood_ok = nbr_score_of_me >= thr.publish_threshold
        else:
            flood_ok = net.nbr_ok
        mask = mask | (
            bitset.pack(origin_is_sender) & jnp.where(
                flood_ok[:, :, None], jnp.uint32(0xFFFFFFFF), jnp.uint32(0)
            )
        )

    mask = jnp.where(acc_ok[:, :, None], mask, jnp.uint32(0))
    return mask & joined_words[:, None, :]


def update_fanout_on_publish(
    cfg: GossipSubConfig,
    net: Net,
    st: "GossipSubState",
    pub_origin: jax.Array,  # [P] i32, -1 pad
    pub_topic: jax.Array,   # [P] i32
    key: jax.Array,
    nbr_sub_words: jax.Array,  # [N,K,Wt] static: neighbors' topic-bit subs
    fp_pack: jax.Array | None = None,
    thr=None,                  # threshold source (cfg | lifted plane)
    msh=None,                  # mesh-degree source (cfg | MeshParams)
):
    """Publishing to an unjoined topic creates/refreshes a fanout slot with
    D random eligible peers (gossipsub.go:983-998) and stamps lastpub.

    Returns the updated state — or, when ``fp_pack`` (the phase loop's
    packed [N,F] u32 peers form) is given, ``(state, fp_pack)`` with
    ``state.fanout_peers`` left untouched (stale; the phase tail unpacks
    the packed form back into it)."""
    thr = cfg if thr is None else thr
    msh = cfg if msh is None else msh
    tick = st.core.tick
    p_dim = pub_origin.shape[0]
    f_dim = cfg.fanout_slots
    o = jnp.clip(pub_origin, 0)
    t = jnp.clip(pub_topic, 0)
    is_pub = pub_origin >= 0
    joined = net.subscribed[o, t]
    # floodsub-only origins flood instead of tracking fanout
    need = is_pub & ~joined & (net.protocol[o] >= 1)

    # find a slot: existing topic match, else the oldest slot. Several
    # same-round fresh publishes by one origin must land on *different*
    # slots: offset each by its rank among that origin's earlier fresh
    # entries (pairwise over the small P axis).
    ftop_o = st.fanout_topic[o]  # [P,F]
    match = ftop_o == t[:, None]
    has_match = jnp.any(match & need[:, None], axis=1)
    match_slot = jnp.argmax(match, axis=1)
    oldest_slot = jnp.argmin(st.fanout_lastpub[o] + jnp.where(ftop_o >= 0, 0, -(2**30)), axis=1)
    fresh = need & ~has_match
    idx_p = jnp.arange(p_dim)
    same_origin_before = (
        fresh[None, :] & fresh[:, None]
        & (o[None, :] == o[:, None]) & (idx_p[None, :] < idx_p[:, None])
    )
    fresh_rank = jnp.sum(same_origin_before.astype(jnp.int32), axis=1)  # [P]
    slot = jnp.where(has_match, match_slot, (oldest_slot + fresh_rank) % f_dim)

    # a matched slot whose peer set has emptied (churn, threshold filtering)
    # is repopulated like a fresh one (gossipsub.go:983-989: empty fanout
    # map entry => select peers anew)
    if fp_pack is not None:
        match_empty = has_match & (
            jnp.take_along_axis(fp_pack[o], slot[:, None], axis=1)[:, 0] == 0
        )
    else:
        match_empty = has_match & (
            count_true(jnp.take_along_axis(st.fanout_peers[o], slot[:, None, None], axis=1)[:, 0, :]) == 0
        )
    fresh = fresh | match_empty

    # candidates for a fresh slot: connected, mesh-capable, subscribed to
    # the topic, not direct, score >= publishThreshold
    nbr_subbed = bitset.bit_get(
        nbr_sub_words[o], jnp.broadcast_to(t[:, None], (p_dim, net.max_degree))
    )
    cand = (
        nbr_subbed
        & net.nbr_ok[o]
        & (net.protocol[jnp.clip(net.nbr[o], 0)] >= 1)
        & ~net.direct[o]
    )
    if cfg.score_enabled:
        cand = cand & (st.scores[o] >= thr.publish_threshold)
    sel = masked_width_random(key, cand, msh.D, net.max_degree,
                              fused=cfg.fused)  # [P,K]

    # commit: new slots take the fresh selection; matched slots keep
    # theirs. A static fold of P masked selects over the [N, F] planes —
    # NOT a 2-axis scatter: .at[po, slot].set lowered to ~670 us/round
    # on the real chip at N=100k (47% of the whole eth2 phase round,
    # round-5 profile) to write <=P rows, while the P fused where-passes
    # cost plane bandwidth (~3 MB) once. Ascending-j overwrite keeps the
    # scatter's last-update-wins semantics for duplicate (origin, slot)
    # pairs in one batch.
    rows = jnp.arange(net.n_peers, dtype=jnp.int32)
    fslots = jnp.arange(f_dim, dtype=jnp.int32)
    fanout_topic = st.fanout_topic
    fanout_lastpub = st.fanout_lastpub
    # (a winner-index fold that touches the [N, F, K] plane once was
    # tried and measured WORSE — eth2 961 -> 555 rounds/s: the extra
    # [N, F] winner plane + two-chain combine broke the single loop
    # fusion XLA builds for this direct P-step where-chain)
    packed = fp_pack is not None
    sel_pack = pack_fanout_peers(sel) if packed else None  # [P] u32
    fanout_peers = st.fanout_peers
    for j in range(p_dim):
        mask = ((rows == jnp.where(need[j], o[j], net.n_peers))[:, None]
                & (fslots == slot[j])[None, :])  # [N, F]
        fanout_topic = jnp.where(mask, t[j], fanout_topic)
        fanout_lastpub = jnp.where(mask, tick, fanout_lastpub)
        if packed:
            fp_pack = jnp.where(mask & fresh[j], sel_pack[j], fp_pack)
        else:
            fanout_peers = jnp.where(
                (mask & fresh[j])[:, :, None], sel[j][None, None, :],
                fanout_peers,
            )
    if packed:
        return st.replace(
            fanout_topic=fanout_topic,
            fanout_lastpub=fanout_lastpub,
        ), fp_pack
    return st.replace(
        fanout_topic=fanout_topic,
        fanout_peers=fanout_peers,
        fanout_lastpub=fanout_lastpub,
    )


def merge_extra_tx(net: Net, msgs, dlv, info, extra: jax.Array, tick,
                   count_events: bool = True, queue_cap: int = 0,
                   val_delay_topic: tuple | None = None):
    """Fold IWANT-response transmissions (not part of senders' fwd sets)
    into the round's delivery results. With the async-validation pipeline
    these receipts enter stage 0 like any other arrival; their verdict
    (forward/Deliver/first_round) happens at pipeline exit.

    With `queue_cap` the responses share the link's outbound budget with
    the mesh push already in `info.trans` — overflow is dropped and
    counted (IWANT responses are ordinary messages in the reference's
    per-peer writer queue, comm.go:139-170)."""
    m = msgs.capacity
    val_delay = 0 if dlv.pending is None else dlv.pending.shape[1]
    extra = extra & ~origin_msg_words(net, msgs)[:, None, :]
    if msgs.wire_block is not None:
        # IWANT responses for oversized messages die at the wire too — but
        # only after the retransmission counter ticked (mcache.GetForPeer
        # counts the attempt before sendRPC drops it, mcache.go:66-80 ->
        # gossipsub.go:1126-1140), which iwant_responses already did
        extra = extra & ~bitset.pack(msgs.wire_block)[None, None, :]
    if queue_cap > 0:
        used = bitset.popcount(info.trans, axis=-1)  # [N,K]
        budget = jnp.maximum(queue_cap - used, 0)
        want = extra
        extra = _prefix_cap_bits(want, budget, m)
        info = info.replace(
            n_drop=info.n_drop
            + bitset.popcount(want & ~extra, axis=None).sum().astype(jnp.int32)
        )

    recv = bitset.word_or_reduce(extra, axis=1)
    new_words = recv & ~dlv.have
    new_bits = bitset.unpack(new_words, m)

    fa_words = bitset.first_set_per_bit(extra, axis=1) & new_words[:, None, :]
    valid_words = bitset.pack(msgs.valid)

    dlv = dlv.replace(
        have=dlv.have | new_words,
        fe_words=(dlv.fe_words & ~new_words[:, None, :]) | fa_words,
    )
    if val_delay > 0:
        from .common import pipeline_insert

        dlv = dlv.replace(
            pending=pipeline_insert(
                dlv.pending, new_words, msgs.topic, val_delay_topic
            )
        )
    else:
        dlv = dlv.replace(
            fwd=dlv.fwd | (new_words & valid_words[None, :]),
            first_round=jnp.where(new_bits, tick, dlv.first_round),
        )

    info = info.replace(
        trans=info.trans | extra,
        recv_new_words=info.recv_new_words | new_words,
    )
    if val_delay == 0:
        info = info.replace(
            new_words=info.new_words | new_words,
            new_bits=info.new_bits | new_bits,
        )
    if count_events:
        n_extra = bitset.popcount(extra, axis=-1).sum().astype(jnp.int32)
        n_new = bitset.popcount(new_words, axis=-1).sum().astype(jnp.int32)
        info = info.replace(
            n_duplicate=info.n_duplicate + (n_extra - n_new),
            n_rpc=info.n_rpc + n_extra,
        )
        if val_delay == 0:
            n_deliver = bitset.popcount(
                new_words & valid_words[None, :], axis=-1
            ).sum().astype(jnp.int32)
            info = info.replace(
                n_deliver=info.n_deliver + n_deliver,
                n_reject=info.n_reject + (n_new - n_deliver),
            )
    return dlv, info


# ---------------------------------------------------------------------------
# the heartbeat (gossipsub.go:1303-1564)


def heartbeat(cfg: GossipSubConfig, net: Net, st: GossipSubState, tp: dict,
              score_params: PeerScoreParams | None,
              nbr_sub: jax.Array, gater_params=None,
              nbr_sub_words: jax.Array | None = None,
              present_ok: jax.Array | None = None,
              gossip_suppress: jax.Array | None = None,
              app_gathered: jax.Array | None = None,
              adversary=None, thr=None, msh=None) -> GossipSubState:
    """`net` is the live view (nbr_ok masked by churn/edge-liveness);
    `present_ok` is the static edge-presence mask, needed by directConnect
    to re-dial edges that are currently dormant (defaults to net.nbr_ok).
    `gossip_suppress` [N,K] marks congested outbound links whose IHAVE
    batch is dropped this heartbeat (queue_cap backpressure).
    ``app_gathered`` is the pre-gathered P5 plane when the coalesced wire
    exchange carried it (app_score is phase-invariant, so the head gather
    equals the tail gather bit-for-bit).
    ``adversary`` (a chaos.adversary.AdversaryConsts, None = elided)
    applies the heartbeat-cadence attacker behaviors: self-promotion
    pins sybil-held scores of fellow sybils, graft-spam overwrites the
    GRAFT outbox ignoring backoff (and zeroes the attackers' own
    backoff bookkeeping — raw-wire fakes keep no router state), and
    lie-in-IHAVE advertises every live message id on every edge.
    ``thr`` is the threshold source (cfg, or a lifted build's traced
    ScoreParams plane — score_params is then that same plane).
    ``msh`` is the mesh-degree source (cfg, or a candidate-lifted
    build's traced MeshParams plane, round 20): every degree width it
    feeds goes through ops/select's masked-width kernels with the
    padded neighbor axis as the static ceiling."""
    thr = cfg if thr is None else thr
    msh = cfg if msh is None else msh
    tick = st.core.tick
    n, s_dim, k_dim = st.mesh.shape
    m = st.core.msgs.capacity
    key = jax.random.fold_in(st.core.key, tick)
    k1, k2, k3, k4, k5, k6 = jax.random.split(key, 6)
    events = st.core.events

    # applyIwantPenalties: broken promises -> P7 (gossipsub.go:1578-1583)
    # (one-hot word pick instead of an [N,K,M] compare-reduce)
    promised_have = bitset.bit_get(st.core.dlv.have[:, None, :], st.promise_mid)
    live = st.promise_mid >= 0
    fulfilled = live & promised_have
    broken = live & ~promised_have & (tick > st.promise_expire)
    score = st.score
    if cfg.score_enabled:
        score = add_penalties(score, broken.astype(jnp.float32))
    promise_mid = jnp.where(fulfilled | broken, -1, st.promise_mid)

    # clearIHaveCounters (gossipsub.go:1566-1576)
    peerhave = jnp.zeros_like(st.peerhave)
    iasked = jnp.zeros_like(st.iasked)

    # clearBackoff every 15 ticks with slack (gossipsub.go:1585-1604)
    clear_now = (tick % cfg.backoff_clear_ticks) == 0
    expired = (st.backoff_expire + cfg.backoff_slack_ticks) < tick
    backoff_present = jnp.where(clear_now, st.backoff_present & ~expired, st.backoff_present)
    # adversary graft-spam: attackers keep NO backoff bookkeeping (the
    # reference attacker is a raw-wire fake with no router state), and
    # the clear must land BEFORE the candidate filter below — a spam
    # attacker pruned last round re-grafts its victims immediately
    # (clearing only at the tail would leave the heartbeat's candidate
    # set backoff-excluded while the post-step state reads clear, a
    # decision/check mismatch the degree-bound oracle would flag)
    if adversary is not None and adversary.has("graft_spam"):
        spam_a = adversary.active_self("graft_spam", tick)
        backoff_present = jnp.where(spam_a[:, None, None], False,
                                    backoff_present)

    # refreshScores + memoized score cache (gossipsub.go:1333-1341)
    if cfg.score_enabled:
        score = refresh_scores(score, st.mesh, tick, tp, score_params)
        scores = compute_scores(score, st.mesh, tp, score_params, st.p6,
                                st.app_score, net, app_gathered=app_gathered)
        # adversary self-promotion (chaos/adversary.py): cooperating
        # sybils pin their held scores of FELLOW sybils at the promo
        # value — applied to the memoized plane at refresh, so every
        # consumer (mesh maintenance, gossip targeting, accept gates,
        # the wire score column) sees the faction's cohesion; honest
        # peers' scoring of sybils (the defense) is untouched
        if adversary is not None and adversary.has("self_promo"):
            promo = adversary.active_self("self_promo", tick)
            scores = jnp.where(promo[:, None] & adversary.sybil_nbr,
                               adversary.promo_score, scores)
    else:
        scores = st.scores

    # gater counter decay (peer_gater.go:204-216; DecayInterval default ==
    # the heartbeat interval)
    gater_state = st.gater
    if cfg.gater_enabled:
        gater_state = gater_decay(gater_state, gater_params)

    # ---- mesh maintenance per (peer, topic-slot) ------------------------
    # floodsub-only nodes run no mesh/gossip machinery at all
    mesh = st.mesh
    slot_live = (net.my_topics >= 0) & (net.protocol >= 1)[:, None]
    connected = net.nbr_ok[:, None, :] & slot_live[:, :, None]
    scores_b = jnp.broadcast_to(scores[:, None, :], mesh.shape)

    tograft = jnp.zeros_like(mesh)
    toprune = jnp.zeros_like(mesh)

    # drop negative-score mesh members, no PX (gossipsub.go:1361-1368)
    if cfg.score_enabled:
        bad = mesh & (scores_b < 0)
        toprune = toprune | bad
        mesh = mesh & ~bad

    # candidate filter (gossipsub.go:1374-1380): backoff *presence*
    cand = connected & nbr_sub & ~mesh & ~backoff_present & ~net.direct[:, None, :]
    if cfg.score_enabled:
        cand = cand & (scores_b >= 0)

    # Each maintenance sub-pass below is lax.cond-gated on "any row needs
    # it": in a converged mesh the low-degree/over-subscription/quota cases
    # are rare, and skipping their selection ranks most ticks is pure win
    # (both branches produce identical results to the unconditional code —
    # a selection with an all-zero need-vector is the empty mask).

    # |mesh| < Dlo -> graft to D (gossipsub.go:1371-1385)
    deg = count_true(mesh)
    ineed = jnp.where(deg < msh.Dlo, msh.D - deg, 0)
    grafts = jax.lax.cond(
        jnp.any(ineed > 0),
        lambda: masked_width_random(k1, cand, ineed, k_dim, fused=cfg.fused),
        lambda: jnp.zeros_like(mesh),
    )
    mesh = mesh | grafts
    tograft = tograft | grafts

    # |mesh| > Dhi -> keep Dscore best + random to D, Dout outbound
    # (gossipsub.go:1388-1448)
    deg = count_true(mesh)
    over = (deg > msh.Dhi)[:, :, None]
    outb = jnp.broadcast_to(net.outbound[:, None, :], mesh.shape)

    def _over_subscribed():
        noise = jax.random.uniform(k2, mesh.shape)
        if cfg.score_enabled:
            topscore = masked_width_topk(scores_b, mesh, msh.Dscore, k_dim,
                                         key=k3, fused=cfg.fused)
        else:
            topscore = masked_width_random(k3, mesh, msh.Dscore, k_dim,
                                           fused=cfg.fused)
        rest_rand = masked_width_topk(noise, mesh & ~topscore,
                                      msh.D - msh.Dscore, k_dim,
                                      fused=cfg.fused)
        keep = topscore | rest_rand
        x_need = jnp.maximum(msh.Dout - count_true(keep & outb), 0)
        bring = select_topk_mask(noise, mesh & outb & ~keep, x_need,
                                 fused=cfg.fused)
        drop = select_topk_mask(-noise, keep & ~outb & ~topscore,
                                count_true(bring), fused=cfg.fused)
        keep = (keep & ~drop) | bring
        pruned_over = mesh & ~keep & over
        return jnp.where(over, mesh & keep, mesh), pruned_over

    mesh, pruned_over = jax.lax.cond(
        jnp.any(over),
        _over_subscribed,
        lambda: (mesh, jnp.zeros_like(mesh)),
    )
    toprune = toprune | pruned_over
    # over-subscription prunes carry PX; score-prunes (`bad` above) are
    # noPX (gossipsub.go:1365 vs :1446 — makePrune's doPX argument)
    if cfg.do_px:
        px_prune = pruned_over & (scores_b >= 0 if cfg.score_enabled else True)
    else:
        px_prune = jnp.zeros_like(pruned_over)

    # outbound quota top-up at Dlo <= |mesh| (gossipsub.go:1451-1476)
    deg = count_true(mesh)
    need_out = jnp.where(
        deg >= msh.Dlo, jnp.maximum(msh.Dout - count_true(mesh & outb), 0), 0
    )
    grafts2 = jax.lax.cond(
        jnp.any(need_out > 0),
        lambda: masked_width_random(k4, cand & outb & ~mesh, need_out, k_dim,
                                    fused=cfg.fused),
        lambda: jnp.zeros_like(mesh),
    )
    mesh = mesh | grafts2
    tograft = tograft | grafts2

    # opportunistic grafting (gossipsub.go:1479-1510)
    if cfg.score_enabled and cfg.opportunistic_graft_ticks > 0:
        def _oppo_grafts():
            med = median_masked(scores_b, mesh)  # [N,S]
            low = (med < thr.opportunistic_graft_threshold) & (count_true(mesh) > 1)
            cand3 = cand & ~mesh & (scores_b > med[:, :, None])
            return select_random_mask(
                k5, cand3, jnp.where(low, cfg.opportunistic_graft_peers, 0),
                fused=cfg.fused,
            )

        grafts3 = jax.lax.cond(
            (tick % cfg.opportunistic_graft_ticks) == 0,
            _oppo_grafts,
            lambda: jnp.zeros_like(mesh),
        )
        mesh = mesh | grafts3
        tograft = tograft | grafts3

    new_grafts = tograft & ~st.mesh
    if cfg.score_enabled:
        score = on_graft(score, new_grafts, tick)
        score = on_prune(score, toprune, tp)
    backoff_expire = jnp.where(
        toprune, jnp.maximum(st.backoff_expire, tick + cfg.prune_backoff_ticks),
        st.backoff_expire,
    )
    backoff_present = backoff_present | toprune

    # ---- fanout maintenance (gossipsub.go:1517-1554) --------------------
    ft = st.fanout_topic
    fpeers = st.fanout_peers
    flastpub = st.fanout_lastpub
    if nbr_sub_words is not None and cfg.fanout_slots > 0:
        # expire by FanoutTTL since last publish (gossipsub.go:1518-1524)
        expired = (ft >= 0) & (flastpub + cfg.fanout_ttl_ticks < tick)
        ft = jnp.where(expired, -1, ft)
        f_live = ft >= 0
        fpeers = fpeers & f_live[:, :, None]
        # drop peers below the publish threshold (gossipsub.go:1528-1534)
        if cfg.score_enabled:
            fpeers = fpeers & (scores[:, None, :] >= thr.publish_threshold)
        # neighbor-subscribes-fanout-topic via topic-bit extraction
        n_f, f_dim = ft.shape
        nbr_sub_f = bitset.bit_get(
            jnp.broadcast_to(
                nbr_sub_words[:, None, :, :], (n_f, f_dim) + nbr_sub_words.shape[1:]
            ),
            jnp.broadcast_to(jnp.clip(ft, 0)[:, :, None], fpeers.shape),
        )
        mesh_capable = (net.peer_gather(net.protocol) >= 1) & net.nbr_ok
        base_f = (
            nbr_sub_f
            & mesh_capable[:, None, :]
            & ~net.direct[:, None, :]
            & f_live[:, :, None]
        )
        cand_f = base_f & ~fpeers
        if cfg.score_enabled:
            cand_f = cand_f & (scores[:, None, :] >= thr.publish_threshold)
        ineed_f = jnp.where(f_live, msh.D - count_true(fpeers), 0)
        kf1, kf2 = jax.random.split(jax.random.fold_in(key, 11))
        fpeers = fpeers | masked_width_random(kf1, cand_f, ineed_f, k_dim,
                                              fused=cfg.fused)

    # ---- choke/unchoke decision (routers/choke.py, DESIGN.md §24b) ------
    # after mesh maintenance (the guard must see the post-maintenance
    # mesh), before emitGossip (whose targets fold the choked links in).
    # The sender learns it is choked via ONE extra edge gather — the
    # choke annotation piggybacks the heartbeat's control batch (an
    # instant-knowledge approximation of the one-RTT outbox model,
    # documented in §24b; the suppression itself is receiver-local).
    router = cfg.router
    choked_by = None
    if router is not None and router.choke:
        choked_next = choke_guard(msh.Dlo, mesh, st.choked)
        choked_next, n_choke, n_unchoke = choke_decide(
            router, msh.Dlo, mesh, choked_next, st.choke_ema,
            fused=cfg.fused,
        )
        choked_by = net.edge_gather(jnp.any(choked_next, axis=1)) & net.nbr_ok
        if cfg.count_events:
            events = (
                events.at[EV.CHOKE].add(n_choke)
                .at[EV.UNCHOKE].add(n_unchoke)
            )

    # ---- emitGossip (gossipsub.go:1669-1723) ----------------------------
    gwin = bitset.word_or_reduce(st.mcache[:, : cfg.history_gossip, :], axis=1)  # [N,W]
    gossip_cand = connected & nbr_sub & ~mesh & ~net.direct[:, None, :]
    if gossip_suppress is not None:
        gossip_cand = gossip_cand & ~gossip_suppress[:, None, :]
    if cfg.score_enabled:
        gossip_cand = gossip_cand & (scores_b >= thr.gossip_threshold)
    n_cand = count_true(gossip_cand)
    target = jnp.maximum(
        msh.Dlazy,
        (jnp.asarray(msh.gossip_factor, jnp.float32)
         * n_cand.astype(jnp.float32)).astype(jnp.int32),
    )
    chosen = masked_width_random(k6, gossip_cand, target, k_dim,
                                 fused=cfg.fused)  # [N,S,K]
    if choked_by is not None:
        # a choked mesh link is IHAVE-only: the choked sender ALWAYS
        # gossips to the choking neighbor (not a lottery entry — episub's
        # lazy links carry every id), so ids keep flowing and IWANT
        # service keeps working while eager data is suppressed
        chosen = chosen | (
            connected & nbr_sub & choked_by[:, None, :]
            & ~net.direct[:, None, :]
        )

    slot_tw = slot_topic_words(net, st.core.msgs.topic)  # [N,S,W]
    adv = jnp.where(
        chosen[..., None], (gwin[:, None, :] & slot_tw)[:, :, None, :], jnp.uint32(0)
    )  # [N,S,K,W]
    ihave_out = bitset.word_or_reduce(adv, axis=1)  # [N,K,W]

    # fanout-topic gossip (gossipsub.go:1551-1553; fanout peers excluded)
    if nbr_sub_words is not None and cfg.fanout_slots > 0:
        gossip_cand_f = base_f & ~fpeers
        if gossip_suppress is not None:
            gossip_cand_f = gossip_cand_f & ~gossip_suppress[:, None, :]
        if cfg.score_enabled:
            gossip_cand_f = gossip_cand_f & (scores[:, None, :] >= thr.gossip_threshold)
        n_cand_f = count_true(gossip_cand_f)
        target_f = jnp.where(
            (ft >= 0),
            jnp.maximum(
                msh.Dlazy,
                (jnp.asarray(msh.gossip_factor, jnp.float32)
                 * n_cand_f.astype(jnp.float32)).astype(jnp.int32),
            ),
            0,
        )
        chosen_f = masked_width_random(kf2, gossip_cand_f, target_f, k_dim,
                                       fused=cfg.fused)  # [N,F,K]
        ftw = fanout_topic_words(ft, st.core.msgs.topic)
        adv_f = jnp.where(
            chosen_f[..., None], (gwin[:, None, :] & ftw)[:, :, None, :], jnp.uint32(0)
        )
        ihave_out = ihave_out | bitset.word_or_reduce(adv_f, axis=1)

    # mcache.Shift (gossipsub.go:1563)
    mcache = jnp.concatenate(
        [jnp.zeros_like(st.mcache[:, :1, :]), st.mcache[:, :-1, :]], axis=1
    )

    # directConnect (gossipsub.go:1606-1628): every DirectConnectTicks,
    # re-dial direct peers — in the PX edge-liveness model, a dormant
    # direct edge reactivates (both directions)
    edge_live = st.edge_live
    if cfg.do_px and cfg.direct_connect_ticks > 0:
        direct_sym = net.direct | net.edge_gather(net.direct)
        # tick 0 is skipped: the reference delays the first dial
        # (DirectConnectInitialDelay) past connection setup
        redial = ((tick % cfg.direct_connect_ticks) == 0) & (tick > 0)
        ok = net.nbr_ok if present_ok is None else present_ok
        edge_live = jnp.where(redial, edge_live | (direct_sym & ok), edge_live)

    # ---- adversary heartbeat behaviors (chaos/adversary.py §13) ---------
    graft_out_next = new_grafts
    if adversary is not None:
        if adversary.has("graft_spam"):
            # GRAFT every eligible (live slot, edge) ignoring backoff
            # (the GRAFT-flood attacker, gossipsub_spam_test.go:365);
            # spam attackers keep no backoff bookkeeping of their own —
            # the reference attacker is a raw-wire fake with no router
            # state — so their planes zero (the oracle plane's backoff
            # properties quantify over peers that RUN the router)
            spam_a = adversary.active_self("graft_spam", tick)
            spam = (spam_a[:, None, None] & slot_live[:, :, None]
                    & adversary.spam_edges[:, None, :])
            graft_out_next = graft_out_next | spam
            backoff_present = jnp.where(spam_a[:, None, None], False,
                                        backoff_present)
            backoff_expire = jnp.where(spam_a[:, None, None], 0,
                                       backoff_expire)
            if cfg.count_events:
                events = events.at[EV.ADV_GRAFT_SPAM].add(
                    jnp.sum(spam.astype(jnp.int32)))
        if adversary.has("lie_ihave"):
            # advertise EVERY live message id on every present edge,
            # held or not (IHAVE spam, gossipsub_spam_test.go:290) —
            # the victims' IWANTs go unserved (the attacker's real
            # mcache lacks the ids), breaking gossip promises → P7
            lie_a = adversary.active_self("lie_ihave", tick)
            live_w = bitset.pack(st.core.msgs.birth >= 0)     # [W]
            lie = jnp.where((lie_a[:, None] & net.nbr_ok)[:, :, None],
                            live_w[None, None, :], jnp.uint32(0))
            if cfg.count_events:
                events = events.at[EV.ADV_IHAVE_LIE].add(
                    bitset.popcount(lie & ~ihave_out, axis=None)
                    .sum().astype(jnp.int32))
            ihave_out = ihave_out | lie

    if cfg.count_events:
        events = (
            events.at[EV.GRAFT].add(jnp.sum(new_grafts.astype(jnp.int32)))
            .at[EV.PRUNE].add(jnp.sum(toprune.astype(jnp.int32)))
        )

    return st.replace(
        core=st.core.replace(events=events),
        mesh=mesh,
        edge_live=edge_live,
        backoff_expire=backoff_expire,
        backoff_present=backoff_present,
        mcache=mcache,
        ihave_out=ihave_out,
        graft_out=graft_out_next,
        prune_out=st.prune_out | toprune,
        prune_px_out=st.prune_px_out | px_prune,
        peerhave=peerhave,
        iasked=iasked,
        promise_mid=promise_mid,
        score=score,
        scores=scores,
        gater=gater_state,
        fanout_topic=ft,
        fanout_peers=fpeers,
        fanout_lastpub=flastpub,
        **({"choked": choked_next}
           if router is not None and router.choke else {}),
    )


def gather_nbr_subscribed(net: Net) -> jax.Array:
    """[N,S,K]: neighbor k subscribes the topic of my slot s."""
    n, s_dim = net.my_topics.shape
    k_dim = net.nbr.shape[1]
    sub_nbr = net.subscribed[jnp.clip(net.nbr, 0)]  # [N,K,T]
    out = jnp.take_along_axis(
        sub_nbr, jnp.broadcast_to(jnp.clip(net.my_topics, 0)[:, None, :], (n, k_dim, s_dim)),
        axis=2,
    ).transpose(0, 2, 1)
    return out & net.nbr_ok[:, None, :] & (net.my_topics >= 0)[:, :, None]


# ---------------------------------------------------------------------------
# the full per-round step


def apply_validation_throttle(dlv, info, cap: int, m: int, valid_words):
    """Model the validation front-end queue (validation.go:230-244 Push with
    a full queue => RejectValidationThrottled): each peer admits at most
    `cap` new receipts per round; overflow receipts are refused — not marked
    seen, not forwarded, no score attribution (score.go:745-749,761-767).
    The cap applies at queue admission (this round's fresh receipts), so
    with the async pipeline it clears stage 0 instead of the verdict state.

    Returns (dlv, info, accepted_new_words, n_throttled[N])."""
    val_delay = 0 if dlv.pending is None else dlv.pending.shape[1]
    entry = info.recv_new_words
    # static cap: the clear-lowest-bit chain, not the unpack+cumsum form
    # (this runs per SUB-ROUND under the phase engine — the cumsum was
    # 55% of the sybil phase round, bitset.keep_lowest_bits docstring)
    accepted = bitset.keep_lowest_bits(entry, cap, m)
    refused = entry & ~accepted
    n_throttled = bitset.popcount(refused, axis=-1)
    n_ref = n_throttled.sum().astype(jnp.int32)

    if val_delay > 0:
        # refused receipts are fresh this round, so they sit in exactly
        # their entry stage; clearing every stage is equivalent and works
        # for any per-topic entry pattern
        dlv = dlv.replace(
            have=dlv.have & ~refused,
            fe_words=dlv.fe_words & ~refused[:, None, :],
            pending=dlv.pending & ~refused[:, None, :],
        )
        # this round's verdicts (pipeline exits) are unaffected; throttled
        # receipts trace Reject now
        info = info.replace(
            recv_new_words=accepted,
            n_reject=info.n_reject + n_ref,
        )
        return dlv, info, info.new_words, n_throttled

    refused_bits = bitset.unpack(refused, m)
    dlv = dlv.replace(
        have=dlv.have & ~refused,
        fwd=dlv.fwd & ~refused,
        first_round=jnp.where(refused_bits, -1, dlv.first_round),
        fe_words=dlv.fe_words & ~refused[:, None, :],
    )
    info = info.replace(
        new_words=accepted,
        new_bits=bitset.unpack(accepted, m),
        recv_new_words=accepted,
        # accepted-valid deliver; accepted-invalid + throttled trace Reject
        n_deliver=bitset.popcount(accepted & valid_words[None, :], axis=-1).sum().astype(jnp.int32),
        n_reject=bitset.popcount(accepted & ~valid_words[None, :], axis=-1).sum().astype(jnp.int32) + n_ref,
    )
    return dlv, info, accepted, n_throttled


class StepConsts:
    """Static per-topology jit constants shared by the per-round step
    (`make_gossipsub_step`) and the multi-round phase step
    (`gossipsub_phase.make_gossipsub_phase_step`). Computed eagerly once
    at build time."""

    __slots__ = (
        "score_params", "tp", "tpa", "window_rounds_t", "nbr_sub_const",
        "flood_from", "i_am_floodsub", "nbr_sub_words", "sender_fwd_ok",
        "adv",
    )

    def __init__(self, **kw):
        for k in self.__slots__:
            setattr(self, k, kw[k])


def topology_views(net: Net):
    """The neighbor-derived topology views the step reads every round:
    (nbr_sub, flood_from, nbr_sub_words). Static builds compute them
    once, eagerly, in `prepare_step_consts`; dynamic-overlay builds
    (``dynamic_topo=True``) recompute them on device each round from the
    mutated edge planes — same expressions, traced instead of baked, so
    the two paths can never drift apart.

    ``i_am_floodsub`` is NOT here: a peer's protocol never changes
    across mutations (death + replacement revives the same peer id with
    its protocol), so it stays a jit constant even under dynamics."""
    # mesh candidates require a mesh-capable far end (gossipsub_feat.go
    # GossipSubFeatureMesh; checked at gossipsub.go:1374,1692)
    mesh_capable = (net.protocol[jnp.clip(net.nbr, 0)] >= 1) & net.nbr_ok
    nbr_sub = gather_nbr_subscribed(net) & mesh_capable[:, None, :]
    # floodsub-semantics edges: the far end only speaks /floodsub/1.0.0
    flood_from = (net.protocol[jnp.clip(net.nbr, 0)] == 0) & net.nbr_ok
    # neighbors' full subscriptions as topic-bit words (for fanout checks)
    subscribed_words_t = bitset.pack(net.subscribed)  # [N, Wt]
    nbr_sub_words = jnp.where(
        net.nbr_ok[:, :, None],
        subscribed_words_t[jnp.clip(net.nbr, 0)],
        jnp.uint32(0),
    )  # [N,K,Wt]
    return nbr_sub, flood_from, nbr_sub_words


def prepare_step_consts(
    cfg: GossipSubConfig,
    net: Net,
    score_params: PeerScoreParams | None,
    heartbeat_interval: float,
    gater_params,
    sub_knowledge_holes: np.ndarray | None,
    adversary_no_forward: np.ndarray | None,
    adversary=None,
) -> StepConsts:
    """Validate the configuration and build the static topology constants
    (see the field comments inline — each maps a reference-side check)."""
    if cfg.edge_layout != net.edge_layout:
        # the layout is a FROZEN static: one engine build traces exactly
        # one layout (docs/DESIGN.md §15) — a config/net mismatch would
        # silently trace the net's layout while the fingerprint records
        # the config's
        raise ValueError(
            f"cfg.edge_layout={cfg.edge_layout!r} but the Net was built "
            f"with edge_layout={net.edge_layout!r} — build both with the "
            "same layout (Net.build(..., edge_layout=...))"
        )
    if cfg.fused != net.fused:
        # same frozen-static contract as edge_layout (round 21): the
        # fused flag selects one kernel set per build — the config
        # drives the selection/heartbeat blocks, the net drives the
        # shared delivery seam, and a mismatch would trace half of each
        raise ValueError(
            f"cfg.fused={cfg.fused!r} but the Net was built with "
            f"fused={net.fused!r} — build both with the same flag "
            "(Net.build(..., fused=...))"
        )
    if cfg.gater_enabled:
        assert gater_params is not None
        gater_params.validate()
    if cfg.validation_delay_topic is not None and (
        len(cfg.validation_delay_topic) != net.n_topics
    ):
        # the engine's per-message delay gather would silently clamp
        # out-of-range topic ids; reject the mismatch at build time
        raise ValueError(
            f"validation_delay_topic has {len(cfg.validation_delay_topic)} "
            f"entries for a {net.n_topics}-topic universe"
        )
    if cfg.score_enabled:
        assert score_params is not None
        score_params.validate()
        tpa = TopicParamsArrays.build(score_params, net.n_topics, heartbeat_interval)
    else:
        score_params = PeerScoreParams(topics={}, skip_app_specific=True)
        tpa = TopicParamsArrays.build(score_params, net.n_topics)
    tp = tpa.gather(net.my_topics)
    window_rounds_t = jnp.asarray(tpa.window_rounds)
    nbr_sub_const, flood_from, nbr_sub_words = topology_views(net)
    # announce-visibility holes (pubsub.go:842-901): sub_knowledge_holes
    # [N,K,T] marks (receiver i, edge k, topic t) triples whose SubOpts
    # announcement has not yet arrived — the unannounced subscriber is
    # invisible to mesh-candidate selection, gossip targeting, and fanout
    # (the host's announce-retry model under queue_cap supplies the mask
    # and recompiles as announcements land; api.Network._process_announces)
    if sub_knowledge_holes is not None:
        _holes = np.asarray(sub_knowledge_holes, bool)  # [N,K,T]
        _mt = np.asarray(net.my_topics)                 # [N,S]
        _hs = np.take_along_axis(
            _holes, np.clip(_mt, 0, None)[:, None, :], axis=2
        ).transpose(0, 2, 1)                            # [N,S,K]
        _hs = _hs & (_mt >= 0)[:, :, None]
        nbr_sub_const = nbr_sub_const & ~jnp.asarray(_hs)
    i_am_floodsub = net.protocol == 0
    if sub_knowledge_holes is not None:
        # unannounced subscriptions are invisible to fanout selection too
        nbr_sub_words = nbr_sub_words & ~bitset.pack(
            jnp.asarray(np.asarray(sub_knowledge_holes, bool))
        )
    # adversary behavior vector: edge (j,k) carries data only if its sender
    # nbr[j,k] forwards (static jit constant; None => all-honest fast path)
    if adversary_no_forward is not None:
        adv = jnp.asarray(adversary_no_forward, bool)
        sender_fwd_ok = ~adv[jnp.clip(net.nbr, 0)] & net.nbr_ok  # [N,K]
    else:
        sender_fwd_ok = None
    # adversary plane (chaos/adversary.py): None elides it statically;
    # when live, every per-peer plane and its neighbor view is an EAGER
    # jit constant here, so per-round activity tests are elementwise
    # compares against the tick — zero extra halo permutes
    adversary = adversary_mod.resolve(adversary)
    adv_consts = (
        adversary_mod.AdversaryConsts(adversary, net)
        if adversary is not None else None
    )
    return StepConsts(
        score_params=score_params, tp=tp, tpa=tpa,
        window_rounds_t=window_rounds_t, nbr_sub_const=nbr_sub_const,
        flood_from=flood_from, i_am_floodsub=i_am_floodsub,
        nbr_sub_words=nbr_sub_words, sender_fwd_ok=sender_fwd_ok,
        adv=adv_consts,
    )


def apply_peer_transitions(cfg: GossipSubConfig, net: Net, st: GossipSubState,
                           up_next: jax.Array, tp: dict):
    """Peer lifecycle transitions (dynamic_peers builds): disconnect
    down/blacklisted peers with full dead-peer cleanup (handleDeadPeers
    pubsub.go:648-689 + router RemovePeer gossipsub.go:545-562 + score
    retention score.go:604-689). Returns (st, live-edge mask)."""
    eff_next = up_next & ~st.blacklist
    down_tr = st.up & ~eff_next
    up_tr = ~st.up & eff_next
    down_nbr = net.peer_gather(down_tr) & net.nbr_ok
    # every edge touching a down peer dies (both directions; a
    # restarting node comes back with fresh soft state)
    down_edge = (down_nbr | down_tr[:, None]) & net.nbr_ok
    de3 = down_edge[:, None, :]
    score0 = st.score
    if cfg.score_enabled:
        # removePeer (score.go:604-637): first convert any standing
        # P3 deficit on mesh edges of the departing peer into the
        # one-shot sticky P3b penalty, then drop in-mesh status on
        # every dead edge; only then delete stats — except retained
        # (negative-score) neighbors, whose counters keep decaying
        score0 = on_prune(score0, st.mesh & down_nbr[:, None, :], tp)
        score0 = clear_mesh_status(score0, down_nbr)
        clear_mask = (down_nbr & (st.scores >= 0)) | down_tr[:, None]
        score0 = clear_edges(score0, clear_mask)
    # a crashing node loses all soft state: seen-cache, forward set,
    # receipt history (it will re-receive after restart), mcache
    dlv0 = st.core.dlv.replace(
        have=jnp.where(down_tr[:, None], jnp.uint32(0), st.core.dlv.have),
        fwd=jnp.where(down_tr[:, None], jnp.uint32(0), st.core.dlv.fwd),
        first_round=jnp.where(down_tr[:, None], -1, st.core.dlv.first_round),
        fe_words=jnp.where(
            down_tr[:, None, None], jnp.uint32(0), st.core.dlv.fe_words
        ),
        pending=jnp.where(
            down_tr[:, None, None], jnp.uint32(0), st.core.dlv.pending
        ) if st.core.dlv.pending is not None else None,
    )
    ev0 = st.core.events
    if cfg.count_events:
        ev0 = (
            ev0
            .at[EV.REMOVE_PEER].add(jnp.sum(down_tr.astype(jnp.int32)))
            .at[EV.ADD_PEER].add(jnp.sum(up_tr.astype(jnp.int32)))
        )
    # router plane cleanup (cfg.router builds): a crashing announcer
    # forgets its IDONTWANT set with the rest of its soft state; choke
    # state and in-flight delayed commits die with their edges (the
    # guard re-establishes choked ⊆ mesh and the Dlo floor against the
    # post-churn mesh — a death that took an unchoked link fails open)
    router_clear = {}
    if st.dontwant is not None:
        router_clear["dontwant"] = jnp.where(
            down_tr[:, None], jnp.uint32(0), st.dontwant)
    if st.choked is not None:
        router_clear["choked"] = choke_guard(
            cfg.Dlo, st.mesh & ~de3, st.choked & ~de3)
        router_clear["choke_ema"] = jnp.where(down_edge, 0.0, st.choke_ema)
    if st.inflight is not None:
        router_clear["inflight"] = jnp.where(
            down_edge[:, :, None, None], jnp.uint32(0), st.inflight)
    st = st.replace(
        core=st.core.replace(dlv=dlv0, events=ev0),
        mcache=jnp.where(down_tr[:, None, None], jnp.uint32(0), st.mcache),
        mesh=st.mesh & ~de3,
        fanout_peers=st.fanout_peers & ~de3,
        **router_clear,
        graft_out=st.graft_out & ~de3,
        prune_out=st.prune_out & ~de3,
        ihave_out=jnp.where(down_edge[:, :, None], jnp.uint32(0), st.ihave_out),
        iwant_out=jnp.where(down_edge[:, :, None], jnp.uint32(0), st.iwant_out),
        served_lo=jnp.where(down_edge[:, :, None], jnp.uint32(0), st.served_lo),
        served_hi=jnp.where(down_edge[:, :, None], jnp.uint32(0), st.served_hi),
        peerhave=jnp.where(down_edge, 0, st.peerhave),
        iasked=jnp.where(down_edge, 0, st.iasked),
        promise_mid=jnp.where(down_edge, -1, st.promise_mid),
        score=score0,
        up=eff_next,
    )
    live = net.nbr_ok & st.up[:, None] & net.peer_gather(st.up)
    return st, live


def clear_mutated_edges(cfg: GossipSubConfig, st: GossipSubState,
                        wr_edge: jax.Array, tp: dict) -> GossipSubState:
    """Dead-edge cleanup for mutated slots (dynamic_topo builds): a
    written slot names a NEW connection — whatever edge occupied it
    before (possibly nothing) is gone, so every per-edge soft-state
    plane clears exactly the way `apply_peer_transitions` clears the
    edges of a departing peer: score retention converts standing mesh
    deficits into the sticky P3b penalty before the stats drop, and the
    control outboxes / promise / gossip counters reset.

    Two deliberate differences from peer departure. Backoff ALSO clears
    here: the reference's backoff map is keyed by peer id, and a rewired
    slot is a different peer — keeping the old slot's backoff would
    wrongly embargo the new connection (while the genuinely-backed-off
    old peer, if re-attached later, re-earns backoff on its next PRUNE).
    And per-peer planes (seen-cache, mcache, forward set) do NOT clear:
    both endpoints stay up across a rewire — only the edge died.

    ``wr_edge`` is the [N, K] written-slot mask from
    `topo.dynamics.written_edge_mask` (padding rows excluded)."""
    we3 = wr_edge[:, None, :]
    score0 = st.score
    if cfg.score_enabled:
        score0 = on_prune(score0, st.mesh & we3, tp)
        score0 = clear_mesh_status(score0, wr_edge)
        score0 = clear_edges(score0, wr_edge)
    # first-arrival attribution credits the OLD far end of the slot;
    # the new edge starts with a clean delivery record
    dlv0 = st.core.dlv.replace(
        fe_words=jnp.where(
            wr_edge[:, :, None], jnp.uint32(0), st.core.dlv.fe_words
        ),
    )
    return st.replace(
        core=st.core.replace(dlv=dlv0),
        mesh=st.mesh & ~we3,
        fanout_peers=st.fanout_peers & ~we3,
        graft_out=st.graft_out & ~we3,
        prune_out=st.prune_out & ~we3,
        ihave_out=jnp.where(wr_edge[:, :, None], jnp.uint32(0), st.ihave_out),
        iwant_out=jnp.where(wr_edge[:, :, None], jnp.uint32(0), st.iwant_out),
        served_lo=jnp.where(wr_edge[:, :, None], jnp.uint32(0), st.served_lo),
        served_hi=jnp.where(wr_edge[:, :, None], jnp.uint32(0), st.served_hi),
        peerhave=jnp.where(wr_edge, 0, st.peerhave).astype(st.peerhave.dtype),
        iasked=jnp.where(wr_edge, 0, st.iasked).astype(st.iasked.dtype),
        promise_mid=jnp.where(wr_edge, -1, st.promise_mid),
        backoff_present=jnp.where(we3, False, st.backoff_present),
        backoff_expire=jnp.where(we3, 0, st.backoff_expire),
        congested_in=st.congested_in & ~wr_edge,
        score=score0,
    )


def live_step_views(cfg: GossipSubConfig, net: Net, st: GossipSubState,
                    live: jax.Array | None, consts: StepConsts):
    """Apply the churn/PX edge-liveness mask to the static topology views.
    Returns (net_l, nbr_sub_l, flood_from_l, nbr_sub_words_l)."""
    if cfg.do_px or cfg.edge_liveness:
        # edge-liveness plane: dormant edges carry nothing until
        # activated (edge_live kept symmetric, so one side suffices) —
        # by PX (pxConnect) or by a runtime connect() activation
        live = (net.nbr_ok if live is None else live) & st.edge_live
    if live is not None:
        net_l = net.replace(nbr_ok=live)
        nbr_sub_l = consts.nbr_sub_const & live[:, None, :]
        flood_from_l = consts.flood_from & live
        nbr_sub_words_l = jnp.where(
            live[:, :, None], consts.nbr_sub_words, jnp.uint32(0)
        )
    else:
        net_l = net
        nbr_sub_l = consts.nbr_sub_const
        flood_from_l = consts.flood_from
        nbr_sub_words_l = consts.nbr_sub_words
    return net_l, nbr_sub_l, flood_from_l, nbr_sub_words_l


def accept_gates(cfg: GossipSubConfig, net_l: Net, st: GossipSubState,
                 gater_params, key, tick, thr=None):
    """AcceptFrom gate (gossipsub.go:583-594): direct always accepted;
    graylisted dropped entirely; the gater's RED decision drops only
    the message plane (AcceptControl, peer_gater.go:362).
    Returns (acc_ok, acc_msg) [N,K] bool. ``thr`` is the threshold
    source (cfg, or a lifted build's traced ScoreParams plane)."""
    thr = cfg if thr is None else thr
    if cfg.score_enabled:
        acc_ok = (st.scores >= thr.graylist_threshold) | net_l.direct
    else:
        acc_ok = net_l.nbr_ok
    if cfg.gater_enabled:
        # per-subsystem streams: double fold with a distinct tag so no
        # round's stream collides with another subsystem's at any tick
        # (heartbeat consumes fold_in(key, tick) directly)
        gkey = jax.random.fold_in(jax.random.fold_in(key, tick), 0x6A7E)
        acc_msg = acc_ok & (
            gater_accept(st.gater, net_l, gater_params, cfg.gater_quiet_ticks,
                         tick, gkey)
            | net_l.direct
        )
    else:
        acc_msg = acc_ok
    return acc_ok, acc_msg


def control_parts(cfg: GossipSubConfig, net: Net, st: GossipSubState,
                  include_score: bool):
    """The control-plane outboxes as named packed word tensors — the wire
    format both exchange paths (XLA gather-merge and fused Pallas halo
    kernel) transmit, kept single-source so the two cannot drift."""
    named_parts = [
        ("graft", edges.topic_pack(st.graft_out, net.my_topics, net.n_topics)),
        ("prune", edges.topic_pack(st.prune_out, net.my_topics, net.n_topics)),
        ("ihave", st.ihave_out),
    ]
    if cfg.do_px:
        named_parts.append(
            ("px", edges.topic_pack(st.prune_px_out, net.my_topics, net.n_topics))
        )
    if include_score and cfg.score_enabled:
        named_parts.append(
            ("score",
             jax.lax.bitcast_convert_type(st.scores, jnp.uint32)[..., None])
        )
    return named_parts


def control_unpack(cfg: GossipSubConfig, net: Net, net_l: Net, w_seg):
    """Receiver-side split of the gathered control words (w_seg(i) = the
    i-th part's edge view, ordered as control_parts lists them)."""
    ok_slots = net_l.nbr_ok[:, None, :]
    graft_in_raw = edges.topic_unpack(w_seg(0), net.my_topics) & ok_slots
    prune_in_raw = edges.topic_unpack(w_seg(1), net.my_topics) & ok_slots
    ihave_in_raw = w_seg(2)
    px_in_raw = (
        edges.topic_unpack(w_seg(3), net.my_topics) & ok_slots
        if cfg.do_px else None
    )
    return graft_in_raw, prune_in_raw, ihave_in_raw, px_in_raw


def control_exchange(cfg: GossipSubConfig, net: Net, net_l: Net,
                     st: GossipSubState):
    """Merged control-plane wire exchange (XLA path): every per-edge outbox
    crosses the edge involution in as few gathers as the measured
    gather-merge policy allows — the vectorized analogue of the reference
    piggybacking all control into one RPC (gossipsub.go:1096-1141 sendRPC +
    piggyback). Returns (graft_in_raw, prune_in_raw, ihave_in_raw,
    px_in_raw, nbr_score_of_me)."""
    named_parts = control_parts(cfg, net, st, include_score=True)
    parts = [p for _, p in named_parts]
    # Gather-merge policy (measured on the real chip, round 3).
    # Each gathered tensor = one set of rolled halo permutes on
    # the sharded mesh (test_collectives pins the total), so fewer
    # gathers is better — UNLESS merging parts whose consumers
    # want different layouts, which re-creates the monolithic
    # relayout copy (1.2 ms/round when the f32-bitcast score
    # column rode along in round 2; eth2 210 -> 168 when ihave
    # merged with the 2-word topic parts). Measured policy: at
    # wt == 1 ALL control words share one gather ([N,K,4] merged,
    # 408 vs 400 ticks/s); at wt > 1 only the topic_unpack
    # consumers (graft/prune/px) merge and ihave rides alone; the
    # score plane ALWAYS rides alone. Grouping is by part name so
    # the policy cannot drift from the parts list above.
    ctrl_names = [nm for nm, _ in named_parts if nm != "score"]
    wt_t = parts[0].shape[-1]
    if wt_t == 1:
        groups = [list(range(len(ctrl_names)))]
    else:
        topicish = [
            i for i, nm in enumerate(ctrl_names) if nm != "ihave"
        ]
        groups = [topicish, [ctrl_names.index("ihave")]]
    gathered = [None] * len(ctrl_names)
    for grp in groups:
        g = (
            jnp.concatenate([parts[i] for i in grp], axis=-1)
            if len(grp) > 1 else parts[grp[0]]
        )
        gg = jnp.where(
            net_l.nbr_ok[:, :, None], net_l.edge_gather(g), jnp.uint32(0)
        )
        off = 0
        for i in grp:
            pw = parts[i].shape[-1]
            gathered[i] = gg[..., off : off + pw]
            off += pw
    if cfg.score_enabled:
        # the score plane always rides alone: its f32-bitcast
        # consumer's layout caused the round-2 relayout copy
        score_g = jnp.where(
            net_l.nbr_ok[:, :, None],
            net_l.edge_gather(dict(named_parts)["score"]),
            jnp.uint32(0),
        )
        nbr_score_of_me = jnp.where(
            net_l.nbr_ok,
            jax.lax.bitcast_convert_type(score_g[..., 0], jnp.float32),
            0.0,
        )
    else:
        nbr_score_of_me = None
    return (*control_unpack(cfg, net, net_l, lambda i: gathered[i]),
            nbr_score_of_me)


def control_exchange_coalesced(cfg: GossipSubConfig, net: Net, net_l: Net,
                               st: GossipSubState, include_app: bool = False):
    """ONE stacked wire exchange for the whole phase control head (round-7
    tentpole): every control outbox, the score plane, the IWANT-service
    mcache window — and, when ``include_app``, the P5 app-score plane the
    heartbeat tail consumes — cross the edge involution in a single
    gather, so the sharded lowering emits ONE halo-permute set for the
    entire control head instead of three-plus-one (16·(r+4) →
    16·(r+1) permutes per phase; perf/projection.py charges 1–5 µs
    launch latency per permute).

    The [N]-shaped planes (mcache window, app score) broadcast over the
    edge axis before the concat, turning their peer gather into the same
    edge involution (x[n,k] = v[n] ⇒ gathered[j,k] = v[nbr[j,k]]) —
    byte-wasteful per direction but launch-free, the right trade in the
    launch-dominated halo regime the projection models.

    The round-3 measured merge policy (control_exchange above) deliberately
    kept the score column and the ihave words on separate gathers: their
    consumers' layouts forced a relayout copy per ROUND on the real chip.
    The phase engine pays the control head once per PHASE, so a once-per-
    phase relayout buys r rounds of avoided launches — the opposite
    tradeoff; the per-round step keeps the round-3 policy, and the legacy
    phase path stays selectable (cfg.wire_coalesced=False) for A/B.

    Returns (graft_in_raw, prune_in_raw, ihave_in_raw, px_in_raw,
    nbr_score_of_me, window_g, app_g)."""
    named_parts = control_parts(cfg, net, st, include_score=True)
    names = [nm for nm, _ in named_parts]
    parts = [p for _, p in named_parts]
    n_ctrl = len([nm for nm in names if nm != "score"])
    n_peers, k_dim = net.nbr.shape
    sender_window = bitset.word_or_reduce(st.mcache, axis=1)       # [N,W]
    w = sender_window.shape[-1]
    names.append("window")
    parts.append(jnp.broadcast_to(
        sender_window[:, None, :], (n_peers, k_dim, w)))
    if include_app:
        names.append("app")
        parts.append(jnp.broadcast_to(
            jax.lax.bitcast_convert_type(st.app_score, jnp.uint32)[:, None, None],
            (n_peers, k_dim, 1)))
    sizes = np.cumsum([0] + [p.shape[-1] for p in parts])
    gg = jnp.where(
        net_l.nbr_ok[:, :, None],
        net_l.edge_gather(jnp.concatenate(parts, axis=-1)),
        jnp.uint32(0),
    )

    def seg(i):
        return gg[..., int(sizes[i]) : int(sizes[i + 1])]

    def seg_named(nm):
        return seg(names.index(nm))

    # control parts lead the concat in control_parts order (score is
    # always appended last by control_parts), so the plain index view
    # feeds control_unpack directly
    assert "score" not in names[:n_ctrl]
    graft_in_raw, prune_in_raw, ihave_in_raw, px_in_raw = control_unpack(
        cfg, net, net_l, seg
    )
    if cfg.score_enabled:
        nbr_score_of_me = jnp.where(
            net_l.nbr_ok,
            jax.lax.bitcast_convert_type(seg_named("score")[..., 0], jnp.float32),
            0.0,
        )
    else:
        nbr_score_of_me = None
    window_g = seg_named("window")
    app_g = (
        jax.lax.bitcast_convert_type(seg_named("app")[..., 0], jnp.float32)
        if include_app else None
    )
    return (graft_in_raw, prune_in_raw, ihave_in_raw, px_in_raw,
            nbr_score_of_me, window_g, app_g)


def px_connect(cfg: GossipSubConfig, net: Net, net_l: Net, st: GossipSubState,
               px_ok, dynamic_peers: bool) -> jax.Array:
    """PX connect (pxConnect gossipsub.go:861-941): a peer pruned with PX
    activates its dormant provisioned edges to peers the pruner suggested —
    the pruner's current mesh members for the topic (makePrune/getPeers
    :1814-1872; here the union over the pruner's topics, one-round-stale by
    the outbox model). The id match runs per prune-edge over the small K
    axis. `net_l` is the live view (suggestions ride live edges); `net` the
    static topology (dormant slots live there). Returns next edge_live."""
    if not cfg.do_px:
        return st.edge_live
    sugg_ids = jnp.where(
        jnp.any(st.mesh, axis=1) & net_l.nbr_ok, net_l.nbr, -1
    )  # [N,C] each peer's suggestion list
    sugg_g = net.peer_gather(sugg_ids)  # [N,K,C] per-edge pruner rows
    dormant_avail = net.nbr_ok & ~st.edge_live & (net.nbr >= 0)
    if dynamic_peers:
        dormant_avail = dormant_avail & st.up[:, None] & net.peer_gather(st.up)
    act = jnp.zeros_like(dormant_avail)
    for kk in range(net.max_degree):
        hit = jnp.any(
            net.nbr[:, :, None] == sugg_g[:, kk, :][:, None, :], axis=-1
        )  # [N,K']: my dormant-slot peer is among pruner kk's suggestions
        act = act | (hit & px_ok[:, kk : kk + 1])
    act = act & dormant_avail
    act_sym = (act | net.edge_gather(act)) & net.nbr_ok
    return st.edge_live | act_sym


def make_gossipsub_step(
    cfg: GossipSubConfig,
    net: Net,
    score_params: PeerScoreParams | None = None,
    heartbeat_interval: float = 1.0,
    gater_params=None,
    dynamic_peers: bool = False,
    adversary_no_forward: np.ndarray | None = None,
    static_heartbeat: bool = False,
    sub_knowledge_holes: np.ndarray | None = None,
    telemetry=None,
    adversary=None,
    lift_scores: bool = False,
    dynamic_topo: bool = False,
    link_delay: np.ndarray | None = None,
):
    """Build the jitted per-round step for a fixed config + topology.

    ``link_delay`` is the router plane's static [N, K] i32 per-edge
    delay in rounds (docs/DESIGN.md §24c — ``topo.link_class_planes``
    normalized so the fastest class is 0, ``topo.link_delay_plane``),
    REQUIRED iff ``cfg.router.latency_rounds > 0``; values must lie in
    [0, latency_rounds]. It is a jit constant like the topology — the
    latency classes are as static as the graph they annotate.

    step(state, pub_origin[P], pub_topic[P], pub_valid[P]) -> state

    With ``lift_scores=True`` (round 16, docs/DESIGN.md §16) the step
    takes a trailing TRACED ``score_plane`` argument (a
    ``score.params.ScoreParams`` pytree): every score weight, decay
    factor and v1.1 threshold the liftability audit proves VALUE-only
    (LIFT_AUDIT.json) is read from the plane instead of the baked
    statics, so two calls with different weight sets share ONE
    compiled program (the recompile-free A/B sentinel) and a vmapped
    plane axis sweeps weight populations. Matched values reproduce the
    static build bit for bit (tests/test_score_lift.py). Requires
    ``cfg.score_enabled``. Since round 21 the fused Pallas data
    plane is eligible too: its kernel takes the thresholds as a traced
    [1, 2] f32 row, closing the float(threshold) SHAPE seam the audit
    used to pin.

    With ``static_heartbeat=True`` (and ``cfg.heartbeat_every > 1``) the
    step takes a trailing *static* python bool ``do_heartbeat`` instead of
    deciding via ``tick % heartbeat_every`` on device. A driver that knows
    the cadence at trace time (any fixed-schedule scan does) should use
    this: the ``lax.cond`` form carries every state array through both
    branches, and the branch-materialization copies measured 407 -> 113
    ticks/s at heartbeat_every=2 on the bench (BASELINE.md round 3). The
    caller owns the contract do_heartbeat == (tick % heartbeat_every == 0).

    ``pub_valid`` is either bool (True = accept, False = reject) or an
    integer array of state.VERDICT_* codes — ACCEPT / REJECT / IGNORE
    with the reference's ValidationResult numbering (validation.go:40-52).
    Ignored messages are dropped without the P4 penalty and trace
    REJECT with reason "validation ignored" (score.go:768-774).

    With ``dynamic_peers=True`` the step takes an extra ``up_next [N] bool``
    argument (the notify plane, notify.go:19-75): peers transitioning down
    — or blacklisted via ``state.blacklist`` — are disconnected with full
    dead-peer cleanup (handleDeadPeers pubsub.go:648-689 + router
    RemovePeer gossipsub.go:545-562 + score retention score.go:604-689),
    and every edge touching a down peer carries nothing until it returns.

    ``adversary_no_forward`` is a static [N] bool behavior vector (survey
    §7 stage 6): marked peers run the full control plane — subscribe,
    GRAFT/PRUNE, IHAVE gossip — but never transmit message data (mesh
    push, flood-publish, fanout, IWANT service). This is the vectorized
    analogue of the reference test suite's ``sybilSquatter`` attacker
    (gossipsub_test.go:1777-1811): grafted-but-silent peers that starve
    their mesh neighbors, to be caught by the P3 mesh-delivery deficit and
    IWANT-promise (P7) machinery.

    ``telemetry`` (a telemetry.TelemetryConfig) appends the time-series
    recorder as the step's LAST operation: one ``[N_METRICS]`` f32 panel
    row per round — EV-counter deltas covering everything this round
    accumulated (delivery, control, churn, heartbeat), delivery ratio,
    mesh/score stats — written into ``state.core.telem`` on device
    (docs/DESIGN.md §11). The state must be built with the same config
    (``GossipSubState.init(telemetry=...)``). None (the default) elides
    the plane statically: the traced program and the state tree are the
    pre-telemetry ones, bit for bit.

    ``adversary`` (a chaos.adversary.Adversary) arms the vectorized
    attack suite (docs/DESIGN.md §13): per-peer sybil/behavior masks
    drive drop-on-forward, lie-in-IHAVE, graft-spam, self-promotion
    and censorship as masked variants of this step's own math, with
    per-peer onset/stop schedules compared against the tick on device
    (stateless — checkpoints resume the exact attack sequence). None
    (or an all-off population) elides the plane statically: the traced
    program is the pre-adversary one, bit for bit
    (tests/test_adversary.py).

    With ``dynamic_topo=True`` (round 22, docs/DESIGN.md §22) the step
    takes an extra REQUIRED ``mut_writes [B, 4] i32`` trailing positional
    (after ``up_next`` and the scheduled-chaos ``link_deny`` when
    present, before the lifted ``score_plane``): a padded batch of edge
    writes ``(slot, peer, rev, ok)`` from a host-compiled
    `topo.MutationSchedule` — applied device-side to the state-resident
    `TopoState` overlay at round entry (join / death-replacement /
    rewire with zero recompiles across a window; padding rows carry
    ``topo.dynamics.PAD_SLOT`` and drop). Requires ``dynamic_peers=True``
    (death/replacement rides the up plane), a net built with
    ``Net.build(..., dynamic=True)``, and none of the planes that bake
    neighbor identity into jit constants (adversary, announce holes,
    PX / edge-liveness, fused/banded kernels). No schedule — i.e.
    ``dynamic_topo=False``, the default — elides the plane statically:
    the traced program, kernel census and state tree are the pre-dynamics
    ones, bit for bit (tests/test_dynamics.py).
    """
    if lift_scores and not cfg.score_enabled:
        raise ValueError(
            "lift_scores=True needs cfg.score_enabled — the lifted "
            "plane parameterizes the v1.1 score machinery"
        )
    if dynamic_topo:
        # every rejected combination below bakes neighbor identity (or
        # the banded edge geometry) into an eager jit constant that a
        # device-side mutation could not update without a recompile —
        # exactly what dynamic_topo exists to avoid
        if not dynamic_peers:
            raise ValueError(
                "dynamic_topo=True requires dynamic_peers=True — node "
                "death/replacement rides the up_next plane"
            )
        if net.band_off is not None or net.fused or cfg.fused:
            raise ValueError(
                "dynamic_topo=True needs an unbanded net "
                "(Net.build(..., dynamic=True)) — the banded/fused halo "
                "kernels bake the edge geometry at trace time"
            )
        if net.edge_layout == "csr" and (
            not net.csr_identity
            or net.n_edges != net.n_peers * net.max_degree
        ):
            raise ValueError(
                "dynamic_topo=True on CSR needs the full-capacity "
                "identity plane (Net.build(..., edge_layout='csr', "
                "dynamic=True)) — a degree-compacted CSR cannot gain "
                "edges without a rebuild"
            )
        if adversary is not None or adversary_no_forward is not None:
            raise ValueError(
                "dynamic_topo=True is incompatible with the adversary "
                "planes — their behavior masks and neighbor views are "
                "eager jit constants over the static topology"
            )
        if sub_knowledge_holes is not None:
            raise ValueError(
                "dynamic_topo=True is incompatible with "
                "sub_knowledge_holes — the announce-hole mask is indexed "
                "by static (receiver, slot) edge identity"
            )
        if cfg.do_px or cfg.edge_liveness:
            raise ValueError(
                "dynamic_topo=True is incompatible with do_px/"
                "edge_liveness — the edge_live plane binds activation to "
                "static slot identity; topology changes go through the "
                "mutation schedule instead"
            )
    router = cfg.router
    if router is not None:
        router.validate()
        if dynamic_topo:
            raise ValueError(
                "cfg.router is incompatible with dynamic_topo — the "
                "link_delay plane and the choke guard's edge views are "
                "static over the build topology; mutate topology on a "
                "v1.1 build or rebuild the router step"
            )
    if router is not None and router.latency_rounds > 0:
        if link_delay is None:
            raise ValueError(
                "cfg.router.latency_rounds > 0 needs the static link_delay "
                "plane (make_gossipsub_step(..., link_delay=...) — see "
                "topo.link_delay_plane)"
            )
        link_delay = np.asarray(link_delay, np.int32)
        if link_delay.shape != net.nbr.shape:
            raise ValueError(
                f"link_delay shape {link_delay.shape} does not match the "
                f"topology's [N, K] = {net.nbr.shape}"
            )
        if link_delay.min() < 0 or link_delay.max() > router.latency_rounds:
            raise ValueError(
                "link_delay values must lie in [0, "
                f"{router.latency_rounds}] (the ring depth); got "
                f"[{link_delay.min()}, {link_delay.max()}]"
            )
        link_delay_c = jnp.asarray(link_delay)
    else:
        if link_delay is not None:
            raise ValueError(
                "link_delay given but cfg.router.latency_rounds == 0 — "
                "the delay plane would be silently unread"
            )
        link_delay_c = None
    consts = prepare_step_consts(
        cfg, net, score_params, heartbeat_interval, gater_params,
        sub_knowledge_holes, adversary_no_forward, adversary,
    )
    score_params = consts.score_params
    tp = consts.tp
    window_rounds_t = consts.window_rounds_t
    nbr_sub_const = consts.nbr_sub_const
    flood_from = consts.flood_from
    i_am_floodsub = consts.i_am_floodsub
    nbr_sub_words = consts.nbr_sub_words
    sender_fwd_ok = consts.sender_fwd_ok

    # fused Pallas data plane (ops/fused_round.py): the whole edge-crossing
    # exchange + delivery as two kernels on banded topologies. Opt-in via
    # PUBSUB_FUSED=1 (bit-identical to the XLA path — tests/
    # test_fused_round.py): measured on the current libtpu the kernels
    # lose to XLA's fusion pipeline (per-grid-step and strided-DMA
    # overheads dominate the halo reads at these shapes), so the XLA path
    # stays the production default. The async-validation pipeline always
    # keeps the XLA path (pending stages live outside the kernel).
    from .common import USE_PALLAS as _old_pallas

    # chaos plane (chaos/faults.py): None elides it statically — every
    # chaos branch below disappears from the trace and the program is
    # the pre-chaos one, bit for bit (tests/test_chaos.py)
    chaos = chaos_faults.resolve(cfg.chaos)
    chaos_sched = chaos is not None and chaos.scheduled
    adv = consts.adv

    fused_env = os.environ.get("PUBSUB_FUSED", "")
    fused_eligible = (
        net.band_off is not None
        and fr.fused_supported(net.n_peers, net.band_off, net.max_degree)
        and cfg.validation_delay_rounds == 0
        and cfg.queue_cap == 0
        and not _old_pallas
        and chaos is None  # the fused halo kernel predates the chaos plane
        and adv is None    # ... and the adversary plane
        and cfg.router is None  # ... and the router plane (§24)
        # lifted ScoreParams builds are eligible since round 21: the
        # kernel takes thresholds as a traced [1, 2] f32 row, so the
        # former float(threshold) SHAPE seam is closed (the lifted+fused
        # guards row pins the one-compile A/B sentinel on this path)
    )
    fused_interp = jax.default_backend() != "tpu"
    use_fused = fused_eligible and fused_env == "1"
    fused_block = (
        fr.pick_block(net.n_peers, net.band_off) if use_fused else None
    )
    sender_fwd_full = (
        sender_fwd_ok if sender_fwd_ok is not None
        else jnp.ones(net.nbr.shape, bool)
    )
    if dynamic_topo:
        # lazy import: the static build's module graph (and trace) stays
        # byte-identical to the pre-dynamics one
        from ..topo import dynamics as topo_dynamics

    # `net=net, consts=consts` are default-bound parameters, NOT closure
    # reads: the dynamic_topo block below rebinds them to the mutated
    # overlay, and a closure variable assigned anywhere in the body
    # would be local EVERYWHERE in it (UnboundLocalError on the static
    # path). Callers never pass them.
    def _round(st: GossipSubState, pub_origin, pub_topic, pub_valid, up_next,
               do_heartbeat: bool = True,
               link_deny=None, score_plane=None, mut_writes=None,
               *, net=net, consts=consts) -> GossipSubState:
        # lifted score plane (round 16): the VALUE-proved score fields
        # read from the traced plane — per-topic rows gathered to the
        # same [N, S] views TopicParamsArrays.gather bakes, thresholds
        # and scalar params from the plane's leaves. score_plane=None
        # is the static path, byte-identical to the pre-lift program
        # (thr=cfg routes every threshold read to the same Python
        # floats it always read).
        # a combined candidate plane (round 20, score.params.
        # CandidateParams) nests the score plane with a traced MeshParams
        # — detect it by its `mesh` attribute; a bare ScoreParams keeps
        # the score-only semantics unchanged
        mesh_plane = getattr(score_plane, "mesh", None)
        if score_plane is not None:
            sc = score_plane.score if mesh_plane is not None else score_plane
            tp_r = sc.gather(net.my_topics)
            sp_r, thr, wrt = sc, sc, sc.window_rounds
        else:
            tp_r, sp_r, thr, wrt = tp, score_params, cfg, window_rounds_t
        msh = cfg if mesh_plane is None else mesh_plane
        # ---- dynamic overlay mutation (dynamic_topo builds) -------------
        # the round's write batch lands FIRST: the whole step — peer
        # transitions, control exchange, delivery, heartbeat — runs on
        # the post-mutation topology, so a round that rewires an edge and
        # a round that merely uses it trace the same program (recompile-
        # free by construction: writes are a traced [B, 4] operand)
        if dynamic_topo:
            topo1 = topo_dynamics.apply_mutation(st.core.topo, mut_writes)
            wr_edge = topo_dynamics.written_edge_mask(
                mut_writes, net.n_peers, net.max_degree
            )
            net = net.with_overlay(topo1)
            nsc, ffr, nsw = topology_views(net)
            consts = StepConsts(
                score_params=consts.score_params, tp=consts.tp,
                tpa=consts.tpa, window_rounds_t=consts.window_rounds_t,
                nbr_sub_const=nsc, flood_from=ffr,
                i_am_floodsub=consts.i_am_floodsub, nbr_sub_words=nsw,
                sender_fwd_ok=consts.sender_fwd_ok, adv=consts.adv,
            )
            st = clear_mutated_edges(cfg, st, wr_edge, tp_r)
            st = st.replace(core=st.core.replace(topo=topo1))
        else:
            topo1 = None
        # telemetry: counters at step ENTRY (before the churn plane's
        # ADD/REMOVE_PEER accounting), so the row's EV deltas cover the
        # whole step and the panel sums telescope to the drained totals
        ev_prev = st.core.events if telemetry is not None else None
        # ---- peer lifecycle transitions (dynamic_peers only) ------------
        if dynamic_peers:
            st, live = apply_peer_transitions(cfg, net, st, up_next, tp_r)
        else:
            live = None
        net_l, nbr_sub_l, flood_from_l, nbr_sub_words_l = live_step_views(
            cfg, net, st, live, consts
        )

        core = st.core
        tick = core.tick
        m = core.msgs.capacity

        acc_ok, acc_msg = accept_gates(cfg, net_l, st, gater_params,
                                       core.key, tick, thr=thr)

        # ---- chaos plane: this round's link outages ---------------------
        # TCP-flap semantics — the WHOLE link (control head + data, both
        # directions) drops for the round, with no endpoint state cleanup
        # (the peers don't learn the link flapped; outboxes written into
        # the outage are genuinely lost, which is exactly the loss the
        # IHAVE/IWANT machinery exists to recover). net_w is the wire
        # view: the one-round-masked net_l every receiver gather uses.
        if chaos is not None:
            ge_bad0 = core.chaos.ge_bad if core.chaos is not None else None
            link_ok, ge_bad_next = chaos_faults.round_link_ok(
                chaos, chaos_faults.chaos_seed(core.key), net.nbr, tick,
                ge_bad0, link_deny, topo=topo1,
            )
            net_w = net_l.replace(nbr_ok=net_l.nbr_ok & link_ok)
            # data-plane gate: acc_msg feeds gossip_edge_mask and the
            # IWANT-response mask — one AND covers every data transmit
            acc_msg = acc_msg & link_ok
        else:
            link_ok = ge_bad_next = None
            net_w = net_l

        # 0b. merged wire exchange: every per-edge outbox crosses the edge
        # involution in ONE gather. Separate gathers each pay a fixed
        # dispatch cost on TPU, so the control plane ships as a single
        # concatenated word tensor (graft | prune | ihave [| px] [| score])
        # and is split receiver-side — the vectorized analogue of the
        # reference piggybacking all control into one RPC (gossipsub.go:
        # 1096-1141 sendRPC + piggyback). On banded topologies the gather
        # runs as a Pallas halo kernel (ops/fused_round.edge_exchange) and
        # the score plane rides as f32 instead of a bitcast word.
        n_peers = net.n_peers
        k_dim = net.max_degree
        if use_fused:
            # the score plane rides inside the kernel as f32, not a part
            parts = [p for _, p in control_parts(cfg, net, st,
                                                 include_score=False)]
            sizes = np.cumsum([0] + [p.shape[-1] for p in parts])
            wc = int(sizes[-1])
            wire_flat, nbr_score_of_me = fr.edge_exchange(
                jnp.concatenate(parts, axis=-1).reshape(n_peers, k_dim * wc),
                st.scores if cfg.score_enabled else None,
                net_l.nbr_ok.astype(jnp.uint32),
                block=fused_block, offsets=net.band_off, revs=net.band_rev,
                c=wc, score_enabled=cfg.score_enabled,
                interpret=fused_interp,
            )
            wire = wire_flat.reshape(n_peers, k_dim, wc)
            if not cfg.score_enabled:
                nbr_score_of_me = None
            graft_in_raw, prune_in_raw, ihave_in_raw, px_in_raw = (
                control_unpack(cfg, net, net_l,
                               lambda i: wire[..., sizes[i] : sizes[i + 1]])
            )
        else:
            (graft_in_raw, prune_in_raw, ihave_in_raw, px_in_raw,
             nbr_score_of_me) = control_exchange(cfg, net, net_w, st)

        # 1. GRAFT/PRUNE ingest
        st2, prune_resp, px_resp, px_ok, n_graft, n_prune = handle_graft_prune(
            cfg, net_l, st, tp_r, acc_ok, graft_in_raw, prune_in_raw,
            px_in_raw, thr=thr, msh=msh,
        )
        events = st.core.events
        if cfg.count_events:
            events = events.at[EV.GRAFT].add(n_graft).at[EV.PRUNE].add(n_prune)

        # router choke guard at the GRAFT/PRUNE mutation site: the ingest
        # may have pruned an unchoked link or grafted a fresh one, and the
        # no-choke-below-Dlo invariant holds at every round boundary
        # (oracle/invariants.py), so re-establish choked ⊆ mesh here
        if router is not None and router.choke:
            st2 = st2.replace(choked=choke_guard(msh.Dlo, st2.mesh, st2.choked))

        # 1b. PX connect (see px_connect)
        edge_live_next = px_connect(cfg, net, net_l, st, px_ok, dynamic_peers)

        joined_words = joined_msg_words(net_l, core.msgs)
        slotw = slot_topic_words(net_l, core.msgs.topic)
        pre_have = core.dlv.have
        n_adv_drop = None
        if use_fused:
            if core.msgs.wire_block is not None:
                raise NotImplementedError(
                    "the fused Pallas data plane predates the wire_block "
                    "(max-message-size) plane — use the default XLA path"
                )
            # 2+3+4 fused: IHAVE ingest first (it consumes nothing the
            # delivery kernel writes), then the whole delivery plane —
            # mesh/fanout/flood push, echo suppression, IWANT service with
            # retransmission counters, seen-cache dedup, first-arrival
            # attribution — in one Pallas kernel over the post-graft mesh.
            asked_old = st2.iwant_out
            served_lo_old, served_hi_old = st2.served_lo, st2.served_hi
            st2 = handle_ihave(cfg, net_l, st2, joined_words, acc_ok, ihave_in_raw)

            carry = sender_carry_words(st2.mesh, slotw)
            if cfg.fanout_slots > 0:
                carry = carry | fanout_carry_words(
                    st2.fanout_peers, st2.fanout_topic, core.msgs.topic
                )
            origin_w = origin_msg_words(net_l, core.msgs)
            if cfg.flood_publish:
                # sender-side fold of v1.1 flood-publish: the origin pushes
                # its own messages on every edge it scores above
                # publishThreshold (gossipsub.go:957-963) — equivalent to
                # the receiver-side origin compare, because nbr_score_of_me
                # at the receiver IS the sender's score of that edge
                fp_ok = (
                    (st.scores >= thr.publish_threshold)
                    if cfg.score_enabled else net_l.nbr_ok
                )
                carry = carry | jnp.where(
                    fp_ok[:, :, None], origin_w[:, None, :], jnp.uint32(0)
                )
            flags = fr.make_flags(
                acc_msg, flood_from, i_am_floodsub, sender_fwd_full,
                net_l.nbr_ok,
            )
            mcw = bitset.word_or_reduce(st2.mcache, axis=1)
            w_dim = bitset.n_words(m)
            kw = k_dim * w_dim
            res = fr.fused_delivery(
                carry.reshape(n_peers, kw),
                core.dlv.fe_words.reshape(n_peers, kw),
                core.dlv.fwd, mcw,
                nbr_score_of_me,
                asked_old.reshape(n_peers, kw),
                served_lo_old.reshape(n_peers, kw),
                served_hi_old.reshape(n_peers, kw),
                flags, pre_have, origin_w, joined_words,
                bitset.pack(core.msgs.valid)[None, :],
                block=fused_block, offsets=net.band_off, revs=net.band_rev,
                w=w_dim, score_enabled=cfg.score_enabled,
                want_cohorts=cfg.count_events,
                retrans_cap=cfg.gossip_retransmission,
                gossip_thr=jnp.asarray(thr.gossip_threshold, jnp.float32),
                publish_thr=jnp.asarray(thr.publish_threshold, jnp.float32),
                interpret=fused_interp,
            )
            new_words_f = res["new"]
            new_bits_f = bitset.unpack(new_words_f, m)
            dlv = core.dlv.replace(
                have=res["have"], fwd=res["fwd"],
                first_round=jnp.where(new_bits_f, tick, core.dlv.first_round),
                fe_words=res["fe"].reshape(n_peers, k_dim, w_dim),
            )
            st2 = st2.replace(
                served_lo=res["served_lo"].reshape(n_peers, k_dim, w_dim),
                served_hi=res["served_hi"].reshape(n_peers, k_dim, w_dim),
            )
            if cfg.count_events:
                # cohort-split counters matching the XLA path's two-stage
                # accounting (delivery_round then merge_extra_tx): RPCs
                # count mesh-push and IWANT-response transmissions
                # separately even when they overlap on an (edge, msg)
                valid_pack = bitset.pack(core.msgs.valid)
                n_rpc = (
                    bitset.popcount(res["mesh_trans"], axis=None).sum()
                    + bitset.popcount(res["extra"], axis=None).sum()
                ).astype(jnp.int32)
                n_new = bitset.popcount(new_words_f, axis=None).sum().astype(jnp.int32)
                n_deliver = (
                    bitset.popcount(new_words_f & valid_pack[None, :], axis=None)
                    .sum().astype(jnp.int32)
                )
                n_reject = n_new - n_deliver
                n_duplicate = n_rpc - n_new
            else:
                n_rpc = n_new = n_deliver = n_reject = n_duplicate = jnp.int32(0)
            info = RoundInfo(
                trans=res["trans"].reshape(n_peers, k_dim, w_dim),
                new_words=new_words_f,
                new_bits=new_bits_f,
                recv_new_words=new_words_f,
                n_deliver=n_deliver, n_reject=n_reject,
                n_duplicate=n_duplicate, n_rpc=n_rpc,
            )
        else:
            # 2. IWANT service (requests sent to me last round -> delivery
            # carry) — the mcache-window gather rides the wire view, so a
            # flapped link's responses are lost (and its retransmission
            # counters don't tick: the data never arrived)
            st2, iwant_resp = iwant_responses(cfg, net_w, st2,
                                              nbr_score_of_me, thr=thr)

            # 3. IHAVE ingest (advertisements -> next round's requests)
            st2 = handle_ihave(cfg, net_l, st2, joined_words, acc_ok,
                               ihave_in_raw, thr=thr)

            # 4. delivery: mesh/fanout push + flood edges + IWANT responses
            # floodsub-peer edges: sender floodsub => flood; receiver floodsub
            # => gossipsub sender still sends everything (score-gated,
            # gossipsub.go:973-978)
            if cfg.score_enabled:
                recv_ok = nbr_score_of_me >= thr.publish_threshold
            else:
                recv_ok = net_l.nbr_ok
            flood_edges = flood_from_l | (i_am_floodsub[:, None] & recv_ok & net_l.nbr_ok)
            edge_mask = gossip_edge_mask(
                cfg, net_l, st2, joined_words, acc_msg, slotw,
                core.msgs.topic, flood_edges,
                nbr_score_of_me, thr=thr,
            )
            if sender_fwd_ok is not None:
                edge_mask = jnp.where(sender_fwd_ok[:, :, None], edge_mask, jnp.uint32(0))
                iwant_resp = jnp.where(sender_fwd_ok[:, :, None], iwant_resp, jnp.uint32(0))
            # adversary data plane (chaos/adversary.py): drop-on-
            # forward / censorship suppress bits on edges from ACTIVE
            # attackers — one AND into the receiver gathers the step
            # already performs, zero extra halo permutes (the behavior
            # masks and their neighbor views are eager jit constants)
            if adv is not None and adv.data_plane:
                edge_mask, rem_mask = adv.mask_transmit_nbr(
                    tick, edge_mask, core.msgs)
                iwant_resp, rem_resp = adv.mask_transmit_nbr(
                    tick, iwant_resp, core.msgs)
                if cfg.count_events:
                    # withheld-transmission attribution: suppressed
                    # carry bits ∩ the senders' forward sets (the same
                    # fwd gather delivery_round performs — XLA CSE
                    # merges the two); IWANT-response bits are actual
                    # serves, counted whole
                    fwd_g = net_l.peer_gather(core.dlv.fwd)
                    n_adv_drop = (
                        bitset.popcount(rem_mask & fwd_g, axis=None).sum()
                        + bitset.popcount(rem_resp, axis=None).sum()
                    ).astype(jnp.int32)
            # ---- router plane (docs/DESIGN.md §24) ----------------------
            # receiver-side data suppression: both IDONTWANT (§24a) and
            # choke (§24b) land as ANDs on edge_mask BEFORE delivery_round,
            # so the dense and the flat-[E] CSR layouts (which pack
            # edge_mask internally) are covered identically, with zero
            # extra halo permutes — the sender's view of "I was told not
            # to" is receiver-indexed, exactly like the adversary masks
            n_dup_sup = None
            ring_tx = None
            if router is not None:
                mesh_edge = jnp.any(st2.mesh, axis=1)
                suppress = jnp.zeros_like(edge_mask)
                if router.idontwant_eligible:
                    suppress = suppress | dontwant_suppression(
                        st.dontwant, mesh_edge
                    )
                if router.choke:
                    ch_edge = choke_suppression(st2.choked)
                    suppress = suppress | jnp.where(
                        ch_edge[:, :, None], jnp.uint32(0xFFFFFFFF),
                        jnp.uint32(0),
                    )
                removed = edge_mask & suppress
                edge_mask = edge_mask & ~suppress
                if cfg.count_events:
                    # suppressed-transmission attribution: withheld carry
                    # bits ∩ the senders' forward sets — the n_adv_drop
                    # convention above (same fwd gather delivery_round
                    # performs; XLA CSE merges them)
                    fwd_g = net_l.peer_gather(core.dlv.fwd)
                    n_dup_sup = bitset.popcount(
                        removed & fwd_g, axis=None
                    ).sum().astype(jnp.int32)
                if router.latency_rounds > 0:
                    # §24c latency ring — store-and-forward: the sender's
                    # fwd plane is a ONE-round window (this round's
                    # validated cohort, models/common.py), so a commit
                    # landing d rounds later would find it already empty.
                    # The decision therefore resolves against the
                    # sender's fwd window and the echo exclusion AT SEND
                    # TIME (what's on the wire was valid when it left),
                    # and the ring carries the resolved transmission
                    # words; slot-0 pops commit below via merge_extra_tx,
                    # the path built for transmissions outside senders'
                    # current fwd sets (IWANT responses). Delay-0 edges
                    # never enter the ring: they keep the v1.1
                    # delivery_round path bit-for-bit.
                    d0w = jnp.where(
                        (link_delay_c == 0)[:, :, None],
                        jnp.uint32(0xFFFFFFFF), jnp.uint32(0))
                    eager = (edge_mask & net_l.peer_gather(core.dlv.fwd)
                             & ~net_l.edge_gather(core.dlv.fe_words)
                             & ~d0w)
                    ring_tx, inflight_next = ring_commit(
                        st.inflight, eager, link_delay_c
                    )
                    edge_mask = edge_mask & d0w
            dlv, info = delivery_round(
                net_l, core.msgs, core.dlv, edge_mask, tick,
                count_events=cfg.count_events, queue_cap=cfg.queue_cap,
                val_delay_topic=cfg.validation_delay_topic,
            )
            if ring_tx is not None:
                # latency-ring arrivals land this round (merged before
                # the IWANT responses so the recovery attribution below
                # stays IWANT-only)
                dlv, info = merge_extra_tx(
                    net_l, core.msgs, dlv, info, ring_tx, tick,
                    count_events=cfg.count_events, queue_cap=cfg.queue_cap,
                    val_delay_topic=cfg.validation_delay_topic)
            iwant_resp = jnp.where(acc_msg[:, :, None], iwant_resp, jnp.uint32(0))
            have_pre_merge = dlv.have
            dlv, info = merge_extra_tx(net_l, core.msgs, dlv, info, iwant_resp, tick,
                                       count_events=cfg.count_events,
                                       queue_cap=cfg.queue_cap,
                                       val_delay_topic=cfg.validation_delay_topic)
            if chaos is not None and cfg.count_events:
                # IWANT-recovery attribution: receipts whose FIRST arrival
                # rode the IWANT service rather than an eager push (the
                # chaos metrics' recovery-efficacy numerator; valid-plane
                # membership read at arrival — under async validation the
                # verdict lands later, same arrival-cohort convention as
                # the duplicate counter)
                n_iwant_rec = bitset.popcount(
                    (dlv.have & ~have_pre_merge)
                    & bitset.pack(core.msgs.valid)[None, :], axis=None,
                ).sum().astype(jnp.int32)

        # exact-trace duplicate plane: arrivals beyond the first per
        # (peer, msg) — captured pre-throttle (throttled receipts are
        # fresh, traced Reject, and the dup counter excludes them) and
        # arrival-based under async validation (recv_new_words)
        if cfg.trace_exact:
            dup_plane = info.trans & ~(
                dlv.fe_words & info.recv_new_words[:, None, :]
            )
        else:
            dup_plane = None

        # router choke signal: fold this round's per-edge lateness into
        # the EMA (arrival-based, pre-throttle — the same cohort the dup
        # counter uses). Router builds never take the fused path, so
        # info/dlv here are always the XLA delivery plane's.
        if router is not None and router.choke:
            choke_ema_next = choke_lateness_update(
                router, st2.choke_ema, info.trans, dlv.fe_words,
                info.new_words,
            )

        # 4b. validation front-end throttle (validation.go:230-244)
        valid_words_all = bitset.pack(core.msgs.valid)
        if cfg.validation_capacity > 0:
            dlv, info, accepted_new, n_throttled = apply_validation_throttle(
                dlv, info, cfg.validation_capacity, m, valid_words_all
            )
        else:
            accepted_new = info.new_words
            n_throttled = jnp.zeros((net.n_peers,), jnp.int32)

        # 5. score delivery attribution (packed)
        score = st2.score
        if cfg.score_enabled:
            score = on_deliveries(
                score, net_l, st2.mesh, tp_r, info.trans, info.new_words,
                dlv.fe_words, dlv.first_round,
                core.msgs.topic, core.msgs.valid, tick, wrt,
                msg_ignored=core.msgs.ignored,
                slotw=slotw,
                pending_words=(
                    bitset.word_or_reduce(dlv.pending, axis=1)
                    if cfg.validation_delay_rounds > 0 else None
                ),
                recv_new_words=info.recv_new_words,
            )

        # 5b. gater outcome counters (the RawTracer hooks,
        # peer_gater.go:365-443)
        gater_state = st2.gater
        if cfg.gater_enabled:
            fe_words_post = dlv.fe_words
            # fe ⊆ arrivals, so the packed first-arrival plane restricted
            # to the validated cohort is the attribution mask directly
            first_arrival = (
                fe_words_post & accepted_new[:, None, :]
                & valid_words_all[None, None, :]
            )
            deliver_inc = bitset.popcount(first_arrival, axis=-1).astype(jnp.float32)
            dup_inc = bitset.popcount(
                info.trans & pre_have[:, None, :], axis=-1
            ).astype(jnp.float32)
            # reject vs ignore split (peer_gater.go:416-432: ignored
            # verdicts land on the `ignore` counter, not `reject`)
            ignored_words = bitset.pack(core.msgs.ignored)
            rej_inc = bitset.popcount(
                info.trans & ~valid_words_all[None, None, :]
                & ~ignored_words[None, None, :], axis=-1
            ).astype(jnp.float32)
            ign_inc = bitset.popcount(
                info.trans & ignored_words[None, None, :], axis=-1
            ).astype(jnp.float32)
            n_validated = bitset.popcount(accepted_new, axis=-1)
            gater_state = gater_on_round(
                gater_state, n_validated, n_throttled, deliver_inc, dup_inc,
                rej_inc, tick, ignore_inc=ign_inc,
            )

        # 6. mcache put: validated new receipts in joined topics
        valid_words = bitset.pack(core.msgs.valid)
        put = info.new_words & valid_words[None, :] & joined_words
        mcache = st2.mcache.at[:, 0, :].set(st2.mcache[:, 0, :] | put)

        # 7. publishes + slot-recycle cleanup
        msgs, dlv, _slots, is_pub, keep_words, pub_words = allocate_publishes(
            core.msgs, dlv, tick, pub_origin, pub_topic, pub_valid,
            stacked_clears=cfg.wire_coalesced,
        )
        # recycled-slot clearing must precede the put: the fresh publishes
        # land on exactly the recycled slots, and clearing after the OR
        # would erase them — leaving the origin without its own message in
        # mcache (it must serve IWANTs and advertise IHAVE for it from the
        # publish round on; mcache.Put in Publish, gossipsub.go:946)
        mcache = mcache & keep_words[None, None, :]
        mcache = mcache.at[:, 0, :].set(mcache[:, 0, :] | pub_words)
        # IHAVE outboxes were gathered by the far end this round (step 3);
        # clear so a batch is received exactly once per heartbeat emission
        # (the reference sends IHAVE once, at the heartbeat) — emitGossip
        # below repopulates on heartbeat rounds
        ihave_out = jnp.zeros_like(st2.ihave_out)
        if cfg.wire_coalesced:
            iwant_out, served_lo, served_hi = bitset.masked_keep(
                [st2.iwant_out, st2.served_lo, st2.served_hi], keep_words
            )
        else:
            iwant_out = st2.iwant_out & keep_words[None, None, :]
            served_lo = st2.served_lo & keep_words[None, None, :]
            served_hi = st2.served_hi & keep_words[None, None, :]
        # one-hot word pick instead of an [N,K,M] compare-reduce
        promise_reused = bitset.bit_get((~keep_words)[None, None, :], st2.promise_mid)
        promise_mid = jnp.where(
            (st2.promise_mid >= 0) & promise_reused, -1, st2.promise_mid
        )

        # 7b. fanout slots for publishes to unjoined topics
        if cfg.fanout_slots > 0:
            st2 = update_fanout_on_publish(
                cfg, net_l, st2, pub_origin, pub_topic,
                jax.random.fold_in(jax.random.fold_in(core.key, tick), 0xFA40),
                nbr_sub_words_l, thr=thr, msh=msh,
            )

        # ---- router plane state roll (docs/DESIGN.md §24) ---------------
        # announcements accumulate at round END from this round's
        # post-throttle first receipts and are consumed NEXT round — the
        # one-RTT control latency every other outbox pays. Every per-edge
        # and per-id router plane gets the same keep-words recycle the
        # mcache gets.
        router_next = {}
        if router is not None:
            if router.idontwant_eligible:
                ann = dontwant_announcements(
                    router, info.recv_new_words, joined_words
                )
                router_next["dontwant"] = (
                    (st.dontwant | ann) & keep_words[None, :]
                )
            if router.choke:
                router_next["choke_ema"] = choke_ema_next
            if router.latency_rounds > 0:
                router_next["inflight"] = ring_keep(inflight_next, keep_words)

        if cfg.count_events:
            events = accumulate_round_events(
                events, info, jnp.sum(is_pub.astype(jnp.int32))
            )
            if router is not None:
                if router.idontwant_eligible:
                    events = events.at[EV.IDONTWANT_SENT].add(
                        idontwant_sent_count(ann, mesh_edge)
                    )
                if n_dup_sup is not None:
                    events = events.at[EV.DUP_SUPPRESSED].add(n_dup_sup)
            if chaos is not None:
                events = events.at[EV.LINK_DOWN].add(
                    chaos_faults.count_links_down(net.nbr, net_l.nbr_ok,
                                                  link_ok)
                ).at[EV.IWANT_RECOVER].add(n_iwant_rec)
            if n_adv_drop is not None:
                events = events.at[EV.ADV_DROP].add(n_adv_drop)
        core_next = core.replace(msgs=msgs, dlv=dlv, events=events)
        if chaos is not None and chaos.needs_state:
            core_next = core_next.replace(
                chaos=core.chaos.replace(ge_bad=ge_bad_next)
            )
        st2 = st2.replace(
            core=core_next,
            mcache=mcache,
            ihave_out=ihave_out,
            iwant_out=iwant_out,
            served_lo=served_lo,
            served_hi=served_hi,
            promise_mid=promise_mid,
            graft_out=jnp.zeros_like(st2.graft_out),
            prune_out=prune_resp,
            prune_px_out=px_resp,
            edge_live=edge_live_next,
            score=score,
            gater=gater_state,
            # NOT keep-masked: a dup bit always names the message the slot
            # held when the arrival happened, so the drain attributes the
            # plane against the PRE-publish slot->mid mapping — including
            # arrivals in a message's own death round (which the device
            # counter also counted)
            dup_trans=dup_plane,
            **router_next,
        )

        # congested links suppress next heartbeat's gossip toward them:
        # a full writer queue drops the IHAVE batch and gossip is never
        # retried (gossipsub.go:1757-1764 flush drops, :1155-1160)
        if cfg.queue_cap > 0:
            sat_recv = bitset.popcount(info.trans, axis=-1) >= cfg.queue_cap
            gossip_suppress = net_l.edge_gather(sat_recv) & net_l.nbr_ok
            st2 = st2.replace(congested_in=sat_recv)
        else:
            gossip_suppress = None

        # 8. heartbeat — inline when it runs every round (the default tick
        # model); lax.cond otherwise. The cond carries the whole state
        # through both branches, which costs real copies of the big arrays.
        def hb(s):
            return heartbeat(
                cfg, net_l, s, tp_r, sp_r, nbr_sub_l, gater_params,
                nbr_sub_words_l, present_ok=net.nbr_ok,
                gossip_suppress=gossip_suppress, adversary=adv, thr=thr,
                msh=msh,
            )

        if cfg.heartbeat_every == 1:
            st2 = hb(st2)
        elif static_heartbeat:
            # trace-time decision: the driver asserts the cadence; the
            # non-heartbeat trace contains no heartbeat code at all (no
            # lax.cond branch-materialization copies of the state)
            if do_heartbeat:
                st2 = hb(st2)
        else:
            st2 = jax.lax.cond((tick % cfg.heartbeat_every) == 0, hb, lambda s: s, st2)

        # telemetry row — the step's LAST operation, after the heartbeat
        # (whose GRAFT/PRUNE accounting the EV deltas must cover)
        if telemetry is not None:
            from ..telemetry import panel as _tele

            core_f = st2.core
            telem = _tele.record_step(
                telemetry, core_f.telem, tick, ev_prev, core_f.events,
                net_l, core_f.msgs, core_f.dlv,
                mesh=st2.mesh, my_topics=net_l.my_topics,
                scores=st2.scores,
                backoff_active=(st2.backoff_present
                                & (st2.backoff_expire > tick)),
            )
            st2 = st2.replace(core=core_f.replace(telem=telem))

        return st2.replace(core=st2.core.replace(tick=tick + 1))

    if net.edge_layout == "csr":
        # CSR-resident state tier (round 18, docs/DESIGN.md §18): the
        # per-edge planes live FLAT in the carry (fe_words/served_*/
        # peerhave/iasked as [E, ...]); densify at entry, re-pack at
        # exit — the step body above stays the dense-written program,
        # bit-exact, while checkpoints/scan carries hold the flat tier
        _round = wrap_csr_resident(net, _round)

    use_static_hb = static_heartbeat and cfg.heartbeat_every > 1
    if lift_scores:
        # lifted call convention: the TRACED score plane rides as the
        # LAST positional, after the per-round arrays (up_next /
        # link_deny keep their usual slots) — so ensemble.lift_step
        # vmaps it like any other per-sim input, which is exactly the
        # configs×sims sweep axis the ROADMAP parameter search needs
        def _dispatch(st, pub_origin, pub_topic, pub_valid, rest,
                      do_heartbeat=True):
            up = rest[0] if dynamic_peers else None
            deny = rest[int(dynamic_peers)] if chaos_sched else None
            writes = (
                rest[int(dynamic_peers) + int(chaos_sched)]
                if dynamic_topo else None
            )
            return _round(st, pub_origin, pub_topic, pub_valid, up,
                          do_heartbeat, deny, score_plane=rest[-1],
                          mut_writes=writes)

        if use_static_hb:
            def step(st, pub_origin, pub_topic, pub_valid, *rest,
                     do_heartbeat):
                return _dispatch(st, pub_origin, pub_topic, pub_valid,
                                 rest, do_heartbeat)
            return jax.jit(step, donate_argnums=0,
                           static_argnames=("do_heartbeat",))

        def step(st, pub_origin, pub_topic, pub_valid, *rest):
            return _dispatch(st, pub_origin, pub_topic, pub_valid, rest)
        return jax.jit(step, donate_argnums=0)
    if use_static_hb:
        # do_heartbeat is REQUIRED here: a default would let a driver
        # silently heartbeat every round (or never) against the cadence.
        # A scheduled-chaos build likewise takes the Scenario's forced-
        # down link mask as a REQUIRED trailing positional ([N, K] bool,
        # True = link down this round) — a default would silently run
        # the scenario with no partitions.
        if dynamic_topo and chaos_sched:
            # mut_writes is REQUIRED for the same reason link_deny is: a
            # default would silently run the window with no mutations
            def step(st, pub_origin, pub_topic, pub_valid, up_next,
                     link_deny, mut_writes, *, do_heartbeat):
                return _round(st, pub_origin, pub_topic, pub_valid, up_next,
                              do_heartbeat, link_deny,
                              mut_writes=mut_writes)
        elif dynamic_topo:
            def step(st, pub_origin, pub_topic, pub_valid, up_next,
                     mut_writes, *, do_heartbeat):
                return _round(st, pub_origin, pub_topic, pub_valid, up_next,
                              do_heartbeat, mut_writes=mut_writes)
        elif dynamic_peers and chaos_sched:
            def step(st, pub_origin, pub_topic, pub_valid, up_next,
                     link_deny, *, do_heartbeat):
                return _round(st, pub_origin, pub_topic, pub_valid, up_next,
                              do_heartbeat, link_deny)
        elif dynamic_peers:
            def step(st, pub_origin, pub_topic, pub_valid, up_next, *, do_heartbeat):
                return _round(st, pub_origin, pub_topic, pub_valid, up_next,
                              do_heartbeat)
        elif chaos_sched:
            def step(st, pub_origin, pub_topic, pub_valid, link_deny,
                     *, do_heartbeat):
                return _round(st, pub_origin, pub_topic, pub_valid, None,
                              do_heartbeat, link_deny)
        else:
            def step(st, pub_origin, pub_topic, pub_valid, *, do_heartbeat):
                return _round(st, pub_origin, pub_topic, pub_valid, None,
                              do_heartbeat)
        return jax.jit(step, donate_argnums=0,
                       static_argnames=("do_heartbeat",))

    if dynamic_topo and chaos_sched:
        def step(st, pub_origin, pub_topic, pub_valid, up_next, link_deny,
                 mut_writes):
            return _round(st, pub_origin, pub_topic, pub_valid, up_next,
                          link_deny=link_deny, mut_writes=mut_writes)
    elif dynamic_topo:
        def step(st, pub_origin, pub_topic, pub_valid, up_next, mut_writes):
            return _round(st, pub_origin, pub_topic, pub_valid, up_next,
                          mut_writes=mut_writes)
    elif dynamic_peers and chaos_sched:
        def step(st, pub_origin, pub_topic, pub_valid, up_next, link_deny):
            return _round(st, pub_origin, pub_topic, pub_valid, up_next,
                          link_deny=link_deny)
    elif dynamic_peers:
        def step(st, pub_origin, pub_topic, pub_valid, up_next):
            return _round(st, pub_origin, pub_topic, pub_valid, up_next)
    elif chaos_sched:
        def step(st, pub_origin, pub_topic, pub_valid, link_deny):
            return _round(st, pub_origin, pub_topic, pub_valid, None,
                          link_deny=link_deny)
    else:
        def step(st, pub_origin, pub_topic, pub_valid):
            return _round(st, pub_origin, pub_topic, pub_valid, None)

    return jax.jit(step, donate_argnums=0)


def no_publish(p: int = 4):
    """Empty publish buffers."""
    z = jnp.full((p,), -1, jnp.int32)
    return z, z, jnp.zeros((p,), bool)


def set_blacklist(st: GossipSubState, mask) -> GossipSubState:
    """BlacklistPeer (pubsub.go:590-605): host-side toggle; takes effect on
    the next dynamic_peers step with full disconnect cleanup, and keeps the
    peer disconnected for as long as the flag is set (the blacklist checks
    at pubsub.go:1048-1060 and connection-time :636-639)."""
    return st.replace(blacklist=jnp.asarray(mask, bool))
