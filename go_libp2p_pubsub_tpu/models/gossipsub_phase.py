"""Multi-round phase engine: r delivery rounds per dispatch, control once.

The reference runs *continuous* delivery (every RPC is forwarded the
moment validation finishes) against a 1 Hz maintenance heartbeat
(gossipsub.go:1278-1301) — message hops are ~ms apart while GRAFT/PRUNE/
IHAVE/IWANT/score refresh happen ~1000x less often. The per-round step
(`make_gossipsub_step`) compresses that to "control every hop": a
deliberately *heavier* coupling than the reference's. This module builds
the step the other way — faithful to the reference's timing shape — by
batching ``rounds_per_phase`` (r) delivery rounds into ONE jitted phase:

  * control plane (wire exchange, GRAFT/PRUNE ingest, PX connect, IHAVE
    ingest, IWANT service, gater draw, score attribution, heartbeat) runs
    once per phase — control latency becomes r rounds, the analogue of
    the reference's heartbeat-granularity control;
  * the data plane (publish allocation, mesh/fanout/flood push, seen-
    cache dedup, first-arrival attribution, mcache insertion) runs every
    sub-round, so per-hop delivery latency is UNCHANGED — the
    propagation CDF keeps 1-round resolution via per-sub-round
    ``first_round`` stamps.

Perf shape: the sub-round body is computed *sender-side* — each sender
composes what it pushes per edge (mesh/fanout carry & fwd & not-echo) so
the whole data exchange crosses the edge involution in ONE [N,K,W]
gather, vs three for the receiver-side form (fwd peer-gather + echo
edge-gather + carry edge-gather). On the sharded mesh that is one set of
halo permutes per sub-round. The two forms are boolean-algebra equal;
tests/test_phase.py pins r=1 phase == per-round step bit-exactly.

Edge layout (round 15): every cross-peer gather here — the sub-round
sender-side exchange AND the stacked coalesced control head — goes
through ``net.edge_gather``/``net.peer_gather``, so a
``cfg.edge_layout="csr"`` build (ops/csr.py, with a matching
``Net.build(edge_layout="csr")``) routes the whole phase over the flat
[E] edge space with zero runtime branching; prepare_step_consts
rejects a layout mismatch, and tests/test_csr.py pins phase-engine
dense-vs-CSR bit-exactness at r∈{4,8} with chaos on.

Round 7 (cfg.wire_coalesced, the default) restructures the rest of the
phase the same way — launch count over everything else, because at the
12.5k shard BOTH terms of rate = 1/(shard_ms + ici_ms) are
launch-overhead, not bytes:
  * the CONTROL HEAD coalesces into one stacked wire exchange
    (gossipsub.control_exchange_coalesced): control outboxes + score
    plane + IWANT mcache window + (when weighted) the P5 app plane
    cross the edge involution in ONE gather — the phase's halo budget
    drops from 16·(r+4) to 16·(r+1) permutes (the number the v5e-8
    projection charges; tests/test_collectives.py pins it exactly);
  * the per-sub-round PUBLISH ALLOCATION hoists to the head
    (state.PhasePubPlan): slot/index math, recycled-slot keep masks,
    origin pub words and message-table snapshots precompute as wide
    ops, replacing r allocate_publishes calls' tiny-kernel swarm
    ([M]-table scatters, cursor scalar chains — the round-6 profile's
    dominant launch pool);
  * the ATTRIBUTION ACCUMULATORS fold as one leading-axis-stacked
    tensor (_AccStack) — one OR + one keep-AND per sub-round for every
    live plane — and the shared keep-clears go through
    bitset.masked_keep.
Measured on this image's XLA:CPU at N=12.5k r=16: 410.9 -> 85.1
executed kernels/round (docs/PERF.md round-7 table). The legacy
per-plane path stays selectable (cfg.wire_coalesced=False) and
bit-identical (tests/test_phase_stacked.py compares full state trees
across gossipsub/floodsub/randomsub at r in {1, 8, 16}).

Score/gater attribution is folded over the phase in packed word planes:
every (edge, msg) pair transmits at most once per phase (the fwd set is
one-shot and IWANT retransmissions are capped per phase head), so OR
accumulation preserves the exact transmission multiset. The P3 window
gate is evaluated per sub-round against each arrival's own tick
(on_deliveries(mesh_credit_words=...)), keeping window semantics at
1-round resolution.

Known deviations vs the per-round step, all bounded in PARITY.md:
  * control actions (grafts taking effect, gossip emission, IWANT
    service, score refresh, gater decisions) lag up to r-1 rounds — the
    reference's own control lags up to a full heartbeat interval;
  * deliveries of a message whose slot is recycled by a *later publish
    in the same phase* earn no score/gater credit (per-round attribution
    ran before each round's publishes; phase attribution runs at phase
    end, after recycled columns are cleared). Slots live M/publish-rate
    rounds, so this touches only messages already ~fully propagated;
  * heartbeat-tick quantization: the heartbeat always executes at the
    phase TAIL with ``tick_last``, while the schedule owner
    (driver.heartbeat_schedule) flags a phase when ANY tick in its
    window [t, t+r) is ≡ 0 (mod heartbeat_every). When heartbeat_every
    is a multiple of rounds_per_phase (every bench/driver default) the
    nominal tick IS the phase tail and there is no drift; when it is
    not, the executed heartbeat tick drifts up to r-1 rounds from the
    nominal schedule tick, so backoff expiry and fanout-TTL expiry —
    which compare against tick — quantize to phase tails. Callers
    choosing ``heartbeat_every % rounds_per_phase != 0`` accept that
    quantization (the reference's own timers are heartbeat-quantized
    the same way: backoff slack, gossipsub.go:1596).
"""

from __future__ import annotations

import os

import jax
import jax.numpy as jnp
import numpy as np

from ..chaos import faults as chaos_faults
from ..ops import bitset
from ..score.engine import (
    apply_delivery_counts,
    on_deliveries,
    per_slot_counts,
    slot_topic_words,
)
from ..score.gater import gater_on_round
from ..state import Net, PhasePubPlan, allocate_publishes, wrap_csr_resident
from ..trace.events import EV
from .common import RoundInfo, accumulate_round_events, finish_delivery
from .gossipsub import (
    GossipSubConfig,
    GossipSubState,
    accept_gates,
    apply_peer_transitions,
    apply_validation_throttle,
    control_exchange,
    control_exchange_coalesced,
    fanout_carry_words,
    fanout_carry_words_packed,
    handle_graft_prune,
    pack_fanout_peers,
    unpack_fanout_peers,
    handle_ihave,
    heartbeat,
    iwant_responses,
    joined_msg_words,
    live_step_views,
    merge_extra_tx,
    origin_msg_words,
    prepare_step_consts,
    px_connect,
    sender_carry_words,
    update_fanout_on_publish,
)


class PhaseAdmissionError(ValueError):
    """The phase publish schedule can re-allocate message slots WITHIN
    one phase (``rounds_per_phase * pub_width > msg_slots``) — the
    deferred recycled-slot clears' exactness assumption breaks, so the
    built step refuses at trace time (ADVICE round 5, item 2: the
    engine layer enforces what previously only ``api.Network._run_phase``
    enforced). Cap admitted publishes (``admission_capped=True`` after
    doing so), raise ``msg_slots``, or lower the publish rate."""


class _AccStack:
    """The phase's attribution accumulators as ONE edge-axis-stacked
    ``[N, C, W]`` tensor (round-7 tentpole): every live plane — [N, W]
    planes contribute one lane, [N, K, W] planes K lanes — shares the
    same two word-algebra folds per sub-round (OR the sub-round's update
    in, AND the recycled-slot keep mask), so the stacked form runs each
    fold as one wide kernel instead of one small kernel per plane. At
    the 12.5k shard the phase engine is fusion-count-bound (docs/PERF.md
    round-6 table: 94% of device time in many small ``not_and``/
    ``broadcast_and`` fusions), so lanes are cheaper than launches.

    ``stacked=False`` keeps every plane a separate array with separate
    folds — the legacy round-4..6 kernel structure — selected by
    ``cfg.wire_coalesced=False`` for A/B; both paths run the same
    updates in the same order, so they are bit-identical by
    construction (pinned by tests/test_phase_stacked.py)."""

    def __init__(self, specs, n: int, w: int, stacked: bool):
        # specs: (name, lanes, keep_masked); lanes=1 packs an [N, W]
        # plane, lanes=k an [N, k, W] plane
        self.specs = tuple(specs)
        self.stacked = stacked
        self.offs = {}
        off = 0
        for name, lanes, _ in self.specs:
            self.offs[name] = (off, lanes)
            off += lanes
        self.c = off
        if stacked:
            self.buf = jnp.zeros((n, off, w), jnp.uint32) if off else None
        else:
            self.planes = {
                name: jnp.zeros((n, w) if lanes == 1 else (n, lanes, w),
                                jnp.uint32)
                for name, lanes, _ in self.specs
            }

    def __contains__(self, name: str) -> bool:
        return name in self.offs

    def or_(self, updates: dict) -> "_AccStack":
        """OR the sub-round's updates in — one wide op when stacked.
        Every live plane must have an update (all accumulation sites run
        every sub-round)."""
        if self.stacked:
            if self.buf is not None:
                n, _, w = self.buf.shape
                upd = jnp.concatenate(
                    [updates[name].reshape(n, lanes, w)
                     for name, lanes, _ in self.specs], axis=1)
                self.buf = self.buf | upd
        else:
            for name, _, _ in self.specs:
                self.planes[name] = self.planes[name] | updates[name]
        return self

    def keep(self, keep_w: jax.Array) -> "_AccStack":
        """AND the recycled-slot keep mask into every keep-masked plane —
        one wide op when stacked (planes that must survive recycling,
        e.g. the exact-trace dup plane, ride an all-ones lane mask)."""
        if self.stacked:
            if self.buf is not None:
                lane_masked = jnp.asarray(
                    [m for _, lanes, m in self.specs for _ in range(lanes)],
                    bool)
                mask = jnp.where(
                    lane_masked[:, None], keep_w[None, :],
                    jnp.uint32(0xFFFFFFFF))
                self.buf = self.buf & mask[None]
        else:
            for name, lanes, masked in self.specs:
                if masked:
                    km = keep_w[None, :] if lanes == 1 else keep_w[None, None, :]
                    self.planes[name] = self.planes[name] & km
        return self

    def get(self, name: str, default=None):
        if name not in self.offs:
            return default
        if not self.stacked:
            return self.planes[name]
        off, lanes = self.offs[name]
        if lanes == 1:
            return self.buf[:, off, :]
        return self.buf[:, off : off + lanes, :]


def make_gossipsub_phase_step(
    cfg: GossipSubConfig,
    net: Net,
    rounds_per_phase: int,
    score_params=None,
    heartbeat_interval: float = 1.0,
    gater_params=None,
    dynamic_peers: bool = False,
    adversary_no_forward: np.ndarray | None = None,
    sub_knowledge_holes: np.ndarray | None = None,
    score_counts: bool | None = None,
    exact_counters: bool = False,
    admission_capped: bool = False,
    telemetry=None,
    adversary=None,
    lift_scores: bool = False,
):
    """Build the jitted multi-round phase step.

    With ``lift_scores=True`` (round 16, docs/DESIGN.md §16) the step
    takes a trailing TRACED ``score_plane`` (score.params.ScoreParams):
    weights/decays/thresholds read from the plane, one compiled
    program across weight sets, bit-exact vs the static build at
    matched values. The phase engine's static weight elision
    (p3_live/p4_live) is a build-time STRUCTURE decision on weight
    values, so the lifted build pins the conservative all-planes-live
    structure — LIFT_AUDIT.json records those reads as the guarded
    elision sites they are.

    phase_step(state, pub_origin[r,P], pub_topic[r,P], pub_valid[r,P],
               [up_next], *, do_heartbeat) -> state     (tick advances by r)

    ``do_heartbeat`` is a REQUIRED static bool: the caller owns the
    heartbeat schedule (`driver.scan_rounds` does this for you — phases
    whose tick window [t, t+r) contains a multiple of
    ``cfg.heartbeat_every`` must pass True). The heartbeat runs at most
    once per phase, at the phase tail, with the phase's last tick.

    Publish batches land per sub-round: ``pub_*[i]`` is injected at tick
    ``t + i`` exactly as the per-round step would, so workload timing and
    the propagation CDF are directly comparable.

    The fused Pallas data plane (PUBSUB_FUSED) is not applicable here —
    the phase engine's sender-side form already collapses the exchange to
    one gather per sub-round.

    ``cfg.wire_coalesced`` (default True) selects the round-7 stacked
    data plane — coalesced control-head exchange, head publish plan,
    stacked accumulator folds (see the module docstring); False builds
    the legacy per-plane structure, bit-identical, for A/B.

    **Admission invariant** (enforced here since round 6): a phase may
    admit at most ``msg_slots // 2`` publishes — slots recycled WITHIN a
    phase wipe their in-flight receipts before the boundary drain can
    observe them, and the deferred recycled-slot clears below additionally
    assume a slot is never re-allocated within its phase. The API layer
    caps admission (api.Network._run_phase); direct drivers feeding full
    ``[r, P]`` schedules can exceed it silently (e.g. pub_width=4, r=32,
    M=64 = 128 potential publishes/phase), so the built step WARNS at
    trace time when ``rounds_per_phase * pub_width > msg_slots // 2``.
    ``admission_capped=True`` (the API's builds) suppresses the warning —
    the caller certifies it enforces the flat cap itself.

    ``telemetry`` (a telemetry.TelemetryConfig) appends the time-series
    recorder at the phase TAIL: ONE panel row per PHASE
    (``rounds_per_row = r`` — the same cadence caveat the drain and the
    chaos metrics document), whose EV deltas cover all r sub-rounds plus
    the control head and heartbeat, so summed rows still reconcile
    bit-for-bit against the drained counters. The state must be built
    with the same config (``GossipSubState.init(telemetry=...)``) and a
    driver must start ticks at a multiple of r (every scan/driver does —
    the row index is ``tick0 // r``). None elides the plane statically.

    ``adversary`` (a chaos.adversary.Adversary) arms the vectorized
    attack suite (docs/DESIGN.md §13) at phase cadence: the data-plane
    behaviors (drop-on-forward, censorship) mask each sub-round's
    SENDER-side transmit composition with that round's own activity
    window, and the heartbeat-cadence behaviors (lie-in-IHAVE,
    graft-spam, self-promotion) ride the phase-tail heartbeat. None
    elides the plane statically (tests/test_adversary.py pins
    bit-exact adversary-off parity on the stacked wire path).
    """
    r = int(rounds_per_phase)
    assert r >= 1
    if lift_scores and not cfg.score_enabled:
        raise ValueError(
            "lift_scores=True needs cfg.score_enabled — the lifted "
            "plane parameterizes the v1.1 score machinery"
        )
    if cfg.router is not None:
        raise ValueError(
            "the phase engine predates the router plane (docs/DESIGN.md "
            "§24) — IDONTWANT suppression, choking, and the latency ring "
            "hook the per-round delivery composition; use "
            "make_gossipsub_step for router builds"
        )
    consts = prepare_step_consts(
        cfg, net, score_params, heartbeat_interval, gater_params,
        sub_knowledge_holes, adversary_no_forward, adversary,
    )
    adv = consts.adv
    tp = consts.tp
    # chaos plane: None elides it statically (the traced program is the
    # pre-chaos one — tests/test_chaos.py pins bit-exactness and `make
    # chaos-smoke` pins the compiled kernel census). When on, the control
    # head's outage mask is ONE AND on the stacked wire gather (net_w),
    # and each data sub-round applies its own round's link mask; the
    # Gilbert–Elliott chain advances once per sub-round, so fault
    # sequences match the per-round engine's cadence. Scheduled builds
    # take ONE link_deny per phase — partitions quantize to phase
    # boundaries, exactly like the churn plane's peer transitions.
    chaos = chaos_faults.resolve(cfg.chaos)
    chaos_sched = chaos is not None and chaos.scheduled
    adv_self = (
        jnp.asarray(adversary_no_forward, bool)
        if adversary_no_forward is not None else None
    )
    n_peers, k_dim = net.nbr.shape
    val_delay = cfg.validation_delay_rounds
    use_counts = (
        score_counts if score_counts is not None
        else os.environ.get("PUBSUB_PHASE_COUNTS", "") == "1"
    )
    # static weight elision: the topic score params are jit constants, so
    # attribution planes whose consuming weights are zero EVERYWHERE can
    # be skipped at build time. The mmd counter has TWO consumers: P3
    # (deficit via w3, compute_scores) and the sticky P3b mesh-failure
    # penalty (on_prune folds deficit^2 into mfp whenever w3b != 0 and
    # thr3 > 0 — score/engine.py on_prune), so the in-window mesh-credit
    # plane stays live if EITHER is weighted for any topic. The honest-
    # net bench configs zero both, dropping one of the two [N,K,W]
    # OR+store passes per sub-round. imd's only consumer is P4 via w4.
    #
    # ``exact_counters=True`` disables elision outright: scores are
    # bit-identical either way (the elided term multiplies by zero), but
    # elision leaves the UNREAD counters non-reference-faithful (mmd
    # undercounts near-first credit, mfp can overcount — see the loop
    # comment below). The reference's inspect surface is exact always
    # (score.go:120-177), so any build with a score inspector / snapshot
    # consumer attached (api.Network: peer_score_snapshots) must pass
    # this; the tracer-detached bench keeps elision.
    _w3 = np.asarray(consts.tpa.w3)
    _w3b = np.asarray(consts.tpa.w3b)
    _thr3 = np.asarray(consts.tpa.thr3)
    p3_live = exact_counters or bool(
        np.any(_w3 != 0.0) or np.any((_w3b != 0.0) & (_thr3 > 0.0))
    )
    p4_live = exact_counters or bool(np.any(np.asarray(consts.tpa.w4) != 0.0))
    if lift_scores:
        # a TRACED weight cannot drive build-time structure: the lifted
        # program keeps every attribution plane live so ONE compile is
        # correct for every weight set the plane sweeps (the elision
        # sites above are LIFT_AUDIT.json's guarded-elision evidence)
        p3_live = p4_live = True

    def _phase(st: GossipSubState, pub_origin, pub_topic, pub_valid, up_next,
               do_heartbeat: bool, link_deny=None,
               score_plane=None) -> GossipSubState:
        # lifted score plane (round 16): the VALUE-proved score fields
        # read from the traced plane; score_plane=None is the static
        # path, byte-identical to the pre-lift program (thr=cfg routes
        # threshold reads to the same Python floats)
        # a combined candidate plane (round 20) nests score + MeshParams;
        # detect by its `mesh` attribute, bare ScoreParams is unchanged
        mesh_plane = getattr(score_plane, "mesh", None)
        if score_plane is not None:
            sc = score_plane.score if mesh_plane is not None else score_plane
            tp_r = sc.gather(net.my_topics)
            sp_r, thr, wrt = sc, sc, sc.window_rounds
        else:
            tp_r, sp_r, thr, wrt = (tp, consts.score_params, cfg,
                                    consts.window_rounds_t)
        msh = cfg if mesh_plane is None else mesh_plane
        # telemetry: counters at phase ENTRY, before the churn plane's
        # ADD/REMOVE_PEER accounting (the phase-tail row's deltas cover
        # the whole phase, so the panel sums telescope exactly)
        ev_prev = st.core.events if telemetry is not None else None
        # ---- control head (once per phase) ------------------------------
        if dynamic_peers:
            st, live = apply_peer_transitions(cfg, net, st, up_next, tp_r)
        else:
            live = None
        net_l, nbr_sub_l, flood_from_l, nbr_sub_words_l = live_step_views(
            cfg, net, st, live, consts
        )
        core = st.core
        tick0 = core.tick
        m = core.msgs.capacity
        w = bitset.n_words(m)

        # the admission invariant, enforced at trace time (shapes are
        # static): see the builder docstring. ADVICE round 5 item 2.
        # Two tiers: a schedule that can exceed msg_slots WITHIN one
        # phase would re-allocate a slot inside its own phase — the
        # deferred recycled-slot clears are then WRONG, not merely
        # lossy, so that is a hard error; the (msg_slots//2, msg_slots]
        # band stays a warning (in-flight receipts of the previous
        # occupants can be wiped before the boundary drain sees them).
        if not admission_capped:
            flat_cap = r * pub_origin.shape[-1]
            if flat_cap > m:
                raise PhaseAdmissionError(
                    f"phase publish capacity rounds_per_phase*pub_width = "
                    f"{r}*{pub_origin.shape[-1]} = {flat_cap} exceeds "
                    f"msg_slots = {m}: a slot can be re-allocated WITHIN "
                    "one phase, which the deferred recycled-slot clears "
                    "assume never happens. Cap admitted publishes at "
                    f"{m // 2} per phase (api.Network._run_phase does; "
                    "pass admission_capped=True once you do), raise "
                    "msg_slots, or lower the publish rate."
                )
            if flat_cap > m // 2:
                import warnings

                warnings.warn(
                    f"phase publish capacity rounds_per_phase*pub_width = "
                    f"{r}*{pub_origin.shape[-1]} exceeds msg_slots//2 = "
                    f"{m // 2}: slots recycled within a phase silently wipe "
                    "in-flight receipts. Cap admitted publishes at "
                    f"{m // 2} per phase (api.Network._run_phase does), "
                    "raise msg_slots, or lower the publish rate.",
                    stacklevel=3,
                )

        acc_ok, acc_msg = accept_gates(cfg, net_l, st, gater_params,
                                       core.key, tick0, thr=thr)

        # ---- chaos plane: the phase-head round's link outages ----------
        # The control head crosses the wire ONCE, at round tick0 — its
        # outage mask is round tick0's, applied as a single AND on the
        # (stacked) wire gather via net_w. Data sub-rounds each apply
        # their own round's mask below (gate_i); the GE chain advances
        # once per sub-round so the fault cadence matches the per-round
        # engine's.
        if chaos is not None:
            chaos_seed = chaos_faults.chaos_seed(core.key)
            ge_bad = core.chaos.ge_bad if core.chaos is not None else None
            link_ok0, ge_bad = chaos_faults.round_link_ok(
                chaos, chaos_seed, net.nbr, tick0, ge_bad, link_deny,
            )
            net_w = net_l.replace(nbr_ok=net_l.nbr_ok & link_ok0)
            n_link_down = (
                chaos_faults.count_links_down(net.nbr, net_l.nbr_ok, link_ok0)
                if cfg.count_events else None
            )
        else:
            link_ok0 = ge_bad = n_link_down = None
            net_w = net_l

        if cfg.wire_coalesced:
            # ONE stacked gather for the whole control head: control
            # outboxes + score plane + IWANT window (+ the P5 app plane
            # when its weight is live) — the phase's halo budget drops
            # from 16·(r+4) to 16·(r+1) permutes (perf/projection.py)
            include_app = (
                cfg.score_enabled
                and consts.score_params.app_specific_weight != 0.0
            )
            (graft_in_raw, prune_in_raw, ihave_in_raw, px_in_raw,
             nbr_score_of_me, window_g, app_g) = control_exchange_coalesced(
                cfg, net, net_w, st, include_app=include_app
            )
        else:
            (graft_in_raw, prune_in_raw, ihave_in_raw, px_in_raw,
             nbr_score_of_me) = control_exchange(cfg, net, net_w, st)
            window_g = app_g = None
        st2, prune_resp, px_resp, px_ok, n_graft, n_prune = handle_graft_prune(
            cfg, net_l, st, tp_r, acc_ok, graft_in_raw, prune_in_raw,
            px_in_raw, thr=thr, msh=msh,
        )
        events = st.core.events
        if cfg.count_events:
            events = events.at[EV.GRAFT].add(n_graft).at[EV.PRUNE].add(n_prune)
        edge_live_next = px_connect(cfg, net, net_l, st, px_ok, dynamic_peers)
        # the IWANT-service window gather rides the wire view (net_w):
        # responses on a flapped link are lost and the retransmission
        # counters don't tick (the data never arrived)
        st2, iwant_resp = iwant_responses(cfg, net_w, st2, nbr_score_of_me,
                                          window_g=window_g, thr=thr)
        st2 = handle_ihave(cfg, net_l, st2, joined_msg_words(net_l, core.msgs),
                           acc_ok, ihave_in_raw, thr=thr)
        if consts.sender_fwd_ok is not None:
            iwant_resp = jnp.where(
                consts.sender_fwd_ok[:, :, None], iwant_resp, jnp.uint32(0)
            )
        # adversary data plane: an active drop/censor attacker withholds
        # its IWANT service too (the responses ride sub-round 0, so the
        # head tick's activity window applies) — receiver-side nbr-view
        # constants, zero extra halo permutes
        n_adv_drop = None
        if adv is not None and adv.data_plane:
            iwant_resp, rem_resp = adv.mask_transmit_nbr(
                tick0, iwant_resp, core.msgs)
            if cfg.count_events:
                n_adv_drop = bitset.popcount(
                    rem_resp, axis=None).sum().astype(jnp.int32)
        iwant_resp = jnp.where(acc_msg[:, :, None], iwant_resp, jnp.uint32(0))

        # phase-fixed data-plane constants (the r-round control latency:
        # mesh membership, scores, accept gates hold for the whole phase)
        mesh2 = st2.mesh
        if cfg.score_enabled:
            send_score_ok = st.scores >= thr.publish_threshold
        else:
            send_score_ok = net_l.nbr_ok
        # floodsub-semantics edges, sender side: I speak only floodsub =>
        # I push everything on every live edge (floodsub.go:76-100); my
        # neighbor speaks only floodsub => I push everything I'd publish
        # to it, score-gated (gossipsub.go:973-978)
        flood_send = (
            (consts.i_am_floodsub[:, None] & net_l.nbr_ok)
            | (flood_from_l & send_score_ok)
        )
        recv_gate = net_l.nbr_ok & acc_msg  # [N,K] receiver-side edge gate
        if cfg.flood_publish:
            fp_ok = send_score_ok if cfg.score_enabled else net_l.nbr_ok

        # ---- data loop: r delivery sub-rounds ---------------------------
        msgs = core.msgs
        dlv = core.dlv
        mcache = st2.mcache
        iwant_out = st2.iwant_out
        served_lo, served_hi = st2.served_lo, st2.served_hi
        promise_mid = st2.promise_mid
        fanout_st = st2  # fanout_topic/lastpub evolve per sub-round
        # fanout peers ride the loop in packed [N,F] u32 form (the bool
        # [N,F,K] plane is a pathological per-sub-round write target —
        # see pack_fanout_peers); unpacked back at the phase tail. The
        # packing needs K <= 32; wider-degree nets keep the bool path.
        fp_pack = (
            pack_fanout_peers(st2.fanout_peers)
            if cfg.fanout_slots > 0 and k_dim <= 32 else None
        )

        zkw = jnp.zeros((n_peers, k_dim, w), jnp.uint32)
        zw = jnp.zeros((n_peers, w), jnp.uint32)
        keep_acc = jnp.full((w,), 0xFFFFFFFF, jnp.uint32)
        s_slots = net.my_topics.shape[1]
        # Two score-attribution paths. The COUNT path (inline validation
        # only) reduces each sub-round's transmit tensor to per-
        # (peer,slot,edge) popcounts at arrival time — no [N,K,W]
        # attribution plane survives the loop, and credit lands exactly
        # when the per-round engine would land it, including a message's
        # death round. Measured on the real chip (N=100k) it LOSES to the
        # plane path (r=8: 1048 vs 1200 rounds/s; r=16: 1250 vs 1365):
        # the r-per-phase popcount trees cost more VPU time than the
        # plane ORs cost HBM stores on this libtpu. The PLANE path is
        # therefore the default; the count path stays as an opt-in
        # (score_counts=True / PUBSUB_PHASE_COUNTS=1) for workloads where
        # within-phase slot recycling would otherwise shave score credit,
        # and is required-off for the async-validation pipeline (pend_dup
        # needs cross-sub-round word algebra).
        count_score = cfg.score_enabled and val_delay == 0 and use_counts
        plane_score = cfg.score_enabled and not count_score
        # elision keeps the score values bit-identical (the elided term
        # multiplies by a zero weight everywhere) but changes what the
        # unread counters show to introspection: imd reads 0; mmd still
        # accrues first-arrival credit (on_deliveries adds it regardless)
        # but not the near-first/window portion — an undercount — and
        # consequently mfp (fed by on_prune's thr3 - mmd deficit) can
        # OVERcount when w3b==0 with thr3>0. All pinned by tests/
        # test_phase.py::test_phase_static_weight_elision_scores_exact
        # (an attempted round-4 optimization derived P4 from the
        # first-edge plane, on the theory that invalid messages travel
        # exactly one hop; FALSIFIED by the r=1 bit-exactness tests — an
        # origin advertises and IWANT-serves its own invalid publishes
        # from mcache, so invalid arrivals repeat across rounds on the
        # same edge. The trans plane stays.)
        # the live attribution planes, folded through _AccStack: one OR +
        # one keep-AND per sub-round over the whole stack when
        # cfg.wire_coalesced, per-plane folds (the legacy kernel
        # structure) otherwise. The exact-trace dup plane is the one
        # NON-keep-masked lane — see the dup_trace comment below.
        acc_specs = []
        if plane_score:
            acc_specs += [("new", 1, True), ("recv", 1, True)]
        if plane_score or cfg.gater_enabled:
            acc_specs += [("accepted", 1, True)]
        if plane_score and p4_live:
            acc_specs += [("trans", k_dim, True)]
        if plane_score and p3_live:
            acc_specs += [("mcw", k_dim, True)]
        if cfg.gater_enabled:
            acc_specs += [("dup", k_dim, True), ("rejw", k_dim, True),
                          ("ignw", k_dim, True)]
        if cfg.trace_exact:
            acc_specs += [("dupt", k_dim, False)]
        accs = _AccStack(acc_specs, n_peers, w, stacked=cfg.wire_coalesced)
        if count_score:
            zsc = jnp.zeros((n_peers, s_slots, k_dim), jnp.float32)
            fmd_counts, mmd_counts, imd_counts = zsc, zsc, zsc
        if cfg.gater_enabled:
            n_validated_acc = jnp.zeros((n_peers,), jnp.int32)
            n_throttled_acc = jnp.zeros((n_peers,), jnp.int32)
        if cfg.count_events:
            cnt = dict(n_deliver=jnp.int32(0), n_reject=jnp.int32(0),
                       n_duplicate=jnp.int32(0), n_rpc=jnp.int32(0),
                       n_drop=jnp.int32(0))
            n_pub = jnp.int32(0)
        info = None

        # phase-head batched publish allocation (state.PhasePubPlan): the
        # whole [r, P] schedule's slot/index math, keep masks, origin pub
        # words, and message-table snapshots as one set of wide head ops,
        # replacing r calls to allocate_publishes (~15 tiny kernels each
        # — the dominant launch swarm at the 12.5k shard)
        plan = (
            PhasePubPlan(msgs, n_peers, tick0, pub_origin, pub_topic,
                         pub_valid)
            if cfg.wire_coalesced else None
        )

        # membership word planes: on NARROW topic universes (T <= 8) the
        # planes are carried incrementally — a sub-round changes the
        # slot->topic mapping only at its <=P publish slots, so clearing
        # recycled columns + OR-ing per-publish one-hot word columns
        # replaces the per-sub-round recompute (measured +7% on the
        # default bench). On wide universes (eth2's T=64) the batched
        # compare+pack FUSES into its consumers and the incremental
        # dependency chain measured 9% SLOWER, so those recompute.
        incr_members = net.n_topics <= 8
        if incr_members:
            slotw = slot_topic_words(net_l, msgs.topic)
            joined_w = joined_msg_words(net_l, msgs)
        if plan is not None:
            # the origin word plane rides the loop incrementally on the
            # plan path: (origin_w & keep) | pub_words IS the next
            # sub-round's origin_msg_words (the recycled columns now
            # belong to the new publishes), replacing an [M]-scatter per
            # sub-round with one wide fold
            origin_w = origin_msg_words(net_l, msgs)

        n_iwant_rec = None
        for i in range(r):
            tick_i = tick0 + i
            # chaos: this sub-round's link mask (round tick0's was already
            # computed at the head — the control head shares it)
            if chaos is not None:
                if i == 0:
                    link_ok_i = link_ok0
                else:
                    link_ok_i, ge_bad = chaos_faults.round_link_ok(
                        chaos, chaos_seed, net.nbr, tick_i, ge_bad, link_deny,
                    )
                    if cfg.count_events:
                        n_link_down = n_link_down + chaos_faults.count_links_down(
                            net.nbr, net_l.nbr_ok, link_ok_i
                        )
                gate_i = recv_gate & link_ok_i
            else:
                gate_i = recv_gate
            if plan is not None:
                # the table as allocate_publishes would have left it after
                # sub-rounds < i (bit-identical snapshot; see PhasePubPlan)
                msgs = plan.msgs_at(i)
            if not incr_members:
                slotw = slot_topic_words(net_l, msgs.topic)
                joined_w = joined_msg_words(net_l, msgs)
            if plan is None:
                origin_w = origin_msg_words(net_l, msgs)

            # sender-side transmit composition: ONE edge gather per
            # sub-round carries the entire data plane
            carry = sender_carry_words(mesh2, slotw)
            if fp_pack is not None:
                carry = carry | fanout_carry_words_packed(
                    fp_pack, k_dim, fanout_st.fanout_topic, msgs.topic
                )
            elif cfg.fanout_slots > 0:
                carry = carry | fanout_carry_words(
                    fanout_st.fanout_peers, fanout_st.fanout_topic, msgs.topic
                )
            carry = carry | jnp.where(
                flood_send[:, :, None], jnp.uint32(0xFFFFFFFF), jnp.uint32(0)
            )
            if cfg.flood_publish:
                # v1.1 flood-publish, sender-side fold (== the receiver-side
                # origin compare: nbr_score_of_me at the receiver IS the
                # sender's score of that edge; gossipsub.go:957-963)
                carry = carry | jnp.where(
                    fp_ok[:, :, None], origin_w[:, None, :], jnp.uint32(0)
                )
            send = carry & dlv.fwd[:, None, :] & ~dlv.fe_words
            if adv_self is not None:
                # adversary behavior vector: marked peers run control but
                # never transmit message data (sybilSquatter analogue)
                send = jnp.where(
                    adv_self[:, None, None], jnp.uint32(0), send
                )
            if adv is not None and adv.data_plane:
                # scheduled drop/censor attackers mask their OWN rows
                # before the one edge gather (sender-side — the phase
                # engine's transmit composition), each sub-round under
                # its own tick's activity window; the removed bits are
                # the withheld-transmission attribution (sender-side —
                # an upper bound: the receiver's joined/origin/link
                # gates apply after the gather)
                send, rem_send = adv.mask_transmit_self(tick_i, send, msgs)
                if cfg.count_events:
                    n_adv_drop = n_adv_drop + bitset.popcount(
                        rem_send, axis=None).sum().astype(jnp.int32)
            trans = jnp.where(
                gate_i[:, :, None], net_l.edge_gather(send), jnp.uint32(0)
            )
            nm = ~origin_w
            if msgs.wire_block is not None:
                nm = nm & ~bitset.pack(msgs.wire_block)[None, :]
            trans = trans & (joined_w & nm)[:, None, :]

            pre_have = dlv.have
            dlv, info = finish_delivery(
                net_l, msgs, dlv, trans, tick_i,
                count_events=cfg.count_events, queue_cap=cfg.queue_cap,
                val_delay_topic=cfg.validation_delay_topic,
            )
            if i == 0:
                # IWANT responses computed at the phase head ride the first
                # sub-round (r-round service latency, like the reference's
                # heartbeat-batched gossip turnaround)
                have_pre_merge = dlv.have
                dlv, info = merge_extra_tx(
                    net_l, msgs, dlv, info, iwant_resp, tick_i,
                    count_events=cfg.count_events, queue_cap=cfg.queue_cap,
                    val_delay_topic=cfg.validation_delay_topic,
                )
                if chaos is not None and cfg.count_events:
                    # IWANT-recovery attribution (same arrival-cohort
                    # convention as the per-round step): first arrivals
                    # that rode the IWANT service
                    valid_w_head = (
                        plan.valid_words[0] if plan is not None
                        else bitset.pack(msgs.valid)
                    )
                    n_iwant_rec = bitset.popcount(
                        (dlv.have & ~have_pre_merge)
                        & valid_w_head[None, :], axis=None,
                    ).sum().astype(jnp.int32)
            acc_upd = {}
            if cfg.trace_exact:
                # pre-throttle, like the per-round step: throttled receipts
                # are fresh (traced Reject), not duplicates. Phase
                # resolution coarsens timestamps; totals stay exact. NOT
                # keep-masked below: a dup bit names the message its slot
                # held at arrival, attributed against the phase-START
                # slot->mid mapping (exact while slots outlive a phase —
                # the M >> r*P sizing every tracing config satisfies)
                acc_upd["dupt"] = (
                    info.trans
                    & ~(dlv.fe_words & info.recv_new_words[:, None, :])
                )
            valid_w_i = (
                plan.valid_words[i] if plan is not None
                else bitset.pack(msgs.valid)
            )
            if cfg.validation_capacity > 0:
                dlv, info, accepted_new, n_thr = apply_validation_throttle(
                    dlv, info, cfg.validation_capacity, m, valid_w_i
                )
            else:
                accepted_new = info.new_words
                n_thr = None

            # ---- attribution accumulation (ONE stacked OR of word
            # planes when cfg.wire_coalesced, per-plane ORs otherwise, or
            # the direct per-slot count reduction; all exact — each
            # (edge,msg) transmits at most once per phase) ----------------
            if plane_score:
                acc_upd["new"] = info.new_words
                acc_upd["recv"] = info.recv_new_words
                if "trans" in accs:
                    acc_upd["trans"] = info.trans
            if "accepted" in accs:
                acc_upd["accepted"] = accepted_new
            if cfg.score_enabled and (p3_live or count_score):
                # P3 window gate at this arrival's own tick (score.go:
                # 944-974 markDuplicateMessageDelivery window check)
                msg_window = wrt[jnp.clip(msgs.topic, 0)]
                within_i = bitset.pack(
                    (dlv.first_round >= 0)
                    & ((tick_i - dlv.first_round) <= msg_window[None, :])
                )
            if count_score:
                valid3 = valid_w_i[None, None, :]
                mesh_w = info.trans & valid3 & within_i[:, None, :]
                fa_w = dlv.fe_words & info.new_words[:, None, :] & valid3
                ign_i = (
                    plan.ignored_words[i] if plan is not None
                    else bitset.pack(msgs.ignored)
                )
                inv_w = info.trans & ~(valid_w_i | ign_i)[None, None, :]

                mmd_counts = mmd_counts + per_slot_counts(mesh_w, slotw)
                fmd_counts = fmd_counts + per_slot_counts(fa_w, slotw)
                imd_counts = imd_counts + per_slot_counts(inv_w, slotw)
            elif plane_score and p3_live:
                mcw_i = info.trans & within_i[:, None, :]
                if val_delay > 0:
                    # duplicates arriving while the message sits in the
                    # validation pipeline (score.go:712-718); the fresh
                    # first arrival earns credit at its verdict instead
                    pend_post = bitset.word_or_reduce(dlv.pending, axis=1)
                    fa_i = dlv.fe_words & info.recv_new_words[:, None, :]
                    mcw_i = mcw_i | (
                        info.trans & pend_post[:, None, :] & ~fa_i
                    )
                acc_upd["mcw"] = mcw_i
            if cfg.gater_enabled:
                acc_upd["dup"] = info.trans & pre_have[:, None, :]
                ign_w_i = (
                    plan.ignored_words[i] if plan is not None
                    else bitset.pack(msgs.ignored)
                )
                acc_upd["rejw"] = (
                    info.trans & ~(valid_w_i | ign_w_i)[None, None, :]
                )
                acc_upd["ignw"] = info.trans & ign_w_i[None, None, :]
                n_validated_acc = n_validated_acc + bitset.popcount(
                    accepted_new, axis=-1
                )
                if n_thr is not None:
                    n_throttled_acc = n_throttled_acc + n_thr
            accs = accs.or_(acc_upd)
            if cfg.count_events:
                for k in cnt:
                    cnt[k] = cnt[k] + getattr(info, k)

            # mcache insertion: validated receipts in joined topics
            put = info.new_words & valid_w_i[None, :] & joined_w
            if not cfg.wire_coalesced:
                mcache = mcache.at[:, 0, :].set(mcache[:, 0, :] | put)

            # publishes for this sub-round + recycled-slot cleanup (the
            # scatter form wins in the phase sub-round at N >= 20k —
            # state.py allocate_publishes docstring has the measurements)
            if plan is not None:
                # the table half already lives in the head snapshots
                # (msgs_at(i+1) is read at the next iteration's top); only
                # the delivery-state folds run here, fed by the
                # precomputed masks
                _slots, is_pub = plan.sidx[i], plan.is_pub[i]
                keep_w, pub_words = plan.keep_w[i], plan.pub_words[i]
                dlv = plan.apply_to_delivery(
                    dlv, i, tick_i, scatter_form=n_peers >= 20_000
                )
                origin_w = (origin_w & keep_w[None, :]) | pub_words
            else:
                msgs, dlv, _slots, is_pub, keep_w, pub_words = \
                    allocate_publishes(
                        msgs, dlv, tick_i, pub_origin[i], pub_topic[i],
                        pub_valid[i], scatter_form=n_peers >= 20_000,
                    )
            # incremental membership-plane maintenance (narrow universes):
            # recycled columns clear, then each publish ORs its one-hot
            # word column where the peer/slot matches the new topic
            p_dim = pub_origin.shape[-1]
            if incr_members and cfg.wire_coalesced:
                # batched form of the per-publish loop below: the P one-hot
                # word columns are built at once and OR-reduced into the
                # planes — ~4 wide kernels instead of ~4 small ones per
                # publish slot (OR is associative: identical bits land)
                slotw, joined_w, mcache = bitset.masked_keep(
                    [slotw, joined_w, mcache], keep_w
                )
                t_p = jnp.clip(pub_topic[i], 0)  # [P]
                warange = jnp.arange(w, dtype=jnp.int32)
                colw = jnp.where(
                    (warange[None, :] == _slots[:, None] // bitset.WORD)
                    & is_pub[:, None],
                    jnp.uint32(1)
                    << (_slots[:, None] % bitset.WORD).astype(jnp.uint32),
                    jnp.uint32(0),
                )  # [P, W] one-hot word columns
                # subscribed[:, t_p] without the [N]-row gather: a compare
                # +any over the narrow (T <= 8) topic axis fuses to vector
                # work (same finding as slot_topic_words)
                t_onehot = (
                    jnp.arange(net.n_topics, dtype=jnp.int32)[None, :, None]
                    == t_p[None, None, :]
                )  # [1, T, P]
                sub_p = jnp.any(
                    net_l.subscribed[:, :, None] & t_onehot, axis=1
                )  # [N, P]
                joined_w = joined_w | bitset.word_or_reduce(
                    jnp.where(sub_p[:, :, None], colw[None], jnp.uint32(0)),
                    axis=1,
                )
                slot_match = (
                    net_l.my_topics[:, :, None] == t_p[None, None, :]
                )  # [N, S, P]
                slotw = slotw | bitset.word_or_reduce(
                    jnp.where(slot_match[..., None], colw[None, None],
                              jnp.uint32(0)),
                    axis=2,
                )
            elif incr_members:
                slotw = slotw & keep_w[None, None, :]
                joined_w = joined_w & keep_w[None, :]
                warange = jnp.arange(w, dtype=jnp.int32)
                for j in range(p_dim):
                    s_j = _slots[j]
                    t_j = jnp.clip(pub_topic[i, j], 0)
                    live_j = is_pub[j]
                    colw = jnp.where(
                        (warange == s_j // bitset.WORD) & live_j,
                        jnp.uint32(1)
                        << (s_j % bitset.WORD).astype(jnp.uint32),
                        jnp.uint32(0),
                    )  # [W] one-hot word column for slot s_j
                    joined_w = joined_w | jnp.where(
                        net_l.subscribed[:, t_j][:, None], colw[None, :],
                        jnp.uint32(0),
                    )
                    slotw = slotw | jnp.where(
                        (net_l.my_topics == t_j)[:, :, None],
                        colw[None, None, :], jnp.uint32(0),
                    )
            if cfg.wire_coalesced:
                if not incr_members:
                    mcache = mcache & keep_w[None, None, :]
                # one window-0 update for this sub-round's put AND the
                # publish stamps: ((m|put)&keep)|pub == (m&keep)|(put&keep)
                # |pub — the mcache clear already ran (masked_keep above /
                # the & keep_w line), so fold put through keep_w here
                mcache = mcache.at[:, 0, :].set(
                    mcache[:, 0, :] | (put & keep_w[None, :]) | pub_words
                )
            else:
                mcache = mcache & keep_w[None, None, :]
                mcache = mcache.at[:, 0, :].set(mcache[:, 0, :] | pub_words)
            # iwant_out / served / promise recycled-slot clears DEFER to
            # the phase tail (keep_acc): nothing inside the loop reads or
            # writes them (asks and service budgets are written at the
            # control head only, promises created at the head only), and
            # a recycled slot is never re-allocated within the same phase
            # (the admission cap bounds publishes at msg_slots // 2), so
            # one tail application of the accumulated mask is exact —
            # saving three [N,K,W] AND passes + a bit_get per sub-round
            # (mcache CANNOT defer: its clear must precede the same
            # sub-round's put of the slot's NEW message)
            keep_acc = keep_acc & keep_w
            # recycled slots drop out of the phase accumulators too — their
            # columns now belong to a different message (the count path
            # needs no clearing: its credits were reduced at arrival time,
            # when the slot still named the right message; the exact-trace
            # dup lane is deliberately NOT cleared — see its comment)
            accs = accs.keep(keep_w)
            if cfg.count_events:
                n_pub = n_pub + jnp.sum(is_pub.astype(jnp.int32))

            if cfg.fanout_slots > 0:
                upd = update_fanout_on_publish(
                    cfg, net_l,
                    fanout_st.replace(core=fanout_st.core.replace(tick=tick_i)),
                    pub_origin[i], pub_topic[i],
                    jax.random.fold_in(
                        jax.random.fold_in(core.key, tick_i), 0xFA40
                    ),
                    nbr_sub_words_l,
                    fp_pack=fp_pack, thr=thr, msh=msh,
                )
                if fp_pack is not None:
                    fanout_st, fp_pack = upd
                else:
                    fanout_st = upd

        # ---- phase tail (once) ------------------------------------------
        if plan is not None:
            msgs = plan.msgs_at(r)  # the phase-final message table
        # deferred recycled-slot clears (see the loop comment) — one
        # stacked fold over the three [N,K,W] planes on the coalesced path
        if cfg.wire_coalesced:
            iwant_out, served_lo, served_hi = bitset.masked_keep(
                [iwant_out, served_lo, served_hi], keep_acc
            )
        else:
            iwant_out = iwant_out & keep_acc[None, None, :]
            served_lo = served_lo & keep_acc[None, None, :]
            served_hi = served_hi & keep_acc[None, None, :]
        promise_reused = bitset.bit_get(
            (~keep_acc)[None, None, :], promise_mid
        )
        promise_mid = jnp.where(
            (promise_mid >= 0) & promise_reused, -1, promise_mid
        )
        tick_last = tick0 + (r - 1)
        score = st2.score
        if count_score:
            score = apply_delivery_counts(
                score, tp_r, fmd_counts, mmd_counts, imd_counts, mesh2
            )
        elif plane_score:
            score = on_deliveries(
                score, net_l, mesh2, tp_r,
                accs.get("trans", zkw), accs.get("new"),
                dlv.fe_words, dlv.first_round,
                msgs.topic, msgs.valid, tick_last, wrt,
                msg_ignored=msgs.ignored,
                slotw=slot_topic_words(net_l, msgs.topic),
                recv_new_words=accs.get("recv"),
                mesh_credit_words=accs.get("mcw", zkw),
            )
        gater_state = st2.gater
        if cfg.gater_enabled:
            valid_w_end = bitset.pack(msgs.valid)
            first_arrival = (
                dlv.fe_words & accs.get("accepted")[:, None, :]
                & valid_w_end[None, None, :]
            )
            deliver_inc = bitset.popcount(first_arrival, axis=-1).astype(jnp.float32)
            gater_state = gater_on_round(
                gater_state, n_validated_acc, n_throttled_acc, deliver_inc,
                bitset.popcount(accs.get("dup"), axis=-1).astype(jnp.float32),
                bitset.popcount(accs.get("rejw"), axis=-1).astype(jnp.float32),
                tick_last,
                ignore_inc=bitset.popcount(
                    accs.get("ignw"), axis=-1
                ).astype(jnp.float32),
            )
        if cfg.count_events:
            # accumulate_round_events consumes only the scalar counters;
            # the plane fields are placeholders (DCE'd when unaccumulated)
            info_sum = RoundInfo(
                trans=zkw, new_words=zw,
                new_bits=bitset.unpack(zw, m), recv_new_words=zw,
                **cnt,
            )
            events = accumulate_round_events(events, info_sum, n_pub)
            if chaos is not None:
                events = events.at[EV.LINK_DOWN].add(n_link_down)
                if n_iwant_rec is not None:
                    events = events.at[EV.IWANT_RECOVER].add(n_iwant_rec)
            if n_adv_drop is not None:
                events = events.at[EV.ADV_DROP].add(n_adv_drop)

        core_next = core.replace(msgs=msgs, dlv=dlv, events=events,
                                 tick=tick_last)
        if chaos is not None and chaos.needs_state:
            core_next = core_next.replace(
                chaos=core.chaos.replace(ge_bad=ge_bad)
            )
        st2 = st2.replace(
            core=core_next,
            mcache=mcache,
            ihave_out=jnp.zeros_like(st2.ihave_out),
            iwant_out=iwant_out,
            served_lo=served_lo,
            served_hi=served_hi,
            promise_mid=promise_mid,
            graft_out=jnp.zeros_like(st2.graft_out),
            prune_out=prune_resp,
            prune_px_out=px_resp,
            edge_live=edge_live_next,
            score=score,
            gater=gater_state,
            fanout_topic=fanout_st.fanout_topic,
            fanout_peers=(
                unpack_fanout_peers(fp_pack, k_dim)
                if fp_pack is not None else fanout_st.fanout_peers
            ),
            fanout_lastpub=fanout_st.fanout_lastpub,
            dup_trans=accs.get("dupt"),
        )

        # congested links suppress this heartbeat's gossip toward them
        # (queue_cap backpressure; last sub-round's saturation, like the
        # per-round step's)
        if cfg.queue_cap > 0:
            sat_recv = bitset.popcount(info.trans, axis=-1) >= cfg.queue_cap
            gossip_suppress = net_l.edge_gather(sat_recv) & net_l.nbr_ok
            st2 = st2.replace(congested_in=sat_recv)
        else:
            gossip_suppress = None

        if do_heartbeat:
            st2 = heartbeat(
                cfg, net_l, st2, tp_r, sp_r, nbr_sub_l,
                gater_params, nbr_sub_words_l, present_ok=net.nbr_ok,
                gossip_suppress=gossip_suppress, app_gathered=app_g,
                adversary=adv, thr=thr, msh=msh,
            )

        # telemetry row — one per phase, recorded LAST (after the
        # heartbeat's GRAFT/PRUNE accounting), at phase-tail state
        if telemetry is not None:
            from ..telemetry import panel as _tele

            core_f = st2.core
            telem = _tele.record_step(
                telemetry, core_f.telem, tick0, ev_prev, core_f.events,
                net_l, core_f.msgs, core_f.dlv, rounds_per_row=r,
                mesh=st2.mesh, my_topics=net_l.my_topics,
                scores=st2.scores,
                backoff_active=(st2.backoff_present
                                & (st2.backoff_expire > tick_last)),
            )
            st2 = st2.replace(core=core_f.replace(telem=telem))
        return st2.replace(core=st2.core.replace(tick=tick0 + r))

    if net.edge_layout == "csr":
        # CSR-resident state tier (round 18): flat planes in the carry,
        # dense views inside the phase — same wrap as the per-round step
        _phase = wrap_csr_resident(net, _phase)

    if lift_scores:
        # lifted call convention (same as the per-round builder): the
        # TRACED score plane is the LAST positional, after up_next /
        # link_deny — ensemble.lift_step vmaps it like any per-sim
        # input (the configs×sims sweep axis)
        def step(st, pub_origin, pub_topic, pub_valid, *rest,
                 do_heartbeat):
            up = rest[0] if dynamic_peers else None
            deny = rest[int(dynamic_peers)] if chaos_sched else None
            return _phase(st, pub_origin, pub_topic, pub_valid, up,
                          do_heartbeat, deny, score_plane=rest[-1])
        return jax.jit(step, donate_argnums=0,
                       static_argnames=("do_heartbeat",))

    # scheduled-chaos builds take the Scenario's forced-down link mask as
    # a REQUIRED trailing positional — ONE [N, K] plane per phase (like
    # the churn plane's one liveness row: partitions land at phase heads)
    if dynamic_peers and chaos_sched:
        def step(st, pub_origin, pub_topic, pub_valid, up_next, link_deny,
                 *, do_heartbeat):
            return _phase(st, pub_origin, pub_topic, pub_valid, up_next,
                          do_heartbeat, link_deny)
    elif dynamic_peers:
        def step(st, pub_origin, pub_topic, pub_valid, up_next, *, do_heartbeat):
            return _phase(st, pub_origin, pub_topic, pub_valid, up_next,
                          do_heartbeat)
    elif chaos_sched:
        def step(st, pub_origin, pub_topic, pub_valid, link_deny,
                 *, do_heartbeat):
            return _phase(st, pub_origin, pub_topic, pub_valid, None,
                          do_heartbeat, link_deny)
    else:
        def step(st, pub_origin, pub_topic, pub_valid, *, do_heartbeat):
            return _phase(st, pub_origin, pub_topic, pub_valid, None,
                          do_heartbeat)
    return jax.jit(step, donate_argnums=0, static_argnames=("do_heartbeat",))
