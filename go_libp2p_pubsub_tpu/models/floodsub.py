"""FloodSub router, vectorized (floodsub.go, proto /floodsub/1.0.0).

Reference semantics (floodsub.go:76-100 Publish): forward each message to
every connected peer subscribed to its topic, except the peer it came from
and the origin. Dedup is the seen-cache. No mesh, no gossip, no scoring.

Vector form: the edge-carry mask is simply "receiver subscribes to the
topic" — one packed word-mask per receiver, broadcast over its edges; the
shared delivery engine applies the source/origin exclusions and dedup.

Edge layout: the step inherits the Net's static ``edge_layout`` through
the shared ``delivery_round`` seam — a ``Net.build(edge_layout="csr")``
topology runs the whole transmit composition over the flat [E] edge
space (ops/csr.py; bit-exact vs dense, tests/test_csr.py) with zero
runtime branching, which is what `make scale-smoke` drives at N=1M.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp

from ..chaos import adversary as adversary_mod
from ..chaos import faults as chaos_faults
from ..state import Net, SimState, allocate_publishes
from ..trace.events import EV
from .common import accumulate_round_events, delivery_round, subscribed_msg_words


def flood_edge_mask(net: Net, msgs) -> jax.Array:
    """[N, K, W]: every edge may carry everything its *receiver* subscribes
    to (the sender-side topics-map check of floodsub.go:77-84 seen from the
    receiving end)."""
    sub_words = subscribed_msg_words(net, msgs)  # [N, W]
    return jnp.broadcast_to(sub_words[:, None, :], (net.n_peers, net.max_degree, sub_words.shape[-1]))


@functools.partial(jax.jit, donate_argnums=1,
                   static_argnames=("queue_cap", "stacked", "chaos",
                                    "telemetry", "adversary"))
def floodsub_step(
    net: Net,
    state: SimState,
    pub_origin: jax.Array,  # [P] i32, -1 pad
    pub_topic: jax.Array,   # [P] i32
    pub_valid: jax.Array,   # [P] bool
    queue_cap: int = 0,     # per-edge outbound budget (comm.go:139-170;
                            # floodsub's own drop is floodsub.go:91-98)
    stacked: bool = True,   # stacked recycled-slot clears (round-7;
                            # False = legacy per-plane kernels for A/B)
    chaos=None,             # ChaosConfig | None — link-fault injection
                            # (chaos/faults.py); None/off elides statically
    link_deny: jax.Array | None = None,  # [N,K] bool scheduled outages
                            # (ChaosConfig.scheduled scenarios)
    telemetry=None,         # TelemetryConfig | None — per-round panel row
                            # (telemetry/panel.py; state needs
                            # SimState.init(telemetry=...)); None elides
    adversary=None,         # chaos.adversary.Adversary | None — the
                            # attack plane's DATA behaviors (drop-on-
                            # forward / censorship; the mesh/score
                            # behaviors have no floodsub analogue).
                            # Identity-hashed static arg; None elides
                            # statically. floodsub takes `net` traced,
                            # so the attacker neighbor views trace as
                            # one [N] -> [N, K] gather per round (the
                            # factory engines bake them as constants)
    score_plane=None,       # score.params.ScoreParams | None — the
                            # round-16 lifted-plane seam, TRACED and
                            # KEYWORD-ONLY in practice (the defaulted
                            # statics above sit between it and the
                            # pub arrays). The floodsub router has no
                            # score machinery (floodsub.go has no
                            # scoring), so the plane is accepted and
                            # unused; configs×sims sweeps thread it
                            # positionally through the
                            # ensemble.lift_floodsub(lift_scores=True)
                            # adapter, which keeps the four-engine
                            # lifted call convention uniform
                            # (docs/DESIGN.md §16)
) -> SimState:
    """One synchronous round: deliver in-flight messages one hop, then
    intern this round's publishes (they start propagating next round).

    The async-validation pipeline and the outbound-queue cap both live
    BELOW the router in the reference, so they apply here exactly as in
    gossipsub: build the state with ``SimState.init(val_delay=...)`` for
    the pipeline (its presence in ``state.dlv.pending`` is the
    configuration), pass ``queue_cap`` for lossy backpressure. The chaos
    plane likewise sits below every router: the same generators that
    flap gossipsub links flap floodsub's (a GE-generator config needs
    ``SimState.init(chaos_ge=True)``)."""
    chaos = chaos_faults.resolve(chaos)
    adv_pop = adversary_mod.resolve(adversary)
    edge_mask = flood_edge_mask(net, state.msgs)
    if chaos is not None:
        ge_bad = state.chaos.ge_bad if state.chaos is not None else None
        link_ok, ge_bad_next = chaos_faults.round_link_ok(
            chaos, chaos_faults.chaos_seed(state.key), net.nbr, state.tick,
            ge_bad, link_deny,
        )
        edge_mask = jnp.where(link_ok[:, :, None], edge_mask, jnp.uint32(0))
    n_adv_drop = None
    if adv_pop is not None:
        adv = adversary_mod.AdversaryConsts(adv_pop, net)
        if adv.data_plane:
            edge_mask, removed = adv.mask_transmit_nbr(
                state.tick, edge_mask, state.msgs)
            n_adv_drop = adversary_mod.withheld_count(
                net, state.dlv.fwd, removed)
    dlv, info = delivery_round(net, state.msgs, state.dlv, edge_mask, state.tick,
                               queue_cap=queue_cap)

    msgs, dlv, _slots, is_pub, _keep, _pub_words = allocate_publishes(
        state.msgs, dlv, state.tick, pub_origin, pub_topic, pub_valid,
        stacked_clears=stacked,
    )
    events = accumulate_round_events(state.events, info, jnp.sum(is_pub.astype(jnp.int32)))
    if chaos is not None:
        events = events.at[EV.LINK_DOWN].add(
            chaos_faults.count_links_down(net.nbr, net.nbr_ok, link_ok)
        )
        if chaos.needs_state:
            state = state.replace(chaos=state.chaos.replace(ge_bad=ge_bad_next))
    if n_adv_drop is not None:
        events = events.at[EV.ADV_DROP].add(n_adv_drop)

    telem = state.telem
    if telemetry is not None:
        from ..telemetry import panel as _tele

        # mesh-less engine: the mesh/score columns record zeros (the
        # catalog is fixed so panels from different engines stack)
        telem = _tele.record_step(
            telemetry, telem, state.tick, state.events, events,
            net, msgs, dlv,
        )
    return state.replace(tick=state.tick + 1, msgs=msgs, dlv=dlv,
                         events=events, telem=telem)


def run_rounds(net: Net, state: SimState, n_rounds: int) -> SimState:
    """Run delivery-only rounds (no new publishes) under lax.scan."""
    p = jnp.full((1,), -1, jnp.int32)

    def body(s, _):
        return floodsub_step(net, s, p, p, jnp.zeros((1,), bool)), None

    state, _ = jax.lax.scan(body, state, None, length=n_rounds)
    return state
