"""Harness-level fault injection: chaos engineering for the simulator
itself, not the simulated network.

The chaos/ plane injects faults INTO the simulation (lossy links,
partitions, sybils). This module injects faults into the MACHINERY
AROUND it — the supervised service loop (serve/supervisor.py) — to
drive the recovery tests and ``make service-smoke``:

  * **SIGKILL crash points** (:meth:`FaultPlan.maybe_kill` + the
    checkpoint store's ``write_hook`` seam): die at a segment boundary,
    mid-checkpoint-write (tmp written and TRUNCATED, final not yet in
    place — the dirtiest window), after the snapshot rename but before
    the manifest commit. The recovery contract: resuming the killed run
    finishes bit-exact vs an uninterrupted control.
  * **transient dispatch failures** (:meth:`FaultPlan.before_dispatch`):
    raise :class:`TransientDispatchError` the first k attempts of a
    segment's dispatch, exercising the supervisor's
    backoff-retry-degrade ladder.
  * **state corruption** (:meth:`FaultPlan.corrupt_state`): overwrite
    one element of a named floating-point state leaf with NaN (or drive
    an event counter backwards) after a chosen dispatch — the silent
    host/device corruption the health probes exist to catch. The fault
    fires once on the windowed pass and once more on the supervisor's
    rollback REPLAY (so the per-dispatch localizer sees it at the same
    point), then exhausts — a transient, recoverable corruption. Raise
    ``corrupt_max_fires`` to model persistent damage (the supervisor
    then halts with the forensic bundle).
  * **checkpoint file damage** (module helpers): truncate a snapshot,
    flip a bit, or rewrite one leaf member under an unchanged CRC
    vector — the three flavors ``checkpoint.CheckpointCorrupt`` must
    classify and the store's manifest fallback must survive.

Everything is deterministic given the plan (no wall-clock, no ambient
randomness), so a killed child and its resumed sibling — and the
windowed pass and its replay — see identical fault streams.
"""

from __future__ import annotations

import dataclasses
import os
import signal

import jax
import jax.numpy as jnp
import numpy as np


class TransientDispatchError(RuntimeError):
    """A dispatch failed in a way worth retrying (the injected stand-in
    for flaky host↔device transport / allocator hiccups)."""


#: write_hook stages (serve/store.py) a kill_site may name
KILL_SITES = ("post-segment", "mid-write", "post-rename")


@dataclasses.dataclass
class FaultPlan:
    """One run's fault schedule. Segment indices are the supervisor's
    loop ordinals (0-based); ``corrupt_dispatch`` is the dispatch index
    WITHIN the segment (-1 = the segment's last dispatch)."""

    #: SIGKILL this process when the site is reached for the segment
    kill_segment: int | None = None
    kill_site: str = "post-segment"
    #: segment -> number of transient dispatch failures to inject
    fail_dispatches: dict = dataclasses.field(default_factory=dict)
    #: NaN-corrupt a state leaf after (segment, dispatch)
    corrupt_segment: int | None = None
    corrupt_dispatch: int = -1
    corrupt_leaf: str = "scores"
    corrupt_kind: str = "nan"          # "nan" | "events" | "topo"
    corrupt_max_fires: int = 2         # windowed pass + rollback replay

    def __post_init__(self):
        if self.kill_site not in KILL_SITES:
            raise ValueError(
                f"kill_site must be one of {KILL_SITES}, "
                f"got {self.kill_site!r}")
        self._fails_left = {int(k): int(v)
                            for k, v in self.fail_dispatches.items()}
        self._corrupt_fires = 0

    # -- crash points ---------------------------------------------------

    def maybe_kill(self, site: str, segment: int) -> None:
        """SIGKILL — not an exception; the point is that NOTHING
        downstream runs, exactly like a host power loss."""
        if self.kill_segment is not None and site == self.kill_site \
                and segment == self.kill_segment:
            os.kill(os.getpid(), signal.SIGKILL)

    def store_hook(self, segment_fn):
        """A serve/store.py ``write_hook`` bound to this plan.
        ``segment_fn()`` reports the supervisor's current segment (the
        store doesn't know it). ``mid-write`` truncates the tmp file
        first so the crash really is a partial write."""

        def hook(stage: str, path: str) -> None:
            seg = segment_fn()
            if stage == "tmp-written" and self.kill_site == "mid-write" \
                    and self.kill_segment == seg:
                size = os.path.getsize(path)
                with open(path, "r+b") as f:
                    f.truncate(max(1, size // 2))
                os.kill(os.getpid(), signal.SIGKILL)
            if stage == "renamed":
                self.maybe_kill("post-rename", seg)

        return hook

    # -- transient dispatch failures -------------------------------------

    def before_dispatch(self, segment: int) -> None:
        """The injectable dispatch seam: raises while this segment's
        transient-failure budget remains (the real window call never
        starts, so the un-donated state stays retryable — matching the
        transport failures this models, which fail before launch)."""
        left = self._fails_left.get(int(segment), 0)
        if left > 0:
            self._fails_left[int(segment)] = left - 1
            raise TransientDispatchError(
                f"injected transient dispatch failure (segment {segment}, "
                f"{left - 1} more to come)")

    # -- state corruption ------------------------------------------------

    def wants_corruption(self, segment: int) -> bool:
        return (self.corrupt_segment == segment
                and self._corrupt_fires < self.corrupt_max_fires)

    def resolved_dispatch(self, segment_len: int) -> int:
        """The segment-local dispatch index the corruption targets."""
        return (self.corrupt_dispatch if self.corrupt_dispatch >= 0
                else segment_len - 1)

    def corrupt_state(self, state, segment: int, dispatch: int,
                      segment_len: int):
        """Apply the scheduled corruption after dispatch ``dispatch`` of
        ``segment`` (both loop-local). Returns the (possibly new) state;
        counts a fire only when it actually applied."""
        target = (self.corrupt_dispatch if self.corrupt_dispatch >= 0
                  else segment_len - 1)
        if not self.wants_corruption(segment) or dispatch != target:
            return state
        self._corrupt_fires += 1
        if self.corrupt_kind == "events":
            core = state.core if hasattr(state, "core") else state
            ev = core.events.at[0].set(-1)   # counters are born >= 0
            core = core.replace(events=ev)
            return (state.replace(core=core) if hasattr(state, "core")
                    else core)
        if self.corrupt_kind == "topo":
            # a bad mutation: re-aim one present edge's reverse pointer
            # at its flat neighbor — the plane stops being a self-inverse
            # permutation, which is exactly what the edge-involution-wf
            # invariant (oracle/invariants.py) exists to trip, and what a
            # buggy host-side MutationSchedule would silently produce
            core = state.core if hasattr(state, "core") else state
            if getattr(core, "topo", None) is None:
                raise ValueError(
                    "corrupt_kind='topo' needs a dynamic-overlay state "
                    "(state.core.topo is None — build with dynamic_topo)")
            t = core.topo
            pf = t.edge_perm.reshape(-1)
            e = pf.shape[0]
            bad = t.edge_perm.reshape(-1).at[0].set((pf[0] + 1) % e)
            core = core.replace(
                topo=t.replace(edge_perm=bad.reshape(t.edge_perm.shape)))
            return (state.replace(core=core) if hasattr(state, "core")
                    else core)
        return _nan_leaf(state, self.corrupt_leaf)

    @property
    def corrupt_fires(self) -> int:
        return self._corrupt_fires


def _nan_leaf(state, needle: str):
    """Overwrite element 0 of the first floating-point leaf whose
    pytree path contains ``needle`` with NaN."""
    flat, treedef = jax.tree_util.tree_flatten_with_path(state)
    hit = None
    for i, (path, leaf) in enumerate(flat):
        key = jax.tree_util.keystr(path)
        if needle in key and hasattr(leaf, "dtype") \
                and jnp.issubdtype(leaf.dtype, jnp.floating):
            hit = i
            break
    if hit is None:
        raise ValueError(
            f"no floating-point state leaf matches {needle!r}; "
            f"float leaves: "
            f"{[jax.tree_util.keystr(p) for p, l in flat if hasattr(l, 'dtype') and jnp.issubdtype(l.dtype, jnp.floating)]}")
    leaves = [leaf for _, leaf in flat]
    bad = leaves[hit]
    leaves[hit] = bad.at[(0,) * bad.ndim].set(jnp.nan)
    return jax.tree_util.tree_unflatten(treedef, leaves)


# ---------------------------------------------------------------------------
# checkpoint file damage


def truncate_file(path: str, frac: float = 0.5) -> None:
    """Cut a file to ``frac`` of its size — the mid-write / partial-copy
    shape of damage."""
    size = os.path.getsize(path)
    with open(path, "r+b") as f:
        f.truncate(max(1, int(size * frac)))


def flip_bit(path: str, offset: int | None = None, seed: int = 0) -> None:
    """XOR one byte; default offset is a seeded draw from the middle
    half of the file (deterministic per seed)."""
    size = os.path.getsize(path)
    if offset is None:
        rng = np.random.default_rng(seed)
        offset = int(rng.integers(size // 4, max(size // 4 + 1,
                                                 3 * size // 4)))
    with open(path, "r+b") as f:
        f.seek(offset)
        b = f.read(1)
        f.seek(offset)
        f.write(bytes([b[0] ^ 0xFF]))


def corrupt_leaf_member(path: str, leaf_idx: int) -> None:
    """Rewrite ``leaf_<idx>``'s bytes while keeping the envelope's
    committed CRC vector — a VALID zip whose content lies, so the
    round-17 per-leaf CRC (not the container's) must be what catches it
    and names the leaf."""
    with np.load(path) as data:
        members = {k: data[k] for k in data.files}
    name = f"leaf_{leaf_idx}"
    if name not in members:
        raise ValueError(f"{path} has no member {name}")
    arr = np.array(members[name])
    if arr.size == 0:
        raise ValueError(f"{name} is empty — nothing to corrupt")
    flat = arr.reshape(-1).view(np.uint8)
    flat[0] ^= 0xFF
    members[name] = arr
    np.savez_compressed(path, **members)
