"""Supervised service loop (docs/DESIGN.md §17): the always-on face of
the simulator.

  supervisor — the double-buffered segment pipeline over
               ``ensemble.WindowRunner``: async window dispatch,
               segment-boundary health probes + folded invariants,
               rollback-and-replay localization, retry/backoff/
               degradation, heartbeat + incremental HTML report
  store      — rolling checksummed v6 checkpoints: atomic writes,
               keep-last/keep-every retention, manifest with
               corrupted-snapshot fallback
  faults     — harness-level fault injection (SIGKILL crash points incl.
               mid-checkpoint-write, transient dispatch failures, NaN
               state corruption, checkpoint file damage) driving the
               recovery tests and ``make service-smoke``

Entry points: ``scripts/service_smoke.py`` (``make service-smoke``) and
``python -m go_libp2p_pubsub_tpu.serve._child`` (the subprocess cell
the crash-recovery tests SIGKILL and resume).
"""

from .faults import (  # noqa: F401
    KILL_SITES,
    FaultPlan,
    TransientDispatchError,
    corrupt_leaf_member,
    flip_bit,
    truncate_file,
)
from .store import (  # noqa: F401
    MANIFEST_NAME,
    CheckpointStore,
    RetentionPolicy,
)
from .supervisor import (  # noqa: F401
    ServiceConfig,
    ServiceError,
    ServiceHalted,
    ServiceReport,
    Supervisor,
    state_digest,
)
