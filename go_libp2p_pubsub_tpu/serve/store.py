"""Rolling checkpoint store: the supervised loop's durability plane.

One directory holds numbered, checksummed v6 snapshots plus a
``MANIFEST.json`` that is the source of truth for what exists and what
is trustworthy. Every mutation is crash-ordered so a ``kill -9`` at ANY
point leaves a loadable store:

  1. the snapshot is written to a ``.tmp.npz`` sibling and ``os.replace``d
     into place (a crash mid-write leaves only the tmp, which init
     sweeps);
  2. the manifest is rewritten the same way AFTER the snapshot rename
     (a crash between the two leaves an orphan snapshot the manifest
     does not know about — the previous entry is still valid, and the
     orphan is overwritten by the next save at that ordinal).

Retention is the :class:`RetentionPolicy` pair the ISSUE's durability
story names: ``keep_last`` trailing snapshots always survive, and with
``keep_every = m`` every m-th snapshot (by ordinal) is retained
permanently — the cheap long-horizon audit trail. Pruned files are
deleted eagerly.

Reads are defensive end to end: :meth:`CheckpointStore.restore_latest`
walks the manifest newest-first, and a snapshot that fails the round-17
integrity layer (``checkpoint.CheckpointCorrupt`` — truncation, bit
flips, CRC mismatch) or is simply missing is logged, dropped from the
manifest, and replaced by the next-older entry — the corrupted-latest
fallback ``make service-smoke`` gates. A corrupt or missing manifest is
rebuilt by globbing the snapshot files themselves.
"""

from __future__ import annotations

import dataclasses
import glob
import json
import logging
import os
import re
import time

from .. import checkpoint as _ckpt

_log = logging.getLogger(__name__)

MANIFEST_NAME = "MANIFEST.json"
_SNAP_RE = re.compile(r"^ckpt_(\d+)_t(\d+)\.npz$")


def write_json_atomic(path: str, doc: dict) -> None:
    """Crash-ordered JSON write (tmp sibling + ``os.replace``) — the one
    atomic-write discipline shared by the manifest, the heartbeat and
    the incremental report (a reader never sees a torn file)."""
    tmp = path + ".tmp"
    with open(tmp, "w") as f:
        json.dump(doc, f, indent=1)
        f.write("\n")
    os.replace(tmp, path)


@dataclasses.dataclass(frozen=True)
class RetentionPolicy:
    """``keep_last`` trailing snapshots always kept; ``keep_every = m``
    (0 = off) additionally pins every m-th snapshot by ordinal forever.
    ``keep_last=1, keep_every=0`` degenerates to the single-snapshot
    overwrite the pre-round-17 ``api.Network.run`` auto-snapshots did."""

    keep_last: int = 3
    keep_every: int = 0

    def __post_init__(self):
        if self.keep_last < 1:
            raise ValueError(f"keep_last must be >= 1, got {self.keep_last}")
        if self.keep_every < 0:
            raise ValueError(
                f"keep_every must be >= 0, got {self.keep_every}")

    def keeps(self, ordinal: int, last_ordinals) -> bool:
        if ordinal in last_ordinals:
            return True
        return self.keep_every > 0 and ordinal % self.keep_every == 0


class CheckpointStore:
    """Rolling checksummed snapshots + manifest in one directory.

    ``write_hook(stage, path)`` is the fault-injection seam
    (serve/faults.py): called with ``"tmp-written"`` (tmp file complete,
    final not yet in place), ``"renamed"`` (snapshot durable, manifest
    not yet updated) and ``"manifest"`` (fully committed) — the three
    crash windows the SIGKILL recovery tests aim into."""

    def __init__(self, root: str, policy: RetentionPolicy | None = None,
                 *, write_hook=None):
        self.root = str(root)
        self.policy = policy or RetentionPolicy()
        self.write_hook = write_hook
        os.makedirs(self.root, exist_ok=True)
        # a crash mid-save leaves a tmp sibling; it is dead weight
        for tmp in glob.glob(os.path.join(self.root, "*.tmp.npz")):
            try:
                os.unlink(tmp)
            except OSError:  # pragma: no cover — racing cleaner
                pass
        self._entries = self._load_manifest()

    # -- manifest -------------------------------------------------------

    def _manifest_path(self) -> str:
        return os.path.join(self.root, MANIFEST_NAME)

    def _load_manifest(self) -> list:
        try:
            with open(self._manifest_path()) as f:
                doc = json.load(f)
            entries = list(doc.get("entries", []))
            entries.sort(key=lambda e: int(e["ordinal"]))
            return entries
        except FileNotFoundError:
            pass
        except (OSError, ValueError, KeyError, TypeError) as e:
            _log.warning(
                "checkpoint store %s: unreadable manifest (%s) — "
                "rebuilding from snapshot files", self.root, e)
        # no/corrupt manifest: reconstruct from the files themselves
        entries = []
        for path in glob.glob(os.path.join(self.root, "ckpt_*.npz")):
            m = _SNAP_RE.match(os.path.basename(path))
            if m:
                entries.append({"ordinal": int(m.group(1)),
                                "tick": int(m.group(2)),
                                "file": os.path.basename(path)})
        entries.sort(key=lambda e: e["ordinal"])
        return entries

    def _write_manifest(self) -> None:
        write_json_atomic(self._manifest_path(), {
            "schema": 1,
            "policy": dataclasses.asdict(self.policy),
            "entries": self._entries,
        })

    def entries(self) -> list:
        """Manifest entries, oldest first (copies)."""
        return [dict(e) for e in self._entries]

    def latest(self) -> dict | None:
        return dict(self._entries[-1]) if self._entries else None

    def _hook(self, stage: str, path: str) -> None:
        if self.write_hook is not None:
            self.write_hook(stage, path)

    # -- writes ---------------------------------------------------------

    def save(self, state, tick: int, meta: dict | None = None) -> dict:
        """Write one snapshot: atomic file, then retention prune, then
        atomic manifest update. Returns the new manifest entry."""
        ordinal = self._entries[-1]["ordinal"] + 1 if self._entries else 0
        fname = f"ckpt_{ordinal:06d}_t{int(tick):010d}.npz"
        final = os.path.join(self.root, fname)
        tmp = final + ".tmp.npz"
        # uncompressed: snapshot cadence is the hot path of a supervised
        # run and the envelope's CRCs carry integrity without zlib
        _ckpt.save(tmp, state, compress=False)
        self._hook("tmp-written", tmp)
        os.replace(tmp, final)
        self._hook("renamed", final)
        entry = {"ordinal": ordinal, "tick": int(tick), "file": fname,
                 "written_at": time.time()}
        if meta:
            entry["meta"] = dict(meta)
        self._entries.append(entry)
        drop = self._prune_entries()
        self._write_manifest()
        self._hook("manifest", self._manifest_path())
        # unlink pruned files only AFTER the manifest commit: a crash
        # between an earlier unlink and the manifest rewrite would leave
        # the (stale, valid) manifest pointing at deleted files while
        # the newest snapshot is a manifest-orphan — restore_latest
        # would then cold-start despite a perfectly good snapshot on
        # disk. Post-commit, a crash mid-unlink merely leaves orphan
        # files the next prune re-collects.
        for e in drop:
            try:
                os.unlink(os.path.join(self.root, e["file"]))
            except FileNotFoundError:
                pass
        return dict(entry)

    def _prune_entries(self) -> list:
        """Apply retention to the in-memory manifest; returns the
        dropped entries (files NOT yet unlinked — see save())."""
        last = {e["ordinal"] for e in self._entries[-self.policy.keep_last:]}
        keep, drop = [], []
        for e in self._entries:
            (keep if self.policy.keeps(e["ordinal"], last) else drop).append(e)
        self._entries = keep
        return drop

    # -- reads ----------------------------------------------------------

    def restore_latest(self, template):
        """Restore the newest trustworthy snapshot.

        Walks the manifest newest-first; an entry whose file is missing,
        truncated, bit-flipped or CRC-mismatched
        (:class:`checkpoint.CheckpointCorrupt`) is logged and dropped,
        and the previous entry is tried — the supervisor's fallback
        story. Returns ``(state, entry)``, or ``(None, None)`` when no
        loadable snapshot remains. Template-mismatch ValueErrors
        propagate: a wrong template is a caller bug, not file damage."""
        dropped = False
        while self._entries:
            entry = self._entries[-1]
            path = os.path.join(self.root, entry["file"])
            try:
                state = _ckpt.restore(path, template)
                if dropped:
                    self._write_manifest()
                return state, dict(entry)
            except (_ckpt.CheckpointCorrupt, FileNotFoundError) as e:
                _log.warning(
                    "checkpoint store %s: snapshot ordinal %d unusable "
                    "(%s) — falling back to the previous manifest entry",
                    self.root, entry["ordinal"], e)
                self._entries.pop()
                dropped = True
        if dropped:
            self._write_manifest()
        return None, None
