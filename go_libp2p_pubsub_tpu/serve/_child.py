"""The deterministic supervised cell the crash-recovery tests SIGKILL.

``python -m go_libp2p_pubsub_tpu.serve._child --root DIR ...`` builds a
small gossipsub workload (fixed topology / schedule / seeds — every
process with the same arguments sees the identical run) and drives the
supervisor over it. The parent process kills it at a scheduled point
(via the in-process FaultPlan, so the kill lands EXACTLY at the crash
window under test, including mid-checkpoint-write), then re-invokes the
same command line: the resumed run must finish bit-exact vs an
uninterrupted control, witnessed by the ``state_digest`` the child
writes to ``<root>/FINAL.json`` on completion.

Used by tests/test_serve.py and scripts/service_smoke.py; not a user
entry point.
"""

from __future__ import annotations

import argparse
import dataclasses
import json
import os
import sys


def build_cell(n: int, rounds: int, seed: int, loss: float,
               pub_width: int = 2, msg_slots: int = 64):
    """The fixed workload: ring of gossipsub peers under i.i.d. chaos,
    live scoring + event counters (the probes' food), a seeded publish
    schedule. Returns ``(step, make_args, template_fn, net, cfg)``."""
    import jax.numpy as jnp
    import numpy as np

    from go_libp2p_pubsub_tpu import graph
    from go_libp2p_pubsub_tpu.chaos import ChaosConfig
    from go_libp2p_pubsub_tpu.config import (
        GossipSubParams,
        PeerScoreThresholds,
    )
    from go_libp2p_pubsub_tpu.models.gossipsub import (
        GossipSubConfig,
        GossipSubState,
        make_gossipsub_step,
    )
    from go_libp2p_pubsub_tpu.perf.sweep import bench_score_params
    from go_libp2p_pubsub_tpu.state import Net

    # the oracle plane's known-good gossipsub cell (tests/
    # test_invariants.py, scripts/invariant_report.py): per-round
    # heartbeat cadence, bench score params — all 18 properties hold
    topo = graph.random_connect(n, d=4, seed=seed)
    net = Net.build(topo, graph.subscribe_all(n, 1))
    cfg = GossipSubConfig.build(
        GossipSubParams(D=3, Dlo=2, Dhi=4, Dscore=2, Dout=1,
                        history_length=6, history_gossip=4),
        PeerScoreThresholds(), score_enabled=True,
        chaos=ChaosConfig(loss_rate=loss) if loss > 0 else None,
    )
    cfg = dataclasses.replace(cfg, count_events=True)
    sp = bench_score_params("default", 1)[1]
    step = make_gossipsub_step(cfg, net, score_params=sp)

    rng = np.random.default_rng(seed + 1)
    po_all = rng.integers(0, n, size=(rounds, pub_width)).astype(np.int32)
    pt_all = np.zeros((rounds, pub_width), np.int32)
    pv_all = np.ones((rounds, pub_width), bool)

    def make_args(i):
        return (jnp.asarray(po_all[i]), jnp.asarray(pt_all[i]),
                jnp.asarray(pv_all[i]))

    def template_fn():
        return GossipSubState.init(net, msg_slots, cfg, score_params=sp,
                                   seed=seed)

    return step, make_args, template_fn, net, cfg


def build_supervisor(args) -> "object":
    from go_libp2p_pubsub_tpu.oracle import (
        HealthConfig,
        InvariantConfig,
        ScanInvariants,
    )
    from go_libp2p_pubsub_tpu.serve import (
        FaultPlan,
        RetentionPolicy,
        ServiceConfig,
        Supervisor,
    )

    step, make_args, template_fn, net, cfg = build_cell(
        args.n, args.rounds, args.seed, args.loss)
    invariants = None
    if args.invariants:
        invariants = ScanInvariants(
            "gossipsub", net, cfg,
            InvariantConfig(check_every=args.check_every,
                            delivery_window=16),
            batched=False)
    health = None
    if args.probes:
        health = HealthConfig(delivery_floor=args.floor)
    faults = None
    if (args.kill_segment is not None or args.fail_segment is not None
            or args.corrupt_segment is not None):
        faults = FaultPlan(
            kill_segment=args.kill_segment,
            kill_site=args.kill_site,
            fail_dispatches=({args.fail_segment: args.fail_count}
                             if args.fail_segment is not None else {}),
            corrupt_segment=args.corrupt_segment,
            corrupt_dispatch=args.corrupt_dispatch,
            corrupt_leaf=args.corrupt_leaf,
            corrupt_kind=args.corrupt_kind,
            corrupt_max_fires=args.corrupt_max_fires,
        )
    svc = ServiceConfig(
        n_dispatches=args.rounds,
        segment_len=args.segment,
        health=health,
        retention=RetentionPolicy(keep_last=args.keep_last,
                                  keep_every=args.keep_every),
        checkpoint_every_segments=args.checkpoint_every,
        max_retries=args.max_retries,
        backoff_base_s=0.01,
        report_name="service" if args.report else None,
    )
    return Supervisor(step, make_args, template_fn, args.root, svc,
                      invariants=invariants, faults=faults)


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    ap.add_argument("--root", required=True)
    ap.add_argument("--n", type=int, default=48)
    ap.add_argument("--rounds", type=int, default=32)
    ap.add_argument("--segment", type=int, default=8)
    ap.add_argument("--seed", type=int, default=7)
    ap.add_argument("--loss", type=float, default=0.1)
    ap.add_argument("--invariants", action="store_true")
    ap.add_argument("--check-every", type=int, default=4)
    ap.add_argument("--probes", action="store_true")
    ap.add_argument("--floor", type=int, default=0)
    ap.add_argument("--keep-last", type=int, default=3)
    ap.add_argument("--keep-every", type=int, default=0)
    ap.add_argument("--checkpoint-every", type=int, default=1)
    ap.add_argument("--max-retries", type=int, default=3)
    ap.add_argument("--report", action="store_true")
    ap.add_argument("--fresh", action="store_true",
                    help="ignore existing checkpoints (the control run)")
    ap.add_argument("--kill-segment", type=int, default=None)
    ap.add_argument("--kill-site", default="post-segment")
    ap.add_argument("--fail-segment", type=int, default=None)
    ap.add_argument("--fail-count", type=int, default=1)
    ap.add_argument("--corrupt-segment", type=int, default=None)
    ap.add_argument("--corrupt-dispatch", type=int, default=-1)
    ap.add_argument("--corrupt-leaf", default="scores")
    ap.add_argument("--corrupt-kind", default="nan")
    ap.add_argument("--corrupt-max-fires", type=int, default=2)
    args = ap.parse_args(argv)

    import jax

    jax.config.update("jax_platforms", "cpu")
    # the parent decides the PRNG impl (service_smoke pins the gate
    # PRNG so its in-process legs share the children's key shapes)
    impl = os.environ.get("SERVE_CHILD_PRNG")
    if impl:
        jax.config.update("jax_default_prng_impl", impl)
    cache = os.environ.get("SERVE_CHILD_CACHE")
    if cache:
        from go_libp2p_pubsub_tpu.compile_cache import (
            enable_persistent_cache,
        )

        enable_persistent_cache(cache)

    from go_libp2p_pubsub_tpu.serve import ServiceHalted, state_digest

    sup = build_supervisor(args)
    try:
        report = sup.run(fresh=args.fresh)
    except ServiceHalted as e:
        out = {"status": "halted", "error": str(e),
               "bundle": (e.bundle or {}).get("path")}
        with open(os.path.join(args.root, "FINAL.json"), "w") as f:
            json.dump(out, f)
        print(json.dumps(out))
        return 3
    out = {
        "status": "done",
        "digest": state_digest(report.states),
        "segments": report.segments,
        "recoveries": report.recoveries,
        "retries": report.retries,
        "resumed_from": report.resumed_from,
        "degradations": report.degradations,
        "window_compiles": report.window_compiles,
        "checkpoints": [e["ordinal"] for e in report.checkpoints],
        "bundles": [b["path"] for b in report.bundles],
        "first_bad": [b["first_bad_dispatch"] for b in report.bundles],
        "service": report.fingerprint(),
    }
    with open(os.path.join(args.root, "FINAL.json"), "w") as f:
        json.dump(out, f)
    print(json.dumps(out))
    return 0


if __name__ == "__main__":
    sys.exit(main())
