"""Supervised service loop: fault-tolerant always-on simulation.

``ensemble.WindowRunner`` compiles a segment into one XLA dispatch;
this module wraps it in the machinery a *production* multi-hour run
needs (docs/DESIGN.md §17, the ROADMAP's streaming-service-loop item):

  * **pipeline** — a continuous double-buffered segment loop: segment
    k's window is dispatched asynchronously (JAX dispatch returns
    before the program finishes), segment k+1's stacked scan ``xs``
    are assembled host-side WHILE the device runs, and the only host
    sync per segment is the probe/verdict readback at the boundary.
    The segment length is the checkpoint quantum.
  * **durability** — rolling checksummed v6 checkpoints through
    :class:`serve.store.CheckpointStore` (atomic writes, retention,
    manifest): a ``kill -9`` at ANY point — including mid-checkpoint-
    write — resumes bit-exact vs the uninterrupted run, because resume
    replays deterministically from the last committed snapshot.
  * **detection & recovery** — the :mod:`oracle.probes` health probes
    (NaN/Inf sweep, events-monotone, delivery-floor) fold into every
    segment boundary alongside the scan-folded invariant oracle; on a
    violation the supervisor rolls back to the last good checkpoint and
    REPLAYS the segment per-dispatch with ``replay_check_every=1`` to
    localize the first violating dispatch, emits a forensic bundle
    (violation masks, NaN census, telemetry rows), and either retries
    the segment (transient corruption recovers to a bit-exact final
    state) or halts with the bundle once the per-segment recovery
    budget is spent.
  * **degradation & retry** — transient dispatch failures retried with
    exponential backoff + jitter through the injectable dispatch seam
    (serve/faults.py); when the budget is exhausted the loop degrades
    — shrink the segment length, then drop optional observers — before
    stopping. Rounds are never silently dropped.
  * **liveness** — an atomically-rewritten ``HEARTBEAT.json`` plus an
    incremental per-segment report (jsonl + self-contained HTML), so a
    multi-hour run is watchable and restartable from anywhere.

The supervised loop is OBSERVATIONAL: with probes off, invariants off
and no observer, the compiled window is identical to a bare
``WindowRunner`` program (the service-smoke census leg), and a clean
supervised run's final state tree is bit-exact vs the bare window.
"""

from __future__ import annotations

import dataclasses
import json
import logging
import math
import os
import random
import time

import jax
import jax.numpy as jnp
import numpy as np

from .. import checkpoint as _ckpt
from ..ensemble.runner import WindowRunner
from ..oracle import invariants as _oinv
from ..oracle.probes import HealthConfig, make_health_probe
from .faults import TransientDispatchError
from .store import CheckpointStore, RetentionPolicy, write_json_atomic

_log = logging.getLogger(__name__)

try:  # the real-dispatch-failure class worth retrying, when available
    from jax.errors import JaxRuntimeError as _JaxRuntimeError
except Exception:  # pragma: no cover — older jax
    class _JaxRuntimeError(Exception):
        pass


class ServiceError(RuntimeError):
    """Base class for supervised-loop failures."""


class ServiceHalted(ServiceError):
    """The loop stopped without completing: recovery/degradation budget
    exhausted. ``bundle`` is the last forensic bundle (dict with its
    on-disk ``path``) when a health violation caused the halt."""

    def __init__(self, msg: str, bundle: dict | None = None):
        super().__init__(msg)
        self.bundle = bundle


@dataclasses.dataclass(frozen=True)
class ServiceConfig:
    """The supervised run's shape and policies. ``n_dispatches`` is the
    whole run in engine dispatches (rounds = n_dispatches ×
    rounds_per_dispatch); ``segment_len`` is the checkpoint quantum in
    dispatches and must divide ``n_dispatches``."""

    n_dispatches: int
    segment_len: int
    rounds_per_dispatch: int = 1
    health: HealthConfig | None = HealthConfig()
    retention: RetentionPolicy = RetentionPolicy()
    #: checkpoint every k committed segments (1 = every boundary)
    checkpoint_every_segments: int = 1
    max_retries: int = 3
    backoff_base_s: float = 0.05
    backoff_factor: float = 2.0
    backoff_jitter: float = 0.25
    max_recoveries_per_segment: int = 2
    #: localization cadence of the rollback replay (1 = every dispatch)
    replay_check_every: int = 1
    degrade: bool = True
    report_name: str | None = "service"
    #: drain the device EV counters into a host int64 accumulator at
    #: every committed segment boundary and ZERO them on device, so the
    #: i32 counters only ever hold ONE segment's growth no matter how
    #: long the service runs — the range audit's overflow horizons
    #: (RANGE_AUDIT.json: DUPLICATE_MESSAGE wraps i32 within ~4k rounds
    #: at the dense shards) stop bounding service lifetime. The running
    #: totals ride checkpoint meta and restore on resume, so a crash
    #: loses nothing. OFF by default: draining trades the bare-window
    #: bit-exactness contract (zeroed counters) for an unbounded horizon.
    drain_event_counters: bool = False

    def __post_init__(self):
        if self.n_dispatches < 1 or self.segment_len < 1:
            raise ValueError("n_dispatches and segment_len must be >= 1")
        if self.n_dispatches % self.segment_len:
            raise ValueError(
                f"segment_len {self.segment_len} does not divide the "
                f"{self.n_dispatches}-dispatch run")
        if self.checkpoint_every_segments < 1:
            raise ValueError("checkpoint_every_segments must be >= 1")
        if self.drain_event_counters and self.checkpoint_every_segments != 1:
            raise ValueError(
                "drain_event_counters needs checkpoint_every_segments=1 — "
                "a fast-forward through undrained boundaries would double-"
                "count the drained totals")


@dataclasses.dataclass
class ServiceReport:
    """What one :meth:`Supervisor.run` did. ``window_compiles`` maps
    each window shape (segment length) to its jit-cache growth — the
    one-compile-per-window-shape sentinel ``make service-smoke``
    asserts."""

    states: object
    n_dispatches: int
    rounds: int
    segments: int
    segment_rounds: int
    seconds: float
    recoveries: int
    retries: int
    degradations: list
    resumed_from: int | None
    window_compiles: dict
    checkpoints: list
    heartbeat_path: str
    invariant_checks: int
    probes: tuple
    retention: RetentionPolicy
    bundles: list
    #: stacked per-dispatch observe() pytree ([D, ...] leaves) over the
    #: COMMITTED dispatches, or None without an observer (rolled-back
    #: segments' observations are discarded with the segment)
    observations: object = None
    #: [N_EVENTS] np.int64 drained EV totals over the whole run (the
    #: counters a bare run would hold on device, summed on host past the
    #: i32 horizon), or None when ``drain_event_counters`` is off
    ev_totals: object = None

    def fingerprint(self) -> dict:
        """The schema-v3 ``fingerprint["service"]`` block
        (perf/artifacts.py; legacy artifacts read ``SERVICE_OFF``)."""
        from ..perf.artifacts import service_fingerprint

        return service_fingerprint(
            segment_rounds=self.segment_rounds,
            keep_last=self.retention.keep_last,
            keep_every=self.retention.keep_every,
            probes=self.probes,
            recoveries=self.recoveries,
            segments=self.segments,
            resumes=0 if self.resumed_from is None else 1,
        )


def _core_of(st):
    return st.core if hasattr(st, "core") else st


def _with_events(st, ev):
    """The state tree with its EV counter vector replaced (gossip trees
    nest it under .core; bare SimStates hold it directly)."""
    core = _core_of(st).replace(events=ev)
    return st.replace(core=core) if hasattr(st, "core") else core


def state_digest(state) -> str:
    """Order-stable SHA-256 over the keyless state leaves — the
    cross-process bit-exactness witness the crash-recovery tests and
    ``make service-smoke`` compare (PRNG keys hash their key_data, the
    same normalization the checkpoint backend uses)."""
    import hashlib

    h = hashlib.sha256()
    for leaf in jax.tree_util.tree_leaves(state):
        arr = np.asarray(jax.random.key_data(leaf)
                         if _ckpt.is_prng_key(leaf) else leaf)
        h.update(str(arr.dtype).encode())
        h.update(str(arr.shape).encode())
        h.update(np.ascontiguousarray(arr).tobytes())
    return h.hexdigest()


def overflow_horizon_note(total_rounds: int | None = None,
                          repo_root: str | None = None) -> str | None:
    """One-line startup note from the committed range audit
    (``RANGE_AUDIT.json``, analysis/ranges.py §23): the tightest proven
    int32 event-counter horizon and its f32 telemetry-exactness analogue,
    compared against the planned run length when given. Reads the JSON
    artifact directly — no interpreter import, so startup cost is one
    file read — and returns ``None`` when the artifact is absent or
    malformed (a missing audit never blocks serving; ``make range-audit``
    is the gate that enforces its presence in CI, not the service)."""
    root = repo_root or os.path.dirname(os.path.dirname(
        os.path.dirname(os.path.abspath(__file__))))
    try:
        with open(os.path.join(root, "RANGE_AUDIT.json")) as f:
            horizons = json.load(f)["horizons"]
        active = [(name, row) for name, row in horizons["events"].items()
                  if row["i32_horizon_rounds"] is not None]
        if not active:
            return None
        i32_name, i32_row = min(active,
                                key=lambda kv: kv[1]["i32_horizon_rounds"])
        f32_name, f32_row = min(active,
                                key=lambda kv: kv[1]["f32_exact_horizon_rounds"])
    except (OSError, ValueError, KeyError, TypeError):
        return None
    i32_h = int(i32_row["i32_horizon_rounds"])
    f32_h = int(f32_row["f32_exact_horizon_rounds"])
    note = (
        f"range audit horizons: tightest int32 event counter is {i32_name} "
        f"at {i32_h} rounds (per-round delta bound "
        f"{int(i32_row['per_round_delta_hi'])}); f32 telemetry columns stay "
        f"exact to {f32_name} at {f32_h} rounds"
    )
    if total_rounds is not None:
        worst = min(i32_h, f32_h)
        note += (f"; planned {int(total_rounds)} rounds "
                 + ("fits every horizon" if total_rounds <= worst else
                    f"EXCEEDS the {worst}-round horizon — drain counters "
                    "(trace.drain.counter_events) within that window"))
    return note


class Supervisor:
    """Drive a long run as supervised checkpoint-quantum segments.

    * ``step`` — the jitted per-dispatch engine step (donating, the
      ``make_*_step`` contract; lifted ensemble steps work unchanged —
      pass ``batched=True`` so probes/invariants vmap).
    * ``make_args(i)`` — the per-dispatch positional arrays after the
      state (the ``ensemble.run_rounds`` contract).
    * ``template_fn()`` — a FRESH initial state tree (same configs /
      topology / seed every call): the cold-start state AND the
      checkpoint restore template.
    * ``root`` — the service directory: ``checkpoints/`` (store),
      ``HEARTBEAT.json``, ``<report_name>.jsonl/.html``,
      ``forensics/``.
    * ``heartbeat_fn(i)`` — static cadence flags (global dispatch
      index; must be periodic with the period dividing
      ``segment_len``); ``invariants`` an ``oracle.ScanInvariants``
      built for this engine (``check_every`` must divide
      ``segment_len``); ``observe`` a device fn folded per dispatch.
    * ``faults`` — a serve.faults.FaultPlan (tests/smoke only).
    """

    def __init__(self, step, make_args, template_fn, root: str,
                 svc: ServiceConfig, *, heartbeat_fn=None, invariants=None,
                 observe=None, batched: bool = False, faults=None,
                 unroll: int = 1, retryable=None):
        self.step = step
        self.make_args = make_args
        self.template_fn = template_fn
        self.root = str(root)
        self.svc = svc
        self.heartbeat_fn = heartbeat_fn
        self.invariants = invariants
        self.observe = observe
        self.batched = bool(batched)
        self.faults = faults
        self.unroll = int(unroll)
        self._retryable = tuple(retryable) if retryable is not None else (
            TransientDispatchError, _JaxRuntimeError)
        os.makedirs(self.root, exist_ok=True)
        self._cur_segment = -1
        hook = (faults.store_hook(lambda: self._cur_segment)
                if faults is not None else None)
        self.store = CheckpointStore(
            os.path.join(self.root, "checkpoints"), svc.retention,
            write_hook=hook)
        if svc.health is not None:
            self._probe, self._probe_names = make_health_probe(
                svc.health, batched=batched)
        else:
            self._probe, self._probe_names = None, ()
        self._replay_probe = None  # built lazily on first rollback
        if invariants is not None and svc.segment_len % invariants.check_every:
            raise ValueError(
                f"invariant check_every {invariants.check_every} must "
                f"divide segment_len {svc.segment_len}")
        self._seg_len = int(svc.segment_len)
        self._runners: dict = {}
        self._compiles_base: dict = {}
        self._degradations: list = []
        self._bundles: list = []
        self._rows: list | None = None  # report rows (lazy jsonl load)

    # -- window plumbing ------------------------------------------------

    def _runner_for(self, L: int) -> WindowRunner:
        key = (L, self.observe is not None)
        runner = self._runners.get(key)
        if runner is None:
            runner = WindowRunner(
                self.step, L, rounds_per_phase=self.svc.rounds_per_dispatch,
                heartbeat_fn=self.heartbeat_fn, invariants=self.invariants,
                observe=self.observe, unroll=self.unroll)
            self._runners[key] = runner
            self._compiles_base[key] = runner._cache_size()
        return runner

    def window_compiles(self) -> dict:
        """jit-cache growth per window shape since runner creation."""
        out = {}
        for key, runner in self._runners.items():
            before, after = self._compiles_base[key], runner._cache_size()
            out[f"L{key[0]}" + ("+obs" if key[1] else "")] = (
                -1 if before is None or after is None else after - before)
        return out

    def _segment_due(self, start: int, L: int):
        """Global-tick due rows for dispatches [start, start+L) — the
        supervisor owns the schedule, so the per-segment rows carry the
        RUN's ticks, not segment-local ones."""
        spec = self.invariants
        if spec is None:
            return None, ()
        ce = spec.check_every
        rows, ticks = [], []
        for j in range(L):
            if (j + 1) % ce:
                continue
            tick = (start + j + 1) * self.svc.rounds_per_dispatch
            rows.append(np.asarray(
                spec.due_fn(tick) if spec.due_fn is not None
                else _oinv.due_vector(), np.int32))
            ticks.append(tick)
        due = jnp.asarray(np.stack(rows) if rows
                          else np.zeros((0, len(_oinv.due_vector())),
                                        np.int32))
        return due, tuple(ticks)

    def _step_once(self, st, i: int):
        """One per-dispatch engine step at global dispatch ``i`` — the
        rollback replay's unit (bit-identical to the window's body;
        tests/test_window.py pins the parity)."""
        args = tuple(self.make_args(i))
        kw = {}
        if self.heartbeat_fn is not None:
            kw["do_heartbeat"] = bool(self.heartbeat_fn(i))
        return self.step(st, *args, **kw)

    # -- state reconstruction -------------------------------------------

    def _state_at(self, start: int):
        """The state tree at dispatch boundary ``start``: newest usable
        checkpoint at-or-before it, fast-forwarded deterministically
        through the same window programs when the checkpoint cadence is
        sparser than the rollback target."""
        rps = self.svc.rounds_per_dispatch
        st, d0 = None, 0
        entries = self.store.entries()
        while entries:
            e = entries[-1]
            d = int(e.get("meta", {}).get("dispatch", e["tick"] // rps))
            if d > start:
                entries.pop()
                continue
            try:
                st = _ckpt.restore(os.path.join(self.store.root, e["file"]),
                                   self.template_fn())
                d0 = d
                break
            except (_ckpt.CheckpointCorrupt, FileNotFoundError) as err:
                _log.warning("rollback: snapshot ordinal %d unusable (%s)",
                             e["ordinal"], err)
                entries.pop()
        if st is None:
            st, d0 = self.template_fn(), 0
        while d0 < start:
            L = min(self._seg_len, start - d0)
            runner = self._runner_for(L)
            xs = runner.stack_args(self.make_args, d0, d0 + L)
            due, _ = self._segment_due(d0, L)
            st, _ys = runner.dispatch(st, xs, due)
            d0 += L
        return st

    # -- dispatch with retry / degradation -------------------------------

    def _dispatch_retrying(self, seg: int, start: int, L: int, states,
                           xs, due):
        """One segment dispatch through the injectable seam, with
        exponential-backoff retries and the degradation ladder. Returns
        ``(states, ys, retries, degraded)``; ``states is None`` signals
        "shape changed — re-enter the loop" (the caller rebuilds xs)."""
        svc = self.svc
        retries = 0
        while True:
            try:
                if self.faults is not None:
                    self.faults.before_dispatch(seg)
                out, ys = self._runner_for(L).dispatch(states, xs, due)
                return out, ys, retries, False
            except self._retryable as e:
                retries += 1
                if not isinstance(e, TransientDispatchError):
                    # the window may have started: donated buffers are
                    # gone — rebuild the segment-entry state
                    states = self._state_at(start)
                if retries <= svc.max_retries:
                    delay = (svc.backoff_base_s
                             * svc.backoff_factor ** (retries - 1)
                             * (1.0 + svc.backoff_jitter * random.random()))
                    _log.warning(
                        "segment %d dispatch failed (%s) — retry %d/%d "
                        "in %.3fs", seg, e, retries, svc.max_retries, delay)
                    time.sleep(delay)
                    continue
                # budget spent: degrade before giving up — never
                # silently drop rounds
                if svc.degrade and self._try_degrade(L):
                    return states, None, retries, True
                # liveness: a monitor must see THIS death, not a stale
                # 'running' heartbeat (the recovery-budget halt path
                # writes the same status before raising)
                self._heartbeat(start, "halted")
                raise ServiceHalted(
                    f"segment {seg}: dispatch failed {retries} times and "
                    f"the degradation ladder is exhausted: {e}") from e

    def _try_degrade(self, L: int) -> bool:
        """One rung down: first shrink the segment length (halve while
        alignment allows), then drop optional observers. True = a rung
        was taken and the caller should rebuild the segment."""
        period = 1
        if self.heartbeat_fn is not None:
            from ..driver import min_cycle

            period = len(min_cycle(
                self.heartbeat_fn(i) for i in range(self._seg_len)))
        ce = (self.invariants.check_every
              if self.invariants is not None else 1)
        block = math.lcm(period, ce)
        half = self._seg_len // 2
        if half >= block and half % block == 0:
            self._seg_len = half
            self._degradations.append(f"shrink-segment:{half}")
            # the delivery floor is per SEGMENT: a shrunk segment
            # delivers proportionally less, so the boundary probe must
            # scale with it or every healthy degraded segment trips
            health = self.svc.health
            if health is not None and health.delivery_floor > 0:
                scaled = (health.delivery_floor * half
                          // self.svc.segment_len)
                self._probe, self._probe_names = make_health_probe(
                    dataclasses.replace(health, delivery_floor=scaled),
                    batched=self.batched)
            _log.warning("degraded: segment length halved to %d", half)
            return True
        if self.observe is not None:
            self.observe = None
            self._degradations.append("drop-observers")
            _log.warning("degraded: optional observers dropped")
            return True
        return False

    # -- violation handling ----------------------------------------------

    def _rollback_replay(self, seg: int, start: int, L: int, states_bad,
                         probe_fail, window_report):
        """Roll back to the segment-entry state and replay per dispatch
        with ``replay_check_every`` localization, emitting the forensic
        bundle. Returns the bundle dict (with its on-disk path)."""
        svc = self.svc
        rps = svc.rounds_per_dispatch
        spec = self.invariants
        ce = max(1, int(svc.replay_check_every))
        st = self._state_at(start)
        prev_ev = jnp.copy(_core_of(st).events)
        first_bad, replay_fail = None, []
        if self._probe is not None and self._replay_probe is None:
            # the delivery floor is a PER-SEGMENT quantity — applying it
            # to a single dispatch's delta would spuriously trip at the
            # first replayed dispatch and mislocalize; the replay probe
            # zeroes it (non-negativity still rides events-monotone)
            self._replay_probe, _ = make_health_probe(
                dataclasses.replace(svc.health, delivery_floor=0),
                batched=self.batched)
        for j in range(L):
            i = start + j
            st = self._step_once(st, i)
            if self.faults is not None:
                st = self.faults.corrupt_state(st, seg, j, L)
            fails = []
            if self._probe is not None:
                pm = np.asarray(self._replay_probe(st, prev_ev))
                flat = pm.reshape(-1, pm.shape[-1])
                fails += [self._probe_names[k]
                          for k in np.nonzero(~flat.all(axis=0))[0]]
            if spec is not None and (j + 1) % ce == 0:
                tick = (i + 1) * rps
                due = jnp.asarray(np.asarray(
                    spec.due_fn(tick) if spec.due_fn is not None
                    else _oinv.due_vector(), np.int32))
                om = np.asarray(spec.check(st, prev_ev, due))
                flat = om.reshape(-1, om.shape[-1])
                fails += [f"invariant:{spec.names[k]}"
                          for k in np.nonzero(~flat.all(axis=0))[0]]
            if fails:
                first_bad, replay_fail = i, fails
                break
            prev_ev = jnp.copy(_core_of(st).events)
        return self._write_bundle(seg, start, L, first_bad, replay_fail,
                                  probe_fail, window_report, states_bad)

    def _write_bundle(self, seg, start, L, first_bad, replay_fail,
                      probe_fail, window_report, states_bad) -> dict:
        rps = self.svc.rounds_per_dispatch
        # keyed by start dispatch, not segment ordinal: after a
        # segment-shrink degradation several windows share one ordinal,
        # and a second bundle must never overwrite the first's evidence
        bdir = os.path.join(self.root, "forensics", f"d{start:07d}")
        os.makedirs(bdir, exist_ok=True)
        nan_census = {}
        arrays = {}
        flat, _ = jax.tree_util.tree_flatten_with_path(states_bad)
        for path, leaf in flat:
            if hasattr(leaf, "dtype") and jnp.issubdtype(leaf.dtype,
                                                         jnp.floating):
                n_bad = int(np.asarray(
                    jnp.sum(~jnp.isfinite(leaf))))
                if n_bad:
                    nan_census[jax.tree_util.keystr(path)] = n_bad
        core = _core_of(states_bad)
        if hasattr(core, "telem") and core.telem is not None:
            arrays["telemetry_panel"] = np.asarray(core.telem.panel)
        if window_report is not None:
            arrays["invariant_ok"] = np.asarray(window_report.ok)
        doc = {
            "segment": seg,
            "start_dispatch": start,
            "segment_len": L,
            "first_bad_dispatch": first_bad,
            "first_bad_tick": (None if first_bad is None
                               else (first_bad + 1) * rps),
            "replay_failures": replay_fail,
            "window_probe_failures": probe_fail,
            "window_invariants": (window_report.artifact_block()
                                  if window_report is not None else None),
            "nan_census": nan_census,
            "written_at": time.time(),
        }
        write_json_atomic(os.path.join(bdir, "bundle.json"), doc)
        if arrays:
            np.savez_compressed(os.path.join(bdir, "masks.npz"), **arrays)
        doc["path"] = bdir
        self._bundles.append(doc)
        return doc

    # -- liveness ---------------------------------------------------------

    @property
    def heartbeat_path(self) -> str:
        return os.path.join(self.root, "HEARTBEAT.json")

    def _heartbeat(self, dispatch: int, status: str) -> None:
        write_json_atomic(self.heartbeat_path, {
            "status": status,
            "dispatch": int(dispatch),
            "total_dispatches": int(self.svc.n_dispatches),
            "tick": int(dispatch) * self.svc.rounds_per_dispatch,
            "segments_run": self._segments_run,
            "recoveries": self._recoveries,
            "retries": self._retries,
            "degradations": list(self._degradations),
            "pid": os.getpid(),
            "updated_at": time.time(),
        })

    def _report_paths(self):
        if self.svc.report_name is None:
            return None, None
        base = os.path.join(self.root, self.svc.report_name)
        return base + ".jsonl", base + ".html"

    def _report_row(self, row: dict) -> None:
        jsonl, html = self._report_paths()
        if jsonl is None:
            return
        if self._rows is None:
            # one-time load of a previous run's rows (resume); after
            # this the in-memory list is authoritative — re-parsing the
            # whole jsonl per segment would be O(segments²) host work
            # on a million-round run
            self._rows = []
            try:
                with open(jsonl) as f:
                    self._rows = [json.loads(line) for line in f
                                  if line.strip()]
            except (FileNotFoundError, ValueError):
                pass
        with open(jsonl, "a") as f:
            f.write(json.dumps(row) + "\n")
        self._rows.append(row)
        with open(html + ".tmp", "w") as f:
            f.write(_render_report_html(self._rows, self.svc))
        os.replace(html + ".tmp", html)

    # -- the loop ---------------------------------------------------------

    def run(self, *, fresh: bool = False) -> ServiceReport:
        """Run (or resume) the supervised loop to completion."""
        svc = self.svc
        rps = svc.rounds_per_dispatch
        total = svc.n_dispatches
        self._segments_run = 0
        self._recoveries = 0
        self._retries = 0
        t0 = time.perf_counter()
        resumed_from = None
        states, start = self.template_fn(), 0
        ev_totals = (np.zeros_like(np.asarray(_core_of(states).events),
                                   np.int64)
                     if svc.drain_event_counters else None)
        if not fresh:
            st, entry = self.store.restore_latest(self.template_fn())
            if st is not None:
                states = st
                start = int(entry.get("meta", {}).get(
                    "dispatch", entry["tick"] // rps))
                resumed_from = start
                if ev_totals is not None:
                    # drained totals ride checkpoint meta: a checkpoint's
                    # device counters are zeroed AT its boundary, so the
                    # pair (zeroed counters, meta totals) is the full
                    # count — a legacy checkpoint without the key simply
                    # resumes the accumulator from its own counters
                    ev_totals = np.asarray(
                        entry.get("meta", {}).get("ev_totals",
                                                  ev_totals.tolist()),
                        np.int64)
                _log.info("resuming at dispatch %d (tick %d) from %s",
                          start, start * rps, entry["file"])
        prev_events = jnp.copy(_core_of(states).events)
        recov_per_segment: dict = {}
        xs_cache: dict = {}
        inv_checks = 0
        obs_acc: list = []
        self._heartbeat(start, "running")
        note = overflow_horizon_note(total_rounds=total * rps)
        if note:
            _log.info("%s", note)
        while start < total:
            L = min(self._seg_len, total - start)
            seg = start // svc.segment_len
            self._cur_segment = seg
            runner = self._runner_for(L)
            xs = xs_cache.pop(start, None)
            if xs is None:
                xs = runner.stack_args(self.make_args, start, start + L)
            due, ticks = self._segment_due(start, L)
            t_seg = time.perf_counter()
            out, ys, retries, degraded = self._dispatch_retrying(
                seg, start, L, states, xs, due)
            self._retries += retries
            if degraded:
                # shape changed (or observers dropped): rebuild the
                # segment from an intact state on the new ladder rung
                states = out if out is not None else self._state_at(start)
                xs_cache.clear()
                continue
            states = out
            # double-buffer: assemble the NEXT segment's xs while the
            # device is still executing this one (dispatch is async)
            nxt = start + L
            if nxt < total:
                Ln = min(self._seg_len, total - nxt)
                xs_cache[nxt] = runner.stack_args(self.make_args, nxt,
                                                  nxt + Ln)
            # injected silent corruption lands before the probe reads
            if self.faults is not None and self.faults.wants_corruption(seg):
                states = self.faults.corrupt_state(
                    states, seg,
                    self.faults.resolved_dispatch(L), L)
            # the segment's one host sync: probe + verdict readback
            probe_fail = []
            if self._probe is not None:
                pm = np.asarray(self._probe(states, prev_events))
                flat = pm.reshape(-1, pm.shape[-1])
                probe_fail = [self._probe_names[k]
                              for k in np.nonzero(~flat.all(axis=0))[0]]
            window_report = None
            if self.invariants is not None and ys and "ok" in ys:
                window_report = self.invariants.report(ys["ok"],
                                                       ticks=ticks)
            inv_bad = (window_report is not None
                       and not window_report.all_ok)
            if probe_fail or inv_bad:
                self._recoveries += 1
                n = recov_per_segment.get(start, 0) + 1
                recov_per_segment[start] = n
                bundle = self._rollback_replay(
                    seg, start, L, states, probe_fail, window_report)
                _log.warning(
                    "segment %d unhealthy (%s) — rolled back; replay "
                    "localized first violating dispatch %s (bundle %s)",
                    seg, probe_fail or "invariants",
                    bundle["first_bad_dispatch"], bundle["path"])
                if n > svc.max_recoveries_per_segment:
                    self._heartbeat(start, "halted")
                    what = bundle["replay_failures"] or probe_fail
                    raise ServiceHalted(
                        f"segment {seg}: {n} recoveries exceeded the "
                        f"budget ({svc.max_recoveries_per_segment}) — "
                        f"persistent violation ({what}); forensic "
                        f"bundle at {bundle['path']}", bundle)
                states = self._state_at(start)
                prev_events = jnp.copy(_core_of(states).events)
                continue
            if self.faults is not None:
                self.faults.maybe_kill("post-segment", seg)
            # commit
            self._segments_run += 1
            if window_report is not None:
                inv_checks += window_report.n_checks
            if ys and "obs" in ys:
                obs_acc.append(ys["obs"])
            start += L
            if ev_totals is not None:
                # segment-boundary EV drain (the probe/invariant verdict
                # above already validated this segment): the segment's
                # i32 counter growth folds into the host i64 totals and
                # the device counters zero, so no device counter ever
                # holds more than ONE segment's growth — the overflow
                # horizon becomes per-segment, not per-run
                ev_totals += (np.asarray(_core_of(states).events, np.int64)
                              - np.asarray(prev_events, np.int64))
                states = _with_events(
                    states, jnp.zeros_like(_core_of(states).events))
            if (self._segments_run % svc.checkpoint_every_segments == 0
                    or start >= total):
                meta = {"dispatch": start}
                if ev_totals is not None:
                    meta["ev_totals"] = ev_totals.tolist()
                self.store.save(states, tick=start * rps, meta=meta)
            prev_events = jnp.copy(_core_of(states).events)
            dt = time.perf_counter() - t_seg
            self._heartbeat(start, "running")
            self._report_row({
                "segment": seg,
                "dispatch": start,
                "tick": start * rps,
                "seconds": round(dt, 4),
                "rounds_per_sec": round(L * rps / dt, 2) if dt > 0 else 0.0,
                "probes_ok": not probe_fail,
                "invariants_ok": not inv_bad,
                "invariant_checks": (window_report.n_checks
                                     if window_report else 0),
                "retries": retries,
                "recoveries_total": self._recoveries,
            })
        jax.block_until_ready(states)
        self._heartbeat(start, "done")
        observations = None
        if obs_acc:
            observations = jax.tree_util.tree_map(
                lambda *a: np.concatenate([np.asarray(x) for x in a]),
                *obs_acc)
        return ServiceReport(
            states=states,
            n_dispatches=total,
            rounds=total * rps,
            segments=self._segments_run,
            segment_rounds=svc.segment_len * rps,
            seconds=time.perf_counter() - t0,
            recoveries=self._recoveries,
            retries=self._retries,
            degradations=list(self._degradations),
            resumed_from=resumed_from,
            window_compiles=self.window_compiles(),
            checkpoints=self.store.entries(),
            heartbeat_path=self.heartbeat_path,
            invariant_checks=inv_checks,
            probes=self._probe_names,
            retention=svc.retention,
            bundles=list(self._bundles),
            observations=observations,
            ev_totals=ev_totals,
        )


def _render_report_html(rows: list, svc: ServiceConfig) -> str:
    """Minimal self-contained incremental dashboard: per-segment table
    + a rate sparkline + status chips. Rewritten atomically after every
    segment so a browser mid-run always sees a consistent page."""
    import html as _html

    rates = [r.get("rounds_per_sec", 0.0) for r in rows]
    done = rows[-1]["dispatch"] if rows else 0
    total = svc.n_dispatches
    spark = ""
    if rates:
        hi = max(max(rates), 1e-9)
        w, h = 360, 48
        pts = " ".join(
            f"{i * w / max(len(rates) - 1, 1):.1f},"
            f"{h - 4 - (v / hi) * (h - 8):.1f}"
            for i, v in enumerate(rates))
        spark = (f'<svg width="{w}" height="{h}" role="img">'
                 f'<polyline fill="none" stroke="#36f" stroke-width="1.5" '
                 f'points="{pts}"/></svg>')
    trs = "".join(
        "<tr><td>{segment}</td><td>{dispatch}</td><td>{tick}</td>"
        "<td>{rounds_per_sec}</td><td>{p}</td><td>{v}</td>"
        "<td>{retries}</td></tr>".format(
            p="ok" if r.get("probes_ok", True) else "FAIL",
            v="ok" if r.get("invariants_ok", True) else "FAIL",
            **{k: r.get(k, "") for k in
               ("segment", "dispatch", "tick", "rounds_per_sec",
                "retries")})
        for r in rows[-200:])
    return (
        "<!doctype html><html><head><meta charset='utf-8'>"
        "<title>supervised service loop</title>"
        "<style>body{font:13px system-ui;margin:1.5em;color:#222}"
        "table{border-collapse:collapse}td,th{border:1px solid #ccc;"
        "padding:2px 8px;text-align:right}th{background:#f5f5f5}"
        ".big{font-size:1.4em;font-weight:600}</style></head><body>"
        f"<h1>supervised service loop</h1>"
        f"<p class='big'>{done} / {total} dispatches "
        f"({100.0 * done / max(total, 1):.1f}%)</p>"
        f"<p>segment quantum {svc.segment_len} dispatches · "
        f"{_html.escape(str(len(rows)))} segments reported</p>"
        f"{spark}"
        "<table><tr><th>segment</th><th>dispatch</th><th>tick</th>"
        "<th>rounds/s</th><th>probes</th><th>invariants</th>"
        "<th>retries</th></tr>"
        f"{trs}</table></body></html>")
