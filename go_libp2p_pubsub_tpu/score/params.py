"""Traced score-parameter plane — the first analysis-driven lift.

Round 16 (docs/DESIGN.md §16): the score/mesh knobs have always ridden
the jitted steps as *static* constants — `GossipSubConfig` threshold
fields closed over by the step, `TopicParamsArrays` rows baked in as
numpy constants, `PeerScoreParams` scalars read as Python floats — so
every weight change recompiled the engine, which is exactly what blocks
the ROADMAP's configs×sims parameter search (one generation = one
program sweeping many weight sets).

`analysis/lift.py` (the liftability dataflow pass) machine-classifies
every use site of those fields as SHAPE (feeds a shape, a Python
branch, an index bound, a dtype decision — must stay static) or VALUE
(pure traced arithmetic — liftable), committed as ``LIFT_AUDIT.json``.
This module ships the lift the audit justifies: every VALUE-proved
score field becomes a leaf of :class:`ScoreParams`, a flax-struct
pytree the lifted engines take as a TRACED argument — so two builds
differing only in weights/thresholds share ONE compiled program
(the recompile-free A/B sentinel, ``make analyze``'s ``lifted`` guard
row), and a vmapped plane axis sweeps whole weight populations.

What stays static, per the audit:

* ``PeerScoreParams.app_specific_weight`` — SHAPE: a non-zero weight
  gates the P5 cross-peer gather (one halo-permute set on the sharded
  mesh; score/engine.py compute_scores, the phase head's
  ``include_app``). Program structure, census-pinned — the plane
  carries it as static aux (``pytree_node=False``).
* the mesh degree knobs (D/Dlo/Dhi/Dscore/Dout/Dlazy/gossip_factor)
  rode as static until round 20: the masked-width selection contract
  (``ops/select.masked_width_topk`` — rank the full padded axis, clip
  the traced width) removed the last SHAPE site, so they now lift as
  :class:`MeshParams` and join the candidate plane
  (:class:`CandidateParams`) the tune/ search sweeps.
* the phase engine's static weight elision (p3_live/p4_live) — a
  build-time STRUCTURE decision on weight values. The lifted build
  pins the conservative all-planes-live structure instead (a traced
  weight cannot drive build-time elision), so one program is correct
  for every weight set; `LIFT_AUDIT.json` records those sites as
  guarded elisions.

Bit-exactness contract (tests/test_score_lift.py): at matched values a
lifted build's state trees equal the static build's bit for bit on all
four engines — the plane's [T] rows are built by the SAME
`TopicParamsArrays.build` arithmetic, its `gather` is the same masked
row gather, and every consuming op is unchanged (a traced f32 scalar
compares/multiplies exactly like the Python float it replaces).
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
from flax import struct

from ..config import PeerScoreParams, PeerScoreThresholds
from .engine import TopicParamsArrays

#: the [T] per-topic rows the plane carries — one leaf per
#: TopicParamsArrays field, same dtypes (f32 except the two tick
#: fields and the scored mask), single-sourced for build() and gather()
TOPIC_ROW_FIELDS = (
    "scored", "topic_weight", "w1", "quantum_ticks", "cap1",
    "w2", "decay2", "cap2", "w3", "decay3", "cap3", "thr3",
    "window_rounds", "activation_ticks", "w3b", "decay3b", "w4", "decay4",
)

#: scalar PeerScoreParams fields the plane lifts (audit: VALUE /
#: VALUE_GUARDED — pure traced arithmetic in compute/refresh_scores)
PEER_SCALAR_FIELDS = (
    "topic_score_cap", "ip_colocation_factor_weight",
    "behaviour_penalty_weight", "behaviour_penalty_threshold",
    "behaviour_penalty_decay", "decay_to_zero",
)

#: GossipSubConfig threshold fields the plane lifts (audit: VALUE —
#: every use is a traced score compare)
THRESHOLD_FIELDS = (
    "gossip_threshold", "publish_threshold", "graylist_threshold",
    "accept_px_threshold", "opportunistic_graft_threshold",
)

#: TopicParamsArrays row -> source TopicScoreParams field (provenance;
#: `scored` derives from topic-map membership, not a field)
TOPIC_ROW_PROVENANCE = {
    "scored": None,
    "topic_weight": "topic_weight",
    "w1": "time_in_mesh_weight",
    "quantum_ticks": "time_in_mesh_quantum",
    "cap1": "time_in_mesh_cap",
    "w2": "first_message_deliveries_weight",
    "decay2": "first_message_deliveries_decay",
    "cap2": "first_message_deliveries_cap",
    "w3": "mesh_message_deliveries_weight",
    "decay3": "mesh_message_deliveries_decay",
    "cap3": "mesh_message_deliveries_cap",
    "thr3": "mesh_message_deliveries_threshold",
    "window_rounds": "mesh_message_deliveries_window",
    "activation_ticks": "mesh_message_deliveries_activation",
    "w3b": "mesh_failure_penalty_weight",
    "decay3b": "mesh_failure_penalty_decay",
    "w4": "invalid_message_deliveries_weight",
    "decay4": "invalid_message_deliveries_decay",
}

#: audit-namespace names of everything the plane carries traced — the
#: fingerprint["params"] block and scripts/lift_audit.py cross-check
#: this list against LIFT_AUDIT.json's verdicts
LIFTED_FIELD_NAMES = tuple(sorted(
    [f"GossipSubConfig.{f}" for f in THRESHOLD_FIELDS]
    + [f"PeerScoreParams.{f}" for f in PEER_SCALAR_FIELDS]
    + [f"TopicScoreParams.{TOPIC_ROW_PROVENANCE[r]}"
       for r in TOPIC_ROW_FIELDS if TOPIC_ROW_PROVENANCE[r]]
    + ["TopicParamsArrays.scored"]
))


@struct.dataclass
class ScoreParams:
    """The traced score plane: [T] per-topic rows + scalar leaves.

    Quacks as THREE things inside the lifted engines, so no adapter
    objects exist to drift: (a) the threshold source (attributes named
    exactly like GossipSubConfig's threshold fields), (b) the scalar
    params source for compute_scores/refresh_scores (attributes named
    like PeerScoreParams'), (c) via :meth:`gather`, the per-(peer,
    slot) ``tp`` dict TopicParamsArrays.gather produces. The class
    attribute ``lifted`` marks it for the one Python branch that must
    differ (compute_scores' topic-score-cap elision becomes a
    jnp.where — value-identical at matched values)."""

    # [T] per-topic rows (TopicParamsArrays dtypes)
    scored: jax.Array            # [T] bool
    topic_weight: jax.Array      # [T] f32
    w1: jax.Array
    quantum_ticks: jax.Array     # [T] f32 (>=1)
    cap1: jax.Array
    w2: jax.Array
    decay2: jax.Array
    cap2: jax.Array
    w3: jax.Array
    decay3: jax.Array
    cap3: jax.Array
    thr3: jax.Array
    window_rounds: jax.Array     # [T] i32
    activation_ticks: jax.Array  # [T] i32
    w3b: jax.Array
    decay3b: jax.Array
    w4: jax.Array
    decay4: jax.Array
    # PeerScoreParams scalars (f32 0-d)
    topic_score_cap: jax.Array
    ip_colocation_factor_weight: jax.Array
    behaviour_penalty_weight: jax.Array
    behaviour_penalty_threshold: jax.Array
    behaviour_penalty_decay: jax.Array
    decay_to_zero: jax.Array
    # v1.1 thresholds (f32 0-d; GossipSubConfig field names)
    gossip_threshold: jax.Array
    publish_threshold: jax.Array
    graylist_threshold: jax.Array
    accept_px_threshold: jax.Array
    opportunistic_graft_threshold: jax.Array
    # SHAPE fields ride as static aux: the P5 weight gates a cross-peer
    # gather (program structure — LIFT_AUDIT.json declares it SHAPE)
    app_specific_weight: float = struct.field(pytree_node=False, default=0.0)

    lifted = True  # class marker, not a field

    @classmethod
    def build(
        cls,
        score_params: PeerScoreParams,
        thresholds: PeerScoreThresholds | None = None,
        n_topics: int = 1,
        heartbeat_interval: float = 1.0,
    ) -> "ScoreParams":
        """Build the plane from the SAME host structs the static path
        consumes — the [T] rows go through TopicParamsArrays.build, so
        matched-value parity is arithmetic identity, not coincidence.
        ``thresholds=None`` builds the v1.0 all-zero threshold plane
        (what GossipSubConfig.build records without thresholds)."""
        tpa = TopicParamsArrays.build(score_params, n_topics,
                                      heartbeat_interval)
        kw = {name: jnp.asarray(getattr(tpa, name))
              for name in TOPIC_ROW_FIELDS}
        for f in PEER_SCALAR_FIELDS:
            kw[f] = jnp.float32(getattr(score_params, f))
        for f in THRESHOLD_FIELDS:
            kw[f] = jnp.float32(getattr(thresholds, f)
                                if thresholds is not None else 0.0)
        return cls(app_specific_weight=float(
            score_params.app_specific_weight), **kw)

    @classmethod
    def from_config(cls, cfg, score_params: PeerScoreParams,
                    n_topics: int = 1,
                    heartbeat_interval: float = 1.0) -> "ScoreParams":
        """The matched-values constructor: thresholds read back from a
        built GossipSubConfig, so ``step(state, ..., plane)`` with this
        plane reproduces the static build bit for bit. (THRESHOLD_FIELDS
        are the GossipSubConfig field names, so the cfg duck-types as
        build()'s thresholds source.)"""
        return cls.build(score_params, cfg, n_topics, heartbeat_interval)

    def gather(self, my_topics: jax.Array) -> dict:
        """The per-(peer, slot) [N, S] views — the exact
        TopicParamsArrays.gather math over traced rows; slots with no
        topic (-1) come out zeroed/unscored."""
        t = jnp.clip(my_topics, 0)
        live = my_topics >= 0

        def g(a):
            v = jnp.asarray(a)[t]
            return jnp.where(live, v, jnp.asarray(0, v.dtype))

        return {name: g(getattr(self, name)) for name in TOPIC_ROW_FIELDS}


#: GossipSubConfig mesh degree knobs the mesh plane lifts — i32 widths
#: plus the f32 gossip factor. Audit-proved VALUE (round 20: the
#: masked-width selection contract removed the one SHAPE site,
#: ops/select's conditional-expression broadcast).
MESH_INT_FIELDS = ("D", "Dlo", "Dhi", "Dscore", "Dout", "Dlazy")
MESH_FLOAT_FIELDS = ("gossip_factor",)

#: audit-namespace names the mesh plane carries traced —
#: scripts/lift_audit.py cross-checks this against LIFT_AUDIT.json
MESH_LIFTED_FIELD_NAMES = tuple(sorted(
    f"GossipSubConfig.{f}" for f in MESH_INT_FIELDS + MESH_FLOAT_FIELDS
))


@struct.dataclass
class MeshParams:
    """The traced mesh-degree plane (round 20).

    Attribute names match GossipSubConfig's, so inside the engines a
    MeshParams duck-types as the degree-knob source the same way
    ScoreParams duck-types as the threshold source (the ``msh = cfg if
    msh is None else msh`` seam). All widths reach selection kernels
    through ``ops/select.masked_width_*`` with the padded neighbor axis
    as the static ceiling, so program shape never depends on a leaf."""

    D: jax.Array        # i32 0-d
    Dlo: jax.Array
    Dhi: jax.Array
    Dscore: jax.Array
    Dout: jax.Array
    Dlazy: jax.Array
    gossip_factor: jax.Array  # f32 0-d

    lifted = True  # class marker, not a field

    @classmethod
    def from_config(cls, cfg) -> "MeshParams":
        """Matched-values constructor: a step fed this plane reproduces
        the static build bit for bit (a traced i32 width compares and
        subtracts exactly like the Python int it replaces)."""
        kw = {f: jnp.int32(getattr(cfg, f)) for f in MESH_INT_FIELDS}
        for f in MESH_FLOAT_FIELDS:
            kw[f] = jnp.float32(getattr(cfg, f))
        return cls(**kw)


@struct.dataclass
class CandidateParams:
    """One tune/ candidate: the score plane and the mesh plane, stacked
    together as a single pytree so ``ensemble.stack_planes`` sweeps both
    along the plane axis. The lifted engines detect the combined form by
    its ``mesh`` attribute (``getattr(plane, "mesh", None)``) and fall
    back to score-only semantics otherwise, so every pre-round-20 call
    site keeps working unchanged."""

    score: ScoreParams
    mesh: MeshParams

    lifted = True  # class marker, not a field

    @property
    def app_specific_weight(self) -> float:
        # static aux rides on the nested score plane; surface it so
        # ensemble.stack_planes' aux-agreement check sees it
        return self.score.app_specific_weight

    @classmethod
    def from_config(cls, cfg, score_params: PeerScoreParams,
                    n_topics: int = 1,
                    heartbeat_interval: float = 1.0) -> "CandidateParams":
        return cls(
            score=ScoreParams.from_config(cfg, score_params, n_topics,
                                          heartbeat_interval),
            mesh=MeshParams.from_config(cfg),
        )
