"""The GossipSub v1.1 security plane, vectorized: peer-score engine
(score.go / score_params.go), peer gater (peer_gater.go), IWANT-promise
tracking (gossip_tracer.go)."""

from .engine import (  # noqa: F401
    ScoreState,
    TopicParamsArrays,
    compute_scores,
    ip_colocation_surplus_sq,
    on_deliveries,
    on_graft,
    on_prune,
    refresh_scores,
)
from .params import LIFTED_FIELD_NAMES, ScoreParams  # noqa: F401
