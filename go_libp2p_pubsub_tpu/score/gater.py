"""Peer gater — random-early-drop admission control (peer_gater.go).

When the validation pipeline is overloaded (throttled/validated ratio above
threshold, peer_gater.go:320-363), incoming *messages* from a peer are
accepted with probability (1 + deliver) / (1 + weighted total of its
delivery outcomes); control traffic still flows (AcceptControl).

Vector form: per-edge outcome counters [N,K] with per-source-IP sharing
(stats are aggregated over edges whose far end shares an ip-group —
peer_gater.go:133-137 keys stats by source IP) and a per-peer global
validate/throttle pair. One bernoulli draw per edge per round.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
from flax import struct

from ..config import PeerGaterParams
from ..state import Net


@struct.dataclass
class GaterState:
    validate: jax.Array       # [N] f32 — messages entering validation
    throttle: jax.Array       # [N] f32 — throttle events
    last_throttle: jax.Array  # [N] i32 tick (-inf when never)
    deliver: jax.Array        # [N,K] f32 per-edge outcome counters
    duplicate: jax.Array      # [N,K] f32
    ignore: jax.Array         # [N,K] f32
    reject: jax.Array         # [N,K] f32

    @classmethod
    def empty(cls, n: int, k: int) -> "GaterState":
        z = lambda: jnp.zeros((n, k), jnp.float32)
        return cls(
            validate=jnp.zeros((n,), jnp.float32),
            throttle=jnp.zeros((n,), jnp.float32),
            last_throttle=jnp.full((n,), -(2**30), jnp.int32),
            deliver=z(), duplicate=z(), ignore=z(), reject=z(),
        )


def same_source_matrix(net: Net) -> jax.Array:
    """[N,K,K] f32: neighbors k and k' share a source ip-group (static
    topology => precompute once). Used to share outcome stats per source IP
    (peer_gater.go:261-278)."""
    groups = net.peer_gather(net.ip_group)  # [N,K]
    same = (groups[:, :, None] == groups[:, None, :]) & net.nbr_ok[:, None, :] & net.nbr_ok[:, :, None]
    return same.astype(jnp.float32)


def gater_decay(gs: GaterState, params: PeerGaterParams) -> GaterState:
    """Per-decay-interval counter decay (peer_gater.go:219-259)."""
    dtz = params.decay_to_zero

    def dec(x, d):
        y = x * d
        return jnp.where(y < dtz, 0.0, y)

    return gs.replace(
        validate=dec(gs.validate, params.global_decay),
        throttle=dec(gs.throttle, params.global_decay),
        deliver=dec(gs.deliver, params.source_decay),
        duplicate=dec(gs.duplicate, params.source_decay),
        ignore=dec(gs.ignore, params.source_decay),
        reject=dec(gs.reject, params.source_decay),
    )


def gater_accept(
    gs: GaterState,
    net: Net,
    params: PeerGaterParams,
    quiet_ticks: int,
    tick,
    key: jax.Array,
) -> jax.Array:
    """[N,K] bool: True = AcceptAll, False = AcceptControl (drop messages)
    for this round (peer_gater.go:320-363)."""
    # circuit breaker off: quiet period elapsed, no throttle pressure, or
    # ratio below threshold
    calm = (tick - gs.last_throttle) > quiet_ticks
    calm = calm | (gs.throttle == 0.0)
    calm = calm | ((gs.validate != 0.0) & (gs.throttle / jnp.maximum(gs.validate, 1e-9) < params.threshold))

    # per-source shared outcome totals (stats keyed by source ip-group,
    # peer_gater.go:261-278); the [N,K,K] compare is built in-place and
    # fused into the contraction
    groups = net.peer_gather(net.ip_group)  # [N,K]
    same = (
        (groups[:, :, None] == groups[:, None, :])
        & net.nbr_ok[:, None, :]
        & net.nbr_ok[:, :, None]
    ).astype(jnp.float32)

    def share(x):
        return jnp.einsum("nkj,nj->nk", same, x)

    deliver = share(gs.deliver)
    total = (
        deliver
        + params.duplicate_weight * share(gs.duplicate)
        + params.ignore_weight * share(gs.ignore)
        + params.reject_weight * share(gs.reject)
    )
    p = (1.0 + deliver) / (1.0 + total)
    u = jax.random.uniform(key, p.shape)
    accept = (u < p) | (total == 0.0)
    return calm[:, None] | accept


def gater_on_round(
    gs: GaterState,
    n_validated: jax.Array,   # [N] i32 — receipts entering validation
    n_throttled: jax.Array,   # [N] i32 — receipts refused (queue full)
    deliver_inc: jax.Array,   # [N,K] f32 — first deliveries per edge
    duplicate_inc: jax.Array, # [N,K] f32
    reject_inc: jax.Array,    # [N,K] f32 — rejected-message deliveries
    tick,
    ignore_inc: jax.Array | None = None,  # [N,K] f32 — ValidationIgnore
                                          # verdicts (peer_gater.go:427-429)
) -> GaterState:
    """Fold a round's validation outcomes into the counters (the RawTracer
    hooks, peer_gater.go:365-443)."""
    throttled_any = n_throttled > 0
    return gs.replace(
        validate=gs.validate + n_validated.astype(jnp.float32),
        throttle=gs.throttle + n_throttled.astype(jnp.float32),
        last_throttle=jnp.where(throttled_any, tick, gs.last_throttle),
        deliver=gs.deliver + deliver_inc,
        duplicate=gs.duplicate + duplicate_inc,
        reject=gs.reject + reject_inc,
        ignore=gs.ignore if ignore_inc is None else gs.ignore + ignore_inc,
    )
