"""Batched peer-score engine — the v1.1 security plane (score.go:1-1074).

Every peer n scores each of its neighbor slots k; topic-local counters live
at [N, S, K] (S = topic slots, survey topic-slot compression). The weighted
P1..P7 sum (score.go:258-335), the decay pass (refreshScores,
score.go:497-558) and the delivery-attribution updates (score.go:892-974)
are all elementwise/batched-matmul passes — the "embarrassingly parallel
elementwise pass" the survey §2 checklist names.

Time is integer ticks; durations are converted with ticks_for at
TopicParamsArrays build time. The P3 "mesh delivery window" becomes
window_rounds (default 0: only same-round-as-validation duplicates count,
matching the reference's 10ms window vs 1s heartbeat scale — survey §7
hard-part (e)).
"""

from __future__ import annotations

import dataclasses

import jax
import jax.numpy as jnp
import numpy as np
from flax import struct

from ..config import PeerScoreParams, ticks_for
from ..ops import bitset
from ..state import Net


@dataclasses.dataclass(frozen=True)
class TopicParamsArrays:
    """Per-topic score params as dense [T] numpy arrays (row t zeroed when
    topic t is unscored — unscored topics contribute nothing and track no
    counters, score.go:269-273, 881-884)."""

    scored: np.ndarray        # [T] bool
    topic_weight: np.ndarray  # [T] f32
    w1: np.ndarray
    quantum_ticks: np.ndarray  # [T] f32 (>=1)
    cap1: np.ndarray
    w2: np.ndarray
    decay2: np.ndarray
    cap2: np.ndarray
    w3: np.ndarray
    decay3: np.ndarray
    cap3: np.ndarray
    thr3: np.ndarray
    window_rounds: np.ndarray     # [T] i32
    activation_ticks: np.ndarray  # [T] i32
    w3b: np.ndarray
    decay3b: np.ndarray
    w4: np.ndarray
    decay4: np.ndarray

    @classmethod
    def build(cls, params: PeerScoreParams, n_topics: int, heartbeat_interval: float = 1.0):
        def arr(fn, dtype=np.float32):
            out = np.zeros((n_topics,), dtype)
            for t, tp in params.topics.items():
                if 0 <= t < n_topics:
                    out[t] = fn(tp)
            return out

        scored = np.zeros((n_topics,), bool)
        for t in params.topics:
            if 0 <= t < n_topics:
                scored[t] = True
        return cls(
            scored=scored,
            topic_weight=arr(lambda p: p.topic_weight),
            w1=arr(lambda p: p.time_in_mesh_weight),
            quantum_ticks=arr(lambda p: max(1, ticks_for(p.time_in_mesh_quantum, heartbeat_interval))),
            cap1=arr(lambda p: p.time_in_mesh_cap),
            w2=arr(lambda p: p.first_message_deliveries_weight),
            decay2=arr(lambda p: p.first_message_deliveries_decay),
            cap2=arr(lambda p: p.first_message_deliveries_cap),
            w3=arr(lambda p: p.mesh_message_deliveries_weight),
            decay3=arr(lambda p: p.mesh_message_deliveries_decay),
            cap3=arr(lambda p: p.mesh_message_deliveries_cap),
            thr3=arr(lambda p: p.mesh_message_deliveries_threshold),
            window_rounds=arr(
                lambda p: ticks_for(p.mesh_message_deliveries_window, heartbeat_interval) - 1
                if p.mesh_message_deliveries_window >= heartbeat_interval
                else 0,
                np.int32,
            ),
            activation_ticks=arr(
                lambda p: ticks_for(p.mesh_message_deliveries_activation, heartbeat_interval), np.int32
            ),
            w3b=arr(lambda p: p.mesh_failure_penalty_weight),
            decay3b=arr(lambda p: p.mesh_failure_penalty_decay),
            w4=arr(lambda p: p.invalid_message_deliveries_weight),
            decay4=arr(lambda p: p.invalid_message_deliveries_decay),
        )

    def gather(self, my_topics: jax.Array):
        """Gather all per-topic arrays to per-(peer, slot) [N, S] views;
        slots with no topic (-1) come out zeroed/unscored."""
        t = jnp.clip(my_topics, 0)
        live = my_topics >= 0

        def g(a, fill=0):
            v = jnp.asarray(a)[t]
            return jnp.where(live, v, jnp.asarray(fill, v.dtype))

        return {f.name: g(getattr(self, f.name)) for f in dataclasses.fields(self)}


@struct.dataclass
class ScoreState:
    """Counters the score is computed from (peerStats/topicStats,
    score.go:17-62), per (peer, topic-slot, neighbor-slot)."""

    fmd: jax.Array          # [N,S,K] f32 firstMessageDeliveries
    mmd: jax.Array          # [N,S,K] f32 meshMessageDeliveries
    mfp: jax.Array          # [N,S,K] f32 meshFailurePenalty (P3b, sticky)
    imd: jax.Array          # [N,S,K] f32 invalidMessageDeliveries
    graft_tick: jax.Array   # [N,S,K] i32 tick of last graft (-1 = never)
    mesh_time: jax.Array    # [N,S,K] i32 ticks in mesh (updated on refresh)
    mmd_active: jax.Array   # [N,S,K] bool P3 activation latch
    bp: jax.Array           # [N,K]  f32 behaviourPenalty (P7)

    @classmethod
    def empty(cls, n: int, s: int, k: int) -> "ScoreState":
        f = lambda: jnp.zeros((n, s, k), jnp.float32)
        return cls(
            fmd=f(), mmd=f(), mfp=f(), imd=f(),
            graft_tick=jnp.full((n, s, k), -1, jnp.int32),
            mesh_time=jnp.zeros((n, s, k), jnp.int32),
            mmd_active=jnp.zeros((n, s, k), bool),
            bp=jnp.zeros((n, k), jnp.float32),
        )


# ---------------------------------------------------------------------------
# P6: IP colocation


def ip_colocation_surplus_sq(net: Net, threshold: int, whitelist=()) -> jax.Array:
    """[N, K] f32: (peersInIP - threshold)^2 where the count of my connected
    neighbors sharing neighbor k's ip-group exceeds the threshold
    (score.go:337-381). Static for a static topology — precompute once."""
    groups = net.peer_gather(net.ip_group)  # [N,K]
    same = (groups[:, :, None] == groups[:, None, :]) & net.nbr_ok[:, None, :]
    count = jnp.sum(same.astype(jnp.int32), axis=-1)  # [N,K]
    surplus = (count - threshold).astype(jnp.float32)
    p6 = jnp.where(count > threshold, surplus * surplus, 0.0)
    if len(whitelist):
        wl = jnp.isin(groups, jnp.asarray(list(whitelist), dtype=groups.dtype))
        p6 = jnp.where(wl, 0.0, p6)
    return jnp.where(net.nbr_ok, p6, 0.0)


# ---------------------------------------------------------------------------
# the score function (score.go:258-335)


def compute_scores(
    st: ScoreState,
    in_mesh: jax.Array,   # [N,S,K] bool — router mesh membership
    tp: dict,             # gathered TopicParamsArrays ([N,S] views)
    params: PeerScoreParams,
    p6: jax.Array,        # [N,K] precomputed colocation surplus^2
    app_score: jax.Array,  # [N] per-peer P5 value (gathered at nbr)
    net: Net,
    app_gathered: jax.Array | None = None,  # [N,K] pre-gathered P5 plane
) -> jax.Array:
    """[N, K] f32 — peer n's score of neighbor slot k."""
    e = lambda a: a[..., None]  # [N,S] -> [N,S,1] broadcast over K

    # P1: time in mesh (score.go:279-285)
    p1 = jnp.minimum(st.mesh_time.astype(jnp.float32) / e(tp["quantum_ticks"]), e(tp["cap1"]))
    topic = jnp.where(in_mesh, p1 * e(tp["w1"]), 0.0)

    # P2 (score.go:288-289)
    topic = topic + st.fmd * e(tp["w2"])

    # P3: deficit^2 when active and below threshold (score.go:292-298)
    deficit = e(tp["thr3"]) - st.mmd
    p3 = jnp.where(st.mmd_active & (deficit > 0), deficit * deficit, 0.0)
    topic = topic + p3 * e(tp["w3"])

    # P3b + P4 (score.go:302-308)
    topic = topic + st.mfp * e(tp["w3b"])
    topic = topic + st.imd * st.imd * e(tp["w4"])

    score = jnp.sum(topic * e(tp["topic_weight"]), axis=1)  # [N,K]

    # topic score cap (score.go:315-317). The lifted plane (round 16,
    # score/params.py) carries the cap as a TRACED scalar, so the
    # static elision becomes a jnp.where — value-identical at matched
    # values (cap > 0: both paths apply the same minimum; cap == 0:
    # the where selects the unclamped score, exactly what skipping the
    # minimum produced). LIFT_AUDIT.json records this site as the
    # guarded elision it is.
    if getattr(params, "lifted", False):
        score = jnp.where(params.topic_score_cap > 0,
                          jnp.minimum(score, params.topic_score_cap), score)
    elif params.topic_score_cap > 0:
        score = jnp.minimum(score, params.topic_score_cap)

    # P5 (score.go:320-321) — statically elided when the weight is zero
    # everywhere (the same build-time zero-weight elision the phase engine
    # applies to P3/P4 planes: the term multiplies finite app scores by
    # 0.0, so scores are bit-identical and the cross-peer gather — one
    # full halo-permute set on the sharded mesh — never lowers). When
    # live, the phase engine's coalesced wire exchange pre-gathers the
    # plane at its control head (app_score is phase-invariant) and passes
    # it as ``app_gathered`` so the heartbeat tail adds no extra halo.
    if params.app_specific_weight != 0.0:
        app_g = (app_gathered if app_gathered is not None
                 else net.peer_gather(app_score))
        score = score + app_g * params.app_specific_weight

    # P6 (score.go:324-325)
    score = score + p6 * params.ip_colocation_factor_weight

    # P7 (score.go:328-332)
    excess = st.bp - params.behaviour_penalty_threshold
    p7 = jnp.where(excess > 0, excess * excess, 0.0)
    score = score + p7 * params.behaviour_penalty_weight

    return jnp.where(net.nbr_ok, score, 0.0)


# ---------------------------------------------------------------------------
# decay pass (refreshScores, score.go:497-558)


def refresh_scores(st: ScoreState, in_mesh: jax.Array, tick, tp: dict, params: PeerScoreParams) -> ScoreState:
    dtz = params.decay_to_zero
    e = lambda a: a[..., None]

    def dec(x, d):
        y = x * d
        return jnp.where(y < dtz, 0.0, y)

    fmd = dec(st.fmd, e(tp["decay2"]))
    mmd = dec(st.mmd, e(tp["decay3"]))
    mfp = dec(st.mfp, e(tp["decay3b"]))
    imd = dec(st.imd, e(tp["decay4"]))

    # mesh time + P3 activation (score.go:543-549)
    mesh_time = jnp.where(in_mesh, tick - st.graft_tick, st.mesh_time)
    active = st.mmd_active | (in_mesh & (mesh_time > e(tp["activation_ticks"])))

    bp = st.bp * params.behaviour_penalty_decay
    bp = jnp.where(bp < dtz, 0.0, bp)

    return st.replace(fmd=fmd, mmd=mmd, mfp=mfp, imd=imd, mesh_time=mesh_time, mmd_active=active, bp=bp)


# ---------------------------------------------------------------------------
# mesh membership transitions (Graft/Prune tracer hooks, score.go:642-684)


def on_graft(st: ScoreState, graft_mask: jax.Array, tick) -> ScoreState:
    """graft_mask [N,S,K]: newly grafted edges. Resets mesh time and the P3
    activation latch (score.go:642-660)."""
    return st.replace(
        graft_tick=jnp.where(graft_mask, tick, st.graft_tick),
        mesh_time=jnp.where(graft_mask, 0, st.mesh_time),
        mmd_active=jnp.where(graft_mask, False, st.mmd_active),
    )


def clear_edges(st: ScoreState, mask: jax.Array) -> ScoreState:
    """Reset all per-edge score stats where mask [N,K] — the disconnect path
    (score.go:604-637 removePeer): a peer leaving with a *non-negative*
    score has its stats deleted immediately; negative scores are retained so
    disconnect/reconnect can't wash them (the caller computes the mask
    accordingly). Retained stats keep decaying via refresh_scores, which
    matches the reference's decay-to-zero during the retention window."""
    m3 = mask[:, None, :]
    z = lambda a: jnp.where(m3, jnp.zeros_like(a), a)
    return st.replace(
        fmd=z(st.fmd),
        mmd=z(st.mmd),
        mfp=z(st.mfp),
        imd=z(st.imd),
        graft_tick=jnp.where(m3, -1, st.graft_tick),
        mesh_time=jnp.where(m3, 0, st.mesh_time),
        mmd_active=st.mmd_active & ~m3,
        bp=jnp.where(mask, 0.0, st.bp),
    )


def clear_mesh_status(st: ScoreState, mask: jax.Array) -> ScoreState:
    """Clear in-mesh bookkeeping (graft tick, mesh time, P3 activation) on
    every edge in mask [N,K] — the removePeer path's "no longer in any mesh"
    step (score.go:614-625), applied to retained *and* deleted stats alike.
    Without this, a retained (negative-score) peer's mmd_active would stay
    latched while mmd decays, turning the P3 deficit into a permanent
    penalty instead of the one-shot P3b conversion the reference applies."""
    m3 = mask[:, None, :]
    return st.replace(
        graft_tick=jnp.where(m3, -1, st.graft_tick),
        mesh_time=jnp.where(m3, 0, st.mesh_time),
        mmd_active=st.mmd_active & ~m3,
    )


def on_prune(st: ScoreState, prune_mask: jax.Array, tp: dict) -> ScoreState:
    """prune_mask [N,S,K]: edges leaving the mesh. Applies the sticky mesh
    failure penalty when pruned while active and below threshold
    (score.go:662-684)."""
    e = lambda a: a[..., None]
    deficit = e(tp["thr3"]) - st.mmd
    add = jnp.where(prune_mask & st.mmd_active & (deficit > 0), deficit * deficit, 0.0)
    return st.replace(mfp=st.mfp + add)


# ---------------------------------------------------------------------------
# delivery attribution (score.go:892-974), consuming the round's transmit
# tensor


def per_slot_counts(words: jax.Array, slotw: jax.Array) -> jax.Array:
    """[N,K,W] packed words -> [N,S,K] f32 popcounts per topic slot —
    the shared reduction kernel of on_deliveries and the phase engine's
    count-fold path (single-source so the two score paths cannot
    drift)."""
    s_slots = slotw.shape[1]
    return jnp.stack(
        [bitset.popcount(words & slotw[:, s : s + 1, :], axis=-1)
         for s in range(s_slots)], axis=1
    ).astype(jnp.float32)


def slot_topic_words(net: Net, msg_topic: jax.Array) -> jax.Array:
    """[N, S, W] packed: messages belonging to the topic of my slot s.

    For wide topic universes the [N,S]-row gather from the tiny [T,W]
    table lowers to a slow TPU gather (profiled ~0.3-0.6 ms per
    occurrence at N=100k, T=64); the direct per-message topic compare +
    pack is plain fused vector work instead (the [N,S,M] bool never
    materializes — XLA fuses the compare into the pack reduction)."""
    n_topics = net.subscribed.shape[1]
    if n_topics > 8:
        bits = (
            msg_topic[None, None, :] == net.my_topics[:, :, None]
        ) & (msg_topic >= 0)[None, None, :]
        return bitset.pack(bits)
    onehot_t = msg_topic[None, :] == jnp.arange(n_topics, dtype=jnp.int32)[:, None]
    tw = bitset.pack(onehot_t)                      # [T, W]
    stw = tw[jnp.clip(net.my_topics, 0)]            # [N, S, W]
    return jnp.where((net.my_topics >= 0)[:, :, None], stw, jnp.uint32(0))


def on_deliveries(
    st: ScoreState,
    net: Net,
    in_mesh: jax.Array,       # [N,S,K] bool
    tp: dict,
    trans_words: jax.Array,   # [N,K,W] u32 — this round's per-edge receipts
    new_words: jax.Array,     # [N,W] u32 — first receipts this round
    fe_words: jax.Array,      # [N,K,W] u32 — packed first-arrival edge plane
    first_round: jax.Array,   # [N,M] i32 — validation round of each msg
    msg_topic: jax.Array,     # [M] i32
    msg_valid: jax.Array,     # [M] bool
    tick,
    window_rounds_t: jax.Array,  # [T] i32 — per-topic P3 window (tpa.window_rounds)
    pending_words: jax.Array | None = None,   # [N,W] u32 — msgs in the
                                              # async-validation pipeline
    recv_new_words: jax.Array | None = None,  # [N,W] u32 — fresh receipts
    msg_ignored: jax.Array | None = None,  # [M] bool — ValidationIgnore
    slotw: jax.Array | None = None,  # [N,S,W] — caller's slot_topic_words
                                     # for the same (pre-publish) msg table
    mesh_credit_words: jax.Array | None = None,  # [N,K,W] caller-accumulated
                                     # in-window mesh-credit base (phase mode)
) -> ScoreState:
    """Fold one delivery round into the counters.

    * first receipt of a valid msg: firstMessageDeliveries +1 (capped) on the
      first-arrival edge; meshMessageDeliveries +1 (capped) if that edge is
      in the mesh (markFirstMessageDelivery, score.go:912-939)
    * other same-round arrivals count as near-first mesh deliveries
      (DeliverMessage's drec.peers loop, score.go:712-718), and later
      duplicates within the window also count (markDuplicateMessageDelivery,
      score.go:944-974)
    * every arrival of a *rejected* msg: invalidMessageDeliveries +1
      (markInvalidMessageDelivery via RejectMessage/DuplicateMessage,
      score.go:776-782, 811-813). Ignored messages (ValidationIgnore)
      move no counters at all — their senders are explicitly not
      penalized (validation.go:46-52; score.go:768-774 deliveryIgnored)

    Everything is packed-word algebra: per-(peer,slot,edge) counts are
    popcounts of word-AND — no [N,K,M] gathers, casts, or einsums in the
    hot path."""
    n, s_slots = net.my_topics.shape
    m = msg_topic.shape[0]
    t = jnp.clip(msg_topic, 0)

    if slotw is None:
        slotw = slot_topic_words(net, msg_topic)  # [N,S,W]

    _psc = per_slot_counts

    valid_w = bitset.pack(msg_valid)  # [W]

    # -- P2/P3 credit for valid messages ------------------------------------
    # fe ⊆ arrivals, so the packed first-arrival plane restricted to this
    # round's validated cohort is the attribution mask directly (with async
    # validation the physical arrival was rounds ago; credit lands at the
    # verdict, the reference's DeliverMessage timing, score.go:695-719)
    first_arrival = fe_words & new_words[:, None, :] & valid_w[None, None, :]
    fmd_inc = _psc(first_arrival, slotw)
    e = lambda a: a[..., None]
    fmd = jnp.minimum(st.fmd + fmd_inc, e(tp["cap2"]))

    # mesh delivery credit: first arrivals + near-first (same round) + later
    # duplicates within the window; only on mesh edges, only valid msgs.
    # The window gate requires a set first_round (a message still awaiting
    # its verdict has first_round = -1, which must not pass the compare).
    if mesh_credit_words is not None:
        # phase mode (gossipsub_phase.py): the caller evaluated the window
        # gate per sub-round against each arrival's own tick and OR-folded
        # the result (exact — every (edge,msg) pair transmits at most once,
        # so the fold loses no multiplicity); the pending-duplicate credit
        # is likewise folded in per sub-round. Only the valid mask and the
        # verdict-time first-arrival credit apply at phase end.
        mesh_credit = (
            (mesh_credit_words & valid_w[None, None, :]) | first_arrival
        )
    else:
        msg_window = window_rounds_t[t]  # [M]
        within_w = bitset.pack(
            (first_round >= 0) & ((tick - first_round) <= msg_window[None, :])
        )  # [N,W]
        mesh_credit = trans_words & valid_w[None, None, :] & within_w[:, None, :]
    if mesh_credit_words is None and pending_words is not None:
        # async pipeline (DeliverMessage's drec.peers loop, score.go:712-718):
        #  * the first-arrival edge earns its mesh credit at the verdict —
        #    its physical transmission happened rounds ago, so trans can't
        #    supply it;
        #  * duplicates arriving while the message is pending are in the
        #    delivery record and credited unconditionally (credited here at
        #    arrival; the count matches, only the decay instant differs).
        #    The fresh first arrival itself is excluded — it gets credit at
        #    its own verdict via the first branch.
        exclude_first = (
            fe_words & recv_new_words[:, None, :]
            if recv_new_words is not None else jnp.uint32(0)
        )
        pend_dup = (
            trans_words & pending_words[:, None, :] & valid_w[None, None, :]
            & ~exclude_first
        )
        mesh_credit = mesh_credit | pend_dup | first_arrival
    mmd_inc = _psc(mesh_credit, slotw) * in_mesh.astype(jnp.float32)
    mmd = jnp.minimum(st.mmd + mmd_inc, e(tp["cap3"]))

    # -- P4 penalty for rejected messages -----------------------------------
    penalize_w = ~valid_w
    if msg_ignored is not None:
        penalize_w = penalize_w & ~bitset.pack(msg_ignored)
    invalid_arrival = trans_words & penalize_w[None, None, :]
    imd = st.imd + _psc(invalid_arrival, slotw)

    # unscored slots track nothing (getTopicStats, score.go:881-884)
    scored = e(tp["scored"])
    return st.replace(
        fmd=jnp.where(scored, fmd, st.fmd),
        mmd=jnp.where(scored, mmd, st.mmd),
        imd=jnp.where(scored, imd, st.imd),
    )


def apply_delivery_counts(
    st: ScoreState,
    tp: dict,
    fmd_counts: jax.Array,  # [N,S,K] f32 — first-delivery credits
    mmd_counts: jax.Array,  # [N,S,K] f32 — in-window mesh-delivery credits
    imd_counts: jax.Array,  # [N,S,K] f32 — invalid-arrival penalties
    in_mesh: jax.Array,     # [N,S,K] bool
) -> ScoreState:
    """Fold pre-reduced delivery counts into the counters — the phase
    engine's count-accumulation path (gossipsub_phase.py): each sub-round
    reduces its transmit tensor to per-(peer, slot, edge) popcounts at
    arrival time (valid/window/first-arrival masks applied there, exactly
    as on_deliveries would), so no [N,K,W] attribution plane survives the
    loop. Caps apply once per fold like on_deliveries applies them once
    per round; with multi-round folds the cap can bind up to r-1 rounds
    late (caps are sized in the hundreds — parity rows cover it)."""
    e = lambda a: a[..., None]
    fmd = jnp.minimum(st.fmd + fmd_counts, e(tp["cap2"]))
    mmd = jnp.minimum(
        st.mmd + mmd_counts * in_mesh.astype(jnp.float32), e(tp["cap3"])
    )
    imd = st.imd + imd_counts
    scored = e(tp["scored"])
    return st.replace(
        fmd=jnp.where(scored, fmd, st.fmd),
        mmd=jnp.where(scored, mmd, st.mmd),
        imd=jnp.where(scored, imd, st.imd),
    )


def add_penalties(st: ScoreState, counts: jax.Array) -> ScoreState:
    """behaviourPenalty += counts [N,K] (AddPenalty, score.go:384-398)."""
    return st.replace(bp=st.bp + counts.astype(jnp.float32))
