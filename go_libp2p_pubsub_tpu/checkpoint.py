"""Checkpoint / resume of simulation state.

The reference has no checkpointing — protocol state is soft and rebuilt
from the network (SURVEY §5). For the TPU simulator, snapshotting the
peer×topic device arrays is cheap and makes long simulations resumable, so
this is deliberate new work with no reference semantics to match.

Two backends:
  * npz — `save`/`restore`: flatten the (flax struct) state pytree to a
    flat list of arrays in one compressed .npz. Restore requires a template
    state with the same structure (build it from the same configs/topology);
    shapes and dtypes are checked leaf by leaf. PRNG key leaves are
    serialized via `jax.random.key_data` and re-wrapped on load, so a
    resumed run continues the exact random stream — continuation equals an
    uninterrupted run (tested).
  * orbax — `save_orbax`/`restore_orbax` for async, sharded, multi-host
    checkpoints of the same pytree (optional; imported lazily).
"""

from __future__ import annotations

import logging
import zlib

import jax
import jax.numpy as jnp
import numpy as np

_log = logging.getLogger(__name__)

# v2: Delivery.first_edge [N,M] i8 replaced by packed fe_words [N,K,W] u32
# v3: MsgTable grew the `ignored` verdict plane (ValidationIgnore)
# v4: GossipSubState grew `congested_in` [N,K] (queue-cap link saturation,
#     read by the host announce-retry model)
# v5: MsgTable optionally carries `wire_block` [M] bool (max-message-size
#     transmit block; present only in states built with wire_block=True —
#     leaf count differs between the two modes, so the restore template
#     must be built with the same setting)
# v6: chaos plane — SimState optionally carries `chaos.ge_bad` [N,K] bool
#     (the Gilbert–Elliott link-fault chain; present only in states built
#     with chaos_ge=True / a ChaosConfig whose needs_state is True, same
#     leaf-count contract as wire_block), and the event-counter vector
#     grew the LINK_DOWN / IWANT_RECOVER chaos counters (13 -> 15
#     entries). i.i.d./scheduled chaos adds NO state: fault masks are
#     functions of (key, tick), both checkpointed since v1, so a restored
#     run resumes the exact fault sequence.
#     Round 13 (adversary plane) rides v6 UNCHANGED: attacker activity is
#     a pure function of static build planes and the checkpointed tick —
#     no new leaves, and a restored attacked run resumes the exact attack
#     stream (tests/test_adversary.py). The event vector grew the
#     ADV_DROP / ADV_IHAVE_LIE / ADV_GRAFT_SPAM counters (15 -> 18); a
#     pre-round-13 snapshot restoring into a new template fails the
#     leaf-SHAPE check with the `.events` path named — the format itself
#     is pytree-generic, so no version bump.
#     Round 17 (service loop) keeps v6 and adds an INTEGRITY layer to
#     the envelope, written backward-compatibly: `__header_len__` (the
#     member count the writer emitted — a truncated member table is
#     detected before any leaf is read), a `__crc32__` vector (one CRC32
#     per leaf, over the raw bytes) and `__header_crc__` (CRC32 of the
#     canonical header string + the crc vector). Readers of snapshots
#     that predate the layer log a "no checksum" note and proceed;
#     corruption now raises the typed CheckpointCorrupt error naming
#     the failing section instead of a raw deserialization traceback
#     (serve/store.py falls back to the previous manifest entry on it).
#     Round 22 (dynamic overlay) rides v6 UNCHANGED: the mutable
#     topology is five new state leaves (`.core.topo.{nbr,nbr_ok,rev,
#     edge_perm,epoch}`, present only on dynamic_topo builds) and the
#     format is pytree-generic, so a mid-storm snapshot restores the
#     mutated graph bit-exactly and the remaining mutation schedule
#     replays from the checkpointed tick (tests/test_dynamics.py,
#     scripts/churn_smoke.py check_resume).
_FORMAT_VERSION = 6


class CheckpointCorrupt(ValueError):
    """A checkpoint file failed an integrity check (truncated container,
    bit-flipped member, CRC mismatch). ``section`` names what failed —
    ``"container"``, ``"header"``, ``"member table"`` or the pytree path
    of the damaged leaf — so the supervisor's fallback (and a human) can
    tell corruption apart from a template mismatch, which stays a plain
    ValueError."""

    def __init__(self, path, section: str, detail: str = ""):
        self.path = str(path)
        self.section = section
        msg = f"corrupt checkpoint {self.path}: {section}"
        if detail:
            msg += f" ({detail})"
        super().__init__(msg)


def _crc(arr) -> int:
    """CRC32 over a numpy array's raw bytes (the unit of the envelope's
    per-leaf integrity vector)."""
    return zlib.crc32(np.ascontiguousarray(arr).tobytes()) & 0xFFFFFFFF


def _header_crc(version: int, n_leaves: int, header_len: int,
                crcs: np.ndarray) -> int:
    canon = f"v{version};n{n_leaves};m{header_len};".encode()
    return zlib.crc32(canon + np.ascontiguousarray(crcs).tobytes()) & 0xFFFFFFFF


def is_prng_key(leaf) -> bool:
    """True for typed PRNG-key array leaves — THE key predicate, shared
    by the checkpoint backend, the ensemble plane's key-leaf handling
    (ensemble/batch.py), and the bit-parity comparisons in tests/gates,
    so all of them agree on what counts as a key."""
    return isinstance(leaf, jax.Array) and jnp.issubdtype(leaf.dtype, jax.dtypes.prng_key)


_is_key = is_prng_key


def save(path: str, state, *, compress: bool = True) -> None:
    """Write the state pytree to an .npz with the round-17 integrity
    layer (per-leaf CRC32 vector + header length + header CRC — see the
    version history above). ``compress=False`` trades disk for write
    throughput (the supervised loop's rolling store uses it — the
    per-leaf CRCs carry the integrity either way)."""
    leaves = jax.tree_util.tree_leaves(state)
    out = {"__version__": np.int64(_FORMAT_VERSION),
           "__n_leaves__": np.int64(len(leaves))}
    crcs = np.zeros(len(leaves), np.uint32)
    for i, leaf in enumerate(leaves):
        if _is_key(leaf):
            out[f"leaf_{i}"] = np.asarray(jax.random.key_data(leaf))
            out[f"leaf_{i}__is_key"] = np.bool_(True)
        else:
            out[f"leaf_{i}"] = np.asarray(leaf)
        crcs[i] = _crc(out[f"leaf_{i}"])
    out["__crc32__"] = crcs
    # member count INCLUDING the three integrity entries themselves
    header_len = len(out) + 2
    out["__header_len__"] = np.int64(header_len)
    out["__header_crc__"] = np.uint32(
        _header_crc(_FORMAT_VERSION, len(leaves), header_len, crcs))
    (np.savez_compressed if compress else np.savez)(path, **out)


def _leaf_paths(template) -> list[str]:
    """Human-readable pytree path per template leaf (keystr form, e.g.
    ``.core.dlv.fe_words``) — mismatch errors name the offending FIELD,
    not just a flat leaf index, so "leaf 7 differs" becomes actionable
    ("you built the template without the validation pipeline")."""
    flat, _ = jax.tree_util.tree_flatten_with_path(template)
    return [jax.tree_util.keystr(path) or "<root>" for path, _ in flat]


def _open_envelope(path: str):
    """np.load with container-level failures mapped to the typed error
    (a missing file stays FileNotFoundError — absence is not damage)."""
    try:
        return np.load(path)
    except FileNotFoundError:
        raise
    except Exception as e:
        raise CheckpointCorrupt(
            path, "container", f"{type(e).__name__}: {e}") from e


def _read_member(data, name: str, path: str, section: str):
    """One npz member, with decompression/CRC failures (a bit-flipped
    or truncated member) mapped to CheckpointCorrupt naming ``section``."""
    try:
        return data[name]
    except KeyError:
        raise CheckpointCorrupt(
            path, "member table", f"missing member {name}") from None
    except Exception as e:
        raise CheckpointCorrupt(
            path, section, f"{type(e).__name__}: {e}") from e


def _validate_header(data, path: str):
    """Shared header validation for :func:`restore` / :func:`verify`.

    Returns ``(version, n_leaves, crcs_or_None)``; ``crcs`` is None for
    snapshots predating the integrity layer (a "no checksum" note is
    logged — they load unverified, backward-compatibly)."""
    if "__version__" not in data.files or "__n_leaves__" not in data.files:
        raise ValueError(f"{path} is not a go_libp2p_pubsub_tpu checkpoint")
    version = int(_read_member(data, "__version__", path, "header"))
    if version != _FORMAT_VERSION:
        if version < _FORMAT_VERSION:
            raise ValueError(
                f"checkpoint format v{version} predates the current "
                f"v{_FORMAT_VERSION} (state leaves changed shape/"
                "meaning — see the version history at the top of "
                "checkpoint.py; v6 grew the event-counter vector with "
                "the chaos-plane counters and added the optional "
                "Gilbert–Elliott generator state); re-create the "
                "checkpoint from source state — no migration path is "
                "provided"
            )
        raise ValueError(
            f"checkpoint format v{version} is newer than this build's "
            f"v{_FORMAT_VERSION}"
        )
    n = int(_read_member(data, "__n_leaves__", path, "header"))
    if "__header_len__" in data.files:
        want = int(_read_member(data, "__header_len__", path, "header"))
        if len(data.files) != want:
            raise CheckpointCorrupt(
                path, "member table",
                f"{len(data.files)} members on disk != {want} written "
                "(truncated container)")
    if "__crc32__" not in data.files:
        _log.info(
            "checkpoint %s predates the integrity layer (no checksum) — "
            "loading unverified", path)
        return version, n, None
    crcs = np.asarray(
        _read_member(data, "__crc32__", path, "header"), np.uint32)
    if crcs.shape != (n,):
        raise CheckpointCorrupt(
            path, "header",
            f"crc vector covers {crcs.shape[0] if crcs.ndim else '?'} "
            f"leaves, header says {n}")
    if "__header_crc__" in data.files:
        want = int(_read_member(data, "__header_crc__", path, "header"))
        hl = int(_read_member(data, "__header_len__", path, "header"))
        if _header_crc(version, n, hl, crcs) != want:
            raise CheckpointCorrupt(path, "header", "header CRC32 mismatch")
    return version, n, crcs


def verify(path: str) -> dict:
    """Template-free integrity pass over a checkpoint envelope: header
    consistency, member-table completeness, and every leaf's CRC32.
    Raises :class:`CheckpointCorrupt` on damage (ValueError when the
    file is not a checkpoint at all); returns a summary dict —
    ``{"version", "n_leaves", "checksummed", "members"}`` — on success.
    The serve/ checkpoint store runs this before trusting a manifest
    entry."""
    fpath = path if str(path).endswith(".npz") else str(path) + ".npz"
    with _open_envelope(fpath) as data:
        version, n, crcs = _validate_header(data, fpath)
        for i in range(n):
            arr = _read_member(data, f"leaf_{i}", fpath, f"leaf_{i}")
            if crcs is not None and _crc(arr) != int(crcs[i]):
                raise CheckpointCorrupt(
                    fpath, f"leaf_{i}", "CRC32 mismatch")
        return {"version": version, "n_leaves": n,
                "checksummed": crcs is not None,
                "members": len(data.files)}


def restore(path: str, template):
    """Rebuild a state pytree from `path` using `template`'s structure.

    The template provides the treedef (and expected shapes/dtypes); its
    array values are ignored. Raises ValueError on any mismatch; the
    message carries the PYTREE PATHS of every mismatching leaf. File
    damage — truncation, bit flips, CRC mismatches — raises the typed
    :class:`CheckpointCorrupt` naming the failing section instead
    (round 17); snapshots predating the integrity layer load with a
    logged "no checksum" note.
    """
    fpath = path if str(path).endswith(".npz") else str(path) + ".npz"
    with _open_envelope(fpath) as data:
        _, n, crcs = _validate_header(data, fpath)
        t_leaves, treedef = jax.tree_util.tree_flatten(template)
        paths = _leaf_paths(template)
        if n != len(t_leaves):
            raise ValueError(
                f"checkpoint has {n} leaves, template has {len(t_leaves)} "
                "(different configs/topology? optional planes — chaos_ge / "
                "wire_block / the validation pipeline — change the leaf "
                f"count); template leaves: {', '.join(paths)}"
            )
        leaves = []
        errors = []
        for i, tmpl in enumerate(t_leaves):
            where = f"{paths[i]} (leaf {i})"
            arr = _read_member(data, f"leaf_{i}", fpath, where)
            if crcs is not None and _crc(arr) != int(crcs[i]):
                raise CheckpointCorrupt(fpath, where, "CRC32 mismatch")
            if f"leaf_{i}__is_key" in data.files:
                if not _is_key(tmpl):
                    errors.append(
                        f"{where}: checkpoint holds a PRNG key, template "
                        "does not"
                    )
                    continue
                want = tuple(np.asarray(jax.random.key_data(tmpl)).shape)
                if tuple(arr.shape) != want:
                    errors.append(
                        f"{where}: key data shape {tuple(arr.shape)} != "
                        f"template {want}"
                    )
                    continue
                leaf = jax.random.wrap_key_data(jnp.asarray(arr))
            else:
                if _is_key(tmpl):
                    errors.append(
                        f"{where}: template expects a PRNG key, checkpoint "
                        "holds a plain array"
                    )
                    continue
                leaf = jnp.asarray(arr)
                if tuple(tmpl.shape) != tuple(leaf.shape):
                    errors.append(
                        f"{where}: shape {tuple(leaf.shape)} != template "
                        f"{tuple(tmpl.shape)}"
                    )
                    continue
                if tmpl.dtype != leaf.dtype:
                    errors.append(
                        f"{where}: dtype {leaf.dtype} != {tmpl.dtype}"
                    )
                    continue
            leaves.append(leaf)
        if errors:
            raise ValueError(
                "checkpoint/template mismatch at "
                f"{len(errors)} leaf path(s): " + "; ".join(errors)
            )
    return jax.tree_util.tree_unflatten(treedef, leaves)


def save_orbax(path: str, state) -> None:
    """Orbax backend (async/sharded-capable); keys are unwrapped the same
    way so the two backends are interchangeable."""
    import orbax.checkpoint as ocp

    def unkey(leaf):
        return jax.random.key_data(leaf) if _is_key(leaf) else leaf

    ckptr = ocp.PyTreeCheckpointer()
    ckptr.save(path, jax.tree.map(unkey, state))


def restore_orbax(path: str, template):
    """Validates against the template exactly like `restore` (leaf count,
    per-leaf shape/dtype) so the backends really are interchangeable."""
    import orbax.checkpoint as ocp

    def unkey(leaf):
        return jax.random.key_data(leaf) if _is_key(leaf) else leaf

    ckptr = ocp.PyTreeCheckpointer()
    raw = ckptr.restore(path, item=jax.tree.map(unkey, template))
    t_leaves, treedef = jax.tree_util.tree_flatten(template)
    paths = _leaf_paths(template)
    r_leaves = jax.tree_util.tree_leaves(raw)
    if len(r_leaves) != len(t_leaves):
        raise ValueError(
            f"checkpoint has {len(r_leaves)} leaves, template has "
            f"{len(t_leaves)} (different configs/topology?); template "
            f"leaves: {', '.join(paths)}"
        )
    out = []
    errors = []
    for i, (tmpl, leaf) in enumerate(zip(t_leaves, r_leaves)):
        leaf = jnp.asarray(leaf)
        want = jax.random.key_data(tmpl) if _is_key(tmpl) else tmpl
        where = f"{paths[i]} (leaf {i})"
        if tuple(want.shape) != tuple(leaf.shape):
            errors.append(
                f"{where}: shape {tuple(leaf.shape)} != template "
                f"{tuple(want.shape)}"
            )
            continue
        if want.dtype != leaf.dtype:
            errors.append(f"{where}: dtype {leaf.dtype} != {want.dtype}")
            continue
        out.append(jax.random.wrap_key_data(leaf) if _is_key(tmpl) else leaf)
    if errors:
        raise ValueError(
            "checkpoint/template mismatch at "
            f"{len(errors)} leaf path(s): " + "; ".join(errors)
        )
    return jax.tree_util.tree_unflatten(treedef, out)
