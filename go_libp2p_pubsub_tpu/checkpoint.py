"""Checkpoint / resume of simulation state.

The reference has no checkpointing — protocol state is soft and rebuilt
from the network (SURVEY §5). For the TPU simulator, snapshotting the
peer×topic device arrays is cheap and makes long simulations resumable, so
this is deliberate new work with no reference semantics to match.

Two backends:
  * npz — `save`/`restore`: flatten the (flax struct) state pytree to a
    flat list of arrays in one compressed .npz. Restore requires a template
    state with the same structure (build it from the same configs/topology);
    shapes and dtypes are checked leaf by leaf. PRNG key leaves are
    serialized via `jax.random.key_data` and re-wrapped on load, so a
    resumed run continues the exact random stream — continuation equals an
    uninterrupted run (tested).
  * orbax — `save_orbax`/`restore_orbax` for async, sharded, multi-host
    checkpoints of the same pytree (optional; imported lazily).
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
import numpy as np

# v2: Delivery.first_edge [N,M] i8 replaced by packed fe_words [N,K,W] u32
# v3: MsgTable grew the `ignored` verdict plane (ValidationIgnore)
# v4: GossipSubState grew `congested_in` [N,K] (queue-cap link saturation,
#     read by the host announce-retry model)
# v5: MsgTable optionally carries `wire_block` [M] bool (max-message-size
#     transmit block; present only in states built with wire_block=True —
#     leaf count differs between the two modes, so the restore template
#     must be built with the same setting)
_FORMAT_VERSION = 5


def _is_key(leaf) -> bool:
    return isinstance(leaf, jax.Array) and jnp.issubdtype(leaf.dtype, jax.dtypes.prng_key)


def save(path: str, state) -> None:
    """Write the state pytree to a compressed .npz."""
    leaves = jax.tree_util.tree_leaves(state)
    out = {"__version__": np.int64(_FORMAT_VERSION),
           "__n_leaves__": np.int64(len(leaves))}
    for i, leaf in enumerate(leaves):
        if _is_key(leaf):
            out[f"leaf_{i}"] = np.asarray(jax.random.key_data(leaf))
            out[f"leaf_{i}__is_key"] = np.bool_(True)
        else:
            out[f"leaf_{i}"] = np.asarray(leaf)
    np.savez_compressed(path, **out)


def restore(path: str, template):
    """Rebuild a state pytree from `path` using `template`'s structure.

    The template provides the treedef (and expected shapes/dtypes); its
    array values are ignored. Raises ValueError on any mismatch.
    """
    with np.load(path if str(path).endswith(".npz") else str(path) + ".npz") as data:
        if "__version__" not in data.files or "__n_leaves__" not in data.files:
            raise ValueError(f"{path} is not a go_libp2p_pubsub_tpu checkpoint")
        version = int(data["__version__"])
        if version != _FORMAT_VERSION:
            if version < _FORMAT_VERSION:
                raise ValueError(
                    f"checkpoint format v{version} predates the current "
                    f"v{_FORMAT_VERSION} (state leaves changed shape/"
                    "meaning — see the version history at the top of "
                    "checkpoint.py); re-create the checkpoint from source "
                    "state — no migration path is provided"
                )
            raise ValueError(
                f"checkpoint format v{version} is newer than this build's "
                f"v{_FORMAT_VERSION}"
            )
        t_leaves, treedef = jax.tree_util.tree_flatten(template)
        n = int(data["__n_leaves__"])
        if n != len(t_leaves):
            raise ValueError(
                f"checkpoint has {n} leaves, template has {len(t_leaves)} "
                "(different configs/topology?)"
            )
        leaves = []
        for i, tmpl in enumerate(t_leaves):
            arr = data[f"leaf_{i}"]
            if f"leaf_{i}__is_key" in data.files:
                if not _is_key(tmpl):
                    raise ValueError(
                        f"leaf {i}: checkpoint holds a PRNG key, template does not"
                    )
                want = tuple(np.asarray(jax.random.key_data(tmpl)).shape)
                if tuple(arr.shape) != want:
                    raise ValueError(
                        f"leaf {i}: key data shape {tuple(arr.shape)} != "
                        f"template {want}"
                    )
                leaf = jax.random.wrap_key_data(jnp.asarray(arr))
            else:
                if _is_key(tmpl):
                    raise ValueError(
                        f"leaf {i}: template expects a PRNG key, checkpoint "
                        "holds a plain array"
                    )
                leaf = jnp.asarray(arr)
                if tuple(tmpl.shape) != tuple(leaf.shape):
                    raise ValueError(
                        f"leaf {i}: shape {tuple(leaf.shape)} != template "
                        f"{tuple(tmpl.shape)}"
                    )
                if tmpl.dtype != leaf.dtype:
                    raise ValueError(f"leaf {i}: dtype {leaf.dtype} != {tmpl.dtype}")
            leaves.append(leaf)
    return jax.tree_util.tree_unflatten(treedef, leaves)


def save_orbax(path: str, state) -> None:
    """Orbax backend (async/sharded-capable); keys are unwrapped the same
    way so the two backends are interchangeable."""
    import orbax.checkpoint as ocp

    def unkey(leaf):
        return jax.random.key_data(leaf) if _is_key(leaf) else leaf

    ckptr = ocp.PyTreeCheckpointer()
    ckptr.save(path, jax.tree.map(unkey, state))


def restore_orbax(path: str, template):
    """Validates against the template exactly like `restore` (leaf count,
    per-leaf shape/dtype) so the backends really are interchangeable."""
    import orbax.checkpoint as ocp

    def unkey(leaf):
        return jax.random.key_data(leaf) if _is_key(leaf) else leaf

    ckptr = ocp.PyTreeCheckpointer()
    raw = ckptr.restore(path, item=jax.tree.map(unkey, template))
    t_leaves, treedef = jax.tree_util.tree_flatten(template)
    r_leaves = jax.tree_util.tree_leaves(raw)
    if len(r_leaves) != len(t_leaves):
        raise ValueError(
            f"checkpoint has {len(r_leaves)} leaves, template has "
            f"{len(t_leaves)} (different configs/topology?)"
        )
    out = []
    for i, (tmpl, leaf) in enumerate(zip(t_leaves, r_leaves)):
        leaf = jnp.asarray(leaf)
        want = jax.random.key_data(tmpl) if _is_key(tmpl) else tmpl
        if tuple(want.shape) != tuple(leaf.shape):
            raise ValueError(
                f"leaf {i}: shape {tuple(leaf.shape)} != template "
                f"{tuple(want.shape)}"
            )
        if want.dtype != leaf.dtype:
            raise ValueError(f"leaf {i}: dtype {leaf.dtype} != {want.dtype}")
        out.append(jax.random.wrap_key_data(leaf) if _is_key(tmpl) else leaf)
    return jax.tree_util.tree_unflatten(treedef, out)
