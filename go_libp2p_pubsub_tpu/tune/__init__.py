"""Ensemble-scale protocol parameter search (round 20, docs/DESIGN.md
§20): one evolutionary generation = ONE scanned configs×sims window.

* :mod:`.space` — the declarative knob space over the mesh degrees,
  score weights/decays/caps and v1.1 thresholds, reparameterized so
  every sampled point decodes to a config ``validate()`` accepts by
  construction.
* :mod:`.fitness` — the evaluation cell: a candidate population rides
  the stacked :class:`score.params.CandidateParams` plane through one
  ``WindowRunner`` dispatch under the sybil-flood adversary; fitness is
  the paired per-sim delivery/latency lift against the defaults
  (candidate 0), invariant violations disqualify, and every candidate
  is priced by the static cost auditor.
* :mod:`.driver` — the (mu, lambda) evolution-strategy loop with an
  optional CMA-style covariance update, resumable from a rolling
  JSON checkpoint.
"""

from .driver import (  # noqa: F401
    ESConfig,
    es_ask,
    es_init,
    es_tell,
    load_es_state,
    save_es_state,
    search,
)
from .fitness import (  # noqa: F401
    EvalResult,
    evaluate,
    make_cell,
    rank_scores,
    sybil_profile,
)
from .space import (  # noqa: F401
    Knob,
    Profile,
    SearchSpace,
    check_space,
    default_space,
)
