"""The declarative search space over the protocol's tunable surface.

Every knob the search moves is a field the liftability audit proves
VALUE-only (LIFT_AUDIT.json): the seven mesh degree knobs ride the
round-20 :class:`score.params.MeshParams` plane, the score weights /
decays / caps and the five v1.1 thresholds ride the round-16
:class:`score.params.ScoreParams` plane — so a whole candidate
population shares ONE compiled program.

Legality by construction: the box constraints do not sample the config
fields directly (independent boxes over D/Dlo/Dhi/Dscore/Dout cannot
express ``Dlo <= D <= Dhi``, ``Dscore <= D``, ``Dout < Dlo``,
``Dout <= D//2``), they sample a REPARAMETERIZATION whose image is
inside the accepted region of ``config.py``'s validators:

* ``Dlo`` is a box; ``D = Dlo + D_extra``; ``Dhi = D + Dhi_extra``
  (extras are non-negative boxes) — the degree chain holds.
* ``Dscore = round(Dscore_frac * D)`` with the fraction in [0, 1] —
  inside ``[0, D]``.
* ``Dout = round(Dout_frac * min(Dlo - 1, D // 2))`` — strictly below
  ``Dlo`` and at most ``D // 2`` (``Dlo >= 2`` keeps the bound >= 0).
* thresholds chain downward: ``gossip <= 0`` is a box,
  ``publish = gossip - publish_extra``, ``graylist = publish -
  graylist_extra`` with non-negative extras.
* weight boxes carry the validators' sign conventions (P2 >= 0,
  P3/P3b/P4/P7 <= 0), decays live strictly inside (0, 1).

``decode`` is still only *claimed* legal — :meth:`SearchSpace.
materialize` routes every candidate through the real
``GossipSubParams.validate()`` / ``PeerScoreParams.validate()`` /
``PeerScoreThresholds.validate()``, and :func:`check_space` (the
``make analyze`` tune leg, scripts/tune_check.py) proves the claim by
materializing every box corner plus a seeded random sweep.
"""

from __future__ import annotations

import dataclasses
import hashlib
import json
import math

import numpy as np

from ..config import (
    GossipSubParams,
    PeerScoreParams,
    PeerScoreThresholds,
    TopicScoreParams,
)

# ---------------------------------------------------------------------------
# knobs


@dataclasses.dataclass(frozen=True)
class Knob:
    """One searched dimension: a closed box ``[lo, hi]`` in decoded
    units (``integer`` rounds to the nearest int). The normalized
    genome the ES moves lives in ``[0, 1]^dim``; knob ``i`` decodes as
    ``lo + x_i * (hi - lo)``."""

    name: str
    lo: float
    hi: float
    integer: bool = False

    def decode(self, x: float):
        v = self.lo + float(np.clip(x, 0.0, 1.0)) * (self.hi - self.lo)
        return int(round(v)) if self.integer else float(v)

    def encode(self, v) -> float:
        if self.hi == self.lo:
            return 0.0
        return float(np.clip((float(v) - self.lo) / (self.hi - self.lo),
                             0.0, 1.0))


#: the default searched surface. Reparameterized names (``D_extra``,
#: ``Dscore_frac``, ``publish_extra``, ...) are decoded by
#: :meth:`SearchSpace.decode` into the real config fields; plain names
#: map one-to-one.
DEFAULT_KNOBS = (
    # --- mesh degrees (MeshParams plane) ---
    Knob("Dlo", 2, 6, integer=True),
    Knob("D_extra", 0, 4, integer=True),        # D = Dlo + D_extra
    Knob("Dhi_extra", 0, 6, integer=True),      # Dhi = D + Dhi_extra
    Knob("Dscore_frac", 0.0, 1.0),              # Dscore = round(f * D)
    Knob("Dout_frac", 0.0, 1.0),  # Dout = round(f * min(Dlo-1, D//2))
    Knob("Dlazy", 0, 12, integer=True),
    Knob("gossip_factor", 0.0, 1.0),
    # --- P2: first message deliveries (ScoreParams w2/decay2/cap2) ---
    Knob("first_message_deliveries_weight", 0.0, 2.0),
    Knob("first_message_deliveries_decay", 0.5, 0.99),
    Knob("first_message_deliveries_cap", 10.0, 200.0),
    # --- P3: mesh delivery deficit (w3/decay3/cap3/thr3) ---
    Knob("mesh_message_deliveries_weight", -4.0, 0.0),
    Knob("mesh_message_deliveries_decay", 0.5, 0.99),
    Knob("mesh_message_deliveries_cap", 5.0, 50.0),
    Knob("mesh_message_deliveries_threshold", 0.1, 5.0),
    # --- P3b: sticky mesh failure penalty (w3b/decay3b) ---
    Knob("mesh_failure_penalty_weight", -4.0, 0.0),
    Knob("mesh_failure_penalty_decay", 0.5, 0.99),
    # --- P4: invalid messages (w4/decay4) ---
    Knob("invalid_message_deliveries_weight", -4.0, 0.0),
    Knob("invalid_message_deliveries_decay", 0.1, 0.9),
    # --- P7: behaviour penalty ---
    Knob("behaviour_penalty_weight", -20.0, 0.0),
    Knob("behaviour_penalty_decay", 0.5, 0.99),
    # --- v1.1 thresholds, chained downward ---
    Knob("gossip_threshold", -8.0, 0.0),
    Knob("publish_extra", 0.0, 8.0),    # publish = gossip - extra
    Knob("graylist_extra", 0.0, 8.0),   # graylist = publish - extra
    Knob("accept_px_threshold", 0.0, 20.0),
    Knob("opportunistic_graft_threshold", 0.0, 5.0),
)


#: decoded-value names produced by the degree reparameterization
_DERIVED = ("D", "Dhi", "Dscore", "Dout", "publish_threshold",
            "graylist_threshold")


@dataclasses.dataclass
class Profile:
    """The static half of a candidate: everything the search does NOT
    move — topology-independent base params, the score profile whose
    un-searched fields candidates inherit, and the build switches.
    The profile's own values ARE candidate 0 (the defaults baseline
    every fitness delta is paired against)."""

    params: GossipSubParams
    tp: TopicScoreParams
    sp: PeerScoreParams
    thresholds: PeerScoreThresholds
    score_enabled: bool = True


class SearchSpace:
    """The knob tuple + the decode/encode/materialize machinery."""

    def __init__(self, knobs=DEFAULT_KNOBS):
        self.knobs = tuple(knobs)
        names = [k.name for k in self.knobs]
        if len(set(names)) != len(names):
            raise ValueError(f"duplicate knob names: {names}")
        self._index = {k.name: i for i, k in enumerate(self.knobs)}

    @property
    def dim(self) -> int:
        return len(self.knobs)

    def fingerprint(self) -> str:
        """Stable hash of the knob definitions — ES checkpoints refuse
        to resume across a changed space."""
        payload = [(k.name, k.lo, k.hi, k.integer) for k in self.knobs]
        return hashlib.sha256(
            json.dumps(payload).encode()).hexdigest()[:16]

    # -- genome <-> decoded values ------------------------------------

    def sample(self, rng: np.random.Generator, n: int) -> np.ndarray:
        """[n, dim] uniform genomes (the ES seeds its own gaussians;
        this is the cold-start / random-search face)."""
        return rng.random((n, self.dim))

    def decode(self, x) -> dict:
        """Genome -> decoded candidate values: every knob's box value
        plus the derived config fields the reparameterization fixes."""
        x = np.asarray(x, float)
        if x.shape != (self.dim,):
            raise ValueError(f"genome shape {x.shape} != ({self.dim},)")
        v = {k.name: k.decode(x[i]) for i, k in enumerate(self.knobs)}
        v["D"] = v["Dlo"] + v.pop("D_extra")
        v["Dhi"] = v["D"] + v.pop("Dhi_extra")
        v["Dscore"] = int(round(v.pop("Dscore_frac") * v["D"]))
        dout_max = min(v["Dlo"] - 1, v["D"] // 2)
        v["Dout"] = int(round(v.pop("Dout_frac") * max(dout_max, 0)))
        v["publish_threshold"] = v["gossip_threshold"] - v.pop(
            "publish_extra")
        v["graylist_threshold"] = v["publish_threshold"] - v.pop(
            "graylist_extra")
        return v

    def encode(self, values: dict) -> np.ndarray:
        """Decoded config values -> genome (the inverse map; clips to
        the boxes). Round-trips exactly on in-box values:
        ``decode(encode(v))`` reproduces every config field — the
        defaults-as-candidate-0 assertion depends on it."""
        v = dict(values)
        v["D_extra"] = v["D"] - v["Dlo"]
        v["Dhi_extra"] = v["Dhi"] - v["D"]
        v["Dscore_frac"] = v["Dscore"] / v["D"] if v["D"] else 0.0
        dout_max = min(v["Dlo"] - 1, v["D"] // 2)
        v["Dout_frac"] = (v["Dout"] / dout_max) if dout_max > 0 else 0.0
        v["publish_extra"] = v["gossip_threshold"] - v["publish_threshold"]
        v["graylist_extra"] = (v["publish_threshold"]
                               - v["graylist_threshold"])
        return np.array([k.encode(v[k.name]) for k in self.knobs], float)

    def base_values(self, profile: Profile) -> dict:
        """The profile's own knob values — candidate 0's decoded dict
        (read from the same structs ``materialize`` writes into)."""
        p, tp, sp, th = (profile.params, profile.tp, profile.sp,
                         profile.thresholds)
        out = {}
        for k in self.knobs:
            name = k.name
            if name in ("D_extra", "Dhi_extra", "Dscore_frac",
                        "Dout_frac", "publish_extra", "graylist_extra"):
                continue
            for src in (p, tp, sp, th):
                if hasattr(src, name):
                    out[name] = getattr(src, name)
                    break
            else:
                raise KeyError(f"knob {name!r} matches no profile field")
        for name in _DERIVED:
            for src in (p, th):
                if hasattr(src, name):
                    out[name] = getattr(src, name)
        return out

    # -- candidate -> validated config structs ------------------------

    def materialize(self, values: dict, profile: Profile):
        """Decoded values -> ``(GossipSubParams, TopicScoreParams,
        PeerScoreParams, PeerScoreThresholds)``, all passed through the
        REAL config validators — the legality claim is enforced here,
        not assumed. Raises ``config.ConfigError`` on an illegal
        candidate (the doctored-space negative tests hit this)."""
        pick = lambda src, names: {n: values[n] for n in names  # noqa: E731
                                   if n in values and hasattr(src, n)}
        params = dataclasses.replace(profile.params, **pick(
            profile.params,
            ("D", "Dlo", "Dhi", "Dscore", "Dout", "Dlazy",
             "gossip_factor")))
        tp = dataclasses.replace(profile.tp, **pick(profile.tp, (
            "first_message_deliveries_weight",
            "first_message_deliveries_decay",
            "first_message_deliveries_cap",
            "mesh_message_deliveries_weight",
            "mesh_message_deliveries_decay",
            "mesh_message_deliveries_cap",
            "mesh_message_deliveries_threshold",
            "mesh_failure_penalty_weight",
            "mesh_failure_penalty_decay",
            "invalid_message_deliveries_weight",
            "invalid_message_deliveries_decay",
            "topic_weight",
        )))
        topics = dict(profile.sp.topics)
        topics[0] = tp
        sp = dataclasses.replace(profile.sp, topics=topics,
                                 **pick(profile.sp, (
                 "behaviour_penalty_weight",
                 "behaviour_penalty_decay",
                 "topic_score_cap",
                 )))
        th = dataclasses.replace(profile.thresholds, **pick(
            profile.thresholds, (
                "gossip_threshold", "publish_threshold",
                "graylist_threshold", "accept_px_threshold",
                "opportunistic_graft_threshold",
            )))
        params.validate()
        sp.validate()     # validates tp through topics={0: tp}
        th.validate()
        return params, tp, sp, th

    def to_plane(self, values: dict, profile: Profile, base_cfg,
                 n_topics: int = 1):
        """Decoded values -> the traced :class:`score.params.
        CandidateParams` plane a lifted step consumes. Built from the
        candidate's own VALIDATED config (via the same
        ``GossipSubConfig.build`` arithmetic the static path uses), so
        matched values reproduce a static build of that candidate bit
        for bit."""
        from ..models.gossipsub import GossipSubConfig
        from ..score.params import CandidateParams

        params, _tp, sp, th = self.materialize(values, profile)
        cfg = GossipSubConfig.build(
            params, th, score_enabled=profile.score_enabled,
            heartbeat_every=base_cfg.heartbeat_every,
            chaos=base_cfg.chaos)
        return CandidateParams.from_config(
            cfg, sp, n_topics=n_topics,
            heartbeat_interval=params.heartbeat_interval)

    # -- invariant envelope -------------------------------------------

    def degree_envelope(self) -> dict:
        """The widest degree bounds any in-space candidate can reach:
        ``Dlo`` at its box minimum, ``Dhi``/``Dout`` at their derived
        maxima — the invariant checker's config must be AT LEAST this
        wide or legal candidates would trip ``mesh-degree-bounds``."""
        lo = {k.name: k.lo for k in self.knobs}
        hi = {k.name: k.hi for k in self.knobs}
        d_max = int(hi["Dlo"] + hi["D_extra"])
        return {
            "Dlo": int(lo["Dlo"]),
            "Dhi": int(d_max + hi["Dhi_extra"]),
            "Dout": int(min(hi["Dlo"] - 1, d_max // 2)),
        }

    def envelope_config(self, cfg):
        """``cfg`` with the degree bounds widened to the space envelope
        — feed this to ``oracle.ScanInvariants`` so the folded checks
        gate PROTOCOL violations, not in-space degree diversity."""
        env = self.degree_envelope()
        return dataclasses.replace(
            cfg, Dlo=min(cfg.Dlo, env["Dlo"]),
            Dhi=max(cfg.Dhi, env["Dhi"]),
            Dout=max(cfg.Dout, env["Dout"]))


def default_space() -> SearchSpace:
    return SearchSpace(DEFAULT_KNOBS)


# ---------------------------------------------------------------------------
# the analyze-leg proof: every box point materializes legally


def _corner_genomes(space: SearchSpace) -> np.ndarray:
    """All-lo / all-hi / mid, plus each knob pinned to its lo and hi
    with the others mid — the box extremes where a bad reparameter-
    ization breaks first (2*dim + 3 genomes, not 2^dim)."""
    mid = np.full(space.dim, 0.5)
    rows = [np.zeros(space.dim), np.ones(space.dim), mid]
    for i in range(space.dim):
        for v in (0.0, 1.0):
            g = mid.copy()
            g[i] = v
            rows.append(g)
    return np.stack(rows)


def check_space(space: SearchSpace, profile: Profile, *,
                n_random: int = 64, seed: int = 0) -> list:
    """Prove the space's legality-by-construction claim against the
    REAL validators: materialize every box corner plus ``n_random``
    seeded uniform genomes; return the failure messages (empty =
    proven). A doctored space (a box reaching outside ``config.py``'s
    accepted region) fails here — the tune leg's negative test."""
    from ..config import ConfigError

    genomes = [_corner_genomes(space)]
    if n_random:
        genomes.append(space.sample(np.random.default_rng(seed),
                                    n_random))
    failures = []
    for x in np.concatenate(genomes):
        try:
            values = space.decode(x)
            space.materialize(values, profile)
        except (ConfigError, ValueError, KeyError) as e:
            failures.append(
                f"genome {np.round(x, 3).tolist()} decodes ILLEGAL: {e}")
            if len(failures) >= 8:
                failures.append("... (further failures suppressed)")
                break
    # the round-trip half of the claim: candidate 0 IS the defaults
    base = space.base_values(profile)
    rt = space.decode(space.encode(base))
    for name, want in base.items():
        got = rt[name]
        same = (got == want if isinstance(want, int)
                else math.isclose(float(got), float(want),
                                  rel_tol=1e-9, abs_tol=1e-9))
        if not same:
            failures.append(
                f"defaults round-trip drift: {name} {want!r} -> {got!r}")
    return failures
