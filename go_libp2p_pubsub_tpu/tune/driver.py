"""The (mu, lambda) evolution-strategy generation loop.

Host-side NumPy only (the device runs simulations, not the optimizer):
a gaussian search distribution over the normalized genome cube
``[0, 1]^dim`` with log-rank recombination weights, a 1/5th-style
step-size adaptation, and an optional CMA-style rank-mu covariance
update (``ESConfig.cma``). Candidate 0 of EVERY generation is the
defaults genome — the pairing baseline fitness.py measures lift
against — so the search can never lose sight of the thing it must
beat; sampled candidates fill rows 1..C-1.

Resumability: the full ES state (mean, sigma, covariance, the NumPy
bit-generator state, generation counter, incumbent) round-trips
through a JSON checkpoint bit-identically — resuming generation k
reproduces the straight-through run's generation k exactly
(tests/test_tune.py pins it). The checkpoint records the space
fingerprint and refuses to resume across a changed knob set.
"""

from __future__ import annotations

import dataclasses
import json
import os

import numpy as np

from .fitness import TuneCell, evaluate
from .space import SearchSpace

ES_SCHEMA = 1


@dataclasses.dataclass
class ESConfig:
    """Loop shape: ``n_candidates`` includes the pinned defaults row
    (lambda = n_candidates - 1 sampled offspring), ``mu`` parents
    recombine (log-rank weighted)."""

    n_candidates: int = 8
    mu: int = 3
    sigma0: float = 0.15
    sigma_min: float = 0.02
    sigma_max: float = 0.5
    cma: bool = False
    #: CMA rank-mu learning rate (only with cma=True)
    c_mu: float = 0.3
    seed: int = 0

    def validate(self) -> None:
        if self.n_candidates < 2:
            raise ValueError("n_candidates must be >= 2 (defaults row "
                             "+ at least one offspring)")
        if not (1 <= self.mu < self.n_candidates):
            raise ValueError(
                f"mu must be in [1, n_candidates), got {self.mu}")


@dataclasses.dataclass
class ESState:
    """Everything the next generation depends on."""

    mean: np.ndarray          # [dim] search-distribution mean
    sigma: float
    cov: np.ndarray | None    # [dim, dim] (cma) or None (isotropic)
    rng: np.random.Generator
    generation: int = 0
    best_score: float = -np.inf
    best_values: dict | None = None
    best_generation: int = -1


def _rank_weights(mu: int) -> np.ndarray:
    w = np.log(mu + 0.5) - np.log(np.arange(1, mu + 1))
    return w / w.sum()


def es_init(space: SearchSpace, escfg: ESConfig,
            base_genome: np.ndarray) -> ESState:
    escfg.validate()
    return ESState(
        mean=np.asarray(base_genome, float).copy(),
        sigma=float(escfg.sigma0),
        cov=np.eye(space.dim) if escfg.cma else None,
        rng=np.random.default_rng(escfg.seed),
    )


def es_ask(es: ESState, space: SearchSpace, escfg: ESConfig,
           base_genome: np.ndarray) -> np.ndarray:
    """[C, dim] genomes: row 0 = the defaults (always re-evaluated —
    it IS the pairing baseline), rows 1.. ~ N(mean, sigma^2 C) clipped
    to the cube."""
    c, d = escfg.n_candidates, space.dim
    z = es.rng.standard_normal((c - 1, d))
    if es.cov is not None:
        # numpy cholesky is deterministic — safe for bit-exact resume
        z = z @ np.linalg.cholesky(
            es.cov + 1e-9 * np.eye(d)).T
    x = np.clip(es.mean[None, :] + es.sigma * z, 0.0, 1.0)
    return np.concatenate([np.asarray(base_genome, float)[None, :], x])


def es_tell(es: ESState, escfg: ESConfig, genomes: np.ndarray,
            scores: np.ndarray, values_list: list) -> None:
    """Rank the generation, recombine the mu best into the new mean,
    adapt sigma (success rule: did the incumbent improve?), update the
    covariance (rank-mu) when armed, and advance the incumbent."""
    scores = np.asarray(scores, float)
    order = np.argsort(-scores, kind="stable")
    parents = order[:escfg.mu]
    finite = np.isfinite(scores[parents])
    if finite.any():
        w = _rank_weights(escfg.mu)[finite]
        w = w / w.sum()
        sel = genomes[parents[finite]]
        old_mean = es.mean
        es.mean = np.clip(w @ sel, 0.0, 1.0)
        if es.cov is not None and es.sigma > 0:
            y = (sel - old_mean[None, :]) / es.sigma
            rank_mu = (w[:, None] * y).T @ y
            es.cov = ((1.0 - escfg.c_mu) * es.cov
                      + escfg.c_mu * rank_mu)
    top = float(scores[order[0]])
    improved = top > es.best_score
    es.sigma = float(np.clip(
        es.sigma * (1.1 if improved else 0.9),
        escfg.sigma_min, escfg.sigma_max))
    if improved:
        es.best_score = top
        es.best_values = dict(values_list[int(order[0])])
        es.best_generation = es.generation
    es.generation += 1


# ---------------------------------------------------------------------------
# checkpoint (JSON, bit-identical resume)


def save_es_state(path: str, es: ESState, space: SearchSpace,
                  escfg: ESConfig) -> None:
    payload = {
        "schema": ES_SCHEMA,
        "space": space.fingerprint(),
        "escfg": dataclasses.asdict(escfg),
        "generation": es.generation,
        "mean": es.mean.tolist(),
        "sigma": es.sigma,
        "cov": None if es.cov is None else es.cov.tolist(),
        "rng": es.rng.bit_generator.state,
        "best_score": (None if not np.isfinite(es.best_score)
                       else es.best_score),
        "best_values": es.best_values,
        "best_generation": es.best_generation,
    }
    tmp = path + ".tmp"
    with open(tmp, "w") as f:
        json.dump(payload, f, sort_keys=True)
    os.replace(tmp, path)   # rolling checkpoint: atomic swap


def load_es_state(path: str, space: SearchSpace) -> tuple:
    """-> (ESState, ESConfig). Refuses a checkpoint from a different
    knob set (resuming into a reshaped genome would be silent
    garbage)."""
    with open(path) as f:
        payload = json.load(f)
    if payload.get("schema") != ES_SCHEMA:
        raise ValueError(
            f"ES checkpoint schema {payload.get('schema')} != "
            f"{ES_SCHEMA}")
    if payload["space"] != space.fingerprint():
        raise ValueError(
            "ES checkpoint was recorded against a different search "
            f"space ({payload['space']} != {space.fingerprint()})")
    escfg = ESConfig(**payload["escfg"])
    rng = np.random.default_rng()
    rng.bit_generator.state = payload["rng"]
    es = ESState(
        mean=np.asarray(payload["mean"], float),
        sigma=float(payload["sigma"]),
        cov=(None if payload["cov"] is None
             else np.asarray(payload["cov"], float)),
        rng=rng,
        generation=int(payload["generation"]),
        best_score=(-np.inf if payload["best_score"] is None
                    else float(payload["best_score"])),
        best_values=payload["best_values"],
        best_generation=int(payload["best_generation"]),
    )
    return es, escfg


# ---------------------------------------------------------------------------
# the search loop


def _round_floats(obj, ndigits: int = 6):
    if isinstance(obj, float):
        return round(obj, ndigits)
    if isinstance(obj, dict):
        return {k: _round_floats(v, ndigits) for k, v in obj.items()}
    if isinstance(obj, (list, tuple)):
        return [_round_floats(v, ndigits) for v in obj]
    return obj


def search(cell: TuneCell, *, generations: int,
           escfg: ESConfig | None = None, cost_weight: float = 0.0,
           checkpoint_path: str | None = None, resume: bool = False,
           log=None) -> dict:
    """Run the generation loop on a built cell: sample -> stack planes
    -> ONE window dispatch -> rank -> adapt, checkpointing the ES
    state after every generation. Returns the machine-readable search
    record (the tune-smoke artifact's body): per-generation rows with
    every candidate's values, fitness, invariant verdict and
    ``fingerprint["cost"]``, plus the incumbent."""
    from ..perf.artifacts import params_fingerprint
    from ..score.params import MESH_LIFTED_FIELD_NAMES
    from ..score.params import LIFTED_FIELD_NAMES as SCORE_FIELDS

    escfg = escfg or ESConfig(n_candidates=cell.n_candidates)
    if escfg.n_candidates != cell.n_candidates:
        raise ValueError(
            f"escfg.n_candidates {escfg.n_candidates} != cell's "
            f"{cell.n_candidates}")
    base_genome = cell.space.encode(cell.base_values)
    if resume and checkpoint_path and os.path.exists(checkpoint_path):
        es, escfg = load_es_state(checkpoint_path, cell.space)
    else:
        es = es_init(cell.space, escfg, base_genome)

    pfp = params_fingerprint(
        True, traced=sorted(SCORE_FIELDS + MESH_LIFTED_FIELD_NAMES))
    gens = []
    while es.generation < generations:
        g = es.generation
        genomes = es_ask(es, cell.space, escfg, base_genome)
        values_list = [cell.space.decode(x) for x in genomes]
        res = evaluate(cell, values_list, cost_weight=cost_weight)
        es_tell(es, escfg, genomes, res.score, values_list)
        if checkpoint_path:
            save_es_state(checkpoint_path, es, cell.space, escfg)
        order = np.argsort(-res.score, kind="stable")
        rows = []
        for rank, ci in enumerate(order):
            ci = int(ci)
            rows.append(_round_floats({
                "rank": rank,
                "candidate": ci,
                "defaults": ci == 0,
                "values": values_list[ci],
                "ok": bool(res.ok[ci]),
                "fitness": (None if not np.isfinite(res.fitness[ci])
                            else float(res.fitness[ci])),
                "score": (None if not np.isfinite(res.score[ci])
                          else float(res.score[ci])),
                "delivery": res.delivery[ci].tolist(),
                "delivery_lift": res.delivery_lift[ci].tolist(),
                "mean_latency": res.mean_latency[ci].tolist(),
                "cost_rel": float(res.cost_rel[ci]),
                "fingerprint": {"cost": res.costs[ci],
                                "params": pfp},
            }))
        grec = {
            "generation": g,
            "compiles": res.compiles,
            "dispatches": res.dispatches,
            "disqualified": int((~res.ok).sum()),
            "sigma": round(es.sigma, 6),
            "best_candidate": int(order[0]),
            "best_score": rows[0]["score"],
            "candidates": rows,
        }
        gens.append(grec)
        if log is not None:
            log(grec)
    return {
        "schema": 1,
        "space": cell.space.fingerprint(),
        "dim": cell.space.dim,
        "escfg": dataclasses.asdict(escfg),
        "cost_weight": cost_weight,
        "cell": {
            "n": int(np.asarray(cell.net.nbr).shape[0]),
            "n_candidates": cell.n_candidates,
            "n_sims": cell.n_sims,
            "rounds": cell.rounds,
            "born": list(cell.born),
            "seed": cell.seed,
            "mean_degree": round(cell.mean_degree, 4),
        },
        "generations": gens,
        "best": _round_floats({
            "score": (None if not np.isfinite(es.best_score)
                      else float(es.best_score)),
            "generation": es.best_generation,
            "values": es.best_values,
        }),
    }
