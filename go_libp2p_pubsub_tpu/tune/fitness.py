"""The evaluation cell: one candidate population = ONE window dispatch.

Layout (the configs×sims sweep, docs/DESIGN.md §20): a generation of
``C`` candidates × ``S`` sims runs as ``R = C*S`` ensemble rows in one
``WindowRunner`` program. Row ``c*S + s`` carries sim ``s``'s folded
PRNG key for EVERY candidate ``c`` — so the chaos fault streams, the
adversary behaviors and the heartbeat sampler draws are IDENTICAL
across candidates at matched sim index (the chaos-smoke pairing
discipline, threefry's elementwise vmap batching), and the per-sim
delivery/latency delta against candidate 0 (the defaults, pinned by
the driver) is the candidate's causal effect. The stacked
:class:`score.params.CandidateParams` plane rides the window's
``consts`` seam (driver.make_window round 16), repeated ``S``× along
the row axis — a new population re-dispatches the SAME compiled
window: one compile per search, zero warm recompiles.

Gating and pricing:

* the folded ``oracle.ScanInvariants`` checker runs under the space's
  ENVELOPE config (widest in-space degree bounds); any violated check
  row hard-disqualifies its candidate (fitness -> -inf);
* every candidate's artifact row carries ``fingerprint["cost"]``: the
  static auditor (analysis/costmodel.cost_of) prices the shared
  program once, and the candidate-dependent wire term scales the
  byte-traffic metrics by the mesh fan-out it actually configures
  (``D + Dlazy + gossip_factor * mean_degree``, the per-edge byte
  model) — ``cost_weight`` trades paired lift against hbm bytes/round.
"""

from __future__ import annotations

import dataclasses

import numpy as np

from ..config import (
    GossipSubParams,
    PeerScoreParams,
    PeerScoreThresholds,
    TopicScoreParams,
)
from .space import Profile, SearchSpace

#: smoke-shape defaults — the scripts/attack_report.py sybil-flood
#: cell shrunk to generation cadence (n=64 keeps a C=8 x S=4 window
#: in the tens of seconds warm on CPU)
TUNE_N = 64
TUNE_DEGREE = 4
TUNE_ROUNDS = 48
TUNE_ONSET = 10
TUNE_FRACTION = 0.2
TUNE_LOSS = 0.10
TUNE_BORN = (TUNE_ONSET + 4, TUNE_ONSET + 24)
TUNE_MSG_SLOTS = 128
#: latency histogram depth (rounds); also the latency-lift normalizer
MAX_LAT = 16
#: latency weight inside the scalar fitness (delivery lift dominates)
LAT_WEIGHT = 0.25


def sybil_profile() -> Profile:
    """The searched baseline: scripts/attack_report.py's sybil-flood
    plane — the low-degree v1.1 overlay plus the attack score profile
    (every attacker-catching term live). The profile's own values run
    as candidate 0, so 'beat the defaults on the sybil cell' is the
    headline fitness reads directly."""
    tp = TopicScoreParams(
        topic_weight=1.0,
        time_in_mesh_weight=0.0,
        first_message_deliveries_weight=0.5,
        first_message_deliveries_cap=50.0,
        first_message_deliveries_decay=0.9,
        mesh_message_deliveries_weight=-1.0,
        mesh_message_deliveries_decay=0.9,
        mesh_message_deliveries_cap=20.0,
        mesh_message_deliveries_threshold=0.5,
        mesh_message_deliveries_window=2.0,
        mesh_message_deliveries_activation=8.0,
        mesh_failure_penalty_weight=-1.0,
        mesh_failure_penalty_decay=0.9,
    )
    sp = PeerScoreParams(
        topics={0: tp},
        skip_app_specific=True,
        behaviour_penalty_weight=-10.0,
        behaviour_penalty_threshold=0.0,
        behaviour_penalty_decay=0.9,
        ip_colocation_factor_weight=0.0,
    )
    th = PeerScoreThresholds(
        gossip_threshold=-2.0,
        publish_threshold=-4.0,
        graylist_threshold=-8.0,
        accept_px_threshold=10.0,
        opportunistic_graft_threshold=1.0,
    )
    params = GossipSubParams(D=3, Dlo=2, Dhi=4, Dscore=2, Dout=1,
                             history_length=6, history_gossip=4)
    return Profile(params=params, tp=tp, sp=sp, thresholds=th)


def _honest_publish_schedule(rng, honest_ids, rounds, pub_rounds,
                             width=2):
    """Publish batches from HONEST origins only (the attack_report
    discipline: the measured window must start from honest sources)."""
    po = np.full((rounds, width), -1, np.int32)
    for t in range(*pub_rounds):
        po[t] = rng.choice(honest_ids, size=width)
    pt = np.zeros((rounds, width), np.int32)
    pv = np.ones((rounds, width), bool)
    return po, pt, pv


def _block_tile(states, n_candidates: int, n_sims: int):
    """[S, ...] batched tree -> [C*S, ...] with row ``c*S + s`` equal
    to batched row ``s`` (a gather, so PRNG-key leaves tile too)."""
    import jax
    import jax.numpy as jnp

    idx = jnp.asarray(np.tile(np.arange(n_sims), n_candidates))
    return jax.tree_util.tree_map(lambda x: x[idx], states)


def _wire_units(values: dict, mean_degree: float) -> float:
    """The candidate-dependent wire fan-out in per-peer edge units:
    mesh forwarding floods D edges, gossip IHAVEs cover
    ``max(Dlazy, gossip_factor * candidates)`` non-mesh neighbors —
    the degree-scaled factor the byte metrics move with when the
    program itself is shared across the population."""
    gossip = max(float(values["Dlazy"]),
                 float(values["gossip_factor"]) * float(mean_degree))
    return float(values["D"]) + gossip


@dataclasses.dataclass
class TuneCell:
    """One compiled evaluation cell, reused across generations."""

    space: SearchSpace
    profile: Profile
    net: object
    cfg: object            # the base (defaults) build the step traces
    env_cfg: object        # the invariant checker's envelope config
    sp: PeerScoreParams
    st0: object            # unbatched state template (never donated)
    runner: object         # ensemble.WindowRunner
    po: np.ndarray
    pt: np.ndarray
    pv: np.ndarray
    is_sybil: np.ndarray
    n_candidates: int
    n_sims: int
    rounds: int
    born: tuple
    seed: int
    base_values: dict
    base_cost: dict        # static per-round metrics of one row
    mean_degree: float

    @property
    def n_rows(self) -> int:
        return self.n_candidates * self.n_sims

    def build_states(self):
        """Fresh [C*S, ...] row states (the window donates its input
        buffers, so every generation rebuilds from the template)."""
        from .. import ensemble

        return _block_tile(ensemble.batch_states(self.st0, self.n_sims),
                           self.n_candidates, self.n_sims)

    def make_args(self, i: int):
        from .. import ensemble

        r = self.n_rows
        return (ensemble.tile(self.po[i], r), ensemble.tile(self.pt[i], r),
                ensemble.tile(self.pv[i], r))

    def candidate_cost(self, values: dict) -> dict:
        """The candidate's ``fingerprint["cost"]`` block: the audited
        shared-program metrics with the byte terms scaled by the wire
        model (flops/rng are population-invariant — one program)."""
        from ..perf.artifacts import cost_fingerprint

        scale = (_wire_units(values, self.mean_degree)
                 / max(_wire_units(self.base_values, self.mean_degree),
                       1e-9))
        return cost_fingerprint(
            build="tune/sybil-cell",
            flops_per_round=self.base_cost["flops"],
            hbm_bytes_per_round=self.base_cost["hbm_bytes"] * scale,
            halo_bytes_per_round=self.base_cost["halo_bytes"] * scale,
            rng_bits_per_round=self.base_cost["rng_bits"],
        )


def make_cell(space: SearchSpace, *, n_candidates: int, n_sims: int,
              profile: Profile | None = None, n: int = TUNE_N,
              rounds: int = TUNE_ROUNDS, seed: int = 0,
              fraction: float = TUNE_FRACTION, loss: float = TUNE_LOSS,
              onset: int = TUNE_ONSET, born: tuple = TUNE_BORN,
              adversary: bool = True, envelope="space",
              check_every: int = 8) -> TuneCell:
    """Build the cell: topology, adversary, publish schedule, the
    lifted step, the window runner (invariants folded under the
    envelope config) and the static cost audit — everything that stays
    fixed while generations sweep candidate planes through it.

    ``envelope`` selects the invariant checker's config: ``"space"``
    (default) widens the base config's degree bounds to the space
    envelope, ``"tight"`` keeps the base config's own bounds — the
    negative gate's setting, proving an in-space wide-mesh candidate
    IS disqualified when the envelope doesn't cover it — and a config
    object is used as-is."""
    import jax.numpy as jnp

    from .. import ensemble, graph
    from ..analysis import costmodel
    from ..chaos import AttackScenario, ChaosConfig
    from ..models.gossipsub import (
        GossipSubConfig,
        GossipSubState,
        make_gossipsub_step,
    )
    from ..oracle import invariants as oracle_inv
    from ..score.params import CandidateParams
    from ..state import Net

    profile = profile or sybil_profile()
    topo = graph.random_connect(n, d=TUNE_DEGREE, seed=seed)
    net = Net.build(topo, graph.subscribe_all(n, 1))
    cfg = GossipSubConfig.build(
        profile.params, profile.thresholds,
        score_enabled=profile.score_enabled,
        chaos=ChaosConfig(loss_rate=loss) if loss else None)
    sp = profile.sp

    adv = None
    is_sybil = np.zeros(n, bool)
    if adversary:
        scenario = AttackScenario(
            n_peers=n, sybil_fraction=fraction,
            behaviors=("drop_forward", "lie_ihave", "graft_spam",
                       "self_promo"),
            onset=onset, seed=seed)
        adv = scenario.build()
        is_sybil = np.asarray(adv.is_sybil, bool)
    honest_ids = np.flatnonzero(~is_sybil)
    rng = np.random.default_rng(seed)
    po, pt, pv = _honest_publish_schedule(
        rng, honest_ids, rounds, (2, min(born[1] + 4, rounds)))
    assert 2 * (born[1] + 2) <= TUNE_MSG_SLOTS, \
        "publish volume must not recycle message slots"

    st0 = GossipSubState.init(net, TUNE_MSG_SLOTS, cfg, score_params=sp,
                              seed=seed)
    step = make_gossipsub_step(cfg, net, score_params=sp, adversary=adv,
                               lift_scores=True)

    # static audit of ONE row's program (candidates share it): the raw
    # unjitted body traced with the defaults plane bound in a closure
    base_plane = CandidateParams.from_config(cfg, sp)
    raw = getattr(step, "__wrapped__", step)
    args0 = (jnp.asarray(po[0]), jnp.asarray(pt[0]), jnp.asarray(pv[0]))
    base_cost = costmodel.cost_of(
        lambda s: raw(s, *args0, base_plane), st0)

    if envelope == "space":
        env_cfg = space.envelope_config(cfg)
    elif envelope == "tight":
        env_cfg = cfg
    else:
        env_cfg = envelope
    hook = oracle_inv.ScanInvariants(
        "gossipsub", net, env_cfg,
        oracle_inv.InvariantConfig(check_every=check_every,
                                   delivery_window=12))
    runner = ensemble.WindowRunner(ensemble.lift_step(step), rounds,
                                   invariants=hook)
    return TuneCell(
        space=space, profile=profile, net=net, cfg=cfg, env_cfg=env_cfg,
        sp=sp, st0=st0, runner=runner, po=po, pt=pt, pv=pv,
        is_sybil=is_sybil, n_candidates=int(n_candidates),
        n_sims=int(n_sims), rounds=int(rounds), born=tuple(born),
        seed=int(seed), base_values=space.base_values(profile),
        base_cost=base_cost,
        mean_degree=float(np.asarray(net.nbr_ok).sum() / n),
    )


@dataclasses.dataclass
class EvalResult:
    """One generation's measurements, all [C]-leading host arrays."""

    delivery: np.ndarray      # [C, S] honest delivery ratios
    mean_latency: np.ndarray  # [C, S] mean first-delivery latency
    delivery_lift: np.ndarray  # [C, S] paired delta vs candidate 0
    latency_lift: np.ndarray   # [C, S] paired (lat0 - latc)/MAX_LAT
    ok: np.ndarray            # [C] bool — invariant gate per candidate
    fitness: np.ndarray       # [C] lift scalar (-inf = disqualified)
    score: np.ndarray         # [C] fitness - cost_weight * excess cost
    cost_rel: np.ndarray      # [C] hbm bytes/round vs candidate 0
    costs: list               # [C] fingerprint["cost"] dicts
    compiles: int
    dispatches: int
    seconds: float


def rank_scores(fitness: np.ndarray, cost_rel: np.ndarray,
                cost_weight: float) -> np.ndarray:
    """The ranking scalar: paired lift minus the priced cost excess.
    ``cost_weight`` is lift-per-relative-byte — 0 ranks on lift alone;
    disqualified candidates (-inf fitness) stay -inf at any weight."""
    return np.where(
        np.isfinite(fitness),
        fitness - float(cost_weight) * (np.asarray(cost_rel) - 1.0),
        -np.inf)


def evaluate(cell: TuneCell, values_list: list, *,
             cost_weight: float = 0.0) -> EvalResult:
    """Evaluate one population (decoded values dicts, candidate 0 =
    the pairing baseline) in ONE window dispatch."""
    import jax
    import jax.numpy as jnp

    from .. import ensemble
    from ..ensemble import stats as estats

    c, s = cell.n_candidates, cell.n_sims
    if len(values_list) != c:
        raise ValueError(
            f"population size {len(values_list)} != cell's {c}")
    planes = [cell.space.to_plane(v, cell.profile, cell.cfg)
              for v in values_list]
    plane = ensemble.stack_planes(planes)                      # [C]
    plane_rows = jax.tree_util.tree_map(
        lambda x: jnp.repeat(x, s, axis=0), plane)             # [C*S]

    run = cell.runner.run(cell.build_states(), cell.make_args,
                          consts=(plane_rows,))
    core = run.states.core
    delivery = np.asarray(estats.sim_delivery_ratios(
        core.dlv.first_round, core.msgs.birth, core.msgs.topic,
        core.msgs.origin, cell.net.subscribed, born_in=cell.born,
        receivers=~cell.is_sybil)).reshape(c, s)
    lat_counts = np.asarray(estats.latency_cdf_counts(
        core.dlv.first_round, core.msgs.birth, core.msgs.topic,
        core.msgs.origin, cell.net.subscribed, MAX_LAT,
        born_in=cell.born)).reshape(c, s, MAX_LAT + 1)
    delivered = lat_counts.sum(axis=-1)
    mean_lat = (lat_counts * np.arange(MAX_LAT + 1)).sum(axis=-1) \
        / np.maximum(delivered, 1)

    rep = run.invariant_report
    ok = (rep.ok.all(axis=(0, 2)).reshape(c, s).all(axis=1)
          if rep is not None and rep.n_checks else np.ones(c, bool))

    delivery_lift = delivery - delivery[:1]
    latency_lift = (mean_lat[:1] - mean_lat) / float(MAX_LAT)
    fitness = np.where(
        ok,
        delivery_lift.mean(axis=1) + LAT_WEIGHT * latency_lift.mean(axis=1),
        -np.inf)
    costs = [cell.candidate_cost(v) for v in values_list]
    cost_rel = np.array([
        ct["hbm_bytes_per_round"] / max(costs[0]["hbm_bytes_per_round"],
                                        1e-9)
        for ct in costs])
    return EvalResult(
        delivery=delivery, mean_latency=mean_lat,
        delivery_lift=delivery_lift, latency_lift=latency_lift,
        ok=np.asarray(ok, bool), fitness=fitness,
        score=rank_scores(fitness, cost_rel, cost_weight),
        cost_rel=cost_rel, costs=costs,
        compiles=run.compiles, dispatches=run.dispatches,
        seconds=run.seconds)
