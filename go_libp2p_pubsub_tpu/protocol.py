"""Protocol negotiation and custom protocol matching.

The reference maps negotiated protocol IDs to router *features* —
GossipSubFeatureMesh (speaks meshsub control: GRAFT/PRUNE/IHAVE/IWANT)
and GossipSubFeaturePX (understands prune peer-exchange) — through a
feature function (gossipsub_feat.go:11-36), and lets embedders accept
custom protocol IDs via WithProtocolMatchFn (exercised by
gossipsub_matchfn_test.go: a prefix matcher admits "/meshsub/1.1.0-beta"
as meshsub). The vectorized engine consumes the packed feature level
(`Net.protocol`: 0 = no features/floodsub, 1 = mesh, 2 = mesh+px), so a
custom protocol plugs in by declaring its feature set here — the engine
itself never changes.
"""

from __future__ import annotations

from collections.abc import Callable

FEATURE_MESH = 1  # GossipSubFeatureMesh (gossipsub_feat.go:13)
FEATURE_PX = 2    # GossipSubFeaturePX (gossipsub_feat.go:15)

# the default protocol stack (gossipsub_feat.go:22-33; GossipSubDefaultProtocols)
DEFAULT_FEATURES: dict[str, int] = {
    "/floodsub/1.0.0": 0,
    "/meshsub/1.0.0": FEATURE_MESH,
    "/meshsub/1.1.0": FEATURE_MESH | FEATURE_PX,
}


class ProtocolError(ValueError):
    pass


class ProtocolMatcher:
    """Protocol id -> feature set, with a custom-match seam.

    ``features`` extends/overrides the default table with custom protocol
    ids (an embedder's "/my-app/gossip/2.0.0" can declare MESH|PX and the
    router treats its speakers as full v1.1 peers). ``match_fn`` is the
    WithProtocolMatchFn analogue: called for ids absent from the table,
    it returns the table key the observed id matches (or None to reject)
    — e.g. a prefix matcher admitting versioned variants.
    """

    def __init__(
        self,
        features: dict[str, int] | None = None,
        match_fn: Callable[[str], str | None] | None = None,
    ) -> None:
        self.features = dict(DEFAULT_FEATURES)
        if features:
            for pid, bits in features.items():
                if (bits & FEATURE_PX) and not (bits & FEATURE_MESH):
                    raise ProtocolError(
                        f"protocol {pid!r}: PX requires the mesh feature "
                        "(a peer that can't be grafted can't be PX'd; "
                        "gossipsub_feat.go:22-33)"
                    )
                self.features[pid] = int(bits)
        self.match_fn = match_fn

    def feature_bits(self, protocol_id: str) -> int:
        if protocol_id in self.features:
            return self.features[protocol_id]
        if self.match_fn is not None:
            base = self.match_fn(protocol_id)
            if base is not None and base in self.features:
                return self.features[base]
        raise ProtocolError(
            f"unknown protocol {protocol_id!r}: not in the feature table "
            "and not accepted by the match function (WithProtocolMatchFn)"
        )

    def supports(self, protocol_id: str, feature: int) -> bool:
        """The feature-function surface (gossipsub_feat.go:11-20)."""
        return bool(self.feature_bits(protocol_id) & feature)

    def level(self, protocol_id: str) -> int:
        """The engine's packed encoding (state.Net.protocol)."""
        bits = self.feature_bits(protocol_id)
        if bits & FEATURE_PX:
            return 2
        return 1 if bits & FEATURE_MESH else 0


def prefix_match(*bases: str) -> Callable[[str], str | None]:
    """A match function admitting any id that starts with one of the base
    protocol ids — the shape gossipsub_matchfn_test.go exercises
    ("/meshsub/1.1.0-beta" negotiates as "/meshsub/1.1.0")."""

    def fn(protocol_id: str) -> str | None:
        for base in bases:
            if protocol_id.startswith(base):
                return base
        return None

    return fn
