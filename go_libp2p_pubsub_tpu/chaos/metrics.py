"""Recovery metrics for chaos runs (host-side, numpy).

Everything here is computed from artifacts a chaos run already
produces — the device delivery plane (``dlv.first_round`` + the message
table, the same planes the trace drain reconstructs DELIVER events
from), the cumulative event counters (trace/events.py — including the
chaos plane's LINK_DOWN and IWANT_RECOVER), per-round/phase mesh
snapshots, and the Scenario schedule (host-known partition windows).

The headline metrics, matching the v1.1 evaluation methodology's
degraded-network measurements (arxiv 2007.02754 §4):

  * **delivery ratio** — delivered / expected over (subscriber, live
    message) pairs; the loss a generator actually inflicted end-to-end;
  * **IWANT-recovery share** — the fraction of deliveries whose FIRST
    arrival rode an IWANT service rather than an eager push: the lazy
    gossip machinery's measured contribution under loss;
  * **mesh-repair latency** — rounds from a partition's heal until the
    cross-group mesh re-forms (from mesh snapshots + the group map);
  * **time-to-recover** — rounds from heal until every expected
    delivery of partition-era messages has landed.

Cadence caveat (same shape as the tracestat caveat block): under the
phase engine (r > 1) the LINK_DOWN / IWANT_RECOVER counters are exact
TOTALS but accumulate at phase cadence, and mesh snapshots exist only
at phase boundaries — latencies derived from them quantize to
multiples of r. The delivery plane keeps 1-round resolution at every
cadence (the device stamps ``first_round`` per sub-round).
"""

from __future__ import annotations

import dataclasses

import numpy as np

from ..trace.events import EV


@dataclasses.dataclass
class DeliveryStats:
    """delivered / expected over (subscriber, message) pairs."""

    delivered: int
    expected: int

    @property
    def ratio(self) -> float:
        return self.delivered / self.expected if self.expected else 1.0


def expected_receivers(msg_birth: np.ndarray, msg_topic: np.ndarray,
                       msg_origin: np.ndarray, subscribed: np.ndarray,
                       up: np.ndarray | None = None,
                       born_in: tuple | None = None) -> np.ndarray:
    """[N, M] bool: peers that SHOULD receive each live message — topic
    subscribers excluding the origin (it has its own copy), optionally
    restricted to up peers and to messages born in ``born_in = (lo,
    hi)`` ticks (half-open)."""
    birth = np.asarray(msg_birth)
    live = birth >= 0
    if born_in is not None:
        lo, hi = born_in
        live = live & (birth >= lo) & (birth < hi)
    sub = np.asarray(subscribed)[:, np.clip(np.asarray(msg_topic), 0, None)]
    exp = sub & live[None, :]
    n = exp.shape[0]
    origin = np.clip(np.asarray(msg_origin), 0, n - 1)
    exp[origin[live], np.nonzero(live)[0]] = False
    if up is not None:
        exp &= np.asarray(up, bool)[:, None]
    return exp


def delivery_stats(first_round: np.ndarray, msg_birth, msg_topic,
                   msg_origin, subscribed, up=None,
                   born_in: tuple | None = None) -> DeliveryStats:
    """Delivery ratio from the device delivery plane. Caveat: slots
    recycle — only messages still resident in the table are counted,
    so size ``msg_slots`` above the run's publish volume (every chaos
    scenario in scripts/chaos_report.py does) or compute per-window
    with ``born_in``."""
    exp = expected_receivers(msg_birth, msg_topic, msg_origin, subscribed,
                             up=up, born_in=born_in)
    got = (np.asarray(first_round) >= 0) & exp
    return DeliveryStats(delivered=int(got.sum()), expected=int(exp.sum()))


def iwant_recovery_share(events: np.ndarray) -> float:
    """Fraction of validated deliveries whose FIRST arrival came via
    IWANT service (the chaos plane's IWANT_RECOVER counter over the
    DELIVER_MESSAGE counter). Requires a chaos-enabled build with
    ``count_events=True`` (the counter is statically elided otherwise).
    """
    ev = np.asarray(events)
    deliver = int(ev[EV.DELIVER_MESSAGE])
    return int(ev[EV.IWANT_RECOVER]) / deliver if deliver else 0.0


def links_down_total(events: np.ndarray) -> int:
    """Cumulative undirected link-down rounds (the LINK_DOWN counter)."""
    return int(np.asarray(events)[EV.LINK_DOWN])


def batched_iwant_shares(events) -> np.ndarray:
    """[S] per-sim IWANT-recovery shares from BATCHED ensemble event
    counters (``events [S, N_EVENTS]``) — iwant_recovery_share per
    sim, one vectorized reduction."""
    ev = np.asarray(events)
    deliver = ev[:, EV.DELIVER_MESSAGE].astype(np.float64)
    return np.where(deliver > 0,
                    ev[:, EV.IWANT_RECOVER] / np.maximum(deliver, 1.0),
                    0.0)


# ---------------------------------------------------------------------------
# partition recovery


def _cross_edge_mask(nbr, nbr_ok, groups) -> np.ndarray:
    """[N, K] bool: neighbor-slot positions whose edge crosses the
    group boundary — the ONE definition of "cross edge" every
    partition metric (single-sim and batched) counts with."""
    g = np.asarray(groups, np.int32)
    return ((g[:, None] != g[np.clip(np.asarray(nbr), 0, None)])
            & np.asarray(nbr_ok))


def cross_group_mesh_count(mesh: np.ndarray, nbr: np.ndarray,
                           nbr_ok: np.ndarray, groups) -> int:
    """Directed cross-group mesh edges in a mesh snapshot ([N, S, K])."""
    cross = _cross_edge_mask(nbr, nbr_ok, groups)
    return int((np.asarray(mesh) & cross[:, None, :]).sum())


def batched_cross_group_mesh_counts(mesh: np.ndarray, nbr: np.ndarray,
                                    nbr_ok: np.ndarray,
                                    groups) -> np.ndarray:
    """[S] directed cross-group mesh edge counts for a BATCHED
    ensemble mesh snapshot ([S, N, SL, K]) — cross_group_mesh_count
    per sim, one vectorized reduction."""
    cross = _cross_edge_mask(nbr, nbr_ok, groups)
    return (np.asarray(mesh) & cross[None, :, None, :]).sum(
        axis=(1, 2, 3)).astype(np.int64)


def make_cross_mesh_observer(nbr, nbr_ok, groups):
    """DEVICE counterpart of :func:`batched_cross_group_mesh_counts`
    for scan-window observation (driver.make_window ``observe=``): a
    closure ``state -> [S] i32`` (scalar for unbatched states) counting
    directed cross-group mesh edges on the live mesh plane — the
    per-round repair-arc series without leaving the window program.
    Same ``_cross_edge_mask`` definition, so the scanned series is
    bit-identical to the host reduction (tests/test_window.py)."""
    import jax.numpy as jnp

    cross = jnp.asarray(_cross_edge_mask(nbr, nbr_ok, groups))  # [N, K]

    def observe(state):
        mesh = state.mesh  # [..., N, SL, K]
        return jnp.sum(mesh & cross[:, None, :],
                       axis=(-3, -2, -1)).astype(jnp.int32)

    return observe


def mesh_repair_latency(mesh_series, heal_tick: int,
                        min_edges: int = 1) -> int | None:
    """Rounds from ``heal_tick`` until the cross-group mesh re-forms.

    ``mesh_series`` is an iterable of ``(tick, cross_edge_count)`` rows
    (the runner samples ``cross_group_mesh_count`` per round/phase).
    Returns the first ``tick - heal_tick`` at/after heal with count >=
    ``min_edges``, or None if the mesh never repairs in the observed
    window (infinite — the smoke asserts finiteness)."""
    for tick, count in sorted(mesh_series):
        if tick >= heal_tick and count >= min_edges:
            return int(tick - heal_tick)
    return None


def mesh_reform_latency(mesh_series, heal_tick: int,
                        prune_floor: int = 2,
                        min_edges: int = 6) -> int | None:
    """Rounds from ``heal_tick`` until cross-group connectivity is
    RE-ESTABLISHED after the post-heal starvation prune — the
    band-robust repair metric (round 10).

    The raw ``count >= min_edges`` reading (mesh_repair_latency) is
    ambiguous right after heal: the mesh map still lists partition-era
    ZOMBIE edges (entries that carried no traffic through the window;
    pruning their accumulated P3 deficit is heartbeat-rate-limited, so
    they drain over ~tens of rounds). Measured from the Monte Carlo
    band, the real arc is: zombie edges drain to ~zero, then the prune
    backoff expires and the reference's lazy 15-tick backoff-presence
    clear (gossipsub.go:1585-1604) releases a re-graft wave. This
    metric reports that arc: the first tick at/after the count drops
    to ``prune_floor`` or below (the trough — full starvation prune)
    where a LATER count reaches ``min_edges`` (re-formed), as
    ``tick - heal_tick``. A sim whose count never troughs — the
    starvation prune never completed, so cross connectivity never
    collapsed — reports 0 provided it stays above ``prune_floor`` for
    the whole post-heal window and ends re-formed (``>= min_edges``);
    None when the mesh troughs but never re-forms, or hovers below
    ``min_edges`` without ever recovering."""
    post = [(t, c) for t, c in sorted(mesh_series) if t >= heal_tick]
    troughed = False
    for tick, count in post:
        if not troughed:
            if count <= prune_floor:
                troughed = True
            continue
        if count >= min_edges:
            return int(tick - heal_tick)
    # never troughed == every post-heal count stayed above prune_floor
    if not troughed and post and post[-1][1] >= min_edges:
        return 0
    return None


def time_to_recover(first_round: np.ndarray, msg_birth, msg_topic,
                    msg_origin, subscribed, heal_tick: int,
                    born_in: tuple | None = None,
                    up=None) -> int | None:
    """Rounds from ``heal_tick`` until the LAST expected delivery of
    the window's messages landed (full eventual delivery). None when
    deliveries are still missing in the final state — recovery did not
    complete in the observed run."""
    exp = expected_receivers(msg_birth, msg_topic, msg_origin, subscribed,
                             up=up, born_in=born_in)
    fr = np.asarray(first_round)
    if not exp.any():
        return 0
    missing = exp & (fr < 0)
    if missing.any():
        return None
    return max(0, int(fr[exp].max()) - int(heal_tick))
