"""Chaos plane: vectorized link-fault injection, partition/heal
scenarios, and measured recovery (docs/DESIGN.md §8).

  faults    — ChaosConfig + the i.i.d. / Gilbert–Elliott link-flap
              generators (symmetric per-link masks drawn from the sim
              PRNG stream; checkpoint-exact resume)
  scenario  — declarative partition + crash-storm schedules compiled
              to per-round/per-phase mask arguments
  metrics   — recovery metrics: delivery ratio under loss, IWANT-
              recovery share, mesh-repair latency, time-to-recover
  adversary — the v1.1 attack suite (docs/DESIGN.md §13): per-peer
              sybil/behavior masks driving lie-in-IHAVE, drop-on-
              forward, graft-spam, self-promotion and censorship as
              masked variants of the existing step math, plus
              declarative AttackScenario schedules

The runners live in scripts/chaos_report.py (``make chaos-smoke``)
and scripts/attack_report.py (``make attack-smoke``).
"""

from .adversary import (  # noqa: F401
    Adversary,
    AdversaryError,
    AttackScenario,
    BEHAVIORS,
)
from .faults import ChaosConfig, ChaosConfigError, resolve  # noqa: F401
from .metrics import (  # noqa: F401
    batched_cross_group_mesh_counts,
    batched_iwant_shares,
    DeliveryStats,
    cross_group_mesh_count,
    delivery_stats,
    iwant_recovery_share,
    links_down_total,
    make_cross_mesh_observer,
    mesh_reform_latency,
    mesh_repair_latency,
    time_to_recover,
)
from .scenario import (  # noqa: F401
    CrashStorm,
    Partition,
    Scenario,
    halves,
    two_group_partition,
)
