"""Vectorized adversary plane: the GossipSub v1.1 attack suite as
masked variants of the existing step math (docs/DESIGN.md §13).

The v1.1 hardening paper (arXiv:2007.02754) validates the protocol by
attacking it — sybil flood, eclipse/mesh-takeover, cold boot, covert
flash, censorship — and showing the scoring machinery (P1–P7, gater,
backoff, opportunistic grafting) isolates the attackers while honest
delivery survives. This module supplies those attacker populations as
batched array programs: a per-peer ``is_sybil`` plane plus per-behavior
masks drive attacker behaviors inside the SAME jitted steps the honest
network runs, as masked variants of the existing math — no separate
attacker stack, no per-attacker host loop, vmappable to ensemble bands.

Behaviors (each an independently maskable plane; the reference test
each models is cited inline where the engines apply it):

  * **drop_forward** — run the full control plane but never transmit
    message data (mesh push, flood-publish, fanout, IWANT service):
    the ``sybilSquatter`` attacker (gossipsub_test.go:1777-1811),
    caught by the P3 mesh-delivery deficit + P7 broken promises. The
    scheduled generalization of the static ``adversary_no_forward``
    build vector (which remains supported, always-on, unscheduled).
  * **lie_ihave** — advertise every live message id on every edge,
    whether or not it was ever received (IHAVE spam,
    gossipsub_spam_test.go:290): elicits IWANTs the attacker will not
    serve → broken gossip promises → P7 behaviour penalty.
  * **graft_spam** — GRAFT every (live slot, edge) each heartbeat,
    ignoring PRUNE backoff (GRAFT flood, gossipsub_spam_test.go:365):
    victims double-penalize flood-window GRAFTs (gossipsub.go:760-768)
    → P7. Spam attackers keep NO backoff bookkeeping of their own (the
    reference attacker is a raw-wire fake with no router state) — the
    hook zeroes their backoff planes, so the oracle plane's
    backoff-respect properties hold for the honest population they
    were written about.
  * **self_promo** — cooperating sybils pin their held scores of
    FELLOW sybils at ``promo_score`` (the P5-style app credit a sybil
    faction grants itself): sybils never graylist, prune, or
    score-gate each other, the covert-flash cohesion shape — honest
    peers' scoring of sybils (the defense under test) is untouched.
  * **censor** — forward everything EXCEPT messages originated by the
    ``censor_origins`` target set (selective per-message drop): the
    stealthy censorship attack — P3 stays clean on ambient traffic, so
    isolation must come from the targets' own delivery paths.

Zero-permute contract: every mask ANDs into gathers the steps already
perform. The gossipsub factories (and ``make_randomsub_step``) build
neighbor views of the static per-peer planes EAGERLY at build time
(``is_sybil[nbr]`` etc. are jit constants), so the sharded lowering
adds NO halo permutes; per-round activity is a pure elementwise
compare of those constants against the tick. ``floodsub_step`` takes
``net`` as a traced argument, so its neighbor views trace as one tiny
[N] → [N, K] gather per round (floodsub is outside the pinned
collectives budget; the gossipsub engines stay zero-extra-permute).

Schedules: ``onset``/``stop`` are per-peer i32 planes compared against
the tick on device — an :class:`AttackScenario` compiles declarative
attack windows (onset, ramp, stop, sybil fraction, eclipse target
sets) down to those planes, staggering per-peer onsets across a ramp.
Because activity is a pure function of (static planes, tick), the
plane is stateless: checkpoints resume the exact attack sequence with
no new state leaves and no format bump (tests/test_adversary.py pins
the round trip). It composes orthogonally with chaos link faults /
partitions (``chaos.Scenario``) and the churn plane's ``up`` rows —
cold-boot and covert-flash timing ride those existing arguments.

Static elision contract: ``adversary=None`` (or a population whose
every behavior is off / empty) traces exactly the pre-adversary
program — no masks, no counters, no extra ops; ``resolve`` is the one
shared elision decision, like ``chaos.faults.resolve``. Pinned by
tests/test_adversary.py (bit-exact state trees, all four engines) and
``make attack-smoke`` (adversary-off compiled HLO census equality).
"""

from __future__ import annotations

import dataclasses
import hashlib

import jax
import jax.numpy as jnp
import numpy as np

from ..ops import bitset

#: the maskable behavior planes (one [N] bool mask each; None = the
#: behavior is off for the whole population)
BEHAVIORS = ("drop_forward", "lie_ihave", "graft_spam", "self_promo",
             "censor")

#: "never stops" tick sentinel (far beyond any simulated horizon,
#: safely inside i32)
NEVER = 2 ** 30


class AdversaryError(ValueError):
    """Raised on invalid adversary populations / attack scenarios."""


class Adversary:
    """A build-time adversary population description.

    Plain host object holding numpy planes; the ``make_*_step``
    factories close over eagerly-built constants derived from it
    (:class:`AdversaryConsts`). Hashable by IDENTITY (not value) on
    purpose, so it can also ride jit static args (``floodsub_step``)
    — two distinct instances are two cache entries, like two distinct
    topologies.

    ``is_sybil`` names the attacker faction; each behavior defaults to
    the whole faction and can be restricted with a per-behavior mask
    (``masks={"graft_spam": ...}``) — every behavior mask must be a
    subset of ``is_sybil``. ``onset``/``stop`` are ticks (scalar or
    per-peer [N] i32): a behavior is ACTIVE for peer i exactly when
    ``mask[i] and onset[i] <= tick < stop[i]``.

    ``censor_origins`` is the [N] bool target set whose messages the
    ``censor`` behavior drops; ``graft_targets`` optionally restricts
    ``graft_spam`` to edges toward a victim set (the eclipse shape —
    None spams every edge).
    """

    def __init__(self, n_peers: int, is_sybil, behaviors=("drop_forward",),
                 *, masks: dict | None = None, onset=0, stop=None,
                 promo_score: float = 20.0, censor_origins=None,
                 graft_targets=None):
        self.n_peers = int(n_peers)
        self.is_sybil = np.asarray(is_sybil, bool).reshape(-1)
        self.behaviors = tuple(behaviors)
        self.masks = {k: np.asarray(v, bool).reshape(-1)
                      for k, v in (masks or {}).items()}
        self.onset = np.broadcast_to(
            np.asarray(onset, np.int32), (self.n_peers,)).copy()
        self.stop = np.broadcast_to(
            np.asarray(NEVER if stop is None else stop, np.int32),
            (self.n_peers,)).copy()
        self.promo_score = float(promo_score)
        self.censor_origins = (
            None if censor_origins is None
            else np.asarray(censor_origins, bool).reshape(-1))
        self.graft_targets = (
            None if graft_targets is None
            else np.asarray(graft_targets, bool).reshape(-1))
        self.validate()

    def validate(self) -> None:
        n = self.n_peers
        if self.is_sybil.shape != (n,):
            raise AdversaryError(
                f"is_sybil has shape {self.is_sybil.shape} for {n} peers")
        unknown = [b for b in self.behaviors if b not in BEHAVIORS]
        if unknown:
            raise AdversaryError(
                f"unknown behaviors {unknown}; known: {BEHAVIORS}")
        for k, m in self.masks.items():
            if k not in BEHAVIORS:
                raise AdversaryError(
                    f"mask for unknown behavior {k!r}; known: {BEHAVIORS}")
            if k not in self.behaviors:
                raise AdversaryError(
                    f"mask[{k!r}] given but the behavior is not enabled "
                    f"(behaviors={self.behaviors}) — a silently ignored "
                    "mask would run the experiment without the attack")
            if m.shape != (n,):
                raise AdversaryError(
                    f"mask[{k!r}] has shape {m.shape} for {n} peers")
            if (m & ~self.is_sybil).any():
                raise AdversaryError(
                    f"mask[{k!r}] marks peers outside is_sybil — behavior "
                    "masks restrict the faction, they cannot extend it")
        for name in ("onset", "stop"):
            v = getattr(self, name)
            if v.shape != (n,):
                raise AdversaryError(
                    f"{name} has shape {v.shape} for {n} peers")
        if (self.onset < 0).any():
            raise AdversaryError("onset ticks must be >= 0")
        if "censor" in self.behaviors and self.censor_origins is None:
            raise AdversaryError(
                "the censor behavior needs censor_origins (the [N] bool "
                "target set whose messages are dropped)")
        for name, v in (("censor_origins", self.censor_origins),
                        ("graft_targets", self.graft_targets)):
            if v is not None and v.shape != (n,):
                raise AdversaryError(
                    f"{name} has shape {v.shape} for {n} peers")

    def mask(self, behavior: str) -> np.ndarray | None:
        """[N] bool plane of ``behavior``, or None when it is off."""
        if behavior not in self.behaviors:
            return None
        m = self.masks.get(behavior, self.is_sybil)
        return m if m.any() else None

    @property
    def enabled(self) -> bool:
        """False ⇒ the build elides the adversary plane entirely."""
        return any(self.mask(b) is not None for b in self.behaviors)

    def fingerprint(self) -> dict:
        """The schema-v3 artifact self-description of this population
        (perf/artifacts.py ``adversary`` block)."""
        h = hashlib.sha256()
        h.update(self.is_sybil.tobytes())
        h.update(self.onset.tobytes())
        h.update(self.stop.tobytes())
        for b in BEHAVIORS:
            m = self.mask(b)
            h.update(b"-" if m is None else m.tobytes())
        for v in (self.censor_origins, self.graft_targets):
            h.update(b"-" if v is None else v.tobytes())
        live = [b for b in self.behaviors if self.mask(b) is not None]
        return {
            "enabled": bool(self.enabled),
            "n_sybils": int(self.is_sybil.sum()),
            "behaviors": live,
            "onset": int(self.onset[self.is_sybil].min())
            if self.is_sybil.any() else 0,
            "stop": (lambda s: None if s >= NEVER else s)(
                int(self.stop[self.is_sybil].max())
                if self.is_sybil.any() else NEVER),
            "promo_score": self.promo_score,
            "population": h.hexdigest()[:12],
        }


def resolve(adversary: "Adversary | None") -> "Adversary | None":
    """Normalize to None when the plane is off — the single elision
    decision every engine shares (mirrors chaos.faults.resolve).
    Validation runs FIRST: a typo'd behavior name must raise, not
    silently run the experiment against an honest network."""
    if adversary is None:
        return None
    adversary.validate()
    return adversary if adversary.enabled else None


class AdversaryConsts:
    """Eager per-(adversary, topology) jit constants.

    Built once at step-build time (the ``StepConsts`` pattern): the
    per-peer planes and their NEIGHBOR views are concrete arrays, so
    the steps' per-round activity tests are elementwise compares of
    constants against the tick — zero gathers, zero halo permutes on
    the sharded mesh. Under a traced ``net`` (floodsub's calling
    convention) the neighbor views trace as one [N] → [N, K] gather.
    """

    __slots__ = ("adv", "onset", "stop", "onset_nbr", "stop_nbr",
                 "self_masks", "nbr_masks", "sybil_nbr", "spam_edges",
                 "censor_origin", "promo_score")

    def __init__(self, adv: Adversary, net):
        self.adv = adv
        self.promo_score = jnp.float32(adv.promo_score)
        nbr = jnp.clip(net.nbr, 0)
        self.onset = jnp.asarray(adv.onset)
        self.stop = jnp.asarray(adv.stop)
        self.onset_nbr = self.onset[nbr]
        self.stop_nbr = self.stop[nbr]
        self.self_masks = {}
        self.nbr_masks = {}
        for b in BEHAVIORS:
            m = adv.mask(b)
            if m is None:
                continue
            mj = jnp.asarray(m)
            self.self_masks[b] = mj
            self.nbr_masks[b] = mj[nbr] & net.nbr_ok
        sybil = jnp.asarray(adv.is_sybil)
        self.sybil_nbr = sybil[nbr] & net.nbr_ok
        # graft-spam edge eligibility: present, never self, optionally
        # restricted to the eclipse victim set
        n = net.nbr.shape[0]
        not_self = net.nbr != jnp.arange(n, dtype=net.nbr.dtype)[:, None]
        spam = net.nbr_ok & not_self
        if adv.graft_targets is not None:
            spam = spam & jnp.asarray(adv.graft_targets)[nbr]
        self.spam_edges = spam
        self.censor_origin = (
            jnp.asarray(adv.censor_origins)
            if adv.censor_origins is not None else None)

    def has(self, behavior: str) -> bool:
        return behavior in self.self_masks

    @property
    def data_plane(self) -> bool:
        """True when any data-plane behavior (drop_forward / censor)
        is live — the engines' one gate for the transmit-mask hooks."""
        return self.has("drop_forward") or self.has("censor")

    def active_self(self, behavior: str, tick) -> jax.Array:
        """[N] bool: peers running ``behavior`` this round."""
        return (self.self_masks[behavior]
                & (tick >= self.onset) & (tick < self.stop))

    def active_nbr(self, behavior: str, tick) -> jax.Array:
        """[N, K] bool: edge (j, k) has an active-``behavior`` SENDER
        at its far end this round (the receiver-gather gate)."""
        return (self.nbr_masks[behavior]
                & (tick >= self.onset_nbr) & (tick < self.stop_nbr))

    def censor_words(self, msgs) -> jax.Array:
        """[W] u32 packed mask of message slots an active censor drops
        (live messages originated by the target set)."""
        hit = (self.censor_origin[jnp.clip(msgs.origin, 0)]
               & (msgs.origin >= 0))
        return bitset.pack(hit)

    def mask_transmit_nbr(self, tick, plane, msgs):
        """Receiver-side data-plane gate: suppress bits of a gathered
        [N, K, W] transmit plane on edges whose SENDER is an active
        ``drop_forward`` / ``censor`` attacker this round. Returns
        ``(masked, removed)`` — callers popcount ``removed`` (∩ the
        forwardable set) into the EV.ADV_DROP attribution counter."""
        out = plane
        if self.has("drop_forward"):
            dn = self.active_nbr("drop_forward", tick)
            out = jnp.where(dn[:, :, None], jnp.uint32(0), out)
        if self.has("censor"):
            cn = self.active_nbr("censor", tick)
            cw = self.censor_words(msgs)
            out = jnp.where(cn[:, :, None], out & ~cw[None, None, :], out)
        return out, plane & ~out

    def mask_transmit_self(self, tick, plane, msgs):
        """Sender-side form of the same gate (the phase engine's
        transmit composition is sender-side, so the attacker masks its
        OWN rows before the one edge gather). Returns
        ``(masked, removed)``."""
        out = plane
        if self.has("drop_forward"):
            ds = self.active_self("drop_forward", tick)
            out = jnp.where(ds[:, None, None], jnp.uint32(0), out)
        if self.has("censor"):
            cs = self.active_self("censor", tick)
            cw = self.censor_words(msgs)
            out = jnp.where(cs[:, None, None], out & ~cw[None, None, :], out)
        return out, plane & ~out


def withheld_count(net, fwd, removed) -> jax.Array:
    """i32 scalar EV.ADV_DROP attribution: suppressed receiver-side
    carry bits ∩ the senders' forward sets (the same fwd gather the
    delivery round performs — XLA CSE merges the two, so the counter
    adds no second halo exchange)."""
    fwd_g = net.peer_gather(fwd)
    return bitset.popcount(removed & fwd_g, axis=None).sum().astype(
        jnp.int32)


@dataclasses.dataclass(frozen=True)
class AttackScenario:
    """A declarative, reproducible attack schedule over one run.

    Compiles to the static per-peer planes the engines consume
    (:meth:`build` → :class:`Adversary`) — the adversary analogue of
    ``chaos.Scenario``; it composes with partitions (``link_deny``),
    crash storms / cold boot (the churn ``up`` rows), and covert-flash
    timing (a late ``onset`` after a long honest warmup) purely at the
    schedule layer.

    Sybil recruitment, one of:
      * ``sybils`` — explicit peer indices;
      * ``sybil_fraction`` — the top fraction of the id space
        (deterministic: peers ``[ceil(N·(1-f)), N)``);
      * ``surround_targets=True`` — the TOPOLOGY NEIGHBORS of
        ``targets`` become the sybils (the eclipse placement; needs
        ``build(net=...)``). ``surround_fraction < 1`` recruits only
        that fraction of each target's neighbors (seeded,
        deterministic) — a full surround leaves the victim NO honest
        edge to recover through, the unrecoverable limit case.

    ``ramp_rounds`` staggers per-sybil onsets uniformly (seeded,
    deterministic) across ``[onset, onset + ramp_rounds)`` — the
    attack's arrival is a ramp, not a step. ``stop=None`` never stops.
    """

    n_peers: int
    behaviors: tuple = ("drop_forward",)
    sybils: tuple = ()
    sybil_fraction: float = 0.0
    onset: int = 0
    stop: int | None = None
    ramp_rounds: int = 0
    targets: tuple = ()
    surround_targets: bool = False
    surround_fraction: float = 1.0
    censor_origins: tuple = ()
    promo_score: float = 20.0
    seed: int = 0

    def validate(self) -> None:
        if not (0.0 <= self.sybil_fraction < 1.0):
            raise AdversaryError(
                f"sybil_fraction must be in [0, 1), got {self.sybil_fraction}")
        if self.onset < 0 or self.ramp_rounds < 0:
            raise AdversaryError("onset/ramp_rounds must be >= 0")
        if self.stop is not None and self.stop <= self.onset:
            raise AdversaryError(
                f"stop ({self.stop}) must be > onset ({self.onset})")
        for name in ("sybils", "targets", "censor_origins"):
            for i in getattr(self, name):
                if not (0 <= int(i) < self.n_peers):
                    raise AdversaryError(f"{name} index {i} out of range")
        if self.surround_targets and not self.targets:
            raise AdversaryError("surround_targets needs a target set")
        if not (0.0 < self.surround_fraction <= 1.0):
            raise AdversaryError(
                f"surround_fraction must be in (0, 1], got "
                f"{self.surround_fraction}")

    def _sybil_plane(self, net=None) -> np.ndarray:
        n = self.n_peers
        sybil = np.zeros((n,), bool)
        if self.sybils:
            sybil[list(self.sybils)] = True
        if self.sybil_fraction > 0.0:
            sybil[int(np.ceil(n * (1.0 - self.sybil_fraction))):] = True
        if self.surround_targets:
            if net is None:
                raise AdversaryError(
                    "surround_targets recruits the targets' topology "
                    "neighbors — pass build(net=...)")
            nbr = np.asarray(net.nbr)
            ok = np.asarray(net.nbr_ok)
            rng = np.random.default_rng(self.seed + 0x5A11)
            for t in self.targets:
                nbrs = np.unique(nbr[int(t)][ok[int(t)]])
                if self.surround_fraction < 1.0:
                    keep = max(1, int(np.floor(
                        self.surround_fraction * nbrs.size)))
                    nbrs = rng.permutation(nbrs)[:keep]
                sybil[nbrs] = True
        sybil[list(self.targets)] = False  # a victim is never a sybil
        return sybil

    def build(self, net=None) -> Adversary:
        """Compile to the static per-peer planes (an Adversary)."""
        self.validate()
        n = self.n_peers
        sybil = self._sybil_plane(net)
        onset = np.full((n,), self.onset, np.int32)
        if self.ramp_rounds > 0:
            rng = np.random.default_rng(self.seed)
            idx = np.nonzero(sybil)[0]
            onset[idx] = self.onset + rng.integers(
                0, self.ramp_rounds, size=idx.size)
        stop = NEVER if self.stop is None else self.stop
        censor = None
        if self.censor_origins:
            censor = np.zeros((n,), bool)
            censor[list(self.censor_origins)] = True
        targets = None
        if self.targets:
            targets = np.zeros((n,), bool)
            targets[list(self.targets)] = True
        return Adversary(
            n, sybil, self.behaviors, onset=onset, stop=stop,
            promo_score=self.promo_score, censor_origins=censor,
            graft_targets=targets if "graft_spam" in self.behaviors else None,
        )

    def events(self) -> list:
        """The schedule as (tick, kind, detail) rows — host-known
        exact, like chaos.Scenario.events."""
        out = [(self.onset, "AttackOnset",
                {"behaviors": list(self.behaviors),
                 "ramp_rounds": self.ramp_rounds})]
        if self.stop is not None:
            out.append((self.stop, "AttackStop", {}))
        return out

    def scenario_hash(self) -> str:
        """Stable short hash of the whole schedule (artifact adversary
        fingerprint field)."""
        h = hashlib.sha256()
        h.update(repr((self.n_peers, self.behaviors, tuple(self.sybils),
                       self.sybil_fraction, self.onset, self.stop,
                       self.ramp_rounds, tuple(self.targets),
                       self.surround_targets, self.surround_fraction,
                       tuple(self.censor_origins),
                       self.promo_score, self.seed)).encode())
        return h.hexdigest()[:12]
