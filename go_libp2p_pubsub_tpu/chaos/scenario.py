"""Declarative chaos scenarios: scheduled partitions + crash storms.

A :class:`Scenario` is a host-side schedule — windows of network
partition (the topology split into groups; every cross-group link is
forced down) and windows of peer crash (composed from the existing
churn plane's ``up`` vector, notify.go:19-75 / handleDeadPeers) — that
compiles to the per-round mask arguments the chaos-enabled steps take:

  * ``link_deny_at(tick, nbr)`` → the [N, K] bool forced-down mask the
    ``ChaosConfig(scheduled=True)`` step consumes (True = down);
  * ``up_at(tick)`` → the [N] liveness row a ``dynamic_peers`` build
    consumes.

Phase-cadence quantization: the phase engine applies control once per
phase and takes ONE ``link_deny`` per phase — partitions therefore
quantize to phase boundaries (use ``link_deny_at(phase_head_tick)``;
the mask holds for the whole phase), exactly like peer churn, whose
transitions also land once per phase at its head. Windows whose
start/end are not multiples of ``rounds_per_phase`` round OUTWARD for
partitions (the partition is at least as long as declared) via
``link_deny_at`` evaluated at the head tick — document any finer claim
against the per-round engine.

Everything here is deterministic host-side numpy: the same Scenario +
the same sim seed replays the identical fault sequence (the
determinism test pins a bit-identical trace), and ``scenario_hash``
gives artifacts a stable fingerprint of the schedule.
"""

from __future__ import annotations

import dataclasses
import hashlib

import numpy as np


@dataclasses.dataclass(frozen=True)
class Partition:
    """Split the network into groups for ticks [start, start+rounds):
    links whose endpoints are in different groups are forced down; at
    ``start + rounds`` the partition heals."""

    start: int
    rounds: int
    groups: tuple  # [N] int group id per peer (tuple — hashable/frozen)

    @property
    def end(self) -> int:
        return self.start + self.rounds


@dataclasses.dataclass(frozen=True)
class CrashStorm:
    """Peers down (crashed) for ticks [start, start+rounds): composed
    from the churn plane — a dynamic_peers build disconnects them with
    full dead-peer cleanup and restarts them with fresh soft state."""

    start: int
    rounds: int
    peers: tuple  # peer indices

    @property
    def end(self) -> int:
        return self.start + self.rounds


@dataclasses.dataclass(frozen=True)
class Scenario:
    """A reproducible fault schedule over one simulated run."""

    n_peers: int
    partitions: tuple = ()   # tuple[Partition, ...]
    crashes: tuple = ()      # tuple[CrashStorm, ...]

    def validate(self) -> None:
        for p in self.partitions:
            if len(p.groups) != self.n_peers:
                raise ValueError(
                    f"partition groups has {len(p.groups)} entries for "
                    f"{self.n_peers} peers"
                )
            if p.rounds <= 0:
                raise ValueError("partition window must be >= 1 round")
        for c in self.crashes:
            if c.rounds <= 0:
                raise ValueError("crash window must be >= 1 round")
            for i in c.peers:
                if not (0 <= i < self.n_peers):
                    raise ValueError(f"crash peer {i} out of range")

    # -- per-round mask compilation ---------------------------------------

    def link_deny_at(self, tick: int, nbr: np.ndarray) -> np.ndarray | None:
        """[N, K] bool forced-down mask active at ``tick`` (None when no
        partition window is active — callers may skip the argument-free
        round). ``nbr`` is the topology's neighbor table; padding slots
        (-1) are left False (they carry nothing anyway)."""
        nbr = np.asarray(nbr)
        deny = None
        for p in self.partitions:
            if not (p.start <= tick < p.end):
                continue
            g = np.asarray(p.groups, np.int32)
            cross = g[:, None] != g[np.clip(nbr, 0, None)]
            cross &= nbr >= 0
            deny = cross if deny is None else (deny | cross)
        return deny

    def up_at(self, tick: int) -> np.ndarray:
        """[N] bool liveness row active at ``tick`` (True = up)."""
        up = np.ones((self.n_peers,), bool)
        for c in self.crashes:
            if c.start <= tick < c.end:
                up[list(c.peers)] = False
        return up

    @property
    def scheduled(self) -> bool:
        """True when the scenario carries partition windows (the built
        step then needs ChaosConfig(scheduled=True))."""
        return bool(self.partitions)

    @property
    def dynamic(self) -> bool:
        """True when the scenario carries crash storms (the build then
        needs dynamic_peers=True)."""
        return bool(self.crashes)

    def horizon(self) -> int:
        """Last tick any window is active (run at least this long plus
        the recovery tail you want to measure)."""
        ends = [p.end for p in self.partitions] + [c.end for c in self.crashes]
        return max(ends) if ends else 0

    # -- reporting ---------------------------------------------------------

    def events(self) -> list:
        """The schedule as (tick, kind, detail) rows — the host-side
        PartitionStart/PartitionHeal/CrashStart/CrashHeal event stream
        (the chaos plane's scheduled faults are host-known, so these
        are exact; generator flaps are counted on device via the
        LINK_DOWN counter instead)."""
        out = []
        for i, p in enumerate(self.partitions):
            n_groups = len(set(p.groups))
            out.append((p.start, "PartitionStart",
                        {"partition": i, "groups": n_groups}))
            out.append((p.end, "PartitionHeal", {"partition": i}))
        for i, c in enumerate(self.crashes):
            out.append((c.start, "CrashStart",
                        {"storm": i, "peers": len(c.peers)}))
            out.append((c.end, "CrashHeal", {"storm": i}))
        return sorted(out, key=lambda e: (e[0], e[1]))

    def scenario_hash(self) -> str:
        """Stable short hash of the whole schedule (artifact chaos
        fingerprint field)."""
        h = hashlib.sha256()
        h.update(repr((self.n_peers,
                       [(p.start, p.rounds, tuple(p.groups))
                        for p in self.partitions],
                       [(c.start, c.rounds, tuple(c.peers))
                        for c in self.crashes])).encode())
        return h.hexdigest()[:12]


def halves(n: int) -> tuple:
    """The canonical 2-group split: peers [0, n/2) vs [n/2, n)."""
    return tuple(int(i >= n // 2) for i in range(n))


def two_group_partition(n: int, start: int, rounds: int,
                        groups: tuple | None = None) -> Scenario:
    """Convenience: one partition window splitting the net in half."""
    return Scenario(
        n_peers=n,
        partitions=(Partition(start=start, rounds=rounds,
                              groups=groups or halves(n)),),
    )
