"""Vectorized link-fault injection (the chaos plane's generators).

GossipSub exists to stay reliable on unreliable networks — the IHAVE/
IWANT lazy-gossip machinery recovers eagerly-lost messages and the mesh
self-heals after failure — yet the simulator's wire was perfectly
lossless outside queue-cap overflow. This module supplies the missing
network faults as batched array programs:

  * **link flaps** — a per-link per-round outage mask applied once at
    the receiver gather on the edge involution. TCP semantics: the
    WHOLE link (data plane + control head, both directions) drops for
    the round — a link is a connection, not a per-message lottery; the
    reference's transport either delivers an RPC or the connection
    stalls for the whole exchange.
  * **generators** — i.i.d. (each link down with prob ``loss_rate``
    per round, memoryless) and Gilbert–Elliott (a two-state good/bad
    Markov chain per link: ``ge_p_down`` good→bad, ``ge_p_up``
    bad→good; the bad state is a full outage — bursty, correlated
    loss, the degraded-network shape the v1.1 evaluation methodology
    (arxiv 2007.02754) is built on).
  * **schedules** — ``scheduled=True`` steps additionally take a
    ``link_deny [N, K]`` bool argument (True = forced down), the
    hook the Scenario compiler (chaos/scenario.py) feeds partition/
    heal windows through.

Randomness: masks are pure functions of (sim PRNG key, tick) — a
counter-mode integer hash over the **canonical undirected link id**
(min(i, j), max(i, j)) seeded from ``jax.random.key_data(fold_in(key,
CHAOS_TAG))``. Consequences, all deliberate:

  * **symmetric by construction**: both directions of a link compute
    the same (lo, hi, tick) input, so the whole link drops — no extra
    cross-peer gather to symmetrize (the mask adds ZERO halo permutes
    to the sharded step; the projection's permute budget is unchanged
    even with chaos on).
  * **checkpoint-exact resume**: the key and tick are both in every
    checkpoint, so a restored run reproduces the exact fault sequence
    — the i.i.d. generator needs no device state at all, and the
    Gilbert–Elliott chain's only state is its [N, K] bad plane
    (state.ChaosState, carried in SimState and checkpointed).

Edge-layout composition (round 15): the [N, K] masks this module
produces compose with BOTH exchange layouts for free — routers AND
them into the [N, K, W] edge mask before the shared delivery engine,
and the CSR path (ops/csr.py) packs that composed mask onto the
present edges (``pack_edges``), so chaos adds zero layout-specific
code and the dense-vs-CSR parity suite runs with chaos ON
(tests/test_csr.py).

Static elision contract: a build whose ``ChaosConfig`` is ``None`` (or
``enabled`` is False) traces exactly the code it traced before the
chaos plane existed — no masks, no counters, no extra ops. Pinned by
tests/test_chaos.py (bit-exact state trees) and ``make chaos-smoke``
(compiled HLO kernel census vs the committed PERF_SMOKE baseline).
"""

from __future__ import annotations

import dataclasses

import jax
import jax.numpy as jnp

#: fold_in tag deriving the chaos seed from the sim PRNG key — distinct
#: from the gater (0x6A7E) and fanout (0xFA40) subsystem tags
CHAOS_TAG = 0xC4A05

_C1 = 0x85EBCA6B
_C2 = 0xC2B2AE35
_GOLD = 0x9E3779B9


class ChaosConfigError(ValueError):
    """Raised by ChaosConfig.validate() on invalid parameters."""


@dataclasses.dataclass(frozen=True)
class ChaosConfig:
    """Static (build-time) chaos-plane configuration.

    ``generator`` selects the random fault process:
      * ``"iid"`` — each live link is down with prob ``loss_rate``
        each round, independently (memoryless flaps);
      * ``"ge"`` — Gilbert–Elliott: per-link two-state chain, good→bad
        with ``ge_p_down`` and bad→good with ``ge_p_up`` per round;
        a bad link is fully down (bursty outages whose mean burst
        length is 1/ge_p_up rounds).

    ``scheduled=True`` makes the built step take an extra trailing
    ``link_deny [N, K]`` bool argument (True = link forced down this
    round/phase) — the Scenario partition/heal hook. It composes with
    either generator (deny OR generator-down drops the link).
    """

    generator: str = "iid"
    loss_rate: float = 0.0
    ge_p_down: float = 0.0
    ge_p_up: float = 0.25
    scheduled: bool = False

    def validate(self) -> None:
        if self.generator not in ("iid", "ge"):
            raise ChaosConfigError(
                f"unknown chaos generator {self.generator!r}; "
                "expected 'iid' or 'ge'"
            )
        for name in ("loss_rate", "ge_p_down", "ge_p_up"):
            v = getattr(self, name)
            if not (0.0 <= v <= 1.0):
                raise ChaosConfigError(f"{name} must be in [0, 1], got {v}")
        if self.generator == "ge" and self.ge_p_down > 0 and self.ge_p_up <= 0:
            raise ChaosConfigError(
                "ge_p_up must be > 0 when ge_p_down > 0 (links would "
                "never recover)"
            )

    @property
    def generator_enabled(self) -> bool:
        if self.generator == "ge":
            return self.ge_p_down > 0.0
        return self.loss_rate > 0.0

    @property
    def enabled(self) -> bool:
        """False ⇒ the build elides the chaos plane entirely."""
        return self.generator_enabled or self.scheduled

    @property
    def needs_state(self) -> bool:
        """The Gilbert–Elliott chain carries a per-link [N, K] bad
        plane in the state (state.ChaosState); i.i.d. and pure-schedule
        chaos are stateless."""
        return self.generator == "ge" and self.generator_enabled

    def fingerprint(self) -> dict:
        """The schema-v2 artifact self-description of this generator
        (perf/artifacts.py chaos block; scenario hash added by the
        runner)."""
        fp = {"generator": self.generator if self.generator_enabled else "off",
              "loss_rate": float(self.loss_rate),
              "scheduled": bool(self.scheduled)}
        if self.generator == "ge" and self.generator_enabled:
            fp["ge_p_down"] = float(self.ge_p_down)
            fp["ge_p_up"] = float(self.ge_p_up)
        return fp


def resolve(chaos: ChaosConfig | None) -> ChaosConfig | None:
    """Normalize a config to None when the plane is off (the single
    elision decision every engine shares). Validation runs FIRST — a
    typo'd generator name must raise, not silently elide the plane and
    run the experiment on a lossless wire."""
    if chaos is None:
        return None
    chaos.validate()
    return chaos if chaos.enabled else None


# ---------------------------------------------------------------------------
# counter-mode hash (murmur3 finalizer composition, uint32 wraparound)


def _mix(h: jax.Array) -> jax.Array:
    h = h ^ (h >> 16)
    h = h * jnp.uint32(_C1)
    h = h ^ (h >> 13)
    h = h * jnp.uint32(_C2)
    h = h ^ (h >> 16)
    return h


def chaos_seed(key: jax.Array) -> jax.Array:
    """Scalar u32 seed from the sim PRNG key (works under both threefry
    and unsafe_rbg key layouts; traced-safe)."""
    kd = jax.random.key_data(jax.random.fold_in(key, CHAOS_TAG))
    kd = kd.astype(jnp.uint32).reshape(-1)
    s = jnp.uint32(_GOLD)
    for i in range(kd.shape[0]):  # static, tiny (2 or 4 words)
        s = _mix(s ^ kd[i])
    return s


def _link_key_planes(nbr: jax.Array, topo=None):
    """The canonical symmetric link identity each draw hashes.

    Static topology (``topo=None``): the undirected PEER pair
    (min(i, j), max(i, j)) — the original keying, traced bit for bit.

    Dynamic overlay (``topo`` a state.TopoState, round 22): peer ids no
    longer identify a link (a rewired slot connects different peers
    over time, and a replaced peer's row must NOT inherit the old
    link's fault phase), so the key becomes the canonical SLOT pair
    (min/max of the flat slot and its involution partner) plus the two
    slots' write-epoch sum — slot×epoch re-keying: every rewire bumps
    an endpoint epoch, deterministically re-drawing that link's stream,
    while untouched links keep theirs. Both endpoint slots compute the
    same (lo, hi, eps), so symmetry still costs no extra structure; the
    epoch-partner read is ONE [N, K] i32 involution gather per round.
    Checkpoint-exact resume holds because (key, tick, topo planes) are
    all in the checkpoint."""
    if topo is None:
        n = nbr.shape[0]
        i = jnp.arange(n, dtype=jnp.int32)[:, None]
        j = jnp.clip(nbr, 0)
        lo = jnp.minimum(i, j).astype(jnp.uint32)
        hi = jnp.maximum(i, j).astype(jnp.uint32)
        return lo, hi, None
    n, k = topo.nbr.shape
    own = jnp.arange(n * k, dtype=jnp.int32).reshape(n, k)
    p = topo.edge_perm
    lo = jnp.minimum(own, p).astype(jnp.uint32)
    hi = jnp.maximum(own, p).astype(jnp.uint32)
    ep_partner = topo.epoch.reshape(-1)[p.reshape(-1)].reshape(n, k)
    eps = (topo.epoch + ep_partner).astype(jnp.uint32)
    return lo, hi, eps


def _link_uniform_keyed(seed, lo, hi, eps, tick, salt: int) -> jax.Array:
    h = _mix(seed ^ jnp.uint32(salt))
    h = h ^ (jnp.asarray(tick).astype(jnp.uint32) * jnp.uint32(_GOLD))
    u = _mix(h ^ (lo * jnp.uint32(_C1)))
    u = _mix(u ^ (hi * jnp.uint32(_C2)))
    if eps is not None:
        u = _mix(u ^ (eps * jnp.uint32(_GOLD)))
    return u


def link_uniform(seed: jax.Array, nbr: jax.Array, tick, salt: int,
                 topo=None) -> jax.Array:
    """[N, K] u32 per-LINK uniform draw for one round: both directions
    of an edge hash the same canonical link identity, so the result is
    symmetric over the edge involution by construction — no cross-peer
    gather needed (one epoch gather under a dynamic overlay; see
    ``_link_key_planes``). ``salt`` separates the independent streams
    (iid vs the two GE transition draws)."""
    lo, hi, eps = _link_key_planes(nbr, topo)
    return _link_uniform_keyed(seed, lo, hi, eps, tick, salt)


def _threshold(p: float) -> jnp.uint32:
    """u32 compare threshold for P(u < t) == p (clamped)."""
    return jnp.uint32(min(int(round(p * 4294967296.0)), 0xFFFFFFFF))


def iid_link_down(seed, nbr, tick, loss_rate: float, topo=None) -> jax.Array:
    """[N, K] bool: link down this round under the i.i.d. generator."""
    return (link_uniform(seed, nbr, tick, salt=0x11D, topo=topo)
            < _threshold(loss_rate))


def ge_advance(seed, nbr, tick, bad: jax.Array,
               p_down: float, p_up: float, topo=None) -> jax.Array:
    """One Gilbert–Elliott transition for every link: returns the new
    [N, K] bad plane (symmetric whenever ``bad`` is — transitions use
    symmetric per-link draws). Under a dynamic overlay the chain's
    [N, K] ``bad`` plane stays slot-resident across rewires — a rewired
    slot INHERITS its chain state for one round but its transition
    draws re-key immediately (slot×epoch), so streams decorrelate
    deterministically; the documented semantic is 'the replacement
    connection starts in the old connection's weather'."""
    lo, hi, eps = _link_key_planes(nbr, topo)
    go_down = (_link_uniform_keyed(seed, lo, hi, eps, tick, 0x6E0D)
               < _threshold(p_down))
    go_up = (_link_uniform_keyed(seed, lo, hi, eps, tick, 0x75E1)
             < _threshold(p_up))
    return jnp.where(bad, ~go_up, go_down)


def round_link_ok(chaos: ChaosConfig, seed, nbr, tick,
                  ge_bad: jax.Array | None,
                  link_deny: jax.Array | None,
                  topo=None):
    """The per-round link mask: ``(link_ok [N, K] bool, ge_bad')``.

    ``link_ok`` is True where the link carries traffic this round;
    callers AND it into the receiver-side gather masks (data plane and
    control head — TCP semantics: the whole link drops). ``ge_bad'``
    is the advanced chain state (unchanged input for non-GE
    generators). The composition order is deny ∨ generator-down.
    ``topo`` (a state.TopoState, round 22) switches the draws to the
    slot×epoch keying — pass the post-mutation plane so a rewired link
    re-keys the round it changes."""
    down = None
    if chaos.generator == "ge" and chaos.generator_enabled:
        assert ge_bad is not None, (
            "GE chaos needs ChaosState in the sim state — build it with "
            "SimState.init(..., chaos_ge=True) (GossipSubState.init does "
            "this from cfg.chaos)"
        )
        ge_bad = ge_advance(seed, nbr, tick, ge_bad,
                            chaos.ge_p_down, chaos.ge_p_up, topo=topo)
        down = ge_bad
    elif chaos.generator_enabled:
        down = iid_link_down(seed, nbr, tick, chaos.loss_rate, topo=topo)
    if link_deny is not None:
        deny = jnp.asarray(link_deny, bool)
        down = deny if down is None else (down | deny)
    if down is None:
        # scheduled build driven with link_deny=None this round
        link_ok = jnp.ones(nbr.shape, bool)
    else:
        link_ok = ~down
    return link_ok, ge_bad


def count_links_down(nbr: jax.Array, nbr_ok: jax.Array,
                     link_ok: jax.Array) -> jax.Array:
    """i32 scalar: UNDIRECTED live links down this round (each link
    counted once, at its lower-id endpoint) — the LINK_DOWN counter."""
    n = nbr.shape[0]
    i = jnp.arange(n, dtype=jnp.int32)[:, None]
    und = nbr_ok & ~link_ok & (i < nbr)
    return jnp.sum(und.astype(jnp.int32))
