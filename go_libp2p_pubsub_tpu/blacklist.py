"""Peer blacklists (reference blacklist.go:12-64).

Two host-side implementations with the reference's surface:
  MapBlacklist       — plain set
  TimeCachedBlacklist — entries expire after a TTL (time injectable for
                        tests, like the reference's timecache)

Enforcement points mirror pubsub.go: RPC ingress (1048-1060) and
connection admission (524-530, 636-639). In the vectorized engine the
enforcement is the `blacklist` mask consumed by the dynamic-peers step
(models/gossipsub.py set_blacklist); these classes are the host-side policy
objects an API user manipulates, and `mask()` lowers them onto the device.
"""

from __future__ import annotations

import time
from typing import Callable

import numpy as np


class MapBlacklist:
    def __init__(self):
        self._set: set[bytes] = set()

    def add(self, peer: bytes) -> bool:
        self._set.add(peer)
        return True

    def contains(self, peer: bytes) -> bool:
        return peer in self._set

    def remove(self, peer: bytes) -> None:
        self._set.discard(peer)


class TimeCachedBlacklist:
    """Blacklist whose entries lapse after `ttl` seconds."""

    def __init__(self, ttl: float, now: Callable[[], float] = time.monotonic):
        self.ttl = ttl
        self._now = now
        self._expiry: dict[bytes, float] = {}

    def add(self, peer: bytes) -> bool:
        self._expiry[peer] = self._now() + self.ttl
        return True

    def contains(self, peer: bytes) -> bool:
        exp = self._expiry.get(peer)
        if exp is None:
            return False
        if self._now() >= exp:
            del self._expiry[peer]
            return False
        return True

    def remove(self, peer: bytes) -> None:
        self._expiry.pop(peer, None)


def blacklist_mask(bl, peer_ids: list[bytes]) -> np.ndarray:
    """[N] bool device-lowerable mask from a host blacklist."""
    return np.array([bl.contains(p) for p in peer_ids], dtype=bool)
