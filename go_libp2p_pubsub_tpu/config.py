"""Validated parameter dataclasses for the TPU pubsub framework.

Mirrors the reference's three config mechanisms (survey §5): params structs
with ``validate()`` — GossipSubParams (gossipsub.go:62-199 with defaults at
gossipsub.go:31-59), PeerScoreParams / TopicScoreParams / PeerScoreThresholds
(score_params.go:12-268), PeerGaterParams (peer_gater.go:31-116) — plus the
package-level default vars, here class-level defaults.

Time base: the reference uses wall-clock `time.Duration`; the simulator is
tick-quantized (1 tick == 1 heartbeat interval by default, matching how the
reference already quantizes maintenance to heartbeat ticks: DirectConnectTicks,
OpportunisticGraftTicks, backoff slack gossipsub.go:1596). All durations here
are kept in **seconds** (the reference's semantic unit) and converted to ticks
via `ticks_for(seconds, heartbeat_interval)` when the device state is built;
each conversion rounds up so "at least this long" semantics survive
quantization.
"""

from __future__ import annotations

import dataclasses
import math
from dataclasses import dataclass, field
from typing import Callable, Dict, Optional

# ---------------------------------------------------------------------------
# helpers


def _bad(x: float) -> bool:
    """isInvalidNumber: NaN or Inf (score_params.go:291-293)."""
    return math.isnan(x) or math.isinf(x)


DEFAULT_DECAY_INTERVAL = 1.0  # seconds (score_params.go:271)
DEFAULT_DECAY_TO_ZERO = 0.01  # score_params.go:272


def score_parameter_decay(
    decay_seconds: float,
    base_seconds: float = DEFAULT_DECAY_INTERVAL,
    decay_to_zero: float = DEFAULT_DECAY_TO_ZERO,
) -> float:
    """Per-interval decay factor so a counter hits ``decay_to_zero`` after
    ``decay_seconds`` (score_params.go:277-287)."""
    ticks = float(int(decay_seconds / base_seconds))
    if ticks == 0.0:
        # Go's integer Duration division yields 1/0 = +Inf and
        # math.Pow(decayToZero, +Inf) = 0.0 (score_params.go:285-286); the
        # decay validators then reject 0.0 with a clear error.
        return 0.0
    return decay_to_zero ** (1.0 / ticks)


class ConfigError(ValueError):
    """Raised by validate() on invalid parameters (mirrors the reference's
    error returns from the validate() methods)."""


# ---------------------------------------------------------------------------
# GossipSub parameters


@dataclass
class GossipSubParams:
    """GossipSub router parameters (gossipsub.go:62-199; defaults :31-59).

    Durations are seconds. `validate()` enforces the documented constraints
    (Dout < Dlo, Dout <= D/2 — gossipsub.go:84-90; HistoryGossip <=
    HistoryLength — mcache.go:23-28).
    """

    # overlay degree parameters (gossipsub.go:33-37)
    D: int = 6
    Dlo: int = 5
    Dhi: int = 12
    Dscore: int = 4
    Dout: int = 2

    # gossip parameters (gossipsub.go:38-42,56-58)
    history_length: int = 5
    history_gossip: int = 3
    Dlazy: int = 6
    gossip_factor: float = 0.25
    gossip_retransmission: int = 3
    max_ihave_length: int = 5000
    max_ihave_messages: int = 10
    iwant_followup_time: float = 3.0  # seconds (gossipsub.go:58)

    # heartbeat (gossipsub.go:43-44); the heartbeat interval defines the tick
    heartbeat_interval: float = 1.0
    heartbeat_initial_delay: float = 0.1
    slow_heartbeat_warning: float = 0.1  # fraction of interval (gossipsub.go:258)

    # fanout / prune / connect (gossipsub.go:45-55)
    fanout_ttl: float = 60.0
    prune_peers: int = 16
    prune_backoff: float = 60.0
    unsubscribe_backoff: float = 10.0
    connectors: int = 8
    max_pending_connections: int = 128
    connection_timeout: float = 30.0
    direct_connect_ticks: int = 300
    direct_connect_initial_delay: float = 1.0
    opportunistic_graft_ticks: int = 60
    opportunistic_graft_peers: int = 2
    graft_flood_threshold: float = 10.0

    # v1.1 feature switches (gossipsub.go options WithPeerExchange/
    # WithFloodPublish, gossipsub.go:306-330)
    do_px: bool = False
    flood_publish: bool = False

    def validate(self) -> None:
        if self.D < 0 or self.Dlo < 0 or self.Dhi < self.Dlo or self.D < self.Dlo or self.D > self.Dhi:
            raise ConfigError(
                "invalid degree params; need 0 <= Dlo <= D <= Dhi, got "
                f"Dlo={self.Dlo} D={self.D} Dhi={self.Dhi}"
            )
        if self.Dscore < 0 or self.Dscore > self.D:
            raise ConfigError(
                "invalid Dscore; must be within [0, D], got "
                f"Dscore={self.Dscore} D={self.D}"
            )
        # Dout must be set below Dlo and must not exceed D/2 (gossipsub.go:89)
        if self.Dout >= self.Dlo or self.Dout > self.D // 2:
            raise ConfigError(
                "invalid Dout; must be < Dlo and <= D/2, got "
                f"Dout={self.Dout} Dlo={self.Dlo} D={self.D}"
            )
        # gossip slots cannot exceed history slots (mcache.go:23-28)
        if self.history_gossip > self.history_length:
            raise ConfigError("invalid mcache params; history_gossip must be <= history_length")
        if self.history_length <= 0 or self.history_gossip <= 0:
            raise ConfigError("invalid mcache params; history slots must be positive")
        if not (0.0 <= self.gossip_factor <= 1.0):
            raise ConfigError("invalid gossip_factor; must be in [0,1]")
        if self.heartbeat_interval <= 0:
            raise ConfigError("invalid heartbeat_interval; must be positive")
        if self.max_ihave_length <= 0 or self.max_ihave_messages <= 0:
            raise ConfigError("invalid IHAVE flood-protection caps; must be positive")
        if self.gossip_retransmission < 0:
            raise ConfigError("invalid gossip_retransmission; must be >= 0")


# ---------------------------------------------------------------------------
# Peer score parameters


@dataclass
class TopicScoreParams:
    """Per-topic score parameters (score_params.go:98-148).

    Weight-sign conventions enforced exactly as score_params.go:200-268:
    P1/P2 weights >= 0, P3/P3b/P4 weights <= 0.
    """

    topic_weight: float = 0.5

    # P1: time in mesh (score_params.go:102-108)
    time_in_mesh_weight: float = 1.0
    time_in_mesh_quantum: float = 1.0  # seconds
    time_in_mesh_cap: float = 3600.0

    # P2: first message deliveries (score_params.go:110-116)
    first_message_deliveries_weight: float = 1.0
    first_message_deliveries_decay: float = 0.5
    first_message_deliveries_cap: float = 2000.0

    # P3: mesh message delivery deficit (score_params.go:118-134)
    mesh_message_deliveries_weight: float = -1.0
    mesh_message_deliveries_decay: float = 0.5
    mesh_message_deliveries_cap: float = 100.0
    mesh_message_deliveries_threshold: float = 20.0
    mesh_message_deliveries_window: float = 0.01  # seconds
    mesh_message_deliveries_activation: float = 1.0  # seconds

    # P3b: sticky mesh failure penalty (score_params.go:136-140)
    mesh_failure_penalty_weight: float = -1.0
    mesh_failure_penalty_decay: float = 0.5

    # P4: invalid messages (score_params.go:142-147)
    invalid_message_deliveries_weight: float = -1.0
    invalid_message_deliveries_decay: float = 0.3

    def validate(self) -> None:
        if self.topic_weight < 0 or _bad(self.topic_weight):
            raise ConfigError("invalid topic weight; must be >= 0")
        # P1 (score_params.go:207-218)
        if self.time_in_mesh_quantum == 0:
            raise ConfigError("invalid time_in_mesh_quantum; must be non zero")
        if self.time_in_mesh_weight < 0 or _bad(self.time_in_mesh_weight):
            raise ConfigError("invalid time_in_mesh_weight; must be positive (or 0 to disable)")
        if self.time_in_mesh_weight != 0 and self.time_in_mesh_quantum <= 0:
            raise ConfigError("invalid time_in_mesh_quantum; must be positive")
        if self.time_in_mesh_weight != 0 and (self.time_in_mesh_cap <= 0 or _bad(self.time_in_mesh_cap)):
            raise ConfigError("invalid time_in_mesh_cap; must be positive")
        # P2 (score_params.go:221-229)
        if self.first_message_deliveries_weight < 0 or _bad(self.first_message_deliveries_weight):
            raise ConfigError("invalid first_message_deliveries_weight; must be positive (or 0 to disable)")
        if self.first_message_deliveries_weight != 0:
            if not (0.0 < self.first_message_deliveries_decay < 1.0) or _bad(self.first_message_deliveries_decay):
                raise ConfigError("invalid first_message_deliveries_decay; must be between 0 and 1")
            if self.first_message_deliveries_cap <= 0 or _bad(self.first_message_deliveries_cap):
                raise ConfigError("invalid first_message_deliveries_cap; must be positive")
        # P3 (score_params.go:232-248)
        if self.mesh_message_deliveries_weight > 0 or _bad(self.mesh_message_deliveries_weight):
            raise ConfigError("invalid mesh_message_deliveries_weight; must be negative (or 0 to disable)")
        if self.mesh_message_deliveries_weight != 0:
            if not (0.0 < self.mesh_message_deliveries_decay < 1.0) or _bad(self.mesh_message_deliveries_decay):
                raise ConfigError("invalid mesh_message_deliveries_decay; must be between 0 and 1")
            if self.mesh_message_deliveries_cap <= 0 or _bad(self.mesh_message_deliveries_cap):
                raise ConfigError("invalid mesh_message_deliveries_cap; must be positive")
            if self.mesh_message_deliveries_threshold <= 0 or _bad(self.mesh_message_deliveries_threshold):
                raise ConfigError("invalid mesh_message_deliveries_threshold; must be positive")
            if self.mesh_message_deliveries_activation < 1.0:
                raise ConfigError("invalid mesh_message_deliveries_activation; must be at least 1s")
        if self.mesh_message_deliveries_window < 0:
            raise ConfigError("invalid mesh_message_deliveries_window; must be non-negative")
        # P3b (score_params.go:252-257)
        if self.mesh_failure_penalty_weight > 0 or _bad(self.mesh_failure_penalty_weight):
            raise ConfigError("invalid mesh_failure_penalty_weight; must be negative (or 0 to disable)")
        if self.mesh_failure_penalty_weight != 0 and (
            not (0.0 < self.mesh_failure_penalty_decay < 1.0) or _bad(self.mesh_failure_penalty_decay)
        ):
            raise ConfigError("invalid mesh_failure_penalty_decay; must be between 0 and 1")
        # P4 (score_params.go:260-265)
        if self.invalid_message_deliveries_weight > 0 or _bad(self.invalid_message_deliveries_weight):
            raise ConfigError("invalid invalid_message_deliveries_weight; must be negative (or 0 to disable)")
        if not (0.0 < self.invalid_message_deliveries_decay < 1.0) or _bad(self.invalid_message_deliveries_decay):
            raise ConfigError("invalid invalid_message_deliveries_decay; must be between 0 and 1")


@dataclass
class PeerScoreParams:
    """Global peer-score parameters (score_params.go:53-96).

    ``topics`` maps topic-id -> TopicScoreParams; unscored topics contribute
    nothing (score.go:269-273). ``app_specific_score`` is the P5 injection
    point (score_params.go:62); in the vectorized engine it is evaluated on
    the host into a per-peer array.
    """

    topics: Dict[int, TopicScoreParams] = field(default_factory=dict)
    topic_score_cap: float = 0.0  # 0 = no cap (score_params.go:57-59)

    app_specific_score: Optional[Callable[[int], float]] = None
    app_specific_weight: float = 0.0

    # P6 (score_params.go:65-75)
    ip_colocation_factor_weight: float = 0.0
    ip_colocation_factor_threshold: int = 1
    # whitelist is modeled as a set of exempt ip-group ids (the sim's analogue
    # of IPColocationFactorWhitelist CIDR ranges)
    ip_colocation_factor_whitelist: frozenset = frozenset()

    # P7 (score_params.go:77-86)
    behaviour_penalty_weight: float = 0.0
    behaviour_penalty_threshold: float = 0.0
    behaviour_penalty_decay: float = 0.9

    decay_interval: float = DEFAULT_DECAY_INTERVAL  # seconds
    decay_to_zero: float = DEFAULT_DECAY_TO_ZERO
    retain_score: float = 3600.0  # seconds

    skip_app_specific: bool = False  # sim-only: allow omitting P5 callback

    def validate(self) -> None:
        for tid, tp in self.topics.items():
            try:
                tp.validate()
            except ConfigError as e:
                raise ConfigError(f"invalid score parameters for topic {tid}: {e}") from e
        if self.topic_score_cap < 0 or _bad(self.topic_score_cap):
            raise ConfigError("invalid topic score cap; must be positive (or 0 for no cap)")
        if self.app_specific_score is None and not self.skip_app_specific:
            raise ConfigError("missing application specific score function")
        if self.ip_colocation_factor_weight > 0 or _bad(self.ip_colocation_factor_weight):
            raise ConfigError("invalid ip_colocation_factor_weight; must be negative (or 0 to disable)")
        if self.ip_colocation_factor_weight != 0 and self.ip_colocation_factor_threshold < 1:
            raise ConfigError("invalid ip_colocation_factor_threshold; must be at least 1")
        if self.behaviour_penalty_weight > 0 or _bad(self.behaviour_penalty_weight):
            raise ConfigError("invalid behaviour_penalty_weight; must be negative (or 0 to disable)")
        if self.behaviour_penalty_weight != 0 and (
            not (0.0 < self.behaviour_penalty_decay < 1.0) or _bad(self.behaviour_penalty_decay)
        ):
            raise ConfigError("invalid behaviour_penalty_decay; must be between 0 and 1")
        if self.behaviour_penalty_threshold < 0 or _bad(self.behaviour_penalty_threshold):
            raise ConfigError("invalid behaviour_penalty_threshold; must be >= 0")
        if self.decay_interval < 1.0:
            raise ConfigError("invalid decay_interval; must be at least 1s")
        if not (0.0 < self.decay_to_zero < 1.0) or _bad(self.decay_to_zero):
            raise ConfigError("invalid decay_to_zero; must be between 0 and 1")
        # retain_score: 0 means no retention (score_params.go:196)


@dataclass
class PeerScoreThresholds:
    """Score thresholds (score_params.go:12-51)."""

    gossip_threshold: float = -10.0
    publish_threshold: float = -50.0
    graylist_threshold: float = -80.0
    accept_px_threshold: float = 10.0
    opportunistic_graft_threshold: float = 20.0

    def validate(self) -> None:
        if self.gossip_threshold > 0 or _bad(self.gossip_threshold):
            raise ConfigError("invalid gossip threshold; it must be <= 0")
        if self.publish_threshold > 0 or self.publish_threshold > self.gossip_threshold or _bad(self.publish_threshold):
            raise ConfigError("invalid publish threshold; it must be <= 0 and <= gossip threshold")
        if self.graylist_threshold > 0 or self.graylist_threshold > self.publish_threshold or _bad(self.graylist_threshold):
            raise ConfigError("invalid graylist threshold; it must be <= 0 and <= publish threshold")
        if self.accept_px_threshold < 0 or _bad(self.accept_px_threshold):
            raise ConfigError("invalid accept PX threshold; it must be >= 0")
        if self.opportunistic_graft_threshold < 0 or _bad(self.opportunistic_graft_threshold):
            raise ConfigError("invalid opportunistic grafting threshold; it must be >= 0")


# ---------------------------------------------------------------------------
# Peer gater parameters


@dataclass
class PeerGaterParams:
    """Peer gater (random-early-drop admission control) parameters
    (peer_gater.go:31-116; defaults :19-28)."""

    threshold: float = 0.33
    global_decay: float = field(default_factory=lambda: score_parameter_decay(120.0))
    source_decay: float = field(default_factory=lambda: score_parameter_decay(3600.0))
    decay_interval: float = DEFAULT_DECAY_INTERVAL
    decay_to_zero: float = DEFAULT_DECAY_TO_ZERO
    retain_stats: float = 6 * 3600.0
    quiet: float = 60.0
    duplicate_weight: float = 0.125
    ignore_weight: float = 1.0
    reject_weight: float = 16.0
    topic_delivery_weights: Dict[int, float] = field(default_factory=dict)

    def validate(self) -> None:
        # peer_gater.go:57-88
        if self.threshold <= 0:
            raise ConfigError("invalid threshold; must be > 0")
        if not (0.0 < self.global_decay < 1.0):
            raise ConfigError("invalid global_decay; must be between 0 and 1")
        if not (0.0 < self.source_decay < 1.0):
            raise ConfigError("invalid source_decay; must be between 0 and 1")
        if self.decay_interval < 1.0:
            raise ConfigError("invalid decay_interval; must be at least 1s")
        if not (0.0 < self.decay_to_zero < 1.0):
            raise ConfigError("invalid decay_to_zero; must be between 0 and 1")
        if self.quiet < 1.0:
            raise ConfigError("invalid quiet interval; must be at least 1s")
        if self.duplicate_weight <= 0:
            raise ConfigError("invalid duplicate_weight; must be > 0")
        if self.ignore_weight < 1:
            raise ConfigError("invalid ignore_weight; must be >= 1")
        if self.reject_weight < 1:
            raise ConfigError("invalid reject_weight; must be >= 1")


# ---------------------------------------------------------------------------
# Simulation-level parameters (the TPU build's own knobs; no reference
# counterpart — these size the device arrays)


SEEN_TTL = 120.0  # seconds; TimeCacheDuration pubsub.go:30


@dataclass
class SimParams:
    """Array sizing + time-base for the vectorized simulator.

    n_peers/n_topics/max_degree/max_topics_per_peer bound the dense state;
    msg_slots is the capacity of the rotating global message table (message
    ids are interned to slots; survey §7 hard-part (b)).
    """

    n_peers: int = 1024
    n_topics: int = 1
    max_degree: int = 32           # K: neighbor slots per peer
    max_topics_per_peer: int = 1   # S: subscribed-topic slots per peer
    msg_slots: int = 128           # M: concurrently-live message slots
    seen_ttl: float = SEEN_TTL     # pubsub.go:30 (120s TimeCacheDuration)
    # how many delivery (network-hop) rounds occur per heartbeat tick; the
    # reference's heartbeat is 1s while a network hop is ~ms, so multiple
    # hops per heartbeat. 1 => heartbeat every round (pure-maintenance bench).
    rounds_per_heartbeat: int = 1
    # validation delay in rounds (survey §7 hard-part (c)); 0 = inline
    validation_delay_rounds: int = 0
    seed: int = 0

    def validate(self) -> None:
        if self.n_peers <= 1:
            raise ConfigError("n_peers must be > 1")
        if self.n_topics < 1:
            raise ConfigError("n_topics must be >= 1")
        if not (0 < self.max_degree < self.n_peers):
            raise ConfigError("max_degree must be in (0, n_peers)")
        if not (0 < self.max_topics_per_peer <= self.n_topics):
            raise ConfigError("max_topics_per_peer must be in (0, n_topics]")
        if self.msg_slots < 1:
            raise ConfigError("msg_slots must be >= 1")
        if self.rounds_per_heartbeat < 1:
            raise ConfigError("rounds_per_heartbeat must be >= 1")

def ticks_for(seconds: float, heartbeat_interval: float) -> int:
    """Duration (s) -> heartbeat ticks under a given heartbeat interval;
    rounds up (see SimParams.ticks docstring)."""
    if seconds <= 0:
        return 0
    return max(1, math.ceil(seconds / heartbeat_interval))


def default_topic_score_params() -> TopicScoreParams:
    return TopicScoreParams()


def default_peer_score_params(n_topics: int = 1) -> PeerScoreParams:
    p = PeerScoreParams(
        topics={t: TopicScoreParams() for t in range(n_topics)},
        skip_app_specific=True,
        behaviour_penalty_weight=-1.0,
        behaviour_penalty_threshold=1.0,
        behaviour_penalty_decay=0.9,
        ip_colocation_factor_weight=-1.0,
        ip_colocation_factor_threshold=4,
    )
    return p


def replace(cfg, **kw):
    """dataclasses.replace passthrough, for fluent test configs."""
    return dataclasses.replace(cfg, **kw)
