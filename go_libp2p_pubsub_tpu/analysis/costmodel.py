"""costmodel — static device-cost auditor (docs/DESIGN.md §19).

The fifth static pass, and the first one that prices the machine. The
other four prove STRUCTURE — simlint (source), guards (trace), lift
(dataflow), hloaudit (lowered text) — but every cost claim in the repo
(CSR's power-law win, the telemetry/oracle overhead ceilings, the v5e-8
projection) rests on wall-clock timings from a noisy CPU container.
This pass walks the CLOSED JAXPR of every engine×layout build and
statically computes per-round

    flops          per-primitive arithmetic-op accounting (dot_general
                   2·out·K, reductions charge their input, elementwise
                   their output, shape/layout ops nothing)
    hbm_bytes      sum of operand+result bytes per primitive — the
                   UNFUSED-traffic upper bound (XLA fuses aggressively,
                   so true traffic is lower; the derived arithmetic
                   intensity is therefore a LOWER bound and the
                   roofline term built from it is conservative)
    halo_bytes     the AUDITED cross-peer movement: the ops/edges tally
                   seams armed during the trace (exactly the accounting
                   `make topo-smoke` measures — the seams the sharded
                   lowering turns into collective permutes)
    rng_bits       bits drawn from the PRNG (random_bits et al.;
                   impl-independent at jaxpr level — the impl rides the
                   key dtype, not the primitive)
    gather_bytes / scatter_bytes
                   bytes moved by real gather/scatter ops (the slow
                   path the banded-roll layout exists to avoid)
    collective_bytes
                   payload of explicit collectives (ppermute /
                   all_gather / all_to_all) — zero in single-device
                   traces; the rule exists so sharded jaxprs price
                   their wire bytes through the same table

with a two-point N-slope fit (the memstat pattern: every per-round
metric is affine in N at fixed K/M/r, so two trace points determine
``cost(N) = const + slope·N`` exactly) committed to ``COST_AUDIT.json``
under the byte-identical-reproduction gate (``COST_UPDATE=1``
rewrites).

Hard contracts (each tripped by a doctored-jaxpr negative test in
tests/test_costmodel.py):

  halo-density   on a power-law topology the csr/dense halo_bytes
                 ratio EQUALS the graph density E/(N·K) — the whole
                 sparse-plane argument, now a static theorem instead of
                 a measured ratio;
  halo-measured  the model's halo_bytes equals the measured
                 ``ops/edges.tally_halo_bytes`` sum for the same build
                 (routed through ``edges.tally_step`` — the guarded
                 path that raises :class:`ops.edges.TallyCacheHit`
                 instead of silently reading zero off a cached jaxpr);
  floodsub-rng   floodsub draws ZERO rng bits (the reference defines
                 it with no randomness);
  telemetry-flops  the telemetry-on minus telemetry-off flop delta
                 stays under a static share ceiling of the off build;
  oracle-flops   the invariant checker's flops stay under a bounded
                 share of the step's flops (the "observers are cheap"
                 claim, priced statically).

Entry: ``scripts/cost_audit.py`` / ``make cost-audit`` (wired into
``make analyze``, ``make static`` and ``make quick``). The audit's
arithmetic intensity feeds ``perf/projection.py``'s v5e-8 roofline term
(disarmed by default — committed round-5 projections reproduce
byte-identically).
"""

from __future__ import annotations

import dataclasses
import json
import math
import os

#: the two trace points of the slope fit (any pair works — per-round
#: costs are affine in N at fixed K/M/r; these keep tracing fast)
N_LO, N_HI = 256, 512
#: audit array-sizing (the bench geometry: ring d=8 -> K=16, M=64)
AUDIT_M = 64
AUDIT_DEGREE_D = 2 * 8  # K of the ring builds
#: phase-engine sub-rounds / window dispatches of the audited builds
PHASE_R = 4
WINDOW_D = 4
PUB_WIDTH = 4

#: the power-law cell of the halo-density contract (a scaled-down
#: topo-smoke graph: same generator, same accounting seams)
POWERLAW_N = 512
POWERLAW_EXPONENT = 2.2
POWERLAW_D_MIN = 2
POWERLAW_MAX_DEGREE = 16
POWERLAW_SEED = 0

#: static contract ceilings — committed constants, not measurements:
#: the telemetry recorder may cost at most this share of the base
#: build's flops (measured ~1.4% at the audit shape; runtime gate is
#: telemetry-smoke's 15%), and the invariant checker at most this share
#: of one step's flops (measured ~10%; runtime gate is oracle-smoke's
#: 10% wall-clock — flops overstate the checker, whose planes fuse)
TELEMETRY_FLOP_SHARE_CEILING = 0.05
ORACLE_FLOP_SHARE_CEILING = 0.25

#: fusion contract (round 21): the fused csr build must price at most
#: this fraction of the unfused build's hbm bytes/round — at_hi AND
#: slope (the acceptance floor is a 20% cut; measured ~0.6). The phase
#: row's delivery is dense-commit (its csr traffic rides edge_gather),
#: so only the shared heartbeat fuses there: FALLING, no fixed cut.
FUSED_HBM_RATIO_CEILING = 0.8
PHASE_FUSED_HBM_RATIO_CEILING = 1.0

#: hbm-ceilings contract (round 21): every build row commits
#: ceiling = measured hbm_bytes/round at_hi × (1 + margin) into
#: COST_AUDIT.json; a later audit whose fresh at_hi exceeds the
#: COMMITTED ceiling trips the gate — a named cost regression, not
#: just a byte-identity diff
HBM_CEILING_MARGIN = 0.05

#: tolerance of the halo-density equality (the ratio is exact shape
#: arithmetic; the epsilon only absorbs float division)
HALO_DENSITY_TOL = 1e-9

AUDIT_NAME = "COST_AUDIT.json"

METRICS = ("flops", "hbm_bytes", "halo_bytes", "rng_bits",
           "gather_bytes", "scatter_bytes", "collective_bytes")

#: every engine×layout build the audit prices (the guards/hloaudit
#: registry plus the scanned window). Round 21: the csr/phase_csr rows
#: price the FUSED builds (sort-composite selection + capacity-bounded
#: segmented scan — the shipping configuration); the *_unfused rows
#: keep the legacy pairwise/log2(E) pricing live so the fusion
#: contract has a same-trace denominator.
AUDIT_BUILDS = ("gossipsub", "gossipsub_phase", "floodsub", "randomsub",
                "csr", "phase_csr", "csr_unfused", "phase_csr_unfused",
                "lifted", "window")


class CostContractViolation(Exception):
    """One failed cost contract; .build and .contract say which."""

    def __init__(self, build: str, contract: str, msg: str):
        super().__init__(f"[{build}] {contract}: {msg}")
        self.build = build
        self.contract = contract


# ---------------------------------------------------------------------------
# the jaxpr interpreter (pure accounting — unit-testable on tiny fns)


def _zero() -> dict:
    return {m: 0 for m in METRICS}


def _add(acc: dict, other: dict, scale: int = 1) -> None:
    for m in METRICS:
        acc[m] += other[m] * scale


def _aval_bytes(aval) -> int:
    """Byte size of one aval; PRNG keys normalize to 8 bytes/element
    (the memstat/STATE_SCHEMA normalization) so the audit is
    independent of the ambient jax_default_prng_impl."""
    dt = str(aval.dtype)
    if dt.startswith("key<"):
        return int(aval.size) * 8
    return int(aval.size) * aval.dtype.itemsize


def _var_bytes(v) -> int:
    aval = getattr(v, "aval", None)
    if aval is None or not hasattr(aval, "size"):
        return 0
    return _aval_bytes(aval)


#: primitives that only relayout/alias data — zero flops (their bytes
#: still count toward the unfused-traffic bound)
_SHAPE_OPS = frozenset({
    "broadcast_in_dim", "reshape", "transpose", "slice", "squeeze",
    "concatenate", "pad", "iota", "convert_element_type",
    "bitcast_convert_type", "copy", "rev", "expand_dims",
    "dynamic_slice", "dynamic_update_slice", "stop_gradient",
    "random_seed", "random_wrap", "random_unwrap", "random_split",
    "random_fold_in", "device_put",
})

#: reductions charge their INPUT size (one op per reduced element)
_REDUCE_OPS = frozenset({
    "reduce", "reduce_sum", "reduce_max", "reduce_min", "reduce_and",
    "reduce_or", "reduce_xor", "reduce_prod", "reduce_window",
    "argmax", "argmin", "reduce_precision",
})

_CUM_OPS = frozenset({"cumsum", "cummax", "cummin", "cumprod",
                      "cumlogsumexp"})

_SCATTER_OPS = frozenset({"scatter", "scatter-add", "scatter-mul",
                          "scatter-min", "scatter-max"})

_RNG_OPS = frozenset({"random_bits", "rng_bit_generator", "threefry2x32",
                      "random_gamma"})

#: explicit collectives: payload = operand bytes (the halo permutes the
#: sharded lowering emits price through here on an sharded trace)
_COLLECTIVE_OPS = frozenset({"ppermute", "all_gather", "all_to_all",
                             "psum", "pmax", "pmin"})


def _dot_flops(eqn) -> int:
    (lhs_c, _rhs_c), _batch = eqn.params["dimension_numbers"]
    lhs = eqn.invars[0].aval
    k = 1
    for d in lhs_c:
        k *= int(lhs.shape[d])
    out = eqn.outvars[0].aval
    return 2 * int(out.size) * k


def _leaf_cost(eqn) -> dict:
    """Accounting for one primitive equation (no sub-jaxprs)."""
    out = _zero()
    name = eqn.primitive.name
    in_bytes = sum(_var_bytes(v) for v in eqn.invars)
    out_bytes = sum(_var_bytes(v) for v in eqn.outvars)
    out["hbm_bytes"] = in_bytes + out_bytes
    first_out = eqn.outvars[0].aval if eqn.outvars else None
    out_size = int(getattr(first_out, "size", 0) or 0)

    if name in _SHAPE_OPS:
        return out
    if name == "dot_general":
        out["flops"] = _dot_flops(eqn)
        return out
    if name in _REDUCE_OPS:
        out["flops"] = sum(
            int(v.aval.size) for v in eqn.invars
            if hasattr(getattr(v, "aval", None), "size"))
        return out
    if name in _CUM_OPS:
        out["flops"] = out_size
        return out
    if name == "sort":
        n = max(int(eqn.invars[0].aval.shape[
            eqn.params.get("dimension", -1)]), 2)
        out["flops"] = sum(int(v.aval.size) for v in eqn.invars
                           if hasattr(getattr(v, "aval", None), "size")
                           ) * max(int(math.ceil(math.log2(n))), 1)
        return out
    if name == "gather":
        out["gather_bytes"] = out_bytes
        return out
    if name in _SCATTER_OPS:
        upd = eqn.invars[2].aval if len(eqn.invars) > 2 else None
        out["scatter_bytes"] = _aval_bytes(upd) if upd is not None else 0
        out["flops"] = int(getattr(upd, "size", 0) or 0)
        return out
    if name in _RNG_OPS:
        bits = 0
        for v in eqn.outvars:
            aval = getattr(v, "aval", None)
            dt = str(getattr(aval, "dtype", ""))
            if dt.startswith("key<"):
                continue
            if hasattr(aval, "size"):
                bits += int(aval.size) * aval.dtype.itemsize * 8
        out["rng_bits"] = bits
        return out
    if name in _COLLECTIVE_OPS:
        out["collective_bytes"] = in_bytes
        return out
    # default: elementwise — one op per output element
    out["flops"] = out_size
    return out


def _closed_jaxprs(v) -> list:
    """ClosedJaxpr values inside one eqn param (scalars pass through)."""
    import jax

    if isinstance(v, jax.core.ClosedJaxpr):
        return [v]
    if isinstance(v, (list, tuple)):
        out = []
        for item in v:
            out.extend(_closed_jaxprs(item))
        return out
    return []


def cost_jaxpr(jaxpr) -> dict:
    """Walk one ``jax.core.Jaxpr`` and return the metric totals.
    Control flow: ``scan`` multiplies its body by the static trip
    count, ``while`` charges cond+body ONCE (trip count is dynamic —
    the engines carry no unbounded whiles; the window's loop is a
    scan), ``cond`` charges its most-expensive branch (by flops)."""
    total = _zero()
    for eqn in jaxpr.eqns:
        name = eqn.primitive.name
        if name == "pjit":
            _add(total, cost_closed(eqn.params["jaxpr"]))
            continue
        if name == "scan":
            body = cost_closed(eqn.params["jaxpr"])
            _add(total, body, scale=int(eqn.params["length"]))
            continue
        if name == "while":
            _add(total, cost_closed(eqn.params["cond_jaxpr"]))
            _add(total, cost_closed(eqn.params["body_jaxpr"]))
            continue
        if name == "cond":
            branches = [cost_closed(b) for b in eqn.params["branches"]]
            _add(total, max(branches, key=lambda c: c["flops"]))
            continue
        if name in _REDUCE_OPS:
            # `reduce`'s monoid jaxpr is per-pair — the input-size
            # charge already prices it; don't double count
            _add(total, _leaf_cost(eqn))
            continue
        subs = []
        for v in eqn.params.values():
            subs.extend(_closed_jaxprs(v))
        if subs:
            # custom_jvp/vjp/remat-style calls: the sub-jaxpr IS the
            # computation
            for sub in subs[:1]:
                _add(total, cost_closed(sub))
            continue
        _add(total, _leaf_cost(eqn))
    return total


def cost_closed(closed) -> dict:
    return cost_jaxpr(closed.jaxpr)


def cost_of(fn, state, *, with_halo: bool = True) -> dict:
    """Cost one traced call ``fn(state)`` (bind everything else in a
    closure): the jaxpr walk for the primitive metrics plus — when
    ``with_halo`` — the ops/edges byte tally armed DURING this same
    trace, so ``halo_bytes`` is the audited seam accounting, not a
    primitive heuristic. ``fn`` must be an UNJITTED body (the
    :func:`ops.edges.tally_step` cache caveat)."""
    import jax

    from ..ops import edges

    entries: list = []
    if with_halo:
        with edges.tally_halo_bytes(entries):
            jpr = jax.make_jaxpr(fn)(state)
        if not entries:
            # the same footgun tally_step guards: a jit hidden inside
            # the costed callable can satisfy the trace from a cached
            # jaxpr without re-running the seams — committing a zero
            # halo fit would bless the broken number forever
            raise edges.TallyCacheHit(
                "cost_of recorded ZERO halo seams — a cached inner "
                "jaxpr skipped the ops/edges seams (pass the raw "
                "body), or the build moved nothing cross-peer; use "
                "with_halo=False for seam-free programs")
    else:
        jpr = jax.make_jaxpr(fn)(state)
    cost = cost_closed(jpr)
    missing = [k for k, b in entries if b is None]
    if missing:
        raise CostContractViolation(
            "trace", "halo-measured",
            f"halo seams without byte accounting: {missing} — a gather "
            "seam predates the round-18 moved-tensor tally")
    cost["halo_bytes"] = sum(b for _, b in entries)
    return cost


# ---------------------------------------------------------------------------
# build harnesses (raw bodies at a parametric N — the guards registry
# shapes, re-derived so the slope fit can move N)


def _pub_args(shape, n: int):
    import jax.numpy as jnp
    import numpy as np

    po = np.full(shape, -1, np.int32)
    po.reshape(-1)[0] = 0
    pt = np.zeros(shape, np.int32)
    pv = np.ones(shape, bool)
    del n
    return jnp.asarray(po), jnp.asarray(pt), jnp.asarray(pv)


def _ring_net(n: int, edge_layout: str = "dense"):
    from .. import graph
    from ..state import Net

    return Net.build(graph.ring_lattice(n, d=8),
                     graph.subscribe_all(n, 1), edge_layout=edge_layout)


@dataclasses.dataclass
class BuildCell:
    """One costable build: an unjitted ``call(state)`` closure, its
    initial state, and how many delivery rounds one call advances
    (``halo_rounds`` differs only for the window, whose scan body — and
    therefore the one armed tally — is traced once for D dispatches)."""

    name: str
    call: object
    state: object
    rounds_per_call: int
    halo_rounds_per_call: int


def build_cell(name: str, n: int) -> BuildCell:
    from ..perf.sweep import build_bench

    if name in ("gossipsub", "csr", "csr_unfused", "lifted"):
        layout = "csr" if name.startswith("csr") else None
        st, step, _, _ = build_bench(
            n, AUDIT_M, heartbeat_every=1, rounds_per_phase=1,
            edge_layout=layout, lift_scores=(name == "lifted"),
            fused=(name == "csr"))
        raw = getattr(step, "__wrapped__", step)
        args = _pub_args((PUB_WIDTH,), n)
        if name == "lifted":
            from .guards import lifted_plane_pair

            plane, _ = lifted_plane_pair()
            return BuildCell(name, lambda s: raw(s, *args, plane), st, 1, 1)
        return BuildCell(name, lambda s: raw(s, *args), st, 1, 1)
    if name in ("gossipsub_phase", "phase_csr", "phase_csr_unfused"):
        st, step, _, _ = build_bench(
            n, AUDIT_M, heartbeat_every=PHASE_R, rounds_per_phase=PHASE_R,
            edge_layout=("csr" if name.startswith("phase_csr") else None),
            fused=(name == "phase_csr"))
        raw = getattr(step, "__wrapped__", step)
        args = _pub_args((PHASE_R, PUB_WIDTH), n)
        return BuildCell(
            name, lambda s: raw(s, *args, do_heartbeat=True), st,
            PHASE_R, PHASE_R)
    if name == "floodsub":
        from ..models.floodsub import floodsub_step
        from ..state import SimState

        net = _ring_net(n)
        raw = floodsub_step.__wrapped__
        st = SimState.init(n, AUDIT_M, k=net.max_degree)
        args = _pub_args((PUB_WIDTH,), n)
        return BuildCell(name, lambda s: raw(net, s, *args), st, 1, 1)
    if name == "randomsub":
        from ..models.randomsub import make_randomsub_step
        from ..state import SimState

        net = _ring_net(n)
        step = make_randomsub_step(net)
        raw = getattr(step, "__wrapped__", step)
        st = SimState.init(n, AUDIT_M, k=net.max_degree)
        args = _pub_args((PUB_WIDTH,), n)
        return BuildCell(name, lambda s: raw(s, *args), st, 1, 1)
    if name == "window":
        import jax.numpy as jnp
        import numpy as np

        from ..driver import make_window
        from ..models.floodsub import floodsub_step
        from ..state import SimState

        net = _ring_net(n)

        def stepped(st, po, pt, pv):
            # the RAW body, so the window's scan trace re-runs the
            # tally seams (a jitted inner call could hit a cached
            # jaxpr and tally nothing)
            return floodsub_step.__wrapped__(net, st, po, pt, pv)

        win = make_window(stepped)
        raw = getattr(win, "__wrapped__", win)
        st = SimState.init(n, AUDIT_M, k=net.max_degree)
        po = np.full((WINDOW_D, PUB_WIDTH), -1, np.int32)
        po[:, 0] = 0
        xs = (jnp.asarray(po),
              jnp.zeros((WINDOW_D, PUB_WIDTH), jnp.int32),
              jnp.ones((WINDOW_D, PUB_WIDTH), bool))
        # the scan body (and its armed tally) traces ONCE for the
        # whole window: jaxpr metrics amortize over D dispatches, the
        # tally is already per-dispatch
        return BuildCell(name, lambda s: raw(s, xs), st, WINDOW_D, 1)
    raise ValueError(f"unknown build {name!r}; expected one of "
                     f"{AUDIT_BUILDS}")


def per_round_cost(cell: BuildCell) -> dict:
    """Per-ROUND metrics of one build cell (phase/window calls amortize
    their cadence)."""
    cost = cost_of(cell.call, cell.state)
    out = {}
    for m in METRICS:
        div = (cell.halo_rounds_per_call if m == "halo_bytes"
               else cell.rounds_per_call)
        out[m] = cost[m] / div if div != 1 else cost[m]
    return out


# ---------------------------------------------------------------------------
# contracts (pure functions over costed numbers — the negative tests
# feed them doctored-jaxpr costs)


def check_floodsub_rng(build: str, cost: dict) -> None:
    """floodsub must draw ZERO rng bits — the reference defines it
    with no randomness (the same contract hloaudit pins on the lowered
    text; this one holds at jaxpr level, PRNG-impl-independent)."""
    if cost["rng_bits"] != 0:
        raise CostContractViolation(
            build, "floodsub-rng",
            f"{cost['rng_bits']} rng bits in a program the reference "
            "defines with no randomness — a sampler leaked in")


def check_halo_density(dense_halo: float, csr_halo: float,
                       density: float, *,
                       tol: float = HALO_DENSITY_TOL) -> float:
    """csr/dense halo-bytes ratio must EQUAL the topology density
    E/(N·K) — flat [E] planes cross the seams where dense moves the
    full [N,K] capacity; any deviation means a seam moves bytes that
    do not scale with the edge count."""
    if dense_halo <= 0:
        raise CostContractViolation(
            "powerlaw_dense", "halo-density",
            "dense build moved zero halo bytes — the tally seams are "
            "not firing")
    ratio = csr_halo / dense_halo
    if abs(ratio - density) > tol:
        raise CostContractViolation(
            "powerlaw_csr", "halo-density",
            f"csr/dense halo-bytes ratio {ratio:.9f} != topology "
            f"density {density:.9f} — the sparse layout's wire bytes "
            "stopped tracking the edge count")
    return ratio


def check_halo_measured(build: str, model_halo: float,
                        measured_halo: float) -> None:
    """The cost model's halo_bytes must equal the MEASURED
    ``tally_halo_bytes`` sum for the same build (the topo-smoke
    accounting, routed through ``edges.tally_step`` — the guarded
    path)."""
    if model_halo != measured_halo:
        raise CostContractViolation(
            build, "halo-measured",
            f"model halo_bytes {model_halo} != measured tally "
            f"{measured_halo} — the cost trace and the audited seams "
            "disagree (cached jaxpr, or a seam outside the trace)")


def check_telemetry_flops(off_flops: float, on_flops: float, *,
                          ceiling: float = TELEMETRY_FLOP_SHARE_CEILING
                          ) -> float:
    """The telemetry recorder's flop delta must stay under the static
    share ceiling of the base build."""
    if off_flops <= 0:
        raise CostContractViolation(
            "telemetry", "telemetry-flops",
            "telemetry-off build costs zero flops — broken cell")
    share = (on_flops - off_flops) / off_flops
    if share > ceiling:
        raise CostContractViolation(
            "telemetry", "telemetry-flops",
            f"telemetry-on flop delta is {share:.4f} of the off build "
            f"(> static ceiling {ceiling}) — the recorder stopped "
            "being a cheap observer")
    return share


def check_oracle_flops(step_flops: float, checker_flops: float, *,
                       ceiling: float = ORACLE_FLOP_SHARE_CEILING
                       ) -> float:
    """The folded invariant checker's flops must stay under a bounded
    share of one step's flops — observers never dominate the work."""
    if step_flops <= 0:
        raise CostContractViolation(
            "oracle", "oracle-flops",
            "step build costs zero flops — broken cell")
    share = checker_flops / step_flops
    if share > ceiling:
        raise CostContractViolation(
            "oracle", "oracle-flops",
            f"invariant checker costs {share:.4f} of a step's flops "
            f"(> static ceiling {ceiling}) — the oracle plane stopped "
            "being a cheap observer")
    return share


def check_fused_hbm(build: str, fused: dict, unfused: dict, *,
                    ceiling: float = FUSED_HBM_RATIO_CEILING) -> dict:
    """The fused build's hbm_bytes/round must price at most ``ceiling``
    × the unfused build's — on the at_hi point AND the N-slope (both
    fit rows are ``per_round['hbm_bytes']``). The fused path exists to
    move fewer bytes; a composite that stops cutting traffic is a
    regression even while staying bit-exact."""
    out = {}
    for field in ("at_hi", "slope"):
        f, u = fused["hbm_bytes"][field], unfused["hbm_bytes"][field]
        if u <= 0:
            raise CostContractViolation(
                build, "fused-hbm",
                f"unfused hbm_bytes {field} is {u} — broken cell")
        ratio = f / u
        if ratio > ceiling or ratio >= 1.0:
            raise CostContractViolation(
                build, "fused-hbm",
                f"fused/unfused hbm_bytes {field} ratio {ratio:.4f} "
                f"(ceiling {ceiling}) — the fused build stopped "
                "cutting traffic")
        out[field] = ratio
    return out


def hbm_ceilings(builds: dict, *,
                 margin: float = HBM_CEILING_MARGIN) -> dict:
    """Per-build hbm_bytes/round ceilings from this audit's measured
    at_hi points — the numbers COMMITTED into COST_AUDIT.json that
    ``check_hbm_ceilings`` gates later runs against."""
    return {name: row["per_round"]["hbm_bytes"]["at_hi"] * (1 + margin)
            for name, row in builds.items()}


def check_hbm_ceilings(committed: dict, builds: dict) -> None:
    """Every fresh build row's hbm_bytes/round at_hi must stay under
    the COMMITTED ceiling — the cost-regression gate of ``make
    cost-audit`` (byte-identity says "something moved"; this says
    "the byte budget REGRESSED, in this build, past the margin")."""
    for name, row in builds.items():
        if name not in committed:
            continue  # a new build has no committed budget yet
        fresh = row["per_round"]["hbm_bytes"]["at_hi"]
        if fresh > committed[name]:
            raise CostContractViolation(
                name, "hbm-ceiling",
                f"hbm_bytes/round at N_HI is {fresh:.6g}, over the "
                f"committed ceiling {committed[name]:.6g} — the device "
                "program grew its byte budget (review, then "
                "COST_UPDATE=1 to re-commit)")


# ---------------------------------------------------------------------------
# contract cells (extra builds the headline registry doesn't carry)


def _powerlaw_pair():
    """(dense_cost, csr_cost, density, measured) of the scaled-down
    topo-smoke cell: floodsub on one power-law edge list, both
    layouts. ``measured`` maps layout -> the tally_halo_bytes sum via
    the guarded ``edges.tally_step`` path."""
    from .. import graph, topo
    from ..models.floodsub import floodsub_step
    from ..ops import edges
    from ..state import SimState

    el = topo.powerlaw(POWERLAW_N, exponent=POWERLAW_EXPONENT,
                       d_min=POWERLAW_D_MIN,
                       max_degree=POWERLAW_MAX_DEGREE, seed=POWERLAW_SEED)
    subs = graph.subscribe_all(POWERLAW_N, 1)
    _t, net_d, net_c = topo.build_nets(el, subs,
                                       max_degree=POWERLAW_MAX_DEGREE)
    density = net_c.n_edges / float(POWERLAW_N * net_d.max_degree)
    args = _pub_args((PUB_WIDTH,), POWERLAW_N)
    raw = floodsub_step.__wrapped__
    out = {}
    measured = {}
    for layout, net in (("dense", net_d), ("csr", net_c)):
        st = SimState.init(POWERLAW_N, AUDIT_M, k=net.max_degree,
                           n_edges=net.n_edges)
        out[layout] = cost_of(lambda s: raw(net, s, *args), st)
        # the measured cross-check goes through the GUARDED tally path
        # (tally_step raises TallyCacheHit instead of reading zero)
        tally = edges.tally_step(
            floodsub_step,
            SimState.init(POWERLAW_N, AUDIT_M, k=net.max_degree,
                          n_edges=net.n_edges),
            args, {}, net=net, count_bytes=True)
        measured[layout] = sum(b for _, b in tally if b is not None)
    return out["dense"], out["csr"], density, measured


def _telemetry_pair():
    """(off_flops, on_flops) of the bench gossipsub step with the
    per-round telemetry recorder off/on at the audit shape."""
    from ..perf.sweep import build_bench
    from ..telemetry import TelemetryConfig

    flops = []
    for tcfg in (None, TelemetryConfig(rows=8, tracked=(0, 7))):
        st, step, _, _ = build_bench(
            N_LO, AUDIT_M, heartbeat_every=1, rounds_per_phase=1,
            telemetry=tcfg, count_events=True)
        raw = getattr(step, "__wrapped__", step)
        args = _pub_args((PUB_WIDTH,), N_LO)
        flops.append(cost_of(lambda s: raw(s, *args),
                             st, with_halo=False)["flops"])
    return flops[0], flops[1]


def _oracle_pair():
    """(step_flops, checker_flops) of the guard-shape gossipsub build
    and its full invariant checker."""
    import dataclasses as _dc

    import jax.numpy as jnp

    from ..config import GossipSubParams, PeerScoreThresholds
    from ..models.gossipsub import (
        GossipSubConfig,
        GossipSubState,
        make_gossipsub_step,
    )
    from ..oracle import invariants
    from ..perf.sweep import bench_score_params

    net = _ring_net(N_LO)
    _tp, sp = bench_score_params("default", 1)
    cfg = GossipSubConfig.build(
        _dc.replace(GossipSubParams(), flood_publish=False),
        PeerScoreThresholds(), score_enabled=True)
    cfg = _dc.replace(cfg, count_events=False, fanout_slots=0)
    st = GossipSubState.init(net, AUDIT_M, cfg, score_params=sp)
    step = make_gossipsub_step(cfg, net, score_params=sp)
    raw = getattr(step, "__wrapped__", step)
    args = _pub_args((PUB_WIDTH,), N_LO)
    step_flops = cost_of(lambda s: raw(s, *args), st,
                         with_halo=False)["flops"]

    checker, _names = invariants.make_checker("gossipsub", net, cfg)
    craw = getattr(checker, "__wrapped__", checker)
    prev = jnp.zeros_like(getattr(st, "core", st).events)
    due = jnp.asarray(invariants.due_vector(), jnp.int32)
    checker_flops = cost_of(lambda s: craw(s, prev, due), st,
                            with_halo=False)["flops"]
    return step_flops, checker_flops


# ---------------------------------------------------------------------------
# the audit artifact


def _fit_rows(lo: dict, hi: dict) -> dict:
    rows = {}
    for m in METRICS:
        a, b = lo[m], hi[m]
        slope = (b - a) / float(N_HI - N_LO)
        const = a - slope * N_LO
        rows[m] = {"at_lo": a, "at_hi": b,
                   "slope": slope, "const": const}
    return rows


def eval_fit(rows: dict, metric: str, n: int) -> float:
    """``const + slope·N`` of one committed fit row — the projection's
    read path (perf.projection roofline term)."""
    r = rows[metric]
    return float(r["const"]) + float(r["slope"]) * float(n)


def build_audit() -> dict:
    """The full audit: per-build slope fits + the contract block.
    Deterministic trace arithmetic — committed COST_AUDIT.json must
    reproduce byte-identical (the MEM_AUDIT pattern)."""
    builds = {}
    for name in AUDIT_BUILDS:
        lo = per_round_cost(build_cell(name, N_LO))
        hi = per_round_cost(build_cell(name, N_HI))
        rows = _fit_rows(lo, hi)
        builds[name] = {
            "per_round": rows,
            "arithmetic_intensity_at_hi": (
                hi["flops"] / hi["hbm_bytes"] if hi["hbm_bytes"] else 0.0),
        }

    contracts: dict = {}

    check_floodsub_rng(
        "floodsub", {m: builds["floodsub"]["per_round"][m]["at_hi"]
                     for m in METRICS})
    contracts["floodsub_rng"] = {
        "rng_bits": builds["floodsub"]["per_round"]["rng_bits"]["at_hi"],
        "pass": True,
    }

    dense, csr, density, measured = _powerlaw_pair()
    for layout, cost in (("dense", dense), ("csr", csr)):
        check_halo_measured(f"powerlaw_{layout}", cost["halo_bytes"],
                            measured[layout])
    ratio = check_halo_density(dense["halo_bytes"], csr["halo_bytes"],
                               density)
    contracts["halo_density"] = {
        "n_peers": POWERLAW_N,
        "density": density,
        "dense_halo_bytes": dense["halo_bytes"],
        "csr_halo_bytes": csr["halo_bytes"],
        "ratio": ratio,
        "measured_tally_bytes": measured,
        "pass": True,
    }

    off_flops, on_flops = _telemetry_pair()
    tshare = check_telemetry_flops(off_flops, on_flops)
    contracts["telemetry_flops"] = {
        "off_flops": off_flops, "on_flops": on_flops,
        "share": tshare, "ceiling": TELEMETRY_FLOP_SHARE_CEILING,
        "pass": True,
    }

    step_flops, checker_flops = _oracle_pair()
    oshare = check_oracle_flops(step_flops, checker_flops)
    contracts["oracle_flops"] = {
        "step_flops": step_flops, "checker_flops": checker_flops,
        "share": oshare, "ceiling": ORACLE_FLOP_SHARE_CEILING,
        "pass": True,
    }

    fusion = {}
    for fused_name, ceil in (("csr", FUSED_HBM_RATIO_CEILING),
                             ("phase_csr", PHASE_FUSED_HBM_RATIO_CEILING)):
        f_rows = builds[fused_name]["per_round"]
        u_rows = builds[f"{fused_name}_unfused"]["per_round"]
        ratios = check_fused_hbm(fused_name, f_rows, u_rows, ceiling=ceil)
        fusion[fused_name] = {
            "fused_hbm_at_hi": f_rows["hbm_bytes"]["at_hi"],
            "unfused_hbm_at_hi": u_rows["hbm_bytes"]["at_hi"],
            "ratio_at_hi": ratios["at_hi"],
            "ratio_slope": ratios["slope"],
            "ceiling": ceil,
        }
    contracts["fusion"] = {**fusion, "pass": True}

    contracts["hbm_ceilings"] = {
        "margin": HBM_CEILING_MARGIN,
        "ceilings": hbm_ceilings(builds),
        "pass": True,
    }

    return {
        "schema": 1,
        "note": ("static device-cost audit (analysis/costmodel.py; "
                 "COST_UPDATE=1 rewrites). Per-round metric fits are "
                 "const + slope*N from two trace points; hbm_bytes is "
                 "the unfused-traffic upper bound, halo_bytes the "
                 "audited ops/edges seam accounting."),
        "shape": {"n_lo": N_LO, "n_hi": N_HI, "msg_slots": AUDIT_M,
                  "k": AUDIT_DEGREE_D, "rounds_per_phase": PHASE_R,
                  "window_dispatches": WINDOW_D,
                  "pub_width": PUB_WIDTH},
        "builds": builds,
        "contracts": contracts,
    }


# ---------------------------------------------------------------------------
# byte-identity gate helpers (shared with the MEM/LIFT audit gates —
# the round-19 satellite: a failed reproduction must NAME the diverging
# key, not just say "mismatch")


def baseline_divergences(committed, fresh, prefix: str = "",
                         limit: int = 8) -> list:
    """JSON-path strings of every point where two parsed artifacts
    diverge (first ``limit``): ``builds.floodsub.per_round.flops.slope:
    <committed> != <fresh>``. Shared by the cost/mem/lift
    byte-identity gates so a stale artifact names its drift."""
    out: list = []
    _diverge(committed, fresh, prefix, out, limit)
    return out


def _diverge(a, b, path, out, limit) -> None:
    if len(out) >= limit:
        return
    if isinstance(a, dict) and isinstance(b, dict):
        for k in sorted(set(a) | set(b), key=str):
            p = f"{path}.{k}" if path else str(k)
            if k not in a:
                out.append(f"{p}: missing from committed artifact")
            elif k not in b:
                out.append(f"{p}: missing from this run")
            else:
                _diverge(a[k], b[k], p, out, limit)
            if len(out) >= limit:
                return
        return
    if isinstance(a, list) and isinstance(b, list):
        if len(a) != len(b):
            out.append(f"{path}: length {len(a)} != {len(b)}")
            return
        for i, (x, y) in enumerate(zip(a, b)):
            _diverge(x, y, f"{path}[{i}]", out, limit)
            if len(out) >= limit:
                return
        return
    if a != b:
        out.append(f"{path}: {a!r} != {b!r}")


def audit_path(repo_root: str | None = None) -> str:
    root = repo_root or os.path.dirname(os.path.dirname(os.path.dirname(
        os.path.abspath(__file__))))
    return os.path.join(root, AUDIT_NAME)


def dump_audit(payload: dict) -> str:
    return json.dumps(payload, indent=1, sort_keys=True) + "\n"
