"""simlint — repo-specific AST lint for the vectorized simulator.

pytest can only catch what a test executes; these rules catch the
failure modes that *trace fine and run wrong* (or run fine today and
recompile/corrupt silently after the next refactor). Every rule is
calibrated against this repo's idioms — plane tensors, packed uint32
word algebra, the fold_in/counter-mode key discipline — so the clean
state is enforceable: the repo lints clean (tests/test_analysis.py
pins it) and intentional exceptions are committed to ``ALLOWLIST``.

Rule catalog (ids are stable; docs/DESIGN.md §9):

  traced-branch  Python ``if``/``while``/``assert`` whose test calls
                 ``jnp.*`` / ``jax.lax.*`` / ``jax.random.*`` in device
                 scope (models/, ops/, score/, chaos/, state.py).
                 Branching on a traced value either fails at trace time
                 or — worse — silently bakes one branch into the
                 compiled program. Host-side numpy branching (e.g.
                 ops/edges.detect_banded) is untouched: the rule keys
                 on jnp-rooted calls, not method syntax. (Method-form
                 ``x.any()`` on tracers is the guard harness's job —
                 it raises at trace time.)
  host-sync      ``.item()`` / ``.tolist()`` / ``.block_until_ready()``
                 anywhere in device scope, plus ``np.asarray`` /
                 ``np.array`` / ``float()`` / ``int()`` / ``bool()``
                 inside *traced* functions (jit-decorated, jit-wrapped,
                 or the step/_round/_phase/body closures a ``make_*``
                 builder returns). Each is a device→host sync that
                 serializes the round loop — the reference's event-loop
                 equivalent of blocking the single writer goroutine.
  prng-key       ``jax.random`` sampler calls in device scope whose key
                 does not flow from ``fold_in``/``split`` (of the sim
                 key or a key-named parameter), fresh ``jax.random.key``
                 / ``PRNGKey`` constants inside traced functions, and
                 the same key name fed to two samplers in one function
                 (key reuse — correlated draws, the bug class the
                 counter-mode fault-hash scheme exists to avoid).
  word-dtype     bare Python-int literals in packed-word bitwise ops
                 (``& | ^ << >>``) in ops/bitset.py or any function
                 with word-plane parameters. Weak-int mixing is where
                 silent promotion corrupts uint32 planes the moment
                 someone swaps an operand to a strong int32 array; the
                 committed fix is explicit ``jnp.uint32`` literals.
  import-exec    ``jnp.*`` / ``jax.lax.*`` / ``jax.random.*`` executed
                 at import time (module or class body, outside any
                 function/lambda) anywhere in the package. Import-time
                 device execution breaks JAX_PLATFORMS forcing and the
                 virtual-device test harness, and hides compile cost in
                 import.
  config-hash    ``*Config`` dataclasses in device scope must be
                 ``frozen=True`` with hashable field types (no list/
                 dict/set/ndarray annotations): configs ride jit
                 ``static_argnames`` (floodsub_step's ``chaos``) and an
                 unhashable config turns every call into a TypeError —
                 or, with ``eq`` but broken ``hash``, a silent
                 recompile per call.
  ev-drain       every ``EV.*`` counter in trace/events.py must be (a)
                 referenced outside trace/ (someone accumulates it),
                 and (b) either emitted by trace/drain.py as a
                 ``TraceEvent.<NAME>`` record (proto-backed events) or
                 named in drain.py's counter-only documentation
                 (sim-only counters) — so no counter can silently stop
                 being drained or documented. Since the telemetry plane
                 (round 11) an EV accumulated into the per-round
                 timeline ALSO counts as drained: a sim-only counter
                 whose ``ev_<name>`` column is in telemetry/panel.py's
                 catalog is visible to every run report even if the
                 drain prose never names it.
  telemetry-panel  telemetry/panel.py's ``EV_METRICS`` catalog must
                 carry one ``ev_<name>`` column per ``EV`` member, in
                 enum order (the panel writes the whole delta vector by
                 position — a missing/misordered column silently
                 relabels every metric to its right), and every
                 recorded EV column must be in ``RECONCILED`` — a
                 recorded-but-never-reconciled metric is a timeline
                 that can drift from the drained counters unchecked.
  invariant-registry  every property registered in
                 oracle/invariants.py's ``@invariant(...)`` catalog
                 must declare a literal ``kind`` (safety|liveness), a
                 literal non-empty ``engines`` applicability tuple
                 drawn from the module's ``ENGINES``, a ``doc``
                 citation — and be referenced by name in a
                 tests/test_invariant*.py file (the seeded-violation
                 negative-test catalog; names quoted incidentally in
                 other test files do not count: a property nothing can
                 trip is a rubber stamp, the exact failure mode the
                 oracle plane exists to prevent).

  narrow-dtype   (round 23) every ``.astype`` to a sub-i32 integer
                 dtype in device scope must correspond, positionally
                 per file, to the declared manifest the range auditor
                 commits into ``RANGE_AUDIT.json``
                 (``narrow_astype_manifest`` — each entry carries its
                 range justification in analysis/ranges.py's
                 ``NARROW_ASTYPE_MANIFEST``). A new narrowing cast
                 without a committed range argument is exactly how the
                 next int16/int8 wrap ships; run ``make range-audit``
                 after extending the manifest.

  donated-reuse  (round 19 — the only CALL-SITE rule: it lints the
                 repo's tests/ and scripts/ trees, not the package)
                 reuse of a state tree after it was passed to a
                 donating jitted step/window — the documented container
                 footgun: donation deletes the old buffers, so a later
                 read crashes or reads garbage. ``st = step(st, …)``
                 rebinding is the sanctioned idiom; ``make_*``/
                 ``build_*`` constructors and ``on_*`` observer hooks
                 never donate and are exempt.

Allowlist: ``analysis/ALLOWLIST`` lines of ``<rule> <relpath>`` or
``<rule> <relpath>::<qualname>`` (``#`` comments). Entries match every
violation of that rule in that file (or function). Keep it short — an
allowlist entry is a reviewed, documented exception, not a mute button.
"""

from __future__ import annotations

import ast
import dataclasses
import os
import re

from .lift import name_copy_closure, single_assign_exprs

#: device-scope prefixes (package-relative): the code that runs inside
#: jitted steps or builds their constants
DEVICE_SCOPE = ("models/", "ops/", "score/", "chaos/", "state.py")

#: call roots that mean "this expression executes on device"
_JNP_ROOTS = ("jnp.", "jax.numpy.", "jax.lax.", "jax.random.", "lax.")

#: jax.random callables that produce/derive keys rather than sample
_KEY_FNS = {
    "key", "PRNGKey", "fold_in", "split", "key_data", "wrap_key_data",
    "key_impl", "clone",
}

#: nested-def names a make_* builder returns as its traced step
_TRACED_NESTED = {"step", "_round", "_phase", "body", "hb"}

#: host→device conversion callables flagged inside traced functions
_HOST_CONVERSIONS = {
    "np.asarray", "np.array", "numpy.asarray", "numpy.array",
    "float", "int", "bool",
}

#: attribute calls that force a device→host sync wherever they appear
_SYNC_METHODS = {"item", "tolist", "block_until_ready"}

#: files never linted (generated code)
_SKIP_DIRS = ("pb", "__pycache__")


@dataclasses.dataclass(frozen=True)
class Violation:
    rule: str
    rel: str       # package-relative path, e.g. "models/gossipsub.py"
    line: int
    qual: str      # enclosing def chain, "" at module level
    msg: str

    def format(self) -> str:
        where = f"{self.rel}:{self.line}"
        if self.qual:
            where += f" ({self.qual})"
        return f"[{self.rule}] {where}: {self.msg}"


def _walk_shallow(fn: ast.AST):
    """ast.walk that does NOT descend into nested function bodies — each
    def is analyzed exactly once, in its own scope (nested defs are
    yielded by _iter_functions separately)."""
    stack = list(ast.iter_child_nodes(fn))
    while stack:
        node = stack.pop()
        yield node
        if not isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
            stack.extend(ast.iter_child_nodes(node))


def _call_root(node: ast.AST) -> str:
    """Dotted-source prefix of a call's func, '' when not a plain chain."""
    try:
        return ast.unparse(node)
    except Exception:  # pragma: no cover - unparse of exotic nodes
        return ""


def _in_device_scope(rel: str) -> bool:
    return rel.startswith(DEVICE_SCOPE)


def _iter_functions(tree: ast.Module):
    """Yield (qualname, FunctionDef, parents) for every def, outermost
    first."""
    stack: list[tuple[str, ast.AST]] = [("", tree)]
    while stack:
        prefix, node = stack.pop()
        for child in ast.iter_child_nodes(node):
            if isinstance(child, (ast.FunctionDef, ast.AsyncFunctionDef)):
                qual = f"{prefix}.{child.name}" if prefix else child.name
                yield qual, child
                stack.append((qual, child))
            elif isinstance(child, ast.ClassDef):
                cq = f"{prefix}.{child.name}" if prefix else child.name
                stack.append((cq, child))


def _jitted_names(tree: ast.Module) -> set:
    """Function names wrapped by jax.jit at module level:
    ``jax.jit(step...)`` / ``jit(step...)`` call args."""
    names = set()
    for node in ast.walk(tree):
        if isinstance(node, ast.Call):
            root = _call_root(node.func)
            if root in ("jax.jit", "jit") and node.args:
                a0 = node.args[0]
                if isinstance(a0, ast.Name):
                    names.add(a0.id)
    return names


def _is_jit_decorated(fn: ast.FunctionDef) -> bool:
    for dec in fn.decorator_list:
        src = _call_root(dec)
        if "jit" in src:
            return True
    return False


def _traced_functions(tree: ast.Module):
    """The functions whose bodies trace into compiled steps: jit-
    decorated defs, defs passed to jax.jit, and the conventional
    step/_round/_phase/body closures inside make_* builders (the repo's
    builder idiom — make_gossipsub_step returns ``step``)."""
    jit_wrapped = _jitted_names(tree)
    out = []
    for qual, fn in _iter_functions(tree):
        if _is_jit_decorated(fn) or fn.name in jit_wrapped:
            out.append((qual, fn))
        elif fn.name in _TRACED_NESTED and "." in qual:
            outer = qual.split(".")[0]
            if outer.startswith("make_") or outer in _TRACED_NESTED:
                out.append((qual, fn))
    return out


# ---------------------------------------------------------------------------
# per-file rules


#: attribute reads whose result is a trace-time Python value even on a
#: traced array — an expression rooted in one is host-level
_HOST_ATTRS = frozenset({"shape", "dtype", "ndim", "size"})


def _expr_has_jnp_call(expr: ast.AST) -> bool:
    """True when the expression's VALUE is device-traced: it contains a
    jnp-rooted call that is not under a .shape/.dtype/.ndim/.size read
    (those yield trace-time Python values — `jnp.asarray(x).shape[-1]`
    is host arithmetic, the same calibration the alias closure
    applies)."""
    stack = [expr]
    while stack:
        sub = stack.pop()
        if isinstance(sub, ast.Attribute) and sub.attr in _HOST_ATTRS:
            continue
        if isinstance(sub, ast.Call) and _call_root(sub.func).startswith(
                _JNP_ROOTS):
            return True
        stack.extend(ast.iter_child_nodes(sub))
    return False


def _traced_alias_names(fn: ast.AST) -> set:
    """Single-assignment locals whose value is a jnp-rooted expression
    — the round-16 alias-blindness fix (shared resolver:
    analysis/lift.py). ``w = jnp.any(x)`` makes ``w`` traced; a bare
    Name copy (``v = w``) propagates it. Derived host values
    (``n = x.shape[-1]``, ``flag = x is None``) deliberately do NOT:
    shape reads and identity tests of a traced array are trace-time
    Python values, the same calibration the host-sync rule applies."""
    aliases = single_assign_exprs(fn)
    seed = {n for n, e in aliases.items() if _expr_has_jnp_call(e)}
    return name_copy_closure(aliases, seed)


def _rule_traced_branch(rel, tree, out):
    if not _in_device_scope(rel):
        return
    for qual, fn in _iter_functions(tree):
        traced_names = _traced_alias_names(fn)
        for node in _walk_shallow(fn):
            if not isinstance(node, (ast.If, ast.While, ast.Assert)):
                continue
            hit = None
            stack = [node.test]
            while stack and hit is None:
                sub = stack.pop()
                # identity tests (`x is None`) are host-level even on
                # a traced name — don't descend
                if isinstance(sub, ast.Compare) and all(
                        isinstance(op, (ast.Is, ast.IsNot))
                        for op in sub.ops):
                    continue
                if isinstance(sub, ast.Call):
                    root = _call_root(sub.func)
                    if root.startswith(_JNP_ROOTS):
                        hit = "device expression"
                        break
                # alias blindness fix: a test on a NAME that was
                # assigned from a jnp-rooted expression is the same
                # traced branch wearing a local alias
                if isinstance(sub, ast.Name) and sub.id in traced_names:
                    hit = f"device value (via local alias {sub.id!r})"
                    break
                stack.extend(ast.iter_child_nodes(sub))
            if hit:
                out.append(Violation(
                    "traced-branch", rel, node.lineno, qual,
                    f"Python {type(node).__name__.lower()} on a "
                    f"{hit}: {ast.unparse(node.test)[:80]}"
                    " — use jnp.where/lax.cond or hoist to host",
                ))


def _rule_host_sync(rel, tree, out):
    if not _in_device_scope(rel):
        return
    # sync methods: anywhere in device scope
    for qual, fn in _iter_functions(tree):
        for node in _walk_shallow(fn):
            if (isinstance(node, ast.Call)
                    and isinstance(node.func, ast.Attribute)
                    and node.func.attr in _SYNC_METHODS):
                out.append(Violation(
                    "host-sync", rel, node.lineno, qual,
                    f".{node.func.attr}() forces a device->host sync",
                ))
    # conversions: only inside traced-function bodies (builders
    # legitimately run numpy on static data before the trace), and only
    # when the argument can actually reference a traced value — the
    # function's own parameters or locals assigned from jnp-rooted
    # calls. ``float(cfg.threshold)`` / ``int(np_static[-1])`` in a
    # step body are host statics evaluated once at trace time, not
    # per-call syncs.
    for qual, fn in _traced_functions(tree):
        params = {a.arg for a in fn.args.args + fn.args.kwonlyargs
                  if a.arg not in ("self",)}
        jnp_locals = set()
        for node in _walk_shallow(fn):
            if isinstance(node, ast.Assign):
                rooted = any(
                    isinstance(c, ast.Call)
                    and _call_root(c.func).startswith(_JNP_ROOTS)
                    for c in ast.walk(node.value)
                )
                if rooted:
                    for tgt in node.targets:
                        for t in ast.walk(tgt):
                            if isinstance(t, ast.Name):
                                jnp_locals.add(t.id)
        # alias-blindness fix (round 16, shared closure lift.py):
        # a single-assignment bare-Name alias OF a traced local is
        # traced too — ``y = jnp.sum(v); w = y; float(w)`` was
        # previously missed (derived expressions keep their own
        # host/device status, same calibration as traced-branch)
        jnp_locals = name_copy_closure(single_assign_exprs(fn),
                                       jnp_locals)
        traced_names = params | jnp_locals
        for node in _walk_shallow(fn):
            if isinstance(node, ast.Call):
                root = _call_root(node.func)
                if root in _HOST_CONVERSIONS and node.args and any(
                    isinstance(n, ast.Name) and n.id in traced_names
                    for n in ast.walk(node.args[0])
                ):
                    out.append(Violation(
                        "host-sync", rel, node.lineno, qual,
                        f"{root}(...) of a traced value inside a jitted "
                        "step — a host round-trip per call (keep it jnp)",
                    ))


def _key_derived_names(fn: ast.FunctionDef) -> set:
    """Names assigned (incl. tuple-unpacked) from fold_in/split calls."""
    derived = set()
    for node in _walk_shallow(fn):
        if isinstance(node, ast.Assign) and isinstance(node.value, ast.Call):
            root = _call_root(node.value.func)
            if root.endswith((".fold_in", ".split")) or root in ("fold_in", "split"):
                for tgt in node.targets:
                    for t in ([tgt] if isinstance(tgt, ast.Name)
                              else list(ast.walk(tgt))):
                        if isinstance(t, ast.Name):
                            derived.add(t.id)
    return derived


def _is_keyish_name(name: str) -> bool:
    low = name.lower()
    return "key" in low or "rng" in low or re.fullmatch(r"k[a-z]?\d*", low) is not None


def _rule_prng_key(rel, tree, out):
    if not _in_device_scope(rel):
        return
    traced_ids = {id(fn) for _, fn in _traced_functions(tree)}
    fns = list(_iter_functions(tree))
    by_qual = dict(fns)
    for qual, fn in fns:
        # lexical scoping: keys split/folded in an ENCLOSING function are
        # legitimately closed over by nested defs (heartbeat's k1..k6
        # feeding _over_subscribed/_oppo_grafts)
        derived = set()
        params = set()
        parts = qual.split(".")
        for i in range(len(parts)):
            anc = by_qual.get(".".join(parts[: i + 1]))
            if anc is not None:
                derived |= _key_derived_names(anc)
                params |= {a.arg for a in anc.args.args + anc.args.kwonlyargs}
        key_uses: dict[str, int] = {}
        for node in _walk_shallow(fn):
            if not isinstance(node, ast.Call):
                continue
            root = _call_root(node.func)
            m = re.fullmatch(r"(?:jax\.)?random\.(\w+)", root)
            if m is None:
                continue
            name = m.group(1)
            if name in ("key", "PRNGKey"):
                if id(fn) in traced_ids and node.args and isinstance(
                        node.args[0], ast.Constant):
                    out.append(Violation(
                        "prng-key", rel, node.lineno, qual,
                        f"fresh constant key jax.random.{name}(...) inside "
                        "a traced step — every round draws the same stream; "
                        "fold_in(sim_key, tick) instead",
                    ))
                continue
            if name in _KEY_FNS or not node.args:
                continue
            key_arg = node.args[0]
            ok = False
            if isinstance(key_arg, ast.Call):
                kroot = _call_root(key_arg.func)
                ok = kroot.endswith((".fold_in", ".split")) or kroot in (
                    "fold_in", "split")
            elif isinstance(key_arg, ast.Subscript) and isinstance(
                    key_arg.value, ast.Name):
                nm = key_arg.value.id
                ok = nm in derived or (nm in params and _is_keyish_name(nm))
            elif isinstance(key_arg, ast.Name):
                # provenance, not naming: a local must be ASSIGNED from
                # fold_in/split — ``key = st.key`` does not qualify; only
                # key-named *parameters* are trusted (the builder passes
                # a derived key in — callers are linted at their level)
                nm = key_arg.id
                ok = nm in derived or (nm in params and _is_keyish_name(nm))
                if ok:
                    key_uses[nm] = key_uses.get(nm, 0) + 1
                    if key_uses[nm] > 1:
                        out.append(Violation(
                            "prng-key", rel, node.lineno, qual,
                            f"key {nm!r} feeds a second sampler in this "
                            "function — split() it (reused keys correlate "
                            "draws)",
                        ))
                        continue
            if not ok:
                out.append(Violation(
                    "prng-key", rel, node.lineno, qual,
                    f"jax.random.{name} key {ast.unparse(key_arg)[:40]!r} "
                    "does not flow from fold_in/split of the sim key",
                ))


def _words_scope_functions(rel, tree):
    """Functions subject to word-dtype: everything in ops/bitset.py,
    plus any function whose name or parameters mention word planes."""
    for qual, fn in _iter_functions(tree):
        if rel == "ops/bitset.py":
            yield qual, fn
            continue
        names = {a.arg for a in fn.args.args + fn.args.kwonlyargs}
        names.add(fn.name)
        if any("word" in n or n in ("planes",) for n in names):
            yield qual, fn


_BITWISE = (ast.BitAnd, ast.BitOr, ast.BitXor, ast.LShift, ast.RShift)


def _rule_word_dtype(rel, tree, out):
    if not _in_device_scope(rel):
        return
    for qual, fn in _words_scope_functions(rel, tree):
        for node in _walk_shallow(fn):
            if isinstance(node, ast.AugAssign) and isinstance(
                    node.op, _BITWISE):
                sides = (node.value,)
            elif isinstance(node, ast.BinOp) and isinstance(
                    node.op, _BITWISE):
                sides = (node.left, node.right)
            else:
                continue
            for side in sides:
                if isinstance(side, ast.Constant) and isinstance(
                        side.value, int) and not isinstance(side.value, bool):
                    out.append(Violation(
                        "word-dtype", rel, node.lineno, qual,
                        f"bare int {side.value!r} in packed-word "
                        f"{type(node.op).__name__} — wrap in jnp.uint32() "
                        "(weak-int mixing promotes uint32 planes the moment "
                        "an operand turns strongly typed)",
                    ))


def _rule_import_exec(rel, tree, out):
    def scan(body, qual):
        for node in body:
            if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
                continue
            if isinstance(node, ast.ClassDef):
                scan(node.body, f"{qual}.{node.name}" if qual else node.name)
                continue
            for sub in ast.walk(node):
                if isinstance(sub, ast.Lambda):
                    # default_factory=lambda: jnp.int32(0) runs at call
                    # time, not import — skipped via the walk below
                    continue
                if isinstance(sub, ast.Call):
                    in_lambda = False
                    root = _call_root(sub.func)
                    if root.startswith(_JNP_ROOTS):
                        # re-check: is this call inside a Lambda subtree?
                        for lam in ast.walk(node):
                            if isinstance(lam, ast.Lambda) and any(
                                    s is sub for s in ast.walk(lam)):
                                in_lambda = True
                                break
                        if not in_lambda:
                            out.append(Violation(
                                "import-exec", rel, sub.lineno, qual,
                                f"{root}(...) executes on device at import "
                                "time — breaks platform forcing; build "
                                "lazily (function or default_factory)",
                            ))
    scan(tree.body, "")


_UNHASHABLE_ANN = re.compile(
    r"\b(list|dict|set|List|Dict|Set|ndarray|jax\.Array|jnp\.ndarray)\b"
)


def _decorator_alias_map(tree: ast.Module) -> dict:
    """Module-level aliases of the dataclass decorators — the
    config-hash alias-blindness fix (round 16): ``from dataclasses
    import dataclass as dc``, ``from flax import struct as fs`` and
    ``dc = dataclasses.dataclass(frozen=True)`` style bindings
    previously made a ``*Config`` class invisible to the rule
    (silently skipped, never audited). Values are
    ``(resolved_source, frozen_hint)`` — a partial-call alias carries
    its ``frozen=True`` keyword along."""
    out = {}
    for node in tree.body:
        if isinstance(node, ast.ImportFrom):
            for alias in node.names:
                if alias.asname and (
                        "dataclass" in alias.name or "struct" in alias.name
                        or node.module in ("dataclasses", "flax",
                                           "flax.struct")):
                    out[alias.asname] = (f"{node.module}.{alias.name}",
                                         None)
        elif (isinstance(node, ast.Assign) and len(node.targets) == 1
                and isinstance(node.targets[0], ast.Name)):
            src = _call_root(node.value)
            if "dataclass" in src or "struct" in src:
                frozen_hint = None
                if isinstance(node.value, ast.Call):
                    for kw in node.value.keywords:
                        if kw.arg == "frozen" and isinstance(
                                kw.value, ast.Constant):
                            frozen_hint = bool(kw.value.value)
                out[node.targets[0].id] = (src, frozen_hint)
    return out


def _rule_config_hash(rel, tree, out):
    if not _in_device_scope(rel):
        return
    dec_aliases = _decorator_alias_map(tree)
    for node in ast.walk(tree):
        if not (isinstance(node, ast.ClassDef)
                and node.name.endswith("Config")):
            continue
        is_dc, frozen = False, False
        for dec in node.decorator_list:
            src = _call_root(dec)
            head0 = src.split("(", 1)[0].split(".", 1)[0]
            if head0 in dec_aliases:
                target, frozen_hint = dec_aliases[head0]
                # substitute the alias head so dotted tails survive:
                # fs.dataclass -> flax.struct.dataclass(...)
                src = target + src[len(head0):]
                if frozen_hint:
                    frozen = True
            if "struct.dataclass" in src:
                is_dc = False  # flax state trees are not static configs
                break
            if "dataclass" in src:
                is_dc = True
                if isinstance(dec, ast.Call):
                    for kw in dec.keywords:
                        if kw.arg == "frozen" and isinstance(
                                kw.value, ast.Constant) and kw.value.value:
                            frozen = True
        if not is_dc:
            continue
        if not frozen:
            out.append(Violation(
                "config-hash", rel, node.lineno, node.name,
                f"{node.name} is a mutable dataclass — static jit args "
                "must be frozen=True (hashable)",
            ))
        for stmt in node.body:
            if isinstance(stmt, ast.AnnAssign) and _UNHASHABLE_ANN.search(
                    ast.unparse(stmt.annotation)):
                out.append(Violation(
                    "config-hash", rel, stmt.lineno, node.name,
                    f"field {ast.unparse(stmt.target)}: "
                    f"{ast.unparse(stmt.annotation)} is unhashable — use "
                    "tuple/frozenset so the config can ride static_argnames",
                ))


_FILE_RULES = (
    _rule_traced_branch,
    _rule_host_sync,
    _rule_prng_key,
    _rule_word_dtype,
    _rule_import_exec,
    _rule_config_hash,
)


# ---------------------------------------------------------------------------
# package rule: EV-counter completeness


def _ev_members(events_src: str) -> list:
    tree = ast.parse(events_src)
    out = []
    for node in ast.walk(tree):
        if isinstance(node, ast.ClassDef) and node.name == "EV":
            for stmt in node.body:
                if isinstance(stmt, ast.Assign) and isinstance(
                        stmt.targets[0], ast.Name):
                    out.append(stmt.targets[0].id)
    return out


def _proto_event_names(proto_src: str) -> set:
    m = re.search(r"enum\s+Type\s*\{(.*?)\}", proto_src, re.S)
    if not m:
        return set()
    return set(re.findall(r"^\s*(\w+)\s*=\s*\d+\s*;", m.group(1), re.M))


def check_ev_drain(ev_names, proto_names, drain_src: str,
                   package_refs: set, telemetry_src: str = "") -> list:
    """The ev-drain rule on explicit inputs (unit-testable).

    ``telemetry_src`` is telemetry/panel.py's source (or ``""`` pre-
    telemetry): a sim-only counter whose ``ev_<name>`` timeline column
    appears there counts as drained — the panel records its per-round
    deltas and the reconciliation gate pins them to the counters, which
    is stronger visibility than a prose mention in the drain."""
    out = []
    for name in ev_names:
        if name not in package_refs:
            out.append(Violation(
                "ev-drain", "trace/events.py", 1, "EV",
                f"EV.{name} is never accumulated or consumed outside "
                "trace/events.py — dead counter or missing wiring",
            ))
        if name in proto_names:
            if f"TraceEvent.{name}" not in drain_src:
                out.append(Violation(
                    "ev-drain", "trace/drain.py", 1, "",
                    f"proto event EV.{name} has no TraceEvent.{name} "
                    "emission in the drain — the reconstructive tracer "
                    "silently drops it",
                ))
        elif (name not in drain_src
              and f"ev_{name.lower()}" not in telemetry_src):
            out.append(Violation(
                "ev-drain", "trace/drain.py", 1, "",
                f"sim-only counter EV.{name} is neither documented in "
                "the drain nor recorded as a telemetry timeline column "
                "(counter_events exposes it, but a consumer contract "
                "must say so by name)",
            ))
    return out


def _tuple_value(tree: ast.Module, v: ast.expr) -> list | None:
    """Evaluate a string-tuple expression: a literal tuple/list, a Name
    aliasing another module-level tuple (resolved against ``tree``), or
    a ``+`` concatenation of such expressions."""
    if isinstance(v, ast.Name):             # e.g. RECONCILED = EV_METRICS
        return _tuple_literal(tree, v.id)
    if isinstance(v, (ast.Tuple, ast.List)):
        out = []
        for elt in v.elts:
            if not isinstance(elt, ast.Constant) or not isinstance(
                    elt.value, str):
                return None
            out.append(elt.value)
        return out
    if isinstance(v, ast.BinOp) and isinstance(v.op, ast.Add):
        left = _tuple_value(tree, v.left)
        right = _tuple_value(tree, v.right)
        if left is None or right is None:
            return None
        return left + right
    return None


def _tuple_literal(tree: ast.Module, name: str) -> list | None:
    """A module-level ``NAME = ("a", "b", ...)`` string-tuple literal —
    aliases and ``+`` concatenations of other module-level tuples
    resolve too (None when absent or not statically evaluable)."""
    for node in tree.body:
        if (isinstance(node, ast.Assign) and len(node.targets) == 1
                and isinstance(node.targets[0], ast.Name)
                and node.targets[0].id == name):
            return _tuple_value(tree, node.value)
    return None


def check_telemetry_panel(ev_names, ev_metrics, reconciled) -> list:
    """The telemetry-panel rule on explicit inputs (unit-testable):
    the EV column catalog must mirror the EV enum positionally, and
    every recorded EV column must be reconciled."""
    rel = "telemetry/panel.py"
    out = []
    want = [f"ev_{n.lower()}" for n in ev_names]
    if list(ev_metrics) != want:
        out.append(Violation(
            "telemetry-panel", rel, 1, "EV_METRICS",
            f"EV column catalog {list(ev_metrics)} != one ev_<name> "
            f"column per EV member in enum order {want} — the panel "
            "writes the delta vector by position, so a missing or "
            "misordered column silently relabels every column after it",
        ))
    rec = set(reconciled)
    for col in ev_metrics:
        if col not in rec:
            out.append(Violation(
                "telemetry-panel", rel, 1, "RECONCILED",
                f"telemetry metric {col!r} is recorded into the panel "
                "but missing from RECONCILED — a timeline column the "
                "drain-vs-timeline gate never checks can drift from "
                "the counters unnoticed",
            ))
    for col in reconciled:
        if col not in ev_metrics:
            out.append(Violation(
                "telemetry-panel", rel, 1, "RECONCILED",
                f"RECONCILED names {col!r} which is not a recorded "
                "EV_METRICS column — the reconciliation would read a "
                "column that does not exist",
            ))
    return out


def _rule_telemetry_panel(pkg_root: str) -> list:
    panel_p = os.path.join(pkg_root, "telemetry", "panel.py")
    events_p = os.path.join(pkg_root, "trace", "events.py")
    if not os.path.exists(panel_p):
        return []
    with open(events_p) as f:
        ev_names = _ev_members(f.read())
    with open(panel_p) as f:
        tree = ast.parse(f.read())
    ev_metrics = _tuple_literal(tree, "EV_METRICS")
    reconciled = _tuple_literal(tree, "RECONCILED")
    if ev_metrics is None or reconciled is None:
        return [Violation(
            "telemetry-panel", "telemetry/panel.py", 1, "",
            "EV_METRICS/RECONCILED must be module-level string-tuple "
            "literals (or an alias/concatenation of them) — the lint "
            "pins the catalog against the EV enum and cannot evaluate "
            "computed catalogs",
        )]
    return check_telemetry_panel(ev_names, ev_metrics, reconciled)


def _rule_ev_drain(pkg_root: str) -> list:
    events_p = os.path.join(pkg_root, "trace", "events.py")
    drain_p = os.path.join(pkg_root, "trace", "drain.py")
    proto_p = os.path.join(pkg_root, "pb", "pubsub_trace.proto")
    with open(events_p) as f:
        ev_names = _ev_members(f.read())
    with open(drain_p) as f:
        drain_src = f.read()
    proto_names = set()
    if os.path.exists(proto_p):
        with open(proto_p) as f:
            proto_names = _proto_event_names(f.read())
    refs = set()
    for rel, src in _iter_package_sources(pkg_root):
        # the whole trace/ package is excluded from the accumulation
        # sweep: the drain naming a counter (COUNTER_ONLY_EVENTS, the
        # generic counter_events loop) is consumption, not accumulation
        # — counting it would make the check vacuous for exactly the
        # counters it protects
        if rel.startswith("trace/"):
            continue
        for m in re.finditer(r"\bEV\.(\w+)", src):
            refs.add(m.group(1))
    tele_p = os.path.join(pkg_root, "telemetry", "panel.py")
    telemetry_src = ""
    if os.path.exists(tele_p):
        with open(tele_p) as f:
            telemetry_src = f.read()
    return check_ev_drain(ev_names, proto_names, drain_src, refs,
                          telemetry_src)


_INVARIANT_KINDS = {"safety", "liveness"}


def registry_entries(tree: ast.Module) -> list:
    """Parse oracle/invariants.py's ``@invariant("name", kind=...,
    engines=..., doc=...)`` decorators into plain dicts. ``engines`` is
    resolved through module-level tuple literals/aliases
    (CORE_ENGINES / GOSSIP_ENGINES) via the same extractor the
    telemetry rule uses; None means "not statically resolvable" (a
    violation — the catalog must be literal)."""
    out = []
    for node in ast.walk(tree):
        if not isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
            continue
        for dec in node.decorator_list:
            if not (isinstance(dec, ast.Call)
                    and _call_root(dec.func) == "invariant"):
                continue
            name = (dec.args[0].value
                    if dec.args and isinstance(dec.args[0], ast.Constant)
                    and isinstance(dec.args[0].value, str) else None)
            kw = {k.arg: k.value for k in dec.keywords}
            kind = (kw["kind"].value
                    if isinstance(kw.get("kind"), ast.Constant) else None)
            doc = (kw["doc"].value
                   if isinstance(kw.get("doc"), ast.Constant)
                   and isinstance(kw["doc"].value, str) else None)
            engines = None
            e = kw.get("engines")
            if isinstance(e, ast.Tuple):
                vals = [c.value for c in e.elts
                        if isinstance(c, ast.Constant)]
                engines = vals if len(vals) == len(e.elts) else None
            elif isinstance(e, ast.Name):
                engines = _tuple_literal(tree, e.id)
            out.append({"name": name, "line": dec.lineno, "kind": kind,
                        "engines": engines, "doc": doc})
    return out


def check_invariant_registry(entries, known_engines, tests_src: str) -> list:
    """The invariant-registry rule on explicit inputs (unit-testable):
    every registered property declares literal kind/engines/doc and is
    referenced by a seeded-violation negative test in tests/."""
    rel = "oracle/invariants.py"
    out = []
    if not entries:
        out.append(Violation(
            "invariant-registry", rel, 1, "",
            "no @invariant(...) registrations found — the property "
            "catalog must be literal @invariant decorators (the lint "
            "cannot audit a computed registry)",
        ))
        return out
    known = set(known_engines or ())
    for e in entries:
        where = e["name"] or f"line {e['line']}"
        if e["name"] is None:
            out.append(Violation(
                "invariant-registry", rel, e["line"], "",
                "invariant registered with a non-literal name — the "
                "catalog (and its negative-test cross-check) must be "
                "statically readable",
            ))
            continue
        if e["kind"] not in _INVARIANT_KINDS:
            out.append(Violation(
                "invariant-registry", rel, e["line"], e["name"],
                f"invariant {where} declares kind={e['kind']!r}; must be "
                "a literal 'safety' or 'liveness'",
            ))
        if not e["engines"] or (known and not set(e["engines"]) <= known):
            out.append(Violation(
                "invariant-registry", rel, e["line"], e["name"],
                f"invariant {where} must declare a literal non-empty "
                f"engines applicability tuple drawn from {sorted(known)} "
                f"(got {e['engines']!r}) — a property without declared "
                "applicability silently goes unchecked on the engines "
                "it was meant to cover",
            ))
        if not e["doc"]:
            out.append(Violation(
                "invariant-registry", rel, e["line"], e["name"],
                f"invariant {where} must carry a literal doc string "
                "(the property statement + paper citation the DESIGN "
                "catalog renders)",
            ))
        if e["name"] and (f'"{e["name"]}"' not in tests_src
                          and f"'{e['name']}'" not in tests_src):
            out.append(Violation(
                "invariant-registry", rel, e["line"], e["name"],
                f"invariant {e['name']!r} is not referenced by any "
                "tests/test_invariant*.py file — every property needs "
                "a seeded-violation negative test (corrupt one leaf, "
                "assert exactly this property trips); an untrippable "
                "property is a rubber stamp",
            ))
    return out


def _rule_invariant_registry(pkg_root: str) -> list:
    inv_p = os.path.join(pkg_root, "oracle", "invariants.py")
    if not os.path.exists(inv_p):
        return []
    with open(inv_p) as f:
        tree = ast.parse(f.read())
    entries = registry_entries(tree)
    known = _tuple_literal(tree, "ENGINES") or ()
    tests_dir = os.path.join(os.path.dirname(pkg_root), "tests")
    chunks = []
    if os.path.isdir(tests_dir):
        for fname in sorted(os.listdir(tests_dir)):
            # ONLY the invariant test files count: a property name
            # quoted incidentally elsewhere (an assertion listing the
            # catalog, a docstring) must not satisfy the
            # seeded-violation requirement
            if fname.startswith("test_invariant") and fname.endswith(".py"):
                with open(os.path.join(tests_dir, fname)) as f:
                    chunks.append(f.read())
    return check_invariant_registry(entries, known, "\n".join(chunks))


# ---------------------------------------------------------------------------
# call-site rule: donated-state reuse (tests/ and scripts/)


#: bare callee names (or attribute terminals) that by repo convention
#: are jitted, state-DONATING callables: the ``step`` a ``make_*``
#: builder returns, a ``make_window`` window, the guards/ensemble
#: ``jit_fn``/``ens`` handles. ``make_*``/``build_*`` calls merely
#: CONSTRUCT such callables and never donate.
_DONATING_NAMES = frozenset({"step", "window", "win", "jit_fn", "ens",
                             "step_fn"})

#: argument names that look like a state tree (the donated pytree) —
#: "st", "st2", "st_a", "state*", "states*", "tree*"; NOT "step" (the
#: callable, not the tree)
_STATEISH = re.compile(r"^(st(\d+|_\w+)?|states?\w*|tree\w*)$",
                       re.IGNORECASE)


def _terminal_name(func: ast.AST) -> str:
    if isinstance(func, ast.Name):
        return func.id
    if isinstance(func, ast.Attribute):
        return func.attr
    return ""


def _is_donating_call(node: ast.Call) -> bool:
    name = _terminal_name(node.func)
    # make_*/build_* CONSTRUCT steps; on_* are observer hooks
    # (InvariantHook.on_step reads the live state, never donates)
    if not name or name.startswith(("make_", "build_", "on_")):
        return False
    if isinstance(node.func, ast.Name):
        return (name in _DONATING_NAMES
                or name.endswith(("_step", "_window")))
    # method-style callees: only the conventional jitted handles and
    # the module-level engine steps (floodsub.floodsub_step) — a bare
    # *_step method name is usually an unrelated helper
    return name in _DONATING_NAMES or name.endswith("sub_step")


def _rule_donated_reuse(rel, tree, out):
    """Flag reuse of a state tree AFTER it was passed to a donating
    jitted step/window — the documented container footgun (CHANGES
    rounds 10+): jitted steps and scanned windows DONATE their state
    buffers, so the old tree's arrays are deleted and any later read
    either crashes or (worse, under some backends) reads freed memory.
    The correct idiom rebinds the same name (``st = step(st, ...)``)
    or builds a fresh tree per run. Applies to the CALL SITES — tests/
    and scripts/ — not the package (engine internals are functional)."""
    scopes = [("", tree)] + list(_iter_functions(tree))
    for qual, fn in scopes:
        body = (fn.body if isinstance(
            fn, (ast.Module, ast.FunctionDef, ast.AsyncFunctionDef))
            else [])
        if not body:
            continue
        donations = []   # (name, call_line, rebound_same_stmt)
        rebinds = {}     # name -> rebind lines
        loads = {}       # name -> load lines
        loops = []       # (lineno, end_lineno) of every loop statement
        assigned_calls = set()  # Call ids already handled via an Assign
        nodes = list(_walk_shallow(fn))
        # two passes: _walk_shallow is a DFS stack, not source order, so
        # the Assign handling must run before its inner Call is seen by
        # the bare-call branch
        for node in nodes:
            if isinstance(node, ast.Assign):
                targets = [t.id for tgt in node.targets
                           for t in ast.walk(tgt)
                           if isinstance(t, ast.Name)]
                for t in targets:
                    rebinds.setdefault(t, []).append(node.lineno)
                for sub in ast.walk(node.value):
                    if isinstance(sub, ast.Call) and _is_donating_call(sub):
                        assigned_calls.add(id(sub))
                        for arg in sub.args[:3]:
                            if (isinstance(arg, ast.Name)
                                    and _STATEISH.match(arg.id)):
                                # the statement's END line, so a
                                # multi-line call's own argument loads
                                # never read as after-donation reuse
                                donations.append(
                                    (arg.id,
                                     node.end_lineno or node.lineno,
                                     arg.id in targets))
            elif isinstance(node, (ast.For, ast.AsyncFor)):
                for t in ast.walk(node.target):
                    if isinstance(t, ast.Name):
                        rebinds.setdefault(t.id, []).append(node.lineno)
            if isinstance(node, (ast.For, ast.AsyncFor, ast.While)):
                loops.append((node.lineno, node.end_lineno or node.lineno))
        for node in nodes:
            if (isinstance(node, ast.Call) and _is_donating_call(node)
                    and id(node) not in assigned_calls):
                for arg in node.args[:3]:
                    if isinstance(arg, ast.Name) and _STATEISH.match(arg.id):
                        donations.append(
                            (arg.id, node.end_lineno or node.lineno,
                             False))
            if isinstance(node, ast.Name) and isinstance(node.ctx, ast.Load):
                loads.setdefault(node.id, []).append(node.lineno)
        for name, line, rebound in donations:
            if rebound:
                continue  # st = step(st, ...) — the correct idiom
            next_rebind = min(
                (ln for ln in rebinds.get(name, []) if ln > line),
                default=None)
            reuse = [ln for ln in loads.get(name, [])
                     if ln > line and (next_rebind is None
                                       or ln < next_rebind)]
            if not reuse:
                # the loop back-edge: a donation inside a loop whose
                # state name is never rebound ANYWHERE in that loop
                # re-reads the donated buffers on iteration 2 — the
                # canonical form of the footgun, with no load on a
                # later line
                enclosing = [(lo, hi) for lo, hi in loops
                             if lo <= line <= hi]
                if enclosing:
                    lo, hi = min(enclosing, key=lambda p: p[1] - p[0])
                    if not any(lo <= ln <= hi
                               for ln in rebinds.get(name, [])):
                        reuse = [line]
            if reuse:
                out.append(Violation(
                    "donated-reuse", rel, reuse[0], qual,
                    f"state tree {name!r} is read at line {reuse[0]} "
                    f"after being DONATED to a jitted step/window at "
                    f"line {line} — donation deletes the old buffers; "
                    "rebind the result to the same name or build a "
                    "fresh tree per run",
                ))


def lint_donated_reuse(src: str, rel: str) -> list:
    """The donated-reuse rule on one source string (the negative-test
    surface, like :func:`lint_source` for the device-scope rules)."""
    out: list[Violation] = []
    _rule_donated_reuse(rel, ast.parse(src), out)
    return out


def lint_callsites(repo_root: str) -> list:
    """The donated-reuse rule over the repo's call-site trees (tests/
    and scripts/); rels are repo-relative (``tests/test_x.py``) so the
    ALLOWLIST grammar covers them unchanged."""
    out: list[Violation] = []
    for sub in ("tests", "scripts"):
        d = os.path.join(repo_root, sub)
        if not os.path.isdir(d):
            continue
        for fname in sorted(os.listdir(d)):
            if not fname.endswith(".py"):
                continue
            rel = f"{sub}/{fname}"
            with open(os.path.join(d, fname)) as f:
                src = f.read()
            try:
                out.extend(lint_donated_reuse(src, rel))
            except SyntaxError as e:  # pragma: no cover
                out.append(Violation("parse", rel, e.lineno or 1, "",
                                     str(e)))
    return out


# ---------------------------------------------------------------------------
# package rule: narrow-dtype (the RANGE_AUDIT manifest cross-check)


#: sub-i32 integer dtype names a ``.astype`` may narrow to — the set
#: the range auditor's manifest must account for
_NARROW_INT_NAMES = frozenset({"int8", "int16", "uint8", "uint16"})


def _narrow_dtype_of(node: ast.AST) -> str | None:
    """The sub-i32 integer dtype one ``.astype`` argument names, else
    None (widening casts, float casts and dynamic dtypes pass)."""
    if isinstance(node, ast.Constant) and isinstance(node.value, str):
        return node.value if node.value in _NARROW_INT_NAMES else None
    if isinstance(node, ast.Attribute):
        return node.attr if node.attr in _NARROW_INT_NAMES else None
    if isinstance(node, ast.Name):
        return node.id if node.id in _NARROW_INT_NAMES else None
    return None


def narrow_astype_sites(src: str, rel: str) -> list:
    """Ordered ``(line, dtype)`` of every sub-i32 integer ``.astype``
    callsite in one source — the scanner analysis/ranges.py uses to
    build the committed manifest and this rule replays against it."""
    out = []
    for node in ast.walk(ast.parse(src)):
        if (isinstance(node, ast.Call)
                and isinstance(node.func, ast.Attribute)
                and node.func.attr == "astype" and node.args):
            dt = _narrow_dtype_of(node.args[0])
            if dt is not None:
                out.append((node.lineno, dt))
    out.sort()
    return out


def iter_device_sources(pkg_root: str):
    """(rel, src) for every device-scope package source."""
    for rel, src in _iter_package_sources(pkg_root):
        if _in_device_scope(rel):
            yield rel, src


def check_narrow_dtype(found: dict, manifest: dict) -> list:
    """The narrow-dtype rule on explicit inputs (unit-testable):
    ``found`` maps rel -> ordered (line, dtype) scan results, and the
    per-file dtype sequence must EQUAL the committed manifest — extra
    sites are unaudited narrowing casts, missing ones mean the
    manifest (and its range justification) is stale."""
    out = []
    for rel in sorted(set(found) | set(manifest)):
        sites = list(found.get(rel, ()))
        got = [dt for _line, dt in sites]
        want = list(manifest.get(rel, ()))
        if got == want:
            continue
        line = sites[0][0] if sites else 1
        out.append(Violation(
            "narrow-dtype", rel, line, "",
            f"sub-i32 .astype sites {got} do not match the committed "
            f"RANGE_AUDIT manifest {want} — every narrowing cast in "
            "device scope needs a range justification in "
            "analysis/ranges.py NARROW_ASTYPE_MANIFEST; extend it and "
            "re-record with RANGE_UPDATE=1 make range-audit",
        ))
    return out


def _rule_narrow_dtype(pkg_root: str) -> list:
    import json

    audit_p = os.path.join(os.path.dirname(pkg_root), "RANGE_AUDIT.json")
    if not os.path.exists(audit_p):
        return [Violation(
            "narrow-dtype", "analysis/ranges.py", 1, "",
            "RANGE_AUDIT.json is missing — the narrow-dtype manifest "
            "cross-check needs the committed artifact; run "
            "RANGE_UPDATE=1 make range-audit",
        )]
    with open(audit_p) as f:
        manifest = json.load(f).get("narrow_astype_manifest", {})
    found = {}
    for rel, src in iter_device_sources(pkg_root):
        try:
            sites = narrow_astype_sites(src, rel)
        except SyntaxError:  # pragma: no cover - parse rule reports it
            continue
        if sites:
            found[rel] = sites
    return check_narrow_dtype(found, manifest)


# ---------------------------------------------------------------------------
# drivers


def _iter_package_sources(pkg_root: str):
    for dirpath, dirs, files in os.walk(pkg_root):
        dirs[:] = [d for d in dirs if d not in _SKIP_DIRS]
        for f in sorted(files):
            if not f.endswith(".py"):
                continue
            p = os.path.join(dirpath, f)
            rel = os.path.relpath(p, pkg_root).replace(os.sep, "/")
            with open(p) as fh:
                yield rel, fh.read()


def lint_source(src: str, rel: str) -> list:
    """Per-file rules on a source string (the negative-test surface)."""
    tree = ast.parse(src)
    out: list[Violation] = []
    for rule in _FILE_RULES:
        rule(rel, tree, out)
    return out


def lint_package(pkg_root: str) -> list:
    out: list[Violation] = []
    for rel, src in _iter_package_sources(pkg_root):
        try:
            out.extend(lint_source(src, rel))
        except SyntaxError as e:  # pragma: no cover
            out.append(Violation("parse", rel, e.lineno or 1, "", str(e)))
    out.extend(_rule_ev_drain(pkg_root))
    out.extend(_rule_telemetry_panel(pkg_root))
    out.extend(_rule_invariant_registry(pkg_root))
    out.extend(_rule_narrow_dtype(pkg_root))
    return sorted(out, key=lambda v: (v.rel, v.line, v.rule))


# ---------------------------------------------------------------------------
# allowlist


def load_allowlist(path: str) -> list:
    """Parse ALLOWLIST lines: ``<rule> <relpath>[::<qualname>]``."""
    entries = []
    if not os.path.exists(path):
        return entries
    with open(path) as f:
        for ln, line in enumerate(f, 1):
            line = line.split("#", 1)[0].strip()
            if not line:
                continue
            parts = line.split()
            if len(parts) != 2:
                raise ValueError(f"{path}:{ln}: expected '<rule> "
                                 f"<relpath>[::<qual>]', got {line!r}")
            rule, target = parts
            rel, _, qual = target.partition("::")
            entries.append((rule, rel, qual or None))
    return entries


def filter_allowed(violations, allowlist):
    """(kept, allowed) after applying allowlist entries."""
    kept, allowed = [], []
    for v in violations:
        hit = any(
            r == v.rule and rel == v.rel and (q is None or q == v.qual)
            for r, rel, q in allowlist
        )
        (allowed if hit else kept).append(v)
    return kept, allowed


def run(pkg_root: str | None = None) -> tuple:
    """Lint the package — plus the repo call-site trees (tests/,
    scripts/) under the donated-reuse rule — with the committed
    allowlist applied. Returns (violations, allowed)."""
    if pkg_root is None:
        pkg_root = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    allow = load_allowlist(os.path.join(pkg_root, "analysis", "ALLOWLIST"))
    found = lint_package(pkg_root) + lint_callsites(
        os.path.dirname(pkg_root))
    found.sort(key=lambda v: (v.rel, v.line, v.rule))
    return filter_allowed(found, allow)
