"""ranges — static range/overflow auditor (docs/DESIGN.md §23).

The sixth static pass, and the first one that proves VALUES. simlint
reads source, guards watch traces, lift checks dataflow, hloaudit greps
lowered text, costmodel prices bytes — none of them can say "this int16
add cannot wrap" or "this gather index stays inside its operand". Those
claims exist in the repo as prose: PR 11's ``narrow_counters`` int16
packing is justified by a range argument in a comment, the flat-[E] CSR
index arithmetic is assumed to fit i32 at the MEM_AUDIT 10M-peer
headroom scale, and the i32 EV counters of an always-on ``serve/`` cell
have no stated overflow horizon. This pass turns each of them into a
committed, regression-gated verdict.

It is an abstract interpreter over the same CLOSED JAXPRS the cost
auditor walks (the costmodel build cells, plus the guards registry's
dynamic overlay build, plus a ``narrow_counters=True`` cell and an
event-counting cell):

  interval domain    every variable carries elementwise ``[lo, hi]``
                     float64 bounds in the aval's shape. Trace-time
                     constants (Net tables, publish batches, score
                     planes — closure consts) seed EXACT from their
                     concrete values, so topology-derived index chains
                     get real bounds, not dtype tops.
  known bits         packed-word bitwise ops keep finite bounds through
                     the uint32 planes: ``and`` meets, ``or``/``xor``
                     round up to the next all-ones mask,
                     ``population_count`` is bounded by the lane width,
                     shifts are monotone on the non-negative cone.
  fact seeding       state leaves default to dtype-top; a declared
                     FACTS table (each entry carries its invariant
                     justification — the PR-7/PR-12 oracle checks most
                     of them at runtime) narrows the few leaves whose
                     bounds are protocol invariants rather than dtype
                     facts (heartbeat-cleared IHAVE counters, the
                     mod-M cursor, publish origins in [-1, N-1]).
  control flow       scan runs its body to a widening fixpoint (grown
                     carries widen to dtype-top, then one sound rerun);
                     while widens carries immediately (no unbounded
                     whiles in the engines); cond unions its branches;
                     pjit/custom_* recurse.

Hard contracts (each tripped by a doctored-jaxpr negative test in
tests/test_ranges.py that names the exact eqn/leaf):

  narrow-nonwrap   every eqn producing a sub-i32 integer dtype must be
                   proven non-wrapping — the PR-11 prose proof for the
                   int16 ``peerhave``/``iasked`` counters, machine
                   checked; ``GossipSubConfig.build``'s 2^15 refusals
                   are now derived from ``np.iinfo(np.int16)``.
  index-bounds     every gather/scatter index interval must be proven
                   inside its operand, or the site must be NAMED in the
                   sanctioned-drop catalog (mode fill_or_drop/clip plus
                   a declared reason: the dense junk-convention
                   self-pointing reads, ``apply_mutation``'s drop
                   scatters). An unproven ``promise_in_bounds`` site is
                   always a violation.
  index-width      the flat ``[E]``/``[E,W]``/``e2nk`` index formulas,
                   re-evaluated SYMBOLICALLY (exact ints, no tracing)
                   at the MEM_AUDIT headroom points 100k/1M/10M under
                   the audit geometry AND a growth-envelope geometry —
                   every site gets an explicit PROVEN_I32 / NEEDS_I64
                   verdict (no silent pass); an audit-geometry
                   NEEDS_I64 fails the gate until acknowledged, and the
                   verdicts feed MEM_AUDIT's ``index_width`` column.
  overflow-horizon the per-EV-counter per-round deltas (events seeded
                   [0, 0], the output's hi IS the round bound) give
                   each i32 counter an overflow horizon in rounds —
                   surfaced as a serve/ supervisor startup note — and
                   each f32 telemetry column a 2^24 exact-count
                   horizon; any horizon under the floor fails.
  narrow-manifest  the source-level ``.astype(<sub-i32>)`` sites in the
                   device scope must equal the declared manifest
                   (positionally, per file) — the cross-check simlint's
                   ``narrow-dtype`` rule replays against the committed
                   artifact on every lint.

Entry: ``scripts/range_audit.py`` / ``make range-audit`` (wired into
``make analyze``, ``make static`` and ``make quick``); committed
``RANGE_AUDIT.json`` under the byte-identity gate, ``RANGE_UPDATE=1``
rewrites. Pure tracing + numpy interval arithmetic — no compile, no
execution, PRNG-impl-independent.
"""

from __future__ import annotations

import dataclasses
import os

from .costmodel import (  # noqa: F401  (re-exported audit plumbing)
    AUDIT_M,
    N_LO,
    PHASE_R,
    PUB_WIDTH,
    WINDOW_D,
    audit_path as _cost_audit_path,
    baseline_divergences,
    dump_audit,
)

#: single trace point — range verdicts are not slope fits; one N is
#: enough (bounds that hold at the audit shape are what the contracts
#: pin; the index-width leg re-evaluates the SCALING claims exactly)
RANGE_N = N_LO

AUDIT_NAME = "RANGE_AUDIT.json"

#: every build the range interpreter walks: the costmodel registry rows
#: (one N point each) plus the dynamic-overlay build, the
#: narrow_counters int16 cell and the event-counting cell
RANGE_BUILDS = ("gossipsub", "gossipsub_phase", "floodsub", "randomsub",
                "csr", "phase_csr", "lifted", "window", "dynamic",
                "narrow", "events")

#: contract floor: every i32 EV counter must survive at least this many
#: rounds at the audit shape before wrapping (a standing serve/ cell
#: heartbeats every few hundred rounds; a counter that wraps inside
#: ~2k rounds would corrupt drain accounting within one session)
HORIZON_FLOOR_ROUNDS = 1000

#: f32 telemetry columns count exactly until 2^24 (float32 integer
#: exactness bound) — the horizon divisor of the telemetry leg
F32_EXACT_LIMIT = 2 ** 24

#: index-width scale targets — the MEM_AUDIT headroom points
SCALE_TARGETS = (100_000, 1_000_000, 10_000_000)

#: index-width geometries: ``audit`` is the bench/MEM_AUDIT geometry
#: (ring d=8 -> K=16, M=64) — the one MEM_AUDIT's projections assume;
#: ``envelope`` is the documented growth margin (K=64 high-degree
#: overlays, M=1024 deep message windows) — the qualifier row: indices
#: that refute HERE bound how far the i32 plane stretches
SCALE_GEOMETRIES = {
    "audit": {"k": 16, "m": 64},
    "envelope": {"k": 64, "m": 1024},
}

#: audit-geometry sites allowed to read NEEDS_I64 (none today; adding
#: one here must come with the MEM_AUDIT qualifier — see check_index_width)
I64_ACKNOWLEDGED: tuple = ()


def _w_of(m: int) -> int:
    return (m + 31) // 32


#: the flat-index site table (contract index-width): max index value as
#: an EXACT python-int formula over (n, k, m, w, e) with e = n*k (the
#: density-1 capacity bound — real E is smaller, so the bound is
#: conservative). Mirrors ops/csr.py / the dense planes.
INDEX_SITES = (
    ("e2nk", "flat dense-slot address n*K + k "
     "(ops/csr.py CsrTopology.e2nk, pack_edges/unpack_edges)",
     lambda n, k, m, w, e: n * k - 1),
    ("row_ptr", "CSR row pointer: row_ptr[N] == E (ops/csr.py build_csr)",
     lambda n, k, m, w, e: e),
    ("eperm", "flat edge-involution target (ops/csr.py edge_permute_flat)",
     lambda n, k, m, w, e: e - 1),
    ("col", "flat neighbor peer id (ops/csr.py peer_gather_flat)",
     lambda n, k, m, w, e: n - 1),
    ("flat_ew", "[E, W] packed word-plane linearization e*W + w",
     lambda n, k, m, w, e: e * w - 1),
    ("dense_nkw", "[N, K, W] dense wire-plane linearization",
     lambda n, k, m, w, e: n * k * w - 1),
    ("first_round_nm", "[N, M] first-arrival plane linearization n*M + m",
     lambda n, k, m, w, e: n * m - 1),
)

#: sanctioned drop/clip catalog (contract index-bounds): builds whose
#: gather/scatter indices the interpreter cannot prove in-bounds may
#: pass ONLY when the site's mode drops/clips out-of-range lanes AND
#: the (build, primitive) pair is named here with its reason. Silent
#: passes are what this table exists to forbid.
_DENSE_JUNK = (
    "dense junk-convention reads: absent [N, K] slots self-point "
    "(ops/edges.build_edge_perm) and state-derived slot/peer indices "
    "(first_edge, mesh candidates, mcache slots) are dtype-seeded, so "
    "the interval spans the sentinel -1 / the full axis; every consumer "
    "masks on validity and the lowering's fill/clip mode drops the "
    "out-of-range lanes")
_CSR_JUNK = (
    "flat-[E] plane reads through clip-guarded indices "
    "(ops/csr.py unpack_edges/segment_or_words jnp.clip on e_of_nk/"
    "row_last; -1 marks absent) plus state-derived message-slot "
    "gathers — masked by e_valid/row_nonempty downstream")
_SCATTER_DROP = (
    "scatter updates addressed by state-derived slots (message cache "
    "ring, IWANT bookkeeping, per-peer planes) — the engine masks "
    "invalid rows and the scatter mode drops out-of-range lanes "
    "instead of trapping")
_MUTATION_DROP = (
    "apply_mutation's drop scatters (topo/dynamics.py): write batches "
    "padded with -1 rows are DROPPED by mode=drop scatter semantics — "
    "the documented no-op convention of the mutation word stream")

SANCTIONED_DROPS = {
    "gossipsub": {"gather": _DENSE_JUNK, "scatter": _SCATTER_DROP,
                  "scatter-add": _SCATTER_DROP},
    "gossipsub_phase": {"gather": _DENSE_JUNK, "scatter": _SCATTER_DROP,
                        "scatter-add": _SCATTER_DROP},
    "floodsub": {"gather": _DENSE_JUNK, "scatter": _SCATTER_DROP,
                 "scatter-add": _SCATTER_DROP},
    "randomsub": {"gather": _DENSE_JUNK, "scatter": _SCATTER_DROP,
                  "scatter-add": _SCATTER_DROP},
    "csr": {"gather": _CSR_JUNK, "scatter": _SCATTER_DROP,
            "scatter-add": _SCATTER_DROP},
    "phase_csr": {"gather": _CSR_JUNK, "scatter": _SCATTER_DROP,
                  "scatter-add": _SCATTER_DROP},
    "lifted": {"gather": _DENSE_JUNK, "scatter": _SCATTER_DROP,
               "scatter-add": _SCATTER_DROP},
    "window": {"gather": _DENSE_JUNK, "scatter": _SCATTER_DROP,
               "scatter-add": _SCATTER_DROP},
    "dynamic": {"gather": _DENSE_JUNK,
                "scatter": _MUTATION_DROP + "; plus " + _SCATTER_DROP,
                "scatter-add": _SCATTER_DROP},
    "narrow": {"gather": _DENSE_JUNK, "scatter": _SCATTER_DROP,
               "scatter-add": _SCATTER_DROP},
    "events": {"gather": _DENSE_JUNK, "scatter": _SCATTER_DROP,
               "scatter-add": _SCATTER_DROP},
}

#: source-level sub-i32 ``.astype`` manifest (contract narrow-manifest;
#: the simlint ``narrow-dtype`` rule replays this cross-check against
#: the committed artifact): per device-scope file, the ORDERED narrow
#: target dtypes of its ``.astype`` callsites, each justified here.
NARROW_ASTYPE_MANIFEST = {
    # first-arrival edge slot codes: k_dim <= 128 is asserted at the
    # int8 plane's source (ops/bitset.py first_set_idx) and the pallas
    # kernel is pinned bit-equal to that XLA twin
    "ops/pallas_delivery.py": ("int8",),
}


class RangeContractViolation(Exception):
    """One failed range contract; .build and .contract say which."""

    def __init__(self, build: str, contract: str, msg: str):
        super().__init__(f"[{build}] {contract}: {msg}")
        self.build = build
        self.contract = contract


# ---------------------------------------------------------------------------
# the interval domain (pure numpy — unit-testable on tiny jaxprs)

_INF = float("inf")


def _dtype_top(dtype):
    """Scalar (lo, hi) covering every value of one dtype."""
    import numpy as np

    dt = np.dtype(dtype) if not str(dtype).startswith("key<") else None
    if dt is None:
        return (-_INF, _INF)
    if dt.kind == "b":
        return (0.0, 1.0)
    if dt.kind in "iu":
        info = np.iinfo(dt)
        return (float(info.min), float(info.max))
    return (-_INF, _INF)


def _full(shape, lo, hi):
    import numpy as np

    return (np.broadcast_to(np.float64(lo), shape),
            np.broadcast_to(np.float64(hi), shape))


def _top(aval):
    lo, hi = _dtype_top(aval.dtype)
    return _full(aval.shape, lo, hi)


def _collapse(iv):
    """Global scalar (lo, hi) of one interval pair."""
    lo, hi = iv
    return (float(lo.min()) if lo.size else 0.0,
            float(hi.max()) if hi.size else 0.0)


def _const_ival(c, aval):
    """Exact interval of one trace constant (key dtypes -> top)."""
    import numpy as np

    if str(aval.dtype).startswith("key<"):
        return _top(aval)
    a = np.asarray(c, np.float64)
    return (a, a.copy())


def _nan_guard(lo, hi):
    """0*inf etc. produce NaN — widen those lanes instead of poisoning."""
    import numpy as np

    return (np.where(np.isnan(lo), -_INF, lo),
            np.where(np.isnan(hi), _INF, hi))


def _union(a, b):
    import numpy as np

    return (np.minimum(a[0], b[0]), np.maximum(a[1], b[1]))


def _next_mask(x):
    """Elementwise smallest all-ones mask >= x (known-bits or/xor bound)."""
    import numpy as np

    x = np.maximum(x, 0.0)
    with np.errstate(divide="ignore"):
        bits = np.ceil(np.log2(x + 1.0))
    return np.exp2(np.minimum(bits, 64.0)) - 1.0


#: arithmetic primitives where an integer result can leave its dtype —
#: the narrow-nonwrap recording set (selection/shape ops are
#: value-closed and cannot wrap)
_WRAP_PRIMS = frozenset({
    "add", "sub", "mul", "neg", "dot_general", "reduce_sum", "cumsum",
    "shift_left", "integer_pow", "pow", "scatter-add",
    "convert_element_type", "div", "rem",
})


@dataclasses.dataclass
class NarrowSite:
    path: str
    primitive: str
    dtype: str
    lo: float
    hi: float
    fits: bool


@dataclasses.dataclass
class IndexSite:
    path: str
    primitive: str
    mode: str
    index_lo: float
    index_hi: float
    bound: float
    proven: bool


class Recorder:
    """Per-build site records (None disables recording — the scan
    widening pre-pass walks without double-counting)."""

    def __init__(self):
        self.narrow: list[NarrowSite] = []
        self.index: list[IndexSite] = []

    def narrow_site(self, path, prim, dtype, lo, hi, fits):
        self.narrow.append(NarrowSite(path, prim, str(dtype),
                                      float(lo), float(hi), bool(fits)))

    def index_site(self, path, prim, mode, ilo, ihi, bound, proven):
        self.index.append(IndexSite(path, prim, str(mode), float(ilo),
                                    float(ihi), float(bound), bool(proven)))


def _int_out(eqn, iv, rec, path):
    """Dtype-fit pass over one eqn's first output: record sub-i32
    integer sites (contract narrow-nonwrap), widen wrapped results to
    dtype-top (unsigned wrap is legal; signed i32/i64 overflow widens
    silently — no engine does round-level i32 arithmetic near 2^31
    except the counters the horizon leg bounds)."""
    import numpy as np

    aval = eqn.outvars[0].aval
    dt = np.dtype(aval.dtype) if not str(aval.dtype).startswith("key<") \
        else None
    if dt is None or dt.kind not in "iu":
        return _nan_guard(*iv)
    lo, hi = _nan_guard(*iv)
    glo, ghi = float(lo.min()), float(hi.max())
    dlo, dhi = _dtype_top(dt)
    fits = glo >= dlo and ghi <= dhi
    name = eqn.primitive.name
    if dt.itemsize < 4 and rec is not None and name in _WRAP_PRIMS:
        rec.narrow_site(path, name, dt, glo, ghi, fits)
    if not fits:
        return _full(aval.shape, dlo, dhi)
    return (lo, hi)


def _mode_name(mode) -> str:
    s = str(mode)
    return s.rsplit(".", 1)[-1].lower() if s else "none"


def _gather_bounds(eqn):
    """Per-mapped-dim max legal start index of one gather eqn."""
    dn = eqn.params["dimension_numbers"]
    slice_sizes = eqn.params["slice_sizes"]
    opshape = eqn.invars[0].aval.shape
    return [opshape[d] - slice_sizes[d] for d in dn.start_index_map]


def _transfer_gather(eqn, ivals, rec, path):
    import numpy as np

    op, idx = ivals[0], ivals[1]
    bounds = _gather_bounds(eqn)
    mode = _mode_name(eqn.params.get("mode"))
    ilo, ihi = _collapse(idx)
    proven = bool(bounds) and ilo >= 0 and ihi <= min(bounds)
    if not proven and bounds and len(bounds) > 1:
        # per-column check: the index vector's last axis maps columns to
        # operand dims in start_index_map order
        lo_a, hi_a = idx
        if lo_a.ndim >= 1 and lo_a.shape[-1] == len(bounds):
            proven = all(
                float(lo_a[..., i].min()) >= 0
                and float(hi_a[..., i].max()) <= b
                for i, b in enumerate(bounds))
    if rec is not None:
        rec.index_site(path, "gather", mode, ilo, ihi,
                       float(min(bounds)) if bounds else 0.0, proven)
    aval = eqn.outvars[0].aval
    if proven:
        glo, ghi = _collapse(op)
        return _full(aval.shape, glo, ghi)
    return _top(aval)


def _transfer_scatter(eqn, ivals, rec, path):
    import numpy as np

    name = eqn.primitive.name
    op, idx = ivals[0], ivals[1]
    upd = ivals[2] if len(ivals) > 2 else None
    dn = eqn.params["dimension_numbers"]
    opshape = eqn.invars[0].aval.shape
    dims = getattr(dn, "scatter_dims_to_operand_dims", ())
    bounds = [opshape[d] - 1 for d in dims]
    mode = _mode_name(eqn.params.get("mode"))
    ilo, ihi = _collapse(idx)
    proven = bool(bounds) and ilo >= 0 and ihi <= min(bounds)
    if rec is not None:
        rec.index_site(path, name, mode, ilo, ihi,
                       float(min(bounds)) if bounds else 0.0, proven)
    aval = eqn.outvars[0].aval
    # exact path: 1-D operand, single statically-pinned index, scalar
    # update — the ``counters.at[EV.X].add(n)`` shape. Updating only
    # the addressed slot is what gives the overflow-horizon leg
    # per-EV resolution instead of one uniform bound.
    if (proven and upd is not None and len(op[0].shape) == 1
            and idx[0].size == 1 and ilo == ihi
            and eqn.invars[2].aval.size == 1):
        j = int(ilo)
        lo, hi = op[0].copy(), op[1].copy()
        ulo, uhi = _collapse(upd)
        if name == "scatter-add":
            lo[j], hi[j] = lo[j] + ulo, hi[j] + uhi
        elif name == "scatter":
            lo[j], hi[j] = ulo, uhi
        else:
            lo[j], hi[j] = min(lo[j], ulo), max(hi[j], uhi)
        return (lo, hi)
    olo, ohi = _collapse(op)
    if upd is None:
        return _full(aval.shape, olo, ohi)
    ulo, uhi = _collapse(upd)
    if name == "scatter-add":
        n_upd = int(eqn.invars[2].aval.size) or 1
        return _full(aval.shape, olo + min(0.0, ulo * n_upd),
                     ohi + max(0.0, uhi * n_upd))
    if name == "scatter-mul":
        return _top(aval)
    # replace/min/max: value-closed over operand ∪ updates
    return _full(aval.shape, min(olo, ulo), max(ohi, uhi))


def _reduce_axes(eqn):
    ax = eqn.params.get("axes", ())
    return tuple(int(a) for a in ax)


def _monotone(fn, iv):
    import numpy as np

    with np.errstate(all="ignore"):
        a, b = fn(iv[0]), fn(iv[1])
    return _nan_guard(np.minimum(a, b), np.maximum(a, b))


def _mul_iv(a, b):
    import numpy as np

    with np.errstate(all="ignore"):
        cands = [a[0] * b[0], a[0] * b[1], a[1] * b[0], a[1] * b[1]]
    lo = np.minimum(np.minimum(cands[0], cands[1]),
                    np.minimum(cands[2], cands[3]))
    hi = np.maximum(np.maximum(cands[0], cands[1]),
                    np.maximum(cands[2], cands[3]))
    return _nan_guard(lo, hi)


def _div_iv(a, b):
    import numpy as np

    blo, bhi = b
    if float(blo.min()) <= 0.0 <= float(bhi.max()):
        return None  # divisor may straddle zero — caller widens
    with np.errstate(all="ignore"):
        cands = [a[0] / b[0], a[0] / b[1], a[1] / b[0], a[1] / b[1]]
    lo = np.minimum(np.minimum(cands[0], cands[1]),
                    np.minimum(cands[2], cands[3]))
    hi = np.maximum(np.maximum(cands[0], cands[1]),
                    np.maximum(cands[2], cands[3]))
    return _nan_guard(lo, hi)


def _bitwise(eqn, name, a, b):
    """Known-bits transfer for and/or/xor on the non-negative cone."""
    import numpy as np

    aval = eqn.outvars[0].aval
    if str(aval.dtype) == "bool":
        if name == "and":
            return (a[0] * b[0], a[1] * b[1])
        return (np.maximum(a[0], b[0]) if name == "or"
                else np.zeros_like(a[0]),
                np.minimum(a[1] + b[1], 1.0))
    if float(a[0].min()) < 0 or float(b[0].min()) < 0:
        return _top(aval)
    zero = np.zeros_like(a[0])
    if name == "and":
        return (zero, np.minimum(a[1], b[1]))
    return (zero, _next_mask(np.maximum(a[1], b[1])))


def _transfer(eqn, ivals, rec, path):
    """One primitive equation -> output intervals (list, one per
    outvar). Unknown primitives fall back to dtype-top — sound."""
    import numpy as np

    name = eqn.primitive.name
    aval = eqn.outvars[0].aval if eqn.outvars else None
    p = eqn.params

    if name in ("copy", "stop_gradient", "device_put", "reduce_precision"):
        return [ivals[0]]
    if name == "convert_element_type":
        return [_int_out(eqn, ivals[0], rec, path)]
    if name == "broadcast_in_dim":
        shape = tuple(p["shape"])
        bd = tuple(p["broadcast_dimensions"])
        exp = [1] * len(shape)
        for i, d in enumerate(bd):
            exp[d] = ivals[0][0].shape[i]
        lo = np.broadcast_to(ivals[0][0].reshape(exp), shape)
        hi = np.broadcast_to(ivals[0][1].reshape(exp), shape)
        return [(lo, hi)]
    if name == "reshape":
        dims = p.get("dimensions")
        lo, hi = ivals[0]
        if dims is not None:
            lo, hi = np.transpose(lo, dims), np.transpose(hi, dims)
        ns = tuple(p["new_sizes"])
        return [(lo.reshape(ns), hi.reshape(ns))]
    if name == "transpose":
        perm = tuple(p["permutation"])
        return [(np.transpose(ivals[0][0], perm),
                 np.transpose(ivals[0][1], perm))]
    if name == "squeeze":
        ax = tuple(int(d) for d in p["dimensions"])
        return [(np.squeeze(ivals[0][0], axis=ax),
                 np.squeeze(ivals[0][1], axis=ax))]
    if name == "expand_dims":
        ax = tuple(int(d) for d in p["dimensions"])
        lo, hi = ivals[0]
        for d in sorted(ax):
            lo, hi = np.expand_dims(lo, d), np.expand_dims(hi, d)
        return [(lo, hi)]
    if name == "rev":
        ax = tuple(int(d) for d in p["dimensions"])
        return [(np.flip(ivals[0][0], ax), np.flip(ivals[0][1], ax))]
    if name == "slice":
        starts = p["start_indices"]
        limits = p["limit_indices"]
        strides = p["strides"] or (1,) * len(starts)
        sl = tuple(slice(int(a), int(b), int(s))
                   for a, b, s in zip(starts, limits, strides))
        return [(np.ascontiguousarray(ivals[0][0][sl]),
                 np.ascontiguousarray(ivals[0][1][sl]))]
    if name == "concatenate":
        d = int(p["dimension"])
        return [(np.concatenate([iv[0] for iv in ivals], axis=d),
                 np.concatenate([iv[1] for iv in ivals], axis=d))]
    if name == "pad":
        glo, ghi = _collapse(_union(
            _collapse_pair(ivals[0]), _collapse_pair(ivals[1])))
        return [_full(aval.shape, glo, ghi)]
    if name == "iota":
        d = int(p["dimension"])
        shape = tuple(p["shape"])
        ar = np.arange(shape[d], dtype=np.float64).reshape(
            [shape[d] if i == d else 1 for i in range(len(shape))])
        return [(np.broadcast_to(ar, shape),
                 np.broadcast_to(ar, shape))]
    if name == "dynamic_slice":
        glo, ghi = _collapse(ivals[0])
        return [_full(aval.shape, glo, ghi)]
    if name == "dynamic_update_slice":
        ulo, uhi = _collapse(ivals[1])
        return [(np.minimum(ivals[0][0], ulo),
                 np.maximum(ivals[0][1], uhi))]
    if name == "select_n":
        # elementwise feasibility: a case whose index the predicate
        # interval excludes does not widen the union — this is what
        # keeps the jnp.mod lowering (rem + lt(x,0) + select fix-up)
        # from leaking the infeasible negative branch
        plo, phi = ivals[0]
        lo = np.full(plo.shape, _INF)
        hi = np.full(plo.shape, -_INF)
        for i, iv in enumerate(ivals[1:]):
            feas = (plo <= i) & (phi >= i)
            lo = np.where(feas, np.minimum(lo, iv[0]), lo)
            hi = np.where(feas, np.maximum(hi, iv[1]), hi)
        return [(lo, hi)]
    if name == "clamp":
        mn, x, mx = ivals
        lo = np.minimum(np.maximum(x[0], mn[0]), mx[0])
        hi = np.minimum(np.maximum(x[1], mn[1]), mx[1])
        return [(lo, hi)]
    if name == "gather":
        return [_transfer_gather(eqn, ivals, rec, path)]
    if name.startswith("scatter"):
        return [_int_out(eqn, _transfer_scatter(eqn, ivals, rec, path),
                         rec, path)]
    if name in ("add", "sub"):
        a, b = ivals
        iv = ((a[0] + b[0], a[1] + b[1]) if name == "add"
              else (a[0] - b[1], a[1] - b[0]))
        return [_int_out(eqn, iv, rec, path)]
    if name == "mul":
        return [_int_out(eqn, _mul_iv(*ivals), rec, path)]
    if name == "div":
        out = _div_iv(*ivals)
        if out is None:
            return [_top(aval)]
        if np.dtype(aval.dtype).kind in "iu":
            out = (np.trunc(out[0]), np.trunc(out[1]))
        return [_int_out(eqn, out, rec, path)]
    if name == "rem":
        dmax = np.maximum(np.abs(ivals[1][0]), np.abs(ivals[1][1]))
        glo, ghi = _collapse((dmax, dmax))
        nonneg = float(ivals[0][0].min()) >= 0
        return [_full(aval.shape, 0.0 if nonneg else -(ghi - 1),
                      max(ghi - 1, 0.0))]
    if name == "neg":
        return [_int_out(eqn, (-ivals[0][1], -ivals[0][0]), rec, path)]
    if name == "abs":
        lo, hi = ivals[0]
        alo = np.where(lo > 0, lo, np.where(hi < 0, -hi, 0.0))
        ahi = np.maximum(np.abs(lo), np.abs(hi))
        return [(alo, ahi)]
    if name == "sign":
        return [(np.sign(ivals[0][0]), np.sign(ivals[0][1]))]
    if name in ("max", "min"):
        f = np.maximum if name == "max" else np.minimum
        return [(f(ivals[0][0], ivals[1][0]), f(ivals[0][1], ivals[1][1]))]
    if name in ("eq", "ne", "lt", "le", "gt", "ge"):
        # elementwise decidable comparisons fold to 0/1 — predicate
        # precision is what makes the select_n feasibility filter work
        a, b = ivals
        one = lambda x: x.astype(np.float64)  # noqa: E731
        if name == "lt":
            return [(one(a[1] < b[0]), one(a[0] < b[1]))]
        if name == "le":
            return [(one(a[1] <= b[0]), one(a[0] <= b[1]))]
        if name == "gt":
            return [(one(a[0] > b[1]), one(a[1] > b[0]))]
        if name == "ge":
            return [(one(a[0] >= b[1]), one(a[1] >= b[0]))]
        overlap = (a[0] <= b[1]) & (b[0] <= a[1])
        pinned = (a[0] == a[1]) & (b[0] == b[1]) & (a[0] == b[0])
        if name == "eq":
            return [(one(pinned), one(overlap))]
        return [(one(~overlap), one(~pinned))]
    if name == "is_finite":
        return [_full(aval.shape, 0.0, 1.0)]
    if name in ("and", "or", "xor"):
        return [_bitwise(eqn, name, ivals[0], ivals[1])]
    if name == "not":
        if str(aval.dtype) == "bool":
            return [(1.0 - ivals[0][1], 1.0 - ivals[0][0])]
        return [(-ivals[0][1] - 1.0, -ivals[0][0] - 1.0)]
    if name == "population_count":
        bits = np.dtype(aval.dtype).itemsize * 8
        return [_full(aval.shape, 0.0, float(bits))]
    if name in ("clz", "count_leading_zeros"):
        bits = np.dtype(aval.dtype).itemsize * 8
        return [_full(aval.shape, 0.0, float(bits))]
    if name == "shift_left":
        a, b = ivals
        if float(a[0].min()) < 0 or float(b[0].min()) < 0:
            return [_top(aval)]
        with np.errstate(over="ignore"):
            iv = (a[0] * np.exp2(b[0]), a[1] * np.exp2(b[1]))
        return [_int_out(eqn, iv, rec, path)]
    if name in ("shift_right_logical", "shift_right_arithmetic"):
        a, b = ivals
        with np.errstate(over="ignore"):
            if float(a[0].min()) < 0:
                if name == "shift_right_arithmetic":
                    return [(np.floor(a[0] / np.exp2(b[0])),
                             np.floor(a[1] / np.exp2(b[0])))]
                return [_top(aval)]
            return [(np.floor(a[0] / np.exp2(b[1])),
                     np.floor(a[1] / np.exp2(b[0])))]
    if name in ("reduce_sum",):
        ax = _reduce_axes(eqn)
        iv = (ivals[0][0].sum(axis=ax), ivals[0][1].sum(axis=ax))
        return [_int_out(eqn, iv, rec, path)]
    if name in ("reduce_max", "reduce_min"):
        f = np.max if name == "reduce_max" else np.min
        ax = _reduce_axes(eqn)
        return [(f(ivals[0][0], axis=ax), f(ivals[0][1], axis=ax))]
    if name in ("reduce_or", "reduce_and"):
        ax = _reduce_axes(eqn)
        if str(aval.dtype) == "bool":
            f = np.max if name == "reduce_or" else np.min
            return [(f(ivals[0][0], axis=ax), f(ivals[0][1], axis=ax))]
        if name == "reduce_and" and float(ivals[0][0].min()) >= 0:
            return [(np.zeros(aval.shape),
                     np.min(ivals[0][1], axis=ax))]
        if float(ivals[0][0].min()) >= 0:
            return [(np.zeros(aval.shape),
                     _next_mask(np.max(ivals[0][1], axis=ax)))]
        return [_top(aval)]
    if name in ("argmax", "argmin"):
        ax = _reduce_axes(eqn)
        opshape = eqn.invars[0].aval.shape
        top = max((opshape[a] for a in ax), default=1) - 1
        return [_full(aval.shape, 0.0, float(top))]
    if name in ("cumsum",):
        ax = int(p["axis"])
        lo, hi = ivals[0]
        if p.get("reverse"):
            lo, hi = np.flip(lo, ax), np.flip(hi, ax)
        lo, hi = np.cumsum(lo, axis=ax), np.cumsum(hi, axis=ax)
        if p.get("reverse"):
            lo, hi = np.flip(lo, ax), np.flip(hi, ax)
        return [_int_out(eqn, (lo, hi), rec, path)]
    if name in ("cummax", "cummin"):
        f = np.maximum.accumulate if name == "cummax" \
            else np.minimum.accumulate
        ax = int(p["axis"])
        return [(f(ivals[0][0], axis=ax), f(ivals[0][1], axis=ax))]
    if name == "sort":
        d = int(p.get("dimension", -1))
        outs = []
        for iv in ivals:
            lo = np.broadcast_to(iv[0].min(axis=d, keepdims=True),
                                 iv[0].shape)
            hi = np.broadcast_to(iv[1].max(axis=d, keepdims=True),
                                 iv[1].shape)
            outs.append((lo, hi))
        return outs
    if name == "dot_general":
        (lhs_c, _rhs_c), _batch = p["dimension_numbers"]
        kdim = 1
        for d in lhs_c:
            kdim *= int(eqn.invars[0].aval.shape[d])
        a, b = _collapse(ivals[0]), _collapse(ivals[1])
        cands = [a[0] * b[0], a[0] * b[1], a[1] * b[0], a[1] * b[1]]
        return [_int_out(
            eqn, _full(aval.shape, kdim * min(cands), kdim * max(cands)),
            rec, path)]
    if name == "integer_pow":
        y = int(p["y"])
        lo, hi = ivals[0]
        with np.errstate(all="ignore"):
            c1, c2 = lo ** y, hi ** y
        olo, ohi = np.minimum(c1, c2), np.maximum(c1, c2)
        if y % 2 == 0:
            olo = np.where((lo < 0) & (hi > 0), 0.0, olo)
        return [_int_out(eqn, _nan_guard(olo, ohi), rec, path)]
    if name in ("exp", "log", "tanh", "logistic", "sqrt", "rsqrt",
                "floor", "ceil", "round", "sin", "cos", "log1p",
                "expm1", "erf", "cbrt"):
        fmap = {"exp": np.exp, "log": np.log, "tanh": np.tanh,
                "logistic": lambda x: 1.0 / (1.0 + np.exp(-x)),
                "sqrt": np.sqrt,
                "rsqrt": lambda x: 1.0 / np.sqrt(x),
                "floor": np.floor, "ceil": np.ceil, "round": np.round,
                "log1p": np.log1p, "expm1": np.expm1, "cbrt": np.cbrt,
                "sin": None, "cos": None, "erf": None}
        f = fmap[name]
        if f is None:
            return [_full(aval.shape, -1.0, 1.0)]
        return [_monotone(f, ivals[0])]
    if name in ("random_bits", "rng_bit_generator", "threefry2x32"):
        return [_top(v.aval) for v in eqn.outvars]
    if name == "split":
        sizes = p["sizes"]
        ax = int(p["axis"])
        los = np.split(ivals[0][0], np.cumsum(sizes)[:-1], axis=ax)
        his = np.split(ivals[0][1], np.cumsum(sizes)[:-1], axis=ax)
        return [(np.ascontiguousarray(a), np.ascontiguousarray(b))
                for a, b in zip(los, his)]
    # unknown primitive: sound fallback
    return [_top(v.aval) for v in eqn.outvars]


def _collapse_pair(iv):
    lo, hi = _collapse(iv)
    import numpy as np

    return (np.float64(lo), np.float64(hi))


# ---------------------------------------------------------------------------
# the jaxpr walker (costmodel.cost_jaxpr's control-flow shape, carrying
# intervals instead of byte tallies)


def _read(env, atom):
    import jax
    import numpy as np

    if isinstance(atom, jax.core.Literal):
        return _const_ival(atom.val, atom.aval)
    iv = env.get(atom)
    if iv is None:
        return _top(atom.aval)
    return iv


def _shape_fix(iv, aval):
    """Broadcast a seeded interval to the aval's shape."""
    import numpy as np

    lo = np.broadcast_to(np.asarray(iv[0], np.float64), aval.shape)
    hi = np.broadcast_to(np.asarray(iv[1], np.float64), aval.shape)
    return (lo, hi)


def interp_jaxpr(jaxpr, consts, in_ivals, rec, path=""):
    """Walk one ``jax.core.Jaxpr`` propagating intervals; returns the
    output intervals. ``rec=None`` walks silently (scan pre-pass)."""
    env = {}
    for v, c in zip(jaxpr.constvars, consts):
        env[v] = _const_ival(c, v.aval)
    for v, iv in zip(jaxpr.invars, in_ivals):
        env[v] = _shape_fix(iv, v.aval)

    for i, eqn in enumerate(jaxpr.eqns):
        epath = f"{path}eqns[{i}]"
        name = eqn.primitive.name
        ivals = [_read(env, a) for a in eqn.invars]
        if name == "pjit":
            outs = interp_closed(eqn.params["jaxpr"], ivals, rec,
                                 path=f"{epath}/")
        elif name == "scan":
            outs = _interp_scan(eqn, ivals, rec, epath)
        elif name == "while":
            outs = _interp_while(eqn, ivals, rec, epath)
        elif name == "cond":
            outs = _interp_cond(eqn, ivals, rec, epath)
        else:
            subs = []
            for val in eqn.params.values():
                subs.extend(_closed_jaxprs(val))
            if subs and name not in ("reduce_or", "reduce_and",
                                     "reduce_sum", "reduce_max",
                                     "reduce_min", "reduce"):
                sub = subs[0]
                if len(sub.jaxpr.outvars) == len(eqn.outvars):
                    outs = interp_closed(sub, ivals, rec,
                                         path=f"{epath}/")
                else:
                    outs = [_top(v.aval) for v in eqn.outvars]
            else:
                outs = _transfer(eqn, ivals, rec, epath)
        for v, iv in zip(eqn.outvars, outs):
            env[v] = _shape_fix(iv, v.aval)
    return [_read(env, v) for v in jaxpr.outvars]


def _closed_jaxprs(v):
    from .costmodel import _closed_jaxprs as cj

    return cj(v)


def interp_closed(closed, in_ivals, rec, path=""):
    return interp_jaxpr(closed.jaxpr, closed.consts, in_ivals, rec,
                        path=path)


def _widen_carry(init, out, aval):
    """Scan widening: a carry whose bounds grew widens to dtype-top."""
    import numpy as np

    grew = (float(out[0].min()) < float(init[0].min())
            or float(out[1].max()) > float(init[1].max()))
    return _top(aval) if grew else init


def _interp_scan(eqn, ivals, rec, path):
    import numpy as np

    p = eqn.params
    nc, ncar = int(p["num_consts"]), int(p["num_carry"])
    length = int(p["length"])
    body = p["jaxpr"]
    consts, carry, xs = ivals[:nc], ivals[nc:nc + ncar], ivals[nc + ncar:]
    # per-iteration slice bound of each xs: elementwise union over the
    # leading (iteration) axis
    x_elts = [(x[0].min(axis=0), x[1].max(axis=0)) for x in xs]

    def run(car, r):
        return interp_closed(body, consts + car + x_elts, r,
                             path=f"{path}/scan/")

    pre = run(carry, None)
    carry_avals = [v.aval for v in body.jaxpr.invars[nc:nc + ncar]]
    widened = [_widen_carry(c, o, a)
               for c, o, a in zip(carry, pre[:ncar], carry_avals)]
    outs = run(widened, rec)
    car_out = [_union(_shape_fix(c, a), _shape_fix(o, a))
               for c, o, a in zip(carry, outs[:ncar], carry_avals)]
    ys = []
    for iv, v in zip(outs[ncar:], eqn.outvars[ncar:]):
        lo = np.broadcast_to(iv[0][None], (length,) + iv[0].shape)
        hi = np.broadcast_to(iv[1][None], (length,) + iv[1].shape)
        ys.append((lo.reshape(v.aval.shape), hi.reshape(v.aval.shape)))
    return car_out + ys


def _interp_while(eqn, ivals, rec, path):
    p = eqn.params
    cn, bn = int(p["cond_nconsts"]), int(p["body_nconsts"])
    cond_consts = ivals[:cn]
    body_consts = ivals[cn:cn + bn]
    carry = ivals[cn + bn:]
    carry_avals = [v.aval for v in
                   p["body_jaxpr"].jaxpr.invars[bn:]]
    top_carry = [_top(a) for a in carry_avals]
    interp_closed(p["cond_jaxpr"], cond_consts + top_carry, rec,
                  path=f"{path}/while_cond/")
    interp_closed(p["body_jaxpr"], body_consts + top_carry, rec,
                  path=f"{path}/while_body/")
    return top_carry


def _interp_cond(eqn, ivals, rec, path):
    branches = eqn.params["branches"]
    ops = ivals[1:]
    outs = None
    for b, br in enumerate(branches):
        got = interp_closed(br, ops, rec, path=f"{path}/branches[{b}]/")
        got = [_shape_fix(iv, v.aval)
               for iv, v in zip(got, eqn.outvars)]
        outs = got if outs is None else [
            _union(a, g) for a, g in zip(outs, got)]
    return outs


# ---------------------------------------------------------------------------
# fact seeding (the declared-invariant table; docs/DESIGN.md §23)


@dataclasses.dataclass(frozen=True)
class RangeFact:
    """One declared state-leaf bound: matched by path substring, bounds
    resolved against the build's static shape context."""

    match: str
    lo: object        # int | callable(ctx) -> int
    hi: object
    why: str


FACTS = (
    RangeFact(
        ".peerhave", 0, lambda c: c["heartbeat_every"],
        "IHAVE batch counter: +<=1 per round (handle_ihave counts one "
        "advertising batch per edge per round), cleared every "
        "heartbeat_every rounds (clearIHaveCounters; gossipsub.go "
        "heartbeat parity) — so it never exceeds heartbeat_every "
        "between clears"),
    RangeFact(
        ".iasked", 0, lambda c: c["heartbeat_every"] * c["m"],
        "IWANT-asked counter: grows by at most popcount(ihave) <= M "
        "ids per round on the uncapped branch (the build() guard "
        "M*(heartbeat_every+1) <= max_ihave_length selects it at the "
        "audit shape), cleared with peerhave every heartbeat"),
    RangeFact(
        ".msgs.cursor", 0, lambda c: c["m"] - 1,
        "message-ring cursor: allocator writes cursor' = (cursor + "
        "batch) mod M (state.allocate_publishes)"),
    RangeFact(
        ".tick", 0, lambda c: 2 ** 31 - 1 - 64,
        "round counter: i32 with the overflow-horizon leg's declared "
        "headroom — the supervisor note states the 2^31-1-round "
        "horizon; seeded below it so tick+r proves in-range"),
    RangeFact(
        ".events", 0, 0,
        "cumulative EV counters seeded to ZERO on purpose: the "
        "output's hi is then the exact per-round delta bound, which "
        "is the overflow-horizon divisor (contract overflow-horizon)"),
    RangeFact(
        ".msgs.origin", -1, lambda c: c["n"] - 1,
        "message origin ids: -1 empty sentinel or a peer index "
        "(allocate_publishes writes pub_origin, masked >= 0)"),
    RangeFact(
        ".msgs.topic", -1, lambda c: max(c["t"] - 1, 0),
        "message topics: -1 empty sentinel or a subscribed topic index"),
    RangeFact(
        ".msgs.birth", -1, lambda c: 2 ** 31 - 1 - 64,
        "birth round stamps: -1 or a past tick (bounded by the tick "
        "fact's headroom)"),
    RangeFact(
        ".dlv.first_round", -1, lambda c: 2 ** 31 - 1 - 64,
        "first-arrival round stamps: -1 or a past tick"),
    RangeFact(
        ".dlv.first_edge", -1, lambda c: c["k"] - 1,
        "first-arrival edge slots: -1 or a slot index in [0, K)"),
    RangeFact(
        ".topo.nbr", 0, lambda c: c["n"] - 1,
        "dynamic overlay neighbor ids: the junk convention self-points "
        "absent slots (edges.build_edge_perm; state.DynTopo), so every "
        "entry is a valid peer index — mutation writes preserve it "
        "(apply_mutation's batches carry peer ids or the self id)"),
    RangeFact(
        ".topo.rev", 0, lambda c: c["k"] - 1,
        "dynamic overlay reciprocal slots: rev[j, s] is the slot of "
        "edge (j, s) in the neighbor's row — always in [0, K)"),
    RangeFact(
        ".topo.edge_perm", 0, lambda c: c["n"] * c["k"] - 1,
        "dynamic overlay flat involution nbr*K + rev — a flat [N*K] "
        "edge id (absent slots self-point)"),
    RangeFact(
        ".topo.epoch", 0, lambda c: 2 ** 31 - 1,
        "mutation epoch stamps: grow by at most one per applied write "
        "batch (the ISSUE's declared mutation-epoch growth fact) — "
        "dtype-top is the honest bound; epochs are compared, never "
        "used as indices"),
)


def _fact_ctx(name: str, n: int) -> dict:
    hb = PHASE_R if name in ("gossipsub_phase", "phase_csr") else 1
    return {"n": n, "k": 16, "m": AUDIT_M, "t": 1, "heartbeat_every": hb}


def seed_ivals(state, ctx):
    """(in_ivals, fact_hits): per-leaf intervals — FACTS where matched,
    dtype-top otherwise — in tree-flatten order."""
    import jax.tree_util as jtu

    flat = jtu.tree_flatten_with_path(state)[0]
    ivals, hits = [], []
    for path, leaf in flat:
        key = jtu.keystr(path)
        fact = next((f for f in FACTS if key.endswith(f.match)), None)
        if fact is None:
            ivals.append(_dtype_top(getattr(leaf, "dtype", "float32")))
            continue
        lo = fact.lo(ctx) if callable(fact.lo) else fact.lo
        hi = fact.hi(ctx) if callable(fact.hi) else fact.hi
        ivals.append((float(lo), float(hi)))
        hits.append({"leaf": key, "fact": fact.match,
                     "lo": int(lo), "hi": int(hi)})
    return ivals, hits


def leaf_paths(tree) -> list:
    import jax.tree_util as jtu

    return [jtu.keystr(p) for p, _ in jtu.tree_flatten_with_path(tree)[0]]


# ---------------------------------------------------------------------------
# build cells (the costmodel registry + the three range-only cells)


def range_cell(name: str):
    from .costmodel import build_cell

    if name in ("gossipsub", "csr", "lifted", "floodsub", "randomsub",
                "window"):
        return build_cell(name, RANGE_N)
    if name == "gossipsub_phase":
        return build_cell("gossipsub_phase", RANGE_N)
    if name == "phase_csr":
        return build_cell("phase_csr", RANGE_N)
    if name == "dynamic":
        return _dynamic_cell()
    if name == "narrow":
        return _narrow_cell()
    if name == "events":
        return _events_cell()
    raise ValueError(f"unknown build {name!r}; expected one of "
                     f"{RANGE_BUILDS}")


def _narrow_cell():
    """The narrow_counters=True gossipsub build — the int16 cell whose
    non-wrap proof is contract narrow-nonwrap's whole point."""
    import dataclasses as _dc

    from .costmodel import BuildCell, _pub_args, _ring_net
    from ..config import GossipSubParams, PeerScoreThresholds
    from ..models.gossipsub import (
        GossipSubConfig,
        GossipSubState,
        make_gossipsub_step,
    )
    from ..perf.sweep import bench_score_params

    net = _ring_net(RANGE_N)
    _tp, sp = bench_score_params("default", 1)
    cfg = GossipSubConfig.build(
        _dc.replace(GossipSubParams(), flood_publish=False),
        PeerScoreThresholds(), score_enabled=True, narrow_counters=True)
    cfg = _dc.replace(cfg, count_events=False, fanout_slots=0)
    st = GossipSubState.init(net, AUDIT_M, cfg, score_params=sp)
    step = make_gossipsub_step(cfg, net, score_params=sp)
    raw = getattr(step, "__wrapped__", step)
    args = _pub_args((PUB_WIDTH,), RANGE_N)
    return BuildCell("narrow", lambda s: raw(s, *args), st, 1, 1)


def _events_cell():
    """The count_events=True bench build: EV counters live, so the
    events output's hi (seeded from zero) is the per-round delta bound
    the overflow-horizon leg divides by."""
    from .costmodel import BuildCell, _pub_args
    from ..perf.sweep import build_bench

    st, step, _, _ = build_bench(
        RANGE_N, AUDIT_M, heartbeat_every=1, rounds_per_phase=1,
        count_events=True)
    raw = getattr(step, "__wrapped__", step)
    args = _pub_args((PUB_WIDTH,), RANGE_N)
    return BuildCell("events", lambda s: raw(s, *args), st, 1, 1)


def _dynamic_cell():
    """The dynamic-overlay build (guards.build_dynamic_harness's shape
    at RANGE_N): mutation write batches ride as trace constants, so
    apply_mutation's drop scatters land in this build's site records."""
    import dataclasses as _dc

    import jax.numpy as jnp

    from .costmodel import BuildCell, _pub_args
    from .. import graph
    from ..config import GossipSubParams, PeerScoreThresholds
    from ..models.gossipsub import (
        GossipSubConfig,
        GossipSubState,
        make_gossipsub_step,
    )
    from ..perf.sweep import bench_score_params, bench_wire_coalesced
    from ..state import Net
    from ..topo.dynamics import churn_storm

    topo = graph.ring_lattice(RANGE_N, d=8)
    subs = graph.subscribe_all(RANGE_N, 1)
    net = Net.build(topo, subs, dynamic=True)
    params = _dc.replace(GossipSubParams(), flood_publish=False)
    _tp, sp = bench_score_params("default", 1)
    cfg = GossipSubConfig.build(
        params, PeerScoreThresholds(), score_enabled=True,
        heartbeat_every=1, wire_coalesced=bench_wire_coalesced(None))
    cfg = _dc.replace(cfg, count_events=False, fanout_slots=0)
    st = GossipSubState.init(net, AUDIT_M, cfg, score_params=sp, seed=0,
                             dynamic_topo=True)
    step = make_gossipsub_step(cfg, net, score_params=sp,
                               dynamic_peers=True, dynamic_topo=True)
    sched = churn_storm(topo, n_dispatches=4, kill_frac=0.1, rewires=4,
                        joins=1, join_links=2, seed=0)
    writes, up = sched.build()
    args = _pub_args((PUB_WIDTH,), RANGE_N) + (
        jnp.asarray(up[0]), jnp.asarray(writes[0]))
    raw = getattr(step, "__wrapped__", step)
    return BuildCell("dynamic", lambda s: raw(s, *args), st, 1, 1)


# ---------------------------------------------------------------------------
# contracts (pure functions over the recorded sites — the negative
# tests feed them doctored records)


def check_narrow_nonwrap(build: str, sites: list) -> None:
    """Every recorded sub-i32 integer site must fit its dtype."""
    for s in sites:
        if not s.fits:
            raise RangeContractViolation(
                build, "narrow-nonwrap",
                f"{s.path} ({s.primitive}) produces {s.dtype} with "
                f"value bounds [{s.lo:.0f}, {s.hi:.0f}] outside the "
                "dtype — the narrowed counter can wrap")


def check_index_bounds(build: str, sites: list, catalog: dict) -> dict:
    """PROVEN / SANCTIONED_DROP / VIOLATION triage of one build's
    gather+scatter sites; unproven sites must be drop/clip-moded AND
    named in the catalog, else the violation names the eqn."""
    proven = 0
    sanctioned = []
    for s in sites:
        if s.proven:
            proven += 1
            continue
        if s.mode not in ("fill_or_drop", "clip", "fill", "drop"):
            raise RangeContractViolation(
                build, "index-bounds",
                f"{s.path} ({s.primitive}, mode={s.mode}) index bounds "
                f"[{s.index_lo:.0f}, {s.index_hi:.0f}] not proven "
                f"inside [0, {s.bound:.0f}] and the mode promises "
                "in-bounds — undefined behavior on device")
        reason = catalog.get(s.primitive)
        if reason is None:
            raise RangeContractViolation(
                build, "index-bounds",
                f"{s.path} ({s.primitive}, mode={s.mode}) is unproven "
                "and has NO sanctioned-drop catalog entry — name it in "
                "analysis/ranges.py SANCTIONED_DROPS or tighten the "
                "seeding facts")
        sanctioned.append({
            "path": s.path, "primitive": s.primitive, "mode": s.mode,
            "index_lo": _j(s.index_lo), "index_hi": _j(s.index_hi),
            "bound": _j(s.bound), "reason": reason,
        })
    return {"proven": proven, "sanctioned": sanctioned,
            "checked": len(sites)}


def scale_leg(sites=INDEX_SITES, targets=SCALE_TARGETS,
              geometries=SCALE_GEOMETRIES) -> dict:
    """The symbolic index-width table: exact-int max index per site ×
    geometry × peer-count, with an explicit verdict each."""
    out = {}
    for geo_name, geo in geometries.items():
        k, m = int(geo["k"]), int(geo["m"])
        w = _w_of(m)
        rows = {}
        for name, formula, fn in sites:
            verdicts = {}
            for n in targets:
                e = n * k
                mx = int(fn(n, k, m, w, e))
                verdicts[str(n)] = {
                    "max_index": mx,
                    "verdict": ("PROVEN_I32" if mx < 2 ** 31
                                else "NEEDS_I64"),
                }
            rows[name] = {"formula": formula, "by_n": verdicts}
        out[geo_name] = {"k": k, "m": m, "w": w, "sites": rows}
    return out


def check_index_width(leg: dict, acknowledged=I64_ACKNOWLEDGED) -> list:
    """No silent pass: every site×scale row must carry an explicit
    verdict, and an AUDIT-geometry NEEDS_I64 fails until acknowledged
    (acknowledging one is what puts the qualifier into MEM_AUDIT's
    headroom table). Returns the refuted (geometry, site, n) keys."""
    refuted = []
    for geo_name, geo in leg.items():
        for site, row in geo["sites"].items():
            for n, cell in row["by_n"].items():
                v = cell.get("verdict")
                if v not in ("PROVEN_I32", "NEEDS_I64"):
                    raise RangeContractViolation(
                        "scale", "index-width",
                        f"index_width.{geo_name}.sites.{site}.by_n.{n}"
                        f".verdict is {v!r} — every flat-index site "
                        "must carry an explicit PROVEN_I32/NEEDS_I64 "
                        "verdict (no silent pass)")
                if v == "NEEDS_I64":
                    refuted.append(f"{geo_name}.{site}.{n}")
                    if geo_name == "audit" and site not in acknowledged:
                        raise RangeContractViolation(
                            "scale", "index-width",
                            f"index_width.audit.sites.{site}.by_n.{n}: "
                            f"max index {cell['max_index']} NEEDS_I64 "
                            "at the AUDIT geometry — the MEM_AUDIT "
                            "headroom table overclaims; acknowledge "
                            "the site (I64_ACKNOWLEDGED) and qualify "
                            "the headroom table, or widen the plane")
    return refuted


def index_width_verdict(n: int, geometry: str = "audit") -> str:
    """Worst verdict over all flat-index sites at one peer count — the
    MEM_AUDIT headroom table's ``index_width`` column (scripts/
    memstat.py)."""
    leg = scale_leg(targets=(int(n),))
    geo = leg[geometry]
    verdicts = {row["by_n"][str(int(n))]["verdict"]
                for row in geo["sites"].values()}
    return "NEEDS_I64" if "NEEDS_I64" in verdicts else "PROVEN_I32"


def horizons_from_deltas(deltas: dict, *,
                         floor: int = HORIZON_FLOOR_ROUNDS) -> dict:
    """Per-EV overflow horizons from per-round delta bounds: rounds
    until an i32 counter wraps and until an f32 telemetry column stops
    counting exactly (2^24). A zero delta never wraps (null horizon);
    any finite horizon under the floor is a contract failure."""
    out = {}
    for name, delta in deltas.items():
        d = int(delta)
        if d <= 0:
            out[name] = {"per_round_delta_hi": d,
                         "i32_horizon_rounds": None,
                         "f32_exact_horizon_rounds": None}
            continue
        h32 = (2 ** 31 - 1) // d
        h24 = F32_EXACT_LIMIT // d
        out[name] = {"per_round_delta_hi": d,
                     "i32_horizon_rounds": h32,
                     "f32_exact_horizon_rounds": h24}
        if h32 < floor:
            raise RangeContractViolation(
                "events", "overflow-horizon",
                f"horizons.events.{name}.i32_horizon_rounds = {h32} < "
                f"floor {floor} — an always-on cell wraps this counter "
                "within one session; widen it or drain more often")
    return out


def check_narrow_manifest(found: dict, manifest=None) -> None:
    """Source scan vs the declared manifest, positionally per file."""
    manifest = NARROW_ASTYPE_MANIFEST if manifest is None else manifest
    for rel in sorted(set(found) | set(manifest)):
        got = tuple(found.get(rel, ()))
        want = tuple(manifest.get(rel, ()))
        if got != want:
            raise RangeContractViolation(
                "source", "narrow-manifest",
                f"narrow_astype_manifest.{rel}: source has sub-i32 "
                f".astype sites {list(got)} but the declared manifest "
                f"says {list(want)} — extend NARROW_ASTYPE_MANIFEST "
                "(analysis/ranges.py) with the new site's range "
                "justification")


def narrow_astype_scan(pkg_root: str | None = None) -> dict:
    """Device-scope source scan for ``.astype(<sub-i32 int>)`` sites —
    shared with simlint's ``narrow-dtype`` rule (ordered dtypes per
    file, the manifest's shape)."""
    from . import simlint

    root = pkg_root or os.path.dirname(os.path.dirname(
        os.path.abspath(__file__)))
    found: dict = {}
    for rel, src in simlint.iter_device_sources(root):
        sites = simlint.narrow_astype_sites(src, rel)
        if sites:
            found[rel] = tuple(dt for _line, dt in sites)
    return found


# ---------------------------------------------------------------------------
# the audit artifact


def _j(x):
    """JSON-safe number: exact int when finite, None on +-inf."""
    import math

    f = float(x)
    if math.isinf(f) or math.isnan(f):
        return None
    if f == int(f):
        return int(f)
    return f


def audit_build(name: str) -> dict:
    """Trace + walk one build; returns its artifact row (contracts
    raised, not recorded — a failing build aborts the audit)."""
    import jax

    cell = range_cell(name)
    jpr = jax.make_jaxpr(cell.call)(cell.state)
    ctx = _fact_ctx(name, RANGE_N)
    ivals, fact_hits = seed_ivals(cell.state, ctx)
    rec = Recorder()
    outs = interp_closed(jpr, ivals, rec)

    check_narrow_nonwrap(name, rec.narrow)
    index = check_index_bounds(name, rec.index,
                               SANCTIONED_DROPS.get(name, {}))

    row = {
        "eqn_count": len(jpr.jaxpr.eqns),
        "facts_seeded": fact_hits,
        "narrow": {
            "checked": len(rec.narrow),
            "sites": [{
                "path": s.path, "primitive": s.primitive,
                "dtype": s.dtype, "lo": _j(s.lo), "hi": _j(s.hi),
                "fits": s.fits,
            } for s in rec.narrow],
        },
        "index": index,
    }
    if name == "events":
        row["event_deltas"] = _event_deltas(cell, jpr, outs)
    return row


def _event_deltas(cell, jpr, outs) -> dict:
    """Map the events output leaf (seeded [0,0]) to per-EV per-round
    delta bounds."""
    import jax

    from ..trace.events import EV

    out_tree = jax.eval_shape(cell.call, cell.state)
    paths = leaf_paths(out_tree)
    idx = next(i for i, p in enumerate(paths) if p.endswith(".events"))
    hi = outs[idx][1]
    return {e.name: _j(hi.reshape(-1)[int(e)]) for e in EV}


def build_audit() -> dict:
    """The full audit: per-build site verdicts + the symbolic scale leg
    + the overflow horizons + the source manifest. Deterministic trace
    + interval arithmetic — committed RANGE_AUDIT.json must reproduce
    byte-identical (the COST_AUDIT pattern)."""
    builds = {}
    for name in RANGE_BUILDS:
        builds[name] = audit_build(name)

    leg = scale_leg()
    refuted = check_index_width(leg)

    deltas = builds["events"]["event_deltas"]
    horizons = horizons_from_deltas(deltas)

    found = narrow_astype_scan()
    check_narrow_manifest(found)

    narrow_total = sum(b["narrow"]["checked"] for b in builds.values())
    sanctioned_total = sum(len(b["index"]["sanctioned"])
                           for b in builds.values())
    return {
        "schema": 1,
        "note": ("static range/overflow audit (analysis/ranges.py; "
                 "RANGE_UPDATE=1 rewrites). Interval abstract "
                 "interpretation over every engine jaxpr: narrow-dtype "
                 "non-wrap proofs, gather/scatter bound triage with a "
                 "named sanctioned-drop catalog, symbolic 100k/1M/10M "
                 "index-width verdicts, EV-counter overflow horizons."),
        "shape": {"n_peers": RANGE_N, "msg_slots": AUDIT_M,
                  "rounds_per_phase": PHASE_R, "pub_width": PUB_WIDTH,
                  "window_dispatches": WINDOW_D},
        "facts": [{"match": f.match, "why": f.why} for f in FACTS],
        "builds": builds,
        "index_width": {
            "targets": list(SCALE_TARGETS),
            "geometries": leg,
            "needs_i64": sorted(refuted),
            "acknowledged_audit_sites": sorted(I64_ACKNOWLEDGED),
        },
        "horizons": {
            "floor_rounds": HORIZON_FLOOR_ROUNDS,
            "events": horizons,
            "tick": {"dtype": "int32",
                     "i32_horizon_rounds": 2 ** 31 - 1,
                     "note": ("the round counter itself: one "
                             "increment per round")},
            "telemetry_f32_note": (
                "f32 telemetry columns (telemetry/panel.py EV_METRICS) "
                "count exactly until 2^24; the per-EV "
                "f32_exact_horizon_rounds rows divide that limit by "
                "the same per-round delta bounds"),
        },
        "narrow_astype_manifest": {
            rel: list(dts) for rel, dts in
            sorted(NARROW_ASTYPE_MANIFEST.items())},
        "contracts": {
            "narrow_nonwrap": {
                "pass": True, "sites_checked": narrow_total},
            "index_bounds": {
                "pass": True,
                "proven": sum(b["index"]["proven"]
                              for b in builds.values()),
                "sanctioned": sanctioned_total},
            "index_width": {
                "pass": True, "needs_i64": sorted(refuted)},
            "overflow_horizon": {
                "pass": True,
                "floor_rounds": HORIZON_FLOOR_ROUNDS,
                "min_i32_horizon_rounds": min(
                    (h["i32_horizon_rounds"]
                     for h in horizons.values()
                     if h["i32_horizon_rounds"] is not None),
                    default=None)},
            "narrow_manifest": {
                "pass": True, "files": len(NARROW_ASTYPE_MANIFEST)},
        },
        "summary": {
            "builds": len(builds),
            "narrow_sites": narrow_total,
            "index_sanctioned": sanctioned_total,
        },
    }


def audit_path(repo_root: str | None = None) -> str:
    root = repo_root or os.path.dirname(os.path.dirname(os.path.dirname(
        os.path.abspath(__file__))))
    return os.path.join(root, AUDIT_NAME)
